"""Probe: does a given dbscan_fixed_size config survive re-execution?

The tunneled chip poisons its worker when the bug hits, so each config
must run in a fresh process: `python scripts/probe_reexec.py block layout
cap n [min_samples] [eps]`.  Prints `RESULT <ok|FAIL> <ok|FAIL> ...`.
"""

import sys

import numpy as np
import jax.numpy as jnp

from pypardis_tpu.ops.labels import dbscan_fixed_size
from pypardis_tpu.partition import spatial_order

block = int(sys.argv[1])
layout = sys.argv[2]
cap = int(sys.argv[3])
n = int(sys.argv[4])
min_samples = int(sys.argv[5]) if len(sys.argv) > 5 else 10
eps = float(sys.argv[6]) if len(sys.argv) > 6 else 2.4

rng = np.random.default_rng(0)
centers = rng.uniform(-10, 10, size=(32, 16))
pts = (
    centers[rng.integers(0, 32, size=n)]
    + rng.normal(scale=0.4, size=(n, 16))
).astype(np.float32)
pts = pts[spatial_order(pts)]
pt = np.zeros((cap, 16), np.float32)
pt[:n] = pts - pts.mean(0)
mask = np.zeros(cap, bool)
mask[:n] = True

x = jnp.asarray(pt.T) if layout == "dn" else jnp.asarray(pt)
mask = jnp.asarray(mask)
results = []
for i in range(3):
    try:
        r, c, st = dbscan_fixed_size(
            x, eps, min_samples, mask, block=block, layout=layout,
            backend="pallas",
        )
        np.asarray(r[:1])
        results.append("ok")
    except Exception as e:  # noqa: BLE001
        results.append("FAIL")
print("RESULT", *results, flush=True)
