"""Isolate device-side sort/morton/gather costs at scale."""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def sync(x):
    # On the axon tunnel, block_until_ready can return early; a tiny
    # slice transfer is a reliable barrier.
    return np.asarray(jax.tree_util.tree_leaves(x)[0].ravel()[:1])


def t(fn, *args, reps=2):
    sync(fn(*args))
    t0 = time.perf_counter()
    for _ in range(reps):
        sync(fn(*args))
    return (time.perf_counter() - t0) / reps


def main():
    n = int(sys.argv[1])
    d = 16
    rng = np.random.default_rng(0)
    keys = [jnp.asarray(rng.integers(0, 2**32, n, dtype=np.uint32))
            for _ in range(4)]
    pts = jnp.asarray(rng.normal(size=(d, n)).astype(np.float32))
    mask = jnp.arange(n) < n - 7

    lex4 = jax.jit(lambda ks: jnp.lexsort(tuple(ks)))
    lex2 = jax.jit(lambda ks: jnp.lexsort(tuple(ks[:2])))
    lex1 = jax.jit(lambda ks: jnp.argsort(ks[0]))
    print(f"lexsort 1 key: {t(lex1, keys):.2f}s")
    print(f"lexsort 2 keys: {t(lex2, keys):.2f}s")
    print(f"lexsort 4 keys: {t(lex4, keys):.2f}s")

    from pypardis_tpu.ops.pipeline import _device_morton_words

    mw = jax.jit(lambda x, m: _device_morton_words(x, m))
    print(f"morton words: {t(mw, pts, mask):.2f}s")

    perm = lex1(keys)
    gather = jax.jit(lambda p, i: jnp.take(p, i, axis=1))
    print(f"gather (d,n): {t(gather, pts, perm):.2f}s")


if __name__ == "__main__":
    main()
