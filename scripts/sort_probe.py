"""Isolate device-side sort/morton/gather costs at scale.

These are the device-side primitives the fused engine's layout pass
pays; the HOST-side analogue at out-of-core scale is the external
sample-sort (``partition.morton_range_split_streaming``), timed here
alongside them when ``--stream`` is passed — one probe for both ends
of the ROADMAP item 1 sort story.

Usage: python scripts/sort_probe.py N [DIM] [--stream]
       (makefile: `SORT_N=4000000 make sort-probe`)
"""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def sync(x):
    # On the axon tunnel, block_until_ready can return early; a tiny
    # slice transfer is a reliable barrier.
    return np.asarray(jax.tree_util.tree_leaves(x)[0].ravel()[:1])


def t(fn, *args, reps=2):
    sync(fn(*args))
    t0 = time.perf_counter()
    for _ in range(reps):
        sync(fn(*args))
    return (time.perf_counter() - t0) / reps


def main():
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    n = int(args[0])
    d = int(args[1]) if len(args) > 1 else 16
    rng = np.random.default_rng(0)
    keys = [jnp.asarray(rng.integers(0, 2**32, n, dtype=np.uint32))
            for _ in range(4)]
    pts = jnp.asarray(rng.normal(size=(d, n)).astype(np.float32))
    mask = jnp.arange(n) < n - 7

    lex4 = jax.jit(lambda ks: jnp.lexsort(tuple(ks)))
    lex2 = jax.jit(lambda ks: jnp.lexsort(tuple(ks[:2])))
    lex1 = jax.jit(lambda ks: jnp.argsort(ks[0]))
    print(f"lexsort 1 key: {t(lex1, keys):.2f}s")
    print(f"lexsort 2 keys: {t(lex2, keys):.2f}s")
    print(f"lexsort 4 keys: {t(lex4, keys):.2f}s")

    from pypardis_tpu.ops.pipeline import _device_morton_words

    mw = jax.jit(lambda x, m: _device_morton_words(x, m))
    print(f"morton words: {t(mw, pts, mask):.2f}s")

    perm = lex1(keys)
    gather = jax.jit(lambda p, i: jnp.take(p, i, axis=1))
    print(f"gather (d,n): {t(gather, pts, perm):.2f}s")

    if "--stream" in sys.argv:
        import os
        import tempfile

        sys.path.insert(0, os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        ))
        from pypardis_tpu.partition import (
            morton_range_split,
            morton_range_split_streaming,
        )

        host = np.asarray(pts).T.copy()  # (n, d) C-layout
        t0 = time.perf_counter()
        morton_range_split(host, 8)
        print(f"host in-RAM morton_range_split: "
              f"{time.perf_counter() - t0:.2f}s")
        with tempfile.NamedTemporaryFile(suffix=".f32") as f:
            mm = np.memmap(f.name, dtype=np.float32, mode="w+",
                           shape=host.shape)
            mm[:] = host
            mm.flush()
            ro = np.memmap(f.name, dtype=np.float32, mode="r",
                           shape=host.shape)
            t0 = time.perf_counter()
            morton_range_split_streaming(ro, 8).close()
            print(f"host streaming sample-sort:     "
                  f"{time.perf_counter() - t0:.2f}s")


if __name__ == "__main__":
    main()
