"""One mesh-scale configuration per process, on the 8-device CPU mesh
or the real chip (``PYPARDIS_PROBE_PLATFORM=native``).

Round-4 scale proof for the distributed path, upgraded for round 5
(r4 review, Next #1/#2/#3/#5): each invocation runs ONE configuration
through the public sharded driver and prints ONE JSON line with

* ``cold_fit_s`` AND ``warm_fit_s`` — the fit runs TWICE in-process, so
  the steady-state rate of the distributed program itself is finally
  separable from first-process compiles (every r4 row conflated them);
* ``ari_vs_truth`` — the generator's assignment is kept and scored
  (every earlier artifact validated only cluster counts + SHAs);
* optional ``--skew lognormal`` — ~100x log-normal cluster populations
  with mixed stds (the GeoLife/KDD density-skew stand-in);
* the layout stats (halo_factor / pad_waste / caps), merge convergence,
  shard-build VmHWM delta, and the labels sha1 for the assembler's
  cross-mode agreement check.

Fresh process per configuration: compile-cache reuse makes later
processes effectively warm, and process isolation keeps one config's
allocator state out of the next one's memory measurement.

Usage: python scripts/meshscale_probe.py N MODE [MAX_PARTITIONS] [EPS]
                                        [--dim D] [--skew lognormal]
                                        [--block B] [--std S]
  MODE: device | host | ring | auto_host
  auto_host lowers MERGE_HOST_AUTO so merge='auto' actually crosses
  the host-merge switchover at this size.
"""

import argparse
import hashlib
import json
import os
import sys
import time

# PYPARDIS_PROBE_PLATFORM=native leaves the ambient platform alone (the
# real TPU through axon): a 1-device mesh with 8 partitions exercises
# the identical sharded machinery — multi-partition layout, halos, the
# merge loop — at sizes and speeds the virtual CPU mesh cannot reach
# (its collective rendezvous overhead makes 2M+ runs take most of an
# hour).  The CPU mesh remains the CROSS-DEVICE collective proof at
# smaller N; the native runs are the SCALE proof.
_N_DEV = int(os.environ.get("PYPARDIS_PROBE_DEVICES", "8"))
if os.environ.get("PYPARDIS_PROBE_PLATFORM") != "native":
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={_N_DEV}"
        ).strip()

import numpy as np  # noqa: E402
import jax  # noqa: E402

if os.environ.get("PYPARDIS_PROBE_PLATFORM") != "native":
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", _N_DEV)

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
from benchdata import ari_vs_truth, make_blob_data  # noqa: E402


def reset_hwm():
    try:
        with open("/proc/self/clear_refs", "w") as f:
            f.write("5")
    except OSError:
        pass


def hwm_gb():
    for line in open("/proc/self/status"):
        if line.startswith("VmHWM"):
            return int(line.split()[1]) / 1e6
    return 0.0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("n", type=int)
    ap.add_argument("mode",
                    choices=["device", "host", "ring", "ring_host",
                             "auto_host", "device_input",
                             "global_morton", "global_morton_host"])
    ap.add_argument("max_partitions", type=int, nargs="?", default=8)
    ap.add_argument("eps", type=float, nargs="?", default=0.3)
    ap.add_argument("--dim", type=int, default=4)
    ap.add_argument("--skew", default=None)
    ap.add_argument("--block", type=int, default=1024)
    ap.add_argument("--std", type=float, default=0.1)
    ap.add_argument("--min-samples", type=int, default=10)
    # 0 = scale_probe's density (n // 6250 centers): use for rows meant
    # to be compared against the fused single-shard BENCH_SCALE rows,
    # which must see the SAME data distribution.
    ap.add_argument("--n-centers", type=int, default=64)
    # Explicit pair budget: on axon, the overflow-rerun's SECOND large
    # in-process compile can poison re-execution (session corruption);
    # a sufficient budget makes the first compiled program the final
    # one.
    ap.add_argument("--pair-budget", type=int, default=None)
    # Explicit ring-halo capacity: skips the hcap doubling ladder (each
    # retry is a recompile — same axon poison-avoidance as pair-budget).
    ap.add_argument("--hcap", type=int, default=None)
    args = ap.parse_args()
    n, mode = args.n, args.mode

    import pypardis_tpu.parallel.sharded as sm
    from pypardis_tpu.ops import densify_labels
    from pypardis_tpu.parallel import default_mesh, sharded_dbscan
    from pypardis_tpu.partition import KDPartitioner

    kwargs = {
        "device": dict(merge="device"),
        "host": dict(merge="host"),
        "ring": dict(halo="ring"),
        # the >MERGE_HOST_AUTO spill: device-side ring exchange,
        # compact occurrence tables to the host union-find
        "ring_host": dict(halo="ring", merge="host"),
        "auto_host": dict(merge="auto"),
        # device-resident input route: the warm fit here is the pure
        # distributed program (routing/layout/ring/cluster/merge all
        # on device, no per-fit host layout or dataset transfer) — the
        # steady-state engine rate the r4 review asked to pin.
        "device_input": dict(),
        # zero-duplication global-Morton mode (ISSUE 5): contiguous
        # Morton ranges, boundary-TILE ring, pmin fixpoint merge — the
        # KDPartitioner built above is unused by this engine (its build
        # time still prints for comparability).
        "global_morton": dict(mode="global_morton"),
        "global_morton_host": dict(mode="global_morton", merge="host"),
    }[mode]
    if mode == "auto_host":
        sm.MERGE_HOST_AUTO = min(sm.MERGE_HOST_AUTO, max(1, n // 2))

    n_centers = args.n_centers if args.n_centers > 0 else None
    X, truth = make_blob_data(
        n, args.dim, n_centers=n_centers, std=args.std, skew=args.skew
    )
    n_dev = min(_N_DEV, jax.device_count())
    mesh = default_mesh(n_dev)
    t0 = time.perf_counter()
    part = KDPartitioner(X, max_partitions=args.max_partitions)
    t_part = time.perf_counter() - t0

    reset_hwm()
    pre = hwm_gb()

    if mode == "device_input":
        from pypardis_tpu.parallel import sharded_dbscan_device

        Xd = jax.device_put(X)

        def fit():
            labels, core, stats, _part, _pid = sharded_dbscan_device(
                Xd, eps=args.eps, min_samples=args.min_samples,
                block=args.block, mesh=mesh,
                max_partitions=args.max_partitions,
                pair_budget=args.pair_budget, hcap=args.hcap,
            )
            return labels, core, stats
    else:
        if args.hcap is not None:
            kwargs["hcap"] = args.hcap

        def fit():
            return sharded_dbscan(
                X, part, eps=args.eps, min_samples=args.min_samples,
                block=args.block, mesh=mesh,
                pair_budget=args.pair_budget, **kwargs
            )

    t0 = time.perf_counter()
    labels, core, stats = fit()
    t_cold = time.perf_counter() - t0
    peak = hwm_gb()

    # Second fit in the SAME process: every program is compiled, the
    # budget-hint cache is seeded — this is the steady-state rate of
    # the distributed program (r4 review, Next #1).
    t0 = time.perf_counter()
    labels2, _core2, stats2 = fit()
    t_warm = time.perf_counter() - t0
    assert np.array_equal(labels, labels2), "warm refit changed labels"

    dense = densify_labels(labels)
    print(
        json.dumps(
            {
                "n": n,
                "dim": X.shape[1],
                "mode": mode,
                "skew": args.skew,
                "mesh_devices": n_dev,
                "platform": jax.default_backend(),
                "max_partitions": args.max_partitions,
                "eps": args.eps,
                "partition_s": round(t_part, 2),
                "cold_fit_s": round(t_cold, 2),
                "warm_fit_s": round(t_warm, 2),
                "warm_pts_per_sec_total": round(n / t_warm),
                "warm_pts_per_sec_chip": round(n / t_warm / n_dev),
                "build_highwater_gb": round(max(0.0, peak - pre), 3),
                "dataset_gb": round(X.nbytes / 1e9, 3),
                "ari_vs_truth": round(ari_vs_truth(dense, truth), 4),
                "halo_factor": round(stats.get("halo_factor", -1.0), 4),
                "pad_waste": round(stats.get("pad_waste", -1.0), 4),
                "owned_cap": stats.get("owned_cap"),
                "halo_cap": stats.get("halo_cap"),
                "merge": stats.get("merge", "device-in-graph"),
                "merge_rounds": stats.get("merge_rounds"),
                "merge_converged": stats.get("merge_converged"),
                "clusters": int(dense.max() + 1),
                "noise": int((dense == -1).sum()),
                "core_frac": round(float(core.mean()), 4),
                "labels_sha": hashlib.sha1(
                    np.ascontiguousarray(dense).tobytes()
                ).hexdigest()[:16],
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
