"""One mesh-scale configuration per process, on the 8-device CPU mesh.

Round-4 scale proof for the distributed path (round-3 review, Next #1):
the sharded code had never executed past 4,000 points.  Each invocation
runs ONE (n, mode, max_partitions) configuration through the public
sharded driver on the virtual 8-device mesh and prints ONE JSON line
with wall times, layout stats (halo_factor / pad_waste / caps), merge
convergence, the shard-build host-memory high-water (VmHWM delta), and
a sha1 of the densified labels so the assembler can assert all modes
agree at scale.  Collected into MESHSCALE_r04.json.

Fresh process per configuration: compile-cache reuse makes later
processes effectively warm, and process isolation keeps one config's
allocator state out of the next one's memory measurement.

Usage: python scripts/meshscale_probe.py N MODE [MAX_PARTITIONS] [EPS]
  MODE: device | host | ring | auto_host
  auto_host lowers MERGE_HOST_AUTO so merge='auto' actually crosses
  the host-merge switchover at this size (never exercised in r3).
  EPS (default 0.3) sweeps the halo-duplication factor (r3 review,
  Weak #6: halo_factor vs partition count and eps was unpinned at
  sizes where duplication dominates memory).
"""

import hashlib
import json
import os
import sys
import time

# PYPARDIS_PROBE_PLATFORM=native leaves the ambient platform alone (the
# real TPU through axon): a 1-device mesh with 8 partitions exercises
# the identical sharded machinery — multi-partition layout, halos, the
# merge loop — at sizes and speeds the virtual CPU mesh cannot reach
# (its collective rendezvous overhead makes 2M+ runs take most of an
# hour).  The CPU mesh remains the CROSS-DEVICE collective proof at
# smaller N; the native runs are the SCALE proof.
_N_DEV = int(os.environ.get("PYPARDIS_PROBE_DEVICES", "8"))
if os.environ.get("PYPARDIS_PROBE_PLATFORM") != "native":
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={_N_DEV}"
        ).strip()

import numpy as np  # noqa: E402
import jax  # noqa: E402

if os.environ.get("PYPARDIS_PROBE_PLATFORM") != "native":
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", _N_DEV)


def reset_hwm():
    try:
        with open("/proc/self/clear_refs", "w") as f:
            f.write("5")
    except OSError:
        pass


def hwm_gb():
    for line in open("/proc/self/status"):
        if line.startswith("VmHWM"):
            return int(line.split()[1]) / 1e6
    return 0.0


def make_data(n, k=4, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.uniform(-10, 10, size=(64, k)).astype(np.float32)
    out = centers[rng.integers(0, 64, size=n)]
    chunk = 1 << 20
    for s in range(0, n, chunk):
        e = min(s + chunk, n)
        out[s:e] += rng.normal(scale=0.1, size=(e - s, k)).astype(np.float32)
    return out


def main():
    n = int(sys.argv[1])
    mode = sys.argv[2]
    max_partitions = int(sys.argv[3]) if len(sys.argv) > 3 else 8
    eps = float(sys.argv[4]) if len(sys.argv) > 4 else 0.3

    import pypardis_tpu.parallel.sharded as sm
    from pypardis_tpu.ops import densify_labels
    from pypardis_tpu.parallel import default_mesh, sharded_dbscan
    from pypardis_tpu.partition import KDPartitioner

    kwargs = {
        "device": dict(merge="device"),
        "host": dict(merge="host"),
        "ring": dict(halo="ring"),
        "auto_host": dict(merge="auto"),
    }[mode]
    if mode == "auto_host":
        sm.MERGE_HOST_AUTO = min(sm.MERGE_HOST_AUTO, max(1, n // 2))

    X = make_data(n)
    n_dev = min(_N_DEV, jax.device_count())
    mesh = default_mesh(n_dev)
    t0 = time.perf_counter()
    part = KDPartitioner(X, max_partitions=max_partitions)
    t_part = time.perf_counter() - t0

    reset_hwm()
    pre = hwm_gb()
    t0 = time.perf_counter()
    labels, core, stats = sharded_dbscan(
        X, part, eps=eps, min_samples=10, block=1024, mesh=mesh, **kwargs
    )
    t_fit = time.perf_counter() - t0
    peak = hwm_gb()

    dense = densify_labels(labels)
    print(
        json.dumps(
            {
                "n": n,
                "dim": X.shape[1],
                "mode": mode,
                "mesh_devices": n_dev,
                "platform": jax.default_backend(),
                "max_partitions": max_partitions,
                "eps": eps,
                "partition_s": round(t_part, 2),
                "fit_s": round(t_fit, 2),
                "pts_per_sec_total": round(n / t_fit),
                "build_highwater_gb": round(max(0.0, peak - pre), 3),
                "dataset_gb": round(X.nbytes / 1e9, 3),
                "halo_factor": round(stats.get("halo_factor", -1.0), 4),
                "pad_waste": round(stats.get("pad_waste", -1.0), 4),
                "owned_cap": stats.get("owned_cap"),
                "halo_cap": stats.get("halo_cap"),
                "merge": stats.get("merge", "device-in-graph"),
                "merge_rounds": stats.get("merge_rounds"),
                "merge_converged": stats.get("merge_converged"),
                "clusters": int(dense.max() + 1),
                "noise": int((dense == -1).sum()),
                "core_frac": round(float(core.mean()), 4),
                "labels_sha": hashlib.sha1(
                    np.ascontiguousarray(dense).tobytes()
                ).hexdigest()[:16],
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
