"""Scale probe: blobs at increasing N on one chip (uniform or skewed).

Prints one JSON line per run with both timings the driver cares about:
``device_pps`` (fit on device-resident data — the engine rate) and
``host_pps`` (end-to-end from host numpy, including the tunnel
transfer), plus ``ari_vs_truth`` against the generator's assignment
(round-4 review: scale rows carried no oracle).  Collected into
BENCH_SCALE_r*.json artifacts.

Usage: python scripts/scale_probe.py N [DIM] [EPS] [SPREAD]
                                     [--skew lognormal]
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
from benchdata import ari_vs_truth, make_blob_data  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("n", type=int)
    ap.add_argument("dim", type=int, nargs="?", default=16)
    ap.add_argument("eps", type=float, nargs="?", default=2.4)
    ap.add_argument("spread", type=float, nargs="?", default=10.0)
    ap.add_argument("--skew", default=None)
    args = ap.parse_args()
    n = args.n
    X, truth = make_blob_data(
        n, args.dim, spread=args.spread, std=0.4, skew=args.skew
    )

    import jax

    from pypardis_tpu import DBSCAN

    def run(data):
        return DBSCAN(
            eps=args.eps, min_samples=10, block=2048
        ).fit_predict(data)

    t0 = time.perf_counter()
    labels = run(X)
    tc = time.perf_counter() - t0
    t0 = time.perf_counter()
    labels = run(X)
    host_dt = time.perf_counter() - t0

    Xd = jax.device_put(X)
    run(Xd)  # device-path warm-up (layout programs for this shape)
    dev_dt = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        labels = run(Xd)
        dev_dt = min(dev_dt, time.perf_counter() - t0)

    print(
        json.dumps(
            {
                "n": n,
                "dim": args.dim,
                "eps": args.eps,
                "skew": args.skew,
                "compile_plus_run_s": round(tc, 2),
                "host_e2e_s": round(host_dt, 2),
                "host_pps": round(n / host_dt),
                "device_s": round(dev_dt, 2),
                "device_pps": round(n / dev_dt),
                "ari_vs_truth": round(ari_vs_truth(labels, truth), 4),
                "clusters": int(labels.max() + 1),
                "noise": int((labels == -1).sum()),
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
