"""Scale probe: constant-density blobs at increasing N on one chip."""
import sys
import time

import numpy as np


def make_data(n, dim, pts_per_center=6250, seed=0):
    rng = np.random.default_rng(seed)
    n_centers = max(32, n // pts_per_center)
    centers = rng.uniform(-10, 10, size=(n_centers, dim)).astype(np.float32)
    assign = rng.integers(0, n_centers, size=n)
    out = centers[assign]
    del assign
    chunk = 1 << 20
    for s in range(0, n, chunk):
        e = min(s + chunk, n)
        out[s:e] += rng.normal(scale=0.4, size=(e - s, dim)).astype(np.float32)
    return out


def main():
    n = int(sys.argv[1])
    dim = int(sys.argv[2]) if len(sys.argv) > 2 else 16
    eps = float(sys.argv[3]) if len(sys.argv) > 3 else 2.4
    X = make_data(n, dim)
    from pypardis_tpu import DBSCAN

    def run():
        return DBSCAN(eps=eps, min_samples=10, block=2048).fit_predict(X)

    t0 = time.perf_counter()
    labels = run()
    tc = time.perf_counter() - t0
    t0 = time.perf_counter()
    labels = run()
    dt = time.perf_counter() - t0
    print(
        f"n={n} d={dim} compile+run={tc:.2f}s steady={dt:.2f}s "
        f"pps={n / dt:.0f} clusters={labels.max() + 1} "
        f"noise={(labels == -1).sum()}"
    )


if __name__ == "__main__":
    main()
