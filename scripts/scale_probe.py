"""Scale probe: constant-density blobs at increasing N on one chip.

Prints one JSON line per run with both timings the driver cares about:
``device_pps`` (fit on device-resident data — the engine rate) and
``host_pps`` (end-to-end from host numpy, including the tunnel
transfer).  Collected into BENCH_SCALE_r*.json artifacts.
"""
import json
import sys
import time

import numpy as np


def make_data(n, dim, pts_per_center=6250, seed=0, spread=10.0):
    rng = np.random.default_rng(seed)
    n_centers = max(32, n // pts_per_center)
    centers = rng.uniform(
        -spread, spread, size=(n_centers, dim)
    ).astype(np.float32)
    assign = rng.integers(0, n_centers, size=n)
    out = centers[assign]
    del assign
    chunk = 1 << 20
    for s in range(0, n, chunk):
        e = min(s + chunk, n)
        out[s:e] += rng.normal(scale=0.4, size=(e - s, dim)).astype(np.float32)
    return out


def main():
    n = int(sys.argv[1])
    dim = int(sys.argv[2]) if len(sys.argv) > 2 else 16
    eps = float(sys.argv[3]) if len(sys.argv) > 3 else 2.4
    spread = float(sys.argv[4]) if len(sys.argv) > 4 else 10.0
    X = make_data(n, dim, spread=spread)

    import jax

    from pypardis_tpu import DBSCAN

    def run(data):
        return DBSCAN(eps=eps, min_samples=10, block=2048).fit_predict(data)

    t0 = time.perf_counter()
    labels = run(X)
    tc = time.perf_counter() - t0
    t0 = time.perf_counter()
    labels = run(X)
    host_dt = time.perf_counter() - t0

    Xd = jax.device_put(X)
    run(Xd)  # device-path warm-up (layout programs for this shape)
    dev_dt = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        labels = run(Xd)
        dev_dt = min(dev_dt, time.perf_counter() - t0)

    print(
        json.dumps(
            {
                "n": n,
                "dim": dim,
                "eps": eps,
                "compile_plus_run_s": round(tc, 2),
                "host_e2e_s": round(host_dt, 2),
                "host_pps": round(n / host_dt),
                "device_s": round(dev_dt, 2),
                "device_pps": round(n / dev_dt),
                "clusters": int(labels.max() + 1),
                "noise": int((labels == -1).sum()),
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
