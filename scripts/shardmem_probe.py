"""Layout-only shard-build memory probe (round-4 review, Next #4).

Round 3 restructured ``build_shards`` to per-partition chunked gathers
with no dataset-sized recentred temp (``sharded.py:84-132``), targeting
a build high-water <= 1.5x dataset — but no recorded row could show it:
TPU rows included compile-helper RSS and CPU rows used datasets small
enough that fixed overhead swamped the ratio.  This probe runs the
layout ALONE — no fit, no jit, no device — at a probative size and
reports the VmHWM delta over the resident baseline (dataset + truth +
partitioner state), which is exactly the build's own footprint: the
output slabs (owned + halo + masks/gids, ~(1 + pad_waste + halo_factor)
x dataset) plus any temps.

Usage: python scripts/shardmem_probe.py N [DIM] [MAX_PARTITIONS] [EPS]
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")  # never touch the chip

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
from benchdata import make_blob_data  # noqa: E402


def reset_hwm():
    try:
        with open("/proc/self/clear_refs", "w") as f:
            f.write("5")
    except OSError:
        pass


def hwm_gb():
    for line in open("/proc/self/status"):
        if line.startswith("VmHWM"):
            return int(line.split()[1]) / 1e6
    return 0.0


def main():
    n = int(sys.argv[1])
    dim = int(sys.argv[2]) if len(sys.argv) > 2 else 16
    max_partitions = int(sys.argv[3]) if len(sys.argv) > 3 else 8
    eps = float(sys.argv[4]) if len(sys.argv) > 4 else 2.4

    from pypardis_tpu.parallel.sharded import build_shards
    from pypardis_tpu.partition import KDPartitioner

    X, truth = make_blob_data(n, dim)
    del truth
    part = KDPartitioner(X, max_partitions=max_partitions)

    reset_hwm()
    pre = hwm_gb()
    arrays, stats = build_shards(X, part, eps, 8, 2048)
    peak = hwm_gb()

    slabs_gb = sum(a.nbytes for a in arrays) / 1e9
    build_gb = max(0.0, peak - pre)
    print(
        json.dumps(
            {
                "n": n,
                "dim": dim,
                "max_partitions": max_partitions,
                "eps": eps,
                "dataset_gb": round(X.nbytes / 1e9, 3),
                "build_highwater_gb": round(build_gb, 3),
                "build_vs_dataset": round(build_gb / (X.nbytes / 1e9), 2),
                "output_slabs_gb": round(slabs_gb, 3),
                "pad_waste": round(stats["pad_waste"], 4),
                "halo_factor": round(stats["halo_factor"], 4),
                "owned_cap": stats["owned_cap"],
                "halo_cap": stats["halo_cap"],
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
