#!/usr/bin/env python
"""Fault-tolerance probe (``make fault-probe``, wired into bench-smoke).

Proves the ISSUE-9 acceptance criteria end to end on the faked 8-device
CPU mesh:

1. **mid-fixpoint shard failure** — ``gm.fixpoint_round:1=
   transfer_error`` injected into a global-Morton fit recovers through
   the unified retry layer with labels BYTE-IDENTICAL to the clean run;
2. **staging OOM** — ``staging.device_put:1=oom`` injected into the KD
   owner-computes fit recovers via the evict-and-retry rung, labels
   byte-identical (and byte-identical across the two modes, the pinned
   parity contract);
3. **serving hang** — a ``serve.drain`` hang against a submit deadline
   fails the ticket with ``DeadlineExceeded`` within bounded time
   instead of hanging, and the engine serves cleanly afterwards;
4. **kill/resume parity** — a child process fit (global-Morton, with a
   per-round hang widening the kill window and ``PYPARDIS_CKPT``
   snapshots) is SIGKILLed mid-fixpoint; ``DBSCAN.train(resume=)`` in a
   fresh process replays the snapshot and produces labels
   byte-identical to the uninterrupted fit.
5. **streaming-GM fault/resume (ISSUE 10)** — the same ladder coverage
   on the OUT-OF-CORE route: a ``staging.transfer`` OOM injected into
   a memmap streaming-GM fit recovers byte-identically through the
   evict-and-retry rung; a child streaming fit is SIGKILLed
   mid-fixpoint and ``train(resume=)`` recovers byte-identically; and
   the external sort's spill files (PYPARDIS_SPILL_DIR-scoped) are
   verified cleaned up after every fit, including the injected-fault
   ones.
6. **kill/resume mid-COMPACTION (ISSUE 12)** — a child process serving
   a LiveModel starts a background-compaction cycle (global-Morton
   refit, jobstate snapshots on, per-round hangs widening the kill
   window) and is SIGKILLed mid-refit; a fresh child resumes the
   compaction (``Compactor(ckpt=...)`` replays the jobstate rounds)
   and completes the epoch swap — the swapped-in index's slabs,
   labels, gids, and epoch are BYTE-IDENTICAL to an uninterrupted
   compaction's.

Emits ONE bench-style JSON row (``metric="fault_probe_scenarios"``)
whose telemetry block is the FAULTY global-Morton fit's report — so the
``faults`` block carries real injected/retried counts, which
``scripts/check_bench_json.py`` permits only on ``fault*`` rows (clean
rows must be all-zero).

Geometry via env: FAULT_N (default 3000).
"""

import json
import os
import signal
import subprocess
import sys
import time

_N_DEV = int(os.environ.get("PYPARDIS_PROBE_DEVICES", "8"))


def _force_cpu_mesh() -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={_N_DEV}"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    if "jax_num_cpu_devices" in jax.config._value_holders:
        jax.config.update("jax_num_cpu_devices", _N_DEV)


sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

KW = dict(eps=0.45, min_samples=5, block=64)


def chain_data(n: int):
    """The multi-round fixpoint geometry: one cluster threading every
    Morton shard, so the pmin merge needs several rounds (a wide,
    deterministic window for injections and kills)."""
    import numpy as np

    rng = np.random.default_rng(0)
    X = np.stack(
        [np.arange(n) * 0.1, rng.normal(0, 0.05, n)], axis=1
    )
    return X.astype(np.float32)


def child_fit(out_path: str, ckpt: str, resume: bool) -> None:
    _force_cpu_mesh()
    import numpy as np

    from pypardis_tpu import DBSCAN

    n = int(os.environ.get("FAULT_N", 3000))
    X = chain_data(n)
    if os.environ.get("FAULT_STREAM"):
        # Scenario-5 child: the same fit, out-of-core — a disk-backed
        # memmap through the streaming external sample-sort build.
        import tempfile

        f = tempfile.NamedTemporaryFile(suffix=".f32")
        mm = np.memmap(f.name, dtype=np.float32, mode="w+",
                       shape=X.shape)
        mm[:] = X
        mm.flush()
        X = np.memmap(f.name, dtype=np.float32, mode="r",
                      shape=mm.shape)
    model = DBSCAN(mode="global_morton", merge="device", **KW)
    model.train(X, resume=ckpt)
    np.savez(
        out_path,
        labels=model.labels_,
        core=model.core_sample_mask_,
        restored_rounds=np.int64(
            model._jobstate.restored_rounds if model._jobstate else 0
        ),
    )


def child_compact(out_path: str, ckpt: str) -> None:
    """Scenario-6 child: fit -> live -> compaction (GM refit, jobstate
    snapshots on).  FAULT_HANG widens the kill window via per-round
    fixpoint hangs installed AFTER the initial fit, so the jobstate
    file's appearance marks the compaction refit precisely."""
    _force_cpu_mesh()
    import numpy as np

    from pypardis_tpu import DBSCAN
    from pypardis_tpu.serve import Compactor
    from pypardis_tpu.utils import faults

    n = int(os.environ.get("FAULT_N", 3000))
    X = chain_data(n)
    model = DBSCAN(mode="global_morton", merge="device", **KW)
    model.fit(X)
    live = model.live(leaves=8)
    hang = float(os.environ.get("FAULT_HANG", "0"))
    if hang > 0:
        faults.install(f"gm.fixpoint_round:*=hang({hang})")
    comp = Compactor(
        live, ckpt=ckpt,
        fit_kw={"mode": "global_morton", "merge": "device"},
    )
    comp.compact()
    faults.clear()
    np.savez(
        out_path,
        coords=live.index.coords,
        labels=live.index.labels,
        gids=live.index.gids,
        epoch=np.int64(live.index.epoch),
        live_labels=live.labels(),
        restored_rounds=np.int64(comp.stats["resumed_rounds"]),
    )


def check(msg: str, ok: bool) -> int:
    print(f"fault-probe: {msg}: {'ok' if ok else 'FAILED'}",
          file=sys.stderr)
    if not ok:
        sys.exit(1)
    return 1


def _run_child(env_extra, out, ckpt, resume=False):
    env = dict(os.environ)
    env.update(env_extra)
    args = [sys.executable, os.path.abspath(__file__), "--child", out,
            ckpt]
    if resume:
        args.append("--resume")
    return subprocess.Popen(args, env=env)


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        if os.environ.get("FAULT_COMPACT"):
            child_compact(sys.argv[2], sys.argv[3])
        else:
            child_fit(sys.argv[2], sys.argv[3], "--resume" in sys.argv)
        return

    _force_cpu_mesh()
    import tempfile

    import numpy as np

    from pypardis_tpu import DBSCAN
    from pypardis_tpu.parallel import staging
    from pypardis_tpu.utils import faults

    n = int(os.environ.get("FAULT_N", 3000))
    X = chain_data(n)
    passed = 0

    # -- clean baselines ---------------------------------------------------
    clean_gm = DBSCAN(mode="global_morton", merge="device", **KW)
    clean_gm.fit(X)
    base_labels = np.asarray(clean_gm.labels_)
    assert clean_gm.report()["faults"]["injected"] == 0

    # -- 1: mid-fixpoint shard failure ------------------------------------
    staging.clear()
    with faults.plan("gm.fixpoint_round:1=transfer_error"):
        faulty = DBSCAN(mode="global_morton", merge="device", **KW)
        faulty.fit(X)
    rep = faulty.report()
    passed += check(
        "injected fixpoint transfer_error recovered byte-identically "
        f"(injected={rep['faults']['injected']}, "
        f"retried={rep['faults']['retried']})",
        np.array_equal(faulty.labels_, base_labels)
        and rep["faults"]["injected"] >= 1
        and rep["faults"]["retried"] >= 1,
    )

    # -- 2: staging OOM on the KD owner-computes route ---------------------
    staging.clear()
    with faults.plan("staging.device_put:1=oom"):
        kd = DBSCAN(max_partitions=8, **KW)
        kd.fit(X)
    kd_rep = kd.report()
    passed += check(
        "injected staging OOM recovered via evict-and-retry, labels "
        "byte-identical across modes",
        np.array_equal(kd.labels_, base_labels)
        and kd_rep["faults"]["injected"] >= 1,
    )

    # -- 3: serving hang vs deadline --------------------------------------
    from pypardis_tpu.serve.engine import DeadlineExceeded

    eng = clean_gm.query_engine()
    t0 = time.perf_counter()
    with faults.plan("serve.drain:1=hang(0.3)"):
        ticket = eng.submit(X[:16], timeout_s=0.05)
        eng.drain()
    waited = time.perf_counter() - t0
    failed_right = False
    try:
        ticket.result()
    except DeadlineExceeded:
        failed_right = True
    clean_labels = eng.predict(X[:16])
    passed += check(
        f"stuck drain failed the ticket within bounds ({waited:.2f}s) "
        "and the engine serves cleanly after",
        failed_right and waited < 5.0
        and eng.serving_stats()["deadline_failures"] == 1
        and clean_labels.shape == (16,),
    )

    # -- 4: kill/resume parity --------------------------------------------
    def kill_resume(tag, env_extra):
        tmp = tempfile.mkdtemp(prefix="fault_probe_")
        ckpt = os.path.join(tmp, "fit.ckpt.npz")
        out = os.path.join(tmp, "resumed.npz")
        killed = False
        deadline = time.time() + float(os.environ.get(
            "FAULT_TIMEOUT_S", 300
        ))
        for attempt in range(4):
            if os.path.exists(ckpt):
                os.unlink(ckpt)
            hang = 0.4 * (attempt + 1)
            proc = _run_child(
                {
                    "PYPARDIS_FAULTS":
                        f"gm.fixpoint_round:*=hang({hang})",
                    "PYPARDIS_CKPT_EVERY_S": "0",
                    **env_extra,
                },
                out, ckpt,
            )
            try:
                while time.time() < deadline:
                    if proc.poll() is not None:
                        break  # finished before we could kill — retry
                    if os.path.exists(ckpt):
                        time.sleep(hang * 0.5)  # land INSIDE a round
                        break
                    time.sleep(0.02)
            finally:
                alive = proc.poll() is None
                proc.send_signal(signal.SIGKILL)
                proc.wait()
            if alive and os.path.exists(ckpt):
                killed = True
                break
            print(
                f"fault-probe: attempt {attempt}: kill landed too late "
                f"(alive={alive}); widening the hang", file=sys.stderr,
            )
        check(f"[{tag}] SIGKILL landed mid-fixpoint with a snapshot "
              f"on disk", killed)
        rc = _run_child(env_extra, out, ckpt, resume=True).wait()
        check(f"[{tag}] resumed child fit completed", rc == 0)
        with np.load(out) as z:
            resumed = z["labels"]
            restored = int(z["restored_rounds"])
        return check(
            f"[{tag}] kill/resume parity: resumed labels "
            f"byte-identical (restored_rounds={restored})",
            np.array_equal(resumed, base_labels) and restored >= 1,
        ), restored

    got, restored = kill_resume("in-RAM", {})
    passed += got

    # -- 5: streaming-GM fault/resume + spill hygiene (ISSUE 10) ----------
    spill_dir = tempfile.mkdtemp(prefix="fault_probe_spill_")
    os.environ["PYPARDIS_SPILL_DIR"] = spill_dir
    try:
        with tempfile.NamedTemporaryFile(suffix=".f32") as f:
            mm = np.memmap(f.name, dtype=np.float32, mode="w+",
                           shape=X.shape)
            mm[:] = X
            mm.flush()
            ro = np.memmap(f.name, dtype=np.float32, mode="r",
                           shape=X.shape)
            staging.clear()
            with faults.plan("staging.device_put:1=oom"):
                sgm = DBSCAN(mode="global_morton", merge="device",
                             **KW)
                sgm.fit(ro)
            srep = sgm.report()
            stream_ok = (
                np.array_equal(sgm.labels_, base_labels)
                and srep["faults"]["injected"] >= 1
                and srep["sharding"]["input"] == "stream"
            )
        spill_clean = os.listdir(spill_dir) == []
        passed += check(
            "streaming-GM fit recovered a staging.transfer OOM "
            "byte-identically and cleaned its spill "
            f"(injected={srep['faults']['injected']}, "
            f"spill_clean={spill_clean})",
            stream_ok and spill_clean,
        )
        got_stream, restored_stream = kill_resume(
            "stream", {"FAULT_STREAM": "1"}
        )
        passed += got_stream
        passed += check(
            "spill cleaned after streaming kill/resume children",
            os.listdir(spill_dir) == [],
        )
    finally:
        del os.environ["PYPARDIS_SPILL_DIR"]

    # -- 6: kill/resume mid-COMPACTION (ISSUE 12) -------------------------
    # Uninterrupted reference, in-process: same data, same route — the
    # compacted generation is deterministic, so the resumed child must
    # reproduce it byte-for-byte.
    staging.clear()
    from pypardis_tpu.serve import Compactor

    ref_model = DBSCAN(mode="global_morton", merge="device", **KW)
    ref_model.fit(X)
    ref_live = ref_model.live(leaves=8)
    Compactor(
        ref_live, fit_kw={"mode": "global_morton", "merge": "device"}
    ).compact()

    tmp6 = tempfile.mkdtemp(prefix="fault_probe_compact_")
    ckpt6 = os.path.join(tmp6, "compact.ckpt.npz")
    out6 = os.path.join(tmp6, "compacted.npz")
    killed = False
    deadline6 = time.time() + float(os.environ.get(
        "FAULT_TIMEOUT_S", 300
    ))
    for attempt in range(4):
        if os.path.exists(ckpt6):
            os.unlink(ckpt6)
        hang = 0.4 * (attempt + 1)
        proc = _run_child(
            {
                "FAULT_COMPACT": "1",
                "FAULT_HANG": str(hang),
                "PYPARDIS_CKPT_EVERY_S": "0",
            },
            out6, ckpt6,
        )
        try:
            while time.time() < deadline6:
                if proc.poll() is not None:
                    break  # finished before we could kill — retry
                if os.path.exists(ckpt6):
                    time.sleep(hang * 0.5)  # land INSIDE a round
                    break
                time.sleep(0.02)
        finally:
            alive = proc.poll() is None
            proc.send_signal(signal.SIGKILL)
            proc.wait()
        if alive and os.path.exists(ckpt6):
            killed = True
            break
        print(
            f"fault-probe: compact attempt {attempt}: kill landed too "
            f"late (alive={alive}); widening the hang", file=sys.stderr,
        )
    check("SIGKILL landed mid-compaction with a jobstate snapshot on "
          "disk", killed)
    rc = _run_child({"FAULT_COMPACT": "1"}, out6, ckpt6).wait()
    check("resumed compaction child completed", rc == 0)
    with np.load(out6) as z:
        restored_compact = int(z["restored_rounds"])
        parity = (
            np.array_equal(z["coords"], ref_live.index.coords)
            and np.array_equal(z["labels"], ref_live.index.labels)
            and np.array_equal(z["gids"], ref_live.index.gids)
            and int(z["epoch"]) == ref_live.index.epoch
            and np.array_equal(z["live_labels"], ref_live.labels())
        )
    passed += check(
        f"kill/resume mid-compaction: swapped-in index byte-identical "
        f"to an uninterrupted compaction "
        f"(restored_rounds={restored_compact})",
        parity and restored_compact >= 1,
    )

    # -- 7: pod-scale drill — a fleet WORKER SIGKILLed mid-fixpoint -------
    # (ISSUE 20) The in-process kills above never exercise the multi-
    # controller failure mode: one rank of a jax.distributed fleet
    # dying mid-collective while its peers block.  Reuse the probe's
    # faultfit worker: rank 1 arms dist.worker:3=error and converts it
    # to a real SIGKILL; launch_fleet tears the survivors down; a
    # resumed fleet replays the coordinator's shared snapshot back to
    # byte parity, and the killed rank's fault_injected event is
    # recovered from its unsealed flight file.
    from pypardis_tpu import obs
    from pypardis_tpu.parallel import dist

    probe = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "multihost_probe.py"
    )
    assert _N_DEV % 2 == 0, "fleet drill splits the mesh across 2 procs"
    tmp7 = tempfile.mkdtemp(prefix="fault_probe_fleet_")
    ckpt7 = os.path.join(tmp7, "fleet.ckpt.npz")
    base7 = os.path.join(tmp7, "drill")
    flight7 = os.path.join(tmp7, "flight")
    fleet_env = dict(os.environ)
    fleet_env["PYTHONPATH"] = os.pathsep.join(
        [sys.path[0]] + [p for p in [fleet_env.get("PYTHONPATH")] if p]
    )
    fleet_env.pop("XLA_FLAGS", None)  # launch_fleet sets the workers'
    fleet_env.pop("PYPARDIS_FAULTS", None)
    fleet_env.update({
        "MH_N": str(n), "MH_CKPT": ckpt7, "PYPARDIS_CKPT_EVERY_S": "0",
    })
    argv7 = [sys.executable, probe, "--worker", "faultfit", base7]
    rcs, kill_port, _, _ = dist.launch_fleet(
        argv7, 2, _N_DEV // 2,
        env=dict(fleet_env, MH_KILL_RANK="1", MH_KILL_OCC="3",
                 MH_FLIGHT_BASE=flight7),
        timeout_s=float(os.environ.get("FAULT_TIMEOUT_S", 300)),
    )
    check(f"fleet drill: injected kill took the fleet down "
          f"(rcs={rcs})", any(rc != 0 for rc in rcs))
    check("fleet drill: coordinator snapshot survived",
          os.path.exists(ckpt7))
    rcs, _, _, tails = dist.launch_fleet(
        argv7, 2, _N_DEV // 2, env=fleet_env,
        timeout_s=float(os.environ.get("FAULT_TIMEOUT_S", 300)),
    )
    if any(rcs):
        for t in tails:
            print(t[-2000:], file=sys.stderr)
    check("fleet drill: resumed fleet completed", not any(rcs))
    fleet_parity = True
    restored_fleet = 0
    for r in range(2):
        with np.load(f"{base7}.p{r:02d}.npz") as z:
            fleet_parity &= (
                np.array_equal(z["labels"], base_labels)
                and np.array_equal(z["core"], clean_gm.core_sample_mask_)
            )
            restored_fleet = max(restored_fleet,
                                 int(z["restored_rounds"]))
    injected_fleet = sum(
        1 for r in obs.replay(
            os.path.join(flight7, f"a{kill_port}")
        ).merged_records()
        if r.get("k") == "ev" and r.get("kind") == "fault_injected"
        and r.get("f", {}).get("site") == "dist.worker"
    )
    passed += check(
        f"fleet kill/resume parity: resumed 2-process labels "
        f"byte-identical (restored_rounds={restored_fleet}, "
        f"injected_event_recovered={injected_fleet})",
        fleet_parity and restored_fleet >= 1 and injected_fleet >= 1,
    )

    row = {
        "metric": "fault_probe_scenarios",
        "value": passed,
        "unit": "scenarios",
        "n": n,
        "mesh_devices": _N_DEV,
        "kill_resume": {
            "restored_rounds": restored,
            "labels_match": True,
        },
        "kill_resume_stream": {
            "restored_rounds": restored_stream,
            "labels_match": True,
        },
        "kill_resume_compaction": {
            "restored_rounds": restored_compact,
            "index_byte_identical": True,
        },
        "kill_resume_fleet": {
            "processes": 2,
            "restored_rounds": restored_fleet,
            "fault_injected_seen": injected_fleet,
            "labels_match": True,
        },
        "telemetry": rep,
    }
    print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
