#!/usr/bin/env python
"""Live-observability probe (``make monitor-probe``): prove the export
plane answers DURING a fit, not just after it.

Runs a global-Morton fit on the faked 8-device CPU mesh with the
scrape endpoint (``PYPARDIS_METRICS_PORT=0``) and periodic JSONL
snapshots enabled, and — from this process, while the fit thread is
still inside device work — scrapes ``/metrics`` until one response
carries all three live families at once:

* an open phase span (``pypardis_open_span``),
* per-round heartbeat progress (``pypardis_heartbeat_done`` — the
  global-Morton ring / fixpoint rounds),
* at least one latency histogram series (``..._bucket{le="..."}``).

Every scrape must be well-formed OpenMetrics (``# EOF`` terminated).
If the fit outruns the scraper the probe retries with 2x the points.
Afterwards it drives the query engine, re-attaches the exporter to the
serving recorder, scrapes the ``serving.latency_ms`` histogram,
counts the snapshot lines that parse, renders the fit's flight stream
through ``scripts/monitor.py`` (``--json --once`` and text), and emits
one schema'd row::

    {"metric": "monitor_live_scrape", "value": <scrapes>,
     "unit": "scrapes", "schema": "pypardis_tpu/monitor@1",
     "scrapes": ..., "families": ..., "hist_series": ...,
     "openmetrics_ok": true, "snapshot_lines": ...,
     "monitor_render_ok": true, "serving_hist": {...hist@1...},
     "telemetry": {...run_report@1...}}

validated by ``scripts/check_bench_json.py`` (the ``monitor``
contract) under ``make monitor-probe`` / ``bench-smoke``.

Env knobs: MONITOR_N (fit points, default 40000), MONITOR_DIM
(default 8), MONITOR_Q (serving queries, default 2048),
MONITOR_TIMEOUT_S (overall deadline, default 300).
"""

import json
import os
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _force_cpu_mesh() -> None:
    # Same discipline as tests/conftest.py: the deployment image's
    # sitecustomize may pre-import jax pinned to another platform, so
    # env vars alone can be too late — override via jax.config too.
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    if "jax_num_cpu_devices" in jax.config._value_holders:
        jax.config.update("jax_num_cpu_devices", 8)


def _scrape(port: int, timeout: float = 2.0) -> str:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=timeout
    ) as resp:
        return resp.read().decode("utf-8")


def _families(body: str) -> int:
    return sum(1 for ln in body.splitlines() if ln.startswith("# TYPE "))


def _hist_series(body: str) -> int:
    return sum(1 for ln in body.splitlines() if '_bucket{le="' in ln)


def check(msg: str, ok: bool) -> None:
    status = "ok" if ok else "FAILED"
    print(f"monitor-probe: {msg}: {status}", file=sys.stderr)
    if not ok:
        sys.exit(1)


def main() -> None:
    tmp = tempfile.mkdtemp(prefix="monitor_probe_")
    flight_path = os.path.join(tmp, "flight.jsonl")
    snap_path = os.path.join(tmp, "metrics_snapshot.jsonl")
    # The fit's own train path reads these and attaches the exporters —
    # the probe only ever talks to the endpoint from outside, exactly
    # like a scrape agent would.
    os.environ["PYPARDIS_METRICS_PORT"] = "0"
    os.environ["PYPARDIS_METRICS_SNAPSHOT"] = snap_path
    os.environ["PYPARDIS_METRICS_SNAPSHOT_S"] = "0.1"

    _force_cpu_mesh()
    import numpy as np

    from pypardis_tpu import DBSCAN
    from pypardis_tpu.obs import export as obs_export

    n = int(os.environ.get("MONITOR_N", 40000))
    dim = int(os.environ.get("MONITOR_DIM", 8))
    n_q = int(os.environ.get("MONITOR_Q", 2048))
    deadline = time.time() + float(
        os.environ.get("MONITOR_TIMEOUT_S", 300)
    )

    scrapes = 0
    families = hist_series = 0
    openmetrics_ok = True
    live_ok = False
    model = None
    for attempt in range(4):
        rng = np.random.default_rng(attempt)
        X = rng.normal(size=(n, dim)).astype(np.float32) * 3.0
        model = DBSCAN(
            eps=0.5, min_samples=5, block=256,
            mode="global_morton", flight=flight_path,
        )
        # New binds append to the port log — watch for growth rather
        # than a changed value (the OS may reuse an ephemeral port).
        ports_before = len(obs_export._LAST_HTTP_PORT)
        err: list = []

        def _fit():
            try:
                model.fit(X)
            except Exception as e:  # surfaced below, not swallowed
                err.append(e)

        th = threading.Thread(target=_fit, name="monitor-probe-fit")
        th.start()
        saw_span = saw_hb = saw_hist = False
        while th.is_alive() and time.time() < deadline:
            new_ports = obs_export._LAST_HTTP_PORT[ports_before:]
            if not new_ports:
                time.sleep(0.01)
                continue
            try:
                body = _scrape(new_ports[-1])
            except OSError:
                time.sleep(0.02)  # fit finished; server already down
                continue
            scrapes += 1
            if not body.rstrip().endswith("# EOF"):
                openmetrics_ok = False
            fams, hists = _families(body), _hist_series(body)
            has_span = "pypardis_open_span{" in body
            has_hb = "pypardis_heartbeat_done{" in body
            saw_span |= has_span
            saw_hb |= has_hb
            saw_hist |= hists > 0
            # The row reports a genuinely live frame: prefer the scrape
            # where all three families were present at once.
            if has_span and has_hb and hists > 0:
                families, hist_series = fams, hists
                live_ok = True
            time.sleep(0.05)  # a scrape agent's cadence, not a spin
        th.join()
        if err:
            raise err[0]
        if live_ok:
            break
        print(
            f"monitor-probe: attempt {attempt}: fit outran the scraper "
            f"(scrapes={scrapes} span={saw_span} hb={saw_hb} "
            f"hist={saw_hist}); retrying with n={n * 2}",
            file=sys.stderr,
        )
        n *= 2
    check(
        f"mid-fit scrape saw open span + heartbeat + histogram "
        f"({scrapes} scrapes, {families} families, {hist_series} "
        f"hist series)", live_ok and scrapes >= 1,
    )
    check("every scrape was # EOF-terminated OpenMetrics",
          openmetrics_ok)

    # -- serving histogram over the live endpoint --------------------------
    engine = model.query_engine()
    lo, hi = X.min(axis=0), X.max(axis=0)
    rng = np.random.default_rng(1)
    queries = rng.uniform(lo, hi, size=(n_q, dim)).astype(np.float32)
    tickets = []
    for s in range(0, n_q, 256):
        tickets.append(engine.submit(queries[s:s + 256]))
        if len(tickets) % 8 == 0:
            engine.drain()
    engine.drain()
    for t in tickets:
        t.result()
    stack = obs_export.attach_exporters(engine.recorder, port=0)
    try:
        body = _scrape(stack.http_port)
    finally:
        stack.close()
    check(
        "serving latency histogram scrapes post-fit",
        "pypardis_serving_latency_ms_bucket{" in body
        and body.rstrip().endswith("# EOF"),
    )
    serving_hist = engine.serving_stats()["latency_hist"]

    # -- snapshot stream ---------------------------------------------------
    snap_lines = 0
    with open(snap_path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            r = json.loads(line)
            if r.get("schema") == obs_export.SNAPSHOT_SCHEMA:
                snap_lines += 1
    check(f"snapshot stream parses ({snap_lines} lines)",
          snap_lines >= 1)

    # -- monitor renders the flight stream ---------------------------------
    mon = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "monitor.py")
    out = subprocess.run(
        [sys.executable, mon, flight_path, "--once", "--json"],
        capture_output=True, text=True, timeout=60,
    )
    frame = json.loads(out.stdout) if out.returncode == 0 else {}
    render_ok = (
        out.returncode == 0
        and frame.get("schema") == "pypardis_tpu/monitor_frame@1"
        and frame.get("hosts")
        and frame["hosts"][0]["records"] > 0
    )
    txt = subprocess.run(
        [sys.executable, mon, flight_path, "--once"],
        capture_output=True, text=True, timeout=60,
    )
    render_ok = bool(
        render_ok and txt.returncode == 0 and "records" in txt.stdout
    )
    check("scripts/monitor.py renders the flight stream", render_ok)

    row = {
        "metric": "monitor_live_scrape",
        "value": scrapes,
        "unit": "scrapes",
        "schema": "pypardis_tpu/monitor@1",
        "scrapes": scrapes,
        "families": families,
        "hist_series": hist_series,
        "openmetrics_ok": openmetrics_ok,
        "snapshot_lines": snap_lines,
        "monitor_render_ok": render_ok,
        "serving_hist": serving_hist,
        "telemetry": model.report(),
    }
    print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
