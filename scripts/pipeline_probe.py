"""Where does end-to-end time go at scale? Stage-by-stage timing."""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from scale_probe import make_data


def main():
    n = int(sys.argv[1])
    d = int(sys.argv[2]) if len(sys.argv) > 2 else 16
    eps = 2.4
    block = 2048
    X = make_data(n, d)

    from pypardis_tpu.ops.pipeline import dbscan_device_pipeline
    from pypardis_tpu.utils import round_up

    t0 = time.perf_counter()
    center = X.mean(axis=0, dtype=np.float64)
    cap = round_up(n, block)
    pts_t = np.zeros((d, cap), np.float32)
    chunk = 1 << 20
    for s in range(0, n, chunk):
        e = min(s + chunk, n)
        np.subtract(X[s:e].T, center[:, None], out=pts_t[:, s:e],
                    casting="unsafe")
    t_host = time.perf_counter() - t0

    t0 = time.perf_counter()
    dev = jnp.asarray(pts_t)
    jax.block_until_ready(dev)
    t_upload = time.perf_counter() - t0
    del dev

    def run():
        # Fresh device copy per call: the pipeline's layout gather
        # donates (and so deletes) its input.
        return dbscan_device_pipeline(
            jnp.asarray(pts_t), eps, n, min_samples=10, metric="euclidean",
            block=block, precision="high", backend="auto", sort=True,
        )

    run()  # warm-up (compiles)
    t0 = time.perf_counter()
    packed = run()  # returns a host array: fetch included
    t_dev = time.perf_counter() - t0

    from pypardis_tpu.ops import densify_labels
    from pypardis_tpu.ops.pipeline import unpack_pipeline_result

    t0 = time.perf_counter()
    roots = unpack_pipeline_result(packed)[0]
    labels = densify_labels(roots[:n])
    t_dense = time.perf_counter() - t0

    print(
        f"n={n}: host_prep={t_host:.2f}s upload={t_upload:.2f}s "
        f"device_pipeline+fetch={t_dev:.2f}s "
        f"densify={t_dense:.2f}s clusters={labels.max() + 1}"
    )


if __name__ == "__main__":
    main()
