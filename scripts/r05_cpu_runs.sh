#!/bin/bash
# Round-5 CPU-mesh probe sequence (run with the chip idle — this host
# has ONE core and the rows' warm timings matter).
set -x
cd /root/repo

# 16-partition cliff (r4 review, Next #5): 500k x 4-D, warm/cold split,
# max_partitions in {8, 16, 32} on the 8-device CPU mesh.
for mp in 8 16 32; do
  timeout 5400 python scripts/meshscale_probe.py 500000 device $mp 0.3 \
    >> /tmp/cpu_rows.jsonl 2>/tmp/cpu_cliff_$mp.log
done

# Skewed density through the mesh at 2M x 4-D (r4 review, Next #3).
timeout 7200 python scripts/meshscale_probe.py 2000000 device 8 0.3 --skew lognormal \
  >> /tmp/cpu_rows.jsonl 2>/tmp/cpu_skew_2m.log
timeout 7200 python scripts/meshscale_probe.py 2000000 ring 8 0.3 --skew lognormal \
  >> /tmp/cpu_rows.jsonl 2>/tmp/cpu_skew_2m_ring.log

# Cross-mode agreement at 1M uniform (device/ring/ring_host), carrying
# the new oracle + warm/cold columns.
for mode in device ring ring_host; do
  timeout 7200 python scripts/meshscale_probe.py 1000000 $mode 8 0.3 \
    >> /tmp/cpu_rows.jsonl 2>/tmp/cpu_1m_$mode.log
done

echo ALL-CPU-ROWS-DONE
