#!/usr/bin/env python
"""Validate bench.py's one-line JSON output (``make bench-smoke``).

Reads stdin (or a file given as argv[1]), finds the last JSON object
line, and checks the benchmark row schema: the classic
``metric``/``value``/``unit`` triple plus the ``telemetry`` block
(``pypardis_tpu/run_report@1`` — the same dict ``DBSCAN.report()``
returns).  Exits nonzero with a reason on any violation, so CI catches
schema drift before a BENCH_*.json archive does.

``--require-diff`` (the ``make bench-smoke`` pipe, downstream of
``scripts/bench_diff.py --annotate``) additionally requires the row's
``bench_diff`` verdict field and FAILS on a ``regression`` verdict —
the cross-round perf trajectory is an enforced invariant, not an
archive to eyeball.
"""

import json
import sys


def fail(msg: str) -> None:
    print(f"bench JSON check FAILED: {msg}", file=sys.stderr)
    sys.exit(1)


def check_hist(block, where: str) -> None:
    """Validate one bounded-histogram snapshot
    (``pypardis_tpu/hist@1``): the windowed-percentile latency block
    serving/load/ingest rows carry."""
    if not isinstance(block, dict):
        fail(f"{where} is {block!r}, expected a hist@1 dict")
    if block.get("schema") != "pypardis_tpu/hist@1":
        fail(f"{where}.schema is {block.get('schema')!r}")
    for key in ("count", "window_count", "overflow"):
        v = block.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            fail(f"{where}.{key} is {v!r}, expected a non-negative int")
    for key in ("p50_ms", "p99_ms", "sum_ms", "max_ms"):
        v = block.get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool) \
                or v != v or v in (float("inf"), float("-inf")):
            fail(f"{where}.{key} is {v!r}, expected a finite number")
    buckets = block.get("buckets")
    if not isinstance(buckets, list):
        fail(f"{where}.buckets is {buckets!r}, expected a list")
    prev_le = float("-inf")
    total = 0
    for i, b in enumerate(buckets):
        if (
            not isinstance(b, list) or len(b) != 2
            or not isinstance(b[0], (int, float))
            or not isinstance(b[1], int) or isinstance(b[1], bool)
            or b[1] < 0
        ):
            fail(f"{where}.buckets[{i}] is {b!r}, expected [le, count]")
        if b[0] <= prev_le:
            fail(
                f"{where}.buckets[{i}] le {b[0]!r} not ascending "
                f"(prev {prev_le!r})"
            )
        prev_le = b[0]
        total += b[1]
    if total + block["overflow"] != block["count"]:
        fail(
            f"{where} bucket counts sum to "
            f"{total + block['overflow']}, count says {block['count']}"
        )


def main() -> None:
    args = [a for a in sys.argv[1:] if a != "--require-diff"]
    require_diff = "--require-diff" in sys.argv[1:]
    if args:
        data = open(args[0]).read()
    else:
        data = sys.stdin.read()
    lines = [
        ln for ln in data.strip().splitlines()
        if ln.lstrip().startswith("{")
    ]
    if not lines:
        fail("no JSON line found on stdout")
    try:
        row = json.loads(lines[-1])
    except json.JSONDecodeError as e:
        fail(f"last JSON-looking line does not parse: {e}")

    for key in ("metric", "value", "unit"):
        if key not in row:
            fail(f"missing top-level key {key!r}")
    if not isinstance(row["value"], (int, float)):
        fail(f"value is {type(row['value']).__name__}, expected number")

    tel = row.get("telemetry")
    if not isinstance(tel, dict):
        fail("missing/invalid 'telemetry' block")
    if tel.get("schema") != "pypardis_tpu/run_report@1":
        fail(f"telemetry schema is {tel.get('schema')!r}")
    for key in ("run", "phases", "sharding", "compute", "devices",
                "events", "metrics"):
        if key not in tel:
            fail(f"telemetry missing section {key!r}")

    def number(section, key):
        v = tel[section].get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            fail(f"telemetry.{section}.{key} is {v!r}, expected number")
        if v != v or v in (float("inf"), float("-inf")):
            fail(f"telemetry.{section}.{key} is non-finite ({v!r})")
        return v

    for key in ("halo_factor", "pad_waste"):
        number("sharding", key)
    # Owner-computes / staging perf contract (ISSUE 2): the duplicated
    # clustered-volume factor and the staging-reuse counter must be
    # present and finite on EVERY row (single-shard rows report 1.0/0).
    number("sharding", "duplicated_work_factor")
    number("sharding", "staged_bytes_reused")
    # Honest-mode contract (ISSUE 5): every row says whether the
    # owner-computes step actually ran — the 1-device chained route
    # reports False (it runs the legacy step), never omits the field.
    if not isinstance(tel["sharding"].get("owner_computes"), bool):
        fail(
            f"telemetry.sharding.owner_computes is "
            f"{tel['sharding'].get('owner_computes')!r}, expected bool"
        )
    # Host-pipeline contract (ISSUE 3): the chained-loop overlap gauge
    # and the partitioner's per-level build breakdown must be present
    # and finite on EVERY row (single-shard rows report 0.0 / []).
    number("sharding", "overlap_efficiency")
    levels = tel["sharding"].get("partition_levels_s")
    if not isinstance(levels, list):
        fail(
            f"telemetry.sharding.partition_levels_s is {levels!r}, "
            f"expected a list"
        )
    for i, v in enumerate(levels):
        if not isinstance(v, (int, float)) or isinstance(v, bool) \
                or v != v or v in (float("inf"), float("-inf")):
            fail(
                f"telemetry.sharding.partition_levels_s[{i}] is {v!r}, "
                f"expected a finite number"
            )
    # Achieved-FLOP/s model: live pairs, pass count, mfu — finite
    # numbers always; a fit with no pair telemetry reports zeros, never
    # NaN.
    for key in ("live_pairs", "kernel_passes",
                "achieved_flops_per_sec", "mfu"):
        number("compute", key)
    # Mixed-precision contract (ISSUE 7 / ROADMAP item 3): every row
    # states its kernel precision mode and carries the band-rescoring
    # telemetry — zero off precision="mixed", finite always; mfu is
    # reported against BOTH the bf16 peak (mfu) and the f32-synth
    # (bf16_3x) effective peak.
    mode = tel["compute"].get("precision_mode")
    if mode not in ("default", "high", "highest", "mixed"):
        fail(
            f"telemetry.compute.precision_mode is {mode!r}, expected "
            f"one of default|high|highest|mixed"
        )
    for key in ("band_fraction", "rescored_pairs", "band_pairs",
                "mfu_f32_synth"):
        number("compute", key)
    if number("compute", "band_fraction") > 1.0:
        fail(
            f"telemetry.compute.band_fraction "
            f"{tel['compute']['band_fraction']!r} exceeds 1.0"
        )
    # Dispatch-level sparsity contract (ISSUE 11): every row says what
    # fraction of the dense T^2 tile grid the kernels actually visited
    # and how much of the boundary-ring wall hid behind the overlapped
    # counts pass — both fractions, both finite, on every route.
    for key in ("live_pair_fraction", "exchange_overlap_efficiency"):
        v = number("compute", key)
        if not 0.0 <= v <= 1.0:
            fail(
                f"telemetry.compute.{key} {v!r} outside [0, 1]"
            )
    # Resource-watermark contract (ISSUE 6): every row carries the
    # sampler's peaks, finite on every route (0 is legal — e.g. device
    # bytes on backends that don't report memory_stats — NaN never is).
    if not isinstance(tel.get("resources"), dict):
        fail("missing/invalid 'resources' block")
    for key in ("peak_host_rss_bytes", "peak_device_bytes",
                "staging_pool_bytes"):
        number("resources", key)
    for key in ("restage", "pair_overflow", "halo_overflow",
                "merge_unconverged", "compile", "fault_injected",
                "degraded"):
        if key not in tel["events"]:
            fail(f"telemetry.events missing {key!r}")
    # Fault-tolerance contract (ISSUE 9): every row carries the faults
    # block — injection volume, unified-retry attempts/giveups, and the
    # degradation rung taken.  Clean rows (anything not emitted by the
    # fault probe itself) must show ZERO injections: the injection
    # sites compile to no-ops when PYPARDIS_FAULTS is unset, and a
    # nonzero count on a bench row means a plan leaked into CI.
    fa = tel.get("faults")
    if not isinstance(fa, dict):
        fail("missing/invalid 'faults' block")
    for key in ("injected", "retried", "giveups", "degraded"):
        v = fa.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            fail(
                f"telemetry.faults.{key} is {v!r}, expected a "
                f"non-negative int"
            )
    if not isinstance(fa.get("degraded_to"), str):
        fail(
            f"telemetry.faults.degraded_to is "
            f"{fa.get('degraded_to')!r}, expected a string"
        )
    if not str(row["metric"]).startswith("fault") and fa["injected"]:
        fail(
            f"clean row has telemetry.faults.injected == "
            f"{fa['injected']} (PYPARDIS_FAULTS leaked into this run?)"
        )
    if not tel["phases"]:
        fail("telemetry.phases is empty")
    if "points" not in tel["devices"]:
        fail("telemetry.devices missing per-device point counts")

    # Global-Morton contract (ISSUE 5): a global_morton row must have
    # actually run the morton-ring path — a silent fallback to the KD
    # halo machinery (wrong halo_exchange, duplication above 1.0, or a
    # missing boundary-tile gauge) fails CI here, and the boundary-tile
    # traffic must undercut the legacy halo bytes on the same geometry.
    if str(row["metric"]).startswith("global_morton"):
        if tel["sharding"].get("mode") != "global_morton":
            fail("global_morton row without sharding.mode=global_morton")
        if tel["sharding"].get("halo_exchange") != "morton_ring":
            fail(
                f"global_morton row fell back to halo_exchange="
                f"{tel['sharding'].get('halo_exchange')!r} (expected "
                f"'morton_ring')"
            )
        if number("sharding", "duplicated_work_factor") != 1.0:
            fail(
                f"global_morton duplicated_work_factor is "
                f"{tel['sharding']['duplicated_work_factor']!r}, "
                f"expected exactly 1.0 (zero-duplication contract)"
            )
        if tel["sharding"].get("owner_computes") is not True:
            fail("global_morton row must report owner_computes=True")
        for key in ("boundary_tile_bytes", "boundary_tiles",
                    "ring_rounds", "fixpoint_rounds"):
            number("sharding", key)
        legacy = row.get("legacy_halo_bytes")
        if isinstance(legacy, (int, float)) and not isinstance(
            legacy, bool
        ):
            bnd = tel["sharding"]["boundary_tile_bytes"]
            if bnd >= legacy:
                fail(
                    f"boundary_tile_bytes {bnd} not below legacy "
                    f"halo_bytes {legacy} on the same geometry"
                )
        for key in ("speedup_vs_oc", "fixpoint_rounds"):
            v = row.get(key)
            if v is not None and (
                not isinstance(v, (int, float)) or isinstance(v, bool)
                or v != v
            ):
                fail(f"row.{key} is {v!r}, expected a finite number")

    # Serving contract (ISSUE 4): serve_probe rows must carry the
    # ``serving`` block with finite QPS / latency-percentile /
    # batch-fill gauges; any row that has one is held to the schema.
    if str(row["metric"]).startswith("serve") and "serving" not in tel:
        fail("serve row without telemetry.serving block")
    serving = tel.get("serving")
    if serving is not None:
        if not isinstance(serving, dict):
            fail(f"telemetry.serving is {type(serving).__name__}")
        for key in ("qps", "p50_ms", "p99_ms", "batch_fill"):
            number("serving", key)
        for key in ("queries", "batches", "n_core", "n_leaves",
                    "shed_total", "deadline_failures"):
            v = serving.get(key)
            if not isinstance(v, int) or v < 0:
                fail(
                    f"telemetry.serving.{key} is {v!r}, expected a "
                    f"non-negative int"
                )
        if serving["queries"] > 0 and serving["qps"] <= 0:
            fail("telemetry.serving.qps is 0 with queries > 0")

    # Live-update contract (ISSUE 8): live_* rows must carry the
    # ``live`` telemetry block — update volumes, the measured
    # re-cluster blast radius, the in-place index-refresh economy, and
    # update-latency percentiles — all finite; the tile fraction is a
    # fraction.  Any row that has a live block is held to the schema.
    if str(row["metric"]).startswith("live") and "live" not in tel:
        fail("live row without telemetry.live block")
    live = tel.get("live")
    if live is not None:
        if not isinstance(live, dict):
            fail(f"telemetry.live is {type(live).__name__}")
        for key in ("recluster_tile_fraction", "insert_p50_ms",
                    "insert_p99_ms", "delete_p50_ms", "delete_p99_ms"):
            v = live.get(key)
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or v != v or v in (float("inf"), float("-inf")):
                fail(f"telemetry.live.{key} is {v!r}, expected a "
                     f"finite number")
        if not 0.0 <= live["recluster_tile_fraction"] <= 1.0:
            fail(
                f"telemetry.live.recluster_tile_fraction "
                f"{live['recluster_tile_fraction']!r} outside [0, 1]"
            )
        for key in ("points", "cores", "inserts", "deletes", "updates",
                    "recluster_events", "index_epoch",
                    "index_delta_bytes"):
            v = live.get(key)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                fail(
                    f"telemetry.live.{key} is {v!r}, expected a "
                    f"non-negative int"
                )
        # Streaming-ingest contract (ISSUE 12): every live block says
        # how writes batched (sizes of the applied write batches), the
        # amortization it bought (recluster events per written row),
        # and the LSM maintenance economy (compaction cycles, their
        # seconds, whole-index epoch swaps) — always present, finite.
        bs = live.get("batch_sizes")
        if not isinstance(bs, list):
            fail(
                f"telemetry.live.batch_sizes is {bs!r}, expected a list"
            )
        for i, v in enumerate(bs):
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                fail(
                    f"telemetry.live.batch_sizes[{i}] is {v!r}, "
                    f"expected a non-negative int"
                )
        for key in ("reclusters_per_write", "compaction_s"):
            v = live.get(key)
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or v != v or v in (float("inf"), float("-inf")) \
                    or v < 0:
                fail(
                    f"telemetry.live.{key} is {v!r}, expected a finite "
                    f"number >= 0"
                )
        for key in ("compactions", "epoch_swaps",
                    "recluster_dispatches"):
            v = live.get(key)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                fail(
                    f"telemetry.live.{key} is {v!r}, expected a "
                    f"non-negative int"
                )
    if str(row["metric"]) == "live_load_qps":
        load = row.get("load")
        if not isinstance(load, dict):
            fail("live_load_qps row without the load payload")
        if load.get("arrival") != "poisson":
            fail(f"load.arrival is {load.get('arrival')!r}")
        if int(load.get("clients", 0)) < 4:
            fail(f"sustained load ran {load.get('clients')!r} clients, "
                 f"need >= 4")
        for key in ("qps", "p50_ms", "p99_ms", "batch_fill",
                    "update_visible_p50_ms", "update_visible_p99_ms"):
            v = load.get(key)
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or v != v or v in (float("inf"), float("-inf")):
                fail(f"load.{key} is {v!r}, expected a finite number")
        # ISSUE 16: the percentiles must come from the bounded windowed
        # histogram, and the row must carry the histogram itself so a
        # diff can compare full latency shapes, not two scalars.
        check_hist(load.get("latency_hist"), "load.latency_hist")
    if str(row["metric"]) == "live_replicated_speedup":
        v = row.get("value")
        if not isinstance(v, (int, float)) or v != v or v <= 0:
            fail(f"replicated speedup is {v!r}")
        rep = row.get("replicated")
        if not isinstance(rep, dict):
            fail("live_replicated_speedup row without replicated stats")
        if int(rep.get("replicated_devices", 0)) < 2:
            fail(
                f"replicated mode ran on "
                f"{rep.get('replicated_devices')!r} device(s)"
            )
        if int(rep.get("per_device_index_bytes", 0)) <= 0:
            fail(
                f"per_device_index_bytes is "
                f"{rep.get('per_device_index_bytes')!r}"
            )

    # Streaming-ingest contract (ISSUE 12): the mixed read/write row is
    # the "millions of users, and they write too" artifact — it must
    # say what ran (readers AND writers), prove the never-stop-the-
    # world claim (>= 1 background compaction + epoch swap completed
    # with ZERO dropped tickets), and carry finite throughput /
    # latency / update-visibility / overlap-degradation gauges.
    if str(row["metric"]) == "ingest_mixed_load":
        if row.get("schema") != "pypardis_tpu/ingest@1":
            fail(f"ingest row schema is {row.get('schema')!r}")
        load = row.get("load")
        if not isinstance(load, dict):
            fail("ingest_mixed_load row without the load payload")
        if load.get("arrival") != "poisson":
            fail(f"load.arrival is {load.get('arrival')!r}")
        if int(load.get("clients", 0)) < 2:
            fail(
                f"ingest load ran {load.get('clients')!r} reader(s), "
                f"need >= 2"
            )
        if int(load.get("writers", 0)) < 1:
            fail(
                f"ingest load ran {load.get('writers')!r} writer(s), "
                f"need >= 1"
            )
        if int(load.get("compactions", 0)) < 1:
            fail("ingest load completed no background compaction")
        if int(load.get("epoch_swaps", 0)) < 1:
            fail("ingest load saw no epoch swap")
        if int(load.get("dropped_tickets", -1)) != 0:
            fail(
                f"ingest load dropped "
                f"{load.get('dropped_tickets')!r} ticket(s); the epoch "
                f"swap must drain, never drop"
            )
        if int(load.get("write_failures", 0)) != 0:
            fail(
                f"ingest load had {load.get('write_failures')!r} "
                f"failed write batch(es)"
            )
        for key in ("qps", "write_qps", "p50_ms", "p99_ms",
                    "update_visible_p50_ms", "update_visible_p99_ms",
                    "read_p99_during_compaction_ms",
                    "read_p99_outside_ms", "mean_write_batch",
                    "compaction_overlap_degradation", "compaction_s"):
            v = load.get(key)
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or v != v or v in (float("inf"), float("-inf")):
                fail(f"load.{key} is {v!r}, expected a finite number")
        check_hist(load.get("latency_hist"), "load.latency_hist")
        if "live" not in tel:
            fail("ingest_mixed_load row without telemetry.live block")

    # Multi-tenant gateway contract (ISSUE 19): the fleet row is the
    # "many models, many tenants, one accelerator" artifact — a
    # registry of >= 8 models under a byte budget that actually forced
    # eviction, readmission proven byte-identical, >= 1 hot-swap epoch
    # swap landed mid-traffic with ZERO dropped tickets, and per-tenant
    # windowed latency (plus the inside/outside eviction+swap-window
    # split) carried as measured histograms, not prose.
    if str(row["metric"]) == "gateway_fleet_load":
        if row.get("schema") != "pypardis_tpu/gateway@1":
            fail(f"gateway row schema is {row.get('schema')!r}")
        if row.get("reload_byte_identical") is not True:
            fail(
                f"reload_byte_identical is "
                f"{row.get('reload_byte_identical')!r}; readmitted "
                f"models must answer bitwise equal to pre-eviction"
            )
        load = row.get("load")
        if not isinstance(load, dict):
            fail("gateway_fleet_load row without the load payload")
        if load.get("arrival") != "poisson-zipf":
            fail(f"load.arrival is {load.get('arrival')!r}")
        if int(load.get("tenants", 0)) < 2:
            fail(f"gateway load ran {load.get('tenants')!r} "
                 f"tenant(s), need >= 2")
        gwrep = load.get("gateway")
        if not isinstance(gwrep, dict):
            fail("gateway load without the gateway_report block")
        if gwrep.get("schema") != "pypardis_tpu/gateway_report@1":
            fail(
                f"gateway_report schema is {gwrep.get('schema')!r}"
            )
        if int(gwrep.get("models_registered", 0)) < 8:
            fail(
                f"gateway served {gwrep.get('models_registered')!r} "
                f"model(s), need >= 8"
            )
        if int(gwrep.get("budget_bytes", 0)) <= 0:
            fail("gateway ran without a residency byte budget")
        if int(gwrep.get("resident_bytes", -1)) > \
                int(gwrep.get("budget_bytes", 0)):
            fail(
                f"resident bytes {gwrep.get('resident_bytes')!r} "
                f"exceed the budget {gwrep.get('budget_bytes')!r}"
            )
        for key in ("evictions", "reloads", "epoch_swaps"):
            if int(gwrep.get(key, 0)) < 1:
                fail(f"gateway load saw no {key}; the budget/swap "
                     f"machinery did not exercise")
        if int(load.get("dropped_tickets", -1)) != 0:
            fail(
                f"gateway load dropped "
                f"{load.get('dropped_tickets')!r} ticket(s); "
                f"eviction, readmission, and the epoch swap must "
                f"drain, never drop"
            )
        if int(load.get("deadline_failures", 0)) != 0:
            fail(
                f"gateway load failed "
                f"{load.get('deadline_failures')!r} ticket(s)"
            )
        for key in ("qps", "p50_ms", "p99_ms",
                    "read_p99_in_window_ms", "read_p99_outside_ms"):
            v = load.get(key)
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or v != v or v in (float("inf"), float("-inf")):
                fail(f"load.{key} is {v!r}, expected a finite number")
        check_hist(load.get("latency_hist"), "load.latency_hist")
        tenants = gwrep.get("tenants")
        if not isinstance(tenants, dict) or len(tenants) < 2:
            fail(
                f"gateway report carries "
                f"{len(tenants) if isinstance(tenants, dict) else 0} "
                f"tenant stat block(s), need >= 2"
            )
        for name, st in tenants.items():
            for key in ("p50_ms", "p99_ms"):
                v = st.get(key)
                if not isinstance(v, (int, float)) \
                        or isinstance(v, bool) or v != v \
                        or v in (float("inf"), float("-inf")):
                    fail(
                        f"tenant {name!r} {key} is {v!r}, expected a "
                        f"finite number"
                    )
            check_hist(
                st.get("latency_hist"),
                f"tenant {name!r} latency_hist",
            )

    # Pod-scale execution contract (ISSUE 20): the multihost row proves
    # the multi-process mesh is not a demo — a >= 2-process fleet whose
    # fits land BYTE-IDENTICAL to the single-process run of the same
    # global device count (both merges + the KD route + the streaming
    # build), a SIGKILL-mid-fixpoint drill that resumed from the
    # coordinator's snapshot back to parity with the injected fault
    # visible in the merged fleet flight, and a same-host fleet whose
    # clock-skew flag stayed quiet.  The P=4 streaming-build speedup
    # (>= 1.8x) is enforced only when the probe had the cores to gate
    # it (build.gated) — a 1-core CI box reports, it does not gate.
    if str(row["metric"]) == "multihost_pod_parity":
        if row.get("schema") != "pypardis_tpu/multihost@1":
            fail(f"multihost row schema is {row.get('schema')!r}")
        if int(row.get("processes", 0)) < 2:
            fail(f"multihost row ran {row.get('processes')!r} "
                 f"process(es), need >= 2")
        par = row.get("parity")
        if not isinstance(par, dict):
            fail("multihost row without the parity block")
        for key in ("gm_device", "gm_host", "kd", "stream"):
            if par.get(key) is not True:
                fail(
                    f"multihost parity.{key} is {par.get(key)!r}; the "
                    f"fleet fit must be byte-identical to the "
                    f"single-process run"
                )
        drill = row.get("drill")
        if not isinstance(drill, dict):
            fail("multihost row without the fault-drill block")
        if drill.get("parity") is not True:
            fail(f"multihost drill.parity is {drill.get('parity')!r}")
        if int(drill.get("restored_rounds", 0)) < 1:
            fail(
                f"multihost drill restored "
                f"{drill.get('restored_rounds')!r} round(s); the "
                f"resume must replay snapshotted work, not refit"
            )
        if int(drill.get("fault_injected_seen", 0)) < 1:
            fail("multihost drill saw no fault_injected event in the "
                 "killed run's merged flight")
        build = row.get("build")
        if not isinstance(build, dict):
            fail("multihost row without the build block")
        for key in ("solo_s", "fleet_s", "speedup"):
            v = build.get(key)
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or v != v or v in (float("inf"), float("-inf")):
                fail(f"build.{key} is {v!r}, expected a finite number")
        if build.get("gated") is True and float(build["speedup"]) < 1.8:
            fail(
                f"gated P={build.get('procs')!r} streaming-build "
                f"speedup {build['speedup']!r} < 1.8x"
            )
        ff = row.get("fleet_flight")
        if not isinstance(ff, dict):
            fail("multihost row without the fleet_flight block")
        if int(ff.get("members", 0)) != int(row.get("processes", 0)):
            fail(
                f"fleet flight merged {ff.get('members')!r} member "
                f"file(s) for {row.get('processes')!r} process(es)"
            )
        if ff.get("complete") is not True:
            fail("fleet flight merge is incomplete (a member flight "
                 "is missing its seal)")
        if ff.get("clock_skew_warning") is not False:
            fail(
                f"fleet clock_skew_warning is "
                f"{ff.get('clock_skew_warning')!r} on a same-host "
                f"fleet; expected False"
            )

    # Live-observability contract (ISSUE 16): a monitor row proves the
    # export plane actually answered DURING the fit — the probe must
    # have scraped the OpenMetrics endpoint mid-run (>= 1 scrape with
    # parseable families including >= 1 histogram series), collected
    # >= 1 periodic JSONL snapshot line, rendered the flight stream
    # through scripts/monitor.py, and carry the serving histogram it
    # scraped so the claim is a measured artifact, not prose.
    if str(row["metric"]).startswith("monitor"):
        if row.get("schema") != "pypardis_tpu/monitor@1":
            fail(f"monitor row schema is {row.get('schema')!r}")
        for key in ("scrapes", "families", "hist_series",
                    "snapshot_lines"):
            v = row.get(key)
            if not isinstance(v, int) or isinstance(v, bool) or v < 1:
                fail(f"monitor row.{key} is {v!r}, expected int >= 1")
        for key in ("openmetrics_ok", "monitor_render_ok"):
            if row.get(key) is not True:
                fail(f"monitor row.{key} is {row.get(key)!r}, "
                     f"expected True")
        check_hist(row.get("serving_hist"), "monitor row.serving_hist")

    # North-star contract (ISSUE 10 / ROADMAP item 1): a northstar row
    # is the measured 100M-trajectory artifact — it must decompose the
    # fit into finite build / exchange / compute / merge seconds that
    # actually account for the wall (no silent unattributed time), say
    # what ran (n / dim / mode / devices), carry the sampled peak
    # RssAnon (the out-of-core claim is a MEASURED number, not prose),
    # and state whether checkpoint-resume replayed prior work.  The
    # clean-row faults.injected==0 gate above already applies.
    if str(row["metric"]).startswith("northstar"):
        if row.get("schema") != "pypardis_tpu/northstar@1":
            fail(f"northstar row schema is {row.get('schema')!r}")
        for key in ("n", "dim", "mesh_devices"):
            v = row.get(key)
            if not isinstance(v, int) or v <= 0:
                fail(f"northstar row.{key} is {v!r}, expected int > 0")
        if row.get("mode") not in ("gm_mesh", "gm_chained"):
            fail(f"northstar row.mode is {row.get('mode')!r}")
        comps = {}
        for key in ("build_s", "exchange_s", "compute_s", "merge_s"):
            v = row.get(key)
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or v != v or v < 0 or v == float("inf"):
                fail(f"northstar row.{key} is {v!r}, expected a "
                     f"finite number >= 0")
            comps[key] = float(v)
        wall = float(row["value"])
        total = sum(comps.values())
        if total > wall * 1.02 + 0.5:
            fail(
                f"northstar phase seconds sum to {total:.3f}s, above "
                f"the {wall:.3f}s wall"
            )
        if total < 0.4 * wall - 0.5:
            fail(
                f"northstar phase seconds sum to {total:.3f}s — less "
                f"than 40% of the {wall:.3f}s wall is attributed; the "
                f"decomposition is not honest"
            )
        rss = row.get("rss_anon_peak_gb")
        if not isinstance(rss, (int, float)) or isinstance(rss, bool) \
                or rss != rss or rss <= 0:
            fail(f"northstar rss_anon_peak_gb is {rss!r}")
        if not isinstance(row.get("resume_used"), bool):
            fail(
                f"northstar resume_used is {row.get('resume_used')!r}, "
                f"expected bool"
            )
        if tel["sharding"].get("mode") != "global_morton":
            fail("northstar row did not run the global-Morton engine")

    # Amortized-sweep contract (ISSUE 13): a sweep row must prove the
    # one-distance-pass claim (distance_passes == 1 on a non-degraded
    # row), carry a real graph, state per-config exactness (labels
    # byte-identical + ARI == 1.0 vs solo fits), and — like every
    # other row — the honest owner_computes / dispatch-tag fields.
    if str(row["metric"]).startswith("sweep"):
        if row.get("schema") != "pypardis_tpu/sweep@1":
            fail(f"sweep row schema is {row.get('schema')!r}")
        k = row.get("k")
        if not isinstance(k, int) or k < 2:
            fail(f"sweep row.k is {k!r}, expected int >= 2")
        sw = tel.get("sweep")
        if not isinstance(sw, dict):
            fail("sweep row without telemetry.sweep block")
        degraded = sw.get("degraded")
        dp = row.get("distance_passes")
        if degraded is None and dp != 1:
            fail(
                f"sweep row ran {dp!r} distance passes without a "
                f"degradation reason — the one-pass claim is the row's "
                f"whole point"
            )
        gp = row.get("graph_pairs")
        if not isinstance(gp, int) or (degraded is None and gp <= 0):
            fail(f"sweep row.graph_pairs is {gp!r}")
        v = row.get("value")
        if not isinstance(v, (int, float)) or v != v or v <= 0:
            fail(f"sweep amortization value is {v!r}")
        pcs = row.get("per_config")
        if not isinstance(pcs, list) or len(pcs) != k:
            fail(f"sweep row.per_config has {pcs!r}, expected {k} entries")
        for i, pc in enumerate(pcs):
            if pc.get("labels_match") is not True:
                fail(f"per_config[{i}] labels_match is not True")
            if pc.get("ari") != 1.0:
                fail(f"per_config[{i}] ari is {pc.get('ari')!r}, not 1.0")
            rl = pc.get("relabel_s")
            if not isinstance(rl, (int, float)) or rl != rl or rl < 0:
                fail(f"per_config[{i}] relabel_s is {rl!r}")
        # The comparability contract every row carries, asserted on
        # the sweep block too (stale-NOTE satellite: sweep rows must
        # be as honest about what ran as fit rows are).
        if not isinstance(sw.get("owner_computes"), bool):
            fail(
                f"telemetry.sweep.owner_computes is "
                f"{sw.get('owner_computes')!r}, expected bool"
            )
        if sw.get("dispatch") not in ("pair", "dense"):
            fail(
                f"telemetry.sweep.dispatch is {sw.get('dispatch')!r}, "
                f"expected 'pair' or 'dense'"
            )
        for key in ("graph_bytes", "distance_passes"):
            if not isinstance(sw.get(key), int):
                fail(f"telemetry.sweep.{key} is {sw.get(key)!r}")

    # Density-hierarchy contract (ISSUE 18): a hierarchy row must prove
    # the one-distance-pass claim for the WHOLE ladder, carry the
    # spanning-forest invariant from telemetry (mst_edges ==
    # n_live - n_components), keep Boruvka within its logarithmic round
    # cap, state per-rung exactness (labels byte-identical + ARI == 1.0
    # vs solo fits at the same eps), and a stability-selected eps.
    if str(row["metric"]).startswith("hierarchy"):
        if row.get("schema") != "pypardis_tpu/hierarchy@1":
            fail(f"hierarchy row schema is {row.get('schema')!r}")
        k = row.get("k")
        if not isinstance(k, int) or k < 2:
            fail(f"hierarchy row.k is {k!r}, expected int >= 2")
        hr = tel.get("hierarchy")
        if not isinstance(hr, dict):
            fail("hierarchy row without telemetry.hierarchy block")
        if row.get("distance_passes") != 1 or hr.get(
            "distance_passes"
        ) != 1:
            fail(
                f"hierarchy row ran {row.get('distance_passes')!r} "
                f"distance passes — the one-pass ladder is the row's "
                f"whole point"
            )
        for key in ("mst_edges", "boruvka_rounds", "round_cap",
                    "n_live", "n_components", "condensed_clusters",
                    "selected_clusters"):
            v = hr.get(key)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                fail(
                    f"telemetry.hierarchy.{key} is {v!r}, expected a "
                    f"non-negative int"
                )
        if hr["boruvka_rounds"] > hr["round_cap"]:
            fail(
                f"boruvka_rounds {hr['boruvka_rounds']} exceeds the "
                f"logarithmic cap {hr['round_cap']}"
            )
        if hr["mst_edges"] != hr["n_live"] - hr["n_components"]:
            fail(
                f"mst_edges {hr['mst_edges']} != n_live "
                f"{hr['n_live']} - n_components {hr['n_components']} "
                f"— not a spanning forest"
            )
        for key in ("eps_selected", "stability_total"):
            v = hr.get(key)
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or v != v or v in (float("inf"), float("-inf")) \
                    or v < 0:
                fail(
                    f"telemetry.hierarchy.{key} is {v!r}, expected a "
                    f"finite number >= 0"
                )
        v = row.get("value")
        if not isinstance(v, (int, float)) or v != v or v <= 0:
            fail(f"hierarchy amortization value is {v!r}")
        ladder = row.get("ladder")
        if not isinstance(ladder, list) or len(ladder) != k:
            fail(
                f"hierarchy row.ladder is {ladder!r}, expected {k} "
                f"rungs"
            )
        prs = row.get("per_rung")
        if not isinstance(prs, list) or len(prs) != k:
            fail(
                f"hierarchy row.per_rung has {prs!r}, expected {k} "
                f"entries"
            )
        for i, pr in enumerate(prs):
            if pr.get("labels_match") is not True:
                fail(f"per_rung[{i}] labels_match is not True")
            if pr.get("ari") != 1.0:
                fail(f"per_rung[{i}] ari is {pr.get('ari')!r}, not 1.0")

    # Sketch-prefilter contract (ISSUE 17): a sketch row must carry a
    # positive resolved projection width, a band fraction in [0, 1],
    # the cross-route byte-parity claim, per-dim counts parity, the GM
    # boundary-bytes invariant (the sketch-space send gate can only
    # SHRINK the ring: sketch bytes <= full-d box bytes), and a finite
    # positive headline win.
    if str(row["metric"]).startswith("sketch"):
        if row.get("schema") != "pypardis_tpu/sketch@1":
            fail(f"sketch row schema is {row.get('schema')!r}")
        sk = row.get("sketch_k")
        if not isinstance(sk, int) or isinstance(sk, bool) or sk <= 0:
            fail(f"sketch row.sketch_k is {sk!r}, expected int > 0")
        v = row.get("value")
        if not isinstance(v, (int, float)) or isinstance(v, bool) \
                or v != v or v in (float("inf"), float("-inf")) or v <= 0:
            fail(f"sketch win value is {v!r}, expected finite > 0")
        bf = row.get("sketch_band_fraction")
        if not isinstance(bf, (int, float)) or isinstance(bf, bool) \
                or bf != bf or not 0 <= bf <= 1:
            fail(
                f"sketch_band_fraction is {bf!r}, expected a finite "
                f"number in [0, 1]"
            )
        if row.get("labels_match") is not True:
            fail(
                "sketch row labels_match is not True — sketch-on labels "
                "must be byte-identical to the exact pass on every route"
            )
        pd = row.get("per_dim")
        if not isinstance(pd, list) or not pd:
            fail(f"sketch row.per_dim is {pd!r}, expected non-empty list")
        for i, entry in enumerate(pd):
            if entry.get("counts_match") is not True:
                fail(f"per_dim[{i}] counts_match is not True")
            ek = entry.get("sketch_k")
            if not isinstance(ek, int) or ek <= 0:
                fail(f"per_dim[{i}] sketch_k is {ek!r}")
        if pd[-1].get("auto_on") is not True:
            fail(
                "sketch row's largest dim did not engage the AUTO "
                "policy — the headline win must come from sketch='auto'"
            )
        bs = row.get("boundary_bytes_sketch")
        bb = row.get("boundary_bytes_box")
        if not isinstance(bs, int) or not isinstance(bb, int):
            fail(
                f"sketch boundary bytes are {bs!r} / {bb!r}, expected "
                f"ints"
            )
        if bs > bb:
            fail(
                f"sketch boundary_bytes_sketch {bs} exceeds the full-d "
                f"box bound {bb} — the send gate may only shrink the "
                f"ring"
            )
        if int(tel.get("compute", {}).get("sketch_k", 0)) != sk:
            fail(
                "telemetry.compute.sketch_k disagrees with the row's "
                "resolved sketch_k"
            )

    # Auto-tuning contract (ISSUE 14): a tune row must carry the plan
    # (all five knobs), FINITE predicted per-phase seconds, a probe
    # overhead within the 5% budget, proof that auto-vs-explicit
    # labels were byte-identical, and a >= 6-point measured lattice
    # with the planned config inside the 1.25x envelope of its best.
    if str(row["metric"]).startswith("tune"):
        if row.get("schema") != "pypardis_tpu/tune@1":
            fail(f"tune row schema is {row.get('schema')!r}")
        tn = tel.get("tune")
        if not isinstance(tn, dict):
            fail("tune row without telemetry.tune block")
        plan = row.get("plan")
        if not isinstance(plan, dict) or not isinstance(
            plan.get("config"), dict
        ):
            fail(f"tune row.plan is {plan!r}")
        for knob in ("mode", "block", "precision", "merge",
                     "dispatch", "sketch"):
            # sketch=0 is a real plan value ("prefilter off") and is
            # not in the sentinel tuple — only a MISSING key fails.
            if plan["config"].get(knob) in (None, ""):
                fail(f"tune plan missing knob {knob!r}")
        pred = row.get("predicted_phases")
        if not isinstance(pred, dict):
            fail(f"tune row.predicted_phases is {pred!r}")
        for key in ("build_s", "exchange_s", "compute_s", "merge_s",
                    "total_s"):
            v = pred.get(key)
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or v != v or v in (float("inf"), float("-inf")) \
                    or v < 0:
                fail(
                    f"tune predicted_phases.{key} is {v!r}, expected "
                    f"a finite number >= 0"
                )
        pof = row.get("probe_overhead_fraction")
        if not isinstance(pof, (int, float)) or isinstance(pof, bool) \
                or pof != pof or not 0 <= pof <= 0.05:
            fail(
                f"tune probe_overhead_fraction is {pof!r}, expected a "
                f"finite number in [0, 0.05]"
            )
        if row.get("labels_match") is not True:
            fail(
                "tune row labels_match is not True — auto labels must "
                "be byte-identical to the same explicit config"
            )
        lat = row.get("lattice")
        if not isinstance(lat, list) or len(lat) < 6:
            fail(
                f"tune lattice has {len(lat) if isinstance(lat, list) else lat!r} "
                f"point(s), need >= 6 measured configs"
            )
        for i, e in enumerate(lat):
            w = e.get("wall_s") if isinstance(e, dict) else None
            if not isinstance(w, (int, float)) or isinstance(w, bool) \
                    or w != w or w <= 0:
                fail(f"tune lattice[{i}].wall_s is {w!r}")
        v = row.get("value")
        if not isinstance(v, (int, float)) or v != v or v <= 0:
            fail(f"tune value is {v!r}")
        if v > 1.25:
            fail(
                f"tune planned config measured {v}x the best lattice "
                f"config (gate: 1.25x)"
            )

    # Regression-gate contract (ISSUE 6): rows produced under `make
    # bench-smoke` ride through bench_diff --annotate first; the
    # verdict must be present and must not be a real regression.
    diff_note = ""
    if require_diff:
        bd = row.get("bench_diff")
        if not isinstance(bd, dict) or bd.get("verdict") not in (
            "regression", "noise", "improved", "no_baseline"
        ):
            fail(
                f"--require-diff: missing/invalid bench_diff verdict "
                f"({bd!r}); pipe through scripts/bench_diff.py --annotate"
            )
        if bd["verdict"] == "regression":
            fail(f"bench_diff verdict is 'regression': {bd}")
        diff_note = f", bench_diff={bd['verdict']}"

    serve_note = (
        f", serving: {serving['queries']}q @ {serving['qps']}q/s "
        f"p50={serving['p50_ms']}ms p99={serving['p99_ms']}ms "
        f"fill={serving['batch_fill']}"
        if serving else ""
    )
    print(
        f"bench JSON OK: {row['metric']} = {row['value']} {row['unit']} "
        f"(dup_work={tel['sharding']['duplicated_work_factor']}, "
        f"staged_reuse={tel['sharding']['staged_bytes_reused']}, "
        f"mfu={tel['compute']['mfu']}, "
        f"precision={tel['compute']['precision_mode']}, "
        f"band_fraction={tel['compute']['band_fraction']}, "
        f"rss_peak={tel['resources']['peak_host_rss_bytes']}, "
        f"events: {tel['events']}"
        f"{diff_note}{serve_note})"
    )


if __name__ == "__main__":
    main()
