#!/usr/bin/env python
"""CI probe for the sketch-prefiltered high-d distance pass (ISSUE 17).

Geometry: the regime the prefilter exists for — NOISE-DOMINATED high-d
frames.  Clusters sit on mutually equidistant centers (a scaled
orthonormal latent basis embedded along random ambient directions) with
full-rank ambient noise whose floor dominates every pairwise distance:
per-coordinate the between-cluster signal drowns under the tile's own
noise width, so the axis-aligned full-d tile boxes go blind (nearly
every tile pair is "live" by box gap — the high-d curse), while
pairwise DISTANCES stay cleanly separated (intra ~ noise floor < eps,
inter = 3.5x eps).  That separation is exactly what the certified
random-projection gate sees: ``|Q^T(x-y)|^2 ~ (k/d) |x-y|^2``, so with
the auto width ``k = d/4`` the definitely-out gate (threshold
``~eps * sqrt(d/k) = 2 eps``) retires the box-blind bulk and only
shared-cluster tiles rescore at full d.  On low-noise geometry the
boxes already prune everything and the sketch can only add overhead —
which is why the auto policy gates on dimensionality, not on a
universal win.

Two sections, one row:

* **Counts-pass sweep** — the XLA counts pass at d in {64, 512},
  sketch ON vs OFF, byte-parity asserted per dim.  The headline
  ``value`` is the wall ratio at the LARGEST dim, gated by
  ``SKETCH_MIN_WIN`` (CI default 1.25 on the CPU mesh, where the
  gate's elementwise tail is memory-bound next to the matmuls; the
  acceptance-scale run on TPU hardware targets >= 3x —
  ``SKETCH_N=65536 SKETCH_MIN_WIN=3 make sketch-probe`` — where the
  d/k = 4x MXU-flop reduction is the whole story).
* **Route parity** — full fits at the largest dim across the fused
  single-device engine, the KD owner-computes mesh, and
  ``mode="global_morton"``, each with ``sketch="auto"`` and
  ``sketch=0``: all six label vectors must describe the identical
  clustering (fused renumbered to the distributed family's
  min-core-gid canon, exactly like ``global_morton_probe``).  The GM
  sketch-on fit must also report ``boundary_tile_bytes <=
  boundary_bytes_box`` — the sketch-space send gate can only SHRINK
  the ring.

Emits ONE bench-style JSON row (``schema="pypardis_tpu/sketch@1"``,
``metric="sketch_prefilter_win"``) through the ``bench_diff
--annotate | check_bench_json --require-diff`` pipe; the checker
re-enforces the invariants so a hand-edited row cannot pass.

Geometry via env: SKETCH_N (default 16384 for the counts sweep),
SKETCH_PARITY_N (4096 for the six full fits), SKETCH_DIMS ("64,512"),
SKETCH_BLOCK (128), SKETCH_REPS (2 timing reps), SKETCH_MIN_WIN.
"""

import json
import os
import sys
import time

_N_DEV = int(os.environ.get("PYPARDIS_PROBE_DEVICES", "8"))
if os.environ.get("PYPARDIS_PROBE_PLATFORM") != "native":
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={_N_DEV}"
        ).strip()

import numpy as np  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

if os.environ.get("PYPARDIS_PROBE_PLATFORM") != "native":
    jax.config.update("jax_platforms", "cpu")
    if "jax_num_cpu_devices" in jax.config._value_holders:
        jax.config.update("jax_num_cpu_devices", _N_DEV)

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
from benchdata import ari_vs_truth  # noqa: E402

SIGMA = 0.5  # ambient noise scale; every eps/separation derives from it
MS = 10


def _geometry(n, dim, n_centers=48, seed=0):
    """Noise-dominated equidistant clusters; returns (X, truth, eps).

    Centers are a scaled orthonormal basis (pairwise distance EXACTLY
    ``3.5 * eps`` — comfortably past the out-gate's ``2 eps``
    threshold plus its projection-tail margin, still far inside the
    box-blind window) embedded along random ambient directions, plus
    full-rank N(0, SIGMA^2) noise.  The noise floor sqrt(2) * SIGMA *
    sqrt(dim) concentrates hard in high d, so ``eps`` at 1.06x the
    floor makes every same-cluster pair a neighbor and no cross-cluster
    point reachable — the DBSCAN oracle is the center assignment."""
    rng = np.random.default_rng(seed)
    eps = round(1.06 * SIGMA * np.sqrt(2.0 * dim), 2)
    basis = np.linalg.qr(rng.normal(size=(dim, n_centers)))[0]
    centers = (3.5 * eps / np.sqrt(2.0)) * basis.T
    truth = rng.integers(0, n_centers, size=n)
    X = centers[truth] + rng.normal(scale=SIGMA, size=(n, dim))
    return X.astype(np.float32), truth, eps


def _timed(fn, reps):
    jax.block_until_ready(fn())
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        best = min(best, time.perf_counter() - t0)
    return best


def _counts_sweep(n, dim, block, reps):
    """Sketch on/off counts-pass walls + byte parity on one dim."""
    from pypardis_tpu.ops.distances import neighbor_counts
    from pypardis_tpu.ops.sketch import resolve_sketch
    from pypardis_tpu.partition import spatial_order
    from pypardis_tpu.utils import round_up

    X, _truth, eps = _geometry(n, dim)
    X = X[spatial_order(X - X.mean(axis=0))]
    cap = round_up(n, block)
    pts = np.zeros((cap, dim), np.float32)
    pts[:n] = X
    pts = jnp.asarray(pts)
    mask = jnp.arange(cap) < n

    # Below the SKETCH_MIN_D auto gate (d=64) "auto" resolves to 0 —
    # pin the same d/4 width explicitly there so the on/off parity
    # sweep still exercises the gate at every probed dim, and record
    # that auto would have kept it off.
    sk_auto = resolve_sketch("auto", dim)
    sk = sk_auto or resolve_sketch(max(dim // 4, 1), dim)
    dt_off = _timed(
        lambda: neighbor_counts(pts, eps, mask, block=block, sketch=0),
        reps,
    )
    dt_on = _timed(
        lambda: neighbor_counts(
            pts, eps, mask, block=block, sketch=sk
        )[0],
        reps,
    )
    c_off = np.asarray(
        neighbor_counts(pts, eps, mask, block=block, sketch=0)
    )
    c_on, bstats = neighbor_counts(
        pts, eps, mask, block=block, sketch=sk
    )
    assert np.array_equal(c_off, np.asarray(c_on)), (
        f"sketch counts diverge from exact at d={dim} (k={sk})"
    )
    band_pairs, rescored = [int(v) for v in np.asarray(bstats)]
    win = dt_off / max(dt_on, 1e-9)
    print(
        f"counts d={dim:4d}: off={dt_off:.3f}s on={dt_on:.3f}s "
        f"(k={sk}) win={win:.2f}x band_pairs={band_pairs} "
        f"rescored_tiles={rescored}",
        file=sys.stderr,
    )
    return {
        "dim": dim,
        "eps": eps,
        "sketch_k": sk,
        "auto_on": sk_auto > 0,
        "counts_off_s": round(dt_off, 4),
        "counts_on_s": round(dt_on, 4),
        "win": round(win, 3),
        "band_pairs": band_pairs,
        "rescored_tiles": rescored,
        "counts_match": True,
    }


def main() -> None:
    from pypardis_tpu import DBSCAN
    from pypardis_tpu.ops.labels import densify_labels
    from pypardis_tpu.parallel import default_mesh
    from pypardis_tpu.parallel.sharded import _canonicalize_roots

    n = int(os.environ.get("SKETCH_N", 16384))
    parity_n = int(os.environ.get("SKETCH_PARITY_N", 4096))
    dims = [
        int(d)
        for d in os.environ.get("SKETCH_DIMS", "64,512").split(",")
    ]
    block = int(os.environ.get("SKETCH_BLOCK", 128))
    reps = int(os.environ.get("SKETCH_REPS", 2))
    min_win = float(os.environ.get("SKETCH_MIN_WIN", 1.25))
    n_dev = min(_N_DEV, jax.device_count())
    mesh = default_mesh(n_dev)

    per_dim = [_counts_sweep(n, d, block, reps) for d in sorted(dims)]
    head = per_dim[-1]
    assert head["auto_on"] and head["sketch_k"] > 0, (
        f"auto sketch resolved to 0 at d={head['dim']} — the probe's "
        f"largest dim must sit above the SKETCH_MIN_D gate"
    )
    assert head["win"] >= min_win, (
        f"counts-pass win {head['win']}x at d={head['dim']} below the "
        f"{min_win}x gate"
    )

    # -- route parity at the largest dim ------------------------------
    dim = head["dim"]
    X, truth, eps = _geometry(parity_n, dim)
    kw = dict(eps=eps, min_samples=MS, block=block)
    fits = {}
    for route, extra in (
        ("fused", dict(mesh=default_mesh(1))),
        ("kd", dict(mesh=mesh, max_partitions=n_dev)),
        ("global_morton", dict(mesh=mesh, mode="global_morton")),
    ):
        for sk in ("auto", 0):
            m = DBSCAN(sketch=sk, **kw, **extra)
            m.fit(X)
            fits[(route, sk)] = m

    # The fused engine numbers clusters Morton-first; renumber to the
    # distributed family's min-core-gid canon before the byte compare.
    def canon(route, sk):
        m = fits[(route, sk)]
        labs = np.asarray(m.labels_)
        if route == "fused":
            labs = densify_labels(_canonicalize_roots(
                labs, np.asarray(m.core_sample_mask_)
            ))
        return labs

    ref = canon("global_morton", 0)
    for key in fits:
        labs = canon(*key)
        if not np.array_equal(ref, labs):
            print(
                f"sketch probe FAILED: labels diverge on route={key[0]}"
                f" sketch={key[1]}", file=sys.stderr,
            )
            sys.exit(1)

    gm_on = fits[("global_morton", "auto")]
    rep = gm_on.report()
    sh, comp = rep["sharding"], rep["compute"]
    bytes_sketch = int(sh.get("boundary_tile_bytes", 0))
    bytes_box = int(sh.get("boundary_bytes_box", bytes_sketch))
    if bytes_sketch > bytes_box:
        print(
            f"sketch probe FAILED: GM boundary bytes grew under the "
            f"sketch send gate ({bytes_sketch} > {bytes_box})",
            file=sys.stderr,
        )
        sys.exit(1)
    assert int(comp["sketch_k"]) == head["sketch_k"], (
        "GM fit's resolved sketch_k disagrees with the kernel sweep's"
    )

    row = {
        "metric": "sketch_prefilter_win",
        "value": head["win"],
        "unit": "x",
        "schema": "pypardis_tpu/sketch@1",
        "n": n,
        "parity_n": parity_n,
        "dim": dim,
        "dims": sorted(dims),
        "block": block,
        "eps": head["eps"],
        "sketch_k": head["sketch_k"],
        "sketch_band_fraction": float(comp["band_fraction"]),
        "per_dim": per_dim,
        "routes": ["fused", "kd", "global_morton"],
        "labels_match": True,
        "boundary_bytes_sketch": bytes_sketch,
        "boundary_bytes_box": bytes_box,
        "ari_vs_truth": round(
            ari_vs_truth(gm_on.labels_, truth), 4
        ),
        "telemetry": rep,
    }
    print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
