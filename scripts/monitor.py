#!/usr/bin/env python
"""Live run monitor: tail a flight file (or directory of them) and
render the run's current state (``make monitor MONITOR_PATH=...``).

This is the console you keep open during the 100M north-star run: it
follows the flight JSONL stream(s) a fit (``PYPARDIS_FLIGHT=...``) or
a multi-process harness writes, and redraws, once per interval,

* the phase stack each process is currently inside (open spans),
* per-round progress + ETA from the heartbeat records (global-Morton
  ring / fixpoint rounds, stepped propagation batches, chained loop),
* resource watermarks (host RSS / device bytes / staging pool),
* current latency-histogram percentiles (``h`` records: serving /
  ingest / phase latencies on the bounded windowed histograms),
* terminal status (``fin``) or staleness (seconds since the file last
  grew — a wedged run shows up as a stale RUNNING).

Deliberately **stdlib-only and pypardis-free**: the monitor must start
instantly on any host that can read the file — no JAX import, no mesh
configuration, no dependence on the library version that wrote the
stream.  Directory mode tails every ``*.jsonl`` member (one per
process/host, the layout ``PYPARDIS_FLIGHT=<dir>`` produces and
``obs.fleet`` merges post-hoc).

``--once`` renders a single frame and exits (CI / scripting);
``--json`` emits the frame as one machine-readable JSON object.
"""

import argparse
import glob
import json
import os
import sys
import time


def _fmt_bytes(n):
    try:
        n = float(n)
    except (TypeError, ValueError):
        return "?"
    for unit in ("B", "KB", "MB", "GB"):
        if n < 1024 or unit == "GB":
            return ("%d%s" % (int(n), unit)) if unit == "B" \
                else ("%.1f%s" % (n, unit))
        n /= 1024
    return "%.1fGB" % n


def _bar(done, total, width=20):
    if total <= 0:
        return "?" * width
    frac = min(max(done / total, 0.0), 1.0)
    filled = int(round(frac * width))
    return "#" * filled + "." * (width - filled)


class Tail:
    """Incremental single-file tail: parse only the bytes appended
    since the last poll, fold them into the run-state machine."""

    def __init__(self, path):
        self.path = path
        self.offset = 0
        self.partial = ""  # trailing bytes with no newline yet
        self.header = {}
        self.open_spans = {}   # id -> {name, t, depth}
        self.heartbeats = {}   # stage -> {done,total,eta_s,t}
        self.resources = {}    # last rs record fields
        self.res_peaks = {}    # max over rs records
        self.hists = {}        # key -> last h snapshot
        self.phase_s = {}      # tm aggregates: key -> total seconds
        self.events = 0
        self.records = 0
        self.bad_lines = 0
        self.last_t = 0.0
        self.finished = None   # fin status
        self.last_growth = time.time()

    def poll(self):
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return
        if size < self.offset:
            # Truncated/rotated underneath us: start over.
            self.offset = 0
            self.partial = ""
        if size == self.offset:
            return
        with open(self.path, "r", encoding="utf-8",
                  errors="replace") as f:
            f.seek(self.offset)
            chunk = f.read()
            self.offset = f.tell()
        self.last_growth = time.time()
        buf = self.partial + chunk
        lines = buf.split("\n")
        self.partial = lines.pop()  # "" when chunk ended on a newline
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                r = json.loads(line)
            except ValueError:
                self.bad_lines += 1
                continue
            if not isinstance(r, dict):
                self.bad_lines += 1
                continue
            self._fold(r)

    def _fold(self, r):
        self.records += 1
        k = r.get("k")
        try:
            t = float(r.get("t", self.last_t) or 0.0)
        except (TypeError, ValueError):
            t = self.last_t
        self.last_t = max(self.last_t, t)
        try:
            if k == "header":
                self.header = r
            elif k == "so":
                self.open_spans[int(r["id"])] = {
                    "name": r.get("name", "?"), "t": t,
                    "depth": int(r.get("depth", 0) or 0),
                }
            elif k == "sc":
                self.open_spans.pop(int(r["id"]), None)
                self.last_t = max(
                    self.last_t, t + float(r.get("dur", 0.0) or 0.0)
                )
            elif k == "sx":
                self.last_t = max(
                    self.last_t, t + float(r.get("dur", 0.0) or 0.0)
                )
            elif k == "hb":
                self.heartbeats[str(r.get("stage"))] = {
                    "done": int(r.get("done", 0) or 0),
                    "total": int(r.get("total", 0) or 0),
                    "eta_s": float(r.get("eta_s", -1.0) or 0.0),
                    "t": t,
                }
            elif k == "rs":
                for key, v in r.items():
                    if key in ("k", "t"):
                        continue
                    if isinstance(v, (int, float)):
                        self.resources[key] = v
                        if v > self.res_peaks.get(key, float("-inf")):
                            self.res_peaks[key] = v
            elif k == "h":
                snap = r.get("snap")
                if isinstance(snap, dict):
                    self.hists[str(r.get("key"))] = snap
            elif k == "tm":
                key = str(r.get("key"))
                self.phase_s[key] = (
                    self.phase_s.get(key, 0.0)
                    + float(r.get("s", 0.0) or 0.0)
                )
            elif k == "ev":
                self.events += 1
            elif k == "fin":
                self.finished = str(r.get("status"))
        except (KeyError, TypeError, ValueError):
            self.bad_lines += 1

    # -- frame -------------------------------------------------------------

    def state(self):
        spans = sorted(
            self.open_spans.values(),
            key=lambda s: (s["depth"], s["t"]),
        )
        return {
            "path": self.path,
            "pid": self.header.get("pid"),
            "records": self.records,
            "bad_lines": self.bad_lines,
            "last_t_s": round(self.last_t, 3),
            "stale_s": round(time.time() - self.last_growth, 1),
            "finished": self.finished,
            "phase_stack": [s["name"] for s in spans],
            "heartbeats": self.heartbeats,
            "resources": dict(self.resources),
            "resource_peaks": dict(self.res_peaks),
            "hists": {
                key: {
                    "p50_ms": s.get("p50_ms"),
                    "p99_ms": s.get("p99_ms"),
                    "count": s.get("count"),
                    "window_count": s.get("window_count"),
                }
                for key, s in self.hists.items()
            },
            "phase_s": {
                key: round(v, 3) for key, v in self.phase_s.items()
            },
            "events": self.events,
        }

    def render(self):
        st = self.state()
        if st["finished"] is not None:
            status = "FINISHED %s" % st["finished"]
        elif st["stale_s"] > 5.0:
            status = "RUNNING (stale %.0fs)" % st["stale_s"]
        else:
            status = "RUNNING"
        who = "pid=%s" % st["pid"] if st["pid"] is not None else "?"
        out = [
            "%s  [%s]  t=%.1fs  %d records%s"
            % (
                os.path.basename(st["path"]), status, st["last_t_s"],
                st["records"],
                (", %d bad" % st["bad_lines"]) if st["bad_lines"]
                else "",
            ),
            "  %s  phase: %s"
            % (who, " > ".join(st["phase_stack"]) or "(idle)"),
        ]
        for stage in sorted(st["heartbeats"]):
            hb = st["heartbeats"][stage]
            eta = hb["eta_s"]
            out.append(
                "  %-24s [%s] %d/%d rounds%s"
                % (
                    stage, _bar(hb["done"], hb["total"]),
                    hb["done"], hb["total"],
                    ("  eta %.1fs" % eta) if eta >= 0 else "",
                )
            )
        pk = st["resource_peaks"]
        if pk:
            bits = []
            for key, label in (
                ("rss", "rss"), ("dev", "dev"), ("pool", "pool"),
            ):
                if key in pk:
                    bits.append("%s %s" % (label, _fmt_bytes(pk[key])))
            for key in sorted(pk):
                if key not in ("rss", "dev", "pool"):
                    bits.append("%s %s" % (key, _fmt_bytes(pk[key])))
            out.append("  resources(peak): " + ", ".join(bits))
        for key in sorted(st["hists"]):
            h = st["hists"][key]
            out.append(
                "  %-24s p50 %.2fms  p99 %.2fms  (%s obs, %s in window)"
                % (
                    key, h.get("p50_ms") or 0.0, h.get("p99_ms") or 0.0,
                    h.get("count"), h.get("window_count"),
                )
            )
        top = sorted(
            st["phase_s"].items(), key=lambda kv: -kv[1]
        )[:4]
        if top:
            out.append(
                "  timings: "
                + " | ".join("%s %.2fs" % kv for kv in top)
            )
        return "\n".join(out)


class Monitor:
    """One or many tails (directory mode picks up new members live)."""

    def __init__(self, path):
        self.path = path
        self.tails = {}
        self._refresh_members()
        if not self.tails:
            raise FileNotFoundError(
                "no flight file(s) at %r (expected a .jsonl file or a "
                "directory of them)" % path
            )

    def _refresh_members(self):
        if os.path.isdir(self.path):
            members = sorted(glob.glob(
                os.path.join(self.path, "*.jsonl")
            ))
        elif os.path.exists(self.path):
            members = [self.path]
        else:
            members = []
        for m in members:
            if m not in self.tails:
                self.tails[m] = Tail(m)

    def poll(self):
        self._refresh_members()
        for t in self.tails.values():
            t.poll()

    def frame(self):
        return "\n\n".join(
            t.render() for _, t in sorted(self.tails.items())
        )

    def state(self):
        return {
            "schema": "pypardis_tpu/monitor_frame@1",
            "path": self.path,
            "hosts": [
                t.state() for _, t in sorted(self.tails.items())
            ],
        }

    def all_finished(self):
        return all(
            t.finished is not None for t in self.tails.values()
        )


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="live-tail pypardis_tpu flight file(s)"
    )
    ap.add_argument(
        "path",
        help="flight .jsonl file, or a directory of them "
             "(PYPARDIS_FLIGHT=<dir> layout)",
    )
    ap.add_argument(
        "--interval", type=float, default=1.0,
        help="redraw interval in seconds (default 1.0)",
    )
    ap.add_argument(
        "--once", action="store_true",
        help="render a single frame and exit (CI / scripting)",
    )
    ap.add_argument(
        "--json", action="store_true",
        help="emit the frame as one JSON object instead of text",
    )
    ap.add_argument(
        "--no-clear", action="store_true",
        help="append frames instead of clearing the screen",
    )
    ap.add_argument(
        "--follow-until-fin", action="store_true",
        help="exit once every tailed file has a terminal fin record",
    )
    args = ap.parse_args(argv)

    mon = Monitor(args.path)
    while True:
        mon.poll()
        if args.json:
            frame = json.dumps(mon.state(), sort_keys=True)
        else:
            frame = mon.frame()
        if args.once:
            print(frame)
            return 0
        if not args.no_clear and sys.stdout.isatty():
            sys.stdout.write("\x1b[2J\x1b[H")
        print(frame, flush=True)
        if args.follow_until_fin and mon.all_finished():
            return 0
        try:
            time.sleep(max(args.interval, 0.05))
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
