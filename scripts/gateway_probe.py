#!/usr/bin/env python
"""Multi-tenant gateway probe (``make gateway-probe``, wired into
``bench-smoke``): registry residency under a byte budget, eviction/
readmission byte-identity, admission control, and the hot-swap row.

Asserted end to end (exits nonzero on any violation):

1. **budgeted residency** — a registry of GATEWAY_MODELS (>= 8 for the
   gate) fitted models under a device-slab byte budget sized to hold
   all but ~1.5 of them: registration forces >= 1 LRU eviction and the
   resident byte total never exceeds the budget;
2. **byte-identical readmission** — a model's (labels, distances)
   answered before its eviction equal its post-reload answers bitwise
   (``save_index`` spill -> ``load_index`` restore);
3. **admission control** — an over-quota tenant's requests shed with
   ``TenantQuotaExceeded`` while the same gateway's unlimited tenants
   shed nothing;
4. **fleet traffic + hot swap** — Zipf-distributed multi-tenant load
   (every tenant a different hot model, the long tail churning through
   eviction/readmission) across >= 1 mid-run ``refresh()`` epoch swap,
   zero dropped tickets, per-tenant latency histograms — emitted as
   the schema'd ``gateway@1`` row (``gateway_fleet_load``), piped
   through ``bench_diff --annotate`` into ``check_bench_json`` by the
   make target.

Env knobs: GATEWAY_MODELS (default 10), GATEWAY_N (600),
GATEWAY_DIM (4), GATEWAY_TENANTS (4), GATEWAY_SECONDS (2.0).
"""

import json
import os
import sys
import tempfile

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def fail(msg: str) -> None:
    print(f"gateway probe FAILED: {msg}", file=sys.stderr)
    sys.exit(1)


def main():
    import numpy as np

    from benchdata import make_separated_blob_data
    from pypardis_tpu import DBSCAN
    from pypardis_tpu.parallel.mesh import default_mesh
    from pypardis_tpu.serve import (
        ModelGateway,
        TenantQuotaExceeded,
        gateway_load,
    )

    n_models = int(os.environ.get("GATEWAY_MODELS", 10))
    n = int(os.environ.get("GATEWAY_N", 600))
    dim = int(os.environ.get("GATEWAY_DIM", 4))
    tenants = int(os.environ.get("GATEWAY_TENANTS", 4))
    seconds = float(os.environ.get("GATEWAY_SECONDS", 2.0))
    eps, min_samples = 1.1 * (dim / 4) ** 0.5, 8
    mesh = default_mesh(1)

    def fit_model(seed):
        X, _truth, _centers = make_separated_blob_data(
            n, dim, n_centers=6, std=0.4,
            min_sep=2 * eps + 6 * 0.4 + 1.0, spread=12.0, seed=seed,
        )
        m = DBSCAN(
            eps=eps, min_samples=min_samples, block=256, mesh=mesh,
        ).fit(X)
        return m, X

    # Identical shapes across the fleet: every model's engine reuses
    # the same jitted query kernels — residency churn pays transfer
    # cost, never recompilation.
    fleet = {f"m{i:02d}": fit_model(seed=i) for i in range(n_models)}

    spill_dir = tempfile.mkdtemp(prefix="pypardis_gateway_")
    gw = ModelGateway(budget_bytes=0, spill_dir=spill_dir)
    first = next(iter(fleet))
    gw.register(first, fleet[first][0])
    per = gw.handle(first).index_bytes
    # Budget holds all but ~1.5 models: registering the full fleet
    # MUST evict, and the gate's >= 8 registered models stay served.
    gw.budget_bytes = int(per * (n_models - 1.5))
    for mid, (m, _X) in fleet.items():
        if mid != first:
            gw.register(mid, m)

    rep = gw.gateway_report()
    if rep["models_registered"] != n_models:
        fail(f"registered {rep['models_registered']} of {n_models}")
    if rep["evictions"] < 1:
        fail("budget forced no eviction at registration")
    if rep["resident_bytes"] > rep["budget_bytes"]:
        fail(
            f"resident bytes {rep['resident_bytes']} exceed the "
            f"budget {rep['budget_bytes']}"
        )

    # -- 2: eviction -> readmission byte-identity -------------------------
    probe_mid = first
    _m0, X0 = fleet[probe_mid]
    Q = X0[:64]
    pre = gw.predict(probe_mid, Q, return_distance=True)
    # Touch every other model; the budget squeezes the probe model
    # (now least-recently-served) out.
    for mid, (_m, X) in fleet.items():
        if mid != probe_mid:
            gw.predict(mid, X[:8])
    if gw.gateway_report()["models"][probe_mid]["resident"]:
        fail("LRU did not evict the least-recently-served model")
    post = gw.predict(probe_mid, Q, return_distance=True)
    byte_identical = bool(
        np.array_equal(pre[0], post[0])
        and np.array_equal(pre[1], post[1])
    )
    if not byte_identical:
        fail("readmitted model's answers differ from pre-eviction")
    rep = gw.gateway_report()
    if rep["reloads"] < 1:
        fail("readmission did not reload the spilled index")
    print(
        f"gateway probe: {n_models} models under "
        f"{gw.budget_bytes} B budget -> {rep['resident_models']} "
        f"resident, {rep['evictions']} evictions, {rep['reloads']} "
        f"reloads, readmission byte-identical",
        file=sys.stderr,
    )

    # -- 3: admission control --------------------------------------------
    gw.set_quota("spiky", qps=0.001, burst=2)
    quota_sheds = 0
    for _ in range(6):
        try:
            gw.predict(probe_mid, Q[:4], tenant="spiky")
        except TenantQuotaExceeded:
            quota_sheds += 1
    if quota_sheds != 4:
        fail(f"quota bucket(burst=2) shed {quota_sheds} of 6, "
             f"expected 4")
    if gw.gateway_report()["tenants"].get("default", {}).get("shed", 0):
        fail("quota shedding leaked onto an unlimited tenant")

    # -- 4: Zipf fleet traffic across a mid-run hot swap ------------------
    swap_mid = f"m{n_models // 2:02d}"
    m_new, X_new = fit_model(seed=1000 + n_models // 2)

    res = gateway_load(
        gw, list(fleet), tenants=tenants, clients_per_tenant=2,
        duration_s=seconds, rate_hz=60.0, batch_rows=8,
        zipf_s=1.2, seed=11,
        refresh_at_s=seconds * 0.4,
        refresher=lambda: gw.refresh(swap_mid, m_new),
    )
    if res["dropped_tickets"] != 0:
        fail(
            f"fleet load dropped {res['dropped_tickets']} ticket(s); "
            f"eviction/readmission/swap must drain, never drop"
        )
    if res["deadline_failures"] != 0:
        fail(f"fleet load failed {res['deadline_failures']} ticket(s)")
    gwrep = res["gateway"]
    if gwrep["epoch_swaps"] < 1:
        fail("fleet load completed no epoch swap")
    if gwrep["evictions"] < 1 or gwrep["reloads"] < 1:
        fail(
            f"fleet load saw {gwrep['evictions']} evictions / "
            f"{gwrep['reloads']} reloads, need >= 1 of each"
        )
    if gwrep["resident_bytes"] > gwrep["budget_bytes"]:
        fail(
            f"post-load resident bytes {gwrep['resident_bytes']} "
            f"exceed the budget {gwrep['budget_bytes']}"
        )
    # The swapped handle serves the refreshed clustering.
    got = gw.predict(swap_mid, X_new[:32])
    if not np.array_equal(got, m_new.predict(X_new[:32])):
        fail("post-swap predictions diverge from the refreshed model")

    row = {
        "metric": "gateway_fleet_load",
        "value": res["qps"],
        "unit": "queries/sec",
        "schema": "pypardis_tpu/gateway@1",
        "models": n_models,
        "budget_bytes": int(gw.budget_bytes),
        "reload_byte_identical": byte_identical,
        "quota_shed_demo": int(quota_sheds),
        "load": res,
        "telemetry": fleet[first][0].report(),
    }
    print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
