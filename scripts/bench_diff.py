#!/usr/bin/env python
"""Cross-round benchmark regression gate (``make bench-diff`` and the
``make bench-smoke`` pipe).

The r4->r5 4.7% headline delta cost a manual diagnosis (CHANGES PR 2):
the verdict — tunnel/ambient noise, not code — came from comparing the
raw per-rep sample RANGES, which bench.py has archived ever since
precisely so that question answers itself.  This script is that
diagnosis, automated: it compares two benchmark rows' raw ``samples_s``
distributions (device path; ``host_samples_s`` when both sides carry
it) and emits a per-metric verdict:

* ``regression`` — the current sample range sits strictly ABOVE the
  prior one (no overlap: even the current best rep is slower than the
  prior worst) AND the best-of-N delta clears the threshold (default
  5%, ``--threshold``/``PYPARDIS_BENCH_DIFF_THR``).  Exit code 1.
* ``improved``   — the mirror image (strictly below, delta < -thr).
* ``noise``      — the ranges overlap, or the delta is inside the
  threshold: exactly the r4->r5 situation (r5 [0.45..0.57] vs r4
  [0.43..0.49] overlap), now a machine verdict instead of a PR
  archaeology session.
* ``no_baseline`` — no prior round carries a matching metric + samples.

Two modes:

* ``--prior FILE --current FILE`` — compare two rows/archives directly
  (``BENCH_r*.json`` driver-archive files — ``{parsed, tail}`` wrappers
  — are understood; pre-archiving rounds' samples are recovered from
  the stderr ``samples=[...]`` line in ``tail``).  ``--expect VERDICT``
  additionally fails unless the overall verdict matches — `make
  bench-diff` pins the committed r4->r5 "noise" finding as a CI
  invariant.
* ``--annotate --baseline-dir DIR`` — filter mode for the bench pipe:
  reads bench.py's stdout, finds the latest ``BENCH_r*.json`` in DIR
  with a matching metric, attaches the verdict as the row's
  ``bench_diff`` field, and re-emits the row for
  ``check_bench_json.py --require-diff`` (which fails CI on a
  ``regression`` verdict).
"""

import glob
import json
import os
import re
import sys

VERDICT_RANK = {"no_baseline": 0, "improved": 1, "noise": 2,
                "regression": 3}


def fail(msg: str, code: int = 2) -> None:
    print(f"bench_diff FAILED: {msg}", file=sys.stderr)
    sys.exit(code)


def _tail_samples(tail: str):
    """Recover raw per-rep seconds from an archived stderr tail —
    pre-PR2 rounds printed ``samples=[0.47, 0.43, ...]`` but did not
    yet archive ``samples_s`` in the row."""
    m = re.search(r"\bsamples=\[([^\]]+)\]", tail or "")
    if not m:
        return None
    try:
        return [float(x) for x in m.group(1).split(",")]
    except ValueError:
        return None


def load_bench_row(path: str) -> dict:
    """A bench row dict from a raw row file or a BENCH_r* archive
    (``{n, cmd, rc, tail, parsed}`` wrapper).  Raises ValueError on
    files that are neither (an errored round's archive, say)."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and "metric" in doc:
        row, tail = dict(doc), ""
    elif isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        row, tail = dict(doc["parsed"]), doc.get("tail", "")
    else:
        raise ValueError(
            f"{path}: neither a bench row nor a BENCH_r archive"
        )
    if not row.get("samples_s"):
        s = _tail_samples(tail)
        if s:
            row["samples_s"] = s
    if not row.get("samples_s") and str(row.get("metric", "")).startswith(
        "northstar"
    ):
        # Single-rep north-star rows (the pre-sparsity NORTHSTAR_smoke
        # committed one wall number, no samples array): the wall IS the
        # one sample, so range comparison degenerates to the strict
        # point comparison — exactly right for a 15-minute e2e run
        # nobody repeats three times.
        try:
            v = float(row.get("value"))
            if v == v and v > 0:
                row["samples_s"] = [v]
        except (TypeError, ValueError):
            pass
    return row


def _finite_samples(row: dict, key: str):
    s = row.get(key)
    if not isinstance(s, list) or not s:
        return None
    try:
        vals = [float(x) for x in s]
    except (TypeError, ValueError):
        return None
    return vals if all(v == v and v > 0 for v in vals) else None


def diff_samples(prior, cur, thr: float) -> dict:
    """Verdict for one metric from raw per-rep seconds (lower=better).

    Best-of-N is the headline each round publishes, so the delta is
    best-vs-best; the RANGES decide whether that delta is attributable
    — overlapping ranges mean the rounds plausibly sampled the same
    distribution (the r4->r5 finding), disjoint ranges mean every rep
    agreed on the direction.
    """
    p_lo, p_hi = min(prior), max(prior)
    c_lo, c_hi = min(cur), max(cur)
    delta = c_lo / p_lo - 1.0
    overlap = (c_lo <= p_hi) and (p_lo <= c_hi)
    if not overlap and c_lo > p_hi and delta > thr:
        verdict = "regression"
    elif not overlap and c_hi < p_lo and delta < -thr:
        verdict = "improved"
    else:
        verdict = "noise"
    return {
        "verdict": verdict,
        "delta_best": round(delta, 4),
        "ranges_overlap": overlap,
        "prior_range_s": [round(p_lo, 4), round(p_hi, 4)],
        "current_range_s": [round(c_lo, 4), round(c_hi, 4)],
        "n_prior": len(prior),
        "n_current": len(cur),
    }


def compare_rows(prior_row: dict, cur_row: dict, thr: float) -> dict:
    metrics = {}
    for name, key in (("device", "samples_s"), ("host", "host_samples_s")):
        p = _finite_samples(prior_row, key)
        c = _finite_samples(cur_row, key)
        if p and c:
            metrics[name] = diff_samples(p, c, thr)
    overall = "no_baseline"
    for d in metrics.values():
        if VERDICT_RANK[d["verdict"]] > VERDICT_RANK[overall]:
            overall = d["verdict"]
    return {"verdict": overall, "threshold": thr, "metrics": metrics}


def _northstar_comparable(prior: dict, cur: dict) -> bool:
    """North-star walls are only comparable at the SAME geometry —
    the 120k CI smoke must never be range-compared against the 5M
    committed row (both carry metric ``northstar_e2e``)."""
    return all(
        prior.get(k) == cur.get(k)
        for k in ("n", "dim", "mesh_devices", "mode")
    )


def find_baseline(baseline_dir: str, metric: str, cur_row: dict = None):
    """(path, row) of the highest-numbered archive whose metric matches
    and which carries usable samples, else (None, None).

    BENCH rows compare against ``BENCH_r*.json``; northstar rows
    against ``NORTHSTAR_*.json`` at the same geometry (the same gate,
    pointed at the committed north-star trajectory).
    """
    patterns = ("BENCH_r*.json",)
    row_ok = None
    if str(metric).startswith("northstar"):
        patterns = ("NORTHSTAR_*.json",)
        if cur_row is not None:
            row_ok = lambda prior: _northstar_comparable(prior, cur_row)
    best = (None, None, -1)
    for pattern in patterns:
        for path in glob.glob(os.path.join(baseline_dir, pattern)):
            m = re.search(r"_r(\d+)\.json$", os.path.basename(path))
            rnd = int(m.group(1)) if m else 0
            try:
                row = load_bench_row(path)
            except (ValueError, OSError, json.JSONDecodeError):
                continue  # e.g. a round that errored: no row to compare
            if row.get("metric") != metric:
                continue
            if not _finite_samples(row, "samples_s"):
                continue
            if row_ok is not None and not row_ok(row):
                continue
            if rnd > best[2]:
                best = (path, row, rnd)
    return best[0], best[1]


def parse_args(argv):
    opts = {"prior": None, "current": None, "baseline_dir": None,
            "annotate": False, "expect": None,
            "threshold": float(os.environ.get(
                "PYPARDIS_BENCH_DIFF_THR", 0.05))}
    it = iter(argv)
    for a in it:
        if a == "--prior":
            opts["prior"] = next(it, None)
        elif a == "--current":
            opts["current"] = next(it, None)
        elif a == "--baseline-dir":
            opts["baseline_dir"] = next(it, None)
        elif a == "--annotate":
            opts["annotate"] = True
        elif a == "--expect":
            opts["expect"] = next(it, None)
        elif a == "--threshold":
            opts["threshold"] = float(next(it, "0.05"))
        else:
            fail(f"unknown argument {a!r}")
    return opts


def _human(result: dict, prior_name: str, cur_name: str) -> str:
    bits = [f"bench_diff: {cur_name} vs {prior_name} -> "
            f"{result['verdict'].upper()}"]
    for name, d in result["metrics"].items():
        bits.append(
            f"  {name}: {d['verdict']} (best delta {d['delta_best']:+.1%}, "
            f"prior {d['prior_range_s']} vs current "
            f"{d['current_range_s']}, overlap={d['ranges_overlap']})"
        )
    return "\n".join(bits)


def main() -> None:
    opts = parse_args(sys.argv[1:])
    thr = opts["threshold"]

    if opts["annotate"]:
        data = sys.stdin.read()
        lines = data.strip().splitlines()
        json_idx = [i for i, ln in enumerate(lines)
                    if ln.lstrip().startswith("{")]
        if not json_idx:
            fail("no JSON row on stdin to annotate")
        row = json.loads(lines[json_idx[-1]])
        bdir = opts["baseline_dir"] or "."
        prior_path, prior_row = find_baseline(
            bdir, row.get("metric"), cur_row=row
        )
        if prior_row is None:
            result = {"verdict": "no_baseline", "threshold": thr,
                      "metrics": {},
                      "reason": f"no prior BENCH_r*.json in {bdir} with "
                                f"metric {row.get('metric')!r}"}
        else:
            result = compare_rows(prior_row, row, thr)
            result["vs"] = os.path.basename(prior_path)
            print(_human(result, os.path.basename(prior_path), "current"),
                  file=sys.stderr)
        row["bench_diff"] = result
        for i, ln in enumerate(lines):
            print(json.dumps(row) if i == json_idx[-1] else ln)
        sys.exit(1 if result["verdict"] == "regression" else 0)

    if not (opts["prior"] and opts["current"]):
        fail("need --prior and --current (or --annotate)")
    try:
        prior_row = load_bench_row(opts["prior"])
        cur_row = load_bench_row(opts["current"])
    except (ValueError, OSError, json.JSONDecodeError) as e:
        fail(str(e))
    if prior_row.get("metric") != cur_row.get("metric"):
        fail(
            f"metric mismatch: {prior_row.get('metric')!r} vs "
            f"{cur_row.get('metric')!r} — cross-geometry deltas are not "
            f"comparable"
        )
    result = compare_rows(prior_row, cur_row, thr)
    if not result["metrics"]:
        result["verdict"] = "no_baseline"
    result["metric"] = cur_row.get("metric")
    result["prior"] = os.path.basename(opts["prior"])
    result["current"] = os.path.basename(opts["current"])
    print(json.dumps(result))
    print(_human(result, result["prior"], result["current"]),
          file=sys.stderr)
    if opts["expect"] and result["verdict"] != opts["expect"]:
        fail(
            f"verdict {result['verdict']!r} != expected "
            f"{opts['expect']!r}", code=3,
        )
    sys.exit(1 if result["verdict"] == "regression" else 0)


if __name__ == "__main__":
    main()
