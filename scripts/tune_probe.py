#!/usr/bin/env python
"""CI probe for the auto-tuning subsystem (ISSUE 14).

Warms the candidate configs (jit compiles are a fixed process cost,
not the planning quality under test), then:

1. runs a measured ``DBSCAN(auto=True)`` fit — the probe/plan overhead
   and the planned config come from its ``report()["tune"]`` block;
2. measures a >= 6-point config lattice (mode x block, merge=host) of
   EXPLICIT fits on the same geometry, best-of-2 each, cold staging —
   the planned config added if the grid missed it;
3. gates, enforced here (nonzero exit) and re-checked by
   ``scripts/check_bench_json.py``:

   * planned config's measured wall <= 1.25x the best lattice config;
   * probe + plan overhead <= 5% of the auto fit's wall;
   * auto labels BYTE-IDENTICAL to the same explicit config;
   * every predicted phase finite.

Emits ONE bench-style JSON row (schema ``pypardis_tpu/tune@1``):
``metric="tune_planned_within"``, ``value`` = planned wall / best
lattice wall, the plan + predicted-vs-actual phases, the measured
lattice, probe overhead, and the auto fit's full ``run_report@1``
telemetry (with its ``tune`` block).  Geometry via env: TUNE_N
(default 120000 — large enough that the bounded probe is a small
fraction of the fit), TUNE_DIM (8), TUNE_EPS (0.9), TUNE_BLOCKS
(128,256,512).
"""

import json
import os
import sys
import tempfile
import time

_N_DEV = int(os.environ.get("PYPARDIS_PROBE_DEVICES", "8"))
if os.environ.get("PYPARDIS_PROBE_PLATFORM") != "native":
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={_N_DEV}"
        ).strip()

import numpy as np  # noqa: E402
import jax  # noqa: E402

if os.environ.get("PYPARDIS_PROBE_PLATFORM") != "native":
    jax.config.update("jax_platforms", "cpu")
    if "jax_num_cpu_devices" in jax.config._value_holders:
        jax.config.update("jax_num_cpu_devices", _N_DEV)

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _explicit_kw(cfg):
    kw = dict(block=int(cfg["block"]), precision=cfg["precision"])
    if cfg.get("mode") and cfg["mode"] != "auto":
        kw["mode"] = cfg["mode"]
    if cfg.get("merge") and cfg["merge"] != "auto":
        kw["merge"] = cfg["merge"]
    return kw


def main() -> None:
    from benchdata import ari_vs_truth, make_blob_data
    from pypardis_tpu import DBSCAN
    from pypardis_tpu.parallel import default_mesh, staging

    n = int(os.environ.get("TUNE_N", 120000))
    dim = int(os.environ.get("TUNE_DIM", 8))
    eps = float(os.environ.get("TUNE_EPS", 0.9))
    ms = 5
    lattice_blocks = [
        int(b) for b in os.environ.get(
            "TUNE_BLOCKS", "128,256,512"
        ).split(",")
    ]
    X, truth = make_blob_data(n, dim, seed=7)
    mesh = default_mesh(min(_N_DEV, jax.device_count()))
    # Isolated feedback archive: the probe must not read or mutate the
    # operator's local corpus (the committed archives still harvest).
    corpus = os.path.join(
        tempfile.mkdtemp(prefix="pypardis_tune_probe_"),
        "corpus.jsonl",
    )
    base_kw = dict(min_samples=ms, mesh=mesh)

    # -- warm-up (compiles for auto + every lattice config) -----------
    DBSCAN(eps=eps, auto=True, tune_corpus=corpus, **base_kw).fit(X)
    lattice_cfgs = [
        {"mode": mode, "block": b, "precision": "high",
         "merge": "host", "dispatch": "auto"}
        for mode in ("kd", "global_morton") for b in lattice_blocks
    ]
    for cfg in lattice_cfgs:
        DBSCAN(eps=eps, **_explicit_kw(cfg), **base_kw).fit(X)

    # -- measured auto fit --------------------------------------------
    staging.clear()
    model = DBSCAN(eps=eps, auto=True, tune_corpus=corpus, **base_kw)
    t0 = time.perf_counter()
    model.fit(X)
    auto_wall = time.perf_counter() - t0
    tel = model.report()
    tune = tel["tune"]
    plan_cfg = dict(tune["plan"]["config"])
    overhead = float(tune["plan_s"])  # probe + harvest + scoring
    overhead_fraction = overhead / max(auto_wall, 1e-9)
    ari = ari_vs_truth(np.asarray(model.labels_), truth)

    # -- auto vs explicit byte parity ---------------------------------
    ref = DBSCAN(eps=eps, **_explicit_kw(plan_cfg), **base_kw)
    old_disp = os.environ.get("PYPARDIS_DISPATCH")
    os.environ["PYPARDIS_DISPATCH"] = str(plan_cfg["dispatch"])
    try:
        ref.fit(X)
    finally:
        if old_disp is None:
            os.environ.pop("PYPARDIS_DISPATCH", None)
        else:
            os.environ["PYPARDIS_DISPATCH"] = old_disp
    labels_match = bool(
        np.array_equal(np.asarray(model.labels_),
                       np.asarray(ref.labels_))
    )
    assert labels_match, (
        "auto labels differ from the same explicit config"
    )

    # -- measured lattice (planned config included) -------------------
    if not any(
        all(c[k] == plan_cfg[k] for k in ("mode", "block", "merge",
                                          "precision"))
        for c in lattice_cfgs
    ):
        lattice_cfgs.append(dict(plan_cfg))
        DBSCAN(eps=eps, **_explicit_kw(plan_cfg), **base_kw).fit(X)
    lattice = []
    for cfg in lattice_cfgs:
        walls = []
        for _rep in range(2):
            staging.clear()
            m = DBSCAN(eps=eps, **_explicit_kw(cfg), **base_kw)
            t0 = time.perf_counter()
            m.fit(X)
            walls.append(time.perf_counter() - t0)
        lattice.append({
            "config": cfg,
            "wall_s": round(min(walls), 4),
            "samples_s": [round(w, 4) for w in walls],
        })
    assert len(lattice) >= 6, f"lattice has {len(lattice)} points"
    best = min(lattice, key=lambda e: e["wall_s"])
    planned_entry = min(
        (
            e for e in lattice
            if all(
                e["config"][k] == plan_cfg[k]
                for k in ("mode", "block", "merge", "precision")
            )
        ),
        key=lambda e: e["wall_s"],
        default=None,
    )
    assert planned_entry is not None, "planned config missing from lattice"
    within = planned_entry["wall_s"] / max(best["wall_s"], 1e-9)

    # -- gates --------------------------------------------------------
    assert within <= 1.25, (
        f"planned config {plan_cfg} measured {planned_entry['wall_s']}s"
        f" — {within:.2f}x the best lattice config "
        f"{best['config']} at {best['wall_s']}s"
    )
    assert overhead_fraction <= 0.05, (
        f"probe+plan overhead {overhead:.3f}s is "
        f"{overhead_fraction:.1%} of the {auto_wall:.3f}s auto fit "
        f"(gate: 5%)"
    )
    for k, v in tune["predicted_phases"].items():
        assert np.isfinite(v), f"predicted {k} is {v}"

    row = {
        "metric": "tune_planned_within",
        "value": round(within, 4),
        "unit": "x",
        "schema": "pypardis_tpu/tune@1",
        "n": n,
        "dim": dim,
        "eps": eps,
        "mesh_devices": int(mesh.devices.size),
        "plan": tune["plan"],
        "predicted_phases": tune["predicted_phases"],
        "actual_phases": tune["actual_phases"],
        "probe_overhead_s": round(overhead, 4),
        "probe_overhead_fraction": round(overhead_fraction, 5),
        "auto_wall_s": round(auto_wall, 4),
        "planned_wall_s": planned_entry["wall_s"],
        "best_wall_s": best["wall_s"],
        "best_config": best["config"],
        "labels_match": labels_match,
        "corpus_rows": int(tune["corpus_rows"]),
        "lattice": lattice,
        "samples_s": [planned_entry["wall_s"]],
        "ari_vs_truth": ari,
        "telemetry": tel,
    }
    print(json.dumps(row))


if __name__ == "__main__":
    main()
