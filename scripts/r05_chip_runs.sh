#!/bin/bash
# Round-5 native-chip probe sequence (run AFTER the mode=device row).
# Chip runs are serialized; each is a fresh process (axon poison
# discipline).  Exact pair budgets avoid the overflow-rerun compile.
set -x
cd /root/repo
ENV="PYTHONPATH=/root/repo:/root/.axon_site PYPARDIS_PROBE_PLATFORM=native"

# steady-state engine rate: device-resident input, ring halo, device merge
timeout 3600 env $ENV python scripts/meshscale_probe.py 10000000 device_input 8 2.4 \
  --dim 16 --std 0.4 --block 2048 --n-centers 0 \
  >> /tmp/chip_rows.jsonl 2>/tmp/chip_device_input.log

# ring halo from host input
timeout 3600 env $ENV python scripts/meshscale_probe.py 10000000 ring 8 2.4 \
  --dim 16 --std 0.4 --block 2048 --n-centers 0 --pair-budget 331776 \
  >> /tmp/chip_rows.jsonl 2>/tmp/chip_ring.log

# skewed density through the single-shard fused path at 10M
timeout 3600 env PYTHONPATH=/root/repo:/root/.axon_site \
  python scripts/scale_probe.py 10000000 16 2.4 --skew lognormal \
  >> /tmp/chip_rows.jsonl 2>/tmp/chip_skew_fused.log

# uniform fused 10M for the same-session comparison row
timeout 3600 env PYTHONPATH=/root/repo:/root/.axon_site \
  python scripts/scale_probe.py 10000000 16 2.4 \
  >> /tmp/chip_rows.jsonl 2>/tmp/chip_uniform_fused.log

echo ALL-CHIP-ROWS-DONE
