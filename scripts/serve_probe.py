#!/usr/bin/env python
"""Serving probe: per-batch-size QPS + latency percentiles.

Fits a blobs model once, then drives the query engine
(``pypardis_tpu.serve``) at several request batch sizes, emitting one
JSON row per size::

    {"metric": "serve_qps", "value": <qps>, "unit": "queries/sec",
     "batch_size": B, "p50_ms": ..., "p99_ms": ..., "batch_fill": ...,
     "oracle_exact": true, "telemetry": {...run_report@1 with
     "serving" block...}}

Every row's labels are checked against the brute-force core-point
oracle (exact equality — the serving correctness contract); the last
row's telemetry is validated by ``scripts/check_bench_json.py`` (the
``serving`` schema block) under ``make serve-probe`` / ``bench-smoke``.

Env knobs: SERVE_N (fit points, default 4000), SERVE_DIM (default 4),
SERVE_Q (queries per batch size, default 2048), SERVE_BATCHES (comma
list of request sizes, default "32,256,1024"), SERVE_BACKEND
(auto|xla|pallas, default auto).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import numpy as np

    from benchdata import make_blob_data
    from pypardis_tpu import DBSCAN

    n = int(os.environ.get("SERVE_N", 4000))
    dim = int(os.environ.get("SERVE_DIM", 4))
    n_q = int(os.environ.get("SERVE_Q", 2048))
    sizes = [
        int(s) for s in os.environ.get(
            "SERVE_BATCHES", "32,256,1024"
        ).split(",")
    ]
    backend = os.environ.get("SERVE_BACKEND", "auto")
    eps, min_samples = 2.4 * (dim / 16) ** 0.5, 10
    X, _truth = make_blob_data(n, dim, n_centers=8, std=0.4)

    model = DBSCAN(eps=eps, min_samples=min_samples, block=512)
    model.fit(X)
    rng = np.random.default_rng(1)
    lo, hi = X.min(axis=0), X.max(axis=0)

    for bs in sizes:
        # Fresh engine per size so the latency/QPS gauges describe ONE
        # batch-size regime (the index itself re-stages from the device
        # cache — the warm path the staging economy exists for).
        engine = model.query_engine(backend=backend)
        queries = np.concatenate([
            X[rng.integers(0, n, size=n_q // 2)]
            + rng.normal(scale=eps / 2, size=(n_q // 2, dim)),
            rng.uniform(lo, hi, size=(n_q - n_q // 2, dim)),
        ]).astype(np.float32)
        t0 = time.perf_counter()
        tickets = []
        for s in range(0, n_q, bs):
            tickets.append(engine.submit(queries[s:s + bs]))
            # Drain as the queue fills — the coalescer packs several
            # submitted requests into each padded device batch.
            if len(tickets) % 8 == 0:
                engine.drain()
        engine.drain()
        wall = time.perf_counter() - t0
        got = np.concatenate([t.result() for t in tickets])
        olabs, _od2 = engine.index.oracle_predict(queries)
        exact = bool(np.array_equal(got, olabs))
        stats = engine.serving_stats()
        row = {
            "metric": "serve_qps",
            "value": round(n_q / wall, 1),
            "unit": "queries/sec",
            "batch_size": bs,
            "p50_ms": stats["p50_ms"],
            "p99_ms": stats["p99_ms"],
            "batch_fill": stats["batch_fill"],
            "oracle_exact": exact,
            "telemetry": model.report(),
        }
        print(json.dumps(row), flush=True)
        if not exact:
            print(
                f"serve probe FAILED: batch_size={bs} labels diverge "
                f"from the brute-force oracle", file=sys.stderr,
            )
            sys.exit(1)


if __name__ == "__main__":
    main()
