#!/usr/bin/env python
"""Pod-scale execution probe (``make multihost-probe``, in bench-smoke).

Proves the PR-20 multi-process contract end to end on a localhost
fleet of ``jax.distributed`` controller processes with faked CPU
devices (2 processes x 4 devices — the same 8 global devices the
in-process reference mesh uses):

1. **fit parity** — a 2-process global-Morton fit is BYTE-IDENTICAL to
   the single-process 8-device fit, under BOTH merges (``device`` and
   ``host``), and the KD route likewise;
2. **shared-store streaming build** — the external sample-sort's
   pass 2/3 partition across processes; starts / center / tile boxes /
   sorted order byte-identical to the solo build, with the measured
   build walls reported (the >= 1.8x P=4 speedup gate applies only
   when the host actually has >= 4 cores — report-only on 1-core CI);
3. **fault drill** — one worker SIGKILLs itself mid-fixpoint
   (``dist.worker`` injection), the launcher tears the fleet down, and
   a relaunch with ``train(resume=)`` against the coordinator's
   jobstate snapshot lands labels byte-identical to the clean run;
4. **fleet flight merge** — every process records its own flight file
   into one shared dir; ``obs.replay(dir)`` merges them, the killed
   worker's ``fault_injected`` event survives in the merged stream,
   and the clock-skew flag stays quiet on a same-host fleet.

Emits ONE bench-style JSON row (``schema="pypardis_tpu/multihost@1"``,
``metric="multihost_pod_parity"``) whose telemetry block is the CLEAN
in-process reference fit's report, so the row rides the
``bench_diff --annotate`` / ``check_bench_json --require-diff`` gate
like every other probe.

Workers re-enter this file via ``--worker <task>`` (shared with
``tests/test_multihost.py`` and ``scripts/fault_probe.py`` so there is
exactly one fleet-worker body).  Geometry via env: MH_N (default
3000), MH_STREAM_N (default 20000).
"""

import json
import os
import sys
import tempfile
import time

_DEV_PER_PROC = 4
_N_PROCS = 2

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

KW = dict(eps=0.45, min_samples=5, block=64)
STREAM_KW = dict(eps=0.4, block=64, bucket_bytes=100_000, chunk=3000)


def _force_cpu_mesh(n_dev: int) -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_dev}"
        ).strip()


def chain_data(n: int):
    """One cluster threading every Morton shard: the pmin fixpoint
    needs several rounds, so the ``dist.worker`` injection window is
    wide and deterministic (same geometry as fault_probe)."""
    import numpy as np

    rng = np.random.default_rng(0)
    X = np.stack(
        [np.arange(n) * 0.1, rng.normal(0, 0.05, n)], axis=1
    )
    return X.astype(np.float32)


def stream_data(n: int):
    import numpy as np

    rng = np.random.default_rng(1)
    return rng.normal(size=(n, 4)).astype(np.float32)


# ---------------------------------------------------------------------------
# worker body (one per fleet process; tests and fault_probe reuse it)
# ---------------------------------------------------------------------------


def worker(task: str, out_base: str) -> None:
    """Fleet worker: join via the PYPARDIS_DIST_* env knobs
    (launch_fleet sets them), run ``task``, save
    ``<out_base>.p<rank>.npz``."""
    import numpy as np

    from pypardis_tpu.parallel import dist

    if not dist.init_distributed():
        # A 1-process "fleet" (the parity reference in tests) runs the
        # classic single-process path on its faked devices.
        assert os.environ.get("PYPARDIS_DIST_NPROCS") == "1", \
            "worker needs PYPARDIS_DIST_* set"
    rank = dist.process_index()
    # Per-ATTEMPT flight dir: launch_fleet relaunches the whole fleet
    # on a fresh coordinator port after a bind collision or a gloo
    # transport abort, and a dead first attempt's half-written flight
    # files must not pollute the final fleet's merge — so key the dir
    # by the port, which the launcher reports back to the driver.
    if os.environ.get("MH_FLIGHT_BASE"):
        port = os.environ["PYPARDIS_DIST_COORD"].rsplit(":", 1)[1]
        os.environ["PYPARDIS_FLIGHT"] = os.path.join(
            os.environ["MH_FLIGHT_BASE"], f"a{port}"
        )
    out = {}
    if task == "fits":
        from pypardis_tpu import DBSCAN

        X = chain_data(int(os.environ.get("MH_N", 3000)))
        for mode, merge in (("global_morton", "device"),
                            ("global_morton", "host"),
                            ("kd", "device")):
            m = DBSCAN(mode=mode, merge=merge, **KW)
            m.fit(X)
            out[f"labels_{mode}.{merge}"] = m.labels_
            out[f"core_{mode}.{merge}"] = m.core_sample_mask_
    elif task == "stream":
        from pypardis_tpu.partition import morton_range_split_streaming

        X = stream_data(int(os.environ.get("MH_STREAM_N", 20000)))
        t0 = time.perf_counter()
        sp = morton_range_split_streaming(X, 4, **STREAM_KW)
        out["build_s"] = np.float64(time.perf_counter() - t0)
        ids, _rows = sp.row_span(0, sp.n)
        out.update(
            starts=sp.starts, center=sp.center,
            tlo=sp.tile_lo, thi=sp.tile_hi, ids=ids,
        )
        sp.close()
    elif task == "faultfit":
        # The drill: the designated rank arms a terminal dist.worker
        # fault and converts it to a REAL SIGKILL (no cleanup, no
        # flight seal) — the harshest mid-fixpoint death.  A resumed
        # relaunch (MH_KILL_RANK unset) replays the coordinator's
        # snapshot.
        import signal

        from pypardis_tpu import DBSCAN
        from pypardis_tpu.utils import faults

        X = chain_data(int(os.environ.get("MH_N", 3000)))
        kill_rank = int(os.environ.get("MH_KILL_RANK", -1))
        if rank == kill_rank:
            faults.install(
                "dist.worker:%s=error"
                % os.environ.get("MH_KILL_OCC", "3")
            )
        m = DBSCAN(mode="global_morton", merge="device", **KW)
        try:
            m.train(X, resume=os.environ["MH_CKPT"])
        except faults.FaultInjected:
            os.kill(os.getpid(), signal.SIGKILL)
        out["labels"] = m.labels_
        out["core"] = m.core_sample_mask_
        out["restored_rounds"] = np.int64(
            m._jobstate.restored_rounds if m._jobstate else 0
        )
    else:
        raise SystemExit(f"unknown worker task {task!r}")
    np.savez(f"{out_base}.p{rank:02d}.npz", **out)


# ---------------------------------------------------------------------------
# probe driver
# ---------------------------------------------------------------------------


def check(msg: str, ok: bool) -> bool:
    print(f"multihost-probe: {msg}: {'ok' if ok else 'FAILED'}",
          file=sys.stderr)
    if not ok:
        sys.exit(1)
    return True


def _fleet(task: str, out_base: str, n_procs: int, env_extra=None,
           expect_fail: bool = False):
    from pypardis_tpu.parallel import dist

    env = dict(os.environ)
    # Workers must import the repo regardless of the launch cwd.
    env["PYTHONPATH"] = os.pathsep.join(
        [sys.path[0]] + [p for p in [env.get("PYTHONPATH")] if p]
    )
    # The launcher sets the fleet's own XLA_FLAGS/JAX_PLATFORMS.
    env.pop("XLA_FLAGS", None)
    env.update(env_extra or {})
    rcs, port, attempts, tails = dist.launch_fleet(
        [sys.executable, os.path.abspath(__file__), "--worker", task,
         out_base],
        n_procs, _DEV_PER_PROC, env=env,
        timeout_s=float(os.environ.get("MH_TIMEOUT_S", 600)),
    )
    if attempts > 1:
        print(f"multihost-probe: fleet task {task!r} relaunched "
              f"({attempts} attempts)", file=sys.stderr)
    if not expect_fail and any(rcs):
        for t in tails:
            print(t[-2000:], file=sys.stderr)
        check(f"fleet task {task!r} exited {rcs}", False)
    return rcs, port


def main() -> None:
    if len(sys.argv) > 2 and sys.argv[1] == "--worker":
        worker(sys.argv[2], sys.argv[3])
        return

    _force_cpu_mesh(_N_PROCS * _DEV_PER_PROC)
    import numpy as np

    from pypardis_tpu import DBSCAN, obs
    from pypardis_tpu.partition import morton_range_split_streaming

    n = int(os.environ.get("MH_N", 3000))
    X = chain_data(n)
    tmp = tempfile.mkdtemp(prefix="multihost_probe_")

    # -- in-process reference (8 devices, 1 process) -----------------------
    ref = {}
    for mode, merge in (("global_morton", "device"),
                        ("global_morton", "host"), ("kd", "device")):
        m = DBSCAN(mode=mode, merge=merge, **KW)
        m.fit(X)
        ref[f"{mode}.{merge}"] = (
            np.asarray(m.labels_), np.asarray(m.core_sample_mask_),
        )
        if (mode, merge) == ("global_morton", "device"):
            rep = m.report()
    assert rep["faults"]["injected"] == 0

    # -- 1: fleet fit parity, both merges + KD -----------------------------
    fit_base = os.path.join(tmp, "fits")
    _fleet("fits", fit_base, _N_PROCS)
    parity = {}
    for r in range(_N_PROCS):
        with np.load(f"{fit_base}.p{r:02d}.npz") as z:
            for key, (labels, core) in ref.items():
                ok = (
                    np.array_equal(z[f"labels_{key}"], labels)
                    and np.array_equal(z[f"core_{key}"], core)
                )
                parity[key] = parity.get(key, True) and ok
    for key, ok in parity.items():
        check(f"2-process {key} fit byte-identical to 1-process "
              f"8-device", ok)

    # -- 2: shared-store streaming build -----------------------------------
    sn = int(os.environ.get("MH_STREAM_N", 20000))
    SX = stream_data(sn)
    t0 = time.perf_counter()
    sp = morton_range_split_streaming(SX, 4, **STREAM_KW)
    solo_s = time.perf_counter() - t0
    solo_ids, _ = sp.row_span(0, sp.n)
    cores = os.cpu_count() or 1
    build_procs = 4 if cores >= 4 else _N_PROCS
    st_base = os.path.join(tmp, "stream")
    tf0 = time.perf_counter()
    _fleet("stream", st_base, build_procs,
           env_extra={"MH_STREAM_N": str(sn)})
    fleet_wall = time.perf_counter() - tf0
    stream_ok, fleet_s = True, 0.0
    for r in range(build_procs):
        with np.load(f"{st_base}.p{r:02d}.npz") as z:
            stream_ok &= (
                np.array_equal(z["starts"], sp.starts)
                and np.array_equal(z["center"], sp.center)
                and np.array_equal(z["tlo"], sp.tile_lo)
                and np.array_equal(z["thi"], sp.tile_hi)
                and np.array_equal(z["ids"], solo_ids)
            )
            fleet_s = max(fleet_s, float(z["build_s"]))
    sp.close()
    check(f"{build_procs}-process streaming build byte-identical "
          f"(starts/center/boxes/order)", stream_ok)
    speedup = solo_s / max(fleet_s, 1e-9)
    speedup_gated = cores >= 4 and build_procs >= 4
    if speedup_gated:
        check(f"P=4 streaming build speedup {speedup:.2f}x >= 1.8x "
              f"({cores} cores)", speedup >= 1.8)
    else:
        print(
            f"multihost-probe: build speedup {speedup:.2f}x at "
            f"P={build_procs} (report-only: {cores} core(s))",
            file=sys.stderr,
        )

    # -- 3: fault drill — SIGKILL mid-fixpoint, fleet resume --------------
    # Two flight dirs: one per launch — a fleet merge spans ONE fleet's
    # members; merging two launches minutes apart is exactly what the
    # clock-skew flag exists to call out.
    flight_kill = os.path.join(tmp, "flight_kill")
    flight_resume = os.path.join(tmp, "flight_resume")
    ckpt = os.path.join(tmp, "drill.ckpt.npz")
    drill_base = os.path.join(tmp, "drill")
    rcs, kill_port = _fleet(
        "faultfit", drill_base, _N_PROCS,
        env_extra={
            "MH_CKPT": ckpt, "MH_KILL_RANK": "1", "MH_KILL_OCC": "3",
            "PYPARDIS_CKPT_EVERY_S": "0",
            "MH_FLIGHT_BASE": flight_kill,
        },
        expect_fail=True,
    )
    check(f"drill fleet died from the injected kill (rcs={rcs})",
          any(rc != 0 for rc in rcs))
    check("coordinator jobstate snapshot survived the kill",
          os.path.exists(ckpt))
    _, resume_port = _fleet(
        "faultfit", drill_base, _N_PROCS,
        env_extra={
            "MH_CKPT": ckpt, "PYPARDIS_CKPT_EVERY_S": "0",
            "MH_FLIGHT_BASE": flight_resume,
        },
    )
    # The workers nested each attempt's flights under a<port>; the
    # launcher's returned port names the attempt that actually ran.
    flight_kill = os.path.join(flight_kill, f"a{kill_port}")
    flight_resume = os.path.join(flight_resume, f"a{resume_port}")
    base_labels, base_core = ref["global_morton.device"]
    restored = 0
    drill_ok = True
    for r in range(_N_PROCS):
        with np.load(f"{drill_base}.p{r:02d}.npz") as z:
            drill_ok &= (
                np.array_equal(z["labels"], base_labels)
                and np.array_equal(z["core"], base_core)
            )
            restored = max(restored, int(z["restored_rounds"]))
    check(
        f"fleet resume labels byte-identical to the clean run "
        f"(restored_rounds={restored})",
        drill_ok and restored >= 1,
    )

    # -- 4: fleet flight merge --------------------------------------------
    fleet_rep = obs.replay(flight_resume).report()
    injected = sum(
        1 for r in obs.replay(flight_kill).merged_records()
        if r.get("k") == "ev" and r.get("kind") == "fault_injected"
        and r.get("f", {}).get("site") == "dist.worker"
    )
    check(
        f"fleet flight merge: {fleet_rep['hosts']} members, "
        f"{fleet_rep['records']} records, killed run's injected event "
        f"survived (count={injected})",
        fleet_rep["hosts"] == _N_PROCS and fleet_rep["complete"]
        and fleet_rep["records"] > 0 and injected >= 1,
    )
    check("same-host fleet clock-skew flag quiet",
          fleet_rep["clock_skew_warning"] is False)
    # And the flag's positive side: merging the kill-run and resume-run
    # files as if they were ONE fleet puts the anchors a full fit wall
    # apart — the default 5s threshold must call that out.
    import glob as _glob

    both = sorted(
        _glob.glob(os.path.join(flight_kill, "*.jsonl"))
        + _glob.glob(os.path.join(flight_resume, "*.jsonl"))
    )
    skew_trips = obs.fleet_replay(both).report()["clock_skew_warning"]
    check("skew flag trips on a cross-launch merge", skew_trips is True)

    row = {
        "schema": "pypardis_tpu/multihost@1",
        "metric": "multihost_pod_parity",
        "value": _N_PROCS,
        "unit": "processes",
        "n": n,
        "processes": _N_PROCS,
        "devices_per_process": _DEV_PER_PROC,
        "parity": {
            "gm_device": bool(parity["global_morton.device"]),
            "gm_host": bool(parity["global_morton.host"]),
            "kd": bool(parity["kd.device"]),
            "stream": bool(stream_ok),
        },
        "ring": {
            "boundary_tile_bytes":
                rep["sharding"]["boundary_tile_bytes"],
            "ring_rounds": rep["sharding"]["ring_rounds"],
            "fixpoint_rounds": rep["sharding"]["fixpoint_rounds"],
        },
        "drill": {
            "resume_used": True,
            "restored_rounds": restored,
            "fault_injected_seen": injected,
            "parity": bool(drill_ok),
        },
        "build": {
            "solo_s": round(solo_s, 4),
            "fleet_s": round(fleet_s, 4),
            "fleet_wall_s": round(fleet_wall, 4),
            "procs": build_procs,
            "speedup": round(speedup, 4),
            "gated": bool(speedup_gated),
        },
        "fleet_flight": {
            "members": fleet_rep["hosts"],
            "records": fleet_rep["records"],
            "complete": fleet_rep["complete"],
            "clock_skew_s": fleet_rep["clock_skew_s"],
            "clock_skew_warning": fleet_rep["clock_skew_warning"],
        },
        "telemetry": rep,
    }
    print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
