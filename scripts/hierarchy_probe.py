#!/usr/bin/env python
"""CI probe for the density hierarchy (ISSUE 18).

One distance pass, a whole dendrogram: measures the eps-free path —
mutual-reachability MST + stability-condensed tree over the cached
neighbor-pair graph — by timing an 8-rung ``sweep(X, "auto")`` ladder
(rungs picked by HDBSCAN*-style excess-of-mass stability) against 8
independent ``fit()`` runs at the very same eps values, cold staging
on both sides.  Gates, enforced here (nonzero exit) and re-checked by
``scripts/check_bench_json.py``:

* ``distance_passes == 1`` for the whole ladder (core distances, MST,
  condensation and every rung's flat labels ride ONE cached graph);
* ladder wall <= 0.2x the sum of the solo fits
  (``hierarchy_amortization >= 5``);
* per-rung labels BYTE-IDENTICAL to the solo fits (and ARI == 1.0);
* ``boruvka_rounds <= round_cap`` (= ceil(log2(live components)) + 1)
  and ``mst_edges == n_live - n_components`` — the spanning-forest
  invariant, pinned from telemetry, not recomputed.

Emits ONE bench-style JSON row: ``metric="hierarchy_amortization"``,
``value`` = (sum of solo walls) / ladder wall, ``schema`` =
``pypardis_tpu/hierarchy@1``, the per-rung parity table, the
``hierarchy`` telemetry block and the full ``run_report@1`` telemetry.
Geometry via env: HIER_N (default 16000), HIER_DIM (4), HIER_K
(8 ladder rungs), HIER_BLOCK (128).  The graph ceiling is pinned via
PYPARDIS_HIER_EPS_MAX (default 0.2 here) so the slab stays the same
size class as the sweep probe's; unset geometry knobs inherit the
sweep probe's well-separated-centers regime where cross-route byte
parity is exact.
"""

import json
import os
import sys
import time

_N_DEV = int(os.environ.get("PYPARDIS_PROBE_DEVICES", "8"))
if os.environ.get("PYPARDIS_PROBE_PLATFORM") != "native":
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={_N_DEV}"
        ).strip()

import numpy as np  # noqa: E402
import jax  # noqa: E402

if os.environ.get("PYPARDIS_PROBE_PLATFORM") != "native":
    jax.config.update("jax_platforms", "cpu")
    if "jax_num_cpu_devices" in jax.config._value_holders:
        jax.config.update("jax_num_cpu_devices", _N_DEV)

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _geometry(n: int, dim: int):
    """The sweep probe's well-separated Gaussian clusters (pairwise
    center distance >= ~4 vs std 0.15): no border point ever touches
    two clusters, so every ladder rung's byte parity vs its solo fit
    is unambiguous (verified for the pinned seed)."""
    rng = np.random.default_rng(11)
    k = 8
    centers = rng.normal(size=(k, dim))
    centers *= 4.0 / np.linalg.norm(centers, axis=1, keepdims=True)
    centers = centers * (1.0 + np.arange(k)[:, None] * 0.5)
    per = n // k
    X = np.concatenate(
        [
            c + rng.normal(scale=0.15, size=(per, dim))
            for c in centers
        ]
        + [rng.normal(scale=0.15, size=(n - per * k, dim)) + centers[0]]
    )
    return X.astype(np.float64)


def main() -> None:
    from pypardis_tpu import DBSCAN
    from pypardis_tpu.parallel import default_mesh, staging
    from sklearn.metrics import adjusted_rand_score

    n = int(os.environ.get("HIER_N", 16000))
    dim = int(os.environ.get("HIER_DIM", 4))
    k_cfg = int(os.environ.get("HIER_K", 8))
    block = int(os.environ.get("HIER_BLOCK", 128))
    ms = 5
    # Pin the graph ceiling: the adaptive sample-kNN heuristic is a
    # deliberate overestimate, which on this geometry would connect
    # whole clusters and balloon the slab past the sweep probe's size
    # class without changing what the probe measures.
    os.environ.setdefault("PYPARDIS_HIER_EPS_MAX", "0.2")
    os.environ["PYPARDIS_HIER_LADDER_K"] = str(k_cfg)
    X = _geometry(n, dim)
    mesh = default_mesh(min(_N_DEV, jax.device_count()))
    kw = dict(min_samples=ms, block=block, mesh=mesh)

    # -- warm-up (compiles) -------------------------------------------
    DBSCAN(eps=None, **kw).sweep(X, "auto")
    DBSCAN(eps=0.15, **kw).fit(X)

    # -- measured ladder (cold staging, warm jit; best of 2) ----------
    ladder_samples = []
    for _rep in range(2):
        staging.clear()
        model = DBSCAN(eps=None, **kw)
        t0 = time.perf_counter()
        res = model.sweep(X, "auto")
        ladder_samples.append(time.perf_counter() - t0)
    ladder_wall = min(ladder_samples)
    tel = model.report()
    hier = tel["hierarchy"]
    ladder = [float(e) for e in tel["sweep"]["ladder"]]
    assert len(ladder) == k_cfg, (
        f"auto ladder has {len(ladder)} rungs, requested {k_cfg}"
    )

    # -- measured solo fits at the SAME eps values --------------------
    staging.clear()
    solo_walls = []
    solo_labels = {}
    for e in ladder:
        m = DBSCAN(eps=e, **kw)
        t0 = time.perf_counter()
        m.fit(X)
        solo_walls.append(time.perf_counter() - t0)
        solo_labels[e] = np.asarray(m.labels_)
    solo_wall = float(sum(solo_walls))

    # -- gates --------------------------------------------------------
    assert tel["sweep"]["distance_passes"] == 1, (
        f"ladder ran {tel['sweep']['distance_passes']} distance "
        f"passes, expected 1"
    )
    assert hier["distance_passes"] == 1
    assert hier["boruvka_rounds"] <= hier["round_cap"], (
        f"Boruvka took {hier['boruvka_rounds']} rounds, cap "
        f"{hier['round_cap']}"
    )
    assert hier["mst_edges"] == hier["n_live"] - hier["n_components"], (
        f"MST has {hier['mst_edges']} edges for {hier['n_live']} live "
        f"points / {hier['n_components']} components — not a spanning "
        f"forest"
    )
    per_rung = []
    for e in ladder:
        match = bool(np.array_equal(res.labels(e, ms), solo_labels[e]))
        ari = float(
            adjusted_rand_score(solo_labels[e], res.labels(e, ms))
        )
        assert match, f"labels differ from solo fit at eps={e}"
        assert ari == 1.0, f"ARI {ari} != 1.0 at eps={e}"
        per_rung.append(
            {
                "eps": e,
                "min_samples": ms,
                "labels_match": match,
                "ari": ari,
                "n_clusters": int(res.labels(e, ms).max()) + 1,
            }
        )
    amortization = solo_wall / max(ladder_wall, 1e-9)
    assert amortization >= 5.0, (
        f"ladder wall {ladder_wall:.2f}s not <= 0.2x the "
        f"{solo_wall:.2f}s sum of {k_cfg} solo fits (amortization "
        f"{amortization:.2f})"
    )

    row = {
        "metric": "hierarchy_amortization",
        "value": round(amortization, 3),
        "unit": "x",
        "schema": "pypardis_tpu/hierarchy@1",
        "n": n,
        "dim": dim,
        "k": k_cfg,
        "distance_passes": 1,
        "graph_pairs": int(hier["graph_pairs"]),
        "mst_edges": int(hier["mst_edges"]),
        "boruvka_rounds": int(hier["boruvka_rounds"]),
        "round_cap": int(hier["round_cap"]),
        "eps_selected": float(hier["eps_selected"]),
        "ladder": ladder,
        "ladder_wall_s": round(ladder_wall, 4),
        "solo_wall_s": round(solo_wall, 4),
        "samples_s": [round(s, 4) for s in ladder_samples],
        "per_rung": per_rung,
        "hierarchy": dict(hier),
        "telemetry": tel,
    }
    print(json.dumps(row))


if __name__ == "__main__":
    main()
