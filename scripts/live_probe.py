#!/usr/bin/env python
"""Live-update probe: insert/delete latency, locality, sustained load,
and replicated-index throughput — schema'd rows for ``make live-probe``
(wired into ``bench-smoke``).

Three JSON rows, each validated by ``scripts/check_bench_json.py``:

1. ``live_update_latency`` — K single-point inserts + deletes against a
   fitted model; asserts incremental labels end ARI == 1.0 vs a full
   refit on the final point set, ``predict`` stays bitwise exact vs the
   brute-force oracle on the UPDATED index, and the boundary-interior
   insert's ``recluster_tile_fraction`` is strictly < 1.0 (locality is
   measured, not asserted).
2. ``live_load_qps`` — the Poisson sustained-load harness
   (``pypardis_tpu.serve.load``) with >= 4 concurrent clients and a
   write mix; finite qps/p50/p99/batch_fill/update-visible-latency.
3. ``live_replicated_speedup`` — single-device engine vs the
   replicated-index engine on an identical compute-bound workload,
   with per-device slab bytes.  On hosts that can actually execute
   device programs in parallel (cpu_count >= 4) the probe FAILS below
   2x; on a serial host (the 1-core CI container: all 8 faked devices
   share one core, so wall-clock parallel speedup is physically
   impossible) the row still reports the measured ratio and asserts
   bitwise parity.

Env knobs: LIVE_N (default 4000), LIVE_DIM (4), LIVE_UPDATES (24),
LIVE_CLIENTS (4), LIVE_SECONDS (1.5), LIVE_REP_Q (8192).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def fail(msg: str) -> None:
    print(f"live probe FAILED: {msg}", file=sys.stderr)
    sys.exit(1)


def main():
    import numpy as np
    from sklearn.metrics import adjusted_rand_score

    from benchdata import make_separated_blob_data
    from pypardis_tpu import DBSCAN
    from pypardis_tpu.parallel.mesh import default_mesh
    from pypardis_tpu.serve import ReplicatedQueryEngine, sustained_load

    n = int(os.environ.get("LIVE_N", 4000))
    dim = int(os.environ.get("LIVE_DIM", 4))
    k_updates = int(os.environ.get("LIVE_UPDATES", 24))
    clients = int(os.environ.get("LIVE_CLIENTS", 4))
    seconds = float(os.environ.get("LIVE_SECONDS", 1.5))
    rep_q = int(os.environ.get("LIVE_REP_Q", 8192))
    eps, min_samples = 1.1 * (dim / 4) ** 0.5, 8
    X, _truth, centers = make_separated_blob_data(
        n, dim, n_centers=8, std=0.4,
        min_sep=2 * eps + 6 * 0.4 + 1.0, spread=12.0, seed=0,
    )
    rng = np.random.default_rng(7)

    model = DBSCAN(
        eps=eps, min_samples=min_samples, block=512,
        mesh=default_mesh(1),
    )
    model.fit(X)
    live = model.live(leaves=16)

    # -- row 1: update latency + locality + correctness -------------------
    for i in range(k_updates):
        kind = i % 4
        if kind == 0:
            # Boundary-interior insert: inside one blob, far from every
            # other — the strictly-local blast radius the acceptance
            # criterion measures.
            c = centers[i % len(centers)]
            live.insert(c + rng.normal(scale=0.2, size=(1, dim)))
            frac = live.stats["recluster_tile_fraction"]
            if live.stats["recluster_events"] > 0 and frac >= 1.0:
                fail(
                    f"boundary-interior insert re-clustered every tile "
                    f"(recluster_tile_fraction={frac})"
                )
        elif kind == 1:
            live.insert(
                rng.uniform(-30, 30, size=(1, dim))
            )  # far noise
        elif kind == 2:
            alive = live.ids()
            live.delete(alive[rng.integers(0, len(alive), size=1)])
        else:
            c = centers[(i + 3) % len(centers)]
            live.insert(c + rng.normal(scale=0.3, size=(3, dim)))

    refit = DBSCAN(
        eps=eps, min_samples=min_samples, block=512,
        mesh=default_mesh(1),
    ).fit(live.points())
    ari = float(adjusted_rand_score(refit.labels_, live.labels()))
    if ari != 1.0:
        fail(f"incremental labels diverge from full refit (ARI={ari})")

    Q = np.concatenate([
        live.points()[:512],
        rng.uniform(-15, 15, size=(512, dim)),
    ])
    t = live.engine.submit(Q)
    live.engine.drain()
    olabs, od2 = live.index.oracle_predict(Q)
    if not (np.array_equal(t.labels, olabs)
            and np.array_equal(t.d2, od2)):
        fail("predict diverges from the brute-force oracle on the "
             "updated index")

    stats = dict(live.stats)
    row = {
        "metric": "live_update_latency",
        "value": stats["insert_p50_ms"],
        "unit": "ms",
        "ari_vs_refit": ari,
        "oracle_exact": True,
        "telemetry": model.report(),
    }
    print(json.dumps(row), flush=True)

    # -- row 2: sustained load under Poisson arrivals ---------------------
    if clients < 4:
        fail(f"LIVE_CLIENTS must be >= 4 (got {clients})")
    res = sustained_load(
        live.engine, clients=clients, duration_s=seconds,
        rate_hz=120.0, batch_rows=32, write_fraction=0.15, live=live,
        seed=11,
    )
    for key in ("qps", "p50_ms", "p99_ms", "batch_fill"):
        v = res[key]
        if not np.isfinite(v):
            fail(f"sustained-load {key} is non-finite ({v})")
    row = {
        "metric": "live_load_qps",
        "value": res["qps"],
        "unit": "queries/sec",
        "load": res,
        "telemetry": model.report(),
    }
    print(json.dumps(row), flush=True)

    # -- row 3: replicated-index mode -------------------------------------
    from pypardis_tpu.serve import QueryEngine

    QR = (
        live.points()[rng.integers(0, stats["points"], size=rep_q)]
        + rng.normal(scale=eps / 2, size=(rep_q, dim))
    ).astype(np.float32)

    def best_qps(engine, reps=3):
        best, ticket = 0.0, None
        for _ in range(reps):
            t0 = time.perf_counter()
            ticket = engine.submit(QR)
            engine.drain()
            best = max(best, rep_q / (time.perf_counter() - t0))
        return best, ticket

    single = QueryEngine(
        live.index, backend="xla", batch_capacity=1 << 20,
        max_pending=1 << 20,
    )
    q_single, t_single = best_qps(single)
    rep = ReplicatedQueryEngine(
        live.index, backend="xla", batch_capacity=1 << 20,
        max_pending=1 << 20,
    )
    q_rep, t_rep = best_qps(rep)
    if not (np.array_equal(t_single.labels, t_rep.labels)
            and np.array_equal(t_single.d2, t_rep.d2)):
        fail("replicated engine diverges from the single-device engine")
    speedup = q_rep / q_single if q_single > 0 else 0.0
    parallel = os.cpu_count() or 1
    if parallel >= 4 and speedup < 2.0:
        fail(
            f"replicated speedup {speedup:.2f}x < 2x on a "
            f"{parallel}-core host ({rep.n_devices} devices)"
        )
    if parallel < 4:
        print(
            f"live probe note: host has {parallel} core(s) — the 8 "
            f"faked devices execute serially, so the >=2x replicated "
            f"wall-clock gate is physically unreachable here and is "
            f"reported, not enforced (measured {speedup:.2f}x; parity "
            f"asserted bitwise)",
            file=sys.stderr,
        )
    row = {
        "metric": "live_replicated_speedup",
        "value": round(speedup, 3),
        "unit": "x",
        "single_qps": round(q_single, 1),
        "replicated_qps": round(q_rep, 1),
        "parallel_capacity": parallel,
        "replicated": {
            k: rep.serving_stats()[k]
            for k in ("replicated", "replicated_devices",
                      "per_device_index_bytes", "index_epoch")
        },
        "telemetry": model.report(),
    }
    print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
