#!/usr/bin/env python
"""KDPartitioner build-time-vs-max_partitions micro-bench.

Emits one JSON row per (builder, max_partitions) cell: wall seconds
(best of ``PROBE_REPS``), the per-level breakdown, and the cost ratio
against the smallest mp — the number behind the host-pipeline
acceptance contract (the level-synchronous builder's mp=16 build costs
<= 1.5x its mp=8 build; the legacy builder's per-node gathers measured
~5x at 10M points, MESHSCALE_r05).  Pure numpy: no JAX import, so it
probes the host phase alone.

Env:  PROBE_N (default 1_000_000), PROBE_DIM (16), PROBE_MPS
("8,16,32"), PROBE_REPS (2), PROBE_CHECK ("1" fails the process when
the level builder's ratio exceeds PROBE_RATIO_MAX, default 1.5 —
"0" to just report).
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pypardis_tpu.partition import KDPartitioner, clear_level_pool  # noqa: E402


def main() -> int:
    n = int(os.environ.get("PROBE_N", 1_000_000))
    dim = int(os.environ.get("PROBE_DIM", 16))
    mps = [
        int(x)
        for x in os.environ.get("PROBE_MPS", "8,16,32").split(",")
        if x
    ]
    reps = int(os.environ.get("PROBE_REPS", 2))
    check = os.environ.get("PROBE_CHECK", "1") == "1"
    ratio_max = float(os.environ.get("PROBE_RATIO_MAX", 1.5))

    rng = np.random.default_rng(0)
    pts = rng.normal(size=(n, dim)).astype(np.float32)

    failures = []
    for builder in ("legacy", "level"):
        clear_level_pool()
        base = None
        for mp in sorted(mps):
            best, levels = None, []
            for _ in range(reps):
                t0 = time.perf_counter()
                part = KDPartitioner(
                    pts, max_partitions=mp, builder=builder
                )
                dt = time.perf_counter() - t0
                if best is None or dt < best:
                    best, levels = dt, list(part.level_times_s)
            if base is None:
                base = best
            ratio = best / base if base > 0 else 1.0
            print(
                json.dumps(
                    {
                        "metric": "kdpartitioner_build_s",
                        "builder": builder,
                        "n": n,
                        "dim": dim,
                        "max_partitions": mp,
                        "build_s": round(best, 4),
                        "ratio_vs_min_mp": round(ratio, 3),
                        "levels_s": [round(t, 4) for t in levels],
                        "n_partitions": part.n_partitions,
                    }
                )
            )
            if (
                check
                and builder == "level"
                and mp == 2 * min(mps)
                and ratio > ratio_max
            ):
                failures.append(
                    f"level builder mp={mp} ratio {ratio:.2f} > "
                    f"{ratio_max} vs mp={min(mps)}"
                )
    for f in failures:
        print(f"partition probe FAILED: {f}", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
