"""Assemble meshscale_probe JSON lines into MESHSCALE_r04.json.

Verifies the cross-mode agreement the probe's ``labels_sha`` enables:
every mode that clustered the same (n, dim, eps, max_partitions)
configuration must produce byte-identical densified labels — the
at-scale version of the 4k-point equality tests.

Usage: python scripts/meshscale_assemble.py OUT.json RUNS.jsonl...
"""

import json
import sys
from collections import defaultdict


def main():
    out_path = sys.argv[1]
    runs = []
    for path in sys.argv[2:]:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    runs.append(json.loads(line))

    by_config = defaultdict(list)
    for r in runs:
        by_config[(r["n"], r["dim"], r["eps"], r["max_partitions"])].append(r)

    agreement = {}
    for cfg, group in sorted(by_config.items()):
        shas = {r["labels_sha"] for r in group}
        agreement["x".join(map(str, cfg))] = {
            "modes": [r["mode"] for r in group],
            "labels_agree": len(shas) == 1,
        }
        if len(shas) != 1:
            print(f"WARNING: label mismatch at {cfg}: "
                  f"{[(r['mode'], r['labels_sha']) for r in group]}",
                  file=sys.stderr)

    doc = {
        "round": 4,
        "note": (
            "Scale proof of the distributed path (r3 review Next #1), "
            "two complementary platforms per run's 'platform' field: "
            "platform=cpu rows run the 8-device virtual mesh (XLA "
            "host-platform split) proving the CROSS-DEVICE collectives "
            "(pmin merge, ppermute ring) at moderate N — wall times "
            "there are CPU times, not TPU performance; platform=tpu "
            "rows run the real chip as a 1-device mesh with 8 "
            "partitions, proving the identical sharded machinery "
            "(multi-partition layout, halos, merge loop, overflow "
            "ladders) at 2M-10M points. fit_s includes first-process "
            "compiles. build_highwater_gb is the VmHWM delta across "
            "sharded_dbscan (on tpu rows it includes compile-helper "
            "RSS, so the cpu rows are the clean build-memory measure)."
        ),
        "runs": runs,
        "cross_mode_agreement": agreement,
        "all_agree": all(v["labels_agree"] for v in agreement.values()),
        "all_converged": all(
            r.get("merge_converged", True) in (True, None) for r in runs
        ),
    }
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"wrote {out_path}: {len(runs)} runs, "
          f"all_agree={doc['all_agree']} all_converged={doc['all_converged']}")


if __name__ == "__main__":
    main()
