#!/usr/bin/env python
"""Streaming-ingest probe (``make ingest-probe``, wired into
``bench-smoke``): batched-write amortization, epoch-swap exactness,
and the mixed read/write load row.

Asserted end to end (exits nonzero on any violation):

1. **one-dispatch-per-batch** — inserting B=256 points through
   ``LiveModel.insert_batch`` performs EXACTLY 1 recluster kernel
   dispatch (the ``recluster_dispatches`` counter) and 1 index delta
   (one epoch bump), where the same 256 points applied one call at a
   time pay one dispatch/delta per core-flipping write; incremental
   labels stay ARI == 1.0 vs a full refit either way.
2. **batched mixed sequence** — an ``IngestQueue``-coalesced
   insert/delete stream ends ARI == 1.0 vs refit, predict bitwise
   oracle-exact.
3. **epoch swap** — a full compaction cycle (background refit →
   fresh generation → in-place swap): predict is bitwise oracle-exact
   BEFORE and AFTER the swap, in-flight tickets submitted pre-swap
   resolve against the old generation, appended slabs are gone after.
4. **mixed traffic** — the sustained-load harness with a reader AND a
   Poisson writer population across >= 1 background compaction + epoch
   swap, zero dropped/failed tickets — emitted as the schema'd
   ``ingest@1`` row (``ingest_mixed_load``), piped through
   ``bench_diff --annotate`` into ``check_bench_json`` by the make
   target.

Env knobs: INGEST_N (default 4000), INGEST_DIM (4), INGEST_B (256),
INGEST_READERS (4), INGEST_WRITERS (2), INGEST_SECONDS (2.0).
"""

import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def fail(msg: str) -> None:
    print(f"ingest probe FAILED: {msg}", file=sys.stderr)
    sys.exit(1)


def main():
    import numpy as np
    from sklearn.metrics import adjusted_rand_score

    from benchdata import make_separated_blob_data
    from pypardis_tpu import DBSCAN
    from pypardis_tpu.parallel.mesh import default_mesh
    from pypardis_tpu.serve import Compactor, IngestQueue, sustained_load

    n = int(os.environ.get("INGEST_N", 4000))
    dim = int(os.environ.get("INGEST_DIM", 4))
    B = int(os.environ.get("INGEST_B", 256))
    readers = int(os.environ.get("INGEST_READERS", 4))
    writers = int(os.environ.get("INGEST_WRITERS", 2))
    seconds = float(os.environ.get("INGEST_SECONDS", 2.0))
    eps, min_samples = 1.1 * (dim / 4) ** 0.5, 8
    X, _truth, centers = make_separated_blob_data(
        n, dim, n_centers=8, std=0.4,
        min_sep=2 * eps + 6 * 0.4 + 1.0, spread=12.0, seed=0,
    )
    rng = np.random.default_rng(7)

    def fit_model(pts):
        return DBSCAN(
            eps=eps, min_samples=min_samples, block=512,
            mesh=default_mesh(1),
        ).fit(pts)

    def refit_ari(live):
        refit = fit_model(live.points()).labels_
        return float(adjusted_rand_score(refit, live.labels()))

    # Interior rows dense enough that the batch flips cores — the
    # recluster path MUST run for the one-dispatch assert to bite.
    batch = (
        centers[rng.integers(0, len(centers), B)]
        + rng.normal(scale=0.25, size=(B, dim))
    )

    # -- 1a: the batched path — exactly 1 dispatch, 1 delta ---------------
    model = fit_model(X)
    live = model.live(leaves=16)
    d0 = live.stats["recluster_dispatches"]
    e0 = live.index.epoch
    t0 = time.perf_counter()
    ids = live.insert_batch(batch)
    batch_s = time.perf_counter() - t0
    d_batched = live.stats["recluster_dispatches"] - d0
    deltas_batched = live.index.epoch - e0
    if d_batched != 1:
        fail(
            f"insert_batch(B={B}) ran {d_batched} recluster dispatches, "
            f"contract is exactly 1"
        )
    if deltas_batched != 1:
        fail(
            f"insert_batch(B={B}) shipped {deltas_batched} index "
            f"deltas, contract is exactly 1"
        )
    ari = refit_ari(live)
    if ari != 1.0:
        fail(f"batched insert diverges from full refit (ARI={ari})")

    # -- 1b: the same rows, one write at a time (the amortized cost) ------
    model_pp = fit_model(X)
    live_pp = model_pp.live(leaves=16)
    d0 = live_pp.stats["recluster_dispatches"]
    e0 = live_pp.index.epoch
    t0 = time.perf_counter()
    for row in batch:
        live_pp.insert(row[None])
    per_point_s = time.perf_counter() - t0
    d_per_point = live_pp.stats["recluster_dispatches"] - d0
    deltas_per_point = live_pp.index.epoch - e0
    if d_per_point <= 1:
        fail(
            f"per-point control ran only {d_per_point} dispatches — "
            f"the amortization comparison is vacuous"
        )
    ari = refit_ari(live_pp)
    if ari != 1.0:
        fail(f"per-point inserts diverge from full refit (ARI={ari})")
    print(
        f"ingest probe: B={B} batched 1 dispatch/1 delta in "
        f"{batch_s * 1e3:.0f}ms vs per-point {d_per_point} dispatches/"
        f"{deltas_per_point} deltas in {per_point_s * 1e3:.0f}ms "
        f"({per_point_s / max(batch_s, 1e-9):.1f}x wall)",
        file=sys.stderr,
    )

    # -- 2: IngestQueue-coalesced mixed sequence --------------------------
    queue = IngestQueue(live, max_batch_rows=512)
    tickets = []
    for i in range(6):
        c = centers[(2 * i) % len(centers)]
        tickets.append(queue.submit_insert(
            c + rng.normal(scale=0.3, size=(5, dim))
        ))
    tickets.append(queue.submit_delete(ids[:40]))
    tickets.append(queue.submit_insert(
        rng.uniform(-30, 30, size=(2, dim))
    ))
    resolved = queue.flush()
    if len(resolved) != len(tickets) or any(t.failed for t in resolved):
        fail(f"ingest queue left tickets unresolved/failed: "
             f"{[str(t.error) for t in resolved if t.failed]}")
    qs = queue.stats()
    if qs["batches"] >= len(tickets):
        fail(
            f"ingest queue did not coalesce: {qs['batches']} batches "
            f"for {len(tickets)} submits"
        )
    ari = refit_ari(live)
    if ari != 1.0:
        fail(f"queued mixed sequence diverges from refit (ARI={ari})")

    # -- 3: epoch swap exactness ------------------------------------------
    Q = np.concatenate([
        live.points()[:512],
        rng.uniform(-15, 15, size=(512, dim)),
    ])
    pre_labs, pre_d2 = live.index.oracle_predict(Q)
    inflight = live.engine.submit(Q)  # submitted BEFORE the swap
    gen0 = live.index.generation
    comp = Compactor(live)
    comp.compact()
    if live.index.generation != gen0 + 1:
        fail(f"compaction did not swap a generation "
             f"(generation={live.index.generation})")
    if not inflight.done:
        fail("in-flight ticket was dropped across the epoch swap")
    if not (np.array_equal(inflight.labels, pre_labs)
            and np.array_equal(inflight.d2, pre_d2)):
        fail("pre-swap ticket did not resolve against the old "
             "generation")
    post = live.engine.submit(Q)
    live.engine.drain()
    olabs, od2 = live.index.oracle_predict(Q)
    if not (np.array_equal(post.labels, olabs)
            and np.array_equal(post.d2, od2)):
        fail("predict diverges from the oracle AFTER the epoch swap")
    if live.index.appended_slab_bytes != 0:
        fail(
            f"compaction left {live.index.appended_slab_bytes} "
            f"appended-slab bytes"
        )
    ari = refit_ari(live)
    if ari != 1.0:
        fail(f"compacted clustering diverges from refit (ARI={ari})")

    row = {
        "metric": "ingest_batch_amortization",
        "value": float(B),
        "unit": "rows/dispatch",
        "schema": "pypardis_tpu/ingest@1",
        "batch_rows": B,
        "dispatches_batched": int(d_batched),
        "deltas_batched": int(deltas_batched),
        "dispatches_per_point": int(d_per_point),
        "deltas_per_point": int(deltas_per_point),
        "batch_s": round(batch_s, 6),
        "per_point_s": round(per_point_s, 6),
        "ari_vs_refit": 1.0,
        "oracle_exact": True,
        "telemetry": model.report(),
    }
    print(json.dumps(row), flush=True)

    # -- 4: mixed read/write traffic across a background compaction ------
    def write_sampler(w_rng, m):
        c = centers[w_rng.integers(0, len(centers))]
        return c + w_rng.normal(scale=0.25, size=(m, dim))

    comp2 = Compactor(live)
    res = sustained_load(
        live.engine, clients=readers, duration_s=seconds,
        rate_hz=120.0, batch_rows=32,
        writers=writers, write_rate_hz=40.0, write_batch_rows=8,
        write_sampler=write_sampler, live=live,
        compactor=comp2, compact_at_s=seconds * 0.25, seed=11,
    )
    if res["compactions"] < 1 or res["epoch_swaps"] < 1:
        fail(
            f"mixed load completed {res['compactions']} compactions / "
            f"{res['epoch_swaps']} swaps, need >= 1 of each"
        )
    for key in ("dropped_tickets", "write_failures",
                "deadline_failures"):
        if res[key] != 0:
            fail(f"mixed load {key} = {res[key]}, contract is 0")
    t = live.engine.submit(Q)
    live.engine.drain()
    olabs, od2 = live.index.oracle_predict(Q)
    if not (np.array_equal(t.labels, olabs)
            and np.array_equal(t.d2, od2)):
        fail("predict diverges from the oracle after mixed load")
    row = {
        "metric": "ingest_mixed_load",
        "value": res["qps"],
        "unit": "queries/sec",
        "schema": "pypardis_tpu/ingest@1",
        "load": res,
        "telemetry": model.report(),
    }
    print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
