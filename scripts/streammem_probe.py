"""Anonymous-memory probe for the streaming (memmap) shard build.

Compares peak ANONYMOUS host memory (RssAnon, sampled) of

* (a) the streaming ring fit of a DISK-BACKED memmap
  (``build_owned_shards_streaming``: per-device slab assembly), vs
* (b) the ordinary in-RAM host-halo fit of the same data,

on the 8-device CPU mesh.  RssAnon (not VmHWM) is the honest metric:
memmap pages are file-backed and evictable, and with free RAM the
kernel keeps them resident, which would inflate a VmHWM reading with
memory that never pressures the host.

Caveat stated in the artifact: on the CPU mesh the "device" slabs are
themselves anonymous host memory, so (a)'s floor is ~1x dataset of
device buffers.  On real TPU hardware those live in HBM — the host
anon peak of the streaming build is one device's slab + the int32
index lists (~1/n_devices of the dataset + 4 bytes/point).

Usage: python scripts/streammem_probe.py N [DIM] [EPS] [MODE]
  MODE: stream | inram | both (default) — full fits; or
        build — LAYOUT ONLY (streaming vs host build + device_put,
        no kernels), which isolates the build-memory story at sizes
        where a CPU-mesh fit would take hours
"""

import json
import os
import sys
import tempfile
import threading
import time

_N_DEV = int(os.environ.get("PYPARDIS_PROBE_DEVICES", "8"))
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={_N_DEV}"
    ).strip()

import numpy as np  # noqa: E402
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", _N_DEV)

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
from benchdata import ari_vs_truth, make_blob_data  # noqa: E402


def rss_anon_gb():
    for line in open("/proc/self/status"):
        if line.startswith("RssAnon"):
            return int(line.split()[1]) / 1e6
    return 0.0


class AnonSampler:
    def __init__(self, period=0.05):
        self.peak = 0.0
        self._stop = threading.Event()
        self._t = threading.Thread(target=self._run, args=(period,),
                                   daemon=True)

    def _run(self, period):
        while not self._stop.is_set():
            self.peak = max(self.peak, rss_anon_gb())
            time.sleep(period)

    def __enter__(self):
        self._t.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._t.join()
        self.peak = max(self.peak, rss_anon_gb())


def main():
    n = int(sys.argv[1])
    dim = int(sys.argv[2]) if len(sys.argv) > 2 else 16
    eps = float(sys.argv[3]) if len(sys.argv) > 3 else 2.4
    mode = sys.argv[4] if len(sys.argv) > 4 else "both"

    from pypardis_tpu.parallel import default_mesh, sharded_dbscan
    from pypardis_tpu.partition import KDPartitioner

    mesh = default_mesh(min(_N_DEV, jax.device_count()))
    out = {
        "n": n, "dim": dim, "eps": eps,
        "mesh_devices": mesh.devices.size,
        "dataset_gb": round(n * dim * 4 / 1e9, 3),
    }

    X, truth = make_blob_data(n, dim)
    with tempfile.NamedTemporaryFile(dir="/var/tmp", suffix=".f32") as f:
        mm = np.memmap(f.name, dtype=np.float32, mode="w+",
                       shape=X.shape)
        chunk = 1 << 20
        for s in range(0, n, chunk):
            mm[s:min(s + chunk, n)] = X[s:min(s + chunk, n)]
        mm.flush()
        if mode == "build":
            import jax as _jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            from pypardis_tpu.parallel.sharded import (
                build_owned_shards,
                build_owned_shards_streaming,
            )

            del X
            ro = np.memmap(f.name, dtype=np.float32, mode="r",
                           shape=(n, dim))
            part = KDPartitioner(ro, max_partitions=mesh.devices.size)
            base = rss_anon_gb()
            with AnonSampler() as samp:
                arrays, _lo, _hi, _lab, stats = (
                    build_owned_shards_streaming(
                        ro, part, eps, 1024, mesh
                    )
                )
                _jax.block_until_ready(arrays)
            out.update(
                stream_peak_anon_gb=round(samp.peak, 3),
                stream_build_anon_gb=round(samp.peak - base, 3),
                stream_pad_waste=round(stats.get("pad_waste", -1), 4),
            )
            del arrays
            X2, _ = make_blob_data(n, dim)
            part2 = KDPartitioner(X2, max_partitions=mesh.devices.size)
            sharding = NamedSharding(mesh, P(mesh.axis_names[0]))
            base = rss_anon_gb()
            with AnonSampler() as samp:
                arrs, _lo2, _hi2, _lab2, _st = build_owned_shards(
                    X2, part2, eps, mesh.devices.size, 1024
                )
                dev = tuple(
                    _jax.device_put(a, sharding) for a in arrs
                )
                _jax.block_until_ready(dev)
            out.update(
                inram_peak_anon_gb=round(samp.peak, 3),
                inram_build_anon_gb=round(samp.peak - base, 3),
            )
            print(json.dumps(out), flush=True)
            return
        if mode in ("stream", "both"):
            del X  # the streaming run must not lean on an in-RAM copy
            ro = np.memmap(f.name, dtype=np.float32, mode="r",
                           shape=(n, dim))
            part = KDPartitioner(ro, max_partitions=mesh.devices.size)
            base = rss_anon_gb()
            with AnonSampler() as samp:
                labels, core, stats = sharded_dbscan(
                    ro, part, eps=eps, min_samples=10, block=1024,
                    mesh=mesh, halo="ring",
                )
            out.update(
                stream_peak_anon_gb=round(samp.peak, 3),
                stream_base_anon_gb=round(base, 3),
                stream_build_anon_gb=round(samp.peak - base, 3),
                stream_input=stats.get("input"),
                stream_pad_waste=round(stats.get("pad_waste", -1), 4),
                ari_vs_truth=round(ari_vs_truth(labels, truth), 4),
            )
            del ro, part, labels, core
        if mode in ("inram", "both"):
            X2, _ = make_blob_data(n, dim)
            part = KDPartitioner(X2, max_partitions=mesh.devices.size)
            base = rss_anon_gb()
            with AnonSampler() as samp:
                sharded_dbscan(
                    X2, part, eps=eps, min_samples=10, block=1024,
                    mesh=mesh, halo="host",
                )
            out.update(
                inram_peak_anon_gb=round(samp.peak, 3),
                inram_base_anon_gb=round(base, 3),
                inram_build_anon_gb=round(samp.peak - base, 3),
            )

    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
