"""Anonymous-memory probe for the streaming (memmap) shard build.

Compares peak ANONYMOUS host memory (RssAnon, sampled) of

* (a) the streaming ring fit of a DISK-BACKED memmap
  (``build_owned_shards_streaming``: per-device slab assembly), vs
* (b) the ordinary in-RAM host-halo fit of the same data,

on the 8-device CPU mesh.  RssAnon (not VmHWM) is the honest metric:
memmap pages are file-backed and evictable, and with free RAM the
kernel keeps them resident, which would inflate a VmHWM reading with
memory that never pressures the host.

Caveat stated in the artifact: on the CPU mesh the "device" slabs are
themselves anonymous host memory, so (a)'s floor is ~1x dataset of
device buffers.  On real TPU hardware those live in HBM — the host
anon peak of the streaming build is one device's slab + the int32
index lists (~1/n_devices of the dataset + 4 bytes/point).

Usage: python scripts/streammem_probe.py N [DIM] [EPS] [MODE]
  MODE: stream | inram | both (default) — full fits; or
        build — LAYOUT ONLY (streaming vs host build + device_put,
        no kernels), which isolates the build-memory story at sizes
        where a CPU-mesh fit would take hours; or
        gm_stream — the GLOBAL-MORTON build-memory story (ISSUE 10):
        the streaming external sample-sort + per-shard slab assembly
        of a disk-backed memmap vs the in-RAM morton_range_split +
        full slab fill, HOST BUILD ONLY on both sides (no device
        placement: on the CPU mesh "device" buffers are themselves
        host anon — the same caveat as above — so including them
        would measure the backend, not the build).  The acceptance
        gauge: stream_build peak anon < STREAMMEM_GATE (default
        0.25) x dataset bytes; exceeding the gate exits nonzero.
"""

import json
import os
import sys
import tempfile
import threading
import time

_N_DEV = int(os.environ.get("PYPARDIS_PROBE_DEVICES", "8"))
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={_N_DEV}"
    ).strip()

import numpy as np  # noqa: E402
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
if "jax_num_cpu_devices" in jax.config._value_holders:
    jax.config.update("jax_num_cpu_devices", _N_DEV)

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
from benchdata import ari_vs_truth, make_blob_data  # noqa: E402


def rss_anon_gb():
    for line in open("/proc/self/status"):
        if line.startswith("RssAnon"):
            return int(line.split()[1]) / 1e6
    return 0.0


class AnonSampler:
    def __init__(self, period=0.05):
        self.peak = 0.0
        self._stop = threading.Event()
        self._t = threading.Thread(target=self._run, args=(period,),
                                   daemon=True)

    def _run(self, period):
        while not self._stop.is_set():
            self.peak = max(self.peak, rss_anon_gb())
            time.sleep(period)

    def __enter__(self):
        self._t.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._t.join()
        self.peak = max(self.peak, rss_anon_gb())


def main():
    n = int(sys.argv[1])
    dim = int(sys.argv[2]) if len(sys.argv) > 2 else 16
    eps = float(sys.argv[3]) if len(sys.argv) > 3 else 2.4
    mode = sys.argv[4] if len(sys.argv) > 4 else "both"

    from pypardis_tpu.parallel import default_mesh, sharded_dbscan
    from pypardis_tpu.partition import KDPartitioner

    mesh = default_mesh(min(_N_DEV, jax.device_count()))
    out = {
        "n": n, "dim": dim, "eps": eps,
        "mesh_devices": mesh.devices.size,
        "dataset_gb": round(n * dim * 4 / 1e9, 3),
    }

    X, truth = make_blob_data(n, dim)
    with tempfile.NamedTemporaryFile(dir="/var/tmp", suffix=".f32") as f:
        mm = np.memmap(f.name, dtype=np.float32, mode="w+",
                       shape=X.shape)
        chunk = 1 << 20
        for s in range(0, n, chunk):
            mm[s:min(s + chunk, n)] = X[s:min(s + chunk, n)]
        mm.flush()
        if mode == "gm_stream":
            from pypardis_tpu.parallel.global_morton import (
                _plan_targets,
                _stream_range_plan,
            )
            from pypardis_tpu.partition import (
                morton_range_split_streaming,
            )
            from pypardis_tpu.utils import round_up

            block = 1024
            ndev = mesh.devices.size
            del X
            ro = np.memmap(f.name, dtype=np.float32, mode="r",
                           shape=(n, dim))
            base = rss_anon_gb()
            with AnonSampler() as samp:
                split = morton_range_split_streaming(
                    ro, ndev, eps=eps, block=block
                )
                try:
                    plans, plens = [], []
                    for s in range(ndev):
                        plan, plen, _lo, _hi = _stream_range_plan(
                            split, s, block, eps
                        )
                        plans.append(plan)
                        plens.append(plen)
                    cap = round_up(max(plens + [1]), block)
                    # The HOST side of the real streaming build: spill
                    # pieces are read + target-mapped and then ship
                    # straight into the device-resident slab
                    # (build_morton_shards_streaming assembles on
                    # device via .at[].set) — the host never allocates
                    # a cap-sized buffer.  Device placement is
                    # excluded here for the same reason as the `build`
                    # mode above: on the CPU mesh "device" slabs are
                    # themselves host anon; on real hardware they are
                    # HBM.
                    for s in range(ndev):
                        for off, ids, rows in split.iter_range_rows(
                            s, chunk=1 << 19
                        ):
                            tgt = _plan_targets(plans[s], off, len(ids))
                            del tgt, ids, rows
                    stream_stats = dict(split.stats)
                finally:
                    split.close()
            stream_delta = samp.peak - base
            out.update(
                gm_stream_peak_anon_gb=round(samp.peak, 3),
                gm_stream_build_anon_gb=round(stream_delta, 3),
                gm_stream_buckets=stream_stats["stream_buckets"],
                gm_stream_max_bucket_rows=stream_stats[
                    "stream_max_bucket_rows"
                ],
                gm_owned_cap=int(cap),
            )
            del ro
            # In-RAM comparison: the full morton_range_split (f32 copy
            # + full permutation) + all-shard slab fill, host side of
            # build_morton_shards.
            from pypardis_tpu.parallel.global_morton import (
                _gm_segment_layout,
            )
            from pypardis_tpu.parallel.sharded import _recentre_rows
            from pypardis_tpu.partition import morton_range_split

            X2, _ = make_blob_data(n, dim)
            base = rss_anon_gb()
            with AnonSampler() as samp:
                order, starts, center = morton_range_split(
                    X2, ndev, eps=eps, block=block
                )
                shard_rows = []
                for s in range(ndev):
                    idx = order[int(starts[s]):int(starts[s + 1])]
                    rows = _recentre_rows(X2, idx, center)
                    target, plen = _gm_segment_layout(rows, block, eps)
                    shard_rows.append((idx, rows, target, plen))
                cap2 = round_up(
                    max([p for *_, p in shard_rows] + [1]), block
                )
                owned = np.zeros((ndev, cap2, dim), np.float32)
                omsk = np.zeros((ndev, cap2), bool)
                ogid = np.full((ndev, cap2), n, np.int32)
                for s, (idx, rows, target, _p) in enumerate(shard_rows):
                    if len(idx):
                        owned[s, target] = rows
                        omsk[s, target] = True
                        ogid[s, target] = idx
            inram_delta = samp.peak - base
            dataset_gb = out["dataset_gb"]
            gate = float(os.environ.get("STREAMMEM_GATE", 0.25))
            out.update(
                gm_inram_peak_anon_gb=round(samp.peak, 3),
                gm_inram_build_anon_gb=round(inram_delta, 3),
                gm_stream_vs_dataset=round(
                    stream_delta / max(dataset_gb, 1e-9), 4
                ),
                gm_inram_vs_dataset=round(
                    inram_delta / max(dataset_gb, 1e-9), 4
                ),
                gm_gate=gate,
            )
            print(json.dumps(out), flush=True)
            if stream_delta > gate * dataset_gb:
                print(
                    f"streammem_probe FAILED: gm_stream build anon "
                    f"{stream_delta:.3f}GB exceeds {gate} x dataset "
                    f"({dataset_gb}GB)", file=sys.stderr,
                )
                sys.exit(1)
            return
        if mode == "build":
            import jax as _jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            from pypardis_tpu.parallel.sharded import (
                build_owned_shards,
                build_owned_shards_streaming,
            )

            del X
            ro = np.memmap(f.name, dtype=np.float32, mode="r",
                           shape=(n, dim))
            part = KDPartitioner(ro, max_partitions=mesh.devices.size)
            base = rss_anon_gb()
            with AnonSampler() as samp:
                arrays, _lo, _hi, _lab, stats = (
                    build_owned_shards_streaming(
                        ro, part, eps, 1024, mesh
                    )
                )
                _jax.block_until_ready(arrays)
            out.update(
                stream_peak_anon_gb=round(samp.peak, 3),
                stream_build_anon_gb=round(samp.peak - base, 3),
                stream_pad_waste=round(stats.get("pad_waste", -1), 4),
            )
            del arrays
            X2, _ = make_blob_data(n, dim)
            part2 = KDPartitioner(X2, max_partitions=mesh.devices.size)
            sharding = NamedSharding(mesh, P(mesh.axis_names[0]))
            base = rss_anon_gb()
            with AnonSampler() as samp:
                arrs, _lo2, _hi2, _lab2, _st = build_owned_shards(
                    X2, part2, eps, mesh.devices.size, 1024
                )
                dev = tuple(
                    _jax.device_put(a, sharding) for a in arrs
                )
                _jax.block_until_ready(dev)
            out.update(
                inram_peak_anon_gb=round(samp.peak, 3),
                inram_build_anon_gb=round(samp.peak - base, 3),
            )
            print(json.dumps(out), flush=True)
            return
        if mode in ("stream", "both"):
            del X  # the streaming run must not lean on an in-RAM copy
            ro = np.memmap(f.name, dtype=np.float32, mode="r",
                           shape=(n, dim))
            part = KDPartitioner(ro, max_partitions=mesh.devices.size)
            base = rss_anon_gb()
            with AnonSampler() as samp:
                labels, core, stats = sharded_dbscan(
                    ro, part, eps=eps, min_samples=10, block=1024,
                    mesh=mesh, halo="ring",
                )
            out.update(
                stream_peak_anon_gb=round(samp.peak, 3),
                stream_base_anon_gb=round(base, 3),
                stream_build_anon_gb=round(samp.peak - base, 3),
                stream_input=stats.get("input"),
                stream_pad_waste=round(stats.get("pad_waste", -1), 4),
                ari_vs_truth=round(ari_vs_truth(labels, truth), 4),
            )
            del ro, part, labels, core
        if mode in ("inram", "both"):
            X2, _ = make_blob_data(n, dim)
            part = KDPartitioner(X2, max_partitions=mesh.devices.size)
            base = rss_anon_gb()
            with AnonSampler() as samp:
                sharded_dbscan(
                    X2, part, eps=eps, min_samples=10, block=1024,
                    mesh=mesh, halo="host",
                )
            out.update(
                inram_peak_anon_gb=round(samp.peak, 3),
                inram_base_anon_gb=round(base, 3),
                inram_build_anon_gb=round(samp.peak - base, 3),
            )

    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
