#!/usr/bin/env python3
"""graftlint CLI — the repo's AST-level invariant gate (`make lint`).

Checks every source file in ``pypardis_tpu/``, ``scripts/``,
``bench.py`` and ``benchdata.py`` against the named invariant rules
(R1 tracer constants, R2 device_put aliasing, R3 trace-time env reads,
R4 env-var registry + README table, R5 seal_f32 discipline, R6
fault-site/magic-width hygiene, R7 unused imports).  Exit 1 on any
non-baselined error finding.

Usage::

    python scripts/graftlint.py                # full repo
    python scripts/graftlint.py path.py ...    # just these files
    python scripts/graftlint.py --envdocs      # README env table
    python scripts/graftlint.py --list-rules
    python scripts/graftlint.py --rules env-registry,fault-site
    python scripts/graftlint.py --write-baseline   # grandfather now

The analysis package is stdlib-only; to keep this CLI sub-second we
load ``pypardis_tpu.analysis`` through a stub parent package so
``pypardis_tpu/__init__.py`` (which imports jax and configures the
compile cache) never runs.  In-process consumers (tests) import
``pypardis_tpu.analysis`` normally instead.
"""

import argparse
import importlib
import importlib.machinery
import os
import sys

_ROOT = os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))
)


def _load_analysis():
    if "pypardis_tpu" not in sys.modules:
        spec = importlib.machinery.ModuleSpec(
            "pypardis_tpu", None, is_package=True
        )
        stub = importlib.util.module_from_spec(spec)
        stub.__path__ = [os.path.join(_ROOT, "pypardis_tpu")]
        sys.modules["pypardis_tpu"] = stub
    return importlib.import_module("pypardis_tpu.analysis")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*",
                    help="restrict to these files (default: the "
                         "enforced fileset)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule names to run")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--envdocs", action="store_true",
                    help="print the README env-var table and exit")
    ap.add_argument("--baseline",
                    default=os.path.join(
                        _ROOT, "scripts", "graftlint_baseline.json"
                    ))
    ap.add_argument("--write-baseline", action="store_true",
                    help="grandfather every current finding into the "
                         "baseline file")
    ap.add_argument("--verbose", "-v", action="store_true")
    args = ap.parse_args(argv)

    analysis = _load_analysis()
    envmodel = importlib.import_module(
        "pypardis_tpu.analysis.envmodel"
    )
    report = importlib.import_module("pypardis_tpu.analysis.report")
    baseline_mod = importlib.import_module(
        "pypardis_tpu.analysis.baseline"
    )

    if args.envdocs:
        sys.stdout.write(
            envmodel.parse_env_registry(_ROOT).render_markdown()
        )
        return 0
    if args.list_rules:
        print(report.render_rules())
        return 0

    rules = (
        [r.strip() for r in args.rules.split(",") if r.strip()]
        if args.rules else None
    )
    paths = [os.path.abspath(p) for p in args.paths] or None
    result = analysis.run_lint(
        _ROOT, paths=paths, rules=rules,
        baseline_path=args.baseline,
    )
    if args.write_baseline:
        baseline_mod.write(args.baseline, result.raw_pairs)
        print(
            f"graftlint: wrote {len(result.raw_pairs)} baseline "
            f"entries to {args.baseline}"
        )
        return 0
    print(report.render(result, verbose=args.verbose))
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
