#!/usr/bin/env python
"""CI probe for the zero-duplication global-Morton distributed mode.

Runs the SAME geometry through the owner-computes KD-halo mode and
``mode="global_morton"`` on the 8-device CPU mesh (cold + warm fits),
asserts label byte-parity (and, on the structured manifold row, label
parity against the fused single-device engine plus ARI >= 0.99 against
the generating assignment), and emits ONE bench-style JSON row:

* ``metric="global_morton_probe"``, ``value`` = warm global-Morton
  throughput (pts/s), ``telemetry`` = the global-Morton fit's
  ``run_report@1`` — ``scripts/check_bench_json.py`` validates the row
  and FAILS CI when ``sharding.halo_exchange != "morton_ring"`` or
  ``duplicated_work_factor != 1.0`` (a silent fallback to the KD halo
  path cannot pass) or when ``boundary_tile_bytes`` is not below the
  legacy ``halo_bytes`` on the same geometry;
* top-level comparison fields: ``legacy_halo_bytes``,
  ``boundary_tile_bytes``, ``speedup_vs_oc`` (warm OC wall / warm GM
  wall), ``fixpoint_rounds``, and the ``manifold`` block (structured
  low-rank data: ARI + live-pair/pad-waste stats next to the isotropic
  row).

Geometry via env: GM_N (default 20000), GM_DIM (16), GM_EPS (2.4),
GM_BLOCK (256 — the fastest kernel tile for BOTH modes on the
single-core CI mesh, where wall tracks total work and finer tiles
waste less of each live pair; hardware meshes want the MXU-width
1024), GM_MP (16 KD partitions — the r5 halo-tax setup).  The
acceptance-scale run is ``GM_N=200000 make global-morton-probe``.
"""

import json
import os
import sys
import time

_N_DEV = int(os.environ.get("PYPARDIS_PROBE_DEVICES", "8"))
if os.environ.get("PYPARDIS_PROBE_PLATFORM") != "native":
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={_N_DEV}"
        ).strip()

import numpy as np  # noqa: E402
import jax  # noqa: E402

if os.environ.get("PYPARDIS_PROBE_PLATFORM") != "native":
    jax.config.update("jax_platforms", "cpu")
    if "jax_num_cpu_devices" in jax.config._value_holders:
        jax.config.update("jax_num_cpu_devices", _N_DEV)

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
from benchdata import (  # noqa: E402
    ari_vs_truth, make_blob_data, make_manifold_data,
)


def _fit_twice(model, X):
    t0 = time.perf_counter()
    model.fit(X)
    cold = time.perf_counter() - t0
    labels_cold = model.labels_.copy()
    t0 = time.perf_counter()
    model.fit(X)
    warm = time.perf_counter() - t0
    assert np.array_equal(labels_cold, model.labels_), (
        "warm refit changed labels"
    )
    return cold, warm


def main() -> None:
    from pypardis_tpu import DBSCAN
    from pypardis_tpu.parallel import default_mesh

    n = int(os.environ.get("GM_N", 20000))
    dim = int(os.environ.get("GM_DIM", 16))
    eps = float(os.environ.get("GM_EPS", 2.4))
    block = int(os.environ.get("GM_BLOCK", 256))
    mp = int(os.environ.get("GM_MP", 16))
    min_samples = 10
    n_dev = min(_N_DEV, jax.device_count())
    mesh = default_mesh(n_dev)

    X, truth = make_blob_data(n, dim, n_centers=64, std=0.4)

    kw = dict(eps=eps, min_samples=min_samples, block=block, mesh=mesh)
    oc = DBSCAN(max_partitions=mp, **kw)
    oc_cold, oc_warm = _fit_twice(oc, X)
    gm = DBSCAN(mode="global_morton", **kw)
    gm_cold, gm_warm = _fit_twice(gm, X)

    if not np.array_equal(oc.labels_, gm.labels_):
        print(
            "global_morton probe FAILED: labels diverge from the "
            "owner-computes KD mode", file=sys.stderr,
        )
        sys.exit(1)

    # Structured low-rank manifold data (VERDICT r5 Next #10): fused
    # single-device engine vs the new mode, ARI pinned.  The fused
    # path numbers clusters by Morton-first core point; canonicalize
    # to the distributed family's min-core-gid numbering so the byte
    # comparison means "identical clustering".
    from pypardis_tpu.ops.labels import densify_labels
    from pypardis_tpu.parallel.sharded import _canonicalize_roots

    mn = min(n, int(os.environ.get("GM_MANIFOLD_N", 8000)))
    Xm, tm = make_manifold_data(mn, dim, latent_dim=3)
    fused = DBSCAN(eps=0.8, min_samples=min_samples, block=block,
                   mesh=default_mesh(1))
    fused.fit(Xm)
    fused_canon = densify_labels(_canonicalize_roots(
        np.asarray(fused.labels_), np.asarray(fused.core_sample_mask_)
    ))
    gmm = DBSCAN(eps=0.8, min_samples=min_samples, block=block,
                 mesh=mesh, mode="global_morton")
    gmm.fit(Xm)
    ari_gm = ari_vs_truth(gmm.labels_, tm)
    ari_fused = ari_vs_truth(fused.labels_, tm)
    if not np.array_equal(fused_canon, gmm.labels_):
        print(
            "global_morton probe FAILED: manifold labels diverge from "
            "the fused engine", file=sys.stderr,
        )
        sys.exit(1)
    if ari_gm < 0.99:
        print(
            f"global_morton probe FAILED: manifold ari_vs_truth "
            f"{ari_gm} < 0.99", file=sys.stderr,
        )
        sys.exit(1)

    report = gm.report()
    sh = report["sharding"]
    oc_sh = oc.report()["sharding"]
    row = {
        "metric": "global_morton_probe",
        "value": round(n / gm_warm, 1),
        "unit": "pts/s",
        "n": n,
        "dim": dim,
        "eps": eps,
        "mesh_devices": n_dev,
        "cold_fit_s": round(gm_cold, 3),
        "warm_fit_s": round(gm_warm, 3),
        "oc_cold_fit_s": round(oc_cold, 3),
        "oc_warm_fit_s": round(oc_warm, 3),
        "speedup_vs_oc": round(oc_warm / gm_warm, 3),
        "duplicated_work_factor": sh["duplicated_work_factor"],
        "oc_duplicated_work_factor": oc_sh["duplicated_work_factor"],
        "boundary_tile_bytes": sh["boundary_tile_bytes"],
        "legacy_halo_bytes": oc_sh["halo_bytes"],
        "fixpoint_rounds": sh.get("fixpoint_rounds", 0),
        "ring_rounds": sh.get("ring_rounds", 0),
        "ari_vs_truth": round(ari_vs_truth(gm.labels_, truth), 4),
        "manifold": {
            "n": mn,
            "latent_dim": 3,
            "ari_gm": round(ari_gm, 4),
            "ari_fused": round(ari_fused, 4),
            "labels_match_fused": True,
            "live_pairs": gmm.report()["compute"]["live_pairs"],
            "pad_waste": gmm.report()["sharding"]["pad_waste"],
        },
        "telemetry": report,
    }
    print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
