#!/usr/bin/env python
"""Kill-a-fit-mid-run flight-recorder smoke (``make flight-check``).

Proves the crash-safety contract end to end, the way the north-star
run will actually need it: a child process fit-loops with the flight
recorder enabled; the parent SIGKILLs it the moment the on-disk JSONL
shows an in-flight span (opened, not yet closed — i.e. the kill lands
*inside* device work, with no atexit/finally able to run); then the
parent, from the file alone, asserts

* every surviving line parses (a truncated final line is tolerated),
* the opened-but-unclosed span is visible (the death site),
* ``obs.replay`` reconstructs a Chrome trace and a partial report,
* no terminal ``fin`` record exists (the run really was killed).

Geometry via ``FLIGHT_N`` (default 40000 x 8-D on the faked 8-device
CPU mesh — a few seconds per fit, so the kill window is wide).
"""

import json
import os
import signal
import subprocess
import sys
import tempfile
import time


def _force_cpu_mesh() -> None:
    # Same discipline as tests/conftest.py: the deployment image's
    # sitecustomize may pre-import jax pinned to another platform, so
    # env vars alone can be too late — override via jax.config too.
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    if "jax_num_cpu_devices" in jax.config._value_holders:
        jax.config.update("jax_num_cpu_devices", 8)


def child(path: str) -> None:
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    _force_cpu_mesh()
    import numpy as np

    from pypardis_tpu import DBSCAN

    n = int(os.environ.get("FLIGHT_N", 40000))
    rng = np.random.default_rng(0)
    X = rng.normal(size=(n, 8)).astype(np.float32) * 3.0
    # Fit forever: the parent kills us mid-fit.  Flight appends to one
    # file, so records accumulate across iterations and the parent's
    # open-span poll converges on whichever fit the kill interrupts.
    while True:
        DBSCAN(
            eps=0.5, min_samples=5, block=256, flight=path
        ).fit(X)


def _kill_window(path: str) -> bool:
    """True when the child is inside driver/device work right now
    (more span opens than closes among the parseable lines) and the
    file already carries enough records for a meaningful post-mortem."""
    opens = closes = records = 0
    try:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                try:
                    r = json.loads(line)
                except json.JSONDecodeError:
                    continue
                records += 1
                if r.get("k") == "so":
                    opens += 1
                elif r.get("k") == "sc":
                    closes += 1
    except OSError:
        return False
    return opens > closes and records >= 20


def check(msg: str, ok: bool) -> None:
    status = "ok" if ok else "FAILED"
    print(f"flight-check: {msg}: {status}")
    if not ok:
        sys.exit(1)


def main() -> None:
    if len(sys.argv) > 2 and sys.argv[1] == "--child":
        child(sys.argv[2])
        return
    tmp = tempfile.mkdtemp(prefix="flight_check_")
    path = os.path.join(tmp, "flight.jsonl")
    snap_path = os.path.join(tmp, "metrics_snapshot.jsonl")
    env = dict(os.environ)
    # ISSUE 16: the kill also lands with the live export plane attached
    # — the periodic JSONL snapshot stream must degrade exactly like
    # the flight file does (every line but at worst the last parses).
    env["PYPARDIS_METRICS_SNAPSHOT"] = snap_path
    env["PYPARDIS_METRICS_SNAPSHOT_S"] = "0.1"
    deadline = time.time() + float(os.environ.get("FLIGHT_TIMEOUT_S", 300))
    proc = None
    killed_mid_span = False
    for attempt in range(5):
        if os.path.exists(path):
            os.unlink(path)
        if os.path.exists(snap_path):
            os.unlink(snap_path)
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--child", path],
            env=env,
        )
        try:
            while time.time() < deadline:
                if proc.poll() is not None:
                    print(
                        f"flight-check: child exited rc={proc.returncode} "
                        f"before the kill", file=sys.stderr,
                    )
                    sys.exit(1)
                if _kill_window(path):
                    break
                time.sleep(0.05)
        finally:
            proc.send_signal(signal.SIGKILL)
            proc.wait()
        # Post-kill ground truth: the file may have gained records
        # between our poll and the kill — re-check from the replay.
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        _force_cpu_mesh()
        from pypardis_tpu import obs

        rep = obs.replay(path)
        if rep.open_spans and not rep.complete:
            killed_mid_span = True
            break
        print(
            f"flight-check: attempt {attempt}: kill landed between spans "
            f"(open={len(rep.open_spans)}, complete={rep.complete}); "
            f"retrying", file=sys.stderr,
        )
    check("SIGKILL landed inside an open span", killed_mid_span)

    from pypardis_tpu import obs

    rep = obs.replay(path)
    check(f"JSONL parses ({rep.records} records, "
          f"{rep.bad_lines} truncated/bad)", rep.records > 0)
    check(
        f"no terminal record (really killed; open spans: "
        f"{[s['name'] for s in rep.open_spans]})",
        not rep.complete and len(rep.open_spans) > 0,
    )
    trace_path = os.path.join(tmp, "post_mortem_trace.json")
    rep.export_chrome_trace(trace_path)
    doc = json.load(open(trace_path))
    names = [e.get("name") for e in doc.get("traceEvents", [])
             if e.get("ph") == "X"]
    unclosed = [
        e["name"] for e in doc.get("traceEvents", [])
        if e.get("ph") == "X" and e.get("args", {}).get("unclosed")
    ]
    check(
        f"Chrome trace reconstructs ({len(names)} spans, death site(s) "
        f"{unclosed})", len(names) > 0 and len(unclosed) > 0,
    )
    report = rep.report()
    check(
        "partial report builds (partial=True, resources finite)",
        report.get("partial") is True
        and isinstance(
            report["resources"]["peak_host_rss_bytes"], int
        ),
    )
    snap_ok = snap_bad = 0
    with open(snap_path, "r", encoding="utf-8") as f:
        lines = [ln for ln in f.read().split("\n") if ln.strip()]
    for i, line in enumerate(lines):
        try:
            r = json.loads(line)
            if r.get("schema") == "pypardis_tpu/metrics_snapshot@1":
                snap_ok += 1
            else:
                snap_bad += 1
        except json.JSONDecodeError:
            # SIGKILL may truncate the line being written — but ONLY
            # that one: every earlier line was flushed whole.
            if i == len(lines) - 1:
                continue
            snap_bad += 1
    check(
        f"metrics-snapshot stream survives the kill ({snap_ok} lines, "
        f"{snap_bad} bad)", snap_ok >= 1 and snap_bad == 0,
    )
    print(rep.summary())
    print(f"flight-check OK: post-mortem at {path}")


if __name__ == "__main__":
    main()
