"""North-star shard-size probe: one BIG single-chip fit on the real TPU.

BASELINE.md's north star is 100M x 16-D on a v5e-8 — 12.5M points per
chip as a mesh shard, but the reference's scaling claim is about the
dataset exceeding one worker, so this probe pins what ONE chip can
actually hold and sustain (round-3 review, Next #2: run >= 50M x 16-D
single-chip and pin the per-chip memory ceiling from a measured row,
not an extrapolation).

Prints ONE JSON line (scale_probe schema + HBM fields).  Run it in a
fresh process per size (axon session quirks) with the chip otherwise
idle.  The fit takes the host-stepped propagation path automatically
past PYPARDIS_STEP_THRESHOLD, keeping each device execution short.

Usage: PYTHONPATH=/root/repo:/root/.axon_site python scripts/northstar_probe.py N [DIM]
"""

import json
import sys
import time

import numpy as np


def make_data(n, dim, pts_per_center=6250, seed=0):
    rng = np.random.default_rng(seed)
    n_centers = max(32, n // pts_per_center)
    centers = rng.uniform(-10, 10, size=(n_centers, dim)).astype(np.float32)
    assign = rng.integers(0, n_centers, size=n)
    out = centers[assign]
    del assign
    chunk = 1 << 20
    for s in range(0, n, chunk):
        e = min(s + chunk, n)
        out[s:e] += rng.normal(scale=0.4, size=(e - s, dim)).astype(
            np.float32
        )
    return out


def hbm_stats():
    import jax

    try:
        stats = jax.devices()[0].memory_stats() or {}
        return {
            "hbm_in_use_gb": round(stats.get("bytes_in_use", 0) / 1e9, 2),
            "hbm_peak_gb": round(stats.get("peak_bytes_in_use", 0) / 1e9, 2),
            "hbm_limit_gb": round(stats.get("bytes_limit", 0) / 1e9, 2),
        }
    except Exception:  # noqa: BLE001 — stats are best-effort diagnostics
        return {}


def main():
    n = int(sys.argv[1])
    dim = int(sys.argv[2]) if len(sys.argv) > 2 else 16
    eps = 2.4
    X = make_data(n, dim)

    from pypardis_tpu import DBSCAN

    t0 = time.perf_counter()
    model = DBSCAN(eps=eps, min_samples=10, block=2048)
    labels = model.fit_predict(X)
    t_cold = time.perf_counter() - t0

    t0 = time.perf_counter()
    labels = model.fit_predict(X)
    t_warm = time.perf_counter() - t0

    phases = {
        f"phase_{k}": round(v, 2)
        for k, v in model.metrics_.items()
        if isinstance(v, float) and k.endswith("_s")
    }
    print(
        json.dumps(
            {
                "n": n,
                "dim": dim,
                "eps": eps,
                "cold_s": round(t_cold, 2),
                "warm_s": round(t_warm, 2),
                "warm_pps": round(n / t_warm),
                "clusters": int(labels.max() + 1),
                "noise": int((labels == -1).sum()),
                **phases,
                **hbm_stats(),
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
