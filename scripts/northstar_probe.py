"""North-star shard-size probe: one BIG single-chip fit on the real TPU.

BASELINE.md's north star is 100M x 16-D on a v5e-8 — 12.5M points per
chip as a mesh shard, but the reference's scaling claim is about the
dataset exceeding one worker, so this probe pins what ONE chip can
actually hold and sustain (round-3 review, Next #2: run >= 50M x 16-D
single-chip and pin the per-chip memory ceiling from a measured row,
not an extrapolation).

Prints ONE JSON line (scale_probe schema + HBM fields).  Run it in a
fresh process per size (axon session quirks) with the chip otherwise
idle.  The fit takes the host-stepped propagation path automatically
past PYPARDIS_STEP_THRESHOLD, keeping each device execution short.

Usage: PYTHONPATH=/root/repo:/root/.axon_site python scripts/northstar_probe.py N [DIM]
"""

import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
from benchdata import ari_vs_truth, make_blob_data  # noqa: E402


def hbm_stats():
    import jax

    try:
        stats = jax.devices()[0].memory_stats() or {}
        return {
            "hbm_in_use_gb": round(stats.get("bytes_in_use", 0) / 1e9, 2),
            "hbm_peak_gb": round(stats.get("peak_bytes_in_use", 0) / 1e9, 2),
            "hbm_limit_gb": round(stats.get("bytes_limit", 0) / 1e9, 2),
        }
    except Exception:  # noqa: BLE001 — stats are best-effort diagnostics
        return {}


def main():
    n = int(sys.argv[1])
    dim = int(sys.argv[2]) if len(sys.argv) > 2 else 16
    eps = 2.4
    X, truth = make_blob_data(n, dim)

    from pypardis_tpu import DBSCAN

    t0 = time.perf_counter()
    model = DBSCAN(eps=eps, min_samples=10, block=2048)
    labels = model.fit_predict(X)
    t_cold = time.perf_counter() - t0

    t0 = time.perf_counter()
    labels = model.fit_predict(X)
    t_warm = time.perf_counter() - t0

    phases = {
        f"phase_{k}": round(v, 2)
        for k, v in model.metrics_.items()
        if isinstance(v, float) and k.endswith("_s")
    }
    print(
        json.dumps(
            {
                "n": n,
                "dim": dim,
                "eps": eps,
                "cold_s": round(t_cold, 2),
                "warm_s": round(t_warm, 2),
                "warm_pps": round(n / t_warm),
                "ari_vs_truth": round(ari_vs_truth(labels, truth), 4),
                "clusters": int(labels.max() + 1),
                "noise": int((labels == -1).sum()),
                **phases,
                **hbm_stats(),
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
