"""Timing breakdown: counts pass vs one minlab pass vs full pipeline,
plus a precision-mode sweep of the counts pass (default / mixed / high /
highest) — the kernel-level view of what ``precision="mixed"`` buys:
one bf16 pass + band-restricted rescores vs bf16_3x vs native f32.
Mixed rows also print the measured band stats (in-band pairs, rescored
tile visits)."""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from scale_probe import make_data


def t(fn, *args, reps=3, **kw):
    r = fn(*args, **kw)
    jax.block_until_ready(r)
    t0 = time.perf_counter()
    for _ in range(reps):
        r = fn(*args, **kw)
        jax.block_until_ready(r)
    return (time.perf_counter() - t0) / reps


def main():
    n = int(sys.argv[1])
    d = int(sys.argv[2]) if len(sys.argv) > 2 else 16
    eps = 2.4
    block = int(sys.argv[3]) if len(sys.argv) > 3 else 2048
    X = make_data(n, d)
    from pypardis_tpu.ops.pallas_kernels import (
        _pallas_block,
        min_neighbor_label_pallas,
        neighbor_counts_pallas,
    )
    from pypardis_tpu.partition import spatial_order
    from pypardis_tpu.utils import round_up

    t0 = time.perf_counter()
    X = X - X.mean(axis=0)
    order = spatial_order(X)
    X = X[order]
    print(f"host sort: {time.perf_counter() - t0:.2f}s")
    cap = round_up(n, block)
    pts = np.zeros((cap, d), np.float32)
    pts[:n] = X
    pts = jnp.asarray(pts)
    mask = jnp.arange(cap) < n
    print(f"pallas block: {_pallas_block(block, cap, d, 'high')}")

    dt_c = t(neighbor_counts_pallas, pts, eps, mask, block=block)
    print(f"counts pass: {dt_c:.2f}s")

    # Precision-mode sweep: one counts pass per mode on the identical
    # input.  "mixed" reports its band stats so the rescore economy
    # (fast-peak bulk vs band-restricted bf16_3x tiles) is visible per
    # geometry, not just per bench row.
    for mode in ("default", "mixed", "high", "highest"):
        def run_mode(mode=mode):
            out = neighbor_counts_pallas(
                pts, eps, mask, block=block, precision=mode
            )
            return out[0] if mode == "mixed" else out

        dt_m_sweep = t(run_mode)
        note = ""
        if mode == "mixed":
            _, bstats = neighbor_counts_pallas(
                pts, eps, mask, block=block, precision="mixed"
            )
            bp, rt = [int(v) for v in np.asarray(bstats)]
            note = f"  band_pairs={bp} rescored_tiles={rt}"
        print(f"counts[precision={mode:7s}]: {dt_m_sweep:.2f}s{note}")
    counts = neighbor_counts_pallas(pts, eps, mask, block=block)
    core = (counts >= 10) & mask
    labels = jnp.where(core, jnp.arange(cap, dtype=jnp.int32), 2**31 - 1)
    dt_m = t(
        min_neighbor_label_pallas, pts, labels, eps, core,
        block=block, row_mask=mask,
    )
    print(f"minlab pass: {dt_m:.2f}s")

    from pypardis_tpu.ops.labels import dbscan_fixed_size

    dt_f = t(
        lambda *a, **k: dbscan_fixed_size(*a, **k)[:2], pts, eps, 10, mask,
        block=block, backend="pallas", reps=1,
    )
    print(f"full dbscan_fixed_size: {dt_f:.2f}s")
    est_rounds = (dt_f - dt_c) / dt_m
    print(f"=> est minlab passes: {est_rounds:.1f}")


if __name__ == "__main__":
    main()
