"""Kernel-level timing breakdowns.

Two sections:

* **Dispatch sweep** (every backend, wired into ``make bench-smoke``
  via ``make kernel-probe``): the XLA counts pass under DENSE dispatch
  (scan all T^2 column tiles, ``lax.cond``-skip the pruned ones) vs
  the COMPACTED pair-list dispatch (one scan step per live tile pair)
  on the same Morton-sorted input — per-mode seconds, the measured
  ``live_pair_fraction``, and a byte-parity assert.  Emits one JSON
  row (``kernel_dispatch_sweep``) and exits nonzero on parity/sanity
  failure, so the dense-dispatch win is a measured CI row, not a
  claim.

* **Pallas section** (TPU only): counts / minlab / full-fit timings
  plus the precision-mode sweep (default / mixed / high / highest)
  with mixed band stats — the kernel-level view of what
  ``precision="mixed"`` buys.
"""
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import jax
import jax.numpy as jnp
import numpy as np


def t(fn, *args, reps=3, **kw):
    r = fn(*args, **kw)
    jax.block_until_ready(r)
    t0 = time.perf_counter()
    for _ in range(reps):
        r = fn(*args, **kw)
        jax.block_until_ready(r)
    return (time.perf_counter() - t0) / reps


def _sorted_padded(n, d, block):
    from benchdata import make_blob_data

    from pypardis_tpu.partition import spatial_order
    from pypardis_tpu.utils import round_up

    X, _truth = make_blob_data(n, d)
    X = X.astype(np.float32)
    t0 = time.perf_counter()
    X = X - X.mean(axis=0)
    order = spatial_order(X)
    X = X[order]
    print(f"host sort: {time.perf_counter() - t0:.2f}s")
    cap = round_up(n, block)
    pts = np.zeros((cap, d), np.float32)
    pts[:n] = X
    return jnp.asarray(pts), jnp.arange(cap) < n, cap


def dispatch_sweep(n, d, block, eps):
    """Dense vs compacted XLA dispatch on the identical input; returns
    the JSON row dict after asserting byte parity and a sane
    live_pair_fraction."""
    from pypardis_tpu.ops.distances import neighbor_counts, xla_pair_list

    pts, mask, cap = _sorted_padded(n, d, block)
    nt = cap // block
    pairs, stats = xla_pair_list(pts, mask, eps, block, "nd")
    total, budget = [int(v) for v in np.asarray(stats)]
    if total > budget:
        print(f"pair budget overflow ({total} > {budget}); "
              f"re-extracting exact", file=sys.stderr)
        pairs, stats = xla_pair_list(
            pts, mask, eps, block, "nd", budget=total
        )
        total, budget = [int(v) for v in np.asarray(stats)]
    frac = total / float(nt * nt)
    dt_dense = t(neighbor_counts, pts, eps, mask, block=block)
    dt_pair = t(
        lambda: neighbor_counts(pts, eps, mask, block=block, pairs=pairs)
    )
    c_dense = np.asarray(neighbor_counts(pts, eps, mask, block=block))
    c_pair = np.asarray(
        neighbor_counts(pts, eps, mask, block=block, pairs=pairs)
    )
    assert np.array_equal(c_dense, c_pair), (
        "dense vs compacted dispatch count mismatch"
    )
    assert 0.0 <= frac <= 1.0 and frac == frac, frac
    speedup = dt_dense / dt_pair if dt_pair > 0 else float("inf")
    print(f"counts[dispatch=dense ]: {dt_dense:.3f}s")
    print(
        f"counts[dispatch=pair  ]: {dt_pair:.3f}s  "
        f"live_pair_fraction={frac:.4f} ({total}/{nt * nt} tile pairs) "
        f"speedup={speedup:.2f}x"
    )
    return {
        "metric": "kernel_dispatch_sweep",
        "value": round(dt_pair, 4),
        "unit": "s",
        "n": n,
        "dim": d,
        "block": block,
        "eps": eps,
        "dense_s": round(dt_dense, 4),
        "pair_s": round(dt_pair, 4),
        "live_pairs": total,
        "tile_pairs_total": nt * nt,
        "live_pair_fraction": round(frac, 6),
        "speedup_vs_dense": round(speedup, 3),
        "parity": "byte-identical",
    }


def pallas_section(n, d, block, eps):
    from pypardis_tpu.ops.pallas_kernels import (
        _pallas_block,
        min_neighbor_label_pallas,
        neighbor_counts_pallas,
    )

    pts, mask, cap = _sorted_padded(n, d, block)
    print(f"pallas block: {_pallas_block(block, cap, d, 'high')}")

    dt_c = t(neighbor_counts_pallas, pts, eps, mask, block=block)
    print(f"counts pass: {dt_c:.2f}s")

    # Precision-mode sweep: one counts pass per mode on the identical
    # input.  "mixed" reports its band stats so the rescore economy
    # (fast-peak bulk vs band-restricted bf16_3x tiles) is visible per
    # geometry, not just per bench row.
    for mode in ("default", "mixed", "high", "highest"):
        def run_mode(mode=mode):
            out = neighbor_counts_pallas(
                pts, eps, mask, block=block, precision=mode
            )
            return out[0] if mode == "mixed" else out

        dt_m_sweep = t(run_mode)
        note = ""
        if mode == "mixed":
            _, bstats = neighbor_counts_pallas(
                pts, eps, mask, block=block, precision="mixed"
            )
            bp, rt = [int(v) for v in np.asarray(bstats)]
            note = f"  band_pairs={bp} rescored_tiles={rt}"
        print(f"counts[precision={mode:7s}]: {dt_m_sweep:.2f}s{note}")
    counts = neighbor_counts_pallas(pts, eps, mask, block=block)
    core = (counts >= 10) & mask
    labels = jnp.where(core, jnp.arange(cap, dtype=jnp.int32), 2**31 - 1)
    dt_m = t(
        min_neighbor_label_pallas, pts, labels, eps, core,
        block=block, row_mask=mask,
    )
    print(f"minlab pass: {dt_m:.2f}s")

    from pypardis_tpu.ops.labels import dbscan_fixed_size

    dt_f = t(
        lambda *a, **k: dbscan_fixed_size(*a, **k)[:2], pts, eps, 10, mask,
        block=block, backend="pallas", reps=1,
    )
    print(f"full dbscan_fixed_size: {dt_f:.2f}s")
    est_rounds = (dt_f - dt_c) / dt_m
    print(f"=> est minlab passes: {est_rounds:.1f}")


def main():
    n = int(sys.argv[1])
    d = int(sys.argv[2]) if len(sys.argv) > 2 else 16
    eps = 2.4
    block = int(sys.argv[3]) if len(sys.argv) > 3 else 2048
    row = dispatch_sweep(n, d, block, eps)
    print(json.dumps(row), flush=True)
    if jax.default_backend() == "tpu":
        pallas_section(n, d, block, eps)


if __name__ == "__main__":
    main()
