#!/usr/bin/env python
"""CI probe for the amortized hyperparameter sweep (ISSUE 13).

One distance pass, k clusterings: warms both paths (jit compiles are a
fixed process cost, not the amortization under test — the persistent
XLA cache eats them across processes anyway), then measures a k-config
``DBSCAN.sweep`` against k independent ``fit()`` runs at the same
configs on the 8-device CPU mesh, cold staging on both sides.  Gates,
enforced here (nonzero exit) and re-checked by
``scripts/check_bench_json.py``:

* ``distance_passes == 1`` for the k=8 eps sweep;
* sweep wall <= 0.5x the sum of the k independent fits
  (``sweep_amortization >= 2``);
* per-config labels BYTE-IDENTICAL to the solo fits (and ARI == 1.0).

Emits ONE bench-style JSON row: ``metric="sweep_amortization"``,
``value`` = measured (sum of solo walls) / sweep wall, ``schema`` =
``pypardis_tpu/sweep@1``, the per-config parity/ARI table, the
``sweep`` telemetry block (graph pairs/bytes, per-config relabel
seconds, the honest ``owner_computes``/``dispatch`` fields), and the
full ``run_report@1`` telemetry of the sweep.  Geometry via env:
SWEEP_N (default 16000), SWEEP_DIM (8), SWEEP_K (8 eps points),
SWEEP_BLOCK (128).  Clusters sit on well-separated centers so no
border point touches two clusters — the regime where the engine
family's cross-route byte parity is exact (see DBSCAN.sweep's
docstring for the shared multi-cluster-border caveat).
"""

import json
import os
import sys
import time

_N_DEV = int(os.environ.get("PYPARDIS_PROBE_DEVICES", "8"))
if os.environ.get("PYPARDIS_PROBE_PLATFORM") != "native":
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={_N_DEV}"
        ).strip()

import numpy as np  # noqa: E402
import jax  # noqa: E402

if os.environ.get("PYPARDIS_PROBE_PLATFORM") != "native":
    jax.config.update("jax_platforms", "cpu")
    if "jax_num_cpu_devices" in jax.config._value_holders:
        jax.config.update("jax_num_cpu_devices", _N_DEV)

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _geometry(n: int, dim: int):
    """Gaussian clusters on well-separated centers (pairwise center
    distance >= ~4 vs std 0.15): the eps ladder sits far above the
    intra-cluster fragmentation scale and far below cluster contact,
    so no border point ever touches two clusters and byte parity is
    unambiguous at every config (verified for the pinned seed)."""
    rng = np.random.default_rng(11)
    k = 8
    centers = rng.normal(size=(k, dim))
    centers *= 4.0 / np.linalg.norm(centers, axis=1, keepdims=True)
    # push pairs apart deterministically: scale each center's radius
    centers = centers * (1.0 + np.arange(k)[:, None] * 0.5)
    per = n // k
    X = np.concatenate(
        [
            c + rng.normal(scale=0.15, size=(per, dim))
            for c in centers
        ]
        + [rng.normal(scale=0.15, size=(n - per * k, dim)) + centers[0]]
    )
    return X.astype(np.float64)


def main() -> None:
    from pypardis_tpu import DBSCAN
    from pypardis_tpu.parallel import default_mesh, staging
    from sklearn.metrics import adjusted_rand_score

    n = int(os.environ.get("SWEEP_N", 16000))
    dim = int(os.environ.get("SWEEP_DIM", 4))
    k_cfg = int(os.environ.get("SWEEP_K", 8))
    block = int(os.environ.get("SWEEP_BLOCK", 128))
    eps_list = [round(0.14 + 0.005 * i, 3) for i in range(k_cfg)]
    ms = 5
    X = _geometry(n, dim)
    mesh = default_mesh(min(_N_DEV, jax.device_count()))
    kw = dict(min_samples=ms, block=block, mesh=mesh)

    # -- warm-up (compiles) -------------------------------------------
    DBSCAN(eps=eps_list[-1], **kw).sweep(X, eps_list)
    DBSCAN(eps=eps_list[0], **kw).fit(X)

    # -- measured sweep (cold staging, warm jit; best of 2 — the same
    # best-of-N discipline every BENCH row uses) ----------------------
    sweep_samples = []
    for _rep in range(2):
        staging.clear()
        model = DBSCAN(eps=eps_list[-1], **kw)
        t0 = time.perf_counter()
        res = model.sweep(X, eps_list)
        sweep_samples.append(time.perf_counter() - t0)
    sweep_wall = min(sweep_samples)

    # -- measured solo fits -------------------------------------------
    staging.clear()
    solo_walls = []
    solo_labels = {}
    for e in eps_list:
        m = DBSCAN(eps=e, **kw)
        t0 = time.perf_counter()
        m.fit(X)
        solo_walls.append(time.perf_counter() - t0)
        solo_labels[e] = np.asarray(m.labels_)
    solo_wall = float(sum(solo_walls))

    # -- gates --------------------------------------------------------
    sweep_tel = model.report()
    assert sweep_tel["sweep"]["distance_passes"] == 1, (
        f"sweep ran {sweep_tel['sweep']['distance_passes']} distance "
        f"passes, expected 1"
    )
    per_config = []
    for e in eps_list:
        match = bool(np.array_equal(res.labels(e), solo_labels[e]))
        ari = float(
            adjusted_rand_score(solo_labels[e], res.labels(e))
        )
        assert match, f"labels differ from solo fit at eps={e}"
        assert ari == 1.0, f"ARI {ari} != 1.0 at eps={e}"
        per_config.append(
            {
                "eps": e,
                "min_samples": ms,
                "labels_match": match,
                "ari": ari,
                "relabel_s": next(
                    c["relabel_s"] for c in res.per_config
                    if c["eps"] == e
                ),
                "n_clusters": int(res.labels(e).max()) + 1,
            }
        )
    amortization = solo_wall / max(sweep_wall, 1e-9)
    assert amortization >= 2.0, (
        f"sweep wall {sweep_wall:.2f}s not <= 0.5x the {solo_wall:.2f}s "
        f"sum of {k_cfg} solo fits (amortization {amortization:.2f})"
    )

    row = {
        "metric": "sweep_amortization",
        "value": round(amortization, 3),
        "unit": "x",
        "schema": "pypardis_tpu/sweep@1",
        "n": n,
        "dim": dim,
        "k": k_cfg,
        "distance_passes": 1,
        "graph_pairs": int(sweep_tel["sweep"]["graph_pairs"]),
        "graph_bytes": int(sweep_tel["sweep"]["graph_bytes"]),
        "sweep_wall_s": round(sweep_wall, 4),
        "solo_wall_s": round(solo_wall, 4),
        "samples_s": [round(s, 4) for s in sweep_samples],
        "per_config": per_config,
        "sweep": dict(sweep_tel["sweep"]),
        "telemetry": sweep_tel,
    }
    print(json.dumps(row))


if __name__ == "__main__":
    main()
