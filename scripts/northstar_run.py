"""North-star end-to-end driver (ISSUE 10 / ROADMAP item 1).

Composes the pieces the 100M x 16-D target needs — chunked dataset
generation straight into a DISK-BACKED memmap (never an in-RAM copy),
the streaming global-Morton build (external sample-sort), chained
(1-device) or distributed (mesh) execution, host-spillable merge, and
``PYPARDIS_CKPT`` checkpoint-resume — and emits ONE schema'd
``pypardis_tpu/northstar@1`` JSON row decomposing the fit into
build / exchange / compute / merge seconds plus the sampled peak
RssAnon, turning the extrapolated <60s claim into a measured
trajectory.

Knobs (env):
  NS_N            points (default: 100_000_000 on TPU, else 2_000_000 —
                  the largest CPU-feasible smoke, committed as
                  NORTHSTAR_smoke.json)
  NS_DIM          dimensions (16)
  NS_EPS          eps (2.4)         NS_MIN_SAMPLES  min_samples (10)
  NS_BLOCK        kernel block (1024)
  NS_MERGE        auto|device|host (auto)
  NS_CHAIN        ranges for the chained 1-device route (default:
                  ceil(dataset / 512MB), min 8 — only used on a
                  1-device mesh)
  NS_DEVICES      mesh size cap (default: all visible devices)
  NS_DATA         reuse an existing f32 memmap instead of generating
  NS_ARI          compute ARI vs the generating truth (default 1 when
                  the dataset is generated here)
  NS_CKPT         checkpoint path (default: <workdir>/northstar.ckpt)

Usage: python scripts/northstar_run.py [| python scripts/check_bench_json.py]
"""

import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def rss_anon_gb():
    for line in open("/proc/self/status"):
        if line.startswith("RssAnon"):
            return int(line.split()[1]) / 1e6
    return 0.0


class AnonSampler:
    """Peak anonymous-RSS sampler (RssAnon, not VmHWM: memmap pages are
    file-backed and evictable — they never pressure the host)."""

    def __init__(self, period=0.05):
        self.peak = 0.0
        self._stop = threading.Event()
        self._t = threading.Thread(target=self._run, args=(period,),
                                   daemon=True)

    def _run(self, period):
        while not self._stop.is_set():
            self.peak = max(self.peak, rss_anon_gb())
            time.sleep(period)

    def __enter__(self):
        self._t.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._t.join()
        self.peak = max(self.peak, rss_anon_gb())


def gen_blob_memmap(path, truth_path, n, dim, seed=0, spread=10.0,
                    std=0.4, pts_per_center=6250, chunk=1 << 20):
    """Chunked blob generation straight to disk — the driver never
    holds the dataset (or an f64 temp) in RAM.  Same family as
    benchdata.make_blob_data (uniform centers, one std); truth rides
    in a second int32 memmap so ARI stays free at any N."""
    import numpy as np

    rng = np.random.default_rng(seed)
    n_centers = max(32, n // pts_per_center)
    centers = rng.uniform(-spread, spread, size=(n_centers, dim)).astype(
        np.float32
    )
    X = np.memmap(path, dtype=np.float32, mode="w+", shape=(n, dim))
    T = np.memmap(truth_path, dtype=np.int32, mode="w+", shape=(n,))
    for s in range(0, n, chunk):
        e = min(s + chunk, n)
        assign = rng.integers(0, n_centers, size=e - s, dtype=np.int32)
        X[s:e] = centers[assign] + rng.normal(
            0.0, std, size=(e - s, dim)
        ).astype(np.float32)
        T[s:e] = assign
    X.flush()
    T.flush()
    del X, T


def main():
    import jax
    import numpy as np

    from pypardis_tpu import DBSCAN
    from pypardis_tpu.parallel import default_mesh

    on_tpu = jax.default_backend() == "tpu"
    n = int(os.environ.get(
        "NS_N", 100_000_000 if on_tpu else 2_000_000
    ))
    dim = int(os.environ.get("NS_DIM", 16))
    eps = float(os.environ.get("NS_EPS", 2.4))
    min_samples = int(os.environ.get("NS_MIN_SAMPLES", 10))
    block = int(os.environ.get("NS_BLOCK", 1024))
    merge = os.environ.get("NS_MERGE", "auto")
    n_dev = min(
        int(os.environ.get("NS_DEVICES", jax.device_count())),
        jax.device_count(),
    )
    mesh = default_mesh(n_dev)

    workdir = tempfile.mkdtemp(prefix="northstar_")
    data_path = os.environ.get("NS_DATA")
    truth_path = None
    t_gen = 0.0
    if data_path is None:
        data_path = os.path.join(workdir, "points.f32")
        truth_path = os.path.join(workdir, "truth.i32")
        t0 = time.perf_counter()
        gen_blob_memmap(data_path, truth_path, n, dim)
        t_gen = time.perf_counter() - t0
    ro = np.memmap(data_path, dtype=np.float32, mode="r",
                   shape=(n, dim))

    chain = 0
    if n_dev == 1:
        chain = int(os.environ.get(
            "NS_CHAIN",
            max(8, -(-n * dim * 4 // (512 * 1024 * 1024))),
        ))
        os.environ["PYPARDIS_GM_CHAIN"] = str(chain)
    ckpt = os.environ.get(
        "NS_CKPT", os.path.join(workdir, "northstar.ckpt")
    )

    model = DBSCAN(
        eps=eps, min_samples=min_samples, block=block, mesh=mesh,
        mode="global_morton", merge=merge,
    )
    t0 = time.perf_counter()
    with AnonSampler() as samp:
        model.train(ro, resume=ckpt)
    wall = time.perf_counter() - t0

    rep = model.report()
    phases = rep["phases"]
    js = model._jobstate
    resume_used = bool(
        js is not None
        and (js.restored_partitions > 0 or js.restored_rounds > 0)
    )
    row = {
        "metric": "northstar_e2e",
        "value": round(wall, 3),
        "unit": "s",
        "schema": "pypardis_tpu/northstar@1",
        "n": n,
        "dim": dim,
        "eps": eps,
        "min_samples": min_samples,
        "block": block,
        "mode": "gm_chained" if chain else "gm_mesh",
        "mesh_devices": int(n_dev),
        "chain_ranges": int(chain),
        "backend": str(jax.default_backend()),
        "build_s": float(phases.get("gm_build", 0.0)),
        "exchange_s": float(phases.get("gm_exchange", 0.0)),
        "compute_s": float(phases.get("gm_execute", 0.0)),
        "merge_s": float(phases.get("gm_merge", 0.0)),
        "gen_s": round(t_gen, 3),
        # One-rep sample array: what bench_diff range-compares against
        # the committed NORTHSTAR_*.json at the same geometry.
        "samples_s": [round(wall, 3)],
        "pts_per_sec": round(n / wall, 1),
        "rss_anon_peak_gb": round(samp.peak, 3),
        "dataset_gb": round(n * dim * 4 / 1e9, 3),
        "resume_used": resume_used,
        "telemetry": rep,
    }
    if truth_path is not None and os.environ.get("NS_ARI", "1") == "1":
        from benchdata import ari_vs_truth

        truth = np.memmap(truth_path, dtype=np.int32, mode="r",
                          shape=(n,))
        row["ari_vs_truth"] = round(
            ari_vs_truth(model.labels_, np.asarray(truth)), 4
        )
    print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
