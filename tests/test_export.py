"""Live export plane (pypardis_tpu.obs.export, ISSUE 16).

Unit: the bounded windowed histogram (log bucketing, sliding-window
percentiles with lifetime fallback, merge/clone, snapshot round-trip,
fixed footprint), the registry's histogram integration, and the
OpenMetrics text rendering.  Integration: ``attach_exporters`` on a
live recorder — a mid-span HTTP scrape, the periodic JSONL snapshot
stream, and exact sink-seam restoration on close (including an
attached flight recorder riding the same seam).
"""

import json
import math
import time
import urllib.request

import pytest

from pypardis_tpu.obs import RunRecorder
from pypardis_tpu.obs.export import (
    HIST_SCHEMA,
    SNAPSHOT_SCHEMA,
    Histogram,
    LiveState,
    attach_exporters,
    render_openmetrics,
)
from pypardis_tpu.obs.flight import FlightRecorder
from pypardis_tpu.obs.registry import MetricsRegistry


# ---------------------------------------------------------------------------
# Histogram
# ---------------------------------------------------------------------------


def test_histogram_percentiles_order_and_range():
    h = Histogram(window_s=60)
    for i in range(1, 101):
        h.observe(float(i))  # 1..100 ms
    p50, p99 = h.percentile(50), h.percentile(99)
    assert 0 < p50 <= p99
    # Log-bucket resolution is ~33%/bucket: generous envelopes.
    assert 30 <= p50 <= 80
    assert 70 <= p99 <= 140
    assert h.count == 100
    assert h.max_ms == 100.0
    assert h.sum_ms == pytest.approx(5050.0)


def test_histogram_window_expiry_and_lifetime_fallback():
    h = Histogram(window_s=8)  # chunk_s = 1
    old = time.monotonic() - 1000.0
    for _ in range(10):
        h.observe(1.0, now_s=old)
    # All observations expired from the window: windowed percentile
    # falls back to lifetime instead of answering 0.
    assert h.window_count == 0
    assert h.percentile(50) == pytest.approx(1.0, rel=0.4)
    snap = h.snapshot()
    assert snap["window_count"] == 0 and snap["count"] == 10
    # Fresh observations are two decades up: the window sees ONLY them.
    for _ in range(5):
        h.observe(100.0)
    assert h.window_count == 5
    assert h.percentile(50) == pytest.approx(100.0, rel=0.4)
    # Lifetime still dominated by the old 1ms points.
    assert h.percentile(50, window=False) == pytest.approx(1.0, rel=0.4)


def test_histogram_footprint_never_grows():
    h = Histogram()
    before = h.nbytes
    for i in range(50_000):
        h.observe((i % 977) / 7.0)
    assert h.nbytes == before  # the memory-bound contract
    assert h.count == 50_000


def test_histogram_nan_and_overflow():
    h = Histogram()
    h.observe(float("nan"))
    assert h.count == 0
    h.observe(1e9)  # 1e6 s: beyond the last edge -> overflow bucket
    snap = h.snapshot()
    assert snap["overflow"] == 1 and snap["buckets"] == []
    # Overflow percentile clamps to the max seen, not an edge.
    assert h.percentile(99) == pytest.approx(1e9)


def test_histogram_merge_clone_snapshot_roundtrip():
    a, b = Histogram(window_s=60), Histogram(window_s=60)
    for v in (0.5, 2.0, 8.0):
        a.observe(v)
    for v in (32.0, 128.0):
        b.observe(v)
    c = a.clone()
    c.merge_from(b)
    assert c.count == 5 and a.count == 3  # clone is independent
    assert c.max_ms == 128.0
    assert c.sum_ms == pytest.approx(a.sum_ms + b.sum_ms)

    snap = c.snapshot()
    assert snap["schema"] == HIST_SCHEMA and snap["unit"] == "ms"
    assert sum(cnt for _, cnt in snap["buckets"]) == 5
    les = [le for le, _ in snap["buckets"]]
    assert les == sorted(les)
    back = Histogram.from_snapshot(json.loads(json.dumps(snap)))
    assert back.snapshot()["buckets"] == snap["buckets"]
    assert back.count == 5
    assert back.sum_ms == pytest.approx(snap["sum_ms"])
    assert back.percentile(50, window=False) == pytest.approx(
        c.percentile(50, window=False)
    )


# ---------------------------------------------------------------------------
# registry integration
# ---------------------------------------------------------------------------


def test_registry_observe_feeds_histogram():
    reg = MetricsRegistry()
    reg.observe("phase.cluster", 0.004)  # seconds -> 4ms
    reg.observe("phase.cluster", 0.016)
    d = reg.as_dict()
    snap = d["hists"]["phase.cluster"]
    assert snap["count"] == 2
    assert 3.0 <= snap["p50_ms"] <= 20.0
    # timings and hists stay in lockstep
    assert d["timings"]["phase.cluster"]["count"] == 2


def test_registry_observe_ms_and_load_hist():
    reg = MetricsRegistry()
    reg.observe_ms("serving.latency_ms", 2.5)
    assert reg.hist("serving.latency_ms").count == 1
    donor = Histogram()
    donor.observe(40.0)
    reg.load_hist("serving.latency_ms", donor.snapshot())
    assert reg.hist("serving.latency_ms").count == 2

    other = MetricsRegistry()
    other.observe_ms("serving.latency_ms", 9.0)
    reg.merge(other)
    assert reg.hist("serving.latency_ms").count == 3


# ---------------------------------------------------------------------------
# OpenMetrics rendering
# ---------------------------------------------------------------------------


def test_render_openmetrics_families():
    reg = MetricsRegistry()
    reg.inc("events.compile")
    reg.set("metrics.http_port", 9200)
    reg.observe_ms("serving.latency_ms", 3.0)
    state = LiveState()
    state.span_open(1, "cluster", 0.0, 0, {})
    state.span_close(2, "gm.ring_round", 0.0, 0.012, {})
    state.heartbeat("gm.ring", 3, 7, 1.5)
    state.sample(rss=12345.0)
    body = render_openmetrics(reg.as_dict(), state)
    assert body.endswith("# EOF\n")
    assert "pypardis_events_compile_total 1" in body
    assert "pypardis_metrics_http_port 9200" in body
    assert 'pypardis_serving_latency_ms_bucket{le="' in body
    # Span closes feed LIVE histograms (the mid-fit scrape contract:
    # latency distributions exist before the profiling accumulator
    # observes anything at fit end).
    assert 'pypardis_span_gm_ring_round_bucket{le="' in body
    assert 'pypardis_open_span{name="cluster",depth="0"}' in body
    assert 'pypardis_heartbeat_done{stage="gm.ring"} 3' in body
    assert 'pypardis_heartbeat_total{stage="gm.ring"} 7' in body
    assert "pypardis_resource_rss 12345" in body
    assert "pypardis_run_finished 0" in body
    # bucket series are cumulative and finite
    for ln in body.splitlines():
        if "_bucket{" in ln:
            assert math.isfinite(float(ln.rsplit(" ", 1)[1]))


# ---------------------------------------------------------------------------
# attach_exporters
# ---------------------------------------------------------------------------


def test_attach_exporters_off_is_none(monkeypatch):
    monkeypatch.delenv("PYPARDIS_METRICS_PORT", raising=False)
    monkeypatch.delenv("PYPARDIS_METRICS_SNAPSHOT", raising=False)
    assert attach_exporters(RunRecorder()) is None
    assert attach_exporters(None) is None


def test_http_scrape_mid_span_and_seam_restore():
    rec = RunRecorder()
    stack = attach_exporters(rec, port=0)
    assert stack is not None and stack.http_port
    try:
        with rec.span("unit.scrape_phase"):
            rec.metrics.observe_ms("serving.latency_ms", 1.5)
            with urllib.request.urlopen(
                f"http://127.0.0.1:{stack.http_port}/metrics", timeout=5
            ) as resp:
                ctype = resp.headers.get("Content-Type", "")
                body = resp.read().decode("utf-8")
            assert "openmetrics-text" in ctype
            assert body.rstrip().endswith("# EOF")
            assert 'pypardis_open_span{name="unit.scrape_phase"' in body
            assert 'pypardis_serving_latency_ms_bucket{le="' in body
            with urllib.request.urlopen(
                f"http://127.0.0.1:{stack.http_port}/state.json",
                timeout=5,
            ) as resp:
                st = json.loads(resp.read())
            assert st["schema"] == SNAPSHOT_SCHEMA
            assert "unit.scrape_phase" in st["open_spans"]
        assert rec.metrics.gauge("metrics.http_port") == stack.http_port
    finally:
        stack.close()
    # seam restored exactly: no fanout left behind
    assert rec.flight is None
    assert rec.tracer.sink is None
    assert rec.metrics.sink is None


def test_snapshot_stream_lines_parse(tmp_path):
    rec = RunRecorder()
    path = tmp_path / "snap.jsonl"
    stack = attach_exporters(
        rec, snapshot_path=str(path), snapshot_interval_s=0.05
    )
    try:
        with rec.span("unit.snap_phase"):
            rec.metrics.observe_ms("serving.latency_ms", 2.0)
            time.sleep(0.18)
    finally:
        stack.close()
    lines = [
        json.loads(ln) for ln in path.read_text().splitlines() if ln
    ]
    assert len(lines) >= 2  # immediate first line + final line at close
    for r in lines:
        assert r["schema"] == SNAPSHOT_SCHEMA
        assert "span_hists" in r and "heartbeats" in r
    assert lines[-1]["hists"]["serving.latency_ms"]["count"] == 1
    # the span closed before the final line: its live hist is in there
    assert lines[-1]["span_hists"]["span.unit.snap_phase"]["count"] == 1


def test_exporters_tee_with_flight_recorder(tmp_path):
    rec = RunRecorder()
    fpath = tmp_path / "flight.jsonl"
    flight = FlightRecorder(str(fpath), flush_interval_s=0.0)
    rec.attach_flight(flight)
    stack = attach_exporters(rec, port=0)
    try:
        with rec.span("unit.tee_phase"):
            pass
    finally:
        stack.close()
    # the flight recorder rode the same seam and saw every record...
    kinds = [
        json.loads(ln)["k"]
        for ln in fpath.read_text().splitlines() if ln
    ]
    assert "so" in kinds and "sc" in kinds
    # ...and close() restored it as THE sink, not a leftover fanout
    assert rec.flight is flight
    assert rec.tracer.sink is flight
    assert rec.metrics.sink is flight
