"""Checkpoint-resumable fits (ISSUE 9 tentpole, prong 3).

Phase-boundary snapshots (``pypardis_tpu.utils.jobstate``) + the
``DBSCAN.train(resume=path)`` surface, plus the ladder-exhaustion
error-message satellites (the raises must name the env knob).

The resume contract under test: a fit interrupted mid-run (here via an
injected TERMINAL fault — the in-process stand-in for SIGKILL, which
``make fault-probe`` exercises for real with a subprocess kill)
resumes to labels BYTE-IDENTICAL to an uninterrupted fit, replaying
only the unfinished partitions/rounds.
"""

import numpy as np
import pytest
from sklearn.datasets import make_blobs

from pypardis_tpu import DBSCAN
from pypardis_tpu.parallel import default_mesh, sharded_dbscan, staging
from pypardis_tpu.partition import KDPartitioner
from pypardis_tpu.utils import faults
from pypardis_tpu.utils.jobstate import JobState, fit_meta


@pytest.fixture(autouse=True)
def _clean_slate():
    faults.clear()
    staging.clear()
    yield
    faults.clear()
    staging.clear()


@pytest.fixture()
def blob_data():
    X, _ = make_blobs(
        n_samples=4000, centers=10, n_features=3, cluster_std=0.3,
        random_state=5,
    )
    return X.astype(np.float32)


@pytest.fixture()
def chain_data():
    rng = np.random.default_rng(0)
    n = 3000
    X = np.stack(
        [np.arange(n) * 0.1, rng.normal(0, 0.05, n)], axis=1
    )
    return X.astype(np.float32)


KW = dict(eps=0.45, min_samples=5, block=64)


def test_chained_resume_byte_identical(blob_data, tmp_path):
    """Kill the chained route mid-loop (terminal injected error at
    partition 5), resume from the snapshot: only the unfinished
    partitions recompute and labels match the uninterrupted run."""
    part = KDPartitioner(blob_data, max_partitions=8)
    mesh1 = default_mesh(1)
    clean, clean_core, _ = sharded_dbscan(
        blob_data, part, mesh=mesh1, **KW
    )
    path = str(tmp_path / "chained.ckpt.npz")
    meta = fit_meta(blob_data, eps=KW["eps"],
                    min_samples=KW["min_samples"], metric="euclidean",
                    block=KW["block"], mode="kd")

    staging.clear()
    js = JobState.open(path, meta)
    with faults.plan("chained.partition:5=error"):
        with pytest.raises(faults.FaultInjected):
            sharded_dbscan(blob_data, part, mesh=mesh1, jobstate=js,
                           **KW)

    staging.clear()
    js2 = JobState.open(path, meta, resume=True)
    labels, core, _stats = sharded_dbscan(
        blob_data, part, mesh=mesh1, jobstate=js2, **KW
    )
    assert js2.restored_partitions == 4  # partitions 0-3 replayed
    np.testing.assert_array_equal(labels, clean)
    np.testing.assert_array_equal(core, clean_core)


def test_gm_resume_via_train(chain_data, tmp_path):
    """DBSCAN.train(resume=) on the global-Morton route: die inside
    fixpoint round 2, resume from the saved lab_map, labels
    byte-identical to the uninterrupted fit."""
    clean = DBSCAN(mode="global_morton", merge="device", **KW)
    clean.fit(chain_data)
    path = str(tmp_path / "gm.ckpt")

    staging.clear()
    with faults.plan("gm.fixpoint_round:2=error"):
        with pytest.raises(faults.FaultInjected):
            DBSCAN(mode="global_morton", merge="device", **KW).train(
                chain_data, resume=path
            )

    staging.clear()
    model = DBSCAN(mode="global_morton", merge="device", **KW)
    model.train(chain_data, resume=path)
    np.testing.assert_array_equal(model.labels_, clean.labels_)
    np.testing.assert_array_equal(
        model.core_sample_mask_, clean.core_sample_mask_
    )
    # the resume really replayed saved fixpoint state
    assert model._jobstate.restored_rounds >= 1
    assert model.report()["metrics"]["counters"].get(
        "events.jobstate_restore", 0
    ) >= 1


def test_resume_rejects_mismatched_fit(chain_data, blob_data, tmp_path):
    path = str(tmp_path / "mismatch.ckpt")
    with faults.plan("gm.fixpoint_round:1=error"):
        with pytest.raises(faults.FaultInjected):
            DBSCAN(mode="global_morton", merge="device", **KW).train(
                chain_data, resume=path
            )
    with pytest.raises(ValueError, match="different fit"):
        DBSCAN(mode="global_morton", merge="device", **KW).train(
            blob_data, resume=path
        )


def test_budget_mismatch_invalidates_snapshot(tmp_path):
    """Tables snapshotted under one pair budget are never served to a
    run using another — a ladder retry with a bigger budget must
    recompute, not consume tables built from a truncated pair list."""
    js = JobState(str(tmp_path / "b.npz"), {"schema": "x"})
    js.chained_note(0, np.zeros(8, np.int32), np.zeros(8, bool),
                    np.zeros(5, np.int64), budget=0)
    assert set(js.chained_restore(0)) == {0}
    assert js.chained_restore(4096) == {}
    js.chained_note(1, np.zeros(8, np.int32), np.zeros(8, bool),
                    np.zeros(5, np.int64), budget=4096)
    # the budget generation reset dropped the old entry
    assert set(js.chained_restore(4096)) == {1}
    assert js.chained_restore(0) == {}


def test_jobstate_atomic_roundtrip(tmp_path):
    path = str(tmp_path / "rt.npz")
    meta = {"schema": "pypardis_tpu/jobstate@1", "eps": 0.5}
    js = JobState.open(path, meta)
    js.gm_note(np.arange(17, dtype=np.int32), 3, budget=0)
    js.stepped_note(np.arange(32, dtype=np.int32), 2, budget=64)
    js.flush(force=True)
    js2 = JobState.open(path, meta, resume=True)
    lab, rounds = js2.gm_restore(0, 17)
    np.testing.assert_array_equal(lab, np.arange(17, dtype=np.int32))
    assert rounds == 3
    f, batches = js2.stepped_restore(64, 32)
    np.testing.assert_array_equal(f, np.arange(32, dtype=np.int32))
    assert batches == 2
    # shape / budget mismatches refuse
    assert js2.gm_restore(0, 18) is None
    assert js2.stepped_restore(0, 32) is None


# ---------------------------------------------------------------------------
# ladder-exhaustion messages name their knobs (satellite)
# ---------------------------------------------------------------------------


def test_pair_budget_exhaustion_names_knob():
    from pypardis_tpu.utils.budget import run_ladders

    def run_step(pb, _mr):
        # always overflows: total 50000 against whatever budget
        return None, np.asarray([[50000, 10, 1, 0, 0]]), True

    with pytest.raises(RuntimeError) as ei:
        run_ladders(run_step, ("t",), None, 8)
    msg = str(ei.value)
    assert "pair_budget=" in msg
    assert "PYPARDIS_PAIR_BUDGET" in msg


def test_pair_budget_env_knob(monkeypatch):
    from pypardis_tpu.utils.budget import run_ladders

    seen = []

    def run_step(pb, _mr):
        seen.append(pb)
        return "out", np.asarray([[100, 0, 1, 0, 0]]), True

    monkeypatch.setenv("PYPARDIS_PAIR_BUDGET", "8192")
    out, _ = run_ladders(run_step, ("t2",), None, 8)
    assert seen == [8192]


def test_btcap_exhaustion_names_knob(blob_data):
    from pypardis_tpu.parallel.global_morton import global_morton_dbscan

    with pytest.raises(RuntimeError) as ei:
        # eps large enough that every tile is a boundary tile: an
        # explicit btcap=1 must overflow and fail loudly
        global_morton_dbscan(
            blob_data, eps=5.0, min_samples=5, block=64, btcap=1,
        )
    msg = str(ei.value)
    assert "btcap" in msg
    assert "PYPARDIS_GM_BTCAP" in msg
