"""Live-update subsystem (ISSUE 8): incremental insert/delete on a
fitted model + in-place index refresh + sustained serving.

Correctness contracts:

* after any tested insert/delete sequence, labels are ARI == 1.0
  (label-permutation-equivalent) vs a FULL REFIT on the final point
  set — across fused, KD-sharded, and global-Morton fitted models, on
  geometries with guaranteed blob separation (the one DBSCAN ambiguity
  — a border point within eps of two clusters' cores — is excluded by
  construction, as documented in serve/live.py);
* ``predict`` stays bitwise exact (labels AND d2) against the
  brute-force oracle on the UPDATED index — the ``seal_f32`` contract
  holds through the in-place ``serve_index_delta`` refresh;
* one insert can bridge several clusters (the union-find stitch, not a
  single-min edge), one delete can split one.
"""

import numpy as np
import pytest
from sklearn.metrics import adjusted_rand_score

from benchdata import make_separated_blob_data
from pypardis_tpu import DBSCAN
from pypardis_tpu.parallel.mesh import default_mesh
from pypardis_tpu.serve import (
    LiveModel,
    QueryEngine,
    ReplicatedQueryEngine,
    sustained_load,
)

EPS, MS = 1.1, 6


def _fit(mode="fused", n=600, dim=3, seed=0):
    X, _truth, centers = make_separated_blob_data(
        n, dim, n_centers=5, std=0.35,
        min_sep=2 * EPS + 6 * 0.35 + 1.0, spread=10.0, seed=seed,
    )
    if mode == "fused":
        m = DBSCAN(eps=EPS, min_samples=MS, mesh=default_mesh(1),
                   block=128)
    elif mode == "kd":
        m = DBSCAN(eps=EPS, min_samples=MS, block=128)
    elif mode == "global_morton":
        m = DBSCAN(eps=EPS, min_samples=MS, block=128,
                   mode="global_morton")
    else:
        raise AssertionError(mode)
    return m.fit(X), X, centers


def _assert_refit_equivalent(live):
    refit = DBSCAN(
        eps=live.eps, min_samples=live.min_samples,
        mesh=default_mesh(1), block=128,
    ).fit(live.points()).labels_
    ari = adjusted_rand_score(refit, live.labels())
    assert ari == 1.0, f"ARI {ari} vs full refit"


def _assert_oracle_exact(live, Q):
    t = live.engine.submit(Q)
    live.engine.drain()
    olabs, od2 = live.index.oracle_predict(Q)
    np.testing.assert_array_equal(t.labels, olabs)
    np.testing.assert_array_equal(t.d2, od2)


def test_insert_fast_path_border_and_noise():
    m, X, centers = _fit()
    live = m.live(leaves=8)
    epoch0 = live.index.epoch
    # Far point: noise; near-blob non-core point: joins the blob's
    # cluster — neither flips anyone, so no re-cluster, no index delta.
    ids = m.insert(np.array([[40.0, 40.0, 40.0]]))
    assert live.labels()[-1] == -1
    near = centers[0] + np.array([0.0, 0.0, EPS * 0.9])
    ids2 = m.insert(near[None])
    lab = live._labels[ids2[0]]
    assert lab >= 0
    if live.stats["recluster_events"] == 0:
        assert live.index.epoch == epoch0
    assert live.stats["inserts"] == 2
    _assert_refit_equivalent(live)


def test_one_insert_bridges_three_clusters():
    """The bridging geometry: three arms whose tips surround a gap; a
    single core insert at the center merges all three — the PR 2
    lesson (one bridge links EVERY adjacent cluster, not a single-min
    edge) applied to the live path."""
    eps, ms = 0.9, 5
    arms = []
    for a in (0.0, 2 * np.pi / 3, 4 * np.pi / 3):
        r = np.arange(0.8, 3.01, 0.1)
        arms.append(np.stack([r * np.cos(a), r * np.sin(a)], axis=1))
    X = np.concatenate(arms)
    m = DBSCAN(eps=eps, min_samples=ms, mesh=default_mesh(1),
               block=64).fit(X)
    labs0 = np.asarray(m.labels_)
    assert len(np.unique(labs0[labs0 >= 0])) == 3
    live = m.live(leaves=4)
    ids = live.insert(np.zeros((1, 2)))
    assert live._core[ids[0]], "bridge point must itself become core"
    labs = live.labels()
    assert len(np.unique(labs[labs >= 0])) == 1
    refit = DBSCAN(eps=eps, min_samples=ms, mesh=default_mesh(1),
                   block=64).fit(live.points()).labels_
    assert adjusted_rand_score(refit, labs) == 1.0
    _assert_oracle_exact(live, np.concatenate([X, np.zeros((1, 2))]))


def test_delete_splits_cluster():
    """Deleting the bridge point of a bar-shaped cluster splits it —
    the affected-cluster re-cluster path (splits are cluster-local,
    never leaf-local)."""
    eps, ms = 0.6, 3
    line = np.stack(
        [np.arange(0.0, 8.01, 0.4), np.zeros(21)], axis=1
    )
    m = DBSCAN(eps=eps, min_samples=ms, mesh=default_mesh(1),
               block=64).fit(line)
    labs0 = np.asarray(m.labels_)
    assert len(np.unique(labs0[labs0 >= 0])) == 1
    live = m.live(leaves=4)
    mid = np.argmin(np.abs(line[:, 0] - 4.0))
    live.delete([int(mid)])
    labs = live.labels()
    assert len(np.unique(labs[labs >= 0])) == 2, "cluster must split"
    _assert_refit_equivalent(live)
    _assert_oracle_exact(live, live.points())


@pytest.mark.parametrize("mode", ["fused", "kd", "global_morton"])
def test_randomized_sequences_match_refit(mode):
    """Property sweep: seeded insert/delete sequences against models
    fitted by every route end ARI == 1.0 vs a full refit on the final
    point set, and predict stays bitwise oracle-exact throughout."""
    m, X, centers = _fit(mode=mode)
    live = m.live(leaves=8)
    rng = np.random.default_rng(17)
    dim = X.shape[1]
    for step in range(8):
        kind = step % 4
        if kind == 0:  # interior inserts (may flip borders to core)
            c = centers[step % len(centers)]
            live.insert(c + rng.normal(scale=0.3, size=(4, dim)))
        elif kind == 1:  # a brand-new clump: fresh cluster from thin air
            spot = np.full(dim, 20.0 + 3 * step)
            live.insert(spot + rng.normal(scale=0.2, size=(MS + 2, dim)))
        elif kind == 2:  # scattered noise
            live.insert(rng.uniform(-30, 30, size=(2, dim)))
        else:  # delete a handful, cores included
            alive = live.ids()
            take = rng.choice(alive, size=6, replace=False)
            live.delete(take)
    _assert_refit_equivalent(live)
    Q = np.concatenate([
        live.points()[:200],
        rng.uniform(-25, 25, size=(100, dim)),
    ])
    _assert_oracle_exact(live, Q)
    # Locality was measured along the way, and an update sequence that
    # re-clustered must have touched fewer tiles than exist.
    assert 0.0 <= live.stats["recluster_tile_fraction"] < 1.0


def test_index_delta_pad_absorption_then_overflow():
    """Pad slots absorb inserts (delta bytes << resident bytes, no new
    slab); an overflowing leaf rebuilds ALONE (other leaves' columns
    never re-ship); predict stays oracle-exact across both."""
    m, X, centers = _fit(n=800)
    live = m.live(leaves=8, block=32, qblock=32)
    idx = live.index
    assert idx.n_leaves > 1, "need a multi-leaf index for locality"
    slabs0 = idx.n_leaves
    resident = idx.stats["index_bytes"]
    epoch0, delta0 = idx.epoch, idx.delta_bytes

    # One interior insert: a pad-slot fill (or a single-leaf rebuild at
    # worst) — the delta must undercut the resident slab bytes.
    live.insert(centers[0] + np.full((1, X.shape[1]), 0.05))
    if idx.epoch > epoch0:
        assert 0 < idx.delta_bytes - delta0 < resident

    # Pour points into ONE region until its leaf overflows.
    rng = np.random.default_rng(3)
    before_cols = idx.coords.shape[1]
    live.insert(centers[1] + rng.normal(scale=0.3, size=(300, X.shape[1])))
    assert idx.coords.shape[1] > before_cols, "expected slab growth"
    grown = [l for l, s in idx.leaf_slabs.items() if len(s) > 1]
    assert grown, "an overflowing leaf must own appended slabs"
    assert idx.n_leaves > slabs0
    _assert_oracle_exact(live, np.concatenate([
        live.points()[:200], rng.uniform(-20, 20, size=(50, X.shape[1]))
    ]))
    _assert_refit_equivalent(live)


def test_delete_frees_slots_for_later_inserts():
    m, X, centers = _fit()
    live = m.live(leaves=8)
    idx = live.index
    core_ids = live.ids()[live.core_mask()]
    live.delete(core_ids[:10])
    free_after = int((idx.labels == np.iinfo(np.int32).max).sum())
    cols = idx.coords.shape[1]
    live.insert(centers[2] + np.random.default_rng(5).normal(
        scale=0.2, size=(5, X.shape[1])
    ))
    assert idx.coords.shape[1] == cols, "freed pad slots must absorb"
    assert int((idx.labels == np.iinfo(np.int32).max).sum()) < free_after
    _assert_refit_equivalent(live)


def test_stale_engine_raises_after_refit():
    """Satellite: a caller-held engine (or LiveModel) from before a
    refit raises a clear error instead of silently serving the old
    clustering; model.query_engine() hands out the rebuilt engine."""
    m, X, _centers = _fit()
    engine = m.query_engine()
    live = m.live()
    assert engine.predict(X[:4]) is not None  # fresh: works
    m.fit(X[: len(X) // 2])
    with pytest.raises(RuntimeError, match="refit"):
        engine.predict(X[:4])
    with pytest.raises(RuntimeError, match="refit"):
        engine.submit(X[:4])
    with pytest.raises(RuntimeError, match="refit"):
        live.insert(X[:1])
    # The model's own surface re-builds transparently.
    assert m.query_engine().predict(X[:4]) is not None
    assert len(m.live().insert(X[:1])) == 1


def test_live_checkpoint_roundtrip(tmp_path):
    """Satellite: save/load round-trips the MUTATED state — a
    restarted server answers byte-identically to the pre-restart one
    and keeps accepting writes."""
    m, X, centers = _fit(n=500)
    live = m.live(leaves=8, block=32, qblock=32)
    rng = np.random.default_rng(9)
    live.insert(centers[0] + rng.normal(scale=0.3, size=(40, X.shape[1])))
    live.delete(live.ids()[5:15])
    live.insert(rng.uniform(-20, 20, size=(3, X.shape[1])))
    Q = np.concatenate([
        live.points()[:150], rng.uniform(-15, 15, size=(80, X.shape[1]))
    ])
    t = live.engine.submit(Q)
    live.engine.drain()

    path = str(tmp_path / "live.npz")
    live.save(path)
    restored = LiveModel.load(path)
    assert restored.index.epoch == live.index.epoch
    np.testing.assert_array_equal(restored.index.coords, live.index.coords)
    np.testing.assert_array_equal(restored.index.labels, live.index.labels)
    t2 = restored.engine.submit(Q)
    restored.engine.drain()
    np.testing.assert_array_equal(t.labels, t2.labels)
    np.testing.assert_array_equal(t.d2, t2.d2)
    # The restored server keeps taking writes, still refit-equivalent.
    restored.insert(centers[1] + rng.normal(scale=0.2,
                                            size=(4, X.shape[1])))
    restored.delete(restored.ids()[:2])
    _assert_refit_equivalent(restored)

    # A PLAIN model checkpoint (no live state) still loads the old way.
    plain = str(tmp_path / "plain.npz")
    m2, _X2, _c = _fit(n=300, seed=4)
    m2.save(plain)
    with pytest.raises(ValueError, match="without live state"):
        LiveModel.load(plain)


def test_replicated_engine_parity_and_stats():
    m, X, _centers = _fit(n=500)
    live = m.live(leaves=8)
    rng = np.random.default_rng(2)
    Q = np.concatenate([
        X[:200], rng.uniform(-15, 15, size=(100, X.shape[1]))
    ])
    single = QueryEngine(live.index, backend="xla")
    rep = ReplicatedQueryEngine(live.index, backend="xla")
    t1 = single.submit(Q)
    single.drain()
    t2 = rep.submit(Q)
    rep.drain()
    np.testing.assert_array_equal(t1.labels, t2.labels)
    np.testing.assert_array_equal(t1.d2, t2.d2)
    olabs, od2 = live.index.oracle_predict(Q)
    np.testing.assert_array_equal(t2.labels, olabs)
    np.testing.assert_array_equal(t2.d2, od2)
    stats = rep.serving_stats()
    assert stats["replicated"] is True
    assert stats["replicated_devices"] == 8
    assert stats["per_device_index_bytes"] > 0
    # A live update re-broadcasts: parity must survive an epoch bump.
    live.insert(X[:1] + 0.01)
    t3 = rep.submit(Q)
    rep.drain()
    ol3, od3 = live.index.oracle_predict(Q)
    np.testing.assert_array_equal(t3.labels, ol3)
    np.testing.assert_array_equal(t3.d2, od3)


def test_sustained_load_harness():
    m, X, _centers = _fit(n=500)
    live = m.live(leaves=8)
    res = sustained_load(
        live.engine, clients=4, duration_s=0.7, rate_hz=120.0,
        batch_rows=16, write_fraction=0.4, live=live, seed=1,
    )
    assert res["arrival"] == "poisson"
    assert res["clients"] == 4
    assert res["queries"] > 0
    for key in ("qps", "p50_ms", "p99_ms", "batch_fill"):
        assert np.isfinite(res[key]), (key, res)
    if res["writes"]:
        assert res["update_visible_p50_ms"] > 0
        assert live.index.epoch >= 0
    _assert_refit_equivalent(live)


def test_report_live_block_and_summary():
    m, X, centers = _fit()
    live = m.live(leaves=8)
    live.insert(centers[0] + np.full((1, X.shape[1]), 0.1))
    live.delete(live.ids()[:1])
    rep = m.report()
    lv = rep["live"]
    for key in ("points", "cores", "inserts", "deletes", "updates",
                "recluster_events", "index_epoch", "index_delta_bytes",
                "recluster_tile_fraction", "insert_p50_ms",
                "insert_p99_ms", "delete_p50_ms", "delete_p99_ms"):
        assert key in lv, key
        assert np.isfinite(lv[key]), (key, lv[key])
    assert 0.0 <= lv["recluster_tile_fraction"] <= 1.0
    assert lv["inserts"] == 1 and lv["deletes"] == 1
    assert "live:" in m.summary()


def test_inflight_tickets_survive_epoch_bump():
    """A ticket submitted before a live update resolves on the next
    drain against the refreshed index — the engine picks up the new
    epoch through its normal path without dropping anything."""
    m, X, centers = _fit(n=400, seed=3)
    live = m.live(leaves=8)
    Q = X[:64]
    t = live.engine.submit(Q)
    epoch0 = live.index.epoch
    live.insert(centers[0] + np.random.default_rng(8).normal(
        scale=0.25, size=(10, X.shape[1])
    ))
    live.engine.drain()
    assert t.done
    olabs, od2 = live.index.oracle_predict(Q)  # post-update oracle
    np.testing.assert_array_equal(t.labels, olabs)
    np.testing.assert_array_equal(t.d2, od2)
    assert live.engine.serving_stats()["index_epoch"] \
        == live.index.epoch >= epoch0


def test_epoch_swap_readers_old_then_new_generation():
    """ISSUE 12: readers submitted BEFORE a compaction epoch swap drain
    against the old generation; readers after see the new one — both
    bitwise against their generation's oracle, zero dropped."""
    from pypardis_tpu.serve import Compactor

    m, X, centers = _fit(n=500, seed=5)
    live = m.live(leaves=8)
    rng = np.random.default_rng(11)
    # Updates first, so the canonical live labels and a re-densified
    # refit numbering genuinely differ across the swap.
    spot = np.full(X.shape[1], 22.0)
    live.insert(spot + rng.normal(scale=0.2, size=(MS + 2, X.shape[1])))
    live.delete(live.ids()[3:9])
    Q = np.concatenate([
        live.points()[:150],
        spot + rng.normal(scale=0.2, size=(20, X.shape[1])),
    ])
    pre_labs, pre_d2 = live.index.oracle_predict(Q)
    before = live.engine.submit(Q)

    comp = Compactor(live)
    comp.compact()

    assert before.done and not before.failed
    np.testing.assert_array_equal(before.labels, pre_labs)
    np.testing.assert_array_equal(before.d2, pre_d2)
    after = live.engine.submit(Q)
    live.engine.drain()
    olabs, od2 = live.index.oracle_predict(Q)
    np.testing.assert_array_equal(after.labels, olabs)
    np.testing.assert_array_equal(after.d2, od2)
    assert live.engine.serving_stats()["index_generation"] == 1
    _assert_refit_equivalent(live)


def test_replicated_engine_consistent_across_epoch_swap():
    """ISSUE 12: a ReplicatedQueryEngine built over the live index
    stays consistent across a whole-index generation swap — the
    in-place replace + epoch bump re-broadcasts the replicas, answers
    bitwise vs the new generation's oracle and vs the single-device
    engine."""
    from pypardis_tpu.serve import Compactor

    m, X, centers = _fit(n=500, seed=6)
    live = m.live(leaves=8)
    rng = np.random.default_rng(12)
    rep = ReplicatedQueryEngine(live.index, backend="xla")
    Q = np.concatenate([
        X[:150], rng.uniform(-15, 15, size=(60, X.shape[1]))
    ])
    t0 = rep.submit(Q)
    rep.drain()
    o0 = live.index.oracle_predict(Q)
    np.testing.assert_array_equal(t0.labels, o0[0])

    live.insert(centers[0] + rng.normal(scale=0.25,
                                        size=(20, X.shape[1])))
    Compactor(live).compact()

    t1 = rep.submit(Q)
    rep.drain()
    o1, od1 = live.index.oracle_predict(Q)
    np.testing.assert_array_equal(t1.labels, o1)
    np.testing.assert_array_equal(t1.d2, od1)
    t2 = live.engine.submit(Q)
    live.engine.drain()
    np.testing.assert_array_equal(t1.labels, t2.labels)
    np.testing.assert_array_equal(t1.d2, t2.d2)
    assert rep.serving_stats()["index_generation"] == 1


def test_insert_validation_and_delete_unknown_id():
    m, X, _centers = _fit(n=300, seed=2)
    live = m.live()
    with pytest.raises(ValueError, match="2-D"):
        live.insert(np.zeros(3))
    with pytest.raises(ValueError):
        live.insert(np.zeros((2, X.shape[1] + 1)))
    bad = np.zeros((1, X.shape[1]))
    bad[0, 0] = np.nan
    with pytest.raises(ValueError, match="NaN"):
        live.insert(bad)
    with pytest.raises(KeyError, match="unknown"):
        live.delete([10 ** 9])
    ids = live.insert(np.full((1, X.shape[1]), 30.0))
    live.delete(ids)
    with pytest.raises(KeyError, match="deleted"):
        live.delete(ids)


def test_warm_compile_excludes_first_insert(monkeypatch):
    """Satellite (ISSUE 9): live() warm-compiles the recluster kernel
    at build time, so the FIRST core-flipping insert runs against an
    already-compiled bucket — no jit trace inside the insert latency.

    One leaf forces every blast radius to the all-cores bucket the
    warmup compiled; the ambient compile-event counter must not move
    across the insert while the live() build itself did compile."""
    from pypardis_tpu import obs

    m, X, centers = _fit(n=500, seed=4)
    amb = obs.current().metrics

    def compiles():
        return int(amb.counter("events.compile", 0))

    live = m.live(leaves=1)
    # the warmup ran (its wall time is the gauge; whether it TRACED
    # depends on what earlier tests already compiled — order-immune)
    assert live.stats["warm_compile_ms"] > 0.0
    built = compiles()
    # A batch dense enough to flip cores -> the recluster path runs.
    batch = centers[0] + np.random.default_rng(5).normal(
        scale=0.2, size=(8, X.shape[1])
    )
    live.insert(batch)
    assert live._counters["recluster_events"] >= 1
    assert compiles() == built  # first insert paid ZERO compiles
    _assert_refit_equivalent(live)


def test_lazy_model_sync_copies_only_on_read():
    """Satellite (ISSUE 9): LiveModel no longer copies the O(N) model
    arrays on every update — updates mark dirty, the copy happens at
    most once per read of labels_/core_sample_mask_/data."""
    m, X, centers = _fit(n=500, seed=6)
    live = m.live(leaves=4)
    assert live.stats["model_syncs"] == 0
    rng = np.random.default_rng(9)
    for i in range(5):
        live.insert(centers[i % 5] + rng.normal(
            scale=0.2, size=(2, X.shape[1])
        ))
    # five updates, zero syncs: the write path never copied
    assert live.stats["model_syncs"] == 0
    n_now = len(m.labels_)  # the read triggers exactly one sync
    live._publish()
    assert live.stats["model_syncs"] == 1
    assert n_now == 500 + 10
    # the synced surface is current and consistent
    np.testing.assert_array_equal(m.labels_, live.labels())
    np.testing.assert_array_equal(m.core_sample_mask_, live.core_mask())
    assert live.stats["model_sync_bytes"] > 0
    # repeated reads stay free until the next write
    _ = m.labels_, m.data
    live._publish()
    assert live.stats["model_syncs"] == 1
    live.delete([0])
    np.testing.assert_array_equal(m.labels_, live.labels())
    live._publish()
    assert live.stats["model_syncs"] == 2
