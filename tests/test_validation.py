"""Input validation (ISSUE 3 satellite): the sklearn-inherited input
contract.  ``eps=-0.3`` used to behave exactly like ``eps=0.3`` (the
kernels compare squared distances) and a single NaN poisoned the
Morton span into silently wrong labels."""

import numpy as np
import pytest

from pypardis_tpu import DBSCAN


@pytest.fixture()
def X():
    return np.random.default_rng(0).normal(size=(64, 3))


@pytest.mark.parametrize("eps", [0.0, -0.3, float("nan"), float("inf")])
def test_train_rejects_bad_eps(X, eps):
    with pytest.raises(ValueError, match="eps"):
        DBSCAN(eps=eps, min_samples=5).fit(X)


@pytest.mark.parametrize("min_samples", [0, -1])
def test_train_rejects_bad_min_samples(X, min_samples):
    with pytest.raises(ValueError, match="min_samples"):
        DBSCAN(eps=0.5, min_samples=min_samples).fit(X)


@pytest.mark.parametrize("bad", [np.nan, np.inf, -np.inf])
def test_train_rejects_nonfinite_coordinates(X, bad):
    X = X.copy()
    X[17, 1] = bad
    with pytest.raises(ValueError, match="NaN or infinite"):
        DBSCAN(eps=0.5, min_samples=5).fit(X)


def test_train_rejects_nonfinite_device_input(X):
    import jax.numpy as jnp

    Xd = jnp.asarray(X.astype(np.float32)).at[3, 0].set(jnp.nan)
    with pytest.raises(ValueError, match="NaN or infinite"):
        DBSCAN(eps=0.5, min_samples=5).fit(Xd)


def test_finite_check_env_opt_out(X, monkeypatch):
    """Trusted pipelines can skip the O(N*k) pass — the fit then runs
    (and may return garbage labels, which is the documented trade)."""
    monkeypatch.setenv("PYPARDIS_SKIP_FINITE_CHECK", "1")
    X = X.copy()
    X[0, 0] = np.nan
    DBSCAN(eps=0.5, min_samples=5).fit(X)  # must not raise


def test_dbscan_fixed_size_rejects_bad_params():
    import jax.numpy as jnp

    from pypardis_tpu.ops.labels import dbscan_fixed_size

    pts = jnp.zeros((128, 2), jnp.float32)
    mask = jnp.ones((128,), bool)
    with pytest.raises(ValueError, match="eps"):
        dbscan_fixed_size(pts, -1.0, 5, mask, block=128)
    with pytest.raises(ValueError, match="min_samples"):
        dbscan_fixed_size(pts, 0.5, 0, mask, block=128)


def test_valid_fit_still_works(X):
    labels = DBSCAN(eps=0.5, min_samples=3).fit_predict(X)
    assert labels.shape == (len(X),)


# -- serve/route query validation (ISSUE 4 satellite) -------------------


def test_route_rejects_wrong_dimensionality(X):
    part = __import__("pypardis_tpu").KDPartitioner(X, max_partitions=4)
    with pytest.raises(ValueError, match="dimensionality"):
        part.route(np.zeros((5, X.shape[1] + 2)))
    with pytest.raises(ValueError, match="2-D"):
        part.route(np.zeros(3))


def test_route_rejects_nonfinite(X):
    part = __import__("pypardis_tpu").KDPartitioner(X, max_partitions=4)
    bad = X.copy()
    bad[3, 1] = np.nan
    with pytest.raises(ValueError, match="NaN or infinite"):
        part.route(bad)


def test_route_tree_rejects_too_narrow_points(X):
    """Regression: a wrong-d array used to route through split axes
    that mean something else (or crash on an out-of-range axis)."""
    from pypardis_tpu.partition import KDPartitioner, route_tree

    part = KDPartitioner(X, max_partitions=4)
    if not part.tree:
        pytest.skip("degenerate tree")
    need = max(a for _p, a, _b, _l, _r in part.tree) + 1
    if need < 2:
        pytest.skip("tree routes on axis 0 only")
    with pytest.raises(ValueError, match="split tree"):
        route_tree(part.tree, np.zeros((5, need - 1)))


def test_loaded_partition_tree_route_validates(tmp_path, X):
    from pypardis_tpu import KDPartitioner, load_partitioner, \
        save_partitioner

    part = KDPartitioner(X, max_partitions=4)
    path = str(tmp_path / "tree.npz")
    save_partitioner(part, path)
    tree = load_partitioner(path)
    np.testing.assert_array_equal(tree.route(X), part.route(X))
    with pytest.raises(ValueError, match="dimensionality"):
        tree.route(np.zeros((2, X.shape[1] + 1)))
