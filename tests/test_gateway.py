"""Multi-tenant serving gateway (ISSUE 19): model registry under a
device-slab byte budget, per-tenant admission control, hot swap.

Contracts pinned here:

* **Eviction round-trips byte-identical**: forcing a resident model
  past the budget spills it (``save_index``) and frees its device
  slabs; the next request readmits it (``load_index``) and its
  predictions are bitwise equal to pre-eviction — LRU picks the
  least-recently-served victim;
* **epoch swap drops nothing**: ``refresh()`` under concurrent
  multi-tenant load lands a new generation with zero dropped tickets,
  and post-swap answers match the refreshed model's own ``predict``;
* **quota shedding isolates tenants**: a hot tenant over its token
  bucket sheds with :class:`TenantQuotaExceeded` while a quiet tenant
  on the same gateway sheds nothing and resolves everything;
* **staleness is refused, never silently served**: a refit after
  registration raises :class:`StaleModelHandle` until ``refresh()``;
  likewise unknown models raise :class:`ModelNotRegistered`;
* the ``gateway.admit`` fault site fires at the front door — an
  injected fault sheds the request before any engine state mutates.
"""

import numpy as np
import pytest

from benchdata import make_separated_blob_data
from pypardis_tpu import DBSCAN
from pypardis_tpu.parallel.mesh import default_mesh
from pypardis_tpu.serve import (
    GatewayError,
    ModelGateway,
    ModelNotRegistered,
    StaleModelHandle,
    TenantQuotaExceeded,
    gateway_load,
)
from pypardis_tpu.utils import faults
from pypardis_tpu.utils.faults import FaultInjected

EPS, MS = 1.0, 5


def _fit(seed=0, n=300, dim=4):
    X, _truth, _centers = make_separated_blob_data(
        n, dim, n_centers=4, std=0.35,
        min_sep=2 * EPS + 6 * 0.35 + 1.0, spread=10.0, seed=seed,
    )
    m = DBSCAN(eps=EPS, min_samples=MS, mesh=default_mesh(1),
               block=128).fit(X)
    return m, X


def _fleet(gw, k=3):
    fleet = {}
    for i in range(k):
        m, X = _fit(seed=i)
        mid = f"m{i:02d}"
        gw.register(mid, m)
        fleet[mid] = (m, X)
    return fleet


def test_eviction_reload_byte_identity(tmp_path):
    gw = ModelGateway(spill_dir=str(tmp_path))
    fleet = _fleet(gw, 3)
    pre = {mid: m.predict(X[:40]) for mid, (m, X) in fleet.items()}
    for mid, (m, X) in fleet.items():
        np.testing.assert_array_equal(gw.predict(mid, X[:40]), pre[mid])

    # Budget fits ~2 of the 3 residents; enforcement must evict
    # exactly the least-recently-served model (m00: the serve loop
    # above touched models in registration order, m02 last).
    per = gw.handle("m01").index_bytes
    gw.budget_bytes = int(per * 2.5)
    gw._ensure_budget(keep="m02")
    rep = gw.gateway_report()
    assert rep["evictions"] == 1
    assert rep["resident_models"] == 2
    evicted = [m for m, b in rep["models"].items() if not b["resident"]]
    assert evicted == ["m00"]
    spills = list(tmp_path.glob("*.npz"))
    assert len(spills) == 1 and spills[0].stem == "m00"

    # Readmission on demand: answers bitwise equal to pre-eviction
    # (load_index restores the slabs byte-identical), and the reload
    # displaced the new least-recently-served resident.
    np.testing.assert_array_equal(
        gw.predict("m00", fleet["m00"][1][:40]), pre["m00"]
    )
    rep = gw.gateway_report()
    assert rep["reloads"] == 1
    assert rep["evictions"] == 2  # the new LRU resident made room
    assert rep["models"]["m00"]["resident"]
    # m02 is the victim: the handle("m01") byte probe above touched
    # m01, leaving m02 least-recently-served among the residents.
    assert not rep["models"]["m02"]["resident"]
    assert rep["models"]["m01"]["resident"]


def test_pinned_models_never_evicted(tmp_path):
    gw = ModelGateway(spill_dir=str(tmp_path))
    m0, X0 = _fit(seed=0)
    m1, _ = _fit(seed=1)
    gw.register("keep", m0, pin=True)
    gw.register("spare", m1)
    gw.budget_bytes = 1  # nothing fits; only the unpinned spills
    gw._ensure_budget(keep="")
    rep = gw.gateway_report()
    assert rep["models"]["keep"]["resident"]
    assert not rep["models"]["spare"]["resident"]
    # The pinned model keeps serving without a reload.
    np.testing.assert_array_equal(
        gw.predict("keep", X0[:8]), m0.predict(X0[:8])
    )
    assert gw.gateway_report()["reloads"] == 0


def test_epoch_swap_under_load_zero_drops(tmp_path):
    gw = ModelGateway(spill_dir=str(tmp_path))
    fleet = _fleet(gw, 3)
    refreshed, _X2 = fleet["m02"]
    m_new, X_new = _fit(seed=7)

    res = gateway_load(
        gw, list(fleet), tenants=3, clients_per_tenant=1,
        duration_s=1.2, rate_hz=120.0, batch_rows=4, seed=3,
        refresh_at_s=0.4,
        refresher=lambda: gw.refresh("m02", m_new),
    )
    assert res["dropped_tickets"] == 0
    assert res["deadline_failures"] == 0
    assert res["gateway"]["epoch_swaps"] == 1
    assert res["queries"] > 0
    # Post-swap the handle serves the REFRESHED clustering.
    np.testing.assert_array_equal(
        gw.predict("m02", X_new[:30]), m_new.predict(X_new[:30])
    )
    # Per-tenant latency stats materialized for every tenant.
    tenants = res["gateway"]["tenants"]
    assert {"t00", "t01", "t02"} <= set(tenants)
    for st in tenants.values():
        assert np.isfinite(st["p99_ms"])


def test_quota_shedding_isolates_tenants(tmp_path):
    gw = ModelGateway(spill_dir=str(tmp_path))
    m, X = _fit(seed=0)
    gw.register("m00", m)
    # Hot tenant: bucket of 3 then dry (refill is negligible within
    # the loop); quiet tenant: unlimited.
    gw.set_quota("hot", qps=0.001, burst=3)
    hot_ok = hot_shed = 0
    for _ in range(10):
        try:
            gw.predict("m00", X[:4], tenant="hot")
            hot_ok += 1
        except TenantQuotaExceeded:
            hot_shed += 1
    for _ in range(10):
        gw.predict("m00", X[:4], tenant="quiet")  # never sheds
    assert hot_ok == 3 and hot_shed == 7
    rep = gw.gateway_report()
    assert rep["tenants"]["hot"]["shed"] == 7
    assert rep["tenants"]["quiet"]["shed"] == 0
    assert rep["tenants"]["quiet"]["admitted"] == 10
    assert rep["tenants"]["quiet"]["failed"] == 0
    assert rep["admission_sheds"] == 7


def test_stale_handle_rejected_after_refit(tmp_path):
    gw = ModelGateway(spill_dir=str(tmp_path))
    m, X = _fit(seed=0)
    gw.register("m00", m)
    gw.predict("m00", X[:4])
    m.fit(X)  # refit bumps the model's fit generation
    with pytest.raises(StaleModelHandle, match="refit after"):
        gw.predict("m00", X[:4])
    # refresh() adopts the new generation; serving resumes.
    gw.refresh("m00")
    np.testing.assert_array_equal(
        gw.predict("m00", X[:30]), m.predict(X[:30])
    )


def test_unknown_model_and_double_register(tmp_path):
    gw = ModelGateway(spill_dir=str(tmp_path))
    m, X = _fit(seed=0)
    with pytest.raises(ModelNotRegistered, match="no model 'nope'"):
        gw.predict("nope", X[:2])
    gw.register("m00", m)
    with pytest.raises(GatewayError, match="already registered"):
        gw.register("m00", m)
    gw.unregister("m00")
    with pytest.raises(ModelNotRegistered):
        gw.predict("m00", X[:2])


def test_admit_fault_site_sheds_upstream(tmp_path):
    gw = ModelGateway(spill_dir=str(tmp_path))
    m, X = _fit(seed=0)
    gw.register("m00", m)
    with faults.plan("gateway.admit:2=error"):
        gw.predict("m00", X[:4], tenant="a")  # occurrence 1: clean
        with pytest.raises(FaultInjected):
            gw.predict("m00", X[:4], tenant="a")
        # The injected fault landed BEFORE admission bookkeeping and
        # before any engine touch: nothing shed, nothing failed, and
        # the next request serves normally.
        gw.predict("m00", X[:4], tenant="a")
    rep = gw.gateway_report()
    assert rep["admission_sheds"] == 0
    assert rep["tenants"]["a"]["failed"] == 0
    assert rep["tenants"]["a"]["admitted"] == 2


def test_live_handle_is_pinned_and_writable(tmp_path):
    gw = ModelGateway(spill_dir=str(tmp_path))
    m, X = _fit(seed=0)
    h = gw.register("m00", m, live=True)
    assert h.pinned and h.live is not None
    q = X[:1] + 0.05
    h.live.insert(q)
    labs = gw.predict("m00", q)
    assert labs[0] == h.live.labels()[-1]
    # Live handles refuse refresh(): the Compactor owns their swaps.
    with pytest.raises(GatewayError, match="live handle"):
        gw.refresh("m00")
