"""BoundingBox algebra vs reference semantics (geometry.py:34-96)."""

import numpy as np

from pypardis_tpu.geometry import BoundingBox, BoxStack


def test_intersection_union():
    a = BoundingBox([0, 0], [2, 2])
    b = BoundingBox([1, -1], [3, 1])
    i = a.intersection(b)
    np.testing.assert_array_equal(i.lower, [1, 0])
    np.testing.assert_array_equal(i.upper, [2, 1])
    u = a.union(b)
    np.testing.assert_array_equal(u.lower, [0, -1])
    np.testing.assert_array_equal(u.upper, [3, 2])


def test_all_space_contains_negatives():
    # Fixes the reference's sys.float_info.min sign bug (geometry.py:25).
    box = BoundingBox(k=3, all_space=True)
    assert box.contains([-1e300, 0.0, 1e300])


def test_empty_box_union_identity():
    empty = BoundingBox(k=2)
    b = BoundingBox([1, 2], [3, 4])
    u = empty.union(b)
    np.testing.assert_array_equal(u.lower, b.lower)
    np.testing.assert_array_equal(u.upper, b.upper)


def test_split_shares_plane():
    box = BoundingBox([0, 0], [4, 4])
    left, right = box.split(0, 1.5)
    assert left.upper[0] == 1.5 and right.lower[0] == 1.5
    # both children contain the plane (inclusive semantics)
    assert left.contains([1.5, 2]) and right.contains([1.5, 2])


def test_expand_add_multiply():
    box = BoundingBox([0, 0], [2, 4])
    e = box.expand(0.5)
    np.testing.assert_array_equal(e.lower, [-0.5, -0.5])
    np.testing.assert_array_equal(e.upper, [2.5, 4.5])
    m = box.expand(0.5, how="multiply")
    np.testing.assert_array_equal(m.lower, [-1, -2])
    np.testing.assert_array_equal(m.upper, [3, 6])


def test_contains_inclusive():
    box = BoundingBox([0, 0], [1, 1])
    assert box.contains([0, 0]) and box.contains([1, 1])
    assert not box.contains([1.0001, 0.5])


def test_boxstack_membership_matches_scalar():
    rng = np.random.default_rng(0)
    boxes = [
        BoundingBox([0, 0], [1, 1]),
        BoundingBox([0.5, 0.5], [2, 2]),
        BoundingBox([-1, -1], [0, 0]),
    ]
    stack = BoxStack.from_boxes(boxes)
    pts = rng.uniform(-1.5, 2.5, size=(50, 2))
    mem = stack.membership(pts)
    for p in range(3):
        expected = np.array([boxes[p].contains(x) for x in pts])
        np.testing.assert_array_equal(mem[:, p], expected)
