"""The terminal-runnable demo (reference README.md:40-42 parity)."""

import numpy as np

from pypardis_tpu.demo import make_demo_data, run_demo


def test_demo_runs_and_matches_sklearn(tmp_path, capsys):
    labels = run_demo(n=750, eps=0.3, min_samples=10)
    out = capsys.readouterr().out
    assert "3 clusters" in out
    assert "ARI vs single-node sklearn: 1.0" in out
    assert labels.shape == (750,)


def test_demo_plots(tmp_path):
    try:
        import matplotlib  # noqa: F401
    except ImportError:
        import pytest

        pytest.skip("matplotlib not installed")
    run_demo(n=200, eps=0.3, min_samples=5, out=str(tmp_path))
    for f in ("partitioning.png", "clusters.png", "clusters_partitions.png",
              "dbscan_animated.gif"):
        assert (tmp_path / f).exists()
    # One scatter per KD leaf, like the reference's plots/*/partition_N.png.
    assert list(tmp_path.glob("partition_*.png"))


def test_demo_data_shape():
    X, y = make_demo_data(100)
    assert X.shape == (100, 2)
    assert abs(float(np.mean(X))) < 1e-6  # standardized
