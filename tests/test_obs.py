"""Unified telemetry layer (pypardis_tpu.obs).

Unit: registry schema/merge, span nesting + sync_on, Chrome-trace
export round-trip, recorder events, the log_phase -> registry bridge.
Integration: ``DBSCAN.fit().report()`` on the faked 8-device CPU mesh
carries phase times, per-device partition sizes, halo_factor,
pad_waste, and ladder event counts — and the exported trace JSON loads
with a valid ``traceEvents`` list.
"""

import json

import numpy as np
import pytest

from pypardis_tpu import DBSCAN
from pypardis_tpu.obs import (
    MetricsRegistry,
    RunRecorder,
    Tracer,
    build_run_report,
    format_summary,
    use_recorder,
)


# ---------------------------------------------------------------------------
# MetricsRegistry
# ---------------------------------------------------------------------------


def test_registry_counter_gauge_timing():
    reg = MetricsRegistry()
    reg.inc("events.retry.restage")
    reg.inc("events.retry.restage", 2)
    reg.set("sharded.halo_factor", 0.25)
    reg.observe("phase.cluster", 1.0)
    reg.observe("phase.cluster", 3.0)
    d = reg.as_dict()
    assert d["counters"]["events.retry.restage"] == 3
    assert d["gauges"]["sharded.halo_factor"] == 0.25
    t = d["timings"]["phase.cluster"]
    assert t["count"] == 2
    assert t["total_s"] == pytest.approx(4.0)
    assert t["min_s"] == 1.0 and t["max_s"] == 3.0
    assert t["mean_s"] == pytest.approx(2.0)


def test_registry_rejects_bad_keys():
    reg = MetricsRegistry()
    for bad in ("Upper.case", "spa ce", "", "trailing.", ".leading",
                "dash-key"):
        with pytest.raises(ValueError):
            reg.inc(bad)


def test_registry_numpy_values_become_python():
    reg = MetricsRegistry()
    reg.set("run.n_partitions", np.int32(8))
    reg.inc("events.compile", np.int64(1))
    reg.observe("phase.x", np.float32(0.5))
    json.dumps(reg.as_dict())  # must not raise
    assert isinstance(reg.as_dict()["gauges"]["run.n_partitions"], int)


def test_registry_merge_semantics():
    a, b = MetricsRegistry(), MetricsRegistry()
    a.inc("events.compile", 1)
    b.inc("events.compile", 2)
    a.set("run.n_partitions", 4)
    b.set("run.n_partitions", 8)  # newer wins
    a.observe("phase.cluster", 1.0)
    b.observe("phase.cluster", 3.0)
    a.merge(b)
    d = a.as_dict()
    assert d["counters"]["events.compile"] == 3
    assert d["gauges"]["run.n_partitions"] == 8
    assert d["timings"]["phase.cluster"]["count"] == 2
    assert d["timings"]["phase.cluster"]["max_s"] == 3.0


# ---------------------------------------------------------------------------
# Tracer / spans
# ---------------------------------------------------------------------------


def test_span_nesting_depths_and_durations():
    tr = Tracer()
    with tr.span("outer"):
        with tr.span("inner", stage=1):
            pass
    # inner closes first
    assert [s.name for s in tr.spans] == ["inner", "outer"]
    inner, outer = tr.spans
    assert inner.depth == 1 and outer.depth == 0
    assert inner.attrs == {"stage": 1}
    assert 0 <= inner.dur_s <= outer.dur_s
    # containment: inner lies within outer's interval
    assert outer.t0_s <= inner.t0_s
    assert inner.t0_s + inner.dur_s <= outer.t0_s + outer.dur_s + 1e-6
    assert tr.durations()["outer"] >= tr.durations()["inner"]


def test_span_sync_on_blocks_on_device_work():
    import jax.numpy as jnp

    tr = Tracer()
    with tr.span("compute") as sp:
        y = jnp.arange(1024) * 2
        sp.sync_on(y)
    assert tr.spans[0].dur_s is not None
    # after the span, the pending handle is consumed
    assert tr.spans[0]._pending is None


def test_chrome_trace_export_round_trip(tmp_path):
    tr = Tracer()
    with tr.span("fit", n=100):
        with tr.span("cluster"):
            pass
    path = tr.export_chrome_trace(str(tmp_path / "trace.json"))
    doc = json.load(open(path))
    events = doc["traceEvents"]
    assert isinstance(events, list)
    x_events = [e for e in events if e.get("ph") == "X"]
    assert {e["name"] for e in x_events} == {"fit", "cluster"}
    for e in x_events:
        assert e["ts"] >= 0 and e["dur"] >= 0  # microseconds
    assert x_events[-1]["args"] == {"n": 100}


# ---------------------------------------------------------------------------
# RunRecorder / events / log bridge
# ---------------------------------------------------------------------------


def test_recorder_events_bump_counters():
    rec = RunRecorder()
    rec.event("pair_overflow", total=10, budget=4)
    rec.event("retry.restage", wait_s=10)
    rec.event("retry.restage", wait_s=75)
    counts = rec.event_counts()
    assert counts == {"pair_overflow": 1, "retry.restage": 2}
    kinds = [e["kind"] for e in rec.events]
    assert kinds == ["pair_overflow", "retry.restage", "retry.restage"]
    assert rec.events[0]["total"] == 10


def test_log_phase_records_into_current_recorder():
    from pypardis_tpu.utils.log import log_phase

    rec = RunRecorder()
    with use_recorder(rec):
        log_phase("train", n=100, clusters=3)
    assert rec.event_counts() == {"log.train": 1}
    assert rec.events[0]["n"] == 100


def test_phase_timer_feeds_registry_and_tracer():
    from pypardis_tpu.utils.profiling import PhaseTimer

    rec = RunRecorder()
    with use_recorder(rec):
        t = PhaseTimer()
        with t.phase("cluster"):
            pass
    assert "cluster_s" in t.as_dict()  # original surface intact
    reg = rec.metrics.as_dict()
    assert reg["timings"]["phase.cluster"]["count"] == 1
    assert [s.name for s in rec.tracer.spans] == ["cluster"]


# ---------------------------------------------------------------------------
# integration: DBSCAN.report() / summary() / export_trace()
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def fitted_model():
    from sklearn.datasets import make_blobs

    X, _ = make_blobs(
        n_samples=2000, centers=8, n_features=4, cluster_std=0.3,
        random_state=3,
    )
    return DBSCAN(eps=0.4, min_samples=5, block=64).fit(X)


def test_report_schema_on_mesh(fitted_model):
    r = fitted_model.report()
    assert r["schema"] == "pypardis_tpu/run_report@1"
    json.dumps(r)  # serializable end to end

    # per-phase wall times
    assert set(r["phases"]) >= {"partition", "cluster", "densify"}
    assert all(v >= 0 for v in r["phases"].values())
    assert r["run"]["total_s"] > 0
    assert r["run"]["n_points"] == 2000 and r["run"]["n_dims"] == 4
    assert r["run"]["n_devices"] == 8

    # shard-layout overheads
    assert r["sharding"]["halo_factor"] > 0
    assert r["sharding"]["pad_waste"] >= 0
    assert r["sharding"]["n_shard_partitions"] == 8
    assert r["sharding"]["halo_bytes"] > 0

    # per-device partition sizes: 8 devices, all points accounted for
    dev = r["devices"]
    assert dev["count"] == 8
    assert len(dev["partition_sizes"]) == 8
    assert sum(dev["points"]) == 2000

    # restage / ladder event counts always present
    assert set(r["events"]) == {
        "restage", "transient_retry", "pair_overflow", "halo_overflow",
        "merge_unconverged", "compile", "fault_injected", "degraded",
    }
    assert r["events"]["restage"] == 0

    # fault-tolerance block: always present, all-zero on a clean fit
    # (the injection sites are no-ops without PYPARDIS_FAULTS)
    assert r["faults"] == {
        "injected": 0, "retried": 0, "giveups": 0, "degraded": 0,
        "degraded_to": "",
    }

    # registry dump rides along
    assert "phase.cluster" in r["metrics"]["timings"]


def test_summary_one_screen(fitted_model):
    s = fitted_model.summary()
    assert "2,000 pts x 4D" in s
    assert "halo_factor" in s and "pad_waste" in s
    assert "events:" in s
    assert "resources:" in s  # watermark line (ISSUE 6)
    assert "live-metrics:" not in s  # only rendered when exporting
    assert len(s.splitlines()) <= 9  # one screen, not a dump


def test_summary_live_metrics_line(tmp_path, monkeypatch):
    """ISSUE 16: a fit run with the export plane attached says WHERE
    the live metrics went — one extra summary line, still one screen."""
    from sklearn.datasets import make_blobs

    snap = tmp_path / "snap.jsonl"
    monkeypatch.setenv("PYPARDIS_METRICS_SNAPSHOT", str(snap))
    monkeypatch.setenv("PYPARDIS_METRICS_SNAPSHOT_S", "0.1")
    X, _ = make_blobs(
        n_samples=400, centers=4, n_features=4, cluster_std=0.3,
        random_state=0,
    )
    m = DBSCAN(eps=0.4, min_samples=5, block=64).fit(X)
    s = m.summary()
    assert "live-metrics:" in s
    assert str(snap) in s
    assert len(s.splitlines()) <= 10  # the one extra line, no more
    # the stream really was written, and its lines parse
    lines = [ln for ln in snap.read_text().splitlines() if ln]
    assert lines
    assert all(
        json.loads(ln)["schema"] == "pypardis_tpu/metrics_snapshot@1"
        for ln in lines
    )


def test_report_compute_and_perf_contract_sections(fitted_model):
    """ISSUE 2 telemetry: the compute section (achieved-FLOP/s model
    from the kernels' in-band pair stats) and the always-present
    duplicated_work_factor / staged_bytes_reused fields — finite
    numbers, never NaN (scripts/check_bench_json.py enforces the same
    contract on bench rows)."""
    import math

    r = fitted_model.report()
    comp = r["compute"]
    for key in ("live_pairs", "kernel_block", "kernel_passes",
                "model_flops", "achieved_flops_per_sec", "peak_flops",
                "mfu"):
        assert key in comp, key
        assert math.isfinite(float(comp[key])), key
    # The mesh fit really ran tiled passes over live pairs.
    assert comp["live_pairs"] > 0
    assert comp["kernel_passes"] >= 2  # counts + >=1 propagation pass
    assert comp["kernel_block"] > 0
    assert comp["achieved_flops_per_sec"] > 0
    assert 0 < comp["mfu"] < 1
    sh = r["sharding"]
    assert sh["owner_computes"] is True
    # Owner-computes: clustered volume ~ owned slots + padding, far
    # below the legacy 1 + pad + halo_factor.
    assert 1.0 <= sh["duplicated_work_factor"] < 1.0 + sh[
        "pad_waste"
    ] + 0.5
    assert sh["staged_bytes_reused"] == 0  # cold fit
    assert sh["staged_bytes"] > 0
    # "compute:" line renders in the one-screen summary.
    assert "compute:" in fitted_model.summary()


def test_report_compute_single_shard_nonzero():
    """The single-shard pipeline threads its packed pair stats into the
    same compute section."""
    X = np.random.default_rng(2).normal(size=(600, 4))
    m = DBSCAN(eps=0.4, min_samples=5, block=64, max_partitions=1).fit(X)
    comp = m.report()["compute"]
    assert comp["live_pairs"] > 0
    assert comp["kernel_passes"] >= 2
    assert comp["mfu"] > 0


def test_export_trace_valid_chrome_json(fitted_model, tmp_path):
    path = fitted_model.export_trace(str(tmp_path / "fit_trace.json"))
    doc = json.load(open(path))
    assert isinstance(doc["traceEvents"], list)
    names = {e["name"] for e in doc["traceEvents"] if e.get("ph") == "X"}
    assert "cluster" in names  # the driver phases are there
    assert "sharded.build_shards" in names


def test_report_single_shard_path():
    # n < 2 * n_devices -> single-device route; schema keys still there.
    X = np.random.default_rng(0).normal(size=(12, 3))
    m = DBSCAN(eps=0.5, min_samples=2, block=64).fit(X)
    r = m.report()
    json.dumps(r)
    assert r["run"]["n_devices"] == 1
    assert r["devices"]["points"] == [12]
    assert r["sharding"]["halo_factor"] == 0.0
    assert "cluster" in r["phases"]


def test_refit_resets_telemetry(fitted_model):
    X = np.random.default_rng(1).normal(size=(64, 3))
    m = DBSCAN(eps=0.5, min_samples=3, block=64)
    m.fit(X)
    first = m.report()
    m.fit(X)
    second = m.report()
    # phases don't accumulate across fits
    assert second["phases"]["cluster"] < first["phases"]["cluster"] * 10
    assert second["run"]["n_points"] == 64
    assert len(m._recorder.tracer.spans) < 40  # fresh tracer per fit


def test_pair_overflow_event_recorded():
    """An explicit too-small pair budget triggers the ladder; the event
    lands in the active recorder (the same signal report() exposes)."""
    from sklearn.datasets import make_blobs

    from pypardis_tpu.obs import RunRecorder as RR
    from pypardis_tpu.parallel import default_mesh, sharded_dbscan
    from pypardis_tpu.partition import KDPartitioner
    from pypardis_tpu.utils.hints import PAIR_BUDGET_HINTS

    PAIR_BUDGET_HINTS.clear()
    X, _ = make_blobs(
        n_samples=2000, centers=8, n_features=3, cluster_std=0.3,
        random_state=1,
    )
    mesh = default_mesh(8)
    part = KDPartitioner(X, max_partitions=8)
    rec = RR()
    with use_recorder(rec):
        sharded_dbscan(
            X, part, eps=0.4, min_samples=5, block=64, mesh=mesh,
            merge="device", pair_budget=1,
        )
    assert rec.event_counts().get("pair_overflow", 0) >= 1
    PAIR_BUDGET_HINTS.clear()


def test_report_host_pipeline_fields():
    """ISSUE 3 contract: overlap_efficiency and partition_levels_s are
    present on EVERY report — 0.0/[] for single-shard fits, populated
    per-level timings for sharded fits."""
    import numpy as np

    from pypardis_tpu import DBSCAN

    rng = np.random.default_rng(9)
    X = rng.normal(size=(12, 2))  # < 2*n_devices: the single-shard path
    m = DBSCAN(eps=0.5, min_samples=3).fit(X)
    rep = m.report()
    assert rep["sharding"]["overlap_efficiency"] == 0.0
    assert rep["sharding"]["partition_levels_s"] == []
    assert "overlap" in rep["params"]

    X = rng.normal(size=(4000, 3))
    m = DBSCAN(eps=0.4, min_samples=5, block=64).fit(X)  # 8-dev sharded
    rep = m.report()
    levels = rep["sharding"]["partition_levels_s"]
    assert isinstance(levels, list) and len(levels) >= 1
    assert all(isinstance(t, float) and t >= 0 for t in levels)
    assert rep["sharding"]["partition_builder"] == "level"
    # summary renders the new lines without raising
    assert "partition levels" in m.summary()
