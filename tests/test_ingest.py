"""Streaming ingest subsystem (ISSUE 12): batched writes, the
IngestQueue coalescer, and LSM-style background compaction with an
atomic whole-index epoch swap.

Contracts pinned here:

* ``insert_batch(B rows)`` runs EXACTLY one recluster kernel dispatch
  and ships EXACTLY one index delta — the amortization that makes
  heavy write traffic affordable — with labels ARI == 1.0 vs a full
  refit (``delete_batch`` same);
* the ``IngestQueue`` coalesces consecutive same-kind writes in order,
  resolves every ticket, and fails a faulted batch's tickets without
  poisoning the queue or the model;
* a compaction cycle swaps a re-Mortoned, re-balanced generation in
  WITHOUT stopping the world: in-flight tickets drain against the old
  generation, post-swap predict is bitwise oracle-exact, appended
  slabs are gone, writes that landed DURING the refit are replayed
  (the memtable replay), and the deterministic ``PYPARDIS_COMPACT_*``
  watermark policy drives ``should_compact``;
* ``LiveModel.save``/``load`` mid-compaction round-trips the serving
  (pre-swap) state byte-exactly and cleanly discards the partial
  generation — never a half-swapped index.
"""

import numpy as np
import pytest
from sklearn.metrics import adjusted_rand_score

from benchdata import make_separated_blob_data
from pypardis_tpu import DBSCAN
from pypardis_tpu.parallel.mesh import default_mesh
from pypardis_tpu.serve import Compactor, IngestQueue, LiveModel
from pypardis_tpu.utils import faults
from pypardis_tpu.utils.faults import FaultInjected

EPS, MS = 1.1, 6


def _fit(n=600, dim=3, seed=0):
    X, _truth, centers = make_separated_blob_data(
        n, dim, n_centers=5, std=0.35,
        min_sep=2 * EPS + 6 * 0.35 + 1.0, spread=10.0, seed=seed,
    )
    m = DBSCAN(eps=EPS, min_samples=MS, mesh=default_mesh(1),
               block=128).fit(X)
    return m, X, centers


def _assert_refit_equivalent(live):
    refit = DBSCAN(
        eps=live.eps, min_samples=live.min_samples,
        mesh=default_mesh(1), block=128,
    ).fit(live.points()).labels_
    ari = adjusted_rand_score(refit, live.labels())
    assert ari == 1.0, f"ARI {ari} vs full refit"


def _assert_oracle_exact(live, Q):
    t = live.engine.submit(Q)
    live.engine.drain()
    olabs, od2 = live.index.oracle_predict(Q)
    np.testing.assert_array_equal(t.labels, olabs)
    np.testing.assert_array_equal(t.d2, od2)


def test_insert_batch_one_dispatch_one_delta():
    m, X, centers = _fit()
    live = m.live(leaves=8)
    rng = np.random.default_rng(1)
    B = 64
    batch = (
        centers[rng.integers(0, len(centers), B)]
        + rng.normal(scale=0.25, size=(B, X.shape[1]))
    )
    d0 = live.stats["recluster_dispatches"]
    e0 = live.index.epoch
    ids = live.insert_batch(batch)
    assert len(ids) == B
    assert live.stats["recluster_dispatches"] - d0 == 1
    assert live.index.epoch - e0 == 1, "one index delta per batch"
    assert live.stats["batch_sizes"][-1] == B
    assert live.stats["reclusters_per_write"] < 0.05
    _assert_refit_equivalent(live)

    # delete_batch: same one-dispatch/one-delta contract.
    d0 = live.stats["recluster_dispatches"]
    e0 = live.index.epoch
    core_ids = ids[live._core[ids]]
    assert len(core_ids) > 2
    live.delete_batch(core_ids[:16])
    assert live.stats["recluster_dispatches"] - d0 == 1
    assert live.index.epoch - e0 == 1
    _assert_refit_equivalent(live)


def test_ingest_queue_coalesces_in_order():
    m, X, centers = _fit()
    live = m.live(leaves=8)
    rng = np.random.default_rng(2)
    q = IngestQueue(live, max_batch_rows=256)
    t1 = q.submit_insert(
        centers[0] + rng.normal(scale=0.2, size=(3, X.shape[1]))
    )
    t2 = q.submit_insert(
        centers[1] + rng.normal(scale=0.2, size=(4, X.shape[1]))
    )
    t3 = q.submit_delete(live.ids()[:2])
    t4 = q.submit_insert(
        centers[2] + rng.normal(scale=0.2, size=(2, X.shape[1]))
    )
    resolved = q.flush()
    # 4 submits coalesce to 3 batches: [3+4 insert], [2 delete],
    # [2 insert] — consecutive same-kind runs merge, order preserved.
    assert q.stats()["batches"] == 3
    assert [t.done for t in (t1, t2, t3, t4)] == [True] * 4
    assert len(resolved) == 4 and not any(t.failed for t in resolved)
    np.testing.assert_array_equal(t3.result(), t3.ids)
    assert len(t1.result()) == 3 and len(t2.result()) == 4
    # the two coalesced inserts got DISTINCT contiguous ids
    assert set(t1.ids).isdisjoint(t2.ids)
    _assert_refit_equivalent(live)
    assert q.flush() == []  # empty queue: no-op


def test_ingest_queue_backpressure_and_fault_isolation():
    from pypardis_tpu.serve.engine import QueueFull

    m, X, centers = _fit(n=400, seed=1)
    live = m.live(leaves=4)
    q = IngestQueue(live, max_pending_rows=8)
    q.submit_insert(np.full((6, X.shape[1]), 20.0))
    with pytest.raises(QueueFull):
        q.submit_insert(np.full((6, X.shape[1]), 21.0))
    assert q.stats()["shed"] == 1
    q.flush()

    # An injected ingest.batch fault fails ONLY that batch's tickets —
    # fired before any mutation, so the model is untouched and the
    # next flush works.
    pts0 = live.points()
    with faults.plan("ingest.batch:1=error"):
        bad = q.submit_insert(
            centers[0] + np.full((2, X.shape[1]), 0.1)
        )
        ok = q.flush()
    assert bad.failed and isinstance(bad.error, FaultInjected)
    assert q.stats()["failed_batches"] == 1
    np.testing.assert_array_equal(live.points(), pts0)
    good = q.submit_insert(centers[0] + np.full((2, X.shape[1]), 0.1))
    q.flush()
    assert good.done and not good.failed
    _assert_refit_equivalent(live)


def test_compaction_swap_correctness():
    m, X, centers = _fit(n=700)
    live = m.live(leaves=8, block=32, qblock=32)
    rng = np.random.default_rng(3)
    # Pour writes into one region until the leaf overflows: appended
    # slabs are the write debt compaction must clear.
    live.insert_batch(
        centers[1] + rng.normal(scale=0.3, size=(250, X.shape[1]))
    )
    live.delete_batch(live.ids()[10:30])
    assert live.index.appended_slab_bytes > 0
    assert live.index.deltas_since_compact >= 2

    Q = np.concatenate([
        live.points()[:150],
        rng.uniform(-15, 15, size=(60, X.shape[1])),
    ])
    pre_labs, pre_d2 = live.index.oracle_predict(Q)
    inflight = live.engine.submit(Q)
    epoch0, gen0 = live.index.epoch, live.index.generation

    comp = Compactor(live)
    stats = comp.compact()
    assert stats["compactions"] == 1

    # Readers submitted before the swap drained against the OLD
    # generation; readers after see the new one — both bitwise.
    assert inflight.done and not inflight.failed
    np.testing.assert_array_equal(inflight.labels, pre_labs)
    np.testing.assert_array_equal(inflight.d2, pre_d2)
    _assert_oracle_exact(live, Q)

    assert live.index.generation == gen0 + 1
    assert live.index.epoch == epoch0 + 1
    assert live.index.appended_slab_bytes == 0
    assert live.index.deltas_since_compact == 0
    # the fresh generation is build-layout: every leaf owns one slab
    assert all(
        len(s) == 1 for s in live.index.leaf_slabs.values()
    )
    assert live.stats["epoch_swaps"] == 1
    assert live.stats["compactions"] == 1
    assert live.stats["compaction_s"] > 0
    _assert_refit_equivalent(live)
    # writes keep working on the swapped-in generation
    live.insert_batch(
        centers[0] + rng.normal(scale=0.2, size=(5, X.shape[1]))
    )
    _assert_refit_equivalent(live)


def test_writes_during_compaction_are_replayed():
    """The memtable replay: writes landing between the snapshot and
    the swap survive into the new generation (deterministically
    scheduled via the phase hook — no thread races in CI)."""
    m, X, centers = _fit(n=600, seed=2)
    live = m.live(leaves=8)
    rng = np.random.default_rng(4)
    mid = {}

    def hook(phase):
        if phase == "build":
            spot = np.full(X.shape[1], 25.0)
            mid["ids"] = live.insert(
                spot + rng.normal(scale=0.2, size=(MS + 2, X.shape[1]))
            )
            live.delete(live.ids()[5:12])

    comp = Compactor(live, phase_hook=hook)
    stats = comp.compact()
    assert stats["replayed_inserts"] == MS + 2
    assert stats["replayed_deletes"] == 7
    # the mid-compaction clump is alive, clustered, and refit-exact
    labs = live._labels[mid["ids"]]
    assert (labs >= 0).all() and len(np.unique(labs)) == 1
    _assert_refit_equivalent(live)
    _assert_oracle_exact(live, live.points())


def test_compaction_trigger_watermarks(monkeypatch):
    m, X, centers = _fit(n=500, seed=3)
    live = m.live(leaves=8)
    rng = np.random.default_rng(5)
    comp = Compactor(live, max_deltas=2, slab_bytes=1 << 40)
    assert not comp.should_compact()
    for i in range(2):
        live.insert_batch(
            centers[i] + rng.normal(scale=0.25, size=(8, X.shape[1]))
        )
    assert live.index.deltas_since_compact >= 2
    assert comp.should_compact()
    comp.compact()
    assert not comp.should_compact(), "swap resets the watermarks"

    # env-knob defaults flow into fresh Compactors
    monkeypatch.setenv("PYPARDIS_COMPACT_DELTAS", "7")
    monkeypatch.setenv("PYPARDIS_COMPACT_SLAB_BYTES", "12345")
    c2 = Compactor(live)
    assert c2.max_deltas == 7 and c2.slab_bytes == 12345


def test_compact_phase_fault_leaves_old_generation_serving():
    m, X, centers = _fit(n=400, seed=4)
    live = m.live(leaves=4)
    live.insert_batch(
        centers[0] + np.full((4, X.shape[1]), 0.1)
    )
    Q = live.points()[:100]
    pre = live.engine.predict(Q)
    gen0, epoch0 = live.index.generation, live.index.epoch
    with faults.plan("compact.phase:2=error"):  # dies in the refit
        with pytest.raises(FaultInjected):
            Compactor(live).compact()
    assert live.index.generation == gen0
    assert live.index.epoch == epoch0
    assert not live._compact_active
    np.testing.assert_array_equal(live.engine.predict(Q), pre)
    # and a clean retry completes
    Compactor(live).compact()
    assert live.index.generation == gen0 + 1
    _assert_refit_equivalent(live)


def test_mid_compaction_save_load_discards_partial(tmp_path):
    """Satellite (ISSUE 12): a checkpoint written mid-compaction
    restores the pre-swap generation byte-exactly — never a
    half-swapped index — flags compact_pending, and a fresh compaction
    on the restored model completes."""
    m, X, centers = _fit(n=500, seed=5)
    live = m.live(leaves=8)
    rng = np.random.default_rng(6)
    live.insert_batch(
        centers[0] + rng.normal(scale=0.25, size=(30, X.shape[1]))
    )
    path = str(tmp_path / "mid.npz")
    pre_epoch = live.index.epoch
    pre_coords = live.index.coords.copy()
    pre_labels = live.index.labels.copy()

    def hook(phase):
        if phase == "build":  # refit done, partial generation pending
            live.save(path)

    Compactor(live, phase_hook=hook).compact()
    assert live.index.epoch == pre_epoch + 1  # original DID swap

    restored = LiveModel.load(path)
    assert restored.compact_pending is True
    assert restored.index.epoch == pre_epoch
    assert restored.index.generation == 0
    np.testing.assert_array_equal(restored.index.coords, pre_coords)
    np.testing.assert_array_equal(restored.index.labels, pre_labels)
    _assert_oracle_exact(restored, restored.points()[:100])
    Compactor(restored).compact()
    assert restored.compact_pending is True  # cleared by the operator
    _assert_refit_equivalent(restored)
    # a normal (no compaction in flight) save doesn't set the flag
    path2 = str(tmp_path / "clean.npz")
    restored.save(path2)
    assert LiveModel.load(path2).compact_pending is False


def test_mixed_load_with_background_compaction():
    """Acceptance: sustained mixed read/write load across a background
    compaction + epoch swap — zero dropped/failed tickets, oracle
    exact after, >= 1 swap observed."""
    from pypardis_tpu.serve import sustained_load

    m, X, centers = _fit(n=600, seed=6)
    live = m.live(leaves=8)

    def wsamp(rng, k):
        c = centers[rng.integers(0, len(centers))]
        return c + rng.normal(scale=0.25, size=(k, X.shape[1]))

    comp = Compactor(live)
    res = sustained_load(
        live.engine, clients=2, duration_s=1.2, rate_hz=80.0,
        batch_rows=16, writers=1, write_rate_hz=30.0,
        write_batch_rows=4, write_sampler=wsamp, live=live,
        compactor=comp, compact_at_s=0.2, seed=9,
    )
    assert res["compactions"] >= 1
    assert res["epoch_swaps"] >= 1
    assert res["dropped_tickets"] == 0
    assert res["write_failures"] == 0
    assert res["deadline_failures"] == 0
    for key in ("qps", "write_qps", "p99_ms",
                "read_p99_during_compaction_ms",
                "read_p99_outside_ms",
                "compaction_overlap_degradation"):
        assert np.isfinite(res[key]), (key, res[key])
    _assert_oracle_exact(live, live.points()[:150])


def test_report_ingest_fields_and_summary():
    m, X, centers = _fit(n=400, seed=7)
    live = m.live(leaves=4)
    live.insert_batch(
        centers[0] + np.full((4, X.shape[1]), 0.1)
    )
    Compactor(live).compact()
    lv = m.report()["live"]
    assert isinstance(lv["batch_sizes"], list) and lv["batch_sizes"]
    for key in ("reclusters_per_write", "compaction_s"):
        assert np.isfinite(lv[key]) and lv[key] >= 0
    for key in ("compactions", "epoch_swaps", "recluster_dispatches",
                "index_generation"):
        assert isinstance(lv[key], int) and lv[key] >= 0
    assert lv["compactions"] == 1 and lv["epoch_swaps"] == 1
    s = m.summary()
    assert "compact x1" in s and "batch mean" in s
