"""Sketch-prefiltered high-d distance pass (ISSUE 17).

The contract under test: ``sketch=k`` / ``"auto"`` classifies tile
pairs in a seeded k-dim random-projection space against ``eps^2 +-
band`` and only in-band tiles rerun the UNCHANGED exact full-d kernel
— so labels and counts are BYTE-IDENTICAL to the unsketched pass for
ANY k (``np.array_equal``, not ARI), across the XLA scan kernels, the
Pallas pair-list kernels (interpret mode), the fused engine, the KD
owner-computes mesh, and global-Morton — where the sketch-space send
gate may only SHRINK the boundary ring.  Plus the certified sandwich
itself, the resolution policy (d/4, min-d gate, cityblock off), and
construction-time spec validation.
"""

import functools
import os

import jax.numpy as jnp
import numpy as np
import pytest

from pypardis_tpu import DBSCAN
from pypardis_tpu.ops.labels import dbscan_fixed_size
from pypardis_tpu.ops.sketch import (
    SKETCH_MAX_K,
    SKETCH_MIN_K,
    auto_k,
    check_sketch_spec,
    jl_band,
    resolve_sketch,
    sketch_gate_band,
    sketch_matrix,
    sketch_slab,
)
from pypardis_tpu.parallel import default_mesh, staging

SIGMA = 0.5
MS = 5


@pytest.fixture(autouse=True)
def _fresh_staging():
    staging.clear()
    yield
    staging.clear()


def _noise_dominated(n, dim, n_centers=8, seed=0):
    """The sketch's target regime (scripts/sketch_probe.py geometry):
    equidistant centers on a scaled orthonormal latent basis + full-rank
    noise whose floor dominates every coordinate — axis-aligned tile
    boxes go blind while pairwise distances stay separated."""
    rng = np.random.default_rng(seed)
    eps = round(1.06 * SIGMA * np.sqrt(2.0 * dim), 2)
    basis = np.linalg.qr(rng.normal(size=(dim, n_centers)))[0]
    centers = (3.5 * eps / np.sqrt(2.0)) * basis.T
    truth = rng.integers(0, n_centers, size=n)
    X = centers[truth] + rng.normal(scale=SIGMA, size=(n, dim))
    return X.astype(np.float32), eps


# -- spec validation and resolution policy ------------------------------


def test_spec_validation():
    assert check_sketch_spec(None) is None
    assert check_sketch_spec("auto") == "auto"
    assert check_sketch_spec("off") == 0
    assert check_sketch_spec(0) == 0
    assert check_sketch_spec("32") == 32
    assert check_sketch_spec(np.int64(8)) == 8
    for bad in ("weird", -1, 1.5, True, [16]):
        with pytest.raises((ValueError, TypeError)):
            check_sketch_spec(bad)
    with pytest.raises(ValueError, match="sketch"):
        DBSCAN(eps=0.3, min_samples=5, sketch="sometimes")


def test_resolve_policy():
    # auto gates on dimensionality: off below SKETCH_MIN_D...
    assert resolve_sketch("auto", 64) == 0
    # ... and d/4 above it (the measured ratio — d/8 LOST on the
    # sketch's own target geometry, see ops/sketch.py:auto_k).
    assert resolve_sketch("auto", 512) == 512 // 4 == auto_k(512)
    # clamped to [SKETCH_MIN_K, SKETCH_MAX_K] ...
    assert auto_k(2048) == SKETCH_MAX_K
    assert auto_k(130) == max(SKETCH_MIN_K, 130 // 4)
    # ... and an explicit pin never exceeds d // 2 (the residual split
    # degenerates at k = d) but DOES apply below the auto min-d gate.
    assert resolve_sketch(500, 64) == 32
    assert resolve_sketch(16, 64) == 16
    # squared-euclidean discipline only.
    assert resolve_sketch("auto", 512, metric="cityblock") == 0
    assert resolve_sketch(64, 512, metric="cityblock") == 0
    assert resolve_sketch(0, 512) == 0


def test_resolve_min_d_env(monkeypatch):
    monkeypatch.setenv("PYPARDIS_SKETCH_MIN_D", "32")
    assert resolve_sketch("auto", 64) == SKETCH_MIN_K


# -- the projection matrix and the certified sandwich -------------------


def test_matrix_deterministic_and_orthonormal():
    q, eta = sketch_matrix(256, 64, seed=7)
    assert q.shape == (256, 64) and q.dtype == np.float32
    # f32 QR output: defect far below the gate band's 4*eta*s^2 term
    # ever mattering on unit-scale frames.
    assert eta < 1e-4
    gram = q.astype(np.float64).T @ q.astype(np.float64)
    np.testing.assert_allclose(gram, np.eye(64), atol=1e-5)
    q2, eta2 = sketch_matrix(256, 64, seed=7)
    assert q2 is q and eta2 == eta  # lru-cached trace-time constant
    q3, _ = sketch_matrix(256, 64, seed=8)
    assert not np.array_equal(q, q3)


def test_gate_sandwich_certified():
    """t2 <= d2 <= t2 + 4 rx ry, within the certified band, on random
    high-d data — the inequality the kernels' verdicts stand on."""
    rng = np.random.default_rng(0)
    d, k, n = 384, 96, 256
    X = rng.normal(size=(n, d)).astype(np.float32)
    q, eta = sketch_matrix(d, k)
    slab = np.asarray(sketch_slab(jnp.asarray(X.T), q))
    assert slab.shape == (k + 1, n)
    nmax = float(np.linalg.norm(X, axis=1).max())
    band = float(sketch_gate_band(jnp.float32(nmax), d, k, eta))
    i = rng.integers(0, n, size=500)
    j = rng.integers(0, n, size=500)
    d2 = np.sum((X[i] - X[j]) ** 2, axis=1, dtype=np.float64)
    t2 = np.sum(
        (slab[:, i] - slab[:, j]) ** 2, axis=0, dtype=np.float64
    )
    spread = 4.0 * slab[k, i].astype(np.float64) * slab[k, j]
    assert np.all(t2 <= d2 + band)
    assert np.all(d2 <= t2 + spread + band)


def test_jl_band_is_predictive_only_and_monotone():
    assert jl_band(64) > jl_band(256)
    assert jl_band(64, delta=0.1) < jl_band(64, delta=0.001)


# -- kernel-level byte parity -------------------------------------------


def _counts(X, eps, block=128, **kw):
    from pypardis_tpu.ops.distances import neighbor_counts
    from pypardis_tpu.partition import spatial_order
    from pypardis_tpu.utils import round_up

    X = X[spatial_order(X - X.mean(axis=0))]
    cap = round_up(len(X), block)
    pts = np.zeros((cap, X.shape[1]), np.float32)
    pts[: len(X)] = X
    mask = jnp.arange(cap) < len(X)
    return neighbor_counts(
        jnp.asarray(pts), eps, mask, block=block, **kw
    )


def test_counts_byte_parity_across_widths():
    X, eps = _noise_dominated(768, 256)
    ref = np.asarray(_counts(X, eps, sketch=0))
    assert ref.max() >= MS  # the geometry actually clusters
    for sk in (16, 64, "auto"):
        counts, stats = _counts(X, eps, sketch=sk)
        np.testing.assert_array_equal(ref, np.asarray(counts), str(sk))
        band_pairs, rescored = [int(v) for v in np.asarray(stats)]
        # Shared-cluster tiles are in-band by construction (every true
        # neighbor pair is), so the rescore path must actually fire —
        # parity with zero rescores would mean the gate never ran.
        assert band_pairs > 0 and rescored > 0, str(sk)


def test_counts_byte_parity_mixed_precision():
    """sketch composes with precision='mixed': the sketch gate decides
    WHERE full-d arithmetic runs, mixed decides HOW — counts stay
    byte-identical to the plain exact pass."""
    X, eps = _noise_dominated(768, 256, seed=1)
    ref = np.asarray(_counts(X, eps, sketch=0))
    counts, _ = _counts(X, eps, sketch="auto", precision="mixed")
    np.testing.assert_array_equal(ref, np.asarray(counts))


def test_fixed_size_backend_parity(monkeypatch):
    """dbscan_fixed_size sketch on/off parity on the XLA kernels AND
    the Pallas pair-list kernels (interpret mode — CPU CI's view of
    the Mosaic twins)."""
    from pypardis_tpu.ops import pallas_kernels as pk

    X, eps = _noise_dominated(512, 160, seed=2)
    cap = 512
    pts = np.zeros((cap, X.shape[1]), np.float32)
    pts[: len(X)] = X - X.mean(axis=0)
    mask = jnp.arange(cap) < len(X)

    def fit(backend, sketch):
        out = dbscan_fixed_size(
            jnp.asarray(pts), eps, MS, jnp.asarray(mask), block=128,
            backend=backend, sketch=sketch,
        )
        return [np.asarray(o) for o in out]

    l_ref, c_ref, _ = fit("xla", 0)
    assert l_ref.max() >= 0
    l_on, c_on, ps_on = fit("xla", "auto")
    np.testing.assert_array_equal(l_ref, l_on)
    np.testing.assert_array_equal(c_ref, c_on)
    assert ps_on[3] > 0  # sketch-band pairs counted in the stats slab

    monkeypatch.setattr(
        pk, "neighbor_counts_pallas",
        functools.partial(pk.neighbor_counts_pallas, interpret=True),
    )
    monkeypatch.setattr(
        pk, "min_neighbor_label_pallas",
        functools.partial(pk.min_neighbor_label_pallas, interpret=True),
    )
    for sketch in (0, "auto"):
        l_p, c_p, _ = fit("pallas", sketch)
        np.testing.assert_array_equal(l_ref, l_p, str(sketch))
        np.testing.assert_array_equal(c_ref, c_p, str(sketch))


# -- driver-level byte parity + telemetry -------------------------------


def _route_kw():
    return (
        ("fused", dict(mesh=default_mesh(1))),
        ("kd", dict(mesh=default_mesh(8), max_partitions=8)),
        ("global_morton", dict(mesh=default_mesh(8),
                               mode="global_morton")),
    )


def test_routes_sketch_on_off_byte_parity():
    X, eps = _noise_dominated(1024, 160, seed=3)
    for route, extra in _route_kw():
        fits = {}
        for sk in (0, "auto"):
            staging.clear()
            m = DBSCAN(eps=eps, min_samples=MS, block=128,
                       sketch=sk, **extra)
            m.fit(X)
            fits[sk] = m
        np.testing.assert_array_equal(
            np.asarray(fits[0].labels_),
            np.asarray(fits["auto"].labels_), err_msg=route,
        )
        np.testing.assert_array_equal(
            np.asarray(fits[0].core_sample_mask_),
            np.asarray(fits["auto"].core_sample_mask_), err_msg=route,
        )
        comp_on = fits["auto"].report()["compute"]
        assert comp_on["sketch_k"] == auto_k(160), route
        assert fits[0].report()["compute"]["sketch_k"] == 0, route


def test_global_morton_boundary_ring_only_shrinks():
    """The sketch-space send gate ANDs with the full-d box test, so
    the GM boundary ring can only get SMALLER — and with sketch off
    the box twins equal the primary stats exactly."""
    X, eps = _noise_dominated(1024, 160, seed=4)
    kw = dict(eps=eps, min_samples=MS, block=128,
              mesh=default_mesh(8), mode="global_morton")
    staging.clear()
    m_off = DBSCAN(sketch=0, **kw)
    m_off.fit(X)
    sh = m_off.report()["sharding"]
    assert sh["sent_tiles"] == sh["sent_tiles_box"]
    assert sh["boundary_tile_bytes"] == sh["boundary_bytes_box"]

    staging.clear()
    m_on = DBSCAN(sketch="auto", **kw)
    m_on.fit(X)
    sh = m_on.report()["sharding"]
    assert sh["sent_tiles"] <= sh["sent_tiles_box"]
    assert sh["boundary_tile_bytes"] <= sh["boundary_bytes_box"]
    np.testing.assert_array_equal(
        np.asarray(m_off.labels_), np.asarray(m_on.labels_)
    )


def test_env_knob_resolves_like_constructor(monkeypatch):
    """PYPARDIS_SKETCH is the knob's env spelling; the constructor pin
    wins over it and restores the token after the fit."""
    import jax

    monkeypatch.setenv("PYPARDIS_SKETCH", "0")
    X, eps = _noise_dominated(512, 160, seed=5)
    jax.clear_caches()  # trace-time read, like PYPARDIS_DISPATCH
    try:
        m = DBSCAN(eps=eps, min_samples=MS, block=128, sketch="auto",
                   mesh=default_mesh(1))
        m.fit(X)
        assert m.report()["compute"]["sketch_k"] == auto_k(160)
        assert os.environ["PYPARDIS_SKETCH"] == "0"  # token restored
    finally:
        jax.clear_caches()
