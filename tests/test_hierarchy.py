"""Density hierarchy over the cached pair graph (ISSUE 18).

One distance pass at a data-derived ceiling materializes the
neighbor-pair graph; per-point core distances, the mutual-reachability
MST (Borůvka rounds), and the condensed dendrogram with HDBSCAN*'s
excess-of-mass stability rule turn it into the ENTIRE continuous
clustering family.  The correctness bar:

* ``DBSCAN(eps=None).fit(X)`` labels byte-identical to a solo
  ``fit(eps_)`` at the stability-selected eps, deterministic across
  repeated fits and across fused/KD/global-Morton (min-core-gid canon);
* every rung of the ``sweep(eps_list="auto")`` ladder byte-identical to
  an independent ``fit(eps)`` at that config, on both kernel backends;
* MST weights equal a scipy ``minimum_spanning_tree`` oracle on the
  truncated mutual-reachability matrix;
* degenerate geometries (duplicates, all-noise, single cluster) and the
  jitted core-distance twin's bitwise parity with the host pass.
"""

import numpy as np
import pytest
from sklearn.datasets import make_blobs

from pypardis_tpu import DBSCAN
from pypardis_tpu.ops import densify_labels
from pypardis_tpu.ops.distances import neighbor_pair_graph_host
from pypardis_tpu.ops.hierarchy import (
    build_hierarchy,
    core_distances,
    core_distances_device,
    hierarchy_prepare,
    mutual_reachability_mst,
    thr_from_user_eps,
    user_eps_from_thr,
)
from pypardis_tpu.parallel import default_mesh

MS = 5


@pytest.fixture(scope="module")
def blobs():
    X, _ = make_blobs(
        n_samples=1200, centers=5, n_features=3, cluster_std=0.3,
        random_state=3,
    )
    return X


def _canon(labels, core):
    from pypardis_tpu.parallel.sharded import _canonicalize_roots

    return densify_labels(
        _canonicalize_roots(np.asarray(labels), np.asarray(core))
    )


def _solo(X, eps, ms, **kw):
    m = DBSCAN(eps=eps, min_samples=ms, **kw)
    m.fit(X)
    return np.asarray(m.labels_), np.asarray(m.core_sample_mask_)


def _graph_state(X, eps_max, ms, block=128):
    """The ops-level harness: padded host pair graph + prepared state."""
    n, d = X.shape
    cap = -(-n // block) * block
    P = np.zeros((cap, d), np.float32)
    P[:n] = X
    mask = np.zeros(cap, bool)
    mask[:n] = True
    gi, gj, dv, _ = neighbor_pair_graph_host(
        P, mask, eps_max, metric="euclidean", block=block
    )
    state = hierarchy_prepare(gi, gj, dv)
    return state, mask, cap


# -- eps=None fits ------------------------------------------------------


def test_eps_none_fit_selects_stable_cut(blobs):
    m = DBSCAN(eps=None, min_samples=MS, block=128, mesh=default_mesh(1))
    m.fit(blobs)
    assert m.eps_ is not None and m.eps_ > 0
    assert m.eps is None  # the constructor spec survives the fit
    # Labels byte-identical to a solo fit at the selected eps.
    ref_l, ref_c = _solo(blobs, m.eps_, MS, block=128,
                         mesh=default_mesh(1))
    np.testing.assert_array_equal(m.labels_, ref_l)
    np.testing.assert_array_equal(np.asarray(m.core_sample_mask_), ref_c)
    h = m.report()["hierarchy"]
    assert h["distance_passes"] == 1
    assert h["boruvka_rounds"] <= h["round_cap"]
    assert h["mst_edges"] > 0 and h["condensed_clusters"] >= 1
    assert h["selected_clusters"] >= 1
    assert h["eps_selected"] == m.eps_
    assert 0 < h["eps_selected"] <= h["eps_max"] * (1 + 1e-6)
    assert "hierarchy" in m.summary()


def test_eps_none_determinism_across_fits(blobs):
    a = DBSCAN(eps=None, min_samples=MS, block=128).fit(blobs)
    b = DBSCAN(eps=None, min_samples=MS, block=128).fit(blobs)
    assert a.eps_ == b.eps_
    np.testing.assert_array_equal(a.labels_, b.labels_)
    np.testing.assert_array_equal(
        np.asarray(a.core_sample_mask_), np.asarray(b.core_sample_mask_)
    )


def test_eps_none_across_modes(blobs):
    """fused vs KD vs global-Morton: same selected eps, canon-identical
    labels (min-core-gid), each at one distance pass."""
    runs = {}
    for tag, kw in (
        ("fused", dict(mesh=default_mesh(1))),
        ("kd", dict(mesh=default_mesh(8))),
        ("gm", dict(mesh=default_mesh(8), mode="global_morton")),
    ):
        m = DBSCAN(eps=None, min_samples=MS, block=128, **kw)
        m.fit(blobs)
        h = m.report()["hierarchy"]
        assert h["distance_passes"] == 1, tag
        assert h["boruvka_rounds"] <= h["round_cap"], tag
        runs[tag] = (m.eps_, _canon(m.labels_, m.core_sample_mask_))
    eps0, canon0 = runs["fused"]
    for tag, (e, c) in runs.items():
        assert e == eps0, tag
        np.testing.assert_array_equal(c, canon0, err_msg=tag)


def test_eps_none_serving_uses_selected_eps(blobs):
    """predict/serving against an eps=None model runs at the
    stability-selected ``eps_`` (the validate.py contract)."""
    m = DBSCAN(eps=None, min_samples=MS, block=128).fit(blobs)
    pred = m.predict(np.asarray(blobs[:32], np.float64))
    np.testing.assert_array_equal(np.asarray(pred), m.labels_[:32])
    assert m.kernel_eps == np.float32(m.eps_)


def test_min_cluster_size_controls_condensation(blobs):
    """A larger min_cluster_size prunes the condensed tree — never
    more condensed clusters than the default, same one-pass cost."""
    small = DBSCAN(eps=None, min_samples=MS, block=128).fit(blobs)
    big = DBSCAN(
        eps=None, min_samples=MS, min_cluster_size=100, block=128
    ).fit(blobs)
    hs = small.report()["hierarchy"]
    hb = big.report()["hierarchy"]
    assert hb["condensed_clusters"] <= hs["condensed_clusters"]
    assert hb["distance_passes"] == 1
    # And the flat labels still match a solo fit at ITS selected eps.
    ref_l, _ = _solo(blobs, big.eps_, MS, block=128)
    np.testing.assert_array_equal(big.labels_, ref_l)


# -- the auto ladder ----------------------------------------------------


@pytest.mark.parametrize(
    "tag,kw",
    [
        ("fused", dict(mesh=None)),
        ("kd", dict(mesh="mesh8")),
        ("gm", dict(mesh="mesh8", mode="global_morton")),
    ],
)
def test_auto_ladder_rung_parity(blobs, tag, kw):
    """Every rung of the dendrogram-extracted eps ladder byte-identical
    to a solo fit(eps) on the same mode."""
    kw = dict(kw)
    kw["mesh"] = default_mesh(8) if kw["mesh"] == "mesh8" \
        else default_mesh(1)
    m = DBSCAN(eps=None, min_samples=MS, block=128, **kw)
    res = m.sweep(blobs, eps_list="auto")
    assert res.stats["distance_passes"] == 1
    assert res.stats["eps_source"] == "hierarchy_auto"
    ladder = res.stats["ladder"]
    assert ladder == sorted(ladder)
    assert len(res.configs) == len(ladder)
    for eps, ms in res.configs:
        ref_l, ref_c = _solo(blobs, eps, ms, block=128, **kw)
        np.testing.assert_array_equal(
            res.labels(eps, ms), ref_l, err_msg=f"{tag} eps={eps}"
        )
        np.testing.assert_array_equal(
            res.core(eps, ms), ref_c, err_msg=f"{tag} eps={eps}"
        )


@pytest.mark.parametrize("backend", ["xla", "pallas"])
def test_auto_ladder_kernel_backends(blobs, backend, monkeypatch):
    """The ladder rides the same cached graph under both kernel
    backends (pallas in interpret mode on the CPU mesh, the
    test_pallas.py convention)."""
    if backend == "pallas":
        import functools

        from pypardis_tpu.ops import pallas_kernels as pk

        monkeypatch.setattr(
            pk, "neighbor_counts_pallas",
            functools.partial(pk.neighbor_counts_pallas, interpret=True),
        )
        monkeypatch.setattr(
            pk, "min_neighbor_label_pallas",
            functools.partial(
                pk.min_neighbor_label_pallas, interpret=True
            ),
        )
    kw = dict(block=128, mesh=default_mesh(1), kernel_backend=backend)
    m = DBSCAN(eps=None, min_samples=MS, **kw)
    res = m.sweep(blobs, eps_list="auto")
    assert res.stats["distance_passes"] == 1
    for eps, ms in res.configs[:3]:
        ref_l, _ = _solo(blobs, eps, ms, **kw)
        np.testing.assert_array_equal(
            res.labels(eps, ms), ref_l, err_msg=f"{backend} eps={eps}"
        )


def test_auto_ladder_multi_min_samples(blobs):
    """min_samples_list x auto ladder: each (eps, ms) rung cuts the
    RIGHT ms's hierarchy (cd2 differs per ms) and matches a solo fit."""
    m = DBSCAN(eps=None, min_samples=MS, block=128)
    res = m.sweep(blobs, eps_list="auto", min_samples_list=[3, 8])
    assert {ms for _, ms in res.configs} == {3, 8}
    for eps, ms in res.configs:
        ref_l, _ = _solo(blobs, eps, ms, block=128)
        np.testing.assert_array_equal(
            res.labels(eps, ms), ref_l, err_msg=f"eps={eps} ms={ms}"
        )


def test_sweep_rejects_unknown_eps_string(blobs):
    with pytest.raises(ValueError):
        DBSCAN(eps=None, min_samples=MS).sweep(blobs, eps_list="all")


# -- MST oracle ---------------------------------------------------------


def test_mst_weights_match_scipy_oracle():
    """Borůvka over the pair slab == scipy minimum_spanning_tree on the
    dense mutual-reachability matrix truncated at the ceiling (same
    edge-weight multiset; total weight equal at f32 resolution)."""
    from scipy.sparse import csr_matrix
    from scipy.sparse.csgraph import minimum_spanning_tree

    rng = np.random.default_rng(7)
    X = np.concatenate([
        rng.normal(c, 0.25, size=(100, 3)) for c in
        ([0, 0, 0], [4, 0, 0], [0, 4, 0], [2, 2, 3])
    ]).astype(np.float32)
    n = len(X)
    eps_max = 1.2
    state, mask, cap = _graph_state(X, eps_max, MS)
    cd2 = core_distances(state, mask, MS)
    mi, mj, mw, info = mutual_reachability_mst(state, cd2, cap)
    assert info["boruvka_rounds"] <= info["round_cap"]
    assert info["mst_edges"] == info["n_live"] - info["n_components"]

    # Oracle: the dense mutual-reachability matrix over the SLAB's own
    # d2 entries (the kernels' exact f32 arithmetic — a numpy
    # recomputation differs in last-ulp accumulation order), truncated
    # at the ceiling like the cached family is.
    gi_s, gj_s, dv_s = state[0], state[1], state[2]
    w = np.zeros((n, n), np.float64)
    live = (
        np.isfinite(dv_s) & (gi_s != gj_s) & (gi_s < n) & (gj_s < n)
    )
    mre = np.maximum(
        dv_s[live], np.maximum(cd2[gi_s[live]], cd2[gj_s[live]])
    )
    keep = np.isfinite(mre)
    w[gi_s[live][keep], gj_s[live][keep]] = mre[keep]
    oracle = minimum_spanning_tree(csr_matrix(np.triu(w)))
    ow = np.sort(np.asarray(oracle[oracle.nonzero()]).ravel())
    got = np.sort(np.asarray(mw, np.float64))
    assert len(got) == len(ow)
    np.testing.assert_allclose(got, ow, rtol=1e-6)


def test_core_distances_device_twin_bitwise():
    """The jitted k-th-smallest segment reduction == the host pass,
    bitwise, across min_samples values."""
    import jax.numpy as jnp

    rng = np.random.default_rng(11)
    X = rng.normal(size=(300, 3)).astype(np.float32)
    state, mask, cap = _graph_state(X, 1.5, MS)
    gi_s, gj_s, dv_s = state[0], state[1], state[2]
    for ms in (1, 2, 5, 11):
        host = core_distances(state, mask, ms)
        dev = np.asarray(core_distances_device(
            jnp.asarray(gi_s), jnp.asarray(gj_s), jnp.asarray(dv_s),
            jnp.asarray(mask), ms,
        ))
        np.testing.assert_array_equal(host, dev, err_msg=f"ms={ms}")


def test_thr_user_eps_round_trip():
    """thr_from_user_eps and user_eps_from_thr replicate the engines'
    exact f32 framing, both directions, for every metric frame."""
    for frame, eps in (("euclidean", 0.37), ("cityblock", 0.52),
                       ("cosine", 0.02), ("haversine", 0.1)):
        thr = thr_from_user_eps(eps, frame)
        rt = user_eps_from_thr(thr, frame)
        assert thr_from_user_eps(rt, frame) == thr, frame


# -- degenerate geometries ----------------------------------------------


def test_duplicate_points_collapse_to_one_cluster():
    X = np.tile(np.array([[1.0, 2.0, 3.0]], np.float32), (64, 1))
    X = np.concatenate([X, np.tile([[9.0, 9.0, 9.0]], (64, 1))])
    m = DBSCAN(eps=None, min_samples=MS, block=128).fit(X)
    assert m.eps_ > 0
    lab = np.asarray(m.labels_)
    assert set(lab[:64]) == {lab[0]} and set(lab[64:]) == {lab[64]}
    ref_l, _ = _solo(X, m.eps_, MS, block=128)
    np.testing.assert_array_equal(lab, ref_l)


def test_all_noise_geometry(monkeypatch):
    """Points mutually farther than the (pinned) ceiling: everything
    noise, the fit still completes with a deterministic eps_.  The
    ceiling must be pinned — the adaptive sample-kNN heuristic scales
    past any spacing by construction (it is an overestimate)."""
    monkeypatch.setenv("PYPARDIS_HIER_EPS_MAX", "1.0")
    X = (np.arange(32, dtype=np.float32)[:, None] * 1000.0) * np.ones(
        (1, 3), np.float32
    )
    a = DBSCAN(eps=None, min_samples=MS, block=128).fit(X)
    b = DBSCAN(eps=None, min_samples=MS, block=128).fit(X)
    assert a.eps_ == b.eps_ and a.eps_ > 0
    assert (np.asarray(a.labels_) == -1).all()
    assert a.report()["hierarchy"]["mst_edges"] == 0
    ref_l, _ = _solo(X, a.eps_, MS, block=128)
    np.testing.assert_array_equal(a.labels_, ref_l)
    # An adaptive-ceiling fit on the same geometry chains everything
    # into one cluster instead — the truncated-family honesty caveat.
    monkeypatch.delenv("PYPARDIS_HIER_EPS_MAX")
    c = DBSCAN(eps=None, min_samples=MS, block=128).fit(X)
    ref_l, _ = _solo(X, c.eps_, MS, block=128)
    np.testing.assert_array_equal(c.labels_, ref_l)


def test_single_cluster_geometry():
    rng = np.random.default_rng(5)
    X = rng.normal(0, 0.1, size=(200, 3)).astype(np.float32)
    m = DBSCAN(eps=None, min_samples=MS, block=128).fit(X)
    lab = np.asarray(m.labels_)
    assert lab.max() == 0  # exactly one cluster
    h = m.report()["hierarchy"]
    assert h["mst_edges"] == 199  # n_live - 1, one component
    ref_l, _ = _solo(X, m.eps_, MS, block=128)
    np.testing.assert_array_equal(lab, ref_l)


# -- validation surface -------------------------------------------------


def test_eps_validation_rules():
    # eps=None legal at construction; concrete invalids still fail.
    DBSCAN(eps=None)
    with pytest.raises(ValueError):
        DBSCAN(eps=0.0)
    with pytest.raises(ValueError):
        DBSCAN(eps=-1.0)
    with pytest.raises(ValueError):
        DBSCAN(eps=float("nan"))
    with pytest.raises(ValueError):
        DBSCAN(eps=float("inf"))
    with pytest.raises(ValueError):
        DBSCAN(eps=None, min_cluster_size=1)
    # An unfitted eps=None model has no radius to serve at.
    m = DBSCAN(eps=None)
    with pytest.raises(RuntimeError):
        _ = m.kernel_eps
    from pypardis_tpu.utils.validate import validate_params

    with pytest.raises(ValueError):
        validate_params(None, 5)  # downstream call sites stay strict
    validate_params(None, 5, allow_none_eps=True)


def test_eps_none_rejects_resume_and_empty():
    m = DBSCAN(eps=None, min_samples=MS)
    with pytest.raises(ValueError):
        m.train(np.zeros((0, 3), np.float32))
    with pytest.raises(ValueError):
        m.train(np.ones((16, 3), np.float32), resume="ckpt.npz")


def test_hier_env_ceiling_override(blobs, monkeypatch):
    """PYPARDIS_HIER_EPS_MAX pins the graph ceiling (user frame); the
    selected eps never exceeds it and labels stay solo-fit-exact."""
    monkeypatch.setenv("PYPARDIS_HIER_EPS_MAX", "0.9")
    m = DBSCAN(eps=None, min_samples=MS, block=128).fit(blobs)
    h = m.report()["hierarchy"]
    assert h["eps_max"] == pytest.approx(0.9)
    assert m.eps_ <= 0.9 * (1 + 1e-6)
    ref_l, _ = _solo(blobs, m.eps_, MS, block=128)
    np.testing.assert_array_equal(m.labels_, ref_l)


def test_hier_ladder_k_env(blobs, monkeypatch):
    monkeypatch.setenv("PYPARDIS_HIER_LADDER_K", "3")
    m = DBSCAN(eps=None, min_samples=MS, block=128)
    res = m.sweep(blobs, eps_list="auto")
    assert len(res.stats["ladder"]) <= 3


# -- ops-level hierarchy invariants -------------------------------------


def test_labels_at_thr_matches_host_engine(blobs):
    """Dendrogram cuts at arbitrary thresholds == the host relabel
    engine over the same graph — the backbone identity."""
    from pypardis_tpu.ops.labels import graph_dbscan_host

    X = np.asarray(blobs, np.float32)
    eps_max = 1.2
    state, mask, cap = _graph_state(X, eps_max, MS)
    thr_max = float(np.float32(eps_max) ** 2)
    hier = build_hierarchy(
        state, mask, cap, MS, kernel_metric="euclidean",
        user_frame="euclidean", thr_max=thr_max,
    )
    for eps in (0.2, 0.35, 0.5, 0.8, 1.1):
        thr = float(np.float32(eps) ** 2)
        lab, core = hier.labels_at_thr(thr)
        ref_lab, ref_core, _passes = graph_dbscan_host(
            state, mask, eps, MS, metric="euclidean"
        )
        np.testing.assert_array_equal(lab, ref_lab, err_msg=str(eps))
        np.testing.assert_array_equal(core, ref_core, err_msg=str(eps))
