"""Disk-backed (memmap) ingest: the streaming per-device shard build.

Round-4 review, Next #8 — the honest single-host analogue of the
reference's Spark premise (data larger than one worker,
/root/reference/README.md:60): an ``np.memmap`` clusters from disk with
per-device slab assembly, never holding the dataset as anonymous host
memory.
"""

import numpy as np
import pytest
from sklearn.datasets import make_blobs

from pypardis_tpu import DBSCAN
from pypardis_tpu.parallel import default_mesh, sharded_dbscan
from pypardis_tpu.partition import KDPartitioner


@pytest.fixture
def mm_blobs(tmp_path):
    X, _ = make_blobs(
        n_samples=20_000, centers=12, n_features=4, cluster_std=0.3,
        random_state=3,
    )
    X = X.astype(np.float32)
    path = tmp_path / "pts.f32"
    mm = np.memmap(path, dtype=np.float32, mode="w+", shape=X.shape)
    mm[:] = X
    mm.flush()
    ro = np.memmap(path, dtype=np.float32, mode="r", shape=X.shape)
    return ro, X


def test_streaming_build_matches_in_ram(mm_blobs):
    mm, X = mm_blobs
    mesh = default_mesh(8)
    part = KDPartitioner(X, max_partitions=8)
    ref, ref_core, _ = sharded_dbscan(
        X, part, eps=0.4, min_samples=5, block=128, mesh=mesh, halo="ring",
    )
    labels, core, stats = sharded_dbscan(
        mm, part, eps=0.4, min_samples=5, block=128, mesh=mesh,
        halo="ring",
    )
    assert stats.get("input") == "stream"  # auto-enabled for memmap
    np.testing.assert_array_equal(labels, ref)
    np.testing.assert_array_equal(core, ref_core)


def test_streaming_explicit_flag_and_host_halo_rejected(mm_blobs):
    mm, X = mm_blobs
    mesh = default_mesh(8)
    part = KDPartitioner(X, max_partitions=8)
    # explicit stream on an in-RAM array works too
    labels, _core, stats = sharded_dbscan(
        X, part, eps=0.4, min_samples=5, block=128, mesh=mesh,
        halo="ring", stream=True,
    )
    assert stats.get("input") == "stream"
    with pytest.raises(ValueError, match="halo='ring'"):
        sharded_dbscan(
            X, part, eps=0.4, min_samples=5, block=128, mesh=mesh,
            halo="host", stream=True,
        )


def test_streaming_host_merge_spill(mm_blobs):
    """memmap ingest composes with the >MERGE_HOST_AUTO host-merge
    spill: ring exchange on device, compact tables to the host."""
    mm, X = mm_blobs
    mesh = default_mesh(8)
    part = KDPartitioner(X, max_partitions=8)
    ref, _c, _s = sharded_dbscan(
        X, part, eps=0.4, min_samples=5, block=128, mesh=mesh, halo="ring",
    )
    labels, _core, stats = sharded_dbscan(
        mm, part, eps=0.4, min_samples=5, block=128, mesh=mesh,
        halo="ring", merge="host",
    )
    assert stats.get("input") == "stream" and stats.get("merge") == "host"
    np.testing.assert_array_equal(labels, ref)


def test_gm_stream_composition_200k(tmp_path):
    """ISSUE 10 satellite: the 100M path's PLUMBING at CI scale —
    a 200k x 16-D disk-backed memmap fits through the streaming
    global-Morton engine on the 8-device mesh (multi-bucket external
    sample-sort forced via a tiny bucket budget) with labels
    byte-identical to the in-RAM global-Morton fit.  Every PR
    exercises the north-star composition, not only hardware runs."""
    import os

    from benchdata import make_blob_data
    from pypardis_tpu.parallel import staging
    from pypardis_tpu.parallel.global_morton import global_morton_dbscan

    X, _truth = make_blob_data(200_000, 16)
    kw = dict(eps=2.4, min_samples=10, block=256)
    mesh = default_mesh(8)
    staging.clear()
    ref, ref_core, ref_stats = global_morton_dbscan(X, mesh=mesh, **kw)
    staging.clear()
    path = tmp_path / "ns.f32"
    mm = np.memmap(path, dtype=np.float32, mode="w+", shape=X.shape)
    mm[:] = X
    mm.flush()
    ro = np.memmap(path, dtype=np.float32, mode="r", shape=X.shape)
    os.environ["PYPARDIS_STREAM_BUCKET_MB"] = "4"
    try:
        labels, core, stats = global_morton_dbscan(ro, mesh=mesh, **kw)
    finally:
        del os.environ["PYPARDIS_STREAM_BUCKET_MB"]
    assert stats["input"] == "stream"
    assert stats["stream_buckets"] > 1  # real external bucketing ran
    assert stats["duplicated_work_factor"] == 1.0
    assert stats["halo_exchange"] == "morton_ring"
    np.testing.assert_array_equal(labels, ref)
    np.testing.assert_array_equal(core, ref_core)
    # Same slab geometry as the in-RAM build — the layouts (not just
    # the labels) are interchangeable, so staging/layout caches and
    # compiled programs are shared between the two builders.
    assert stats["owned_cap"] == ref_stats["owned_cap"]
    assert stats["partition_sizes"] == ref_stats["partition_sizes"]
    staging.clear()


def test_dbscan_fit_memmap_routes_streaming(mm_blobs):
    mm, X = mm_blobs
    ref = DBSCAN(eps=0.4, min_samples=5, block=128).fit_predict(X)
    m = DBSCAN(eps=0.4, min_samples=5, block=128)
    labels = m.fit_predict(mm)
    assert m.metrics_.get("input") == "stream"
    from sklearn.metrics import adjusted_rand_score

    assert adjusted_rand_score(labels, ref) >= 0.999
