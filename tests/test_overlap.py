"""Double-buffered chained execution (ISSUE 3 tentpole, prong 2).

The overlapped 1-device chained route builds + ships partition i+1's
slabs while the device executes partition i.  The contract: labels are
BYTE-IDENTICAL with overlap on vs off (the overlap changes scheduling,
never values), the rotating staging buffers can never serve a stale or
in-flight-mutated slab, and the loop reports its ``overlap_efficiency``
gauge.
"""

import numpy as np
import pytest
from sklearn.datasets import make_blobs

from pypardis_tpu.parallel import default_mesh, sharded_dbscan
from pypardis_tpu.parallel import staging
from pypardis_tpu.partition import KDPartitioner


@pytest.fixture(autouse=True)
def _fresh_staging():
    staging.clear()
    yield
    staging.clear()


@pytest.fixture()
def data():
    X, _ = make_blobs(
        n_samples=4000, centers=10, n_features=3, cluster_std=0.3,
        random_state=5,
    )
    return X.astype(np.float32)


KW = dict(eps=0.4, min_samples=5, block=64)


@pytest.mark.parametrize("merge", ["device", "host"])
def test_overlap_labels_byte_identical(data, merge):
    """Chained-route labels with overlap on == off == the 8-device
    fused program, on both merge modes."""
    part = KDPartitioner(data, max_partitions=8)
    ref, ref_core, s_ref = sharded_dbscan(
        data, part, mesh=default_mesh(8), merge=merge, **KW
    )
    mesh1 = default_mesh(1)
    staging.clear()
    l_off, c_off, s_off = sharded_dbscan(
        data, part, mesh=mesh1, merge=merge, overlap=False, **KW
    )
    staging.clear()
    l_on, c_on, s_on = sharded_dbscan(
        data, part, mesh=mesh1, merge=merge, overlap=True, **KW
    )
    np.testing.assert_array_equal(l_on, l_off)
    np.testing.assert_array_equal(c_on, c_off)
    np.testing.assert_array_equal(l_on, ref)
    # The overlapped run measured its chained loop; the others ran none.
    assert 0.0 < s_on["overlap_efficiency"] <= 1.0
    assert "overlap_efficiency" not in s_off


def test_overlap_warm_refit_reuses_chained_slabs(data):
    """Warm refits serve the per-partition device slabs from the
    staging cache; an eps sweep re-ships only the (eps-keyed) halos."""
    part = KDPartitioner(data, max_partitions=8)
    mesh1 = default_mesh(1)
    l1, _c, s1 = sharded_dbscan(data, part, mesh=mesh1, overlap=True, **KW)
    assert s1["staged_bytes_reused"] == 0 and s1["staged_bytes"] > 0
    l2, _c, s2 = sharded_dbscan(data, part, mesh=mesh1, overlap=True, **KW)
    assert s2["staged_bytes"] == 0
    assert s2["staged_bytes_reused"] == s1["staged_bytes"]
    np.testing.assert_array_equal(l1, l2)
    kw = dict(KW, eps=0.5)
    _l, _c, s3 = sharded_dbscan(data, part, mesh=mesh1, overlap=True, **kw)
    assert s3["staged_bytes_reused"] > 0  # owned slabs from cache
    assert s3["staged_bytes"] > 0  # halos re-shipped


def test_overlap_mutation_safety(data):
    """The rotating pooled buffers and the device slab cache never
    serve stale bytes: mutate the input in place between overlapped
    fits and the second fit must match a cold fit of the new data."""
    X = np.array(data)
    mesh1 = default_mesh(1)
    part1 = KDPartitioner(X, max_partitions=8)
    l1, _c, _s = sharded_dbscan(X, part1, mesh=mesh1, overlap=True, **KW)
    X[:500] += 50.0  # in place — same array object, same shapes
    part2 = KDPartitioner(X, max_partitions=8)
    l2, _c2, s2 = sharded_dbscan(X, part2, mesh=mesh1, overlap=True, **KW)
    assert s2["staged_bytes_reused"] == 0  # content fingerprint missed
    staging.clear()
    ref, _rc, _rs = sharded_dbscan(
        X, part2, mesh=mesh1, overlap=False, **KW
    )
    np.testing.assert_array_equal(l2, ref)
    assert not np.array_equal(l1, l2)


def test_overlap_pool_rotation_across_fits(data):
    """Back-to-back overlapped fits of DIFFERENT datasets reuse the
    host slab pool (the borrow/return pairs) — results must follow the
    data, never the buffer history."""
    mesh1 = default_mesh(1)
    X2 = data + np.float32(25.0)
    part1 = KDPartitioner(data, max_partitions=8)
    part2 = KDPartitioner(X2, max_partitions=8)
    sharded_dbscan(data, part1, mesh=mesh1, overlap=True, **KW)
    l2, _c, _s = sharded_dbscan(X2, part2, mesh=mesh1, overlap=True, **KW)
    staging.clear()
    ref, _rc, _rs = sharded_dbscan(
        X2, part2, mesh=mesh1, overlap=False, **KW
    )
    np.testing.assert_array_equal(l2, ref)


def test_overlap_env_kill_switch(data, monkeypatch):
    monkeypatch.setenv("PYPARDIS_CHAINED_OVERLAP", "0")
    part = KDPartitioner(data, max_partitions=8)
    _l, _c, stats = sharded_dbscan(
        data, part, mesh=default_mesh(1), **KW
    )
    assert "overlap_efficiency" not in stats
