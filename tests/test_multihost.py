"""Pod-scale execution (pypardis_tpu.parallel.dist, ISSUE 20).

Cheap tier-1 coverage of the multi-process seams — the single-process
degenerate forms of the collectives (every host-stepped loop calls
them unconditionally), the launcher's failure-signature classifiers
and its retry loop (driven by tiny stub workers, no jax), the
per-rank flight naming, the fleet clock-skew flag, and the env-knob /
fault-site registrations — plus ``slow``-marked real-fleet tests that
reuse ``scripts/multihost_probe.py``'s worker body: 2-process fit
parity against this harness's in-process 8-device mesh and the
shared-store streaming build's byte parity.
"""

import json
import os
import socket
import sys
import tempfile

import numpy as np
import pytest

from pypardis_tpu.parallel import dist
from pypardis_tpu.utils import envreg, faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

import multihost_probe  # noqa: E402  (scripts/ shim above)

PROBE = os.path.join(REPO, "scripts", "multihost_probe.py")


# ---------------------------------------------------------------------------
# single-process degenerate forms (tier-1: every fit crosses these)
# ---------------------------------------------------------------------------


def test_single_process_identity():
    assert not dist.is_distributed()
    assert dist.is_coordinator()
    assert dist.process_count() == 1
    assert dist.process_index() == 0


def test_fetch_np_single_process_is_asarray():
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from pypardis_tpu.parallel.mesh import default_mesh

    x = np.arange(24, dtype=np.float32).reshape(8, 3)
    staged = jax.device_put(
        x, NamedSharding(default_mesh(), PartitionSpec("p"))
    )
    np.testing.assert_array_equal(dist.fetch_np(staged), x)
    np.testing.assert_array_equal(dist.fetch_np(x), x)


def test_broadcast_single_process_roundtrip():
    assert dist.broadcast_bytes(b"abc") == b"abc"
    assert dist.broadcast_str("sp/ill") == "sp/ill"
    arrs = [np.arange(5), np.eye(2, dtype=np.float32)]
    out = dist.broadcast_arrays(arrs)
    assert len(out) == 2
    for a, b in zip(arrs, out):
        np.testing.assert_array_equal(a, b)
        assert a.dtype == b.dtype
    dist.barrier("test.noop")  # no fleet: must be a no-op, not a hang


# ---------------------------------------------------------------------------
# launcher plumbing: ports, env, failure-signature classifiers
# ---------------------------------------------------------------------------


def test_pick_port_is_bindable():
    port = dist.pick_port()
    assert 0 < port < 65536
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        s.bind(("127.0.0.1", port))  # just vacated: still free
    finally:
        s.close()


def test_fleet_env_knobs():
    env = dist.fleet_env(12345, 2, 1, 4, base={})
    assert env["PYPARDIS_DIST_COORD"] == "127.0.0.1:12345"
    assert env["PYPARDIS_DIST_NPROCS"] == "2"
    assert env["PYPARDIS_DIST_PROC_ID"] == "1"
    assert env["JAX_PLATFORMS"] == "cpu"
    assert "host_platform_device_count=4" in env["XLA_FLAGS"]


def test_failure_signature_classifiers():
    assert dist._looks_like_bind_collision(
        "E0000 ... Address already in use"
    )
    assert not dist._looks_like_bind_collision("Segmentation fault")
    # transport abort: SIGABRT (-6) AND a gloo marker, jointly
    assert dist._looks_like_transport_abort(
        [-6, 0], ["gloo::EnforceNotMet: op.preamble.length", ""]
    )
    assert dist._looks_like_transport_abort(
        [0, -6], ["", "Connection reset by peer"]
    )
    # a SIGKILL'd worker (fault drill) must NEVER look like transport
    assert not dist._looks_like_transport_abort(
        [-9, -9], ["gloo::EnforceNotMet", ""]
    )
    # an abort without wire markers is a real bug, not a flake
    assert not dist._looks_like_transport_abort(
        [-6], ["assertion failed"]
    )


def _stub_argv(body: str):
    return [sys.executable, "-c", body]


def test_launch_fleet_retries_bind_collision():
    rcs, _port, attempts, tails = dist.launch_fleet(
        _stub_argv(
            "import sys; sys.stderr.write('Failed to bind'); "
            "sys.exit(1)"
        ),
        2, 1, retries=2, timeout_s=60,
    )
    assert rcs == [1, 1]
    assert attempts == 3  # initial + 2 retries, then reported
    assert all("Failed to bind" in t for t in tails)


def test_launch_fleet_retries_simultaneous_transport_abort():
    # BOTH ranks SIGABRT inside one poll window — the regression that
    # used to skip the retry (the early-failure flag was never set
    # when nobody was left alive).
    rcs, _port, attempts, _tails = dist.launch_fleet(
        _stub_argv(
            "import os, signal, sys; "
            "sys.stderr.write('gloo::EnforceNotMet: preamble'); "
            "sys.stderr.flush(); "
            "os.kill(os.getpid(), signal.SIGABRT)"
        ),
        2, 1, retries=1, timeout_s=60,
    )
    assert rcs == [-6, -6]
    assert attempts == 2


def test_launch_fleet_no_retry_on_real_failures():
    # A Python error is a bug: report it once, never relaunch.
    rcs, _port, attempts, tails = dist.launch_fleet(
        _stub_argv("import sys; sys.stderr.write('boom'); sys.exit(3)"),
        2, 1, retries=3, timeout_s=60,
    )
    assert rcs == [3, 3] and attempts == 1
    # A pinned port disables retry even for a bind signature: the
    # caller asked for THAT port, a fresh one would not be it.
    rcs, port, attempts, _ = dist.launch_fleet(
        _stub_argv(
            "import sys; sys.stderr.write('Failed to bind'); "
            "sys.exit(1)"
        ),
        2, 1, port=45678, retries=3, timeout_s=60,
    )
    assert rcs == [1, 1] and attempts == 1 and port == 45678


def test_launch_fleet_success_and_teardown():
    with tempfile.TemporaryDirectory() as d:
        out = os.path.join(d, "r")
        rcs, _port, attempts, _ = dist.launch_fleet(
            _stub_argv(
                "import os; "
                "open(r'%s' + os.environ['PYPARDIS_DIST_PROC_ID'], "
                "'w').write(os.environ['PYPARDIS_DIST_NPROCS'])" % out
            ),
            2, 1, retries=0, timeout_s=60,
        )
        assert rcs == [0, 0] and attempts == 1
        for pid in range(2):
            with open(f"{out}{pid}") as f:
                assert f.read() == "2"


# ---------------------------------------------------------------------------
# registrations + per-rank surfaces
# ---------------------------------------------------------------------------


def test_env_knobs_registered():
    for name in ("PYPARDIS_DIST_COORD", "PYPARDIS_DIST_NPROCS",
                 "PYPARDIS_DIST_PROC_ID", "PYPARDIS_SPILL_DIR",
                 "PYPARDIS_FLEET_SKEW_WARN_S"):
        assert name in envreg.REGISTRY, name


def test_dist_worker_fault_site_known():
    assert "dist.worker" in faults.KNOWN_SITES


def test_open_flight_rank_suffix(tmp_path, monkeypatch):
    from pypardis_tpu.obs import flight as flight_mod

    monkeypatch.setattr(dist, "is_distributed", lambda: True)
    monkeypatch.setattr(dist, "process_index", lambda: 2)
    rec = flight_mod.open_flight(str(tmp_path / "fit.jsonl"))
    rec.close()
    assert (tmp_path / "fit.p02.jsonl").exists()
    rec = flight_mod.open_flight(str(tmp_path / "d"))
    rec.close()
    names = os.listdir(tmp_path / "d")
    assert len(names) == 1 and names[0].startswith("flight-r02-")


def _write_flight(path, t_unix):
    lines = [
        {"k": "header", "schema": "pypardis_tpu/flight@1",
         "pid": 1, "t_unix": t_unix},
        {"k": "so", "id": 0, "name": "fit", "t": 0.01, "depth": 0,
         "a": {}},
        {"k": "sc", "id": 0, "name": "fit", "t": 0.01, "dur": 0.1,
         "a": {}},
        {"k": "fin", "status": "ok", "t": 0.2},
    ]
    path.write_text(
        "\n".join(json.dumps(r) for r in lines) + "\n", encoding="utf-8"
    )


def test_fleet_clock_skew_flag(tmp_path, monkeypatch):
    from pypardis_tpu import obs

    _write_flight(tmp_path / "flight-a.jsonl", 1000.0)
    _write_flight(tmp_path / "flight-b.jsonl", 1010.0)
    rep = obs.replay(str(tmp_path)).report()
    assert rep["clock_skew_s"] == pytest.approx(10.0)
    assert rep["clock_skew_warning"] is True  # default threshold 5s
    monkeypatch.setenv("PYPARDIS_FLEET_SKEW_WARN_S", "30")
    rep = obs.replay(str(tmp_path)).report()
    assert rep["clock_skew_warning"] is False
    summary = obs.replay(str(tmp_path)).summary()
    assert "WARNING" not in summary


# ---------------------------------------------------------------------------
# real localhost fleets (slow: spawn jax.distributed worker processes)
# ---------------------------------------------------------------------------


def _run_fleet(task, out_base, n_procs, dev_per_proc, env_extra=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO] + [p for p in [env.get("PYTHONPATH")] if p]
    )
    env.pop("XLA_FLAGS", None)  # fleet_env sets the workers' own
    env.update(env_extra or {})
    return dist.launch_fleet(
        [sys.executable, PROBE, "--worker", task, out_base],
        n_procs, dev_per_proc, env=env, timeout_s=600,
    )


@pytest.mark.slow
def test_fleet_fit_parity_both_merges():
    """2 processes x 4 devices must land byte-identical to THIS
    harness's in-process 8-device mesh — global-Morton under both
    merges, plus the KD route."""
    from pypardis_tpu import DBSCAN

    n = 1500
    X = multihost_probe.chain_data(n)
    ref = {}
    for mode, merge in (("global_morton", "device"),
                        ("global_morton", "host"), ("kd", "device")):
        m = DBSCAN(mode=mode, merge=merge, **multihost_probe.KW)
        m.fit(X)
        ref[f"{mode}.{merge}"] = (
            np.asarray(m.labels_), np.asarray(m.core_sample_mask_),
        )
    with tempfile.TemporaryDirectory() as d:
        base = os.path.join(d, "fits")
        rcs, _port, _attempts, tails = _run_fleet(
            "fits", base, 2, 4, env_extra={"MH_N": str(n)}
        )
        assert rcs == [0, 0], tails
        for rank in range(2):
            with np.load(f"{base}.p{rank:02d}.npz") as z:
                for key, (labels, core) in ref.items():
                    np.testing.assert_array_equal(
                        z[f"labels_{key}"], labels, err_msg=key
                    )
                    np.testing.assert_array_equal(
                        z[f"core_{key}"], core, err_msg=key
                    )


@pytest.mark.slow
def test_fleet_2x2_matches_single_process_1x4():
    """The ISSUE-20 pinned geometry: 2 processes x 2 devices vs ONE
    process x 4 devices — same global device count, byte-identical
    labels, both merges + KD.  Both runs are subprocess fleets (this
    harness's own mesh is 8-wide), compared file-to-file."""
    n = 1500
    with tempfile.TemporaryDirectory() as d:
        solo, duo = os.path.join(d, "solo"), os.path.join(d, "duo")
        rcs, _p, _a, tails = _run_fleet(
            "fits", solo, 1, 4, env_extra={"MH_N": str(n)}
        )
        assert rcs == [0], tails
        rcs, _p, _a, tails = _run_fleet(
            "fits", duo, 2, 2, env_extra={"MH_N": str(n)}
        )
        assert rcs == [0, 0], tails
        with np.load(f"{solo}.p00.npz") as ref:
            for rank in range(2):
                with np.load(f"{duo}.p{rank:02d}.npz") as z:
                    for key in ref.files:
                        np.testing.assert_array_equal(
                            z[key], ref[key], err_msg=key
                        )


@pytest.mark.slow
def test_fleet_streaming_build_parity():
    """The shared-store external sort partitioned across 2 processes
    reproduces the solo build byte for byte."""
    from pypardis_tpu.partition import morton_range_split_streaming

    n = 8000
    SX = multihost_probe.stream_data(n)
    sp = morton_range_split_streaming(SX, 4, **multihost_probe.STREAM_KW)
    solo_ids, _ = sp.row_span(0, sp.n)
    with tempfile.TemporaryDirectory() as d:
        base = os.path.join(d, "stream")
        rcs, _port, _attempts, tails = _run_fleet(
            "stream", base, 2, 4, env_extra={"MH_STREAM_N": str(n)}
        )
        assert rcs == [0, 0], tails
        for rank in range(2):
            with np.load(f"{base}.p{rank:02d}.npz") as z:
                np.testing.assert_array_equal(z["starts"], sp.starts)
                np.testing.assert_array_equal(z["center"], sp.center)
                np.testing.assert_array_equal(z["tlo"], sp.tile_lo)
                np.testing.assert_array_equal(z["thi"], sp.tile_hi)
                np.testing.assert_array_equal(z["ids"], solo_ids)
    sp.close()
