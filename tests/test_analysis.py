"""graftlint (ISSUE 15): paired fire/pass fixtures per rule,
suppression parsing, baseline round-trip, the whole-repo zero-findings
gate, the < 10s runtime gate, and env-registry/README sync.

Fixture runs build a minimal tmp repo (the real ``envreg.py`` /
``faults.py`` copied in, plus the snippet under test at a controlled
relative path) so rule scoping by path works without touching the real
tree.  Deleting any rule's implementation makes its "must fire" test
here fail — that is the acceptance contract.
"""

import json
import os
import shutil
import subprocess
import sys
import textwrap

import pytest

from pypardis_tpu import analysis
from pypardis_tpu.analysis import baseline as baseline_mod
from pypardis_tpu.analysis import envmodel
from pypardis_tpu.utils import envreg, faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_repo(tmp_path, files, copy_registries=True):
    """A minimal lintable tree: registries + the snippet files."""
    root = str(tmp_path)
    os.makedirs(os.path.join(root, "pypardis_tpu", "utils"),
                exist_ok=True)
    if copy_registries:
        for rel in ("pypardis_tpu/utils/envreg.py",
                    "pypardis_tpu/utils/faults.py"):
            shutil.copyfile(os.path.join(REPO, rel),
                            os.path.join(root, rel))
    paths = []
    for rel, text in files.items():
        p = os.path.join(root, rel)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        with open(p, "w", encoding="utf-8") as f:
            f.write(textwrap.dedent(text))
        paths.append(p)
    return root, paths


def lint(tmp_path, files, **kw):
    root, paths = make_repo(tmp_path, files)
    return analysis.run_lint(root, paths=paths, **kw)


def rules_of(result):
    return [f.rule for f in result.findings]


# -- whole-repo gate ---------------------------------------------------


@pytest.fixture(scope="module")
def repo_result():
    return analysis.run_lint(REPO)


def test_whole_repo_zero_findings(repo_result):
    assert repo_result.findings == [], "\n".join(
        f"{f.location()}: [{f.rule}] {f.message}"
        for f in repo_result.findings
    )


def test_whole_repo_runtime_gate(repo_result):
    # The lint gate must never become the slow step of verify /
    # bench-smoke (ISSUE 15 satellite: < 10s on the CI container).
    assert repo_result.elapsed_s < 10.0, repo_result.elapsed_s
    assert repo_result.files > 80  # really scanned the repo


def test_rule_registry_complete():
    assert set(analysis.RULE_REGISTRY) == {
        "module-jnp-constant", "device-put-aliasing",
        "trace-env-read", "env-registry", "seal-f32",
        "fault-site", "magic-width", "unused-import",
    }


# -- R1 module-jnp-constant --------------------------------------------


def test_r1_fires_on_module_jnp_constant(tmp_path):
    r = lint(tmp_path, {"pypardis_tpu/mod.py": """
        import jax.numpy as jnp
        _ZERO = jnp.int32(0)
    """}, rules=["module-jnp-constant"])
    assert rules_of(r) == ["module-jnp-constant"]


def test_r1_passes_numpy_and_inert(tmp_path):
    r = lint(tmp_path, {"pypardis_tpu/mod.py": """
        import jax.numpy as jnp
        import numpy as np
        _ZERO = np.int32(0)
        _INT_INF = jnp.iinfo(jnp.int32).max
        def f():
            return jnp.int32(0)  # function scope traces lazily
    """}, rules=["module-jnp-constant"])
    assert r.findings == []


# -- R2 device-put-aliasing --------------------------------------------


def test_r2_fires_on_bare_device_put(tmp_path):
    r = lint(tmp_path, {"pypardis_tpu/mod.py": """
        import jax
        def ship(a, dev):
            return jax.device_put(a, dev)
    """}, rules=["device-put-aliasing"])
    assert rules_of(r) == ["device-put-aliasing"]


def test_r2_passes_transfer_wrap_and_give_back(tmp_path):
    r = lint(tmp_path, {"pypardis_tpu/mod.py": """
        import jax
        from .parallel import staging
        def ship(a, dev):
            return staging.transfer(lambda: jax.device_put(a, dev))
        def build(bufs, a, dev):
            out = jax.device_put(a, dev)
            staging.give_back_after_put(bufs)
            return out
    """}, rules=["device-put-aliasing"])
    assert r.findings == []


# -- R3 trace-env-read -------------------------------------------------


def test_r3_fires_via_call_graph(tmp_path):
    r = lint(tmp_path, {"pypardis_tpu/mod.py": """
        import os
        import jax
        def helper():
            return os.environ.get("PYPARDIS_DISPATCH", "auto")
        @jax.jit
        def kernel(x):
            mode = helper()
            return x
    """}, rules=["trace-env-read"])
    assert rules_of(r) == ["trace-env-read"]


def test_r3_passes_envreg_and_host_reads(tmp_path):
    r = lint(tmp_path, {"pypardis_tpu/mod.py": """
        import os
        import jax
        from .utils import envreg
        def helper():
            return envreg.raw("PYPARDIS_DISPATCH", "auto")
        @jax.jit
        def kernel(x):
            mode = helper()
            return x
        def host_only():
            return os.environ.get("PYPARDIS_CKPT")
    """}, rules=["trace-env-read"])
    assert r.findings == []


def test_r3_jit_wrap_call_marks_root(tmp_path):
    # `step = jax.jit(body)` (no decorator) must still mark `body`.
    r = lint(tmp_path, {"pypardis_tpu/mod.py": """
        import os
        import jax
        def body(x):
            flag = os.environ.get("PYPARDIS_GM_OVERLAP", "1")
            return x
        step = jax.jit(body)
    """}, rules=["trace-env-read"])
    assert rules_of(r) == ["trace-env-read"]


# -- R4 env-registry ---------------------------------------------------


def test_r4_fires_on_unregistered_name_with_hint(tmp_path):
    r = lint(tmp_path, {"pypardis_tpu/mod.py": """
        import os
        FLAG = os.environ.get("PYPARDIS_DISPACH")
    """}, rules=["env-registry"])
    assert rules_of(r) == ["env-registry"]
    assert "PYPARDIS_DISPATCH" in r.findings[0].message  # near-miss


def test_r4_passes_registered_and_prefix_refs(tmp_path):
    r = lint(tmp_path, {"pypardis_tpu/mod.py": '''
        import os
        """Docs may reference the PYPARDIS_COMPACT_* watermarks."""
        FLAG = os.environ.get("PYPARDIS_DISPATCH", "auto")
    '''}, rules=["env-registry"])
    assert r.findings == []


def test_r4_scratch_file_fails_lint(tmp_path):
    # The ISSUE acceptance gate: an unregistered PYPARDIS_TYPO literal
    # in a scratch file makes `scripts/graftlint.py <file>` exit 1.
    scratch = tmp_path / "scratch.py"
    scratch.write_text('X = "PYPARDIS_TYPO"\n')
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "graftlint.py"),
         str(scratch)],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "PYPARDIS_TYPO" in proc.stdout
    assert "env-registry" in proc.stdout


def test_cli_clean_run_exits_zero():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "graftlint.py")],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "graftlint: ok" in proc.stdout


# -- R5 seal-f32 -------------------------------------------------------


def test_r5_fires_on_unsealed_accumulate(tmp_path):
    r = lint(tmp_path, {"pypardis_tpu/ops/query.py": """
        def accum(q, c, acc):
            diff = q - c
            return acc + diff * diff
    """}, rules=["seal-f32"])
    assert rules_of(r) == ["seal-f32"]


def test_r5_passes_sealed_and_out_of_scope(tmp_path):
    r = lint(tmp_path, {
        "pypardis_tpu/ops/query.py": """
            def seal_f32(x, z):
                return x
            def accum(q, c, acc, z):
                diff = q - c
                e = q  # standalone square below has no add target
                eps2 = e * e
                return acc + seal_f32(diff * diff, z)
        """,
        # same pattern OUTSIDE the oracle-exact scope: legal
        "pypardis_tpu/ops/other.py": """
            def accum(q, c, acc):
                diff = q - c
                return acc + diff * diff
        """,
    }, rules=["seal-f32"])
    assert r.findings == []


# -- R6 fault-site -----------------------------------------------------


def test_r6_fires_on_unregistered_site(tmp_path):
    r = lint(tmp_path, {"pypardis_tpu/mod.py": """
        from .utils import faults
        def go():
            faults.maybe_fail("gm.exchagne")
    """}, rules=["fault-site"])
    assert rules_of(r) == ["fault-site"]
    assert "gm.exchange" in r.findings[0].message  # near-miss hint


def test_r6_passes_registered_sites_and_plan_specs(tmp_path):
    r = lint(tmp_path, {"pypardis_tpu/mod.py": """
        from .utils import faults
        def go():
            faults.maybe_fail("gm.exchange")
            with faults.plan("staging.device_put:1=oom"):
                pass
    """}, rules=["fault-site"])
    assert r.findings == []


def test_r6_unused_registration_fires_on_full_run(tmp_path):
    root, _ = make_repo(tmp_path, {
        "pypardis_tpu/utils/faults.py": """
            KNOWN_SITES = ("site.used", "site.never_used")
            def maybe_fail(site):
                pass
        """,
        "pypardis_tpu/mod.py": """
            from .utils import faults
            def go():
                faults.maybe_fail("site.used")
        """,
    }, copy_registries=False)
    shutil.copyfile(
        os.path.join(REPO, "pypardis_tpu/utils/envreg.py"),
        os.path.join(root, "pypardis_tpu/utils/envreg.py"),
    )
    r = analysis.run_lint(root, rules=["fault-site"])  # full fileset
    assert rules_of(r) == ["fault-site"]
    assert "site.never_used" in r.findings[0].message


# -- R6 magic-width ----------------------------------------------------


def test_r6_magic_width_fires(tmp_path):
    r = lint(tmp_path, {"pypardis_tpu/ops/pipeline.py": """
        import numpy as np
        def unpack(packed):
            return packed[:-5], int(packed[-5])
        def empty_stats():
            pair_stats = np.zeros((1, 5), np.int32)
            return pair_stats
    """}, rules=["magic-width"])
    assert rules_of(r) == ["magic-width"] * 3


def test_r6_magic_width_passes_symbolic_width(tmp_path):
    r = lint(tmp_path, {"pypardis_tpu/ops/pipeline.py": """
        import numpy as np
        W = 5  # PAIR_STATS_WIDTH imported in real code
        def unpack(packed):
            return packed[:-W], tuple(packed[-W:])
        def empty_stats():
            pair_stats = np.zeros((1, W), np.int32)
            return pair_stats
        def tree_rows(tree):
            return np.asarray(tree).reshape(-1, 5)  # not stats
    """}, rules=["magic-width"])
    assert r.findings == []


# -- R7 unused-import --------------------------------------------------


def test_r7_fires_in_package_notes_in_scripts(tmp_path):
    r = lint(tmp_path, {
        "pypardis_tpu/mod.py": """
            import os
            import json
            def f():
                return os.getcwd()
        """,
        "scripts/probe.py": """
            import json
            print("hi")
        """,
    }, rules=["unused-import"])
    assert rules_of(r) == ["unused-import"]
    assert r.findings[0].path.endswith("pypardis_tpu/mod.py")
    assert [n.rule for n in r.notes] == ["unused-import"]  # scripts


def test_r7_suppressible_for_side_effect_imports(tmp_path):
    r = lint(tmp_path, {"pypardis_tpu/mod.py": """
        # graftlint: disable=unused-import -- imported for side effect
        import json
        print("hi")
    """}, rules=["unused-import"])
    assert r.findings == []
    assert r.suppressed == 1


# -- suppressions ------------------------------------------------------


def test_suppression_requires_reason(tmp_path):
    r = lint(tmp_path, {"pypardis_tpu/mod.py": """
        import jax
        def ship(a, dev):
            # graftlint: disable=device-put-aliasing
            return jax.device_put(a, dev)
    """}, rules=["device-put-aliasing"])
    # reasonless directive: flagged itself AND suppresses nothing
    assert sorted(rules_of(r)) == [
        "bad-suppression", "device-put-aliasing",
    ]


def test_suppression_with_reason_spans_comment_block(tmp_path):
    r = lint(tmp_path, {"pypardis_tpu/mod.py": """
        import jax
        def ship(a, dev):
            # graftlint: disable=device-put-aliasing -- fresh array,
            # reason continues on a second comment line
            return jax.device_put(a, dev)
    """}, rules=["device-put-aliasing"])
    assert r.findings == []
    assert r.suppressed == 1


def test_suppression_unknown_rule_flagged(tmp_path):
    r = lint(tmp_path, {"pypardis_tpu/mod.py": """
        # graftlint: disable=no-such-rule -- whatever
        X = 1
    """})
    assert "bad-suppression" in rules_of(r)


# -- baseline ----------------------------------------------------------


def test_baseline_round_trip(tmp_path):
    files = {"pypardis_tpu/mod.py": """
        import jax
        def ship(a, dev):
            return jax.device_put(a, dev)
    """}
    root, paths = make_repo(tmp_path, files)
    r1 = analysis.run_lint(root, paths=paths,
                           rules=["device-put-aliasing"])
    assert len(r1.findings) == 1
    bl = os.path.join(root, "baseline.json")
    baseline_mod.write(bl, r1.raw_pairs)
    data = json.load(open(bl))
    assert data["format"] == "graftlint_baseline@1"
    assert len(data["entries"]) == 1
    r2 = analysis.run_lint(root, paths=paths,
                           rules=["device-put-aliasing"],
                           baseline_path=bl)
    assert r2.findings == []
    assert r2.baselined == 1


def test_committed_baseline_is_empty():
    data = json.load(open(os.path.join(
        REPO, "scripts", "graftlint_baseline.json"
    )))
    assert data["format"] == "graftlint_baseline@1"
    assert data["entries"] == []  # zero-entry: nothing grandfathered


# -- env registry / docs sync ------------------------------------------


def test_static_render_matches_runtime_render():
    static = envmodel.parse_env_registry(REPO).render_markdown()
    assert static == envreg.render_markdown()


def test_readme_env_table_in_sync():
    text = open(os.path.join(REPO, "README.md")).read()
    from pypardis_tpu.analysis.rules_env import (
        ENVDOCS_BEGIN, ENVDOCS_END,
    )
    begin = text.find(ENVDOCS_BEGIN)
    end = text.find(ENVDOCS_END)
    assert 0 < begin < end
    committed = text[begin + len(ENVDOCS_BEGIN):end].strip("\n")
    assert committed == envreg.render_markdown().strip("\n")


def test_every_repo_env_var_is_registered_and_rendered():
    # Belt and braces over the R4 rule: regex the tree ourselves.
    import re

    pat = re.compile(r"PYPARDIS_[A-Z0-9_]*[A-Z0-9]")
    names = set()
    for base in ("pypardis_tpu", "scripts", "tests"):
        for dirpath, dirnames, filenames in os.walk(
            os.path.join(REPO, base)
        ):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in filenames:
                if not fn.endswith(".py"):
                    continue
                text = open(os.path.join(dirpath, fn)).read()
                for m in pat.finditer(text):
                    tail = text[m.end():m.end() + 2]
                    if tail[:1] == "*" or tail == "_*":
                        continue  # prefix reference
                    names.add(m.group(0))
    names.discard("PYPARDIS_TYPO")   # this file's acceptance fixture
    names.discard("PYPARDIS_DISPACH")  # this file's typo fixture
    registered = set(envreg.declared_names())
    assert names <= registered, sorted(names - registered)
    table = envreg.render_markdown()
    for name in registered:
        assert f"`{name}`" in table


def test_envreg_raw_rejects_unregistered():
    with pytest.raises(envreg.UnregisteredEnvVar):
        envreg.raw("PYPARDIS_TYPO")


def test_known_sites_match_faults_module():
    sites, _ = envmodel.parse_fault_sites(REPO)
    assert sites == faults.KNOWN_SITES


def test_envdocs_cli_emits_table():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "graftlint.py"),
         "--envdocs"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0
    assert proc.stdout == envreg.render_markdown()
