"""Pallas kernel parity vs the XLA tiled path (interpreter mode on CPU).

The Pallas kernels compile with Mosaic only on real TPUs; CI runs them
through the Pallas interpreter, which executes the same kernel body —
including the scalar-prefetch pair-list grid and the first-visit output
accumulation — with identical semantics.  Pairs whose distance sits
within float ulps of eps can legitimately flip between the two paths
(different matmul accumulation orders), so the comparison data keeps a
guard band around eps.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from pypardis_tpu.ops.distances import min_neighbor_label, neighbor_counts
from pypardis_tpu.ops.pallas_kernels import (
    min_neighbor_label_pallas,
    neighbor_counts_pallas,
)
from pypardis_tpu.partition import spatial_order

INT_INF = np.iinfo(np.int32).max


@pytest.fixture(scope="module")
def blob_data():
    rng = np.random.default_rng(7)
    n, d = 2048, 8
    centers = rng.uniform(-10, 10, size=(8, d))
    X = (
        centers[rng.integers(0, 8, size=n)]
        + rng.normal(scale=0.3, size=(n, d))
    ).astype(np.float32)
    X = X[spatial_order(X)]
    mask = np.ones(n, bool)
    mask[-77:] = False
    return jnp.asarray(X), jnp.asarray(mask)


def test_counts_match_xla(blob_data):
    pts, mask = blob_data
    c_x = np.asarray(
        neighbor_counts(pts, 2.0, mask, block=256, precision="highest")
    )
    c_p = np.asarray(
        neighbor_counts_pallas(
            pts, 2.0, mask, block=256, precision="highest", interpret=True
        )
    )
    assert np.array_equal(c_x, c_p)


def test_minlab_match_xla(blob_data):
    pts, mask = blob_data
    c = np.asarray(
        neighbor_counts(pts, 2.0, mask, block=256, precision="highest")
    )
    core = jnp.asarray((c >= 8) & np.asarray(mask))
    lab = jnp.where(
        core, jnp.arange(pts.shape[0], dtype=jnp.int32), INT_INF
    )
    m_x = np.asarray(
        min_neighbor_label(
            pts, lab, 2.0, core, block=256, precision="highest",
            row_mask=mask,
        )
    )
    m_p = np.asarray(
        min_neighbor_label_pallas(
            pts, lab, 2.0, core, block=256, precision="highest",
            interpret=True, row_mask=mask,
        )
    )
    valid = np.asarray(mask)
    assert np.array_equal(m_x[valid], m_p[valid])


def test_counts_high_precision_band(blob_data):
    """The default bf16_3x mode ('high') through the interpreter: counts
    must match the HIGHEST oracle within a small band (data keeps a guard
    band around eps, but bf16_3x error scales with tile-box magnitude, so
    allow isolated single-neighbor flips rather than exact equality)."""
    pts, mask = blob_data
    c_ref = np.asarray(
        neighbor_counts(pts, 2.0, mask, block=256, precision="highest")
    )
    c_hi = np.asarray(
        neighbor_counts_pallas(
            pts, 2.0, mask, block=256, precision="high", interpret=True
        )
    )
    diff = np.abs(c_hi - c_ref)
    assert diff.max() <= 2
    assert (diff == 0).mean() > 0.99


def test_minlab_source_outside_row_mask(blob_data):
    """A source point excluded from row_mask must still donate its label
    (the shared coordinate array keeps real coordinates wherever either
    mask holds — regression for the src_mask-subset precondition)."""
    pts, _ = blob_data
    n = pts.shape[0]
    # Row mask excludes the first point; source mask includes ONLY it.
    row_mask = jnp.ones(n, bool).at[0].set(False)
    src_mask = jnp.zeros(n, bool).at[0].set(True)
    lab = jnp.full(n, INT_INF, jnp.int32).at[0].set(7)
    got = np.asarray(
        min_neighbor_label_pallas(
            pts, lab, 2.0, src_mask, block=256, precision="highest",
            interpret=True, row_mask=row_mask,
        )
    )
    want = np.asarray(
        min_neighbor_label(
            pts, lab, 2.0, src_mask, block=256, precision="highest",
            row_mask=row_mask,
        )
    )
    valid = np.asarray(row_mask)
    assert np.array_equal(got[valid], want[valid])
    # The excluded-row source must actually reach someone within eps.
    d2 = np.sum((np.asarray(pts) - np.asarray(pts)[0]) ** 2, axis=1)
    reachable = (d2 <= 4.0) & valid
    if reachable.any():
        assert (got[reachable] == 7).all()


def test_e2e_backend_pallas_interpret(blob_data, monkeypatch):
    """dbscan_fixed_size with backend='pallas' (kernels forced through the
    interpreter) must agree with backend='xla' labels end to end."""
    import functools

    from pypardis_tpu.ops import labels as labels_mod
    from pypardis_tpu.ops import pallas_kernels as pk
    from pypardis_tpu.ops.labels import dbscan_fixed_size

    pts, mask = blob_data
    l_x, core_x, _ = dbscan_fixed_size(
        pts, 2.0, 8, mask, block=256, backend="xla"
    )
    monkeypatch.setattr(
        pk,
        "neighbor_counts_pallas",
        functools.partial(pk.neighbor_counts_pallas, interpret=True),
    )
    monkeypatch.setattr(
        pk,
        "min_neighbor_label_pallas",
        functools.partial(pk.min_neighbor_label_pallas, interpret=True),
    )
    l_p, core_p, pair_stats = dbscan_fixed_size(
        pts, 2.0, 8, mask, block=256, backend="pallas"
    )
    total, budget, passes, band_pairs, rescored = np.asarray(pair_stats)
    assert (band_pairs, rescored) == (0, 0)  # non-mixed precision
    assert 0 < total <= budget
    assert passes >= 2  # the counts pass plus at least one minlab pass
    valid = np.asarray(mask)
    assert np.array_equal(np.asarray(l_x)[valid], np.asarray(l_p)[valid])
    assert np.array_equal(
        np.asarray(core_x)[valid], np.asarray(core_p)[valid]
    )


def test_owner_computes_pallas_pair_filtering(blob_data, monkeypatch):
    """The owner-computes kernels drive Pallas with FILTERED pair lists
    (owned-row subset for counts, halo-halo pairs dropped for the relay
    propagation, both re-sorted to row-major for `_first_visit`) —
    interpret-mode parity against the XLA kind on the same slab."""
    import functools

    from pypardis_tpu.ops import labels as lb
    from pypardis_tpu.ops import pallas_kernels as pk

    pts, mask = blob_data
    owned = 1536  # 6 of 8 tiles owned, 2 halo, at block 256
    monkeypatch.setattr(
        pk,
        "neighbor_counts_pallas",
        functools.partial(pk.neighbor_counts_pallas, interpret=True),
    )
    monkeypatch.setattr(
        pk,
        "min_neighbor_label_pallas",
        functools.partial(pk.min_neighbor_label_pallas, interpret=True),
    )
    kw = dict(owned=owned, metric="euclidean", block=256,
              precision="highest")
    pairs, stats = pk.kernel_pair_list(
        pts, 2.0, mask, 256, "highest", "nd"
    )
    assert int(stats[0]) <= int(stats[1])
    core_x = lb.oc_counts(pts, 2.0, 8, mask, kind="xla", pairs=None, **kw)
    core_p = lb.oc_counts(
        pts, 2.0, 8, mask, kind="pallas", pairs=pairs, **kw
    )
    assert core_x.shape == (owned,)
    assert np.array_equal(np.asarray(core_x), np.asarray(core_p))
    # Owner-supplied halo flags: the exact full-slab core test.
    full_counts = np.asarray(
        neighbor_counts(pts, 2.0, mask, block=256, precision="highest")
    )
    halo_core = jnp.asarray(
        (full_counts[owned:] >= 8) & np.asarray(mask)[owned:]
    )
    core_all = jnp.concatenate([core_x, halo_core])
    l_x, p_x = lb.oc_propagate(
        pts, 2.0, mask, core_all, kind="xla", pairs=None, **kw
    )
    l_p, p_p = lb.oc_propagate(
        pts, 2.0, mask, core_all, kind="pallas", pairs=pairs, **kw
    )
    valid = np.asarray(mask)
    assert np.array_equal(np.asarray(l_x)[valid], np.asarray(l_p)[valid])
    assert int(p_x) >= 1 and int(p_p) >= 1


def test_resolve_backend_rules():
    from pypardis_tpu.ops.labels import resolve_backend

    assert resolve_backend("auto", "cityblock", 10_000, 1024) == "xla"
    assert resolve_backend("auto", "euclidean", 1024, 1024) == "xla"
    # accepted euclidean spellings normalize before the comparison
    assert resolve_backend("auto", "l2", 1024, 1024) == resolve_backend(
        "auto", "euclidean", 1024, 1024
    )
    assert resolve_backend("xla", "euclidean") == "xla"
    assert resolve_backend("pallas", "euclidean") == "pallas"
    assert resolve_backend("pallas", "l2") == "pallas"
    with pytest.raises(ValueError):
        resolve_backend("bogus", "euclidean")
    with pytest.raises(ValueError):
        resolve_backend("pallas", "cityblock")
