"""Distributed path on the 8-device CPU mesh vs the sklearn oracle.

Invariants from SURVEY §4: every point gets exactly one global label;
core-connected points share a label regardless of partition count;
result invariant (on core points) to max_partitions in {1, 4, 16}.
"""

import numpy as np
import pytest
from sklearn.cluster import DBSCAN as SKDBSCAN
from sklearn.metrics import adjusted_rand_score

import jax

from pypardis_tpu import DBSCAN
from pypardis_tpu.parallel import default_mesh, sharded_dbscan
from pypardis_tpu.partition import KDPartitioner
from pypardis_tpu.ops.labels import densify_labels


def _oracle_check(X, labels, core, eps, min_samples):
    sk = SKDBSCAN(eps=eps, min_samples=min_samples).fit(X)
    sk_core = np.zeros(len(X), bool)
    sk_core[sk.core_sample_indices_] = True
    np.testing.assert_array_equal(core, sk_core)
    np.testing.assert_array_equal(labels == -1, sk.labels_ == -1)
    assert adjusted_rand_score(sk.labels_, labels) >= 0.99
    assert adjusted_rand_score(sk.labels_[sk_core], labels[sk_core]) == 1.0


def test_mesh_has_8_devices():
    assert default_mesh().devices.size == 8


def test_sharded_blobs_matches_sklearn(blobs750):
    eps, ms = 0.3, 10
    part = KDPartitioner(blobs750, max_partitions=8)
    labels, core, stats = sharded_dbscan(
        blobs750, part, eps=eps, min_samples=ms, block=128
    )
    assert stats["halo_factor"] > 0  # duplication actually happened
    _oracle_check(blobs750, densify_labels(labels), core, eps, ms)


def test_api_uses_sharded_path(blobs750):
    model = DBSCAN(eps=0.3, min_samples=10, block=128)
    labels = model.fit_predict(blobs750)
    assert model.metrics_["n_partitions"] == 8
    _oracle_check(blobs750, labels, model.core_sample_mask_, 0.3, 10)


@pytest.mark.parametrize("max_partitions", [8, 16])
def test_partition_count_invariance(max_partitions):
    rng = np.random.default_rng(7)
    X = np.concatenate(
        [
            rng.normal(loc=[0, 0], scale=0.15, size=(300, 2)),
            rng.normal(loc=[3, 3], scale=0.15, size=(300, 2)),
            rng.uniform(-2, 5, size=(60, 2)),
        ]
    )
    eps, ms = 0.25, 8
    model = DBSCAN(eps=eps, min_samples=ms, max_partitions=max_partitions,
                   block=128)
    labels = model.fit_predict(X)
    _oracle_check(X, labels, model.core_sample_mask_, eps, ms)


def test_cluster_spanning_many_partitions():
    # A single long dense chain must come back as ONE cluster even when
    # the KD tree slices it across every device (transitive merge).
    t = np.linspace(0, 20, 2000)
    X = np.stack([t, np.sin(t)], axis=1)
    rng = np.random.default_rng(8)
    X = X + rng.normal(scale=0.005, size=X.shape)
    model = DBSCAN(eps=0.2, min_samples=4, max_partitions=8, block=128)
    labels = model.fit_predict(X)
    assert (labels == labels[0]).all()
    assert labels[0] != -1


def test_every_point_exactly_one_label(blobs750):
    model = DBSCAN(eps=0.3, min_samples=10, block=128)
    labels = model.fit_predict(blobs750)
    assert labels.shape == (len(blobs750),)
    assert labels.dtype == np.int32


def test_single_device_mesh_chained_matches_mesh8():
    """A 1-device mesh with L>1 partitions chains per-partition cluster
    dispatches (watchdog/compile economy on tunneled deployments; the
    execution granularity of a real L=1-per-device pod) — labels must
    be byte-identical to the 8-device fused program, on every mode."""
    from sklearn.datasets import make_blobs

    X, _ = make_blobs(
        n_samples=4000, centers=10, n_features=3, cluster_std=0.3,
        random_state=5,
    )
    X = X.astype(np.float32)
    part = KDPartitioner(X, max_partitions=8)
    ref, ref_core, _ = sharded_dbscan(
        X, part, eps=0.4, min_samples=5, block=64, mesh=default_mesh(8),
    )
    mesh1 = default_mesh(1)
    for kwargs in (
        dict(),                      # host halo + device merge
        dict(halo="ring"),           # ring + device merge
        dict(merge="host"),          # host halo + host merge
        dict(halo="ring", merge="host"),  # ring + host-merge spill
    ):
        labels, core, _stats = sharded_dbscan(
            X, part, eps=0.4, min_samples=5, block=64, mesh=mesh1,
            **kwargs,
        )
        np.testing.assert_array_equal(labels, ref, err_msg=str(kwargs))
        np.testing.assert_array_equal(core, ref_core, err_msg=str(kwargs))
