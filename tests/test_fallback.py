"""Graceful degradation when a Pallas kernel cannot lower.

Round 2's regression mode: 'auto' routed TPU to a Pallas kernel that
Mosaic rejected, so the *default* path crashed with a compiler internals
dump.  The drivers now catch lowering failures and retry on the XLA
path with a warning — but only for 'auto'; an explicit
``backend='pallas'`` must stay strict so hardware smoke tests actually
exercise Mosaic.
"""

import numpy as np
import pytest


def _mosaic_error():
    return ValueError(
        "Mosaic failed to compile TPU kernel: Slice shape along dimension "
        "2 must be aligned to tiling (128), but is 16."
    )


def test_is_kernel_lowering_error_classification():
    from pypardis_tpu.ops.labels import is_kernel_lowering_error

    assert is_kernel_lowering_error(_mosaic_error())
    assert is_kernel_lowering_error(
        ValueError("The Pallas TPU lowering currently requires ...")
    )
    assert not is_kernel_lowering_error(ValueError("points must be (N, k)"))
    assert not is_kernel_lowering_error(RuntimeError("out of memory"))


def test_is_kernel_lowering_error_walks_cause_chain():
    from pypardis_tpu.ops.labels import is_kernel_lowering_error

    try:
        try:
            raise _mosaic_error()
        except ValueError as inner:
            raise RuntimeError("compile failed") from inner
    except RuntimeError as outer:
        assert is_kernel_lowering_error(outer)


def test_with_kernel_fallback_degrades_auto():
    from pypardis_tpu.parallel.sharded import _with_kernel_fallback

    calls = []

    def fn(be):
        calls.append(be)
        if be != "xla":
            raise _mosaic_error()
        return "ok"

    assert _with_kernel_fallback(fn, "auto") == "ok"
    assert calls == ["auto", "xla"]


def test_with_kernel_fallback_explicit_pallas_stays_strict():
    from pypardis_tpu.parallel.sharded import _with_kernel_fallback

    def fn(be):
        raise _mosaic_error()

    with pytest.raises(ValueError, match="Mosaic"):
        _with_kernel_fallback(fn, "pallas")


def test_with_kernel_fallback_unrelated_errors_propagate():
    from pypardis_tpu.parallel.sharded import _with_kernel_fallback

    def fn(be):
        raise RuntimeError("unrelated")

    with pytest.raises(RuntimeError, match="unrelated"):
        _with_kernel_fallback(fn, "auto")


def test_pad_and_run_falls_back_end_to_end(monkeypatch):
    """A broken-Pallas build degrades inside the public driver."""
    from pypardis_tpu import dbscan as dbscan_mod
    from pypardis_tpu.ops import pipeline as pipeline_mod

    real = pipeline_mod.dbscan_device_pipeline
    calls = []

    def flaky(points_t, eps, n, **kw):
        calls.append(kw["backend"])
        if kw["backend"] != "xla":
            raise _mosaic_error()
        return real(points_t, eps, n, **kw)

    monkeypatch.setattr(pipeline_mod, "dbscan_device_pipeline", flaky)
    rng = np.random.default_rng(0)
    X = rng.normal(size=(500, 4)).astype(np.float32)
    # _pad_and_run is the single-shard driver entry (the CI mesh routes
    # DBSCAN.fit to the sharded path, which has its own fallback test).
    roots, core, _kinfo = dbscan_mod._pad_and_run(X, 0.5, 5, "euclidean",
                                                  256)
    assert len(roots) == 500 and len(core) == 500
    assert calls == ["auto", "xla"]


def test_effective_tile_mosaic_legality():
    """Round-4 advisor (medium): configs whose tile cannot satisfy
    Mosaic's trailing-dim-multiple-of-128 constraint must be routed to
    XLA deliberately, not via a lowering-failure/fallback cycle."""
    from pypardis_tpu.ops.pallas_kernels import effective_tile

    # user block below 128: never Mosaic-legal
    assert effective_tile(64, 4000, 3) is None
    # n with no 128-multiple divisor: never Mosaic-legal
    assert effective_tile(1024, 4000, 3) is None
    # clean configs return a 128-multiple dividing n
    for block, n in [(1024, 4096), (256, 1024), (1024, 1 << 20)]:
        eff = effective_tile(block, n, 16)
        assert eff is not None and eff % 128 == 0 and n % eff == 0


def test_check_mosaic_tile_message_is_classified():
    """An explicit backend='pallas' with an illegal tile fails with a
    readable error that the fallback classifier still recognizes."""
    from pypardis_tpu.ops.labels import is_kernel_lowering_error
    from pypardis_tpu.ops.pallas_kernels import _check_mosaic_tile

    with pytest.raises(ValueError, match="multiple of 128"):
        _check_mosaic_tile(64, 4096, interpret=False)
    try:
        _check_mosaic_tile(64, 4096, interpret=False)
    except ValueError as e:
        assert is_kernel_lowering_error(e)
    # interpret mode (CPU tests) has no tiling constraint
    _check_mosaic_tile(64, 4096, interpret=True)


def test_xla_pair_count_grid_matches_pallas(monkeypatch):
    """Round-4 advisor (low): under DENSE dispatch the XLA path's pair
    totals must be computed on the SAME effective tile the Pallas
    extraction would use, so a dense-era budget hint seeded by one
    backend never over/undershoots the other's grid after a kernel
    fallback.  (The compacted default sizes budgets to the XLA grid
    instead — its hints key separately via utils.hints.dispatch_tag,
    which is why this pin holds only for PYPARDIS_DISPATCH=dense.)"""
    import jax
    import jax.numpy as jnp

    from pypardis_tpu.ops import distances
    from pypardis_tpu.ops.labels import dbscan_fixed_size
    from pypardis_tpu.ops.pallas_kernels import effective_tile

    monkeypatch.setenv("PYPARDIS_DISPATCH", "dense")
    jax.clear_caches()

    # Large d drives a VMEM-budget shrink in _pallas_block, so the
    # Pallas grid tile differs from the caller's raw block.
    n, d, block = 2048, 512, 1024
    eff = effective_tile(block, n, d)
    assert eff is not None and eff != block  # the grids would differ

    seen = []
    orig = distances.count_live_tile_pairs

    def spy(points, mask, eps, metric="euclidean", block=1024,
            layout="nd"):
        seen.append(block)
        return orig(points, mask, eps, metric=metric, block=block,
                    layout=layout)

    monkeypatch.setattr(distances, "count_live_tile_pairs", spy)
    # The spy only fires at TRACE time; drop any cached executable so
    # the test is order-independent within the process.
    dbscan_fixed_size.clear_cache()
    rng = np.random.default_rng(0)
    pts = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
    dbscan_fixed_size(
        pts, 0.3, 5, jnp.ones(n, bool), block=block, backend="xla"
    )
    assert seen == [eff]
