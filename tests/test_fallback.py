"""Graceful degradation when a Pallas kernel cannot lower.

Round 2's regression mode: 'auto' routed TPU to a Pallas kernel that
Mosaic rejected, so the *default* path crashed with a compiler internals
dump.  The drivers now catch lowering failures and retry on the XLA
path with a warning — but only for 'auto'; an explicit
``backend='pallas'`` must stay strict so hardware smoke tests actually
exercise Mosaic.
"""

import numpy as np
import pytest


def _mosaic_error():
    return ValueError(
        "Mosaic failed to compile TPU kernel: Slice shape along dimension "
        "2 must be aligned to tiling (128), but is 16."
    )


def test_is_kernel_lowering_error_classification():
    from pypardis_tpu.ops.labels import is_kernel_lowering_error

    assert is_kernel_lowering_error(_mosaic_error())
    assert is_kernel_lowering_error(
        ValueError("The Pallas TPU lowering currently requires ...")
    )
    assert not is_kernel_lowering_error(ValueError("points must be (N, k)"))
    assert not is_kernel_lowering_error(RuntimeError("out of memory"))


def test_is_kernel_lowering_error_walks_cause_chain():
    from pypardis_tpu.ops.labels import is_kernel_lowering_error

    try:
        try:
            raise _mosaic_error()
        except ValueError as inner:
            raise RuntimeError("compile failed") from inner
    except RuntimeError as outer:
        assert is_kernel_lowering_error(outer)


def test_with_kernel_fallback_degrades_auto():
    from pypardis_tpu.parallel.sharded import _with_kernel_fallback

    calls = []

    def fn(be):
        calls.append(be)
        if be != "xla":
            raise _mosaic_error()
        return "ok"

    assert _with_kernel_fallback(fn, "auto") == "ok"
    assert calls == ["auto", "xla"]


def test_with_kernel_fallback_explicit_pallas_stays_strict():
    from pypardis_tpu.parallel.sharded import _with_kernel_fallback

    def fn(be):
        raise _mosaic_error()

    with pytest.raises(ValueError, match="Mosaic"):
        _with_kernel_fallback(fn, "pallas")


def test_with_kernel_fallback_unrelated_errors_propagate():
    from pypardis_tpu.parallel.sharded import _with_kernel_fallback

    def fn(be):
        raise RuntimeError("unrelated")

    with pytest.raises(RuntimeError, match="unrelated"):
        _with_kernel_fallback(fn, "auto")


def test_pad_and_run_falls_back_end_to_end(monkeypatch):
    """A broken-Pallas build degrades inside the public driver."""
    from pypardis_tpu import dbscan as dbscan_mod
    from pypardis_tpu.ops import pipeline as pipeline_mod

    real = pipeline_mod.dbscan_device_pipeline
    calls = []

    def flaky(points_t, eps, n, **kw):
        calls.append(kw["backend"])
        if kw["backend"] != "xla":
            raise _mosaic_error()
        return real(points_t, eps, n, **kw)

    monkeypatch.setattr(pipeline_mod, "dbscan_device_pipeline", flaky)
    rng = np.random.default_rng(0)
    X = rng.normal(size=(500, 4)).astype(np.float32)
    # _pad_and_run is the single-shard driver entry (the CI mesh routes
    # DBSCAN.fit to the sharded path, which has its own fallback test).
    roots, core = dbscan_mod._pad_and_run(X, 0.5, 5, "euclidean", 256)
    assert len(roots) == 500 and len(core) == 500
    assert calls == ["auto", "xla"]
