"""Global-Morton distributed mode (ISSUE 5).

Shards are contiguous ranges of the global Morton order — zero
duplicated rows by construction — with boundary TILES riding the
ppermute ring and a host-stepped cross-device pmin fixpoint merge.
Labels must be byte-identical to the fused single-device engine AND to
the KD-halo family across both merge routes and 1/4/8-device CPU
meshes, including clusters spanning many shard boundaries (multi-hop
label propagation).
"""

import json

import numpy as np
import pytest
from sklearn.datasets import make_blobs

from pypardis_tpu import DBSCAN
from pypardis_tpu.ops.labels import densify_labels
from pypardis_tpu.parallel import default_mesh, sharded_dbscan
from pypardis_tpu.parallel.global_morton import global_morton_dbscan
from pypardis_tpu.partition import (
    KDPartitioner,
    MortonRangePartitioner,
    morton_range_split,
)

KW = dict(eps=0.4, min_samples=5, block=128)


def canon(labels, core):
    """Dense labels under the distributed family's canonical numbering
    (clusters keyed by their min core member, then densified).

    The raw 1-device fused path numbers clusters by their Morton-FIRST
    core point (kernel roots are min sorted-space indices mapped back
    through the permutation), while every sharded mode canonicalizes to
    the min core gid — identical clusterings, permuted dense ids.
    Canonicalizing both sides makes byte-comparison mean exactly
    "identical clustering"."""
    from pypardis_tpu.parallel.sharded import _canonicalize_roots

    return densify_labels(
        _canonicalize_roots(np.asarray(labels), np.asarray(core))
    )


@pytest.fixture(scope="module")
def blobs():
    X, _ = make_blobs(
        n_samples=2000, centers=6, n_features=3, cluster_std=0.3,
        random_state=3,
    )
    return X


@pytest.fixture(scope="module")
def fused(blobs):
    """The fused single-device engine's labels/core (canonical
    numbering) — the byte-parity reference for every distributed
    mode."""
    model = DBSCAN(mesh=default_mesh(1), **KW)
    model.fit(blobs)
    return canon(model.labels_, model.core_sample_mask_), np.asarray(
        model.core_sample_mask_
    )


def test_byte_parity_vs_fused_and_kd(blobs, fused):
    """global_morton labels byte-match the fused engine AND the KD
    owner-computes/legacy modes, on 1/4/8-device meshes, both merges."""
    ref, ref_core = fused
    part = KDPartitioner(blobs, max_partitions=8)
    mesh8 = default_mesh(8)
    for oc in (True, False):
        l_kd, c_kd, _ = sharded_dbscan(
            blobs, part, mesh=mesh8, owner_computes=oc, **KW
        )
        np.testing.assert_array_equal(
            densify_labels(l_kd), ref, err_msg=f"kd oc={oc}"
        )
    for n_dev in (1, 4, 8):
        mesh = default_mesh(n_dev)
        for merge in ("device", "host"):
            labels, core, stats = global_morton_dbscan(
                blobs, mesh=mesh, merge=merge, **KW
            )
            tag = f"gm {n_dev}dev merge={merge}"
            np.testing.assert_array_equal(
                densify_labels(labels), ref, err_msg=tag
            )
            np.testing.assert_array_equal(core, ref_core, err_msg=tag)
            assert stats["mode"] == "global_morton", tag
            assert stats["halo_exchange"] == "morton_ring", tag
            assert stats["duplicated_work_factor"] == 1.0, tag
            assert stats["owner_computes"] is True, tag
            assert stats["merge"] == merge, tag


def test_cluster_spans_many_shard_boundaries():
    """An elongated cluster threading ALL 8 Morton ranges: the eps
    chain crosses >= 7 shard boundaries, so the fixpoint's multi-hop
    label propagation is load-bearing — and must converge to ONE
    cluster byte-identical to the fused engine."""
    rng = np.random.default_rng(0)
    n = 4096
    t = np.linspace(0.0, 100.0, n)
    X = np.stack([t, rng.normal(0.0, 0.01, n)], axis=1)
    kw = dict(eps=0.1, min_samples=5, block=128)
    fused_model = DBSCAN(mesh=default_mesh(1), **kw)
    fused_model.fit(X)
    ref = canon(fused_model.labels_, fused_model.core_sample_mask_)
    labels, core, stats = global_morton_dbscan(
        X, mesh=default_mesh(8), **kw
    )
    dense = densify_labels(labels)
    np.testing.assert_array_equal(dense, ref)
    assert dense.max() == 0  # one chain cluster across every shard
    assert stats["merge_converged"] is True
    # Propagating a min label across a multi-shard chain needs at
    # least one changing round plus the convergence round.
    assert stats["fixpoint_rounds"] >= 2
    # Every interior shard both sends and receives boundary tiles.
    assert stats["boundary_tiles"] >= 7


def test_manifold_structured_data():
    """Low-rank embedding-manifold mixture (VERDICT r5 Next #10):
    correlated structure is the adversarial case for Morton-range
    sharding — labels must still byte-match the fused engine and score
    ARI >= 0.99 against the generating assignment."""
    from benchdata import ari_vs_truth, make_manifold_data

    X, truth = make_manifold_data(4000, 16, latent_dim=3)
    kw = dict(eps=0.8, min_samples=10, block=128)
    fm = DBSCAN(mesh=default_mesh(1), **kw)
    fm.fit(X)
    ref = canon(fm.labels_, fm.core_sample_mask_)
    labels, _core, stats = global_morton_dbscan(
        X, mesh=default_mesh(8), **kw
    )
    dense = densify_labels(labels)
    np.testing.assert_array_equal(dense, ref)
    assert ari_vs_truth(dense, truth) >= 0.99
    # The live-pair / pad-waste stats ride next to the isotropic rows.
    assert stats["live_pairs"] > 0
    assert np.isfinite(stats["pad_waste"])


def test_warm_refit_and_eps_sweep_reuse_staged_slabs(blobs):
    from pypardis_tpu.parallel import staging

    staging.clear()
    mesh = default_mesh(8)
    l1, _, s1 = global_morton_dbscan(blobs, mesh=mesh, **KW)
    assert s1["staged_bytes_reused"] == 0
    l2, _, s2 = global_morton_dbscan(blobs, mesh=mesh, **KW)
    # Warm refit: owned slabs AND boundary tiles reuse.
    assert s2["staged_bytes_reused"] > 0
    np.testing.assert_array_equal(l1, l2)
    # eps sweep: the owned slabs are keyed WITHOUT eps, so they reuse
    # while the (eps-dependent) boundary tiles rebuild.
    _, _, s3 = global_morton_dbscan(
        blobs, mesh=mesh, eps=0.5, min_samples=5, block=128
    )
    assert s3["staged_bytes_reused"] > 0


def test_explicit_btcap(blobs, fused):
    from pypardis_tpu.parallel import staging

    staging.clear()
    labels, _, stats = global_morton_dbscan(
        blobs, mesh=default_mesh(8), btcap=64, **KW
    )
    np.testing.assert_array_equal(densify_labels(labels), fused[0])
    # An explicit too-small send capacity fails loudly (no silent
    # dropped-tile results); the auto ladder would have retried.
    staging.clear()
    with pytest.raises(RuntimeError, match="boundary-tile"):
        global_morton_dbscan(blobs, mesh=default_mesh(8), btcap=1, **KW)
    staging.clear()


def test_dbscan_mode_surface(blobs, fused, tmp_path):
    model = DBSCAN(mode="global_morton", mesh=default_mesh(8), **KW)
    model.fit(blobs)
    np.testing.assert_array_equal(model.labels_, fused[0])
    report = model.report()
    assert report["params"]["mode"] == "global_morton"
    sh = report["sharding"]
    assert sh["mode"] == "global_morton"
    assert sh["halo_exchange"] == "morton_ring"
    assert sh["duplicated_work_factor"] == 1.0
    assert sh["owner_computes"] is True
    assert sh["boundary_tile_bytes"] > 0
    assert sh["ring_rounds"] == 7
    # Parity surface: Morton-range partitioner; work-balanced ranges
    # stay within the documented 1.5x-of-equal-share row cap (in whole
    # tiles of `block` rows).
    part = model.partitioner_
    assert isinstance(part, MortonRangePartitioner)
    sizes = part.partition_sizes()
    assert int(sizes.sum()) == len(blobs)
    nt = -(-len(blobs) // KW["block"])
    max_tiles = -(-int(np.ceil(1.5 * nt)) // 8)
    assert int(sizes.max()) <= max_tiles * KW["block"]
    assert set(np.unique(part.result)) <= set(range(8))
    assert part.tree == []
    # neighbors = OWNED rows only (zero duplication surface).
    total = sum(len(v) for v in model.neighbors.values())
    assert total == len(blobs)
    assert model.cluster_dict  # partition:cluster parity codes exist
    # The summary renders the boundary-tile line without raising.
    assert "boundary" in model.summary()
    # Trace spans: ring rounds + fixpoint rounds separate exchange
    # time from compute time (ISSUE 5 telemetry satellite).
    path = tmp_path / "gm_trace.json"
    model.export_trace(str(path))
    names = {
        ev["name"] for ev in json.load(open(path))["traceEvents"]
    }
    assert "gm.exchange" in names
    assert "gm.ring_round" in names
    assert "gm.fixpoint_round" in names


def test_sharded_dbscan_mode_dispatch(blobs, fused):
    labels, _, stats = sharded_dbscan(
        blobs, None, mode="global_morton", mesh=default_mesh(8), **KW
    )
    assert stats["mode"] == "global_morton"
    np.testing.assert_array_equal(densify_labels(labels), fused[0])
    with pytest.raises(ValueError, match="mode"):
        sharded_dbscan(blobs, None, mode="bogus", **KW)


def test_mode_input_validation(blobs, tmp_path):
    import jax

    with pytest.raises(ValueError, match="mode"):
        DBSCAN(mode="bogus")
    model = DBSCAN(mode="global_morton", mesh=default_mesh(8), **KW)
    with pytest.raises(ValueError, match="host-resident"):
        model.fit(jax.device_put(np.asarray(blobs)))
    # A memmap now STREAMS through the external sample-sort build
    # (ISSUE 10) instead of being rejected; the report says so, and
    # the parity surface degrades gracefully (ranges + boxes, no O(N)
    # permutation — partitioner_ stays None).
    from pypardis_tpu.parallel import staging

    staging.clear()
    mm = np.memmap(
        tmp_path / "x.dat", dtype=np.float32, mode="w+",
        shape=blobs.shape,
    )
    mm[:] = blobs.astype(np.float32)
    m = DBSCAN(mode="global_morton", mesh=default_mesh(8), **KW)
    m.fit(mm)
    assert m.metrics_.get("input") == "stream"
    assert m.partitioner_ is None
    rep = m.report()
    assert rep["sharding"]["input"] == "stream"
    assert rep["sharding"]["stream_buckets"] >= 1
    assert "stream" in m.summary()
    staging.clear()


def test_1dev_chained_route_reports_honestly(blobs):
    """ISSUE 5 satellite: the 1-device chained KD route runs the legacy
    duplicate-and-recluster step — its report must SAY so
    (owner_computes False) and still gauge the duplication, so every
    mode's sharding block is comparable."""
    part = KDPartitioner(blobs, max_partitions=8)
    _, _, stats = sharded_dbscan(
        blobs, part, mesh=default_mesh(1), owner_computes=True, **KW
    )
    assert stats["owner_computes"] is False
    assert np.isfinite(stats["duplicated_work_factor"])
    assert stats["duplicated_work_factor"] > 1.0


@pytest.fixture
def mm_points(tmp_path):
    """A disk-backed f32 memmap + its in-RAM f32 twin (parity must
    compare f32-vs-f32 — the memmap rounds the f64 blobs once)."""
    X, _ = make_blobs(
        n_samples=3000, centers=6, n_features=3, cluster_std=0.3,
        random_state=3,
    )
    X = X.astype(np.float32)
    path = tmp_path / "pts.f32"
    mm = np.memmap(path, dtype=np.float32, mode="w+", shape=X.shape)
    mm[:] = X
    mm.flush()
    return np.memmap(path, dtype=np.float32, mode="r", shape=X.shape), X


def test_streaming_split_byte_parity(blobs):
    """ISSUE 10 satellite: the external sample-sort produces identical
    (order-per-range, starts, center) to the in-RAM split — plain
    equal-rows AND work-balanced cuts — with many spill buckets in
    play (tiny bucket_bytes forces real bucketing)."""
    from pypardis_tpu.partition import morton_range_split_streaming

    for kw in ({}, dict(eps=0.4, block=128)):
        order, starts, center = morton_range_split(blobs, 8, **kw)
        with morton_range_split_streaming(
            blobs, 8, bucket_bytes=50_000, **kw
        ) as sp:
            np.testing.assert_array_equal(sp.center, center)
            np.testing.assert_array_equal(sp.starts, starts)
            assert sp.stats["stream_buckets"] > 1
            cat = np.concatenate(
                [sp.range_ids(s) for s in range(8)]
            )
            np.testing.assert_array_equal(cat, order)
            # Rows are the recentred-f32 frame rows, byte-for-byte.
            ids0, rows0 = sp.range_rows(0)
            ref = (blobs[order[:len(ids0)]] - center).astype(
                np.float32
            )
            np.testing.assert_array_equal(rows0, ref)


def test_streaming_split_all_duplicate_rows():
    """Degenerate geometry where every Morton key collides: the
    (key, id) composite splitter domain still buckets evenly (the id
    tiebreak IS stable-sort order), and the order comes back as the
    identity — byte-identical to the in-RAM stable sort."""
    from pypardis_tpu.partition import morton_range_split_streaming

    D = np.ones((4096, 3), np.float32)
    order, starts, center = morton_range_split(D, 8, eps=0.4, block=64)
    with morton_range_split_streaming(
        D, 8, eps=0.4, block=64, bucket_bytes=20_000
    ) as sp:
        np.testing.assert_array_equal(sp.center, center)
        np.testing.assert_array_equal(sp.starts, starts)
        cat = np.concatenate([sp.range_ids(s) for s in range(8)])
        np.testing.assert_array_equal(cat, order)
        # Splitter keys collide on coordinates; the id column must
        # still have spread the rows across several buckets.
        assert sp.stats["stream_buckets"] > 1
        assert sp.stats["stream_max_bucket_rows"] < 4096


def test_streaming_gm_byte_parity_meshes(mm_points):
    """Memmap streaming-GM labels byte-match the in-RAM global-Morton
    fit AND the fused engine on 1/4/8-device meshes, both merges."""
    from pypardis_tpu.parallel import staging

    mm, X = mm_points
    kw = dict(eps=0.4, min_samples=5, block=128)
    fm = DBSCAN(mesh=default_mesh(1), **kw)
    fm.fit(X)
    ref = canon(fm.labels_, fm.core_sample_mask_)
    ref_core = np.asarray(fm.core_sample_mask_)
    for n_dev, merge in ((1, "device"), (4, "host"), (8, "device"),
                         (8, "host")):
        staging.clear()
        inram, inram_core, _ = global_morton_dbscan(
            X, mesh=default_mesh(n_dev), merge=merge, **kw
        )
        staging.clear()
        labels, core, stats = global_morton_dbscan(
            mm, mesh=default_mesh(n_dev), merge=merge, **kw
        )
        tag = f"stream gm {n_dev}dev merge={merge}"
        assert stats["input"] == "stream", tag
        assert stats["mode"] == "global_morton", tag
        assert stats["duplicated_work_factor"] == 1.0, tag
        np.testing.assert_array_equal(labels, inram, err_msg=tag)
        np.testing.assert_array_equal(core, inram_core, err_msg=tag)
        np.testing.assert_array_equal(
            densify_labels(labels), ref, err_msg=tag
        )
        np.testing.assert_array_equal(core, ref_core, err_msg=tag)
        # The out-of-core phase decomposition rides on every row.
        for key in ("gm_build_s", "gm_exchange_s", "gm_execute_s",
                    "gm_merge_s"):
            assert np.isfinite(stats[key]) and stats[key] >= 0, tag
    staging.clear()


def test_streaming_gm_chained_route(mm_points):
    """The chained 1-device route (ranges visiting one chip in turn)
    is byte-identical to the mesh engine and reports honestly."""
    from pypardis_tpu.parallel import staging

    mm, X = mm_points
    kw = dict(eps=0.4, min_samples=5, block=128)
    staging.clear()
    ref, ref_core, _ = global_morton_dbscan(
        X, mesh=default_mesh(8), **kw
    )
    staging.clear()
    labels, core, stats = global_morton_dbscan(
        mm, mesh=default_mesh(1), chain=4, **kw
    )
    np.testing.assert_array_equal(labels, ref)
    np.testing.assert_array_equal(core, ref_core)
    assert stats["mode"] == "global_morton"
    assert stats["halo_exchange"] == "chained_tiles"
    assert stats["chained"] is True
    assert stats["n_shard_partitions"] == 4
    assert stats["duplicated_work_factor"] == 1.0
    assert stats["owner_computes"] is True
    assert stats["boundary_tiles"] > 0
    # Env-var spelling of the same knob (the northstar driver's path).
    import os

    staging.clear()
    os.environ["PYPARDIS_GM_CHAIN"] = "4"
    try:
        labels2, _, stats2 = global_morton_dbscan(
            mm, mesh=default_mesh(1), **kw
        )
    finally:
        del os.environ["PYPARDIS_GM_CHAIN"]
    np.testing.assert_array_equal(labels2, ref)
    assert stats2["chained"] is True
    staging.clear()


def test_streaming_spill_cleanup(mm_points, tmp_path):
    """Spill files are tempdir-scoped and removed on success AND on a
    terminal failure mid-build (ISSUE 10 satellite)."""
    import os

    from pypardis_tpu.parallel import staging
    from pypardis_tpu.utils import faults

    mm, _X = mm_points
    spill = tmp_path / "spill"
    spill.mkdir()
    os.environ["PYPARDIS_SPILL_DIR"] = str(spill)
    try:
        staging.clear()
        global_morton_dbscan(
            mm, mesh=default_mesh(8), eps=0.4, min_samples=5,
            block=128,
        )
        assert list(spill.iterdir()) == [], "spill left after success"
        # Persistent transfer OOM: the staging ladder gives up, the
        # build unwinds — and the spill dir must still come back empty.
        staging.clear()
        with faults.plan("staging.device_put:*=oom"):
            with pytest.raises(Exception):
                global_morton_dbscan(
                    mm, mesh=default_mesh(8), eps=0.4, min_samples=5,
                    block=128,
                )
        assert list(spill.iterdir()) == [], "spill left after giveup"
    finally:
        del os.environ["PYPARDIS_SPILL_DIR"]
        staging.clear()


def test_morton_range_split_products(blobs):
    from pypardis_tpu.partition import spatial_order

    order, starts, center = morton_range_split(blobs, 8)
    assert sorted(order.tolist()) == list(range(len(blobs)))
    assert starts[0] == 0 and starts[-1] == len(blobs)
    per = -(-len(blobs) // 8)
    assert all(
        0 <= starts[i + 1] - starts[i] <= per for i in range(8)
    )
    # The order IS the recentred-f32 global Morton order — the same
    # frame the shard slabs are built in.
    sub = (blobs - center).astype(np.float32)
    np.testing.assert_array_equal(order, spatial_order(sub))
    # Work-balanced mode (eps + block given): same order, cuts on tile
    # boundaries, every range within the 1.5x-of-equal-share row cap.
    order_b, starts_b, _ = morton_range_split(
        blobs, 8, eps=0.4, block=128
    )
    np.testing.assert_array_equal(order_b, order)
    assert starts_b[0] == 0 and starts_b[-1] == len(blobs)
    diffs = np.diff(starts_b)
    assert (diffs >= 0).all()
    nt = -(-len(blobs) // 128)
    max_t = int(np.ceil(1.5 * nt / 8))
    assert int(diffs.max()) <= max_t * 128
    assert all(s % 128 == 0 for s in starts_b[:-1])
