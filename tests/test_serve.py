"""Serving subsystem (ISSUE 4): device-resident core-point index +
batched out-of-sample query engine.

The correctness contract is EXACT equality with the brute-force numpy
core-point oracle (``ops.query.brute_force_query``): nearest core
point within eps wins, ties go to the smallest label, noise = -1 —
bitwise on labels AND squared distances, on every backend (the kernels
replay the oracle's IEEE float32 op sequence; the anti-FMA seal keeps
compilers from contracting it).
"""

import numpy as np
import pytest

from pypardis_tpu import DBSCAN
from pypardis_tpu.serve import CorePointIndex, QueryEngine, build_index

INT_INF = np.iinfo(np.int32).max


def _fit_blobs(n=750, dim=2, eps=0.3, min_samples=10, seed=0):
    from sklearn.datasets import make_blobs
    from sklearn.preprocessing import StandardScaler

    centers = np.random.default_rng(seed).uniform(-1, 1, size=(3, dim))
    X, _ = make_blobs(
        n_samples=n, centers=centers, cluster_std=0.4, random_state=seed
    )
    X = StandardScaler().fit_transform(X)
    return DBSCAN(eps=eps, min_samples=min_samples).fit(X), X


@pytest.fixture(scope="module")
def fitted():
    return _fit_blobs()


@pytest.fixture(scope="module")
def queries(fitted):
    _m, X = fitted
    rng = np.random.default_rng(3)
    return np.concatenate([
        X[:150],
        X[rng.integers(0, len(X), 200)]
        + rng.normal(scale=0.3, size=(200, X.shape[1])),
        rng.uniform(-3, 3, size=(150, X.shape[1])),
        np.full((4, X.shape[1]), 50.0),  # far from everything: noise
    ])


def _assert_oracle_exact(engine, Q):
    t = engine.submit(Q)
    engine.drain()
    olabs, od2 = engine.index.oracle_predict(Q)
    np.testing.assert_array_equal(t.labels, olabs)
    np.testing.assert_array_equal(t.d2, od2)
    return t


def test_predict_matches_oracle_exactly(fitted, queries):
    m, _X = fitted
    engine = m.query_engine(leaves=8, block=32, qblock=32)
    t = _assert_oracle_exact(engine, queries)
    # the far queries are noise with infinite distance
    assert (t.labels[-4:] == -1).all()
    assert np.isinf(t.d2[-4:]).all()
    # predict() and the ticket agree; distances are sqrt(d2)
    labs, dist = engine.predict(queries, return_distance=True)
    np.testing.assert_array_equal(labs, t.labels)
    np.testing.assert_array_equal(dist, np.sqrt(t.d2))


def test_core_training_points_keep_their_label(fitted):
    m, X = fitted
    core = np.asarray(m.core_sample_mask_, bool)
    labs = m.predict(X[core])
    np.testing.assert_array_equal(labs, m.labels_[core])


def test_leaf_count_invariance(fitted, queries):
    """The KD bucketing is an execution detail: 1 leaf and 8 leaves
    (with the neighbor-leaf routing engaged) answer identically."""
    m, _X = fitted
    l1 = m.query_engine(leaves=1, block=32, qblock=32).predict(queries)
    l8 = m.query_engine(leaves=8, block=32, qblock=32).predict(queries)
    np.testing.assert_array_equal(l1, l8)


def test_boundary_straddling_queries(fitted):
    """Queries sitting within eps of KD leaf boundaries route to every
    candidate leaf and still match the oracle exactly."""
    m, X = fitted
    engine = m.query_engine(leaves=8, block=32, qblock=32)
    index = engine.index
    assert index.tree, "expected a multi-leaf index"
    rng = np.random.default_rng(7)
    qs = []
    for _parent, axis, boundary, _l, _r in index.tree:
        for _ in range(8):
            q = rng.uniform(-2, 2, size=index.d)
            q[axis] = boundary + rng.uniform(-0.9, 0.9) * index.eps
            qs.append(q)
    Q = np.asarray(qs) + index.center  # prepare_queries re-centers
    routed = engine.index.route(index.prepare_queries(Q))
    n_rows = sum(len(arr) for _leaf, arr in routed)
    assert n_rows > len(Q), "no query straddled a leaf boundary"
    _assert_oracle_exact(engine, Q)


def test_backend_parity(fitted, queries):
    """XLA and Pallas (interpreter) kernels answer bit-identically."""
    m, _X = fitted
    xla = m.query_engine(leaves=4, block=32, qblock=32, backend="xla")
    t_x = _assert_oracle_exact(xla, queries)
    pl_eng = QueryEngine(xla.index, backend="pallas", interpret=True)
    t_p = _assert_oracle_exact(pl_eng, queries)
    np.testing.assert_array_equal(t_x.labels, t_p.labels)
    np.testing.assert_array_equal(t_x.d2, t_p.d2)


def test_checkpoint_roundtrip_serves_identically(tmp_path, fitted,
                                                 queries):
    """save_model -> load_model in a "fresh process" (no training data)
    -> predict() byte-identical to the original model's."""
    m, _X = fitted
    want, want_d = m.query_engine(
        leaves=8, block=32, qblock=32
    ).predict(queries, return_distance=True)
    path = str(tmp_path / "model.npz")
    m.save(path)
    m2 = DBSCAN.load(path)
    assert m2.data is None  # serves WITHOUT the dataset
    got, got_d = m2.query_engine(
        leaves=8, block=32, qblock=32
    ).predict(queries, return_distance=True)
    np.testing.assert_array_equal(want, got)
    np.testing.assert_array_equal(want_d, got_d)


def test_index_checkpoint_roundtrip(tmp_path, fitted, queries):
    from pypardis_tpu import load_index, save_index

    m, _X = fitted
    idx = build_index(m, leaves=4, block=32, qblock=32)
    engine = QueryEngine(idx, backend="xla")
    want = engine.predict(queries)
    path = str(tmp_path / "index.npz")
    save_index(idx, path)
    idx2 = load_index(path)
    np.testing.assert_array_equal(idx.coords, idx2.coords)
    np.testing.assert_array_equal(idx.labels, idx2.labels)
    got = QueryEngine(idx2, backend="xla").predict(queries)
    np.testing.assert_array_equal(want, got)


def test_warm_second_index_build_reuses_device_slabs(fitted):
    """Acceptance: a warm second index build reports
    staged_bytes_reused > 0 (the serve_index staging route)."""
    from pypardis_tpu.parallel import staging

    m, _X = fitted
    staging.device_evict("serve_index")  # cold start, deterministically
    first = build_index(m, leaves=4, block=32)
    second = build_index(m, leaves=4, block=32)
    assert first.stats["staged_bytes"] > 0
    assert second.stats["staged_bytes_reused"] > 0
    assert (
        second.stats["staged_bytes_reused"] == first.stats["staged_bytes"]
    )


def test_engine_queue_coalesces_and_reports(fitted, queries):
    m, _X = fitted
    engine = QueryEngine(
        build_index(m, leaves=4, block=32, qblock=32),
        backend="xla", batch_capacity=128,
    )
    tickets = [engine.submit(queries[s:s + 32])
               for s in range(0, 320, 32)]
    assert not tickets[0].done
    n = engine.drain()
    assert n == 320
    olabs, _ = engine.index.oracle_predict(queries[:320])
    got = np.concatenate([t.result() for t in tickets])
    np.testing.assert_array_equal(got, olabs)
    stats = engine.serving_stats()
    assert stats["queries"] == 320
    assert stats["batches"] >= 3  # 320 rows through a 128-row coalescer
    for key in ("qps", "p50_ms", "p99_ms", "batch_fill"):
        assert np.isfinite(stats[key]) and stats[key] > 0, (key, stats)
    assert stats["batch_fill"] <= 1.0


def test_serving_stats_memory_bounded_under_sustained_traffic(fitted):
    """ISSUE 16: the latency structure is a fixed-size histogram — 10x
    the requests must not grow it by a byte (the old deque grew with
    every request until its cap, and percentiles scanned it)."""
    m, _X = fitted
    engine = QueryEngine(
        build_index(m, leaves=2, block=32, qblock=32), backend="xla",
        batch_capacity=64,
    )
    q = np.zeros((8, 2), dtype=np.float32)

    def drive(requests):
        for _ in range(requests):
            engine.submit(q)
            engine.drain()

    drive(20)
    before = engine._lat_hist.nbytes
    drive(200)  # 10x the traffic
    assert engine._lat_hist.nbytes == before
    stats = engine.serving_stats()
    assert stats["queries"] == 220 * 8
    hist = stats["latency_hist"]
    assert hist["schema"] == "pypardis_tpu/hist@1"
    assert hist["count"] == 220
    assert stats["p50_ms"] > 0 and stats["p99_ms"] >= stats["p50_ms"]
    assert sum(c for _, c in hist["buckets"]) + hist["overflow"] == 220


def test_engine_queue_is_bounded(fitted):
    m, _X = fitted
    engine = QueryEngine(
        build_index(m, leaves=2, block=32), max_pending=64
    )
    engine.submit(np.zeros((40, 2)))
    with pytest.raises(RuntimeError, match="queue full"):
        engine.submit(np.zeros((40, 2)))
    engine.drain()  # drains the accepted request; queue reopens
    engine.submit(np.zeros((40, 2)))


def test_report_carries_serving_block(fitted, queries):
    m, _X = fitted
    m.query_engine(leaves=4, block=32).predict(queries[:64])
    rep = m.report()
    srv = rep["serving"]
    assert srv["queries"] >= 64
    for key in ("qps", "p50_ms", "p99_ms", "batch_fill"):
        v = srv[key]
        assert isinstance(v, (int, float)) and np.isfinite(v), (key, v)
    assert "serving:" in m.summary()


def test_all_noise_model_serves_noise():
    rng = np.random.default_rng(5)
    X = rng.uniform(-1, 1, size=(64, 3))
    m = DBSCAN(eps=1e-6, min_samples=5).fit(X)
    assert not m.core_sample_mask_.any()
    labs, dist = m.query_engine().predict(X, return_distance=True)
    assert (labs == -1).all() and np.isinf(dist).all()


def test_query_validation(fitted):
    m, _X = fitted
    engine = m.query_engine(leaves=4, block=32)
    with pytest.raises(ValueError, match="dimensionality"):
        engine.predict(np.zeros((4, 5)))
    with pytest.raises(ValueError, match="2-D"):
        engine.predict(np.zeros(4))
    bad = np.zeros((4, 2))
    bad[1, 0] = np.nan
    with pytest.raises(ValueError, match="NaN or infinite"):
        engine.predict(bad)


def test_replace_generation_keeps_engine_serving(fitted):
    """ISSUE 12: an in-place whole-index generation swap — a fresh
    build adopted by the SAME index object in the same recentring
    frame — is invisible to an engine holding the object: the next
    predict answers bitwise against the new generation's oracle, and
    the epoch/generation clocks advance for replica-cache keys."""
    m, X = fitted
    idx = build_index(m, leaves=4, block=32)
    engine = QueryEngine(idx, backend="xla")
    Q = X[:120]
    engine.predict(Q)  # stage the old generation on device
    epoch0 = idx.epoch

    # A different generation: half the cores, same frame.
    mask = np.asarray(m.core_sample_mask_, bool)
    cores = np.asarray(m.data)[mask]
    labels = np.asarray(m.labels_, np.int32)[mask]
    half = len(cores) // 2
    fresh = CorePointIndex.build(
        cores[:half], labels[:half], m.eps, block=32, qblock=32,
        stage=False, center=idx.center,
    )
    np.testing.assert_array_equal(fresh.center, idx.center)
    idx.replace_generation(fresh)

    assert idx.generation == 1
    assert idx.epoch == epoch0 + 1
    assert idx.n_core == half
    assert idx.appended_slab_bytes == 0
    labs, _ = engine.predict(Q, return_distance=True), None
    olabs, od2 = idx.oracle_predict(Q)
    t = engine.submit(Q)
    engine.drain()
    np.testing.assert_array_equal(t.labels, olabs)
    np.testing.assert_array_equal(t.d2, od2)
    assert engine.serving_stats()["index_generation"] == 1
    # an open delta update refuses to race a generation swap
    idx.begin_update()
    with pytest.raises(RuntimeError, match="delta update open"):
        idx.replace_generation(fresh)
    idx.commit_update()


def test_oracle_property_randomized():
    """Hypothesis-style seeded sweep: random geometry, dtype, backend,
    leaf count — predict() equals the brute-force oracle exactly,
    including boundary-straddling queries."""
    for seed in range(4):
        rng = np.random.default_rng(seed)
        dim = int(rng.integers(2, 6))
        n = int(rng.integers(300, 600))
        dtype = np.float32 if seed % 2 else np.float64
        m, X = _fit_blobs(
            n=n, dim=dim, eps=0.4 * np.sqrt(dim), min_samples=5,
            seed=seed,
        )
        X = X.astype(dtype)
        if not m.core_sample_mask_.any():
            continue
        leaves = int(rng.integers(1, 9))
        idx = build_index(m, leaves=leaves, block=16, qblock=16)
        Q = np.concatenate([
            X[rng.integers(0, n, 100)],
            X[rng.integers(0, n, 100)]
            + rng.normal(scale=m.eps, size=(100, dim)).astype(dtype),
            rng.uniform(-4, 4, size=(50, dim)).astype(dtype),
        ])
        for backend, interp in (("xla", False), ("pallas", True)):
            engine = QueryEngine(idx, backend=backend, interpret=interp)
            _assert_oracle_exact(engine, Q)
