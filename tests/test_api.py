"""User-facing DBSCAN API surface (reference dbscan.py:56-165 parity)."""

import numpy as np
from sklearn.cluster import DBSCAN as SKDBSCAN
from sklearn.metrics import adjusted_rand_score

from pypardis_tpu import DBSCAN


def test_fit_predict_blobs(blobs750):
    model = DBSCAN(eps=0.3, min_samples=10)
    labels = model.fit_predict(blobs750)
    sk = SKDBSCAN(eps=0.3, min_samples=10).fit(blobs750)
    assert adjusted_rand_score(sk.labels_, labels) >= 0.99
    np.testing.assert_array_equal(labels == -1, sk.labels_ == -1)


def test_train_with_keyed_records(blobs750):
    # Reference input contract: RDD of (key, vector) pairs (dbscan.py:107).
    records = [(f"pt{i}", v) for i, v in enumerate(blobs750)]
    model = DBSCAN(eps=0.3, min_samples=10)
    model.train(records)
    result = model.assignments()
    assert len(result) == len(blobs750)
    keys = [k for k, _ in result]
    assert keys[0] == "pt0"
    labels = np.array([l for _, l in result])
    assert (labels >= -1).all() and labels.max() >= 0


def test_attribute_surface(blobs750):
    model = DBSCAN(eps=0.3, min_samples=10)
    model.fit(blobs750)
    assert model.bounding_boxes is not None
    assert model.expanded_boxes is not None
    assert model.result is not None
    assert model.labels_ is not None
    assert model.core_sample_mask_ is not None
    assert model.metrics_["points_per_sec"] > 0
    # expanded boxes are the 2*eps inflation (dbscan.py:144)
    for l, box in model.bounding_boxes.items():
        np.testing.assert_allclose(
            model.expanded_boxes[l].lower, box.lower - 2 * 0.3
        )


def test_dbscan_partition_wire_format(blobs750):
    from pypardis_tpu import dbscan_partition

    records = [((i, 7), v) for i, v in enumerate(blobs750[:100])]
    out = list(
        dbscan_partition(records, {"eps": 0.3, "min_samples": 5})
    )
    assert len(out) == 100
    for key, label in out:
        part, rest = label.split(":")
        assert part == "7"
        int(rest.rstrip("*"))  # parses


def test_map_cluster_id():
    from pypardis_tpu import map_cluster_id

    mapping = {"0:1": 5}
    assert map_cluster_id((3, ["0:1*"]), mapping) == (3, 5)
    assert map_cluster_id((4, ["0:-1"]), mapping) == (4, -1)
    assert map_cluster_id((5, ["9:9"]), mapping) == (5, -1)


def test_assignments_key_sorted():
    """assignments() returns key-sorted pairs — the reference's final
    sortByKey() (dbscan.py:164) is part of its output contract."""
    rng = np.random.default_rng(5)
    X = rng.normal(size=(200, 2)).astype(np.float32)
    keys = rng.permutation(1000)[:200]  # unsorted, non-contiguous
    m = DBSCAN(eps=0.5, min_samples=5).train((keys, X))
    got_keys = [k for k, _ in m.assignments()]
    assert got_keys == sorted(got_keys)
    # labels still line up with their keys
    by_key = dict(m.assignments())
    order = np.argsort(keys, kind="stable")
    for k, l in zip(keys[order], m.labels_[order]):
        assert by_key[int(k)] == int(l)


def test_cluster_mapping_real_partitions():
    """cluster_mapping() reflects the actual partition:cluster pairs
    of a sharded run (not a fabricated single-partition view)."""
    from sklearn.datasets import make_blobs

    X, _ = make_blobs(
        n_samples=2000, centers=6, n_features=3, cluster_std=0.3,
        random_state=1,
    )
    m = DBSCAN(eps=0.5, min_samples=5, block=128, max_partitions=8)
    m.fit(X)
    if m.partitioner_ is None:  # single-device environment: skip
        import pytest

        pytest.skip("sharded path unavailable")
    agg = m.cluster_mapping()
    parts_seen = {int(k.split(":")[0]) for k in agg.fwd}
    real_parts = {
        int(p) for p, l in zip(m.partitioner_.result, m.labels_) if l >= 0
    }
    assert parts_seen == real_parts
    assert len(parts_seen) > 1


def test_device_resident_input_matches_host(blobs750):
    """A jax.Array input flows through without a host round trip and
    yields the same labels as the numpy path (both single-shard and
    the CI mesh's sharded route, which converts internally)."""
    import jax.numpy as jnp

    from sklearn.metrics import adjusted_rand_score

    X = blobs750.astype(np.float32)  # jnp.asarray would downcast anyway
    want = DBSCAN(eps=0.3, min_samples=10).fit_predict(X)
    got = DBSCAN(eps=0.3, min_samples=10).fit_predict(jnp.asarray(X))
    assert adjusted_rand_score(want, got) >= 0.99


def test_pad_and_run_device_input_single_shard(blobs750):
    """The single-shard pipeline accepts device arrays directly
    (device_prep centering/padding on device)."""
    import jax.numpy as jnp

    from pypardis_tpu.dbscan import _pad_and_run

    from sklearn.metrics import adjusted_rand_score

    X = blobs750.astype(np.float32)
    r_host, c_host, _ = _pad_and_run(X, 0.3, 10, "euclidean", 256)
    r_dev, c_dev, _ = _pad_and_run(jnp.asarray(X), 0.3, 10, "euclidean", 256)
    # The two paths center by slightly different constants (f64 vs f32
    # mean), so exact-eps boundary pairs may legitimately flip; demand
    # identical cluster STRUCTURE, not bit-equal roots.
    assert adjusted_rand_score(r_host, np.asarray(r_dev)) >= 0.99
    assert (np.asarray(c_dev) == c_host).mean() >= 0.99


def test_packed_pipeline_result_roundtrip():
    """unpack_pipeline_result inverts _pipeline_pack's encoding."""
    import jax.numpy as jnp

    from pypardis_tpu.ops.pipeline import (
        _pipeline_pack,
        unpack_pipeline_result,
    )

    cap = 16
    roots_s = jnp.asarray([3, -1, 0, 5, -1, 2, 7, 1] + [-1] * 8, jnp.int32)
    core_s = jnp.asarray(
        [True, False, True, False, False, True, True, False] + [False] * 8
    )
    owner = jnp.arange(cap, dtype=jnp.int32)
    stats = jnp.asarray([42, 100, 7, 13, 3], jnp.int32)
    packed = np.asarray(
        _pipeline_pack(roots_s, core_s, stats, owner, cap=cap)
    )
    roots, core, total, budget, passes, band_pairs, rescored = (
        unpack_pipeline_result(packed)
    )
    want = np.asarray([3, -1, 0, 5, -1, 2, 7, 1] + [-1] * 8)
    assert (roots == want).all()
    assert (core == np.asarray(core_s)).all()
    assert (total, budget, passes) == (42, 100, 7)
    assert (band_pairs, rescored) == (13, 3)


def test_cluster_mapping_vectorized_matches_loop():
    """The vectorized cluster_mapping() reproduces the per-point
    aggregator loop exactly (round-4 review: the loop was O(N) Python
    and unusable after large fits)."""
    from sklearn.datasets import make_blobs

    from pypardis_tpu.aggregator import ClusterAggregator

    X, _ = make_blobs(
        n_samples=2000, centers=6, n_features=3, cluster_std=0.3,
        random_state=1,
    )
    m = DBSCAN(eps=0.5, min_samples=5, block=128, max_partitions=8)
    m.fit(X)
    agg = m.cluster_mapping()

    ref = ClusterAggregator()
    parts = (
        np.asarray(m.partitioner_.result)
        if m.partitioner_ is not None
        else np.zeros(len(m.labels_), np.int32)
    )
    for key, part, label in zip(m._keys, parts, m.labels_):
        if label >= 0:
            ref + (key, [f"{int(part)}:{label}"])

    assert dict(agg.fwd) == dict(ref.fwd)
    assert {k: set(v) for k, v in agg.rev.items()} == {
        k: set(v) for k, v in ref.rev.items()
    }
    assert agg.next_global_id == ref.next_global_id


def test_not_fitted_message_unified():
    """Every result surface raises the SAME not-fitted message (they
    used to disagree: "call train() first" vs "call fit()/train()
    first")."""
    import pytest

    m = DBSCAN()
    surfaces = {
        "assignments": m.assignments,
        "report": m.report,
        "summary": m.summary,
        "export_trace": lambda: m.export_trace("/tmp/x.json"),
        "predict": lambda: m.predict(np.zeros((1, 2))),
        "query_engine": m.query_engine,
    }
    for name, fn in surfaces.items():
        with pytest.raises(
            RuntimeError,
            match=r"not fitted; call fit\(\)/train\(\) first",
        ):
            fn()
