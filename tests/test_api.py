"""User-facing DBSCAN API surface (reference dbscan.py:56-165 parity)."""

import numpy as np
from sklearn.cluster import DBSCAN as SKDBSCAN
from sklearn.metrics import adjusted_rand_score

from pypardis_tpu import DBSCAN


def test_fit_predict_blobs(blobs750):
    model = DBSCAN(eps=0.3, min_samples=10)
    labels = model.fit_predict(blobs750)
    sk = SKDBSCAN(eps=0.3, min_samples=10).fit(blobs750)
    assert adjusted_rand_score(sk.labels_, labels) >= 0.99
    np.testing.assert_array_equal(labels == -1, sk.labels_ == -1)


def test_train_with_keyed_records(blobs750):
    # Reference input contract: RDD of (key, vector) pairs (dbscan.py:107).
    records = [(f"pt{i}", v) for i, v in enumerate(blobs750)]
    model = DBSCAN(eps=0.3, min_samples=10)
    model.train(records)
    result = model.assignments()
    assert len(result) == len(blobs750)
    keys = [k for k, _ in result]
    assert keys[0] == "pt0"
    labels = np.array([l for _, l in result])
    assert (labels >= -1).all() and labels.max() >= 0


def test_attribute_surface(blobs750):
    model = DBSCAN(eps=0.3, min_samples=10)
    model.fit(blobs750)
    assert model.bounding_boxes is not None
    assert model.expanded_boxes is not None
    assert model.result is not None
    assert model.labels_ is not None
    assert model.core_sample_mask_ is not None
    assert model.metrics_["points_per_sec"] > 0
    # expanded boxes are the 2*eps inflation (dbscan.py:144)
    for l, box in model.bounding_boxes.items():
        np.testing.assert_allclose(
            model.expanded_boxes[l].lower, box.lower - 2 * 0.3
        )


def test_dbscan_partition_wire_format(blobs750):
    from pypardis_tpu import dbscan_partition

    records = [((i, 7), v) for i, v in enumerate(blobs750[:100])]
    out = list(
        dbscan_partition(records, {"eps": 0.3, "min_samples": 5})
    )
    assert len(out) == 100
    for key, label in out:
        part, rest = label.split(":")
        assert part == "7"
        int(rest.rstrip("*"))  # parses


def test_map_cluster_id():
    from pypardis_tpu import map_cluster_id

    mapping = {"0:1": 5}
    assert map_cluster_id((3, ["0:1*"]), mapping) == (3, 5)
    assert map_cluster_id((4, ["0:-1"]), mapping) == (4, -1)
    assert map_cluster_id((5, ["9:9"]), mapping) == (5, -1)
