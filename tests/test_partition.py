"""KDPartitioner vs reference semantics (partition.py:98-183)."""

import numpy as np
import pytest

from pypardis_tpu.partition import (
    KDPartitioner,
    mean_var_split,
    median_search_split,
    min_var_split,
)


def test_median_search_split_exact():
    v = np.array([5.0, 1.0, 3.0, 2.0, 4.0])
    below, b = median_search_split(v)
    assert b == 3.0
    assert below.sum() == 2  # strictly-below semantics (partition.py:27-30)


def test_mean_var_split_balanced():
    rng = np.random.default_rng(0)
    v = rng.normal(size=10_001)
    below, b = mean_var_split(v)
    # mean +/- 0.9 sigma candidates guarantee balance within ~0.9 sigma mass
    frac = below.mean()
    assert 0.3 < frac < 0.7


def test_min_var_split_picks_max_variance_axis():
    rng = np.random.default_rng(1)
    pts = np.stack([rng.normal(scale=0.1, size=500),
                    rng.normal(scale=5.0, size=500)], axis=1)
    axis, below, b = min_var_split(pts)
    assert axis == 1


@pytest.mark.parametrize("method", ["min_var", "rotation", "mean_var",
                                    "median_search"])
def test_partitioner_covers_all_points(method):
    rng = np.random.default_rng(2)
    pts = rng.normal(size=(2000, 3))
    part = KDPartitioner(pts, max_partitions=8, split_method=method)
    assert part.n_partitions == 8
    all_idx = np.sort(np.concatenate(list(part.partitions.values())))
    np.testing.assert_array_equal(all_idx, np.arange(2000))
    # every point is inside its partition's box
    for label, idx in part.partitions.items():
        box = part.bounding_boxes[label]
        assert box.contains_points(pts[idx]).all()


def test_partition_balance():
    rng = np.random.default_rng(3)
    pts = rng.normal(size=(10_000, 2))
    part = KDPartitioner(pts, max_partitions=8)
    sizes = part.partition_sizes()
    # mean_var candidates bound imbalance (partition.py:55-59)
    assert sizes.max() < 3.5 * sizes.min()


def test_invalid_split_method_falls_back():
    pts = np.random.default_rng(4).normal(size=(100, 2))
    part = KDPartitioner(pts, max_partitions=4, split_method="bogus")
    assert part.split_method == "min_var"  # partition.py:129-130 semantics


def test_route_matches_training_assignment():
    rng = np.random.default_rng(5)
    pts = rng.normal(size=(3000, 3))
    part = KDPartitioner(pts, max_partitions=16)
    np.testing.assert_array_equal(part.route(pts), part.result)


def test_result_labels_consistent():
    rng = np.random.default_rng(6)
    pts = rng.normal(size=(500, 2))
    part = KDPartitioner(pts, max_partitions=4)
    for label, idx in part.partitions.items():
        assert (part.result[idx] == label).all()


def test_expanded_members_matches_box_membership():
    """Tree-replay halo routing == brute-force expanded-box query."""
    from pypardis_tpu.geometry import BoxStack
    from pypardis_tpu.partition import expanded_members

    rng = np.random.default_rng(7)
    pts = rng.normal(size=(4000, 3))
    part = KDPartitioner(pts, max_partitions=16)
    eps = 0.15
    labels = sorted(part.bounding_boxes)
    stack = BoxStack.from_boxes(
        part.bounding_boxes[l] for l in labels
    ).expand(2 * eps)
    member = stack.membership(pts)  # (N, P) oracle

    state = expanded_members(part.tree, pts, 2 * eps)
    assert set(state) == set(labels)
    for j, l in enumerate(labels):
        arr, own = state[l]
        np.testing.assert_array_equal(
            np.sort(arr), np.nonzero(member[:, j])[0]
        )
        # Strict-ownership flags reproduce the partitioner's assignment.
        np.testing.assert_array_equal(
            np.sort(arr[own]), np.sort(part.partitions[l])
        )


def test_partitioner_preserves_float32():
    pts = np.random.default_rng(8).normal(size=(1000, 2)).astype(np.float32)
    part = KDPartitioner(pts, max_partitions=4)
    assert part.points.dtype == np.float32  # no silent f64 doubling
    assert part.n_partitions == 4


# -- level-synchronous fast path vs legacy builder -------------------------


def _assert_builders_identical(pts, **kw):
    a = KDPartitioner(pts, builder="legacy", **kw)
    b = KDPartitioner(pts, builder="level", **kw)
    assert a.tree == b.tree
    np.testing.assert_array_equal(a.result, b.result)
    assert sorted(a.partitions) == sorted(b.partitions)
    for label in a.partitions:
        np.testing.assert_array_equal(
            a.partitions[label], b.partitions[label]
        )
    assert sorted(a.bounding_boxes) == sorted(b.bounding_boxes)
    for label in a.bounding_boxes:
        np.testing.assert_array_equal(
            a.bounding_boxes[label].lower, b.bounding_boxes[label].lower
        )
        np.testing.assert_array_equal(
            a.bounding_boxes[label].upper, b.bounding_boxes[label].upper
        )
    return a, b


@pytest.mark.parametrize("method", ["min_var", "rotation", "mean_var",
                                    "median_search"])
@pytest.mark.parametrize("sample_size", [None, 700])
def test_level_builder_byte_identical(method, sample_size):
    """The level-synchronous fast path reproduces the legacy builder's
    tree, result, partitions, and boxes EXACTLY — same RNG stream for
    the subsample draws, same reductions on the same row order."""
    pts = np.random.default_rng(20).normal(size=(5000, 3))
    _assert_builders_identical(
        pts, max_partitions=16, split_method=method,
        sample_size=sample_size, seed=3,
    )


def test_level_builder_budget_stop_identical():
    """A max_partitions that exhausts mid-level stops both builders at
    the same node."""
    pts = np.random.default_rng(21).normal(size=(3000, 2))
    for mp in (3, 5, 7, 11):
        _assert_builders_identical(pts, max_partitions=mp)


def test_level_builder_degenerate_identical():
    """All-equal coordinates: the exact-median fallback and the
    give-up path replicate."""
    # fully degenerate: no split possible anywhere
    pts = np.ones((100, 2))
    a, b = _assert_builders_identical(pts, max_partitions=8)
    assert a.tree == [] and a.n_partitions == 1
    # one constant axis: rotation hits the fallback on that axis
    rng = np.random.default_rng(22)
    pts = np.concatenate(
        [np.ones((400, 1)), rng.normal(size=(400, 1))], axis=1
    )
    for method in ("rotation", "min_var"):
        _assert_builders_identical(
            pts, max_partitions=8, split_method=method
        )


def test_level_builder_fortran_order_identical():
    pts = np.asfortranarray(
        np.random.default_rng(23).normal(size=(2000, 4))
    )
    _assert_builders_identical(pts, max_partitions=8)


def test_level_builder_emits_level_times():
    pts = np.random.default_rng(24).normal(size=(4000, 3))
    part = KDPartitioner(pts, max_partitions=16, builder="level")
    assert part.builder == "level"
    # 16 partitions = 4 complete levels, one timing each
    assert len(part.level_times_s) == 4
    assert all(t >= 0 for t in part.level_times_s)
    legacy = KDPartitioner(pts, max_partitions=16, builder="legacy")
    assert len(legacy.level_times_s) == 4


def test_builder_auto_resolution(tmp_path):
    pts = np.random.default_rng(25).normal(size=(500, 2))
    assert KDPartitioner(pts, max_partitions=4).builder == "level"
    mm_path = tmp_path / "pts.bin"
    mm = np.memmap(mm_path, dtype=np.float64, mode="w+", shape=(500, 2))
    mm[:] = pts
    # memmaps keep the O(index)-memory legacy build (the level buffer
    # would materialize the dataset in RAM)
    part = KDPartitioner(mm, max_partitions=4)
    assert part.builder == "legacy"
    with pytest.raises(ValueError):
        KDPartitioner(pts, builder="bogus")


def test_level_pool_reuse_stays_correct():
    """Pooled level buffers are reused across builds — a second build
    on DIFFERENT data of the same shape must not inherit anything."""
    from pypardis_tpu.partition import clear_level_pool

    clear_level_pool()
    rng = np.random.default_rng(26)
    pts1 = rng.normal(size=(3000, 3))
    pts2 = rng.normal(size=(3000, 3)) + 5.0
    KDPartitioner(pts1, max_partitions=8, builder="level")
    b = KDPartitioner(pts2, max_partitions=8, builder="level")
    a = KDPartitioner(pts2, max_partitions=8, builder="legacy")
    assert a.tree == b.tree
    np.testing.assert_array_equal(a.result, b.result)
    clear_level_pool()
