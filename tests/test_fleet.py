"""Fleet flight aggregation (pypardis_tpu.obs.fleet, ISSUE 16).

Three synthetic per-host flight files — wall-clock anchors 1000.0 /
1000.5 / 1001.25s, the third killed mid-span with a truncated final
line — aggregated via ``obs.replay(<dir>)``: clock-offset alignment,
one Chrome-trace lane per host, byte-deterministic merged outputs,
pooled registries/histograms, fleet-level partial report, and the
stdlib run monitor rendering the same directory.
"""

import json
import os
import subprocess
import sys

import pytest

from pypardis_tpu import obs
from pypardis_tpu.obs.export import Histogram
from pypardis_tpu.obs.fleet import FleetReplay

MONITOR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "scripts", "monitor.py",
)


def _hist_snap():
    h = Histogram(window_s=60)
    for v in (1.0, 4.0, 16.0):
        h.observe(v)
    return h.snapshot()


def _write_member(path, t_unix, pid, fin=True, truncate=False):
    lines = [
        {"k": "header", "schema": "pypardis_tpu/flight@1",
         "pid": pid, "t_unix": t_unix},
        {"k": "so", "id": 0, "name": "fit", "t": 0.01, "depth": 0,
         "a": {}},
        {"k": "so", "id": 1, "name": "cluster", "t": 0.02, "depth": 1,
         "a": {}},
        {"k": "hb", "stage": "gm.ring", "done": 3, "total": 7,
         "eta_s": 1.5, "t": 0.05},
        {"k": "tm", "key": "phase.cluster", "s": 0.2, "t": 0.25},
        {"k": "h", "key": "serving.latency_ms", "t": 0.3,
         "snap": _hist_snap()},
        {"k": "rs", "rss": 1000.0 * pid, "t": 0.3},
    ]
    if fin:
        lines += [
            {"k": "sc", "id": 1, "name": "cluster", "t": 0.02,
             "dur": 0.3, "a": {}},
            {"k": "sc", "id": 0, "name": "fit", "t": 0.01, "dur": 0.4,
             "a": {}},
            {"k": "fin", "status": "ok", "t": 0.45},
        ]
    txt = "\n".join(json.dumps(r) for r in lines) + "\n"
    if truncate:
        txt += '{"k": "rs", "rss": 123'  # SIGKILL mid-write, no newline
    path.write_text(txt, encoding="utf-8")


@pytest.fixture()
def fleet_dir(tmp_path):
    d = tmp_path / "runs"
    d.mkdir()
    # File names sort AGAINST the wall-clock order on purpose: the
    # merge must order hosts by their t_unix anchor, not the listing.
    _write_member(d / "flight-c.jsonl", 1000.0, pid=11)
    _write_member(d / "flight-a.jsonl", 1000.5, pid=22)
    _write_member(d / "flight-b.jsonl", 1001.25, pid=33, fin=False,
                  truncate=True)
    return d


def test_replay_dispatches_directories_to_fleet(fleet_dir):
    rep = obs.replay(str(fleet_dir))
    assert isinstance(rep, FleetReplay)


def test_clock_offset_alignment_and_host_order(fleet_dir):
    rep = FleetReplay(str(fleet_dir))
    assert [h["pid"] for h in rep.hosts] == [11, 22, 33]
    assert [h["offset_s"] for h in rep.hosts] == [0.0, 0.5, 1.25]
    assert all(h["aligned"] for h in rep.hosts)
    assert [h["complete"] for h in rep.hosts] == [True, True, False]
    assert rep.hosts[2]["open_spans"] == ["fit", "cluster"]


def test_fleet_report_partial_and_pooled_hists(fleet_dir):
    rep = FleetReplay(str(fleet_dir))
    r = rep.report()
    assert r["schema"] == "pypardis_tpu/fleet_report@1"
    assert r["hosts"] == 3 and r["aligned_hosts"] == 3
    assert r["partial"] is True and r["complete"] is False
    json.dumps(r)  # serializable end to end
    # registries pool: 3 hosts x 3 observations each
    hist = r["registry"]["hists"]["serving.latency_ms"]
    assert hist["count"] == 9
    # heartbeats keyed per host on the aligned clock
    hbs = r["heartbeats"]
    assert set(hbs) == {
        "gm.ring@host0", "gm.ring@host1", "gm.ring@host2",
    }
    assert hbs["gm.ring@host2"]["t_s"] == pytest.approx(1.3)
    # summary renders the per-host death site
    s = rep.summary()
    assert "PARTIAL" in s
    assert "killed inside fit,cluster" in s


def test_chrome_trace_one_lane_per_host(fleet_dir):
    doc = FleetReplay(str(fleet_dir)).to_chrome_trace()
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    metas = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert {e["pid"] for e in xs} <= {0, 1, 2}
    assert {e["args"]["name"] for e in metas} == {
        "host0 pid=11", "host1 pid=22", "host2 pid=33",
    }
    ts = [e["ts"] for e in xs]
    assert ts == sorted(ts)


def test_merged_outputs_byte_deterministic(fleet_dir, tmp_path):
    a, b = FleetReplay(str(fleet_dir)), FleetReplay(str(fleet_dir))
    ta, tb = tmp_path / "a.json", tmp_path / "b.json"
    a.export_chrome_trace(str(ta))
    b.export_chrome_trace(str(tb))
    assert ta.read_bytes() == tb.read_bytes()
    ma, mb = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    a.write_merged(str(ma))
    b.write_merged(str(mb))
    assert ma.read_bytes() == mb.read_bytes()

    records = [
        json.loads(ln) for ln in ma.read_text().splitlines() if ln
    ]
    # aligned time order, every record host-stamped, bad line dropped
    assert all("host" in r for r in records)
    times = [r["t"] for r in records]
    assert times == sorted(times)
    assert len(records) == a.records


def test_monitor_renders_fleet_directory(fleet_dir):
    out = subprocess.run(
        [sys.executable, MONITOR, str(fleet_dir), "--once", "--json"],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 0, out.stderr
    frame = json.loads(out.stdout)
    assert frame["schema"] == "pypardis_tpu/monitor_frame@1"
    assert len(frame["hosts"]) == 3
    by_pid = {h["pid"]: h for h in frame["hosts"]}
    assert by_pid[11]["finished"] == "ok"
    assert by_pid[33]["finished"] is None
    assert by_pid[33]["phase_stack"] == ["fit", "cluster"]
    assert by_pid[22]["hists"]["serving.latency_ms"]["count"] == 3
    assert by_pid[22]["heartbeats"]["gm.ring"]["done"] == 3

    txt = subprocess.run(
        [sys.executable, MONITOR, str(fleet_dir), "--once"],
        capture_output=True, text=True, timeout=60,
    )
    assert txt.returncode == 0, txt.stderr
    assert "FINISHED ok" in txt.stdout
    assert "gm.ring" in txt.stdout
