"""Device-resident sharded input: no host round trip of the dataset.

Round-3 review, Missing #2 / Next #4: the sharded path must accept a
device-resident ``jax.Array`` the way the reference's ``train(rdd)``
accepts already-distributed data — KD-split from a host subsample,
route/gather on device — without bouncing the (N, k) coordinates
through ``np.asarray``.
"""

import jax
import numpy as np
import pytest
from sklearn.datasets import make_blobs
from sklearn.metrics import adjusted_rand_score

from pypardis_tpu import DBSCAN
from pypardis_tpu.parallel import (
    default_mesh,
    sharded_dbscan,
    sharded_dbscan_device,
)
from pypardis_tpu.partition import KDPartitioner


def _blobs(n=4000, k=3, seed=5):
    X, _ = make_blobs(
        n_samples=n, centers=10, n_features=k, cluster_std=0.3,
        random_state=seed,
    )
    return X.astype(np.float32)


def test_device_resident_input_matches_host(monkeypatch):
    """The device route produces the same clustering as the host route,
    and never fetches the (N, k) coordinate array to the host."""
    X = _blobs()
    n, k = X.shape
    mesh = default_mesh(8)
    part = KDPartitioner(X, max_partitions=8)
    ref, ref_core, _ = sharded_dbscan(
        X, part, eps=0.4, min_samples=5, block=64, mesh=mesh
    )

    fetched = []
    orig_asarray = np.asarray

    def spy(a, *args, **kwargs):
        if isinstance(a, jax.Array) and getattr(a, "shape", None) == (n, k):
            fetched.append(a.shape)
        return orig_asarray(a, *args, **kwargs)

    monkeypatch.setattr(np, "asarray", spy)
    Xd = jax.device_put(X)
    labels, core, stats, _part, pid = sharded_dbscan_device(
        Xd, eps=0.4, min_samples=5, block=64, mesh=mesh,
        sample_size=1000,  # < n: the subsample fetch must not be (n, k)
    )
    monkeypatch.setattr(np, "asarray", orig_asarray)

    assert fetched == [], "the (N, k) coordinates were fetched to host"
    assert stats["input"] == "device"
    np.testing.assert_array_equal(core, ref_core)
    # Identical clustering: canonicalized labels are partition-agnostic
    # on core points; border points reachable from several clusters are
    # legitimately assignment-ambiguous (reference README.md:28-33), so
    # compare those by ARI.
    np.testing.assert_array_equal(labels[ref_core], ref[ref_core])
    np.testing.assert_array_equal(labels == -1, ref == -1)
    assert adjusted_rand_score(labels, ref) >= 0.999
    # The routed assignment covers all points across the mesh's
    # partition count.
    pid_np = np.asarray(pid)
    assert pid_np.shape == (n,) and len(np.unique(pid_np)) == 8


def test_dbscan_api_device_resident_sharded():
    """DBSCAN.fit on a jax.Array takes the device route end to end and
    keeps the parity attribute surface."""
    X = _blobs(n=2000)
    ref = DBSCAN(eps=0.4, min_samples=5, block=64).fit_predict(X)
    m = DBSCAN(eps=0.4, min_samples=5, block=64)
    labels = m.fit_predict(jax.device_put(X))
    assert adjusted_rand_score(labels, ref) >= 0.999
    assert m.metrics_.get("input") == "device"
    assert m.metrics_["n_partitions"] >= 2
    assert set(m.neighbors) == set(m.bounding_boxes) & set(m.neighbors)
    assert m.cluster_dict and all(
        ":" in key for key in m.cluster_dict
    )
    # result stays key-sorted (the reference's sortByKey contract)
    keys = [key for key, _ in m.result]
    assert keys == sorted(keys)


def test_device_route_matches_host_route():
    from pypardis_tpu.parallel.device_input import device_route, tree_arrays

    X = _blobs(n=1500, k=4)
    part = KDPartitioner(X, max_partitions=8)
    host = part.route(X)
    dev = np.asarray(
        device_route(jax.device_put(X), *map(jax.numpy.asarray,
                                             tree_arrays(part.tree)))
    )
    np.testing.assert_array_equal(host, dev)


def test_device_route_single_partition():
    from pypardis_tpu.parallel.device_input import device_route, tree_arrays

    X = _blobs(n=64)
    part = KDPartitioner(X, max_partitions=1)
    dev = np.asarray(
        device_route(jax.device_put(X), *map(jax.numpy.asarray,
                                             tree_arrays(part.tree)))
    )
    assert (dev == 0).all()


def test_device_input_merge_host_honored(monkeypatch):
    """merge='host' on a device-resident input runs the ring exchange
    device-side and spills only the compact occurrence tables to the
    host union-find — the input stays on device (round-4 review, Next
    #6: this used to fetch the whole dataset and bounce to the host
    path; before that, 'host' was silently replaced by the device
    merge)."""
    X = _blobs(n=2000)
    n, k = X.shape

    fetched = []
    orig_asarray = np.asarray

    def spy(a, *args, **kwargs):
        if isinstance(a, jax.Array) and getattr(a, "shape", None) == (n, k):
            fetched.append(a.shape)
        return orig_asarray(a, *args, **kwargs)

    # Cap the KD subsample below n (at tiny n the "subsample" would
    # otherwise be a full fetch by design) so the spy isolates the
    # merge path's traffic.
    import functools

    import pypardis_tpu.parallel.sharded as sm

    monkeypatch.setattr(
        sm, "sharded_dbscan_device",
        functools.partial(sm.sharded_dbscan_device, sample_size=500),
    )
    m = DBSCAN(eps=0.4, min_samples=5, block=64, merge="host")
    monkeypatch.setattr(np, "asarray", spy)
    labels = m.fit_predict(jax.device_put(X))
    monkeypatch.setattr(np, "asarray", orig_asarray)
    assert fetched == [], "the (N, k) coordinates were fetched to host"
    assert m.metrics_.get("merge") == "host"
    assert m.metrics_.get("input") == "device"
    ref = DBSCAN(eps=0.4, min_samples=5, block=64).fit_predict(X)
    assert adjusted_rand_score(labels, ref) >= 0.999


def test_sharded_ring_host_merge_matches_device_merge():
    """halo='ring' + merge='host' (the >MERGE_HOST_AUTO spill path) is
    label-identical to ring + in-graph merge and to the host-halo
    host-merge path."""
    X = _blobs(n=4000, k=3)
    mesh = default_mesh(8)
    part = KDPartitioner(X, max_partitions=8)
    ring_dev, core_a, _ = sharded_dbscan(
        X, part, eps=0.4, min_samples=5, block=64, mesh=mesh, halo="ring",
        merge="device",
    )
    ring_host, core_b, stats = sharded_dbscan(
        X, part, eps=0.4, min_samples=5, block=64, mesh=mesh, halo="ring",
        merge="host",
    )
    host_host, core_c, _ = sharded_dbscan(
        X, part, eps=0.4, min_samples=5, block=64, mesh=mesh, halo="host",
        merge="host",
    )
    assert stats.get("merge") == "host"
    assert stats.get("halo_exchange") == "ring"
    np.testing.assert_array_equal(ring_dev, ring_host)
    np.testing.assert_array_equal(ring_host, host_host)
    np.testing.assert_array_equal(core_a, core_b)
    np.testing.assert_array_equal(core_b, core_c)


def test_sharded_auto_merge_crosses_to_host_on_ring(monkeypatch):
    """merge='auto' switches to the host merge past MERGE_HOST_AUTO on
    the ring path too (it used to pin merge='device' there)."""
    import pypardis_tpu.parallel.sharded as sm

    X = _blobs(n=2000, k=3)
    mesh = default_mesh(8)
    part = KDPartitioner(X, max_partitions=8)
    monkeypatch.setattr(sm, "MERGE_HOST_AUTO", 1000)
    labels, _core, stats = sharded_dbscan(
        X, part, eps=0.4, min_samples=5, block=64, mesh=mesh, halo="ring",
        merge="auto",
    )
    assert stats.get("merge") == "host"
    ref, _c, _s = sharded_dbscan(
        X, part, eps=0.4, min_samples=5, block=64, mesh=mesh, halo="ring",
        merge="device",
    )
    np.testing.assert_array_equal(labels, ref)


def test_device_boxes_contain_routed_points():
    """The device path's parity boxes replay the split planes from an
    all-space root, so every routed point is inside its box — including
    full-data extremes absent from the subsample."""
    X = _blobs(n=4000)
    m = DBSCAN(eps=0.4, min_samples=5, block=64)
    m.fit(jax.device_put(X))
    for label, idx in m.partitioner_.partitions.items():
        box = m.bounding_boxes[label]
        assert box.contains_points(X[idx]).all()


def test_device_route_neighbors_expanded_membership():
    """``neighbors`` means 2*eps-expanded membership on EVERY route
    (round-4 advisor: the device route used to return owned points) —
    computed lazily from the split tree on first access."""
    from pypardis_tpu.partition import expanded_members

    X = _blobs(n=4000)
    m = DBSCAN(eps=0.4, min_samples=5, block=64)
    m.fit(jax.device_put(X))
    assert m._neighbors is None  # fit itself never materialized it
    members = expanded_members(m.partitioner_.tree, X, 2 * m.eps)
    assert set(m.neighbors) == set(members)
    for label, idx in m.neighbors.items():
        np.testing.assert_array_equal(np.sort(idx),
                                      np.sort(members[label][0]))
        # expanded membership is a superset of the owned points
        owned = m.partitioner_.partitions.get(label, np.empty(0, int))
        assert np.isin(owned, idx).all()
        # and everything in it sits inside the expanded parity box
        assert m.expanded_boxes[label].contains_points(X[idx]).all()


def test_sharded_device_rejects_nothing_small():
    """Tiny inputs still work through the device route."""
    X = _blobs(n=64, k=2)
    labels, core, stats, _p, _pid = sharded_dbscan_device(
        jax.device_put(X), eps=0.4, min_samples=5, block=64,
        mesh=default_mesh(8),
    )
    assert labels.shape == (64,)
    assert labels.max() >= 0
