"""Mixed-precision distance pass with exact rescoring (ISSUE 7).

The contract under test: ``precision="mixed"`` runs the bulk pairwise
pass at the single-pass bf16 peak with a conservatively derived error
band around eps^2, rescores only tiles containing in-band pairs at
``high`` — and the LABELS ARE BYTE-IDENTICAL to ``precision="highest"``
on adversarial near-threshold geometries (points planted at
eps*(1 +- 1e-4) of each other, duplicate coordinates), across the
fused kernel, both KD halo modes, global-Morton, the chained 1-device
route, and serving ``predict``.  Not ARI-equal: ``np.array_equal``.
"""

import functools

import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.datasets import make_blobs

from pypardis_tpu import DBSCAN
from pypardis_tpu.ops.labels import dbscan_fixed_size
from pypardis_tpu.parallel import default_mesh, sharded_dbscan, staging
from pypardis_tpu.partition import KDPartitioner

EPS = 0.9
MS = 6


@pytest.fixture(autouse=True)
def _fresh_staging():
    staging.clear()
    yield
    staging.clear()


def _adversarial(n=3000, d=8, seed=3):
    """Blobs + near-eps shells + duplicates: every way a bf16 verdict
    could flip sits in this set.

    Each planted pair straddles eps by a relative 1e-4 — far inside
    the fast pass's worst-case band (so the rescore path MUST fire)
    and far outside high/highest's ~2^-18 error (so those two agree,
    making byte-equality to highest a meaningful oracle).
    """
    rng = np.random.default_rng(seed)
    X, _ = make_blobs(
        n_samples=n, centers=12, n_features=d, cluster_std=0.25,
        random_state=seed,
    )
    X = X.astype(np.float32)
    # Duplicate coordinates (d^2 == 0 exactly on every path).
    X[10] = X[11]
    X[12] = X[13] = X[14]
    # Near-eps shells around a handful of anchor points.
    for i, anchor in enumerate(range(0, 50, 5)):
        v = rng.normal(size=d)
        v /= np.linalg.norm(v)
        X[100 + 2 * i] = X[anchor] + (EPS * (1 - 1e-4)) * v
        X[101 + 2 * i] = X[anchor] + (EPS * (1 + 1e-4)) * v
    return X


@pytest.fixture(scope="module")
def adv():
    return _adversarial()


def _fixed_size(X, precision, backend="xla"):
    n = len(X)
    cap = ((n + 255) // 256) * 256
    pts = np.zeros((cap, X.shape[1]), np.float32)
    pts[:n] = X - X.mean(axis=0)
    mask = np.arange(cap) < n
    out = dbscan_fixed_size(
        jnp.asarray(pts), EPS, MS, jnp.asarray(mask), block=256,
        precision=precision, backend=backend,
    )
    return [np.asarray(o) for o in out]


def test_fused_xla_mixed_byte_identical_and_banded(adv):
    l_hi, c_hi, ps_hi = _fixed_size(adv, "highest")
    l_mx, c_mx, ps_mx = _fixed_size(adv, "mixed")
    assert np.array_equal(l_hi, l_mx)
    assert np.array_equal(c_hi, c_mx)
    # pair_stats widened to [total, budget, passes, band_pairs,
    # rescored_tiles]; the near-eps plants guarantee in-band pairs.
    assert ps_mx.shape == (5,)
    assert ps_mx[3] > 0, "near-eps geometry produced no in-band pairs"
    assert ps_mx[4] > 0, "in-band pairs but no tile marked for rescore"
    # Non-mixed rows carry zero band columns.
    assert ps_hi[3] == 0 and ps_hi[4] == 0


def test_fused_pallas_interpret_mixed_byte_identical(adv, monkeypatch):
    """Pallas mixed == Pallas high, byte-identical.

    The per-backend contract: mixed's rescore replays the SAME
    arithmetic as that backend's ``high`` pass (the bf16_3x split on
    Pallas), so the right oracle here is Pallas ``high`` — XLA
    ``highest`` differs from the split by last-ulp on NATURAL near-eps
    pairs in random blobs, which is the documented high-vs-highest gap,
    not a mixed-mode defect.  (No cross-backend assertion here: even a
    planted point's CORE status counts its natural neighbors, any of
    which may sit inside the legitimate high-vs-highest ulp gap — the
    XLA test above is where mixed == highest holds bitwise, because
    CPU XLA's default/high/highest dots are one and the same f32
    kernel.)
    """
    from pypardis_tpu.ops import pallas_kernels as pk

    monkeypatch.setattr(
        pk, "neighbor_counts_pallas",
        functools.partial(pk.neighbor_counts_pallas, interpret=True),
    )
    monkeypatch.setattr(
        pk, "min_neighbor_label_pallas",
        functools.partial(pk.min_neighbor_label_pallas, interpret=True),
    )
    l_h, c_h, _ = _fixed_size(adv, "high", backend="pallas")
    l_p, c_p, ps_p = _fixed_size(adv, "mixed", backend="pallas")
    assert np.array_equal(l_h, l_p)
    assert np.array_equal(c_h, c_p)
    assert ps_p[3] > 0 and ps_p[4] > 0


@pytest.mark.parametrize(
    "kw",
    [
        dict(),  # KD owner-computes, device merge
        dict(merge="host"),  # KD owner-computes, collective-free merge
        dict(owner_computes=False),  # legacy duplicate-and-recluster
    ],
    ids=["oc-device", "oc-host", "legacy"],
)
def test_kd_sharded_mixed_byte_identical(adv, kw):
    ref = DBSCAN(
        eps=EPS, min_samples=MS, block=64, precision="highest", **kw
    ).fit(adv)
    got = DBSCAN(
        eps=EPS, min_samples=MS, block=64, precision="mixed", **kw
    ).fit(adv)
    assert np.array_equal(ref.labels_, got.labels_)
    assert np.array_equal(ref.core_sample_mask_, got.core_sample_mask_)
    comp = got.report()["compute"]
    assert comp["precision_mode"] == "mixed"
    assert comp["band_pairs"] > 0
    assert 0.0 <= comp["band_fraction"] <= 1.0


@pytest.mark.parametrize("merge", ["device", "host"])
def test_global_morton_mixed_byte_identical(adv, merge):
    ref = DBSCAN(
        eps=EPS, min_samples=MS, block=64, precision="highest",
        mode="global_morton", merge=merge,
    ).fit(adv)
    got = DBSCAN(
        eps=EPS, min_samples=MS, block=64, precision="mixed",
        mode="global_morton", merge=merge,
    ).fit(adv)
    assert np.array_equal(ref.labels_, got.labels_)
    assert np.array_equal(ref.core_sample_mask_, got.core_sample_mask_)
    assert got.report()["compute"]["band_pairs"] > 0


def test_chained_1dev_mixed_byte_identical(adv):
    part = KDPartitioner(adv, max_partitions=8)
    kw = dict(eps=EPS, min_samples=MS, block=64, mesh=default_mesh(1))
    l_hi, c_hi, _ = sharded_dbscan(adv, part, precision="highest", **kw)
    staging.clear()
    l_mx, c_mx, stats = sharded_dbscan(adv, part, precision="mixed", **kw)
    assert np.array_equal(l_hi, l_mx)
    assert np.array_equal(c_hi, c_mx)
    assert stats.get("band_pairs", 0) > 0


def test_serving_mixed_bitwise_oracle(adv):
    """Mixed-mode serving prunes with bf16 and rescores through the
    sealed path — labels AND d2 stay bitwise equal to the numpy
    oracle, on the XLA and Pallas-interpret query kernels."""
    from pypardis_tpu.serve import QueryEngine

    model = DBSCAN(
        eps=EPS, min_samples=MS, block=64, precision="mixed"
    ).fit(adv)
    eng = model.query_engine()
    # The engine inherits the model's mixed mode.
    assert eng.precision == "mixed"
    idx = eng.index
    rng = np.random.default_rng(7)
    Q = rng.normal(size=(400, adv.shape[1])).astype(np.float32) * 3
    cores = np.asarray(model.data)[model.core_sample_mask_]
    v = rng.normal(size=adv.shape[1])
    v /= np.linalg.norm(v)
    Q[0] = cores[0] + (EPS * (1 - 1e-4)) * v
    Q[1] = cores[0] + (EPS * (1 + 1e-4)) * v
    Q[2] = cores[1]  # exact duplicate of a core point
    want_lab, want_d2 = idx.oracle_predict(Q)
    for be, interp in (("xla", False), ("pallas", True)):
        e = QueryEngine(
            idx, backend=be, interpret=interp, precision="mixed"
        )
        lab, dist = e.predict(Q, return_distance=True)
        assert np.array_equal(lab, want_lab), be
        assert np.array_equal(dist, np.sqrt(want_d2)), be
        assert e.serving_stats()["precision"] == "mixed"


def test_mixed_rejects_cityblock(adv):
    with pytest.raises(ValueError, match="euclidean"):
        _ = DBSCAN(
            eps=EPS, min_samples=MS, metric="cityblock",
            precision="mixed",
        ).fit(adv)


def test_constructor_validates_precision_and_backend():
    """Satellite: a typo'd precision/backend fails AT CONSTRUCTION
    with the allowed list, not deep inside a jit trace at first fit."""
    import jax

    with pytest.raises(ValueError, match="precision"):
        DBSCAN(precision="hgih")
    with pytest.raises(ValueError, match="kernel_backend"):
        DBSCAN(kernel_backend="cuda")
    with pytest.raises(ValueError, match="eps"):
        DBSCAN(eps=-1.0)
    # jax.lax.Precision spellings canonicalize to the mode strings.
    assert DBSCAN(precision=jax.lax.Precision.HIGH).precision == "high"
    assert DBSCAN(precision="MIXED").precision == "mixed"


def test_report_band_fields_always_present(adv):
    """Every fit carries the mixed telemetry fields (zeros off
    mixed), so bench rows stay schema-stable across modes."""
    m = DBSCAN(eps=EPS, min_samples=MS, block=64).fit(adv)
    comp = m.report()["compute"]
    assert comp["precision_mode"] == "high"
    assert comp["band_pairs"] == 0
    assert comp["rescored_pairs"] == 0
    assert comp["band_fraction"] == 0.0
    assert comp["mfu_f32_synth"] >= comp["mfu"]
