"""Merge-loop convergence signalling + sharded pair-budget ladder.

Round-3 review items: (1) the in-graph merge must never return
under-merged labels silently — non-convergence is detected, retried
once at 4x rounds, then raised; (2) the sharded driver's pair-budget
overflow rerun must be exercisable off-hardware (the XLA path now
reports real live-pair totals), and reruns must seed the shared hint
cache so refits compile the right program the first time.
"""

import numpy as np
import pytest

import pypardis_tpu.parallel.sharded as sharded_mod
from pypardis_tpu.parallel import default_mesh, sharded_dbscan
from pypardis_tpu.partition import KDPartitioner
from pypardis_tpu.utils.hints import PAIR_BUDGET_HINTS


@pytest.fixture(autouse=True)
def _clean_hints():
    PAIR_BUDGET_HINTS.clear()
    yield
    PAIR_BUDGET_HINTS.clear()


def _chain_data(n=256, k=2, step=0.09):
    """A single line of points threading every KD partition: the
    worst case for merge depth (one cluster chained across all 8)."""
    x = np.arange(n, dtype=np.float64) * step
    pts = np.zeros((n, k))
    pts[:, 0] = x
    return pts


def test_nonconvergence_detected_and_retried():
    """merge_rounds=1 cannot certify a fixpoint on chained-partition
    data; the driver must retry at 4x and return CORRECT labels (the
    silent under-merge of round 3 is gone)."""
    X = _chain_data()
    mesh = default_mesh(8)
    part = KDPartitioner(X, max_partitions=8)
    ref, _, _ = sharded_dbscan(
        X, part, eps=0.2, min_samples=2, block=64, mesh=mesh,
        merge="device",
    )
    labels, _, stats = sharded_dbscan(
        X, part, eps=0.2, min_samples=2, block=64, mesh=mesh,
        merge="device", merge_rounds=1,
    )
    assert stats["merge_converged"] is True
    np.testing.assert_array_equal(labels, ref)
    # the chain really is one cluster — under-merge would split it
    assert labels.max() == labels.min() >= 0


def test_nonconvergence_raises_instead_of_silent_undermerge():
    """With zero rounds allowed (retry: still zero), the driver must
    raise — not hand back the identity label map as a result."""
    X = _chain_data()
    part = KDPartitioner(X, max_partitions=8)
    with pytest.raises(RuntimeError, match="did not converge"):
        sharded_dbscan(
            X, part, eps=0.2, min_samples=2, block=64,
            mesh=default_mesh(8), merge="device", merge_rounds=0,
        )


def test_nonconvergence_ring_detected():
    X = _chain_data()
    part = KDPartitioner(X, max_partitions=8)
    ref, _, _ = sharded_dbscan(
        X, part, eps=0.2, min_samples=2, block=64, mesh=default_mesh(8),
        halo="ring",
    )
    labels, _, stats = sharded_dbscan(
        X, part, eps=0.2, min_samples=2, block=64, mesh=default_mesh(8),
        halo="ring", merge_rounds=1,
    )
    assert stats["merge_converged"] is True
    np.testing.assert_array_equal(labels, ref)
    with pytest.raises(RuntimeError, match="did not converge"):
        sharded_dbscan(
            X, part, eps=0.2, min_samples=2, block=64,
            mesh=default_mesh(8), halo="ring", merge_rounds=0,
        )


def _spy_step(monkeypatch):
    calls = []
    orig = sharded_mod.sharded_step

    def spy(*args, **kwargs):
        calls.append(kwargs.get("pair_budget"))
        return orig(*args, **kwargs)

    monkeypatch.setattr(sharded_mod, "sharded_step", spy)
    return calls


def test_pair_budget_overflow_rerun_and_hint_reuse(monkeypatch):
    """An explicit too-small pair budget triggers the overflow rerun on
    the CPU mesh (real XLA-path totals), labels stay correct, the exact
    budget lands in the hint cache, and the NEXT fit of the same
    configuration runs the compiled-right program once."""
    from sklearn.datasets import make_blobs

    X, _ = make_blobs(
        n_samples=2000, centers=8, n_features=3, cluster_std=0.3,
        random_state=1,
    )
    mesh = default_mesh(8)
    part = KDPartitioner(X, max_partitions=8)
    ref, _, _ = sharded_dbscan(
        X, part, eps=0.4, min_samples=5, block=64, mesh=mesh,
        merge="device",
    )

    calls = _spy_step(monkeypatch)
    labels, _, _ = sharded_dbscan(
        X, part, eps=0.4, min_samples=5, block=64, mesh=mesh,
        merge="device", pair_budget=1,
    )
    np.testing.assert_array_equal(labels, ref)
    assert calls[0] == 1 and len(calls) == 2 and calls[1] > 1
    assert len(PAIR_BUDGET_HINTS) == 1  # seeded from the rerun

    calls.clear()
    labels2, _, _ = sharded_dbscan(
        X, part, eps=0.4, min_samples=5, block=64, mesh=mesh,
        merge="device",
    )
    np.testing.assert_array_equal(labels2, ref)
    assert len(calls) == 1 and calls[0] is not None  # hint, no rerun


def test_no_hint_seeded_without_overflow(monkeypatch):
    """ADVICE r3 (medium): a fit whose default budget was fine must NOT
    seed a hint — seeding would recompile the second fit of every
    configuration."""
    from sklearn.datasets import make_blobs

    X, _ = make_blobs(
        n_samples=1000, centers=4, n_features=3, cluster_std=0.3,
        random_state=2,
    )
    part = KDPartitioner(X, max_partitions=8)
    calls = _spy_step(monkeypatch)
    sharded_dbscan(
        X, part, eps=0.4, min_samples=5, block=64, mesh=default_mesh(8),
        merge="device",
    )
    assert len(calls) == 1 and calls[0] is None
    assert len(PAIR_BUDGET_HINTS) == 0
    # a refit passes pair_budget=None again -> same compiled program
    calls.clear()
    sharded_dbscan(
        X, part, eps=0.4, min_samples=5, block=64, mesh=default_mesh(8),
        merge="device",
    )
    assert calls == [None]


def test_host_merge_budget_rerun(monkeypatch):
    """The merge='host' path's rerun site also executes in CI."""
    from sklearn.datasets import make_blobs

    X, _ = make_blobs(
        n_samples=2000, centers=8, n_features=3, cluster_std=0.3,
        random_state=4,
    )
    mesh = default_mesh(8)
    part = KDPartitioner(X, max_partitions=8)
    ref, _, _ = sharded_dbscan(
        X, part, eps=0.4, min_samples=5, block=64, mesh=mesh,
        merge="host",
    )
    labels, _, stats = sharded_dbscan(
        X, part, eps=0.4, min_samples=5, block=64, mesh=mesh,
        merge="host", pair_budget=1,
    )
    assert stats["merge"] == "host"
    np.testing.assert_array_equal(labels, ref)
    assert len(PAIR_BUDGET_HINTS) == 1


def test_single_shard_hint_cache_bounded():
    """ADVICE r3 (low): the hint cache is LRU-bounded, not a leak."""
    from pypardis_tpu.utils.hints import BudgetHintCache

    c = BudgetHintCache(maxsize=4)
    for i in range(10):
        c.put(("k", i), i)
    assert len(c) == 4
    assert c.get(("k", 9)) == 9 and c.get(("k", 0)) is None
    # recency refresh: touching an old entry protects it
    c.put(("fresh", 0), 1)
    assert c.get(("k", 9)) == 9  # still present (was refreshed by get)
