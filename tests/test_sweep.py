"""Amortized hyperparameter sweeps (ISSUE 13).

One distance pass at eps_max materializes the neighbor-pair graph;
every (eps, min_samples) config re-thresholds cached d2 and
label-propagates over the cached pair list.  The correctness bar is
the repo's usual one: each sweep config's labels BYTE-IDENTICAL to an
independent train() at that config on the same mode — fused, KD
owner-computes, global-Morton — plus the overflow degradation rung,
eps-order invariance, degenerate geometries, and the staging economy
(owned slabs eps-free, graph slab reused by configs 2..k).
"""

import numpy as np
import pytest
from sklearn.datasets import make_blobs

from pypardis_tpu import DBSCAN, sweep_dbscan
from pypardis_tpu.parallel import default_mesh
from pypardis_tpu.parallel import staging

EPS_LIST = [0.25, 0.4]
MS_LIST = [3, 5]
KW = dict(min_samples=5, block=128)


@pytest.fixture(scope="module")
def blobs():
    X, _ = make_blobs(
        n_samples=1200, centers=5, n_features=3, cluster_std=0.3,
        random_state=3,
    )
    return X


def _solo(X, eps, ms, **kw):
    m = DBSCAN(eps=eps, min_samples=ms, **kw)
    m.fit(X)
    return np.asarray(m.labels_), np.asarray(m.core_sample_mask_)


def _assert_parity(X, res, tag, **kw):
    for eps, ms in res.configs:
        ref_l, ref_c = _solo(X, eps, ms, **kw)
        np.testing.assert_array_equal(
            res.labels(eps, ms), ref_l, err_msg=f"{tag} eps={eps} ms={ms}"
        )
        np.testing.assert_array_equal(
            res.core(eps, ms), ref_c, err_msg=f"{tag} eps={eps} ms={ms}"
        )


def test_fused_byte_parity(blobs):
    """1-device sweep == per-config fused train(), Morton-first
    numbering included, across both min_samples values."""
    kw = dict(block=128, mesh=default_mesh(1))
    m = DBSCAN(eps=0.4, min_samples=5, **kw)
    res = m.sweep(blobs, EPS_LIST, MS_LIST)
    assert res.stats["distance_passes"] == 1
    assert res.stats["graph_pairs"] > 0
    assert len(res) == len(EPS_LIST) * len(MS_LIST)
    _assert_parity(blobs, res, "fused", **kw)
    # The sweep leaves the model fitted at the LAST config.
    last = res.configs[-1]
    np.testing.assert_array_equal(m.labels_, res.labels(*last))
    rep = m.report()
    assert rep["sweep"]["distance_passes"] == 1
    assert rep["sweep"]["k"] == len(res)
    assert isinstance(rep["sweep"]["owner_computes"], bool)
    assert rep["sweep"]["dispatch"] in ("pair", "dense")


def test_kd_sharded_byte_parity_and_staging_reuse(blobs):
    """8-device KD sweep == per-config sharded train() (canonical
    min-core-gid labels), and configs 2..k reuse the device-resident
    graph slab (staged_bytes_reused > 0)."""
    kw = dict(block=128, mesh=default_mesh(8))
    m = DBSCAN(eps=0.4, min_samples=5, **kw)
    res = m.sweep(blobs, EPS_LIST, MS_LIST)
    assert res.stats["distance_passes"] == 1
    assert res.stats["mode"] == "kd"
    assert res.stats["owner_computes"] is True
    _assert_parity(blobs, res, "kd", **kw)
    assert res.per_config[0]["staged_bytes_reused"] == 0
    for cfg in res.per_config[1:]:
        assert cfg["staged_bytes_reused"] > 0, cfg


def test_global_morton_byte_parity(blobs):
    """Global-Morton sweep == per-config GM train(): boundary tiles
    selected at eps_max cover every smaller eps by construction."""
    kw = dict(block=128, mesh=default_mesh(8), mode="global_morton")
    m = DBSCAN(eps=0.4, min_samples=5, **kw)
    res = m.sweep(blobs, EPS_LIST)
    assert res.stats["mode"] == "global_morton"
    assert res.stats["distance_passes"] == 1
    _assert_parity(blobs, res, "gm", **kw)


@pytest.mark.parametrize("precision", ["highest", "mixed"])
def test_precision_modes(blobs, precision):
    """The graph stores the rescore arithmetic's exact d2, so mixed
    (and highest) sweeps stay byte-identical to same-precision fits."""
    kw = dict(block=128, mesh=default_mesh(1), precision=precision)
    res = DBSCAN(eps=0.4, min_samples=5, **kw).sweep(blobs, EPS_LIST)
    _assert_parity(blobs, res, f"precision={precision}", **kw)


def test_explicit_xla_backend(blobs):
    kw = dict(block=128, mesh=default_mesh(1), kernel_backend="xla")
    res = DBSCAN(eps=0.4, min_samples=5, **kw).sweep(blobs, [0.4])
    _assert_parity(blobs, res, "xla", **kw)


def test_eps_order_invariance(blobs):
    """Sorted vs unsorted eps_list: identical per-config labels (the
    graph depends only on eps_max; configs are independent)."""
    m = DBSCAN(eps=0.4, min_samples=5, block=128, mesh=default_mesh(1))
    res_sorted = m.sweep(blobs, sorted(EPS_LIST))
    res_shuffled = m.sweep(blobs, sorted(EPS_LIST)[::-1])
    for eps in EPS_LIST:
        np.testing.assert_array_equal(
            res_sorted.labels(eps), res_shuffled.labels(eps),
            err_msg=f"eps={eps}",
        )


def test_second_sweep_reuses_graph(blobs):
    """A second sweep under the cached eps ceiling reuses the graph
    slab through the eps-free ``sweep_graph`` staging route: the
    reused graph is the eps_max=0.4 one (same pair count), not a fresh
    smaller extraction at 0.25."""
    kw = dict(block=128, mesh=default_mesh(1))
    staging.clear()
    m = DBSCAN(eps=0.4, min_samples=5, **kw)
    res1 = m.sweep(blobs, EPS_LIST)
    res2 = m.sweep(blobs, [0.25])  # ceiling under the cached 0.4
    assert int(m.metrics_["staged_bytes_reused"]) > 0
    assert res2.stats["graph_pairs"] == res1.stats["graph_pairs"]
    _assert_parity(blobs, res2, "warm", **kw)


def test_overflow_degrades_to_per_config_refits(blobs, monkeypatch):
    """A graph past PYPARDIS_SWEEP_MAX_PAIRS degrades label-safely:
    per-config refits, telemetry says so, labels still exact."""
    monkeypatch.setenv("PYPARDIS_SWEEP_MAX_PAIRS", "64")
    staging.clear()  # a cached graph would bypass the extraction cap
    kw = dict(block=128, mesh=default_mesh(1))
    m = DBSCAN(eps=0.4, min_samples=5, **kw)
    res = m.sweep(blobs, EPS_LIST)
    assert res.stats["degraded"] == "per_config_refit"
    assert res.stats["distance_passes"] == len(res.configs)
    _assert_parity(blobs, res, "degraded", **kw)
    assert m.report()["events"]["degraded"] >= 1


def test_duplicate_points_and_all_noise():
    """Degenerate geometries: coincident duplicates (zero-distance
    edges, self-pair handling) and an eps so small every point is
    noise at min_samples=5."""
    rng = np.random.default_rng(0)
    base = rng.normal(size=(40, 3))
    X = np.concatenate([base, base, base, rng.normal(size=(80, 3)) + 8.0])
    kw = dict(block=64, mesh=default_mesh(1))
    res = DBSCAN(eps=0.3, min_samples=5, **kw).sweep(X, [1e-4, 0.3])
    _assert_parity(X, res, "degenerate", **kw)
    # the tiny-eps config: duplicates (3 copies each) miss
    # min_samples=5, so everything is noise
    assert set(np.unique(res.labels(1e-4))) == {-1}


def test_min_samples_only_sweep(blobs):
    """min_samples grid at one eps rides the same graph."""
    kw = dict(block=128, mesh=default_mesh(1))
    m = DBSCAN(eps=0.4, min_samples=5, **kw)
    res = m.sweep(blobs, [0.4], [2, 5, 20])
    assert res.stats["distance_passes"] == 1
    _assert_parity(blobs, res, "ms-grid", **kw)


def test_sweep_dbscan_functional(blobs):
    res = sweep_dbscan(
        blobs, [0.4], min_samples_list=[5], block=128,
        mesh=default_mesh(1),
    )
    ref_l, _ = _solo(blobs, 0.4, 5, block=128, mesh=default_mesh(1))
    np.testing.assert_array_equal(res.labels(0.4, 5), ref_l)
    assert res.model.report()["sweep"]["k"] == 1


def test_validation():
    m = DBSCAN(eps=0.4, min_samples=5)
    with pytest.raises(ValueError):
        m.sweep(np.zeros((10, 2)), [])
    with pytest.raises(ValueError):
        m.sweep(np.zeros((10, 2)), [-0.5])
    with pytest.raises(ValueError):
        m.sweep(np.zeros((10, 2)), [0.5], [0])


# -- the staging-aliasing regression the sweep work surfaced ------------


def test_eps_change_staging_reuse_is_correct(blobs):
    """fit(eps1) -> fit(eps2) with owned-slab reuse: labels must match
    a cold fit at eps2 (regression: on CPU, device_put zero-copies, so
    pooling the build buffers let a later borrow overwrite memory the
    cached owned slabs still aliased — give_back_after_put)."""
    part_kw = dict(min_samples=5, block=128, mesh=default_mesh(8))
    staging.clear()
    DBSCAN(eps=0.6, **part_kw).fit(blobs)
    m = DBSCAN(eps=0.25, **part_kw)
    m.fit(blobs)
    warm = np.asarray(m.labels_)
    assert m.metrics_["staged_bytes_reused"] > 0
    staging.clear()
    m2 = DBSCAN(eps=0.25, **part_kw)
    m2.fit(blobs)
    np.testing.assert_array_equal(warm, np.asarray(m2.labels_))


# -- cosine metric (ISSUE 13 satellite) ---------------------------------


@pytest.fixture(scope="module")
def sphere_clusters():
    """CLIP-like manifold data: clusters of directions, magnitudes
    varied — cosine must ignore the magnitudes entirely."""
    rng = np.random.default_rng(7)
    centers = rng.normal(size=(4, 8))
    centers /= np.linalg.norm(centers, axis=1, keepdims=True)
    X = np.concatenate(
        [c + rng.normal(scale=0.03, size=(120, 8)) for c in centers]
    )
    return X * rng.uniform(0.5, 2.0, size=(len(X), 1))


def _cosine_oracle(X, eps, ms):
    """Brute-force numpy cosine DBSCAN: f64 cosine distances, parallel
    formulation (min-core-index components, border = min adjacent
    root), canonical densified labels."""
    from pypardis_tpu.ops.labels import densify_labels

    Xn = X / np.linalg.norm(X, axis=1, keepdims=True)
    adj = (1.0 - Xn @ Xn.T) <= eps
    core = adj.sum(1) >= ms
    n = len(X)
    comp = np.full(n, -1)
    cid = 0
    import collections

    for i in range(n):
        if core[i] and comp[i] < 0:
            q = collections.deque([i])
            comp[i] = cid
            while q:
                u = q.popleft()
                for v in np.flatnonzero(adj[u] & core):
                    if comp[v] < 0:
                        comp[v] = cid
                        q.append(v)
            cid += 1
    roots = np.full(cid, n)
    for i in np.flatnonzero(core):
        roots[comp[i]] = min(roots[comp[i]], i)
    lab = np.full(n, -1, np.int64)
    for i in range(n):
        if core[i]:
            lab[i] = roots[comp[i]]
        else:
            nbr = np.flatnonzero(adj[i] & core)
            if len(nbr):
                lab[i] = min(roots[comp[j]] for j in nbr)
    return densify_labels(lab), core


def _canon(labels, core):
    from pypardis_tpu.ops.labels import densify_labels
    from pypardis_tpu.parallel.sharded import _canonicalize_roots

    return densify_labels(
        _canonicalize_roots(np.asarray(labels), np.asarray(core))
    )


def test_cosine_fit_pinned_against_numpy_oracle(sphere_clusters):
    X = sphere_clusters
    m = DBSCAN(eps=0.02, min_samples=5, metric="cosine", block=128)
    m.fit(X)
    ol, oc = _cosine_oracle(X, 0.02, 5)
    np.testing.assert_array_equal(
        _canon(m.labels_, m.core_sample_mask_), ol
    )
    np.testing.assert_array_equal(np.asarray(m.core_sample_mask_), oc)
    # user-facing spec survives the kernel-frame swap
    assert m.metric == "cosine" and m.eps == 0.02
    assert m.report()["params"]["metric"] == "cosine"


def test_cosine_predict_bitwise_oracle(sphere_clusters, tmp_path):
    """predict == the index's brute-force oracle bitwise, and a
    save/load round trip serves identical answers (unit_norm metadata
    persisted)."""
    X = sphere_clusters
    rng = np.random.default_rng(1)
    Q = rng.normal(size=(100, 8)) * rng.uniform(0.2, 3.0, (100, 1))
    m = DBSCAN(eps=0.02, min_samples=5, metric="cosine", block=128)
    m.fit(X)
    pred = m.predict(Q)
    olab, _ = m.query_engine().index.oracle_predict(Q)
    np.testing.assert_array_equal(pred, olab)
    # independent f64 cosine check of the noise/member split
    Xn = X / np.linalg.norm(X, axis=1, keepdims=True)
    Qn = Q / np.linalg.norm(Q, axis=1, keepdims=True)
    cores = Xn[np.asarray(m.core_sample_mask_)]
    within = ((1.0 - Qn @ cores.T) <= 0.02).any(1)
    assert ((pred >= 0) == within).mean() > 0.99
    path = str(tmp_path / "cosine_model.npz")
    m.save(path)
    m2 = DBSCAN.load(path)
    assert m2.metric == "cosine"
    np.testing.assert_array_equal(m2.predict(Q), pred)


def test_cosine_sweep_rides_cached_graph(sphere_clusters):
    X = sphere_clusters
    kw = dict(metric="cosine", block=128, mesh=default_mesh(1))
    m = DBSCAN(eps=0.02, min_samples=5, **kw)
    res = m.sweep(X, [0.01, 0.05])
    assert res.stats["distance_passes"] == 1
    _assert_parity(X, res, "cosine-sweep", **kw)


def test_cosine_validation():
    with pytest.raises(ValueError):
        DBSCAN(eps=2.5, metric="cosine")  # cosine distance <= 2
    m = DBSCAN(eps=0.1, min_samples=2, metric="cosine")
    with pytest.raises(ValueError):
        m.fit(np.array([[1.0, 0.0], [0.0, 0.0]]))  # zero vector
    with pytest.raises(NotImplementedError):
        m.fit(np.eye(3)).live()  # live updates not yet supported


# -- device-route edge-budget ladder (the PR 13 NOTE debt) --------------
#
# On CPU the sweep graph auto-routes to host compaction, so the device
# emission's exact-total budget ladder ran untested until the
# PYPARDIS_SWEEP_EMISSION override landed (ISSUE 14 satellite): force
# the device route on the CI mesh, undersize the initial edge budget,
# and pin (a) the ladder's one-retry recovery with byte-exact labels,
# (b) the hard-cap overflow degrading label-safely to refits.


def test_device_route_ladder_retries_and_recovers(blobs, monkeypatch):
    """Undersized edge budget on the forced device route: exactly one
    pair_overflow event, then the exact-total retry sizes the slab and
    labels stay byte-identical to the host-route sweep."""
    kw = dict(block=128, mesh=default_mesh(8))
    staging.clear()
    ref = DBSCAN(eps=0.4, min_samples=5, **kw).sweep(blobs, EPS_LIST)

    monkeypatch.setenv("PYPARDIS_SWEEP_EMISSION", "device")
    monkeypatch.setenv("PYPARDIS_SWEEP_EDGE_BUDGET", "4096")
    staging.clear()
    m = DBSCAN(eps=0.4, min_samples=5, **kw)
    res = m.sweep(blobs, EPS_LIST)
    rep = m.report()
    assert rep["sweep"]["degraded"] is None
    assert rep["events"]["pair_overflow"] >= 1  # the ladder fired
    for eps in EPS_LIST:
        np.testing.assert_array_equal(
            res.labels(eps), ref.labels(eps), err_msg=str(eps)
        )
        np.testing.assert_array_equal(
            res.core(eps), ref.core(eps), err_msg=str(eps)
        )


def _ladder_geometry():
    """Deterministic 1024-point set whose KD shards climb the edge-budget
    ladder twice: a sparse shard is processed first (tiny need, rung 1 at
    the 4096 floor), then a 256-point tight blob's shard needs ~33k edges
    (rung 2).  Densities chosen so every later shard fits the grown
    budget — the event count is exact, not a lower bound."""
    rng = np.random.default_rng(13)

    def loose(n, x0, span):
        pts = rng.uniform(0, span, size=(n, 3)).astype(np.float32)
        pts[:, 0] += x0
        return pts

    def tight(n, x0, std):
        pts = rng.normal(0.0, std, size=(n, 3)).astype(np.float32)
        pts[:, 0] += x0
        return pts

    return np.concatenate([
        loose(384, 0.0, 40.0),     # sparse head: first shard, ~100 pairs
        tight(128, 60.0, 0.05),    # dense, fits once the ladder grew
        tight(256, 80.0, 0.05),    # densest: ~33k pairs on one shard
        loose(256, 100.0, 40.0),   # sparse tail
    ])


def test_device_route_ladder_multi_rung(monkeypatch):
    """The PR 13 NOTE debt: drive the per-shard edge-budget ladder
    through >= 2 growth rungs in ONE sweep (64 -> 4096 floor -> exact
    retry total) and pin the event count byte-exactly alongside label
    parity with the untouched host route."""
    X = _ladder_geometry()
    kw = dict(block=128, mesh=default_mesh(8))
    staging.clear()
    ref = DBSCAN(eps=0.4, min_samples=5, **kw).sweep(X, EPS_LIST)

    monkeypatch.setenv("PYPARDIS_SWEEP_EMISSION", "device")
    monkeypatch.setenv("PYPARDIS_SWEEP_EDGE_BUDGET", "64")
    staging.clear()
    m = DBSCAN(eps=0.4, min_samples=5, **kw)
    res = m.sweep(X, EPS_LIST)
    rep = m.report()
    assert rep["sweep"]["degraded"] is None
    assert rep["sweep"]["mode"] == "kd"
    # Exactly two rungs: the sparse first shard trips the undersized
    # budget onto the 4096 floor, the dense shard trips that onto its
    # exact round_up total, and every later shard inherits the ceiling.
    assert rep["events"]["pair_overflow"] == 2
    for eps in EPS_LIST:
        np.testing.assert_array_equal(
            res.labels(eps), ref.labels(eps), err_msg=str(eps)
        )
        np.testing.assert_array_equal(
            res.core(eps), ref.core(eps), err_msg=str(eps)
        )


def test_eps_none_fit_rides_device_ladder(monkeypatch):
    """An eps=None hierarchy fit is built on the same cached pair graph,
    so the forced device route's budget ladder serves it unchanged:
    same two rungs, and the stability-selected labels are byte-identical
    to the host-emission fit."""
    X = _ladder_geometry()
    kw = dict(block=128, mesh=default_mesh(8))
    monkeypatch.setenv("PYPARDIS_HIER_EPS_MAX", "0.4")
    staging.clear()
    ref = DBSCAN(eps=None, min_samples=5, **kw).fit(X)

    monkeypatch.setenv("PYPARDIS_SWEEP_EMISSION", "device")
    monkeypatch.setenv("PYPARDIS_SWEEP_EDGE_BUDGET", "64")
    staging.clear()
    m = DBSCAN(eps=None, min_samples=5, **kw).fit(X)
    rep = m.report()
    assert rep["events"]["pair_overflow"] == 2
    assert rep["hierarchy"]["distance_passes"] == 1
    assert m.eps_ == ref.eps_
    np.testing.assert_array_equal(m.labels_, ref.labels_)
    np.testing.assert_array_equal(
        m.core_sample_mask_, ref.core_sample_mask_
    )


def test_device_route_cap_overflow_degrades(blobs, monkeypatch):
    """The hard PYPARDIS_SWEEP_MAX_PAIRS cap on the device route:
    SweepGraphOverflow -> label-safe per-config refits, telemetry
    honest about the degradation."""
    monkeypatch.setenv("PYPARDIS_SWEEP_EMISSION", "device")
    monkeypatch.setenv("PYPARDIS_SWEEP_MAX_PAIRS", "64")
    staging.clear()
    kw = dict(block=128, mesh=default_mesh(8))
    m = DBSCAN(eps=0.4, min_samples=5, **kw)
    res = m.sweep(blobs, EPS_LIST)
    assert res.stats["degraded"] == "per_config_refit"
    assert m.report()["events"]["degraded"] >= 1
    _assert_parity(blobs, res, "device-cap", **kw)


def test_fused_device_route_parity(blobs, monkeypatch):
    """The fused (1-device) sweep's device emission path, forced on
    CPU: byte parity with the auto (host-compaction) route."""
    kw = dict(block=128, mesh=default_mesh(1))
    staging.clear()
    ref = DBSCAN(eps=0.4, min_samples=5, **kw).sweep(blobs, EPS_LIST)
    monkeypatch.setenv("PYPARDIS_SWEEP_EMISSION", "device")
    staging.clear()
    res = DBSCAN(eps=0.4, min_samples=5, **kw).sweep(blobs, EPS_LIST)
    for eps in EPS_LIST:
        np.testing.assert_array_equal(
            res.labels(eps), ref.labels(eps), err_msg=str(eps)
        )


def test_sweep_on_sketch_model_stays_exact(monkeypatch):
    """A sketch-enabled model's sweep (ISSUE 17): the cached
    neighbor-pair graph is an EXACT full-d artifact (the emission pass
    never sketches — a prefilter verdict cannot be re-thresholded at a
    smaller eps), so sweep results are byte-identical whether the
    model carries sketch='auto' or sketch=0, at a dimensionality where
    the fit path WOULD sketch (d >= SKETCH_MIN_D)."""
    rng = np.random.default_rng(11)
    dim, n = 160, 900
    basis = np.linalg.qr(rng.normal(size=(dim, 4)))[0]
    eps = round(1.06 * 0.5 * np.sqrt(2.0 * dim), 2)
    centers = (3.5 * eps / np.sqrt(2.0)) * basis.T
    X = (
        centers[rng.integers(0, 4, size=n)]
        + rng.normal(scale=0.5, size=(n, dim))
    ).astype(np.float32)
    eps_list = [round(0.8 * eps, 2), eps]
    kw = dict(block=128, mesh=default_mesh(8))

    from pypardis_tpu.ops.sketch import resolve_sketch

    assert resolve_sketch("auto", dim) > 0  # the fit path would sketch

    staging.clear()
    ref = DBSCAN(eps=eps, min_samples=5, sketch=0, **kw).sweep(
        X, eps_list
    )
    staging.clear()
    m = DBSCAN(eps=eps, min_samples=5, sketch="auto", **kw)
    res = m.sweep(X, eps_list)
    assert res.stats["distance_passes"] == 1
    for e in eps_list:
        np.testing.assert_array_equal(
            res.labels(e), ref.labels(e), err_msg=str(e)
        )
        np.testing.assert_array_equal(
            res.core(e), ref.core(e), err_msg=str(e)
        )
