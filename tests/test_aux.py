"""Aux subsystems: checkpoint/resume, config, logging, profiling.

The reference has none of these (SURVEY §5) — these tests pin the
TPU-native replacements.
"""

import logging

import numpy as np
import pytest

from pypardis_tpu import (
    DBSCAN,
    DBSCANConfig,
    KDPartitioner,
    load_model,
    load_partitioner,
    save_partitioner,
)
from pypardis_tpu.utils.log import enable, get_logger, log_phase
from pypardis_tpu.utils.profiling import PhaseTimer


def test_partitioner_checkpoint_roundtrip(tmp_path, blobs750):
    part = KDPartitioner(blobs750, max_partitions=8)
    path = str(tmp_path / "tree.npz")
    save_partitioner(part, path)
    tree = load_partitioner(path)
    assert tree.n_partitions == part.n_partitions
    assert tree.k == part.k
    # Routing through the loaded tree matches the original assignment.
    assert np.array_equal(tree.route(blobs750), part.route(blobs750))
    for l, box in part.bounding_boxes.items():
        assert tree.bounding_boxes[l] == box


def test_model_checkpoint_roundtrip(tmp_path, blobs750):
    model = DBSCAN(eps=0.3, min_samples=10).fit(blobs750)
    path = str(tmp_path / "model.npz")
    model.save(path)
    back = DBSCAN.load(path)
    assert np.array_equal(back.labels_, model.labels_)
    assert np.array_equal(back.core_sample_mask_, model.core_sample_mask_)
    assert back.eps == model.eps
    assert back.assignments() == model.assignments()
    assert back.bounding_boxes.keys() == model.bounding_boxes.keys()


def test_untrained_model_save_raises(tmp_path):
    with pytest.raises(ValueError):
        DBSCAN().save(str(tmp_path / "x.npz"))


def test_checkpoint_kind_mismatch(tmp_path, blobs750):
    part = KDPartitioner(blobs750, max_partitions=4)
    path = str(tmp_path / "tree.npz")
    save_partitioner(part, path)
    with pytest.raises(ValueError):
        load_model(path)


def test_config_roundtrip():
    cfg = DBSCANConfig(eps=0.7, min_samples=3, block=256)
    model = cfg.build()
    assert model.eps == 0.7 and model.min_samples == 3
    d = cfg.to_dict()
    assert DBSCANConfig.from_dict(d) == cfg
    # Unknown keys are ignored, not fatal.
    assert DBSCANConfig.from_dict({**d, "bogus": 1}) == cfg


def test_config_build_is_trainable(blobs750):
    labels = DBSCANConfig(eps=0.3, min_samples=10).build().fit_predict(
        blobs750
    )
    assert labels.max() == 2


def test_logging_phase(caplog):
    enable(logging.INFO)
    with caplog.at_level(logging.INFO, logger="pypardis_tpu"):
        log_phase("cluster", n=10, t=0.5)
    assert any("cluster" in r.message for r in caplog.records)
    assert get_logger().name == "pypardis_tpu"


def test_phase_timer():
    t = PhaseTimer()
    with t.phase("a"):
        pass
    with t.phase("a"):
        pass
    with t.phase("b"):
        pass
    d = t.as_dict()
    assert set(d) == {"a_s", "b_s"}
    assert d["a_s"] >= 0


def test_driver_phase_metrics_and_profile_dir(tmp_path):
    """train() must report PhaseTimer phases and honor profile_dir
    (VERDICT r2: the profiling subsystem must be wired into the driver,
    not ornamental)."""
    import numpy as np

    from pypardis_tpu import DBSCAN

    rng = np.random.default_rng(0)
    X = rng.normal(size=(600, 3)).astype(np.float32)
    logdir = tmp_path / "trace"
    m = DBSCAN(eps=0.4, min_samples=5, profile_dir=str(logdir))
    m.fit(X)
    assert "cluster_s" in m.metrics_ and m.metrics_["cluster_s"] > 0
    assert "densify_s" in m.metrics_
    # jax.profiler wrote a trace under the requested directory.
    assert any(logdir.rglob("*")), "no profiler trace captured"
