"""Sharded staging economy: host slab reuse + device slab cache.

The acceptance contract (ISSUE 2): a warm host-input sharded fit must
transfer strictly fewer bytes than a cold one, observable as
``staged_bytes_reused > 0`` — and reuse must be CONTENT-gated, never
identity-gated, so an in-place mutation of the input between fits can
never serve stale slabs.
"""

import numpy as np
import pytest
from sklearn.datasets import make_blobs

from pypardis_tpu import DBSCAN
from pypardis_tpu.parallel import default_mesh, sharded_dbscan
from pypardis_tpu.parallel import staging
from pypardis_tpu.partition import KDPartitioner


@pytest.fixture(autouse=True)
def _fresh_staging():
    staging.clear()
    yield
    staging.clear()


@pytest.fixture()
def data():
    X, _ = make_blobs(
        n_samples=1500, centers=5, n_features=3, cluster_std=0.3,
        random_state=11,
    )
    return X


def test_warm_fit_reuses_staged_slabs(data):
    mesh = default_mesh(8)
    part = KDPartitioner(data, max_partitions=8)
    kw = dict(eps=0.4, min_samples=5, block=128, mesh=mesh)
    l1, c1, s1 = sharded_dbscan(data, part, **kw)
    assert s1["staged_bytes_reused"] == 0  # cold: everything shipped
    assert s1["staged_bytes"] > 0
    l2, c2, s2 = sharded_dbscan(data, part, **kw)
    # Warm: owned AND halo slabs served from the device cache — the
    # fit shipped strictly fewer bytes than cold.
    assert s2["staged_bytes_reused"] > 0
    assert s2["staged_bytes"] < s1["staged_bytes"]
    assert np.array_equal(l1, l2) and np.array_equal(c1, c2)


def test_eps_sweep_reuses_owned_slabs_only(data):
    """Owned slabs are eps-independent: an eps sweep re-ships halos but
    serves the owned layout from the cache."""
    mesh = default_mesh(8)
    part = KDPartitioner(data, max_partitions=8)
    kw = dict(min_samples=5, block=128, mesh=mesh)
    _l, _c, s1 = sharded_dbscan(data, part, eps=0.4, **kw)
    _l, _c, s2 = sharded_dbscan(data, part, eps=0.5, **kw)
    assert s2["staged_bytes_reused"] > 0      # owned came from cache
    assert s2["staged_bytes"] > 0             # halos re-shipped
    assert s2["staged_bytes_reused"] < s1["staged_bytes"]


def test_mutated_input_never_served_stale(data):
    """Content fingerprinting: mutating the SAME array object between
    fits misses the cache and recomputes — labels follow the new data."""
    mesh = default_mesh(8)
    X = np.array(data)
    part = KDPartitioner(X, max_partitions=8)
    kw = dict(eps=0.4, min_samples=5, block=128, mesh=mesh)
    l1, _c, _s = sharded_dbscan(X, part, **kw)
    # Move one blob far away, in place; repartition (the tree changed).
    X[:200] += 100.0
    part2 = KDPartitioner(X, max_partitions=8)
    l2, _c2, s2 = sharded_dbscan(X, part2, **kw)
    assert s2["staged_bytes_reused"] == 0
    assert not np.array_equal(l1, l2)


def test_api_warm_refit_reports_reuse(data):
    """Through the public DBSCAN API: the second fit of the same data
    reports staged reuse in report() even though train() builds a fresh
    (content-identical) partitioner each call."""
    model = DBSCAN(eps=0.4, min_samples=5, block=128)
    model.fit(data)
    r_cold = model.report()
    model.fit(data)
    r_warm = model.report()
    assert r_cold["sharding"]["staged_bytes_reused"] == 0
    assert r_warm["sharding"]["staged_bytes_reused"] > 0


def test_ring_route_caches_owned_slabs(data):
    mesh = default_mesh(8)
    part = KDPartitioner(data, max_partitions=8)
    kw = dict(eps=0.4, min_samples=5, block=128, mesh=mesh, halo="ring")
    _l, _c, s1 = sharded_dbscan(data, part, **kw)
    _l, _c, s2 = sharded_dbscan(data, part, **kw)
    assert s1["staged_bytes_reused"] == 0
    assert s2["staged_bytes_reused"] > 0


def test_single_shard_layout_cache(data):
    """ISSUE 3: the single-shard route caches its layout products
    (sorted device arrays) by content — a warm repeat fit skips the
    staging fill, the transfer, and the device Morton sort, and an
    in-place mutation can never be served stale."""
    from pypardis_tpu.dbscan import _pad_and_run

    X = np.array(data[:1200], np.float32)
    l1, c1, i1 = _pad_and_run(X, 0.4, 5, "euclidean", 128)
    assert i1["staged_bytes_reused"] == 0 and i1["staged_bytes"] > 0
    l2, c2, i2 = _pad_and_run(X, 0.4, 5, "euclidean", 128)
    assert i2["staged_bytes"] == 0
    assert i2["staged_bytes_reused"] == i1["staged_bytes"]
    np.testing.assert_array_equal(l1, l2)
    np.testing.assert_array_equal(c1, c2)
    # different eps -> different layout (segment breaks) -> miss
    _l, _c, i3 = _pad_and_run(X, 0.5, 5, "euclidean", 128)
    assert i3["staged_bytes_reused"] == 0
    # in-place mutation -> content fingerprint miss, fresh labels
    X[:100] += 40.0
    l4, _c4, i4 = _pad_and_run(X, 0.4, 5, "euclidean", 128)
    assert i4["staged_bytes_reused"] == 0
    staging.clear()
    l5, _c5, _i5 = _pad_and_run(X, 0.4, 5, "euclidean", 128)
    np.testing.assert_array_equal(l4, l5)
