"""Auto-tuning subsystem (ISSUE 14).

The correctness bar: ``DBSCAN(auto=True)`` labels BYTE-IDENTICAL to
the same explicit config (every planned knob is label-safe by
construction) on the fused, KD owner-computes, and global-Morton
geometries; user-pinned knobs never overridden; each hard feasibility
rule (memmap -> streaming GM, 1 device -> chained/fused, RSS pressure
-> merge=host) deterministic; the corpus harvest / cost-model fit /
plan checkpoint round-trip all pinned.
"""

import json
import os

import numpy as np
import pytest
from sklearn.datasets import make_blobs

from pypardis_tpu import DBSCAN
from pypardis_tpu.parallel import default_mesh, staging
from pypardis_tpu.tune import (
    CostModel,
    CorpusRow,
    TunePlan,
    harvest_corpus,
    plan_fit,
    probe_dataset,
    row_from_report,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _isolated_corpus(monkeypatch, tmp_path):
    """Never read the developer's local archive or write ~/.cache from
    tests; each test gets a throwaway corpus file."""
    monkeypatch.setenv(
        "PYPARDIS_TUNE_CORPUS", str(tmp_path / "corpus.jsonl")
    )


@pytest.fixture(scope="module")
def blobs():
    X, _ = make_blobs(
        n_samples=1600, centers=5, n_features=4, cluster_std=0.3,
        random_state=3,
    )
    return X


def _explicit(X, proto, cfg, **kw):
    """An explicit fit at exactly the planned config."""
    kw = dict(kw)
    if cfg.get("mode") and cfg["mode"] != "auto":
        kw["mode"] = cfg["mode"]
    if cfg.get("merge") and cfg["merge"] != "auto":
        kw["merge"] = cfg["merge"]
    m = DBSCAN(
        eps=proto.eps, min_samples=proto.min_samples,
        block=cfg["block"], precision=cfg["precision"], **kw,
    )
    old = os.environ.get("PYPARDIS_DISPATCH")
    os.environ["PYPARDIS_DISPATCH"] = str(cfg["dispatch"])
    try:
        m.fit(X)
    finally:
        if old is None:
            os.environ.pop("PYPARDIS_DISPATCH", None)
        else:
            os.environ["PYPARDIS_DISPATCH"] = old
    return m


# -- corpus -------------------------------------------------------------


def test_harvest_committed_archives():
    rows = harvest_corpus(roots=[_REPO], local="")
    assert len(rows) >= 8, [r.source for r in rows]
    assert all(r.schema.endswith("tuning_corpus@1") for r in rows)
    # The northstar row is a FULL row: config + phase decomposition.
    ns = [r for r in rows if r.source.startswith("NORTHSTAR")]
    assert ns and ns[0].complete_for_compute()
    assert ns[0].mode == "global_morton"
    assert ns[0].exchange_s and ns[0].merge_s


def test_row_from_report_and_local_roundtrip(blobs, tmp_path):
    m = DBSCAN(eps=0.4, min_samples=5, block=128).fit(blobs)
    row = row_from_report(m.report(), source="t")
    assert row.n == len(blobs) and row.dim == 4
    assert row.mode in ("fused", "kd", "chained")
    assert row.compute_s is not None and row.compute_s > 0
    d = json.loads(json.dumps(row.to_dict()))
    assert CorpusRow.from_dict(d).to_dict() == row.to_dict()


# -- probe --------------------------------------------------------------


def test_probe_features(blobs):
    p = probe_dataset(blobs, 0.4, devices=8, backend="cpu")
    assert p.n == len(blobs) and p.dim == 4
    assert p.probe_s < 5.0
    assert p.neighbors_per_point > 1
    for b, st in p.blocks.items():
        assert 0.0 < st["live_pair_fraction"] <= 1.0
        assert st["tiles"] == -(-p.n // b)
    # coarser blocks -> fewer tiles, higher live fraction
    bs = sorted(p.blocks)
    fr = [p.blocks[b]["live_pair_fraction"] for b in bs]
    assert fr == sorted(fr)


def test_probe_memmap(tmp_path):
    X, _ = make_blobs(n_samples=4000, n_features=4, random_state=0)
    path = str(tmp_path / "mm.f32")
    mm = np.memmap(path, dtype=np.float32, mode="w+", shape=X.shape)
    mm[:] = X
    mm.flush()
    p = probe_dataset(mm, 0.4, devices=8, backend="cpu")
    assert p.is_memmap and p.n == 4000
    assert p.blocks


# -- planner feasibility rules -----------------------------------------


def test_rule_memmap_forces_streaming_gm(tmp_path):
    X, _ = make_blobs(n_samples=4000, n_features=4, random_state=0)
    mm = np.memmap(
        str(tmp_path / "mm.f32"), dtype=np.float32, mode="w+",
        shape=X.shape,
    )
    mm[:] = X
    mm.flush()
    p = probe_dataset(mm, 0.4, devices=8, backend="cpu")
    plan = plan_fit(p, {}, [])
    assert plan.config["mode"] == "global_morton"
    assert any("memmap" in r for r in plan.rules)


def test_rule_one_device_forces_fused(blobs):
    p = probe_dataset(blobs, 0.4, devices=1, backend="cpu")
    plan = plan_fit(p, {}, [])
    assert plan.config["mode"] == "auto"
    assert plan.config["merge"] == "auto"
    assert any("fused-or-chained" in r for r in plan.rules)


def test_rule_rss_pressure_forces_host_merge(blobs, monkeypatch):
    monkeypatch.setenv("PYPARDIS_RSS_SOFT_LIMIT", "1")
    p = probe_dataset(blobs, 0.4, devices=8, backend="cpu")
    assert p.memory_pressure
    plan = plan_fit(p, {}, [])
    assert plan.config["merge"] == "host"
    assert any("RSS pressure" in r or "pressure" in r
               for r in plan.rules)


def test_pinned_knobs_never_overridden(blobs):
    p = probe_dataset(blobs, 0.4, devices=8, backend="cpu",
                      blocks=(128, 256, 512))
    pin = {"block": 512, "precision": "highest", "mode": "kd",
           "merge": "device", "dispatch": "dense"}
    plan = plan_fit(p, pin, [])
    for k, v in pin.items():
        assert plan.config[k] == v, (k, plan.config)
        assert "pinned" in plan.knob_reasons[k]
    assert plan.pinned == pin


def test_pinned_conflict_with_rule_keeps_pin(blobs, monkeypatch):
    monkeypatch.setenv("PYPARDIS_RSS_SOFT_LIMIT", "1")
    p = probe_dataset(blobs, 0.4, devices=8, backend="cpu")
    plan = plan_fit(p, {"merge": "device"}, [])
    assert plan.config["merge"] == "device"  # the user wins
    assert any("keeping the pin" in r for r in plan.rules)


def test_explain_names_every_knob(blobs):
    p = probe_dataset(blobs, 0.4, devices=8, backend="cpu")
    plan = plan_fit(p, {}, harvest_corpus(roots=[_REPO], local=""))
    text = plan.explain()
    for knob in ("mode", "block", "precision", "merge", "dispatch",
                 "sketch"):
        assert knob in text
    assert "predicted" in text and "probe" in text
    # round-trips through the checkpoint dict form
    p2 = TunePlan.from_dict(
        json.loads(json.dumps(plan.to_dict()))
    )
    assert p2.config == plan.config
    assert p2.explain() == text


# -- cost model ---------------------------------------------------------


def test_cost_model_fit_recovers_coefficients():
    """Synthetic corpus generated from known coefficients: the
    per-bucket least squares recovers them and predictions rank
    configs correctly."""
    rng = np.random.default_rng(0)
    true_flop, true_visit = 2e-10, 5e-6
    rows = []
    for i in range(8):
        pairs = int(rng.integers(1000, 100000))
        block = int(rng.choice([128, 256, 512]))
        passes = int(rng.integers(3, 8))
        dim = 16
        flops = pairs * block * block * (dim + 2) * 2.0 * passes
        rows.append(CorpusRow(
            n=100000, dim=dim, devices=8, backend="cpu", mode="kd",
            block=block, precision="high", merge="host",
            kernel_passes=passes, live_pairs=pairs,
            compute_s=true_flop * flops + true_visit * pairs * passes,
        ))
    m = CostModel.fit_from_corpus(rows, "cpu", 8)
    assert m.sources["pair_flop_s"] == "corpus"
    assert abs(m.coef["pair_flop_s"] - true_flop) / true_flop < 0.05
    ph = m.predict_phases(
        n=100000, dim=16, devices=8, mode="kd", block=256,
        precision="high", merge="host", dispatch="pair",
        live_pairs=50000, tiles=400, passes=5,
    )
    assert all(v >= 0 for v in ph.values())
    assert ph["total_s"] == pytest.approx(
        ph["build_s"] + ph["exchange_s"] + ph["compute_s"]
        + ph["merge_s"]
    )


def test_cost_model_heuristic_fallback():
    m = CostModel.fit_from_corpus([], "cpu", 8)
    assert all(s == "heuristic" for s in m.sources.values())
    ph = m.predict_phases(
        n=10000, dim=8, devices=8, mode="global_morton", block=256,
        precision="mixed", merge="device", dispatch="dense",
        live_pairs=1000, tiles=40, boundary_bytes=1 << 20,
    )
    assert ph["exchange_s"] > 0 and ph["total_s"] > 0


# -- DBSCAN(auto=True): byte parity with the explicit config -----------


def test_auto_fused_byte_parity(blobs):
    m = DBSCAN(eps=0.4, min_samples=5, auto=True, mesh=default_mesh(1))
    m.fit(blobs)
    tune = m.report()["tune"]
    cfg = tune["plan"]["config"]
    assert cfg["mode"] == "auto"  # 1 device: fused engine
    ref = _explicit(blobs, m, cfg, mesh=default_mesh(1))
    np.testing.assert_array_equal(m.labels_, ref.labels_)
    np.testing.assert_array_equal(
        m.core_sample_mask_, ref.core_sample_mask_
    )


def test_auto_kd_byte_parity(blobs):
    staging.clear()
    m = DBSCAN(
        eps=0.4, min_samples=5, auto=True, mode="kd",
        mesh=default_mesh(8),
    )
    m.fit(blobs)
    cfg = m.report()["tune"]["plan"]["config"]
    assert cfg["mode"] == "kd"  # the pin
    ref = _explicit(blobs, m, cfg, mesh=default_mesh(8))
    np.testing.assert_array_equal(m.labels_, ref.labels_)


def test_auto_global_morton_byte_parity(blobs):
    staging.clear()
    m = DBSCAN(
        eps=0.4, min_samples=5, auto=True, mode="global_morton",
        mesh=default_mesh(8),
    )
    m.fit(blobs)
    cfg = m.report()["tune"]["plan"]["config"]
    assert cfg["mode"] == "global_morton"
    ref = _explicit(blobs, m, cfg, mesh=default_mesh(8))
    np.testing.assert_array_equal(m.labels_, ref.labels_)


def test_auto_unpinned_mesh_byte_parity(blobs):
    staging.clear()
    m = DBSCAN(eps=0.4, min_samples=5, auto=True, mesh=default_mesh(8))
    m.fit(blobs)
    cfg = m.report()["tune"]["plan"]["config"]
    assert cfg["mode"] in ("kd", "global_morton")  # planner's choice
    ref = _explicit(blobs, m, cfg, mesh=default_mesh(8))
    np.testing.assert_array_equal(m.labels_, ref.labels_)
    np.testing.assert_array_equal(
        m.core_sample_mask_, ref.core_sample_mask_
    )


def test_auto_user_pin_survives_fit(blobs):
    m = DBSCAN(
        eps=0.4, min_samples=5, auto=True, block=512,
        precision="highest", mesh=default_mesh(8),
    )
    m.fit(blobs)
    assert m.block == 512 and m.precision == "highest"
    cfg = m.report()["tune"]["plan"]["config"]
    assert cfg["block"] == 512 and cfg["precision"] == "highest"


def test_auto_dispatch_env_restored(blobs, monkeypatch):
    monkeypatch.delenv("PYPARDIS_DISPATCH", raising=False)
    m = DBSCAN(eps=0.4, min_samples=5, auto=True, mesh=default_mesh(1))
    m.fit(blobs)
    assert "PYPARDIS_DISPATCH" not in os.environ
    monkeypatch.setenv("PYPARDIS_DISPATCH", "dense")
    m2 = DBSCAN(eps=0.4, min_samples=5, auto=True,
                mesh=default_mesh(1))
    m2.fit(blobs)
    # env pin respected AND restored
    assert os.environ["PYPARDIS_DISPATCH"] == "dense"
    assert m2.report()["tune"]["plan"]["config"]["dispatch"] == "dense"


# -- telemetry, feedback, checkpoint ------------------------------------


def test_auto_report_summary_and_feedback(blobs, tmp_path):
    corpus = str(tmp_path / "corpus.jsonl")
    m = DBSCAN(
        eps=0.4, min_samples=5, auto=True, mesh=default_mesh(1),
        tune_corpus=corpus,
    )
    m.fit(blobs)
    tune = m.report()["tune"]
    for key in ("plan", "explain", "probe_s", "plan_s", "corpus_rows",
                "predicted_phases", "actual_phases",
                "corpus_appended"):
        assert key in tune, key
    pred = tune["predicted_phases"]
    for p in ("build_s", "exchange_s", "compute_s", "merge_s",
              "total_s"):
        assert np.isfinite(pred[p])
    assert tune["corpus_appended"] is True
    with open(corpus) as f:
        lines = [json.loads(ln) for ln in f if ln.strip()]
    assert len(lines) == 1 and lines[0]["n"] == len(blobs)
    assert "auto plan" in m.summary()
    # the next auto fit consumes its predecessor's row
    m2 = DBSCAN(
        eps=0.4, min_samples=5, auto=True, mesh=default_mesh(1),
        tune_corpus=corpus,
    )
    m2.fit(blobs)
    assert m2.report()["tune"]["corpus_rows"] > tune["corpus_rows"]


def test_plan_survives_checkpoint(blobs, tmp_path):
    m = DBSCAN(eps=0.4, min_samples=5, auto=True, mesh=default_mesh(1))
    m.fit(blobs)
    path = str(tmp_path / "auto_model.npz")
    m.save(path)
    m2 = DBSCAN.load(path)
    assert m2._tune_stats is not None
    assert m2._tune_stats["plan"]["config"] == \
        m.report()["tune"]["plan"]["config"]
    np.testing.assert_array_equal(m2.labels_, m.labels_)


def test_non_auto_unchanged(blobs):
    """auto defaults off: no probe, no tune block, classic defaults."""
    m = DBSCAN(eps=0.4, min_samples=5)
    assert m.block == 1024 and m.precision == "high"
    assert m.merge == "auto" and m.mode == "auto"
    m.fit(blobs)
    assert "tune" not in m.report()


# -- sketch knob (ISSUE 17) ---------------------------------------------


def _high_d(n=1536, dim=512, n_centers=8, seed=0):
    """The sketch prefilter's target regime (scripts/sketch_probe.py):
    noise-dominated high-d clusters whose axis-aligned tile boxes are
    blind while pairwise distances stay separated."""
    rng = np.random.default_rng(seed)
    eps = round(1.06 * 0.5 * np.sqrt(2.0 * dim), 2)
    basis = np.linalg.qr(rng.normal(size=(dim, n_centers)))[0]
    centers = (3.5 * eps / np.sqrt(2.0)) * basis.T
    X = (
        centers[rng.integers(0, n_centers, size=n)]
        + rng.normal(scale=0.5, size=(n, dim))
    ).astype(np.float32)
    return X, eps


def test_plan_sketch_on_at_high_d_off_at_low_d(blobs):
    from pypardis_tpu.ops.sketch import auto_k

    X, eps = _high_d()
    p = probe_dataset(X, eps, devices=8, backend="cpu")
    assert p.sketch_k_auto == auto_k(512)
    assert 0.0 < p.pair_fraction_in_sketch_band < 1.0
    plan = plan_fit(p, {}, [])
    assert plan.config["sketch"] == p.sketch_k_auto
    assert "sketch" in plan.knob_reasons

    # Low d: auto resolves to off and the planner must not invent one.
    p_lo = probe_dataset(blobs, 0.4, devices=8, backend="cpu")
    assert p_lo.sketch_k_auto == 0
    assert plan_fit(p_lo, {}, []).config["sketch"] == 0


def test_plan_sketch_pin_conflict_recorded():
    """A user pin the cost model disagrees with: the pin WINS and the
    disagreement lands in the plan's rule trace (the same discipline
    as every other pinned knob)."""
    X, eps = _high_d()
    p = probe_dataset(X, eps, devices=8, backend="cpu")
    plan = plan_fit(p, {"sketch": 0}, [])
    assert plan.config["sketch"] == 0  # the user wins
    assert any(
        "cost model preferred sketch=" in r for r in plan.rules
    )
    assert "pinned" in plan.knob_reasons["sketch"]


def test_plan_sketch_off_for_non_euclidean():
    X, eps = _high_d(n=512)
    p = probe_dataset(X, eps, devices=8, backend="cpu")
    plan = plan_fit(p, {}, [], metric="cityblock")
    assert plan.config["sketch"] == 0


def test_auto_fit_plans_and_applies_sketch_high_d():
    """DBSCAN(auto=True) end-to-end at d=160: the plan carries a
    positive sketch width, the fit applies it (compute telemetry),
    and labels stay byte-identical to the explicit sketch=0 config —
    the knob's label-safety is what makes it plannable at all."""
    from pypardis_tpu.ops.sketch import auto_k

    X, eps = _high_d(n=768, dim=160)
    staging.clear()
    m = DBSCAN(eps=eps, min_samples=5, auto=True, block=128,
               mesh=default_mesh(8))
    m.fit(X)
    rep = m.report()
    planned = rep["tune"]["plan"]["config"]["sketch"]
    assert planned == auto_k(160)
    assert rep["compute"]["sketch_k"] == planned

    staging.clear()
    cfg = dict(rep["tune"]["plan"]["config"])
    ref = _explicit(
        X, m, {**cfg, "sketch": 0}, mesh=default_mesh(8), sketch=0
    )
    np.testing.assert_array_equal(
        np.asarray(m.labels_), np.asarray(ref.labels_)
    )
