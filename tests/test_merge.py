"""Host-side merge utilities vs reference ClusterAggregator semantics."""

import numpy as np

from pypardis_tpu.parallel.merge import merge_occurrences, resolve_label_edges


def test_resolve_edges_min_id():
    ids = np.array([3, 7, 9, 12])
    mapping = resolve_label_edges(np.array([[7, 3], [9, 12]]), ids)
    assert mapping[7] == 3 and mapping[3] == 3
    assert mapping[12] == 9 and mapping[9] == 9


def test_merge_core_links_clusters():
    # points 0,1 in cluster 0 (home part A); points 2,3 in cluster 2
    # (home part B); point 1 is core and appears in B's run labeled 2.
    home = np.array([0, 0, 2, 2])
    core = np.array([True, True, True, True])
    final, mapping = merge_occurrences(home, core, [1], [2])
    assert (final == 0).all()
    assert mapping[2] == 0


def test_noncore_occurrence_does_not_merge():
    # point 1 is a border point (non-core): its duplicate in B must NOT
    # merge clusters (reference README.md:27-29).
    home = np.array([0, 0, 2, 2])
    core = np.array([True, False, True, True])
    final, _ = merge_occurrences(home, core, [1], [2])
    np.testing.assert_array_equal(final, [0, 0, 2, 2])


def test_noise_occurrence_ignored():
    home = np.array([0, 0, 2, 2])
    core = np.array([True, True, True, True])
    final, _ = merge_occurrences(home, core, [1], [-1])
    np.testing.assert_array_equal(final, [0, 0, 2, 2])


def test_transitive_merge_across_three_partitions():
    home = np.array([0, 0, 2, 2, 4, 4])
    core = np.ones(6, bool)
    # 1 links cluster 0<->2; 3 links cluster 2<->4
    final, _ = merge_occurrences(home, core, [1, 3], [2, 4])
    assert (final == 0).all()
