"""Host-side merge utilities vs reference ClusterAggregator semantics."""

import numpy as np

from pypardis_tpu.parallel.merge import merge_occurrences, resolve_label_edges


def test_resolve_edges_min_id():
    ids = np.array([3, 7, 9, 12])
    mapping = resolve_label_edges(np.array([[7, 3], [9, 12]]), ids)
    assert mapping[7] == 3 and mapping[3] == 3
    assert mapping[12] == 9 and mapping[9] == 9


def test_merge_core_links_clusters():
    # points 0,1 in cluster 0 (home part A); points 2,3 in cluster 2
    # (home part B); point 1 is core and appears in B's run labeled 2.
    home = np.array([0, 0, 2, 2])
    core = np.array([True, True, True, True])
    final, mapping = merge_occurrences(home, core, [1], [2])
    assert (final == 0).all()
    assert mapping[2] == 0


def test_noncore_occurrence_does_not_merge():
    # point 1 is a border point (non-core): its duplicate in B must NOT
    # merge clusters (reference README.md:27-29).
    home = np.array([0, 0, 2, 2])
    core = np.array([True, False, True, True])
    final, _ = merge_occurrences(home, core, [1], [2])
    np.testing.assert_array_equal(final, [0, 0, 2, 2])


def test_noise_occurrence_ignored():
    home = np.array([0, 0, 2, 2])
    core = np.array([True, True, True, True])
    final, _ = merge_occurrences(home, core, [1], [-1])
    np.testing.assert_array_equal(final, [0, 0, 2, 2])


def test_transitive_merge_across_three_partitions():
    home = np.array([0, 0, 2, 2, 4, 4])
    core = np.ones(6, bool)
    # 1 links cluster 0<->2; 3 links cluster 2<->4
    final, _ = merge_occurrences(home, core, [1, 3], [2, 4])
    assert (final == 0).all()

def test_host_merge_matches_device_merge():
    """sharded_dbscan(merge='host') must produce exactly the same
    canonicalized labels as the in-graph device merge on the virtual
    mesh (VERDICT r2: the compact host merge must be a wired, proven
    alternative for point counts where replicated (N+1,) arrays stop
    fitting)."""
    from sklearn.datasets import make_blobs

    from pypardis_tpu.parallel import default_mesh, sharded_dbscan
    from pypardis_tpu.partition import KDPartitioner

    X, _ = make_blobs(
        n_samples=4000, centers=12, n_features=3, cluster_std=0.35,
        random_state=3,
    )
    mesh = default_mesh(8)
    part = KDPartitioner(X, max_partitions=8)
    l_dev, c_dev, s_dev = sharded_dbscan(
        X, part, eps=0.5, min_samples=5, block=128, mesh=mesh,
        merge="device",
    )
    l_host, c_host, s_host = sharded_dbscan(
        X, part, eps=0.5, min_samples=5, block=128, mesh=mesh,
        merge="host",
    )
    assert s_host.get("merge") == "host"
    np.testing.assert_array_equal(c_dev, c_host)
    np.testing.assert_array_equal(l_dev, l_host)


def test_merge_auto_switchover(monkeypatch):
    """merge='auto' switches to the host merge past MERGE_HOST_AUTO —
    forced low here so the switchover path actually executes in CI
    (round-3 review: the threshold had never been crossed anywhere)."""
    from sklearn.datasets import make_blobs

    import pypardis_tpu.parallel.sharded as sm
    from pypardis_tpu.parallel import default_mesh, sharded_dbscan
    from pypardis_tpu.partition import KDPartitioner

    X, _ = make_blobs(
        n_samples=3000, centers=8, n_features=3, cluster_std=0.35,
        random_state=7,
    )
    mesh = default_mesh(8)
    part = KDPartitioner(X, max_partitions=8)
    l_dev, _, s_dev = sharded_dbscan(
        X, part, eps=0.5, min_samples=5, block=128, mesh=mesh,
        merge="auto",
    )
    assert s_dev.get("merge") == "device"  # below threshold: in-graph

    monkeypatch.setattr(sm, "MERGE_HOST_AUTO", 1000)
    l_host, _, s_host = sharded_dbscan(
        X, part, eps=0.5, min_samples=5, block=128, mesh=mesh,
        merge="auto",
    )
    assert s_host.get("merge") == "host"  # threshold crossed
    np.testing.assert_array_equal(l_dev, l_host)


def test_ring_halo_host_merge_supported():
    """ring + merge='host' is the >MERGE_HOST_AUTO spill path (round-4
    review, Next #6) — it must run and agree with the device merge."""
    from pypardis_tpu.parallel import default_mesh, sharded_dbscan
    from pypardis_tpu.partition import KDPartitioner

    rng = np.random.default_rng(0)
    X = rng.normal(size=(512, 2))
    part = KDPartitioner(X, max_partitions=8)
    ref, _c, _s = sharded_dbscan(
        X, part, eps=0.3, min_samples=5, block=64,
        mesh=default_mesh(8), halo="ring", merge="device",
    )
    labels, _core, stats = sharded_dbscan(
        X, part, eps=0.3, min_samples=5, block=64,
        mesh=default_mesh(8), halo="ring", merge="host",
    )
    assert stats.get("merge") == "host"
    np.testing.assert_array_equal(labels, ref)
