"""Real-dataset fixture (ISSUE 14 satellite).

``benchdata.load_real_dataset()`` serves the UCI optdigits corpus —
real measured data replacing one synthetic CLIP/KDD stand-in — from a
checksum-verified cache/download when available and the COMMITTED
subsample otherwise, so this file is tier-1 and offline-safe.  The
ARI pin runs our engine against sklearn's DBSCAN at the same config
on the same real rows.
"""

import os

import numpy as np
import pytest

import benchdata
from benchdata import load_real_dataset
from pypardis_tpu import DBSCAN
from pypardis_tpu.parallel import default_mesh

EPS, MS = 22.0, 5


def test_loader_offline_fallback(tmp_path, monkeypatch):
    """With an empty data dir and downloads disabled, the committed
    subsample serves — graceful skip, never a network failure."""
    monkeypatch.setenv("PYPARDIS_DATA_DIR", str(tmp_path))
    X, y, meta = load_real_dataset(download=False)
    assert meta["offline"] and meta["source"] == "committed_subsample"
    assert X.shape == (1797, 64) and len(y) == 1797
    assert X.min() >= 0 and X.max() <= 16  # real 8x8 count data
    assert meta["sha256"] == benchdata._REAL_DATASET_SHA256


def test_loader_rejects_corrupt_cache(tmp_path, monkeypatch):
    monkeypatch.setenv("PYPARDIS_DATA_DIR", str(tmp_path))
    bad = tmp_path / benchdata._REAL_DATASET_FILE
    bad.write_bytes(b"not the dataset")
    X, y, meta = load_real_dataset(download=False)
    assert meta["source"] == "committed_subsample"
    assert not os.path.exists(bad)  # corrupt cache discarded


def test_real_dataset_ari_pin_vs_sklearn(tmp_path, monkeypatch):
    """The pinned-ARI artifact: our labels vs sklearn DBSCAN on the
    same real rows at the same config.  The tiny residual (<1%) is
    the cross-implementation f32/f64 near-threshold border ambiguity
    — measured 0.997 at this config; the pin guards against anything
    structural."""
    from sklearn.cluster import DBSCAN as SKDBSCAN
    from sklearn.metrics import adjusted_rand_score

    monkeypatch.setenv("PYPARDIS_DATA_DIR", str(tmp_path))
    X, _, meta = load_real_dataset(download=False)
    sk = SKDBSCAN(eps=EPS, min_samples=MS).fit(X)
    m = DBSCAN(eps=EPS, min_samples=MS, block=128).fit(X)
    ari = adjusted_rand_score(sk.labels_, np.asarray(m.labels_))
    assert ari >= 0.99, ari
    assert int(m.labels_.max()) + 1 >= 10  # real digit structure
    # the sharded engine agrees with the fused one on the real rows
    ms_ = DBSCAN(
        eps=EPS, min_samples=MS, block=128, mesh=default_mesh(8)
    ).fit(X)
    ari_modes = adjusted_rand_score(
        np.asarray(m.labels_), np.asarray(ms_.labels_)
    )
    assert ari_modes == pytest.approx(1.0)
