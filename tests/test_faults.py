"""Fault-tolerant execution (ISSUE 9 tentpole).

Deterministic fault injection (``pypardis_tpu.utils.faults``), the
unified retry/backoff layer (``utils.retry``), graceful-degradation
rungs (merge host-spill, global-Morton → KD mode fallback), serving
deadlines + load shedding, and the resource-pressure → host-spill
hookup.  The governing contract everywhere: an injected fault RECOVERS
through the production machinery and labels stay byte-identical to the
clean run.
"""

import numpy as np
import pytest
from sklearn.datasets import make_blobs

from pypardis_tpu import DBSCAN
from pypardis_tpu.parallel import staging
from pypardis_tpu.utils import faults
from pypardis_tpu.utils.retry import Retrier


@pytest.fixture(autouse=True)
def _clean_slate():
    faults.clear()
    staging.clear()
    yield
    faults.clear()
    staging.clear()


@pytest.fixture()
def blob_data():
    X, _ = make_blobs(
        n_samples=2000, centers=8, n_features=4, cluster_std=0.3,
        random_state=3,
    )
    return X.astype(np.float32)


@pytest.fixture()
def chain_data():
    """A line of points spanning every global-Morton shard: the single
    cluster threads all 8 ranges, so the pmin fixpoint needs several
    rounds — wide enough to inject into round 2."""
    rng = np.random.default_rng(0)
    n = 3000
    X = np.stack(
        [np.arange(n) * 0.1, rng.normal(0, 0.05, n)], axis=1
    )
    return X.astype(np.float32)


KW = dict(eps=0.45, min_samples=5, block=64)


# ---------------------------------------------------------------------------
# plan parsing / no-op contract
# ---------------------------------------------------------------------------


def test_plan_parse_counts_and_kinds():
    p = faults.FaultPlan.parse(
        "gm.ring_round:2=transfer_error, stepped.batch:5=oom,"
        "serve.drain:1=hang(3s),chained.partition:*=hang(0.25)"
    )
    assert p.entries["gm.ring_round"] == [(2, "transfer_error", 0.0)]
    assert p.entries["stepped.batch"] == [(5, "oom", 0.0)]
    assert p.entries["serve.drain"] == [(1, "hang", 3.0)]
    assert p.entries["chained.partition"] == [("*", "hang", 0.25)]


def test_counted_occurrence_is_reproducible():
    with faults.plan("site.x:3=error") as p:
        faults.maybe_fail("site.x")
        faults.maybe_fail("site.x")
        with pytest.raises(faults.FaultInjected):
            faults.maybe_fail("site.x")
        faults.maybe_fail("site.x")  # 4th arrival: armed occurrence gone
        assert p.injected == {"site.x": 1}


def test_bad_spec_raises():
    with pytest.raises(ValueError, match="site"):
        faults.FaultPlan.parse("whatever this is")
    with pytest.raises(ValueError, match="kind"):
        faults.FaultPlan.parse("a.b:1=explode")


def test_noop_when_unset():
    assert faults.active() is None
    faults.maybe_fail("gm.ring_round")  # must be a no-op, not a KeyError
    assert faults.fault_stats() == {}


# ---------------------------------------------------------------------------
# recovery through the unified retry layer — labels byte-identical
# ---------------------------------------------------------------------------


def test_gm_fixpoint_transfer_error_recovers(chain_data):
    clean = DBSCAN(mode="global_morton", merge="device", **KW)
    clean.fit(chain_data)
    staging.clear()
    with faults.plan("gm.fixpoint_round:1=transfer_error"):
        faulty = DBSCAN(mode="global_morton", merge="device", **KW)
        faulty.fit(chain_data)
    np.testing.assert_array_equal(faulty.labels_, clean.labels_)
    r = faulty.report()
    assert r["faults"]["injected"] == 1
    assert r["faults"]["retried"] >= 1
    assert r["faults"]["giveups"] == 0
    assert r["events"]["fault_injected"] == 1
    assert r["events"]["transient_retry"] >= 1
    # the clean run's report stays all-zero
    assert clean.report()["faults"]["injected"] == 0


def test_gm_ring_round_transfer_error_recovers(chain_data):
    clean = DBSCAN(mode="global_morton", **KW).fit(chain_data)
    staging.clear()
    with faults.plan("gm.ring_round:2=transfer_error"):
        faulty = DBSCAN(mode="global_morton", **KW).fit(chain_data)
    np.testing.assert_array_equal(faulty.labels_, clean.labels_)
    assert faulty.report()["faults"]["injected"] == 1


def test_staging_oom_evicts_and_recovers(blob_data):
    clean = DBSCAN(max_partitions=8, **KW).fit(blob_data)
    staging.clear()
    with faults.plan("staging.device_put:1=oom"):
        faulty = DBSCAN(max_partitions=8, **KW).fit(blob_data)
    np.testing.assert_array_equal(faulty.labels_, clean.labels_)
    r = faulty.report()
    assert r["faults"]["injected"] == 1
    assert r["faults"]["retried"] >= 1


# ---------------------------------------------------------------------------
# graceful degradation rungs
# ---------------------------------------------------------------------------


def test_device_merge_oom_spills_to_host(blob_data):
    clean = DBSCAN(merge="host", max_partitions=8, **KW).fit(blob_data)
    staging.clear()
    with faults.plan("sharded.execute:1=oom"):
        faulty = DBSCAN(merge="device", max_partitions=8, **KW)
        faulty.fit(blob_data)
    np.testing.assert_array_equal(faulty.labels_, clean.labels_)
    r = faulty.report()
    assert r["sharding"]["merge"] == "host"
    assert r["faults"]["degraded"] >= 1
    assert r["faults"]["degraded_to"] == "merge_host"
    assert r["events"]["degraded"] >= 1


def test_gm_terminal_oom_falls_back_to_kd(chain_data):
    clean = DBSCAN(mode="global_morton", **KW).fit(chain_data)
    staging.clear()
    with faults.plan("gm.exchange:1=oom"):
        faulty = DBSCAN(mode="global_morton", **KW).fit(chain_data)
    # mode parity is a pinned repo contract, so the fallback's labels
    # match the clean global-Morton run byte-for-byte
    np.testing.assert_array_equal(faulty.labels_, clean.labels_)
    r = faulty.report()
    assert r["faults"]["degraded_to"] == "kd_owner_computes"
    # the fallback really ran the KD machinery
    assert r["sharding"].get("mode") != "global_morton"


# ---------------------------------------------------------------------------
# Retrier semantics
# ---------------------------------------------------------------------------


def test_retrier_retries_then_succeeds():
    calls = [0]

    def flaky():
        calls[0] += 1
        if calls[0] < 3:
            raise RuntimeError("UNAVAILABLE: synthetic")
        return "ok"

    assert Retrier("t.flaky", waits=(0, 0)).run(flaky) == "ok"
    assert calls[0] == 3


def test_retrier_giveup_counts_and_raises():
    from pypardis_tpu import obs

    rec = obs.RunRecorder()

    def always():
        raise RuntimeError("UNAVAILABLE: forever")

    with obs.use_recorder(rec):
        with pytest.raises(RuntimeError, match="forever"):
            Retrier("t.dead", waits=(0, 0)).run(always)
    c = rec.metrics.as_dict()["counters"]
    assert c["retry.t.dead.attempts"] == 2
    assert c["retry.t.dead.giveups"] == 1


def test_retrier_nonretryable_raises_immediately():
    calls = [0]

    def bad():
        calls[0] += 1
        raise ValueError("user error")

    with pytest.raises(ValueError):
        Retrier("t.bad", waits=(0, 0)).run(bad)
    assert calls[0] == 1


def test_retrier_deadline_bounds_total_wall():
    def always():
        raise RuntimeError("UNAVAILABLE: slow")

    import time

    t0 = time.perf_counter()
    with pytest.raises(RuntimeError):
        Retrier("t.deadline", waits=(60, 60), deadline_s=0.1).run(always)
    assert time.perf_counter() - t0 < 5.0  # never slept the 60s ladder


# ---------------------------------------------------------------------------
# serving deadlines + load shedding
# ---------------------------------------------------------------------------


@pytest.fixture()
def served_model():
    X, _ = make_blobs(
        n_samples=600, centers=3, n_features=2, cluster_std=0.3,
        random_state=1,
    )
    X = X.astype(np.float32)
    model = DBSCAN(eps=0.4, min_samples=5, block=64).fit(X)
    return model, X


def test_serve_drain_hang_fails_ticket_within_deadline(served_model):
    from pypardis_tpu.serve.engine import DeadlineExceeded

    model, X = served_model
    eng = model.query_engine()
    with faults.plan("serve.drain:1=hang(0.3)"):
        t = eng.submit(X[:16], timeout_s=0.05)
        eng.drain()
    assert t.done and t.failed
    with pytest.raises(DeadlineExceeded, match="deadline"):
        t.result()
    assert eng.serving_stats()["deadline_failures"] == 1
    # the engine is healthy afterwards: a clean predict still answers
    labs = eng.predict(X[:8])
    assert labs.shape == (8,)


def test_submit_without_timeout_survives_hang(served_model):
    model, X = served_model
    eng = model.query_engine()
    with faults.plan("serve.drain:1=hang(0.1)"):
        t = eng.submit(X[:4])
        eng.drain()
    assert t.done and not t.failed  # no deadline -> slow success


def test_queue_full_sheds_with_counter(served_model):
    from pypardis_tpu.serve.engine import QueueFull

    model, X = served_model
    eng = model.query_engine(batch_capacity=64, max_pending=8)
    with pytest.raises(QueueFull, match="queue full"):
        eng.submit(X[:16])
    assert eng.serving_stats()["shed_total"] == 1
    # schema: counters always present, ints
    st = eng.serving_stats()
    assert isinstance(st["shed_total"], int)
    assert isinstance(st["deadline_failures"], int)


def test_sustained_load_fault_mode(served_model):
    from pypardis_tpu.serve.load import sustained_load

    model, X = served_model
    eng = model.query_engine()
    with faults.plan("serve.drain:*=hang(0.05)"):
        stats = sustained_load(
            eng, clients=2, duration_s=0.4, rate_hz=60.0,
            batch_rows=4, submit_timeout_s=0.02, seed=7,
        )
    # every drain stalls past the 20ms deadline: the harness completes
    # (never hangs, never aborts) and reports the failures it absorbed
    assert stats["deadline_failures"] >= 1
    assert stats["shed"] >= 0
    assert stats["submit_timeout_s"] == 0.02


# ---------------------------------------------------------------------------
# resource pressure -> preemptive host-spill rung
# ---------------------------------------------------------------------------


def test_rss_soft_limit_prefers_host_merge(blob_data, monkeypatch):
    monkeypatch.setenv("PYPARDIS_RSS_SOFT_LIMIT", "1024")  # 1KB: always
    from pypardis_tpu.obs.resources import memory_pressure

    assert memory_pressure()
    model = DBSCAN(merge="auto", max_partitions=8, **KW).fit(blob_data)
    r = model.report()
    # merge='auto' resolved to the host-spill rung preemptively
    assert r["sharding"]["merge"] == "host"
    # the sampler emitted the resource.pressure event
    assert r["metrics"]["counters"].get(
        "events.resource.pressure", 0
    ) >= 1


def test_no_pressure_without_limit(monkeypatch):
    monkeypatch.delenv("PYPARDIS_RSS_SOFT_LIMIT", raising=False)
    from pypardis_tpu.obs.resources import memory_pressure

    assert not memory_pressure()
