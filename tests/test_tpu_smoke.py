"""Hardware smoke tests: the Pallas kernels must lower through Mosaic.

Round 2 shipped kernels that passed 91 CPU tests (interpret mode) and
crashed on the first real-TPU call — nothing in CI ever exercised the
Mosaic lowering.  These tests compile and RUN both Pallas kernels and the
end-to-end Pallas-backed pipeline on the actual accelerator; they skip
anywhere else (the CPU CI mesh), so `python -m pytest tests/` stays green
off-hardware while `make tpu-smoke` fails loudly if a kernel rewrite
breaks lowering again.

Run via: ``make tpu-smoke`` (sets PYPARDIS_TEST_PLATFORM=native so
conftest.py leaves the ambient TPU platform in place).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    jax.default_backend() != "tpu",
    reason="TPU hardware smoke test (run via `make tpu-smoke`)",
)


def _blob_points(n, d, seed=0):
    """Morton-sorted blobs — the layout the driver always feeds the
    kernels (ops/pipeline.py).  Sorting matters for numerics, not just
    speed: tiles become spatially tight, so the per-tile recentring
    keeps bf16_3x matmul error at eps scale instead of dataset scale."""
    from pypardis_tpu.partition import spatial_order

    rng = np.random.default_rng(seed)
    centers = rng.uniform(-10, 10, size=(8, d))
    assign = rng.integers(0, 8, size=n)
    pts = (
        centers[assign] + rng.normal(scale=0.4, size=(n, d))
    ).astype(np.float32)
    return pts[spatial_order(pts)]


def _banded_counts(pts, mask, eps, rel=1e-3):
    """fp64 host oracle: (tight, loose) neighbor counts excluding /
    including an eps*(1±rel) boundary band.  The Pallas and XLA paths
    schedule the matmul expansion differently, so pairs within float32
    rounding of the eps shell may legitimately flip between them; any
    pair clearly inside or outside must agree with fp64."""
    x = pts.astype(np.float64)[mask]
    d2 = ((x[:, None, :] - x[None, :, :]) ** 2).sum(-1)
    tight = (d2 <= (eps * (1 - rel)) ** 2).sum(1)
    loose = (d2 <= (eps * (1 + rel)) ** 2).sum(1)
    return tight, loose


def test_neighbor_counts_pallas_lowers_and_brackets_fp64():
    from pypardis_tpu.ops.pallas_kernels import neighbor_counts_pallas

    n, d, block = 4096, 16, 1024  # nt = 4 > 1: exercises grid slicing
    pts = _blob_points(n, d)
    mask = np.ones(n, bool)
    mask[-50:] = False
    tight, loose = _banded_counts(pts, mask, 1.5)
    # precision='highest' (exact fp32 matmuls) validates the kernel
    # logic — grid slicing, DMA, two-level pruning — against fp64:
    # every pair clearly off the eps shell must agree.
    got = np.asarray(
        neighbor_counts_pallas(
            jnp.asarray(pts), 1.5, jnp.asarray(mask), block=block,
            precision="highest",
        )
    )[mask]
    assert (got >= tight).all() and (got <= loose).all(), (
        (tight - got).max(), (got - loose).max()
    )
    # The default bf16_3x mode trades boundary-pair exactness for half
    # the MXU passes; its dropped al*bl term scales with coordinate
    # magnitude, so loose tiles can flip shell-adjacent pairs.  Bound
    # the damage rather than demand exactness (cluster structure is
    # covered by the ARI test below).
    got_hi = np.asarray(
        neighbor_counts_pallas(
            jnp.asarray(pts), 1.5, jnp.asarray(mask), block=block,
            precision="high",
        )
    )[mask]
    exact = np.asarray(
        ((pts.astype(np.float64)[mask][:, None, :]
          - pts.astype(np.float64)[mask][None, :, :]) ** 2).sum(-1)
        <= 1.5 * 1.5
    ).sum(1)
    assert np.abs(got_hi - exact).max() <= 5, np.abs(got_hi - exact).max()


def test_min_neighbor_label_pallas_lowers_and_matches_xla():
    from pypardis_tpu.ops.distances import min_neighbor_label, neighbor_counts
    from pypardis_tpu.ops.pallas_kernels import min_neighbor_label_pallas

    n, d, block = 4096, 16, 1024
    pts = _blob_points(n, d, seed=1)
    mask = np.ones(n, bool)
    mask[-50:] = False
    labels = jnp.arange(n, dtype=jnp.int32)
    src = neighbor_counts(jnp.asarray(pts), 1.5, jnp.asarray(mask)) >= 4
    # precision='highest' on both paths: disagreements can then come
    # only from fp32-ULP shell-adjacent pairs, not bf16 splits.
    got = min_neighbor_label_pallas(
        jnp.asarray(pts), labels, 1.5, src, block=block,
        row_mask=jnp.asarray(mask), precision="highest",
    )
    want = min_neighbor_label(
        jnp.asarray(pts), labels, 1.5, src, row_mask=jnp.asarray(mask),
        precision="highest",
    )
    m = np.asarray(mask)
    mismatch = (np.asarray(got)[m] != np.asarray(want)[m]).mean()
    assert mismatch < 1e-2, mismatch


def test_dbscan_fixed_size_pallas_end_to_end():
    from sklearn.cluster import DBSCAN as SKDBSCAN
    from sklearn.metrics import adjusted_rand_score

    from pypardis_tpu.ops import dbscan_fixed_size, densify_labels

    n, d = 8192, 16
    pts = _blob_points(n, d, seed=2)
    mask = np.ones(n, bool)
    roots, core, pair_stats = dbscan_fixed_size(
        jnp.asarray(pts), 1.5, 5, jnp.asarray(mask), backend="pallas"
    )
    total, budget, passes = np.asarray(pair_stats)[:3]
    assert 0 < total <= budget, (total, budget)
    assert passes >= 2, passes
    got = densify_labels(np.asarray(roots))
    want = SKDBSCAN(eps=1.5, min_samples=5).fit_predict(pts)
    assert adjusted_rand_score(got, want) >= 0.99


def test_default_backend_driver_matches_sklearn():
    """The product default (backend='auto' -> Pallas on TPU) end to end."""
    from sklearn.cluster import DBSCAN as SKDBSCAN
    from sklearn.metrics import adjusted_rand_score

    from pypardis_tpu import DBSCAN
    from pypardis_tpu.ops.labels import resolve_backend

    assert resolve_backend("auto", "euclidean", 1 << 20, 1024) == "pallas"
    X = _blob_points(30_000, 16, seed=3)
    got = DBSCAN(eps=1.5, min_samples=10, block=2048).fit_predict(X)
    want = SKDBSCAN(eps=1.5, min_samples=10).fit_predict(X)
    assert adjusted_rand_score(got, want) >= 0.99


def test_stepped_propagation_path(monkeypatch):
    """The host-stepped propagation loop (auto-selected past
    STEP_THRESHOLD to keep single executions under deployment
    watchdogs) must match sklearn like the fused path does."""
    from sklearn.cluster import DBSCAN as SKDBSCAN
    from sklearn.metrics import adjusted_rand_score

    from pypardis_tpu import DBSCAN
    from pypardis_tpu.ops import pipeline

    monkeypatch.setattr(pipeline, "STEP_THRESHOLD", 1)
    X = _blob_points(30_000, 16, seed=4)
    got = DBSCAN(eps=1.5, min_samples=10, block=2048).fit_predict(X)
    want = SKDBSCAN(eps=1.5, min_samples=10).fit_predict(X)
    assert adjusted_rand_score(got, want) >= 0.99
