"""ClusterAggregator merge cases vs reference aggregator.py:38-63."""

import numpy as np

from pypardis_tpu.aggregator import ClusterAggregator, UnionFind, default_value


def test_default_value_is_max():
    import sys

    assert default_value() == sys.maxsize


def test_new_cluster_created():
    agg = ClusterAggregator()
    agg + (0, ["0:0"])
    assert agg.fwd["0:0"] == 0
    assert agg.next_global_id == 1


def test_noise_and_noncore_skipped():
    agg = ClusterAggregator()
    agg + (0, ["0:-1"])
    agg + (1, ["1:2*"])
    assert len(agg.rev) == 0
    assert agg.next_global_id == 0


def test_min_id_merge():
    agg = ClusterAggregator()
    agg + (0, ["0:0"])   # global 0
    agg + (1, ["1:0"])   # global 1
    agg + (2, ["0:0", "1:0"])  # merges 1 into 0
    assert agg.fwd["0:0"] == 0 and agg.fwd["1:0"] == 0
    assert 1 not in agg.rev


def test_transitive_three_way_merge():
    agg = ClusterAggregator()
    agg + (0, ["a"])
    agg + (1, ["b"])
    agg + (2, ["c"])
    agg + (3, ["a", "b"])
    agg + (4, ["b", "c"])
    assert agg.fwd["a"] == agg.fwd["b"] == agg.fwd["c"] == 0
    assert set(agg.rev.keys()) == {0}


def test_combine_two_aggregators():
    a = ClusterAggregator()
    a + (0, ["a"])
    a + (1, ["b"])
    b = ClusterAggregator()
    b + (0, ["b", "c"])
    a + b
    assert a.fwd["b"] == a.fwd["c"]


def test_union_find_min_id_roots():
    uf = UnionFind(6)
    uf.union(4, 2)
    uf.union(2, 0)
    uf.union(5, 3)
    roots = uf.roots()
    assert roots[0] == roots[2] == roots[4] == 0
    assert roots[3] == roots[5] == 3
    assert roots[1] == 1
