"""metric="haversine" for trajectories (ISSUE 14 satellite).

(lat, lon)-radian rows embed onto the 3-D unit sphere and the
great-circle eps remaps to the chord ``2 sin(eps/2)`` for the L2
kernels — the PR 13 cosine machinery with a different projection.
The correctness bar mirrors the cosine one: fit pinned BITWISE against
a brute-force numpy haversine oracle, predict bitwise against the
index oracle, save/load round trip serves identically (projection
metadata persisted), sweeps ride the cached graph, validation rejects
out-of-range eps loudly.
"""

import numpy as np
import pytest

from pypardis_tpu import DBSCAN
from pypardis_tpu.geometry import latlon_to_unit_sphere
from pypardis_tpu.parallel import default_mesh

EPS = 0.05  # radians of great-circle arc
MS = 5


@pytest.fixture(scope="module")
def trajectories():
    """GeoLife-like clusters of (lat, lon) radian points: dense stop
    clusters at well-separated locations (BASELINE config 3's shape),
    longitudes spanning the dateline-free band."""
    rng = np.random.default_rng(11)
    centers = np.column_stack([
        rng.uniform(-1.2, 1.2, 6), rng.uniform(-2.8, 2.8, 6)
    ])
    return np.concatenate([
        c + rng.normal(scale=0.008, size=(130, 2)) for c in centers
    ])


def _haversine_adj(X, eps):
    """f64 numpy haversine adjacency (the standard two-sin formula)."""
    lat, lon = X[:, 0], X[:, 1]
    dlat = lat[:, None] - lat[None, :]
    dlon = lon[:, None] - lon[None, :]
    h = (
        np.sin(dlat / 2.0) ** 2
        + np.cos(lat[:, None]) * np.cos(lat[None, :])
        * np.sin(dlon / 2.0) ** 2
    )
    theta = 2.0 * np.arcsin(np.sqrt(np.clip(h, 0.0, 1.0)))
    return theta <= eps


def _oracle(X, eps, ms):
    """Brute-force haversine DBSCAN, parallel formulation
    (min-core-index components, border = min adjacent root)."""
    import collections

    from pypardis_tpu.ops.labels import densify_labels

    adj = _haversine_adj(X, eps)
    core = adj.sum(1) >= ms
    n = len(X)
    comp = np.full(n, -1)
    cid = 0
    for i in range(n):
        if core[i] and comp[i] < 0:
            q = collections.deque([i])
            comp[i] = cid
            while q:
                u = q.popleft()
                for v in np.flatnonzero(adj[u] & core):
                    if comp[v] < 0:
                        comp[v] = cid
                        q.append(v)
            cid += 1
    roots = np.full(cid, n)
    for i in np.flatnonzero(core):
        roots[comp[i]] = min(roots[comp[i]], i)
    lab = np.full(n, -1, np.int64)
    for i in range(n):
        if core[i]:
            lab[i] = roots[comp[i]]
        else:
            nbr = np.flatnonzero(adj[i] & core)
            if len(nbr):
                lab[i] = min(roots[comp[j]] for j in nbr)
    return densify_labels(lab), core


def _canon(labels, core):
    from pypardis_tpu.ops.labels import densify_labels
    from pypardis_tpu.parallel.sharded import _canonicalize_roots

    return densify_labels(
        _canonicalize_roots(np.asarray(labels), np.asarray(core))
    )


def test_embedding_is_exact_chord_frame(trajectories):
    """The unit-sphere embedding's chord distances reproduce the
    haversine angles: |e(a) - e(b)| == 2 sin(theta/2) to f64 accuracy,
    so the eps remap is a pure monotone re-threshold."""
    X = trajectories[:100]
    E = latlon_to_unit_sphere(X)
    assert E.shape == (100, 3)
    np.testing.assert_allclose(
        np.linalg.norm(E, axis=1), 1.0, atol=1e-12
    )
    adj = _haversine_adj(X, EPS)
    chord2 = np.sum((E[:, None, :] - E[None, :, :]) ** 2, axis=-1)
    kernel_eps = 2.0 * np.sin(EPS / 2.0)
    agree = (chord2 <= kernel_eps ** 2) == adj
    assert agree.mean() > 0.9999  # only exact-threshold ties may differ


def test_fit_pinned_against_numpy_oracle(trajectories):
    X = trajectories
    m = DBSCAN(eps=EPS, min_samples=MS, metric="haversine", block=128)
    m.fit(X)
    ol, oc = _oracle(X, EPS, MS)
    np.testing.assert_array_equal(
        _canon(m.labels_, m.core_sample_mask_), ol
    )
    np.testing.assert_array_equal(np.asarray(m.core_sample_mask_), oc)
    # user-facing spec survives the kernel-frame swap
    assert m.metric == "haversine" and m.eps == EPS
    assert m.report()["params"]["metric"] == "haversine"
    # model.data is the embedded kernel frame every surface shares
    assert m.data.shape == (len(X), 3)


def test_sharded_modes_match_oracle(trajectories):
    X = trajectories
    ol, _ = _oracle(X, EPS, MS)
    for kw in (
        dict(mesh=default_mesh(8)),
        dict(mesh=default_mesh(8), mode="global_morton"),
    ):
        m = DBSCAN(
            eps=EPS, min_samples=MS, metric="haversine", block=128,
            **kw,
        )
        m.fit(X)
        np.testing.assert_array_equal(
            _canon(m.labels_, m.core_sample_mask_), ol,
            err_msg=str(kw),
        )


def test_predict_bitwise_oracle_and_save_load(trajectories, tmp_path):
    X = trajectories
    rng = np.random.default_rng(1)
    Q = X[rng.integers(0, len(X), 80)] + rng.normal(
        scale=0.002, size=(80, 2)
    )
    m = DBSCAN(eps=EPS, min_samples=MS, metric="haversine", block=128)
    m.fit(X)
    pred = m.predict(Q)
    olab, _ = m.query_engine().index.oracle_predict(Q)
    np.testing.assert_array_equal(pred, olab)
    # independent f64 haversine membership check
    cores = np.asarray(m.core_sample_mask_)
    within = _haversine_adj(
        np.concatenate([Q, X]), EPS
    )[:len(Q), len(Q):][:, cores].any(1)
    assert ((pred >= 0) == within).mean() > 0.99
    path = str(tmp_path / "hav_model.npz")
    m.save(path)
    m2 = DBSCAN.load(path)
    assert m2.metric == "haversine"
    np.testing.assert_array_equal(m2.predict(Q), pred)
    # the restored engine still projects (lat, lon) queries
    assert m2.query_engine().index.projection == "latlon"


def test_sweep_rides_cached_graph(trajectories):
    X = trajectories
    kw = dict(metric="haversine", block=128, mesh=default_mesh(1))
    m = DBSCAN(eps=EPS, min_samples=MS, **kw)
    res = m.sweep(X, [0.03, 0.06])
    assert res.stats["distance_passes"] == 1
    for eps in (0.03, 0.06):
        ref = DBSCAN(eps=eps, min_samples=MS, **kw).fit(X)
        np.testing.assert_array_equal(
            res.labels(eps), ref.labels_, err_msg=str(eps)
        )


def test_validation():
    with pytest.raises(ValueError):
        DBSCAN(eps=4.0, metric="haversine")  # radians, not degrees
    m = DBSCAN(eps=0.1, min_samples=2, metric="haversine")
    with pytest.raises(ValueError):
        m.fit(np.zeros((4, 3)))  # needs (N, 2) lat/lon
    with pytest.raises(ValueError):
        m.fit(np.array([[0.1, np.nan]]))
    with pytest.raises(NotImplementedError):
        m.fit(np.random.default_rng(0).normal(
            scale=0.01, size=(8, 2)
        )).live()
