"""Dispatch-level tile-pair sparsity (ISSUE 11).

The contract under test: the XLA kernels driven over the compacted
live tile-pair list (``PYPARDIS_DISPATCH=pair``; ``auto``, the
default, compacts past ``PAIR_DISPATCH_MIN_TILES`` tiles) produce
labels BYTE-IDENTICAL to the dense T^2 grid (``dense``) — across the
fused engine, the KD owner-computes modes (device + host merge),
global-Morton (mesh + chained 1-dev), mixed precision, and the
(pallas-interpret) stepped route — plus the adversarial geometries:
one where every tile pair is live (pair list == dense grid, no
regression possible) and one where almost none are (far-apart blobs,
``live_pair_fraction`` << 1).  The global-Morton exchange/compute
overlap (``PYPARDIS_GM_OVERLAP``) is pinned label-invariant too.

``PYPARDIS_DISPATCH`` is read at trace time, so every env flip here
clears the jit caches.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.datasets import make_blobs

from pypardis_tpu import DBSCAN
from pypardis_tpu.ops.labels import dbscan_fixed_size
from pypardis_tpu.parallel import default_mesh, sharded_dbscan, staging
from pypardis_tpu.partition import KDPartitioner

EPS = 0.6
MS = 6


@pytest.fixture(autouse=True)
def _fresh_staging():
    staging.clear()
    yield
    staging.clear()


@pytest.fixture
def dispatch_env(monkeypatch):
    """Set PYPARDIS_DISPATCH and clear compiled programs so the flip
    actually reaches freshly traced kernels."""

    def set_mode(mode):
        monkeypatch.setenv("PYPARDIS_DISPATCH", mode)
        jax.clear_caches()
        dbscan_fixed_size.clear_cache()

    yield set_mode
    jax.clear_caches()


def _blobs(n=3000, d=8, seed=0, std=0.3):
    X, _ = make_blobs(
        n_samples=n, centers=10, n_features=d, cluster_std=std,
        random_state=seed,
    )
    return X.astype(np.float32)


def _padded(X, block=256):
    n, d = X.shape
    cap = ((n + block - 1) // block) * block
    pts = np.zeros((cap, d), np.float32)
    pts[:n] = X - X.mean(axis=0)
    return jnp.asarray(pts), jnp.asarray(np.arange(cap) < n), cap


# ---------------------------------------------------------------------------
# kernel-level parity (no env involved: pairs passed explicitly)
# ---------------------------------------------------------------------------


def test_kernel_pair_list_parity_counts_and_minlab():
    """neighbor_counts / min_neighbor_label over an explicit pair list
    match the dense scan bitwise — including the owner-computes row
    restriction and the halo-halo tile-pair skip."""
    from pypardis_tpu.ops.distances import (
        min_neighbor_label, neighbor_counts, xla_pair_list,
    )

    pts, mask, cap = _padded(_blobs(), block=128)
    block = 128
    pairs, stats = xla_pair_list(pts, mask, EPS, block, "nd")
    total, budget = [int(v) for v in np.asarray(stats)]
    assert 0 < total <= budget

    cd = np.asarray(neighbor_counts(pts, EPS, mask, block=block))
    cp = np.asarray(
        neighbor_counts(pts, EPS, mask, block=block, pairs=pairs)
    )
    np.testing.assert_array_equal(cd, cp)

    # Owner-computes row restriction: only the first rt tiles count.
    cd_r = neighbor_counts(pts, EPS, mask, block=block, row_tiles=8)
    cp_r = neighbor_counts(
        pts, EPS, mask, block=block, row_tiles=8, pairs=pairs
    )
    np.testing.assert_array_equal(np.asarray(cd_r), np.asarray(cp_r))

    core = jnp.asarray(cd >= MS) & mask
    lab = jnp.where(core, jnp.arange(cap, dtype=jnp.int32), 2**31 - 1)
    md = min_neighbor_label(
        pts, lab, EPS, core, block=block, row_mask=mask
    )
    mp = min_neighbor_label(
        pts, lab, EPS, core, block=block, row_mask=mask, pairs=pairs
    )
    sel = np.asarray(mask)
    np.testing.assert_array_equal(np.asarray(md)[sel], np.asarray(mp)[sel])

    # Halo-halo skip: owned_tiles semantics match per listed entry.
    mo = min_neighbor_label(pts, lab, EPS, core, block=block,
                            owned_tiles=8)
    mop = min_neighbor_label(pts, lab, EPS, core, block=block,
                             owned_tiles=8, pairs=pairs)
    np.testing.assert_array_equal(np.asarray(mo), np.asarray(mop))


def test_kernel_pair_list_mixed_band_stats_match():
    """Mixed-precision counts under pair dispatch: labels AND the
    counts-pass band telemetry match the dense scan (the band
    classification is per-pair and order-free)."""
    from pypardis_tpu.ops.distances import neighbor_counts, xla_pair_list

    pts, mask, _cap = _padded(_blobs(), block=128)
    pairs, _ = xla_pair_list(pts, mask, EPS, 128, "nd")
    cd, bd = neighbor_counts(pts, EPS, mask, block=128, precision="mixed")
    cp, bp = neighbor_counts(
        pts, EPS, mask, block=128, precision="mixed", pairs=pairs
    )
    np.testing.assert_array_equal(np.asarray(cd), np.asarray(cp))
    np.testing.assert_array_equal(np.asarray(bd), np.asarray(bp))


def test_fixed_size_overflow_contract_pair_dispatch():
    """A too-small pair_budget flags total > budget in-band (labels
    from the truncated list are declared invalid, never silently
    wrong) — the exact contract the drivers' ladder consumes."""
    pts, mask, _cap = _padded(_blobs(), block=256)
    _l, _c, ps = dbscan_fixed_size(
        pts, EPS, MS, mask, block=256, pair_budget=1
    )
    ps = np.asarray(ps)
    assert ps[1] == 1 and ps[0] > ps[1]


# ---------------------------------------------------------------------------
# adversarial geometries
# ---------------------------------------------------------------------------


def test_all_live_geometry_no_regression():
    """Every tile pair live (one tight blob, eps covers it): the pair
    list IS the dense grid — same pairs, same labels, fraction 1.0."""
    from pypardis_tpu.ops.distances import xla_pair_list

    rng = np.random.default_rng(0)
    X = rng.normal(0, 0.05, size=(1024, 4)).astype(np.float32)
    pts, mask, cap = _padded(X, block=128)
    nt = cap // 128
    pairs, stats = xla_pair_list(pts, mask, 1.0, 128, "nd")
    total = int(np.asarray(stats)[0])
    assert total == nt * nt  # pair list == dense grid

    l_p, c_p, _ = dbscan_fixed_size(pts, 1.0, MS, mask, block=128)
    # dense oracle via explicit kernels would be identical by the
    # parity tests above; here pin the cluster-level outcome: one blob.
    lab = np.asarray(l_p)[np.asarray(mask)]
    assert (lab >= 0).all() and len(np.unique(lab)) == 1


def test_sparse_ring_of_blobs_fraction_below_one():
    """Far-apart blobs: the extraction keeps a small fraction of the
    grid, and report()['compute'] says so."""
    rng = np.random.default_rng(1)
    centers = 200.0 * np.stack(
        [np.cos(np.linspace(0, 2 * np.pi, 16, endpoint=False)),
         np.sin(np.linspace(0, 2 * np.pi, 16, endpoint=False))], axis=1
    )
    X = np.concatenate([
        c + rng.normal(0, 0.2, size=(256, 2)) for c in centers
    ]).astype(np.float32)
    m = DBSCAN(eps=EPS, min_samples=MS, block=64).fit(X)
    comp = m.report()["compute"]
    assert 0.0 < comp["live_pair_fraction"] < 1.0
    assert comp["kernel_tiles"] > 0
    # All 16 blobs found, no cross-ring merges.
    assert len(np.unique(m.labels_[m.labels_ >= 0])) == 16


# ---------------------------------------------------------------------------
# dense-vs-pair parity across the distributed modes
# ---------------------------------------------------------------------------


def _fit_all_modes(X):
    out = {}
    mesh = default_mesh(8)
    for name, kw in (
        # 1-device mesh routes train() to the fused single-shard engine
        ("fused", dict(mesh=default_mesh(1))),
        ("kd_oc_device", dict(mesh=mesh, merge="device")),
        ("kd_oc_host", dict(mesh=mesh, merge="host")),
        ("gm_mesh_device", dict(mode="global_morton", merge="device")),
        ("gm_mesh_host", dict(mode="global_morton", merge="host")),
    ):
        staging.clear()
        m = DBSCAN(eps=EPS, min_samples=MS, block=64, **kw).fit(X)
        out[name] = (m.labels_.copy(), m.core_sample_mask_.copy())
    # chained 1-dev (KD partitions through one device)
    staging.clear()
    part = KDPartitioner(X, max_partitions=8)
    l, c, _ = sharded_dbscan(
        X, part, eps=EPS, min_samples=MS, block=64, mesh=default_mesh(1)
    )
    out["chained_1dev"] = (l.copy(), c.copy())
    return out


def test_parity_dense_vs_pair_across_modes(dispatch_env):
    """Byte-identical labels dense vs compacted dispatch across the
    six distributed modes (the fused engine rides inside each)."""
    X = _blobs(n=2400, d=6, seed=3)
    dispatch_env("dense")
    dense = _fit_all_modes(X)
    dispatch_env("pair")
    pair = _fit_all_modes(X)
    assert dense.keys() == pair.keys()
    for name in dense:
        np.testing.assert_array_equal(
            dense[name][0], pair[name][0], err_msg=name
        )
        np.testing.assert_array_equal(
            dense[name][1], pair[name][1], err_msg=name
        )
    # The owner-computes mesh modes agree with each other too (their
    # shared min-core-gid canonical numbering; the fused and chained
    # routes densify under their own orderings and are compared only
    # dense-vs-pair above).
    ref = pair["kd_oc_device"][0]
    for name in ("kd_oc_host", "gm_mesh_device", "gm_mesh_host"):
        np.testing.assert_array_equal(pair[name][0], ref, err_msg=name)


def test_mixed_precision_parity_under_pair_dispatch():
    """precision='mixed' stays byte-identical to 'highest' under the
    compacted dispatch (the band rescore classification is per-pair,
    so dispatch order cannot flip a verdict)."""
    X = _blobs(n=2000, d=8, seed=5)
    hi = DBSCAN(eps=EPS, min_samples=MS, block=64,
                precision="highest").fit(X)
    mx = DBSCAN(eps=EPS, min_samples=MS, block=64,
                precision="mixed").fit(X)
    np.testing.assert_array_equal(hi.labels_, mx.labels_)
    np.testing.assert_array_equal(
        hi.core_sample_mask_, mx.core_sample_mask_
    )


def test_stepped_route_parity_with_pair_dispatch(monkeypatch):
    """The host-stepped propagation route matches the FUSED run of the
    same Pallas-interpret kernels byte-for-byte — the stepped leg of
    the parity contract.  (The oracle is fused-pallas, not XLA: the
    bf16_3x 'high' split legitimately differs from CPU XLA's exact f32
    dot at natural near-eps pairs — the documented backend gap — so
    cross-backend bitwise comparison would test the wrong thing.)"""
    import functools

    from pypardis_tpu.ops import pallas_kernels as pk
    from pypardis_tpu.ops import pipeline

    X = _blobs(n=2048, d=8, seed=7)
    monkeypatch.setattr(
        pk, "neighbor_counts_pallas",
        functools.partial(pk.neighbor_counts_pallas, interpret=True),
    )
    monkeypatch.setattr(
        pk, "min_neighbor_label_pallas",
        functools.partial(pk.min_neighbor_label_pallas, interpret=True),
    )
    # 1-device mesh: the stepped path lives in the single-shard
    # pipeline (_pad_and_run); the default 8-device CI mesh would
    # route to the sharded step instead.
    kw = dict(
        eps=EPS, min_samples=MS, block=256, kernel_backend="pallas",
        mesh=default_mesh(1),
    )
    ref = DBSCAN(**kw).fit(X)  # fused pallas (threshold not reached)
    assert "stepped" not in ref.report()
    monkeypatch.setattr(pipeline, "STEP_THRESHOLD", 1)
    staging.clear()
    m = DBSCAN(**kw).fit(X)
    assert m.report()["stepped"]["batches"] >= 1  # really stepped
    np.testing.assert_array_equal(ref.labels_, m.labels_)
    np.testing.assert_array_equal(
        ref.core_sample_mask_, m.core_sample_mask_
    )


# ---------------------------------------------------------------------------
# exchange/compute overlap (global-Morton mesh)
# ---------------------------------------------------------------------------


def test_gm_overlap_on_off_byte_parity(monkeypatch):
    """PYPARDIS_GM_OVERLAP=0/1 labels byte-identical; the overlapped
    run reports a finite exchange_overlap_efficiency in [0, 1].
    Forced pair dispatch: the auto-by-size policy would pick the dense
    grid (no pair list, no overlap) at CI tile counts."""
    X = _blobs(n=3000, d=8, seed=2)
    monkeypatch.setenv("PYPARDIS_DISPATCH", "pair")
    jax.clear_caches()
    monkeypatch.setenv("PYPARDIS_GM_OVERLAP", "0")
    base = DBSCAN(eps=EPS, min_samples=MS, block=64,
                  mode="global_morton").fit(X)
    assert base.report()["compute"]["exchange_overlap_efficiency"] == 0.0
    staging.clear()
    monkeypatch.setenv("PYPARDIS_GM_OVERLAP", "1")
    over = DBSCAN(eps=EPS, min_samples=MS, block=64,
                  mode="global_morton").fit(X)
    np.testing.assert_array_equal(base.labels_, over.labels_)
    np.testing.assert_array_equal(
        base.core_sample_mask_, over.core_sample_mask_
    )
    eff = over.report()["compute"]["exchange_overlap_efficiency"]
    assert 0.0 <= eff <= 1.0
    # The overlapped run really split the counts pass (the delta pass
    # is one extra accounted kernel pass).
    assert (
        over.report()["compute"]["kernel_passes"]
        == base.report()["compute"]["kernel_passes"] + 1
    )
    # Phase decomposition still accounts the wall: the hidden ring
    # seconds moved INTO compute, they didn't vanish.
    ph = over.report()["phases"]
    for key in ("gm_build", "gm_exchange", "gm_execute", "gm_merge"):
        assert ph[key] >= 0.0
    jax.clear_caches()


def test_gm_overlap_mixed_precision_byte_parity(monkeypatch):
    """The overlapped owned+delta counts split preserves the mixed-
    precision exactness contract (sums of disjoint column sets,
    thresholded once)."""
    X = _blobs(n=2400, d=6, seed=9)
    monkeypatch.setenv("PYPARDIS_DISPATCH", "pair")
    jax.clear_caches()
    hi = DBSCAN(eps=EPS, min_samples=MS, block=64, mode="global_morton",
                precision="highest").fit(X)
    staging.clear()
    mx = DBSCAN(eps=EPS, min_samples=MS, block=64, mode="global_morton",
                precision="mixed").fit(X)
    np.testing.assert_array_equal(hi.labels_, mx.labels_)
    assert mx.report()["compute"]["band_pairs"] > 0
    jax.clear_caches()


# ---------------------------------------------------------------------------
# hint cache keys on the dispatch mode
# ---------------------------------------------------------------------------


def test_hint_keys_carry_dispatch_mode(monkeypatch):
    """A budget hint learned under dense dispatch must not be served
    to the compacted kernels (and vice versa): every hint key carries
    the dispatch tag."""
    from pypardis_tpu.parallel.sharded import _sharded_hint_key
    from pypardis_tpu.utils.hints import dispatch_tag

    monkeypatch.setenv("PYPARDIS_DISPATCH", "pair")
    assert dispatch_tag() == "pair"
    k_pair = _sharded_hint_key((8, 256, 4), 64, 64, "high", 0.5, "euclidean")
    monkeypatch.setenv("PYPARDIS_DISPATCH", "dense")
    assert dispatch_tag() == "dense"
    k_dense = _sharded_hint_key((8, 256, 4), 64, 64, "high", 0.5, "euclidean")
    assert k_pair != k_dense
    assert "pair" in k_pair and "dense" in k_dense


def test_report_dispatch_fields_always_present():
    """Every fit carries the sparsity gauges, finite fractions in
    [0, 1] (schema-enforced on bench rows by check_bench_json)."""
    X = _blobs(n=1000, d=4, seed=11)
    m = DBSCAN(eps=EPS, min_samples=MS, block=64).fit(X)
    comp = m.report()["compute"]
    assert 0.0 <= comp["live_pair_fraction"] <= 1.0
    assert 0.0 <= comp["exchange_overlap_efficiency"] <= 1.0
    assert comp["kernel_tiles"] >= 1
