"""Native (C++) merge resolver vs the pure-Python implementation."""

import numpy as np
import pytest

from pypardis_tpu._native import (
    native_available,
    relabel_i32,
    uf_resolve_dense,
)
from pypardis_tpu.aggregator import UnionFind
from pypardis_tpu.parallel.merge import resolve_label_edges


def test_native_builds_on_this_image():
    # g++ is baked into the image; the library must compile and load.
    assert native_available()


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_uf_matches_python_unionfind(seed):
    rng = np.random.default_rng(seed)
    n = 500
    edges = rng.integers(0, n, size=(2000, 2))
    roots = uf_resolve_dense(edges, n)

    uf = UnionFind(n)
    for a, b in edges:
        uf.union(int(a), int(b))
    assert np.array_equal(roots, uf.roots())
    # Min-id invariant: every root is the min of its component.
    for r in np.unique(roots):
        assert r == np.min(np.nonzero(roots == r)[0])


def test_uf_ignores_out_of_range_edges():
    edges = np.array([[0, 1], [-1, 2], [2, 999], [1, 2]])
    roots = uf_resolve_dense(edges, 4)
    assert roots.tolist() == [0, 0, 0, 3]


def test_uf_transitive_chain():
    # A long chain exercises path compression: 0-1, 1-2, ..., n-2 - n-1.
    n = 10_000
    chain = np.stack([np.arange(n - 1), np.arange(1, n)], axis=1)
    roots = uf_resolve_dense(chain[::-1], n)  # reversed order: worst case
    assert (roots == 0).all()


def test_relabel_i32():
    labels = np.array([0, 2, -1, 5, 3], np.int32)
    lut = np.array([10, 11, 12, 13], np.int32)
    out = relabel_i32(labels, lut, fill=-1)
    assert out.tolist() == [10, 12, -1, -1, 13]


def test_resolve_label_edges_sparse_ids():
    # Non-dense, unsorted id universe — mapping must go through the
    # sorted-search and come back as original ids.
    ids = np.array([700, 13, 42, 99])
    edges = np.array([[42, 700], [99, 13]])
    mapping = resolve_label_edges(edges, ids)
    assert mapping == {42: 42, 700: 42, 13: 13, 99: 13}


def test_resolve_label_edges_missing_id_raises():
    import pytest

    with pytest.raises(KeyError):
        resolve_label_edges(np.array([[5, 9]]), np.array([3, 7, 9, 12]))


def test_resolve_label_edges_duplicate_ids():
    mapping = resolve_label_edges(
        np.array([[7, 9]]), np.array([9, 7, 7, 9])
    )
    assert mapping == {7: 7, 9: 7}
