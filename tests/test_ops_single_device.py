"""Single-device DBSCAN kernel vs the sklearn oracle.

Oracle policy per SURVEY §4: compare with ARI (border points reachable
from multiple clusters are legitimately assignment-ambiguous,
reference README.md:28-33); assert exact agreement on core points and
noise status.
"""

import numpy as np
import pytest

import jax.numpy as jnp
from sklearn.cluster import DBSCAN as SKDBSCAN
from sklearn.metrics import adjusted_rand_score

from pypardis_tpu.ops import dbscan_fixed_size, densify_labels, neighbor_counts


def _pad(X, block=256):
    n = len(X)
    cap = -(-n // block) * block
    pts = np.zeros((cap, X.shape[1]), np.float32)
    pts[:n] = X
    mask = np.zeros(cap, bool)
    mask[:n] = True
    return pts, mask, n


def _run(X, eps, min_samples, metric="euclidean", block=256):
    pts, mask, n = _pad(X, block)
    labels, core, _ = dbscan_fixed_size(
        jnp.asarray(pts), eps, min_samples, jnp.asarray(mask),
        metric=metric, block=block,
    )
    return densify_labels(np.asarray(labels)[:n]), np.asarray(core)[:n]


def test_neighbor_counts_bruteforce():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(100, 3))
    pts, mask, n = _pad(X, 64)
    counts = np.asarray(
        neighbor_counts(jnp.asarray(pts), 0.8, jnp.asarray(mask), block=64)
    )[:n]
    d2 = ((X[:, None, :] - X[None, :, :]) ** 2).sum(-1)
    expected = (d2 <= 0.8**2).sum(1)
    np.testing.assert_array_equal(counts, expected)


@pytest.mark.parametrize("metric", ["euclidean", "cityblock"])
def test_blobs_vs_sklearn(blobs750, metric):
    eps, min_samples = 0.3, 10
    ours, core = _run(blobs750, eps, min_samples, metric=metric)
    sk = SKDBSCAN(eps=eps, min_samples=min_samples, metric=metric).fit(
        blobs750
    )
    sk_core = np.zeros(len(blobs750), bool)
    sk_core[sk.core_sample_indices_] = True

    np.testing.assert_array_equal(core, sk_core)
    # noise agreement is exact
    np.testing.assert_array_equal(ours == -1, sk.labels_ == -1)
    assert adjusted_rand_score(sk.labels_, ours) >= 0.99
    # core points agree exactly up to relabeling: same partition on cores
    assert adjusted_rand_score(sk.labels_[sk_core], ours[sk_core]) == 1.0


def test_uniform_noise_no_clusters():
    rng = np.random.default_rng(1)
    X = rng.uniform(-10, 10, size=(200, 4))
    ours, core = _run(X, 0.1, 5)
    assert (ours == -1).all()
    assert not core.any()


def test_single_dense_cluster():
    rng = np.random.default_rng(2)
    X = rng.normal(scale=0.05, size=(300, 2))
    ours, core = _run(X, 0.3, 5)
    assert (ours == 0).all()


def test_padding_invariance(blobs750):
    a, _ = _run(blobs750, 0.3, 10, block=128)
    b, _ = _run(blobs750, 0.3, 10, block=512)
    np.testing.assert_array_equal(a, b)


def test_live_tile_pairs_chunk_boundary():
    """Level-1 group scan must not drop rows when the group count just
    exceeds a scan chunk (regression: dynamic_slice clamps an
    out-of-range start, which misaligned the last chunk's live mask and
    silently dropped real pairs while underreporting the total)."""
    from pypardis_tpu.ops.distances import PAIR_GROUP, live_tile_pairs

    # nt such that ng = nt / PAIR_GROUP lands just past the ~4M-entry
    # chunking threshold's chunk size for this ng (chunk == 2048 when
    # ng is a bit over 2048).
    nt = (2048 + 2) * PAIR_GROUP
    lo = jnp.arange(nt, dtype=jnp.float32)[:, None] * 10.0
    hi = lo  # isolated degenerate boxes: only self-pairs are live
    rows, cols, total = live_tile_pairs(lo, hi, 1.0)
    assert int(total) == nt
    got = {(int(r), int(c)) for r, c in zip(np.asarray(rows), np.asarray(cols))
           if int(r) < nt}
    assert got == {(i, i) for i in range(nt)}


def test_morton_words_chunked_matches_direct(monkeypatch):
    """The chunked Morton-word path (HBM-bounded interleave for big
    caps) must produce bit-identical words to the direct computation,
    including the clamped-overlap last chunk."""
    import jax.numpy as jnp

    import pypardis_tpu.ops.pipeline as pl

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(5, 1000)).astype(np.float32))
    mask = jnp.asarray(rng.random(1000) < 0.9)
    direct = [np.asarray(w) for w in pl._device_morton_words(x, mask)]
    monkeypatch.setattr(pl, "_MORTON_CHUNK", 192)  # 1000 % 192 != 0
    chunked = [np.asarray(w) for w in pl._device_morton_words(x, mask)]
    assert len(direct) == len(chunked)
    for d, c in zip(direct, chunked):
        np.testing.assert_array_equal(d, c)


def test_bounds_dn_chunked_matches_direct(monkeypatch):
    """Chunked tile-bounds (HBM-bounded masked reduce off the (d, N)
    layout) must equal the direct computation, including the
    clamped-overlap last chunk, and must match a numpy oracle."""
    import jax.numpy as jnp

    import pypardis_tpu.ops.pallas_kernels as pk

    rng = np.random.default_rng(4)
    nt, d, block = 13, 3, 32
    pts = rng.normal(size=(d, nt * block)).astype(np.float32)
    mask = rng.random(nt * block) < 0.8
    lo0, hi0 = pk._bounds_dn(jnp.asarray(pts), jnp.asarray(mask), nt, block)
    monkeypatch.setattr(pk, "_BOUNDS_CHUNK_ELEMS", 5 * d * block)  # chunk=5
    lo1, hi1 = pk._bounds_dn(jnp.asarray(pts), jnp.asarray(mask), nt, block)
    np.testing.assert_array_equal(np.asarray(lo0), np.asarray(lo1))
    np.testing.assert_array_equal(np.asarray(hi0), np.asarray(hi1))
    # numpy oracle on a non-empty tile
    seg = pts[:, :block][:, mask[:block]]
    np.testing.assert_allclose(np.asarray(lo0)[0], seg.min(axis=1))
    np.testing.assert_allclose(np.asarray(hi0)[0], seg.max(axis=1))
