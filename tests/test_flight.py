"""Flight recorder, resource watermarks, and the bench regression gate
(ISSUE 6).

Unit/integration: the JSONL flight file is written incrementally and
parseable after both a clean fit and an injected mid-fit exception
(spans an exception unwinds through stay OPEN in the file — the
post-mortem death-site marker); ``obs.replay`` reconstructs a Chrome
trace and a partial report from the file alone; the resource-sampler
thread always joins (no leaks across fits, error paths included) and
``report()["resources"]`` carries finite watermarks on every route;
``export_trace`` works on a failed/partial fit.  Gate: bench_diff
reproduces the committed r4->r5 'noise' verdict and exits nonzero on a
synthetic 20% slowdown; check_bench_json enforces the resources block
and the bench_diff verdict field.
"""

import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest
from sklearn.datasets import make_blobs

from pypardis_tpu import DBSCAN, obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def blobs():
    # Distinct seed from every other module's fixture: the staging
    # device cache is CONTENT-keyed, so sharing another module's exact
    # dataset would warm its cold-fit assertions from here.
    X, _ = make_blobs(
        n_samples=2000, centers=8, n_features=4, cluster_std=0.3,
        random_state=11,
    )
    return X


def _parse_lines(path):
    recs = []
    for line in open(path, encoding="utf-8").read().splitlines():
        if line.strip():
            recs.append(json.loads(line))  # every line must parse
    return recs


def _no_sampler_threads():
    return not [
        t for t in threading.enumerate()
        if t.name.startswith("pypardis-resource-sampler") and t.is_alive()
    ]


# ---------------------------------------------------------------------------
# flight file: clean fit
# ---------------------------------------------------------------------------


def test_flight_file_written_and_replayable(tmp_path, blobs):
    path = str(tmp_path / "flight.jsonl")
    m = DBSCAN(eps=0.4, min_samples=5, block=64, flight=path).fit(blobs)
    recs = _parse_lines(path)
    kinds = {r["k"] for r in recs}
    # header, span open/close, gauges, timings, resource samples,
    # staging notes, terminal record — all flushed to disk.
    assert {"header", "so", "sc", "g", "tm", "rs", "fin"} <= kinds
    hdr = next(r for r in recs if r["k"] == "header")
    assert hdr["schema"] == "pypardis_tpu/flight@1"
    assert hdr["n_points"] == 2000 and hdr["n_dims"] == 4
    assert isinstance(hdr["params"], dict) and hdr["params"]["eps"] == 0.4
    fin = [r for r in recs if r["k"] == "fin"]
    assert len(fin) == 1 and fin[0]["status"] == "ok"

    rep = obs.replay(path)
    assert rep.complete and rep.status == "ok"
    assert rep.open_spans == [] and rep.bad_lines == 0

    # The replayed Chrome trace carries the same closed spans the live
    # model exports.
    live = {
        e["name"]
        for e in json.load(
            open(m.export_trace(str(tmp_path / "live.json")))
        )["traceEvents"]
        if e.get("ph") == "X"
    }
    replayed = {
        e["name"] for e in rep.to_chrome_trace()["traceEvents"]
        if e.get("ph") == "X"
    }
    assert "cluster" in replayed
    assert replayed == live

    # Partial-report surface from the file alone.
    r = rep.report()
    assert r["schema"] == "pypardis_tpu/run_report@1"
    assert r["partial"] is False
    assert r["phases"]["cluster"] > 0
    assert r["run"]["n_points"] == 2000
    assert r["resources"]["peak_host_rss_bytes"] > 0
    assert r["flight"]["status"] == "ok"
    json.dumps(r)
    assert "resources:" in rep.summary()


def test_flight_env_opt_in_directory_mode(tmp_path, blobs, monkeypatch):
    monkeypatch.setenv("PYPARDIS_FLIGHT", str(tmp_path))
    DBSCAN(eps=0.4, min_samples=5, block=64).fit(blobs)
    files = list(tmp_path.glob("flight-*.jsonl"))
    assert len(files) == 1
    assert obs.replay(str(files[0])).complete


def test_no_flight_by_default(tmp_path, blobs):
    m = DBSCAN(eps=0.4, min_samples=5, block=64).fit(blobs)
    assert m._recorder.flight is None
    assert list(tmp_path.iterdir()) == []


# ---------------------------------------------------------------------------
# flight file: injected mid-fit failure
# ---------------------------------------------------------------------------


def test_injected_midfit_exception_leaves_open_span(
    tmp_path, blobs, monkeypatch
):
    """The satellite contract: a fit killed by an exception leaves a
    parseable flight file whose opened-but-unclosed span marks the
    death site, and obs.replay reconstructs a partial report from it."""

    def boom(*a, **kw):
        raise RuntimeError("injected cluster-step failure")

    monkeypatch.setattr(
        "pypardis_tpu.parallel.sharded.sharded_dbscan", boom
    )
    path = str(tmp_path / "flight.jsonl")
    m = DBSCAN(eps=0.4, min_samples=5, block=64, flight=path)
    with pytest.raises(RuntimeError, match="injected"):
        m.fit(blobs)
    assert _no_sampler_threads()  # error path still joins the sampler

    recs = _parse_lines(path)  # parseable end to end
    fin = [r for r in recs if r["k"] == "fin"]
    assert len(fin) == 1 and fin[0]["status"] == "error"
    assert "injected" in fin[0]["error"]
    # The cluster phase span opened but its close never hit the file.
    open_ids = {r["id"] for r in recs if r["k"] == "so"}
    closed_ids = {r["id"] for r in recs if r["k"] == "sc"}
    open_names = {
        r["name"] for r in recs
        if r["k"] == "so" and r["id"] in (open_ids - closed_ids)
    }
    assert "cluster" in open_names

    rep = obs.replay(path)
    assert rep.status == "error"
    assert "cluster" in [s["name"] for s in rep.open_spans]
    r = rep.report()
    assert "cluster" in r["flight"]["open_spans"]
    assert "partition" in r["phases"]  # the phase that DID complete
    trace = rep.to_chrome_trace()
    unclosed = [
        e["name"] for e in trace["traceEvents"]
        if e.get("ph") == "X" and e.get("args", {}).get("unclosed")
    ]
    assert "cluster" in unclosed
    assert "PARTIAL" not in rep.summary()  # fin record = not killed
    # The live model still exports its (in-memory, closed) spans even
    # though the fit failed — export_trace no longer needs _require_fitted.
    assert m.labels_ is None
    out = m.export_trace(str(tmp_path / "failed_fit.json"))
    names = {
        e["name"] for e in json.load(open(out))["traceEvents"]
        if e.get("ph") == "X"
    }
    assert "cluster" in names
    # report()/summary() keep the unified not-fitted contract.
    with pytest.raises(RuntimeError, match="not fitted"):
        m.report()


def test_export_trace_surface_still_guards_unfitted():
    m = DBSCAN()
    with pytest.raises(
        RuntimeError, match=r"not fitted; call fit\(\)/train\(\) first"
    ):
        m.export_trace("/tmp/never.json")


# ---------------------------------------------------------------------------
# resource watermarks
# ---------------------------------------------------------------------------


def test_sampler_never_leaks_threads(blobs):
    for _ in range(2):
        DBSCAN(eps=0.4, min_samples=5, block=64).fit(blobs)
        assert _no_sampler_threads()


def test_resources_finite_on_all_routes(blobs):
    import math

    from pypardis_tpu.parallel import default_mesh

    routes = {
        "fused": DBSCAN(eps=0.4, min_samples=5, block=64,
                        mesh=default_mesh(1)),
        "kd_halo": DBSCAN(eps=0.4, min_samples=5, block=64),
        "global_morton": DBSCAN(eps=0.4, min_samples=5, block=64,
                                mode="global_morton",
                                mesh=default_mesh(8)),
    }
    for name, model in routes.items():
        res = model.fit(blobs).report()["resources"]
        for key in ("peak_host_rss_bytes", "peak_device_bytes",
                    "staging_pool_bytes", "samples"):
            assert math.isfinite(float(res[key])), (name, key)
        assert res["peak_host_rss_bytes"] > 0, name
        assert res["samples"] >= 1, name


def test_gm_ring_counters_surfaced_in_summary(blobs):
    """ISSUE 6 satellite: ring traffic visible without a trace export."""
    from pypardis_tpu.parallel import default_mesh, staging

    # A warm gm_boundary cache (an earlier test fitting the same
    # data/eps) would skip the exchange entirely — force the ring.
    staging.clear()
    m = DBSCAN(
        eps=0.4, min_samples=5, block=64, mode="global_morton",
        mesh=default_mesh(8),
    ).fit(blobs)
    ctr = m.report()["metrics"]["counters"]
    assert ctr.get("gm.ring_bytes_sent", 0) > 0
    assert ctr.get("gm.ring_tiles_kept", 0) > 0
    assert "ring " in m.summary()


# ---------------------------------------------------------------------------
# bench_diff regression gate
# ---------------------------------------------------------------------------


def _run(args, **kw):
    return subprocess.run(
        [sys.executable] + args, cwd=REPO, capture_output=True,
        text=True, **kw,
    )


def test_bench_diff_reproduces_r4_r5_noise_verdict():
    """The PR 2 manual diagnosis, automated: overlapping raw sample
    ranges -> 'noise', exit 0 — straight from the committed archives."""
    p = _run([
        "scripts/bench_diff.py", "--prior", "BENCH_r04.json",
        "--current", "BENCH_r05.json", "--expect", "noise",
    ])
    assert p.returncode == 0, p.stderr
    out = json.loads(p.stdout.strip().splitlines()[-1])
    assert out["verdict"] == "noise"
    dev = out["metrics"]["device"]
    assert dev["ranges_overlap"] is True
    assert dev["delta_best"] == pytest.approx(0.047, abs=0.01)


def test_bench_diff_fails_on_synthetic_slowdown(tmp_path):
    doc = json.load(open(os.path.join(REPO, "BENCH_r05.json")))
    row = dict(doc["parsed"])
    import re

    samples = [
        float(x) for x in re.search(
            r"samples=\[([^\]]+)\]", doc["tail"]
        ).group(1).split(",")
    ]
    row["samples_s"] = [round(s * 1.2, 4) for s in samples]
    slow = tmp_path / "slow_row.json"
    slow.write_text(json.dumps(row))
    p = _run([
        "scripts/bench_diff.py", "--prior", "BENCH_r04.json",
        "--current", str(slow),
    ])
    assert p.returncode == 1, (p.stdout, p.stderr)
    out = json.loads(p.stdout.strip().splitlines()[-1])
    assert out["verdict"] == "regression"
    assert out["metrics"]["device"]["ranges_overlap"] is False


def test_bench_diff_annotate_mode(tmp_path):
    """The bench-smoke pipe: a row with no matching archived metric is
    annotated 'no_baseline' (exit 0) and passes --require-diff."""
    row = {"metric": "points_per_sec_tiny_ci_geometry", "value": 1.0,
           "unit": "points/sec/chip", "samples_s": [0.1, 0.11]}
    p = _run(
        ["scripts/bench_diff.py", "--annotate", "--baseline-dir", "."],
        input=json.dumps(row),
    )
    assert p.returncode == 0, p.stderr
    out = json.loads(p.stdout.strip().splitlines()[-1])
    assert out["bench_diff"]["verdict"] == "no_baseline"


# ---------------------------------------------------------------------------
# check_bench_json schema extensions
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def bench_row(blobs):
    model = DBSCAN(eps=0.4, min_samples=5, block=64).fit(blobs)
    return {
        "metric": "test_row", "value": 1.0, "unit": "points/sec/chip",
        "telemetry": model.report(),
    }


def test_check_bench_json_accepts_report_with_resources(bench_row):
    p = _run(["scripts/check_bench_json.py"], input=json.dumps(bench_row))
    assert p.returncode == 0, p.stderr


def test_check_bench_json_requires_resources(bench_row):
    row = json.loads(json.dumps(bench_row))
    del row["telemetry"]["resources"]
    p = _run(["scripts/check_bench_json.py"], input=json.dumps(row))
    assert p.returncode == 1
    assert "resources" in p.stderr


def test_check_bench_json_require_diff_flag(bench_row):
    # Without the verdict field: --require-diff fails, plain mode passes.
    p = _run(
        ["scripts/check_bench_json.py", "--require-diff"],
        input=json.dumps(bench_row),
    )
    assert p.returncode == 1 and "bench_diff" in p.stderr
    row = json.loads(json.dumps(bench_row))
    row["bench_diff"] = {"verdict": "noise"}
    p = _run(
        ["scripts/check_bench_json.py", "--require-diff"],
        input=json.dumps(row),
    )
    assert p.returncode == 0, p.stderr
    row["bench_diff"] = {"verdict": "regression"}
    p = _run(
        ["scripts/check_bench_json.py", "--require-diff"],
        input=json.dumps(row),
    )
    assert p.returncode == 1 and "regression" in p.stderr
