"""Scale-ish sharded CI test (round-4 review, Next #7).

CI previously never ran the sharded path past 4,000 points; the 2M-10M
proof lived only in hand-run probe artifacts.  This test pushes ~100k
points through the 8-device CPU mesh in BOTH halo modes on every
commit, so the scale machinery — multi-tile layouts, real halo slabs,
the in-graph merge at thousands of clusters — cannot regress silently
between bench runs.  Marked slow (deselect with ``-m "not slow"``).
"""

import numpy as np
import pytest
from sklearn.metrics import adjusted_rand_score

from benchdata import ari_vs_truth, make_blob_data
from pypardis_tpu import DBSCAN
from pypardis_tpu.ops import densify_labels
from pypardis_tpu.parallel import default_mesh, sharded_dbscan
from pypardis_tpu.partition import KDPartitioner

pytestmark = pytest.mark.slow

N = 100_000


@pytest.fixture(scope="module")
def data100k():
    X, truth = make_blob_data(N, 4, n_centers=64, std=0.1)
    return X, truth


@pytest.fixture(scope="module")
def single_shard_ref(data100k):
    X, _ = data100k
    m = DBSCAN(eps=0.3, min_samples=10, block=1024, max_partitions=1)
    labels = m.fit_predict(X)
    return labels, m.core_sample_mask_


@pytest.mark.parametrize("mode", ["device", "ring"])
def test_sharded_100k_matches_single_shard(data100k, single_shard_ref,
                                           mode):
    X, truth = data100k
    ref, ref_core = single_shard_ref
    part = KDPartitioner(X, max_partitions=8)
    kwargs = {"device": dict(merge="device"), "ring": dict(halo="ring")}
    labels, core, stats = sharded_dbscan(
        X, part, eps=0.3, min_samples=10, block=1024,
        mesh=default_mesh(8), **kwargs[mode]
    )
    dense = densify_labels(labels)
    np.testing.assert_array_equal(core, ref_core)
    # Core labels are partition-count invariant; border points reachable
    # from several clusters are legitimately ambiguous (reference
    # README.md:28-33) — compare them by ARI.
    np.testing.assert_array_equal(dense[ref_core], ref[ref_core])
    np.testing.assert_array_equal(dense == -1, ref == -1)
    assert adjusted_rand_score(dense, ref) >= 0.999
    assert ari_vs_truth(dense, truth) >= 0.99
    assert stats.get("merge_converged", True) in (True, None)


def test_sharded_100k_skewed_density(data100k):
    """The log-normal density-skew generator through the mesh: pad
    waste grows with imbalance but labels still match the oracle."""
    X, truth = make_blob_data(N, 4, n_centers=64, std=0.1,
                              skew="lognormal")
    part = KDPartitioner(X, max_partitions=8)
    labels, core, stats = sharded_dbscan(
        X, part, eps=0.3, min_samples=10, block=1024,
        mesh=default_mesh(8), merge="device",
    )
    dense = densify_labels(labels)
    assert ari_vs_truth(dense, truth) >= 0.99
    assert stats.get("merge_converged", True) in (True, None)


def test_owner_computes_100k_all_modes_byte_parity(data100k,
                                                   single_shard_ref):
    """ISSUE 2 acceptance at CI scale: owner-computes labels are
    byte-identical to the legacy step AND to the fused single-shard
    engine across every host-input distributed mode at 100k points,
    with the clustered-volume factor back near 1."""
    X, truth = data100k
    ref, ref_core = single_shard_ref
    part = KDPartitioner(X, max_partitions=8)
    mesh = default_mesh(8)
    kw = dict(eps=0.3, min_samples=10, block=1024, mesh=mesh)
    for mode in (
        dict(), dict(merge="host"), dict(halo="ring"),
        dict(halo="ring", merge="host"),
    ):
        l_oc, c_oc, s_oc = sharded_dbscan(
            X, part, owner_computes=True, **mode, **kw
        )
        l_le, c_le, s_le = sharded_dbscan(
            X, part, owner_computes=False, **mode, **kw
        )
        assert np.array_equal(l_oc, l_le), mode
        assert np.array_equal(c_oc, c_le), mode
        assert s_oc["duplicated_work_factor"] < s_le[
            "duplicated_work_factor"
        ], mode
        dense = densify_labels(l_oc)
        np.testing.assert_array_equal(c_oc, ref_core)
        np.testing.assert_array_equal(dense[ref_core], ref[ref_core])
        np.testing.assert_array_equal(dense == -1, ref == -1)
        assert ari_vs_truth(dense, truth) >= 0.99
