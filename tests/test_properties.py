"""Property / invariant tests (SURVEY §4) + wider oracle coverage.

Invariants pinned here:

* every point gets exactly one finite global label;
* core-point labels are invariant to ``max_partitions`` (1, 4, 16) —
  partitioning must not change what the clustering *is* (border points
  are legitimately assignment-ambiguous, reference README.md:28-33);
* ARI >= 0.99 vs single-node sklearn across dataset shapes (moons,
  anisotropic blobs, high-dim, varied scale) and both metrics;
* callable scipy metrics behave identically to their string spellings.
"""

import numpy as np
import pytest
from sklearn.cluster import DBSCAN as SKDBSCAN
from sklearn.datasets import make_blobs, make_moons
from sklearn.metrics import adjusted_rand_score
from sklearn.preprocessing import StandardScaler

from pypardis_tpu import DBSCAN


def _datasets():
    out = {}
    X, _ = make_moons(n_samples=600, noise=0.05, random_state=0)
    out["moons"] = (StandardScaler().fit_transform(X), 0.2, 5)
    X, _ = make_blobs(
        n_samples=800, centers=4, n_features=2, cluster_std=0.5,
        random_state=1,
    )
    out["aniso"] = (
        X @ np.array([[0.6, -0.6], [-0.4, 0.8]]), 0.3, 10,
    )
    X, _ = make_blobs(
        n_samples=600, centers=5, n_features=24, cluster_std=0.5,
        random_state=2,
    )
    out["high_dim"] = (X, 3.0, 8)
    # Large-magnitude coordinates: exercises the centering that protects
    # the |x|^2+|y|^2-2xy expansion (GPS-like projected meters).
    X, _ = make_blobs(
        n_samples=500, centers=3, n_features=2, cluster_std=30.0,
        center_box=(9.0e5, 1.1e6), random_state=3,
    )
    out["gps_scale"] = (X, 100.0, 5)
    return out


DATASETS = _datasets()


@pytest.mark.parametrize("name", sorted(DATASETS))
def test_oracle_ari_vs_sklearn(name):
    X, eps, ms = DATASETS[name]
    ours = DBSCAN(eps=eps, min_samples=ms, block=128).fit_predict(X)
    sk = SKDBSCAN(eps=eps, min_samples=ms).fit(X)
    assert adjusted_rand_score(sk.labels_, ours) >= 0.99, name


@pytest.mark.parametrize("name", ["moons", "aniso"])
def test_exactly_one_label_per_point(name):
    X, eps, ms = DATASETS[name]
    model = DBSCAN(eps=eps, min_samples=ms, block=128).fit(X)
    assert model.labels_.shape == (len(X),)
    # No sentinel leaks: every label is -1 or a valid point index.
    assert model.labels_.min() >= -1
    assert model.labels_.max() < len(X)
    # assignments() carries the same single label per key, in key order
    keys = [k for k, _ in model.assignments()]
    assert len(keys) == len(set(keys)) == len(X)


@pytest.mark.parametrize("max_partitions", [1, 4, 16])
def test_core_labels_invariant_to_partition_count(blobs750, max_partitions):
    base = DBSCAN(eps=0.3, min_samples=10, block=128).fit(blobs750)
    part = DBSCAN(
        eps=0.3, min_samples=10, block=128, max_partitions=max_partitions
    ).fit(blobs750)
    # Core mask identical regardless of partitioning.
    assert np.array_equal(
        base.core_sample_mask_, part.core_sample_mask_
    ), max_partitions
    # Core points agree on cluster structure exactly (ARI on core subset).
    core = base.core_sample_mask_
    assert (
        adjusted_rand_score(base.labels_[core], part.labels_[core]) == 1.0
    )
    # Noise agreement: a point that is noise in one is noise in both.
    assert np.array_equal(base.labels_ == -1, part.labels_ == -1)


def test_cityblock_end_to_end(blobs750):
    ours = DBSCAN(
        eps=0.35, min_samples=10, metric="cityblock", block=128
    ).fit_predict(blobs750)
    sk = SKDBSCAN(eps=0.35, min_samples=10, metric="manhattan").fit(blobs750)
    assert adjusted_rand_score(sk.labels_, ours) >= 0.99


def test_callable_metric_matches_string(blobs750):
    from scipy.spatial.distance import cityblock, euclidean

    for cb, name, eps in (
        (euclidean, "euclidean", 0.3),
        (cityblock, "cityblock", 0.35),
    ):
        a = DBSCAN(eps=eps, min_samples=10, metric=cb, block=128).fit_predict(
            blobs750
        )
        b = DBSCAN(
            eps=eps, min_samples=10, metric=name, block=128
        ).fit_predict(blobs750)
        assert np.array_equal(a, b), name


def test_duplicate_points():
    # 60 copies of 3 distinct locations: all core, 3 clusters, no noise.
    X = np.repeat(np.array([[0.0, 0.0], [5.0, 5.0], [-5.0, 3.0]]), 60, axis=0)
    labels = DBSCAN(eps=0.1, min_samples=10, block=128).fit_predict(X)
    assert len(np.unique(labels)) == 3
    assert (labels != -1).all()


def test_min_samples_one_everything_clusters():
    rng = np.random.default_rng(5)
    X = rng.uniform(size=(200, 3))
    labels = DBSCAN(eps=1e-6, min_samples=1, block=128).fit_predict(X)
    # Every isolated point is its own core point -> its own cluster.
    assert (labels >= 0).all()
    assert len(np.unique(labels)) == 200
