"""Device-resident ring halo exchange vs the host-built halo layout.

Runs on the 8-device CPU mesh from conftest.py — the ppermute ring and
the host box query must produce identical final clusterings, because
they implement the same 2*eps duplication rule (reference README.md:20).
"""

import numpy as np
import pytest
from sklearn.datasets import make_blobs

from pypardis_tpu.ops.labels import densify_labels
from pypardis_tpu.parallel import default_mesh
from pypardis_tpu.parallel.sharded import sharded_dbscan
from pypardis_tpu.partition import KDPartitioner


@pytest.fixture(scope="module")
def sharded_setup():
    X, _ = make_blobs(
        n_samples=2000, centers=6, n_features=3, cluster_std=0.3,
        random_state=3,
    )
    mesh = default_mesh(8)
    part = KDPartitioner(X, max_partitions=8)
    return X, mesh, part


def test_ring_matches_host_halo(sharded_setup):
    X, mesh, part = sharded_setup
    kw = dict(eps=0.4, min_samples=5, block=128, mesh=mesh)
    l_host, c_host, s_host = sharded_dbscan(X, part, halo="host", **kw)
    l_ring, c_ring, s_ring = sharded_dbscan(X, part, halo="ring", **kw)
    assert np.array_equal(c_host, c_ring)
    assert np.array_equal(
        densify_labels(l_host), densify_labels(l_ring)
    )
    assert s_ring["halo_exchange"] == "ring"


def test_ring_matches_single_node(sharded_setup):
    from sklearn.cluster import DBSCAN as SKDBSCAN
    from sklearn.metrics import adjusted_rand_score

    X, mesh, part = sharded_setup
    l_ring, _, _ = sharded_dbscan(
        X, part, eps=0.4, min_samples=5, block=128, mesh=mesh, halo="ring"
    )
    sk = SKDBSCAN(eps=0.4, min_samples=5).fit(X)
    assert adjusted_rand_score(sk.labels_, densify_labels(l_ring)) >= 0.99


def test_ring_multi_partition_per_device(sharded_setup):
    """halo='ring' with max_partitions > n_devices (two KD partitions
    per device) must match the host-halo labels exactly — the round-2
    one-partition-per-device restriction is lifted."""
    X, mesh, _ = sharded_setup
    part16 = KDPartitioner(X, max_partitions=16)
    kw = dict(eps=0.4, min_samples=5, block=128, mesh=mesh)
    l_host, c_host, _ = sharded_dbscan(X, part16, halo="host", **kw)
    l_ring, c_ring, s_ring = sharded_dbscan(X, part16, halo="ring", **kw)
    assert s_ring["halo_exchange"] == "ring"
    assert np.array_equal(c_host, c_ring)
    assert np.array_equal(densify_labels(l_host), densify_labels(l_ring))


def test_ring_fewer_partitions_than_devices(sharded_setup):
    """max_partitions below the mesh size pads empty ring slots whose
    inverted boxes collect no halo."""
    X, mesh, _ = sharded_setup
    part4 = KDPartitioner(X, max_partitions=4)
    kw = dict(eps=0.4, min_samples=5, block=128, mesh=mesh)
    l_host, c_host, _ = sharded_dbscan(X, part4, halo="host", **kw)
    l_ring, c_ring, _ = sharded_dbscan(X, part4, halo="ring", **kw)
    assert np.array_equal(c_host, c_ring)
    assert np.array_equal(densify_labels(l_host), densify_labels(l_ring))
