"""Device-resident ring halo exchange vs the host-built halo layout.

Runs on the 8-device CPU mesh from conftest.py — the ppermute ring and
the host box query must produce identical final clusterings, because
they implement the same 2*eps duplication rule (reference README.md:20).
"""

import numpy as np
import pytest
from sklearn.datasets import make_blobs

from pypardis_tpu.ops.labels import densify_labels
from pypardis_tpu.parallel import default_mesh
from pypardis_tpu.parallel.sharded import sharded_dbscan
from pypardis_tpu.partition import KDPartitioner


@pytest.fixture(scope="module")
def sharded_setup():
    X, _ = make_blobs(
        n_samples=2000, centers=6, n_features=3, cluster_std=0.3,
        random_state=3,
    )
    mesh = default_mesh(8)
    part = KDPartitioner(X, max_partitions=8)
    return X, mesh, part


def test_ring_matches_host_halo(sharded_setup):
    X, mesh, part = sharded_setup
    kw = dict(eps=0.4, min_samples=5, block=128, mesh=mesh)
    l_host, c_host, s_host = sharded_dbscan(X, part, halo="host", **kw)
    l_ring, c_ring, s_ring = sharded_dbscan(X, part, halo="ring", **kw)
    assert np.array_equal(c_host, c_ring)
    assert np.array_equal(
        densify_labels(l_host), densify_labels(l_ring)
    )
    assert s_ring["halo_exchange"] == "ring"


def test_ring_matches_single_node(sharded_setup):
    from sklearn.cluster import DBSCAN as SKDBSCAN
    from sklearn.metrics import adjusted_rand_score

    X, mesh, part = sharded_setup
    l_ring, _, _ = sharded_dbscan(
        X, part, eps=0.4, min_samples=5, block=128, mesh=mesh, halo="ring"
    )
    sk = SKDBSCAN(eps=0.4, min_samples=5).fit(X)
    assert adjusted_rand_score(sk.labels_, densify_labels(l_ring)) >= 0.99


def test_ring_multi_partition_per_device(sharded_setup):
    """halo='ring' with max_partitions > n_devices (two KD partitions
    per device) must match the host-halo labels exactly — the round-2
    one-partition-per-device restriction is lifted."""
    X, mesh, _ = sharded_setup
    part16 = KDPartitioner(X, max_partitions=16)
    kw = dict(eps=0.4, min_samples=5, block=128, mesh=mesh)
    l_host, c_host, _ = sharded_dbscan(X, part16, halo="host", **kw)
    l_ring, c_ring, s_ring = sharded_dbscan(X, part16, halo="ring", **kw)
    assert s_ring["halo_exchange"] == "ring"
    assert np.array_equal(c_host, c_ring)
    assert np.array_equal(densify_labels(l_host), densify_labels(l_ring))


def test_ring_fewer_partitions_than_devices(sharded_setup):
    """max_partitions below the mesh size pads empty ring slots whose
    inverted boxes collect no halo."""
    X, mesh, _ = sharded_setup
    part4 = KDPartitioner(X, max_partitions=4)
    kw = dict(eps=0.4, min_samples=5, block=128, mesh=mesh)
    l_host, c_host, _ = sharded_dbscan(X, part4, halo="host", **kw)
    l_ring, c_ring, _ = sharded_dbscan(X, part4, halo="ring", **kw)
    assert np.array_equal(c_host, c_ring)
    assert np.array_equal(densify_labels(l_host), densify_labels(l_ring))


# ---------------------------------------------------------------------------
# Owner-computes step (ISSUE 2): halo slots are adjacency evidence,
# never re-clustered.  Labels must be byte-identical to the legacy
# duplicate-and-recluster step on every distributed mode.
# ---------------------------------------------------------------------------


def _six_modes(X, mesh, part, *, eps, min_samples, block, owner_computes):
    """Labels/core/stats for all six distributed modes: {host, ring}
    halo x {device, host} merge on host input, plus the device-input
    ring route under both merges."""
    import jax

    from pypardis_tpu.parallel.sharded import sharded_dbscan_device

    kw = dict(eps=eps, min_samples=min_samples, block=block, mesh=mesh,
              owner_computes=owner_computes)
    out = {}
    for halo in ("host", "ring"):
        for merge in ("device", "host"):
            out[f"{halo}+{merge}"] = sharded_dbscan(
                X, part, halo=halo, merge=merge, **kw
            )
    Xd = jax.device_put(np.asarray(X))
    for merge in ("device", "host"):
        labels, core, stats, _part, _pid = sharded_dbscan_device(
            Xd, eps=eps, min_samples=min_samples, block=block, mesh=mesh,
            merge=merge, owner_computes=owner_computes,
            max_partitions=part.n_partitions,
        )
        out[f"device_input+{merge}"] = (labels, core, stats)
    return out


def test_owner_computes_six_mode_parity(sharded_setup):
    """Owner-computes labels byte-match the legacy step AND each other
    across all six distributed modes (the device-input route
    repartitions from a subsample, so its parity is within-route:
    owner-computes vs legacy on identical partitioning)."""
    X, mesh, part = sharded_setup
    kw = dict(eps=0.4, min_samples=5, block=128)
    oc = _six_modes(X, mesh, part, owner_computes=True, **kw)
    legacy = _six_modes(X, mesh, part, owner_computes=False, **kw)
    for mode in oc:
        l_oc, c_oc, s_oc = oc[mode]
        l_le, c_le, _s_le = legacy[mode]
        assert np.array_equal(c_oc, c_le), mode
        assert np.array_equal(l_oc, l_le), mode
        assert s_oc["owner_computes"] is True, mode
        assert s_oc["duplicated_work_factor"] < _s_le[
            "duplicated_work_factor"
        ], mode
    # Host-input modes agree byte-for-byte among themselves too.
    ref = oc["host+device"][0]
    for mode in ("host+host", "ring+device", "ring+host"):
        assert np.array_equal(oc[mode][0], ref), mode


def test_owner_computes_r5_geometry_duplication_bound():
    """The acceptance geometry (16-D blobs, eps=2.4 — the r5 bench
    setup scaled to CI): owner-computes must report a clustered-volume
    ``duplicated_work_factor`` <= 1.5 where the legacy step pays the
    full 1 + halo_factor duplication, with labels byte-identical to the
    fused single-shard engine."""
    from benchdata import make_blob_data
    from pypardis_tpu import DBSCAN

    X, _truth = make_blob_data(4000, 16, n_centers=32, std=0.4)
    mesh = default_mesh(8)
    part = KDPartitioner(X, max_partitions=8)
    kw = dict(eps=2.4, min_samples=10, block=128, mesh=mesh)
    l_le, c_le, s_le = sharded_dbscan(X, part, owner_computes=False, **kw)
    assert s_le["halo_factor"] > 1.0  # the duplication tax is real here
    assert s_le["duplicated_work_factor"] > 2.0
    # Byte parity with the fused single-shard engine, on EVERY
    # distributed mode, with the clustered volume back near 1.
    single = DBSCAN(eps=2.4, min_samples=10, block=128, max_partitions=1)
    ref = single.fit_predict(X)
    modes = _six_modes(X, mesh, part, eps=2.4, min_samples=10, block=128,
                       owner_computes=True)
    for mode, (labels, core, stats) in modes.items():
        assert stats["duplicated_work_factor"] <= 1.5, mode
        np.testing.assert_array_equal(
            densify_labels(labels), ref, err_msg=mode
        )
        np.testing.assert_array_equal(
            core, single.core_sample_mask_, err_msg=mode
        )
    assert np.array_equal(modes["host+device"][0], l_le)
    assert np.array_equal(modes["host+device"][1], c_le)


def test_owner_computes_halo_bridges_two_owned_clusters():
    """A core halo point adjacent to TWO owned clusters of one foreign
    partition must merge both (the single-min-edge formulation provably
    drops one of the links — this pins the relay propagation).

    Geometry: clumps A and B live left of the KD split, clump H right
    of it; H is within eps of both, A and B are > eps apart, so the
    only path A-B runs through H's points (halo slots in A/B's
    partition)."""
    rng = np.random.default_rng(0)

    def clump(cx, cy, m=20):
        return rng.normal([cx, cy], 0.01, size=(m, 2))

    X = np.concatenate([
        clump(-0.05, 0.0), clump(-0.05, 0.8), clump(0.05, 0.4),
    ])
    part = KDPartitioner(X, max_partitions=2)
    mesh = default_mesh(8)
    for kwargs in (
        dict(), dict(merge="host"), dict(halo="ring"),
        dict(halo="ring", merge="host"),
    ):
        labels, core, _stats = sharded_dbscan(
            X, part, eps=0.5, min_samples=5, block=64, mesh=mesh,
            owner_computes=True, **kwargs,
        )
        assert core.all(), kwargs
        assert (labels == labels[0]).all(), kwargs
