"""Test harness: fake an 8-device mesh on CPU.

The reference tested distribution implicitly via Spark local mode (SURVEY
§4); the TPU equivalent is XLA's host-platform device splitting, so
shard_map halo exchange and label-merge collectives run in CI without
TPU hardware.

Note: this image's sitecustomize pre-imports jax and pins
``JAX_PLATFORMS=axon``, so env vars are too late — we must override via
``jax.config`` before any backend initialization.
"""

import os

# PYPARDIS_TEST_PLATFORM=native leaves the ambient JAX platform alone —
# that's how `make tpu-smoke` runs tests/test_tpu_smoke.py against the
# real chip (the smoke tests skip themselves off-TPU; everything else
# here asserts the 8-device mesh and skips under native).
_NATIVE = os.environ.get("PYPARDIS_TEST_PLATFORM") == "native"

if not _NATIVE:
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

import jax

if not _NATIVE:
    jax.config.update("jax_platforms", "cpu")
    # jax >= 0.5 splits the host platform via this option; on older
    # versions (0.4.x) it doesn't exist and the XLA_FLAGS
    # --xla_force_host_platform_device_count path above already covers
    # the 8-device mesh.
    if "jax_num_cpu_devices" in jax.config._value_holders:
        jax.config.update("jax_num_cpu_devices", 8)

import numpy as np
import pytest


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: scale-ish tests (~100k points on the CPU mesh); "
        "deselect with -m 'not slow'",
    )


@pytest.fixture(scope="session", autouse=True)
def _assert_eight_devices():
    if not _NATIVE:
        assert jax.device_count() == 8, jax.devices()


@pytest.fixture
def blobs750():
    """The reference's de-facto correctness baseline: the sklearn
    plot_dbscan demo setup (make_blobs 2-D, 750 pts, eps=0.3,
    min_samples=10) — README.md:42, plots/*/clusters.png."""
    from sklearn.datasets import make_blobs
    from sklearn.preprocessing import StandardScaler

    centers = [[1, 1], [-1, -1], [1, -1]]
    X, _ = make_blobs(
        n_samples=750, centers=centers, cluster_std=0.4, random_state=0
    )
    return StandardScaler().fit_transform(X).astype(np.float64)
