// Native merge-phase primitives for pypardis_tpu.
//
// The reference resolves cross-partition cluster equivalences with a
// driver-side Python dict aggregation (reference dbscan/aggregator.py:19-63,
// the README.md:60 driver-memory bottleneck).  The TPU framework's
// primary merge runs on-device (parallel/sharded.py); this library backs
// the *host-side* merge utilities (parallel/merge.py) with an array
// union-find in C++, so resolving multi-million-edge equivalence tables
// is near-linear and allocation-free instead of a Python dict walk.
//
// Semantics match ClusterAggregator: min-id linking — the root of every
// component is the minimum id it contains (aggregator.py:45's downward
// merges).
//
// Built as a plain shared library (no pybind11 in this image); loaded
// via ctypes from pypardis_tpu/_native/__init__.py.

#include <cstdint>

namespace {

int64_t find_root(int64_t* parent, int64_t x) {
  int64_t root = x;
  while (parent[root] != root) root = parent[root];
  // Path compression.
  while (parent[x] != root) {
    int64_t next = parent[x];
    parent[x] = root;
    x = next;
  }
  return root;
}

}  // namespace

extern "C" {

// Union-find over n_nodes dense nodes.  edges: (n_edges, 2) int64 pairs,
// entries outside [0, n_nodes) are ignored.  out_parent: (n_nodes,)
// int64, receives the fully-compressed min-id root of every node.
void uf_resolve_dense(const int64_t* edges, int64_t n_edges,
                      int64_t n_nodes, int64_t* out_parent) {
  for (int64_t i = 0; i < n_nodes; ++i) out_parent[i] = i;
  for (int64_t e = 0; e < n_edges; ++e) {
    int64_t a = edges[2 * e];
    int64_t b = edges[2 * e + 1];
    if (a < 0 || b < 0 || a >= n_nodes || b >= n_nodes) continue;
    int64_t ra = find_root(out_parent, a);
    int64_t rb = find_root(out_parent, b);
    if (ra == rb) continue;
    // Min-id wins (matches aggregator.py:45).
    if (ra < rb) {
      out_parent[rb] = ra;
    } else {
      out_parent[ra] = rb;
    }
  }
  for (int64_t i = 0; i < n_nodes; ++i) find_root(out_parent, i);
}

// Relabel: out[i] = lut[labels[i]] for labels in [0, n_lut), else fill.
// The trivial loop is here so multi-GB relabel passes skip numpy fancy
// indexing's temporary allocations.
void relabel_i32(const int32_t* labels, int64_t n, const int32_t* lut,
                 int64_t n_lut, int32_t fill, int32_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    int32_t l = labels[i];
    out[i] = (l >= 0 && l < n_lut) ? lut[l] : fill;
  }
}

}  // extern "C"
