"""Native (C++) merge primitives with transparent Python fallback.

The shared library is compiled on first import with the system ``g++``
(this image ships no pybind11, so the binding layer is plain ctypes) and
cached next to the source.  Every entry point degrades to a numpy/Python
implementation when the toolchain or the build is unavailable, so the
framework never *requires* the native path — it's a host-side merge
accelerator, not a correctness dependency.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "unionfind.cpp")
_LIB = os.path.join(_DIR, "libpypardis_native.so")


def _build() -> bool:
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-o", _LIB, _SRC],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except Exception:
        return False


def _load():
    try:
        stale = not os.path.exists(_LIB) or (
            os.path.exists(_SRC)
            and os.path.getmtime(_LIB) < os.path.getmtime(_SRC)
        )
    except OSError:
        stale = False
    if stale and not _build():
        return None
    if not os.path.exists(_LIB):
        return None
    try:
        lib = ctypes.CDLL(_LIB)
        lib.uf_resolve_dense.argtypes = [
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            ctypes.c_int64,
            ctypes.c_int64,
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        ]
        lib.uf_resolve_dense.restype = None
        lib.relabel_i32.argtypes = [
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            ctypes.c_int64,
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            ctypes.c_int64,
            ctypes.c_int32,
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        ]
        lib.relabel_i32.restype = None
        return lib
    except OSError:
        return None


NATIVE = _load()


def native_available() -> bool:
    return NATIVE is not None


def uf_resolve_dense(edges: np.ndarray, n_nodes: int) -> np.ndarray:
    """Min-id union-find roots for dense node ids 0..n_nodes-1.

    ``edges``: (E, 2) integer array; out-of-range entries are ignored.
    Returns (n_nodes,) int64 — each node's component root, which is the
    component's minimum id (ClusterAggregator's downward-merge rule,
    reference aggregator.py:45).
    """
    edges = np.ascontiguousarray(
        np.asarray(edges, dtype=np.int64).reshape(-1, 2)
    )
    out = np.empty(int(n_nodes), dtype=np.int64)
    if NATIVE is not None:
        NATIVE.uf_resolve_dense(edges, len(edges), int(n_nodes), out)
        return out
    # Python fallback: same linking rule.
    from ..aggregator import UnionFind

    uf = UnionFind(int(n_nodes))
    for a, b in edges:
        if 0 <= a < n_nodes and 0 <= b < n_nodes:
            uf.union(int(a), int(b))
    return uf.roots()


def relabel_i32(
    labels: np.ndarray, lut: np.ndarray, fill: int = -1
) -> np.ndarray:
    """out[i] = lut[labels[i]] for in-range labels, else ``fill``."""
    labels = np.ascontiguousarray(labels, dtype=np.int32)
    lut = np.ascontiguousarray(lut, dtype=np.int32)
    out = np.empty_like(labels)
    if NATIVE is not None:
        NATIVE.relabel_i32(
            labels, labels.size, lut, lut.size, np.int32(fill), out
        )
        return out
    ok = (labels >= 0) & (labels < lut.size)
    out[:] = fill
    out[ok] = lut[labels[ok]]
    return out
