"""Unified retry/backoff layer.

Before this module every recovery path rolled its own loop: the fused
pipeline's 0/10/75s transient ladder (``ops.pipeline._transient_retry``),
the pair-budget/merge-rounds ladder (``utils.budget.run_ladders``), the
ring ``hcap`` doubling and the global-Morton ``btcap`` ladder — four
spellings of "try again, observably".  This module is the one engine
they all report through:

* :class:`Retrier` — attempts, an explicit wait ladder OR exponential
  backoff with jitter, an optional wall-clock deadline, and per-site
  obs counters ``retry.<site>.attempts`` / ``retry.<site>.giveups``
  (summed into ``report()["faults"]["retried"/"giveups"]``).  Used
  directly by the transient-fault scopes: fused/stepped kernel
  dispatch, the chained partition loop, the global-Morton ring and
  fixpoint rounds, and staging ``device_put``s
  (:func:`pypardis_tpu.parallel.staging.transfer`).

* :func:`note_retry` / :func:`note_giveup` — the same counters for the
  capacity ladders (pair budget, hcap, btcap, merge rounds) whose
  *control flow* must stay ladder-shaped (each retry changes a
  capacity, not just waits) but whose telemetry must be uniform.

* :func:`note_degraded` — records a graceful-degradation rung
  (``Pallas→XLA`` kernel fallback, ``merge='device'``→``'host'``
  spill, ``global_morton``→KD owner-computes mode fallback): one
  ``degraded`` event + the ``faults.degraded_to`` gauge.  Every rung is
  label-safe — each fallback mode is pinned byte-identical to the mode
  it replaces.

Error classification helpers (:func:`is_transient_error`,
:func:`is_oom_error`, :func:`is_degradable_error`) are shared with the
fault-injection kinds (:mod:`pypardis_tpu.utils.faults`), so injected
faults exercise exactly the production classification.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, Sequence
from . import envreg

# The historical transient ladder (ops.pipeline round-3): immediate
# retry, then two backed-off ones — a crashed tunnel worker needs tens
# of seconds to restart.
DEFAULT_WAITS = (0.0, 10.0, 75.0)


def is_transient_error(e: BaseException) -> bool:
    """Axon-runtime transient signatures (same set _transient_retry has
    classified since round 3) — the identical call succeeds moments
    later."""
    msg = f"{type(e).__name__}: {e}"
    return any(
        s in msg
        for s in ("UNAVAILABLE", "INTERNAL", "INVALID_ARGUMENT",
                  "InvalidArgument")
    )


def is_oom_error(e: BaseException) -> bool:
    """Out-of-memory signatures (XLA RESOURCE_EXHAUSTED, allocator
    messages, injected ``oom`` faults)."""
    msg = f"{type(e).__name__}: {e}".lower()
    return "resource_exhausted" in msg or "out of memory" in msg \
        or "oom" in msg.split(":")[0]


def is_degradable_error(e: BaseException) -> bool:
    """Whether a terminal failure justifies dropping a degradation rung
    (host-spill merge, mode fallback): OOM-class only — a persistent
    transient means the runtime is down, and a ValueError means the
    caller's inputs are wrong; neither is cured by a cheaper mode."""
    return is_oom_error(e)


def _key(site: str, leaf: str) -> str:
    from ..obs.registry import sanitize_segment

    return "retry." + ".".join(
        sanitize_segment(s) for s in str(site).split(".")
    ) + f".{leaf}"


def note_retry(site: str, wait_s: float, error: BaseException) -> None:
    """One retry, observably: the ``retry.<site>`` event (the report's
    ``transient_retry`` family), the ``retry.<site>.attempts`` counter,
    and a warning line."""
    from ..obs import current, event
    from ..obs.registry import sanitize_segment
    from .log import get_logger

    event(
        "retry." + ".".join(
            sanitize_segment(s) for s in str(site).split(".")
        ),
        wait_s=round(float(wait_s), 3), error=str(error)[:160],
    )
    current().metrics.inc(_key(site, "attempts"))
    get_logger().warning(
        "retryable fault in %s; retrying in %.1fs: %s",
        site, wait_s, str(error)[:160],
    )


def note_giveup(site: str, error: BaseException) -> None:
    from ..obs import current, event

    event("retry_giveup", site=str(site), error=str(error)[:160])
    current().metrics.inc(_key(site, "giveups"))


def note_degraded(rung: str, **fields) -> None:
    """Record one graceful-degradation rung (kernel_xla / merge_host /
    kd_owner_computes / ...)."""
    from ..obs import current, event
    from .log import get_logger

    event("degraded", rung=str(rung), **fields)
    m = current().metrics
    m.inc("faults.degraded")
    m.set("faults.degraded_to", str(rung))
    get_logger().warning("degrading to %s after terminal failure", rung)


class Retrier:
    """Retry a callable through transient faults, observably.

    ``waits`` is an explicit ladder of sleeps between attempts (its
    length caps the retries, matching the historical 0/10/75 ladder);
    otherwise ``attempts``/``base_s``/``factor``/``max_wait_s`` define
    an exponential schedule.  Nonzero waits get up to ``jitter``
    fractional randomization (herd-avoidance on multi-process meshes;
    determinism of the retried *computation* never depends on timing).
    ``deadline_s`` (or ``PYPARDIS_RETRY_DEADLINE_S``) bounds the total
    wall clock spent inside :meth:`run` — a retry whose sleep would
    cross it gives up immediately instead of overshooting.
    """

    def __init__(
        self,
        site: str,
        *,
        waits: Optional[Sequence[float]] = None,
        attempts: int = 3,
        base_s: float = 0.5,
        factor: float = 6.0,
        max_wait_s: float = 75.0,
        jitter: float = 0.25,
        deadline_s: Optional[float] = None,
    ):
        self.site = str(site)
        if waits is not None:
            self.waits = tuple(float(w) for w in waits)
        else:
            self.waits = tuple(
                min(base_s * factor ** i, max_wait_s)
                for i in range(max(int(attempts) - 1, 0))
            )
        self.jitter = float(jitter)
        if deadline_s is None:
            env = envreg.raw("PYPARDIS_RETRY_DEADLINE_S")
            deadline_s = float(env) if env else None
        self.deadline_s = deadline_s

    def run(
        self,
        fn: Callable,
        *,
        retryable: Optional[Callable[[BaseException], bool]] = None,
        on_retry: Optional[Callable[[BaseException], None]] = None,
    ):
        """Call ``fn()`` with up to ``len(waits)`` retries.

        ``retryable`` classifies which exceptions are worth a retry
        (default: :func:`is_transient_error`); everything else
        re-raises immediately.  ``on_retry(error)`` runs before each
        retry — the hook for recovery actions (the staging layer evicts
        its device cache there, so a retried OOM has HBM headroom).
        """
        if retryable is None:
            retryable = is_transient_error
        t0 = time.perf_counter()
        last: Optional[BaseException] = None
        for i in range(len(self.waits) + 1):
            if i > 0:
                wait = self.waits[i - 1]
                if wait > 0 and self.jitter > 0:
                    wait *= 1.0 + self.jitter * random.random()
                if (
                    self.deadline_s is not None
                    and time.perf_counter() - t0 + wait > self.deadline_s
                ):
                    break
                note_retry(self.site, wait, last)
                if on_retry is not None:
                    on_retry(last)
                if wait > 0:
                    time.sleep(wait)
            try:
                return fn()
            except Exception as e:  # noqa: BLE001 — re-raised below
                if not retryable(e):
                    raise
                last = e
        note_giveup(self.site, last)
        raise last
