"""Structured logging.

The reference's entire observability surface is a module flag that is
never read (``LOGGING = False``, reference dbscan.py:9).  This module is
the working version: a package logger plus the same flag name as a
convenience switch.  ``LOGGING = True`` (or standard ``logging``
configuration) enables per-phase driver logs.
"""

from __future__ import annotations

import logging

# Parity with the reference's flag name (dbscan.py:9) — but read.
LOGGING = False

_logger = logging.getLogger("pypardis_tpu")


def get_logger() -> logging.Logger:
    return _logger


def enable(level: int = logging.INFO) -> None:
    """Convenience switch: attach a stderr handler at ``level``."""
    global LOGGING
    LOGGING = True
    if not _logger.handlers:
        h = logging.StreamHandler()
        h.setFormatter(
            logging.Formatter("[%(name)s %(levelname)s] %(message)s")
        )
        _logger.addHandler(h)
    _logger.setLevel(level)


def log_phase(phase: str, **fields) -> None:
    """One structured line per pipeline phase.

    Always recorded as an ``events.log.<phase>`` entry in the current
    telemetry recorder (:mod:`pypardis_tpu.obs`) — the log stream and
    the run report can never disagree.  The logging emission is gated on
    ``_logger.isEnabledFor`` ALONE: the old ``LOGGING or ...``
    short-circuit meant a user configuring standard ``logging`` at INFO
    through root handlers fired only by luck of the effective level,
    while ``LOGGING=True`` force-emitted records the logger's own level
    would then drop — the flag's job is done by ``enable()`` attaching
    the handler, not by bypassing the level check.
    """
    from ..obs import current
    from ..obs.registry import sanitize_segment

    current().event(f"log.{sanitize_segment(phase)}", **fields)
    if LOGGING and not _logger.handlers:
        # The flag was set directly (without enable()) — honor it anyway;
        # the reference's sin was a flag nothing ever read.
        enable()
    if _logger.isEnabledFor(logging.INFO):
        kv = " ".join(f"{k}={v}" for k, v in fields.items())
        _logger.info("%s %s", phase, kv)
