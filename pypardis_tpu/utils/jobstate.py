"""Checkpoint-resumable fits: phase-boundary job state.

An hours-long north-star fit that dies at 95% — OOM, watchdog SIGKILL,
a yanked tunnel — currently restarts from zero.  The flight recorder
(PR 6) can say *where* it died; this module makes the death cheap: the
routes with natural phase boundaries snapshot their completed work to
one atomically-rewritten ``.npz``, and ``DBSCAN.train(resume=path)``
replays only what is missing, producing labels **byte-identical** to an
uninterrupted fit.

What is snapshotted, per route:

* **chained (1-device) route** — the per-partition global-label /
  core-flag tables, fetched post-probe (the kernel's exact outputs);
  resume skips those partitions' dispatches and feeds the identical
  tables to the merge.
* **host-stepped route** — the propagation state ``f`` after each
  consumed round batch; min-label propagation is monotone toward its
  unique fixpoint, so resuming from any intermediate state of the same
  pair tables converges to identical labels.
* **global-Morton fixpoint** — the replicated ``(N+1,)`` ``lab_map``
  after each pmin round (same monotone-fixpoint argument; the cluster
  step recomputes deterministically on resume).

Every payload is keyed by the **effective pair budget** that produced
it: tables computed under a budget that later overflowed are invalid,
and a ladder retry (or a resumed process rediscovering the overflow)
must never consume them — a mismatched budget tag simply recomputes.

The file carries a **fit fingerprint** (content CRC of the points via
the staging layer's chunked fingerprint, plus eps / min_samples /
metric / block / mode): ``train(resume=)`` against different data or
parameters raises instead of silently resuming the wrong fit.

Write cadence: ``PYPARDIS_CKPT_EVERY_S`` seconds between disk writes
(default 0 — every phase boundary; long real runs should raise it so a
100M chained fit is not rewriting its snapshot per partition).  Writes
are atomic (tmp + ``os.replace``), so a SIGKILL mid-write leaves the
previous consistent snapshot.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional, Tuple

import numpy as np
from . import envreg

SCHEMA = "pypardis_tpu/jobstate@1"


def _norm_npz(path: str) -> str:
    return path if str(path).endswith(".npz") else f"{path}.npz"


def fit_meta(points, *, eps, min_samples, metric, block, mode) -> Dict:
    """The fit fingerprint a snapshot is bound to."""
    try:
        from ..parallel.staging import points_fingerprint

        fp = list(points_fingerprint(np.asarray(points)))
        fp[0] = list(fp[0])  # shape tuple -> list (json round-trip)
    except Exception:  # noqa: BLE001 — device arrays: shape/dtype only
        fp = [list(getattr(points, "shape", ())),
              str(getattr(points, "dtype", "")), 0]
    return {
        "schema": SCHEMA,
        "fingerprint": fp,
        "eps": float(eps),
        "min_samples": int(min_samples),
        "metric": str(metric),
        "block": int(block),
        "mode": str(mode),
    }


def discard_stale(path: str, meta: Dict) -> bool:
    """Remove a snapshot written by a DIFFERENT fit; True if removed.

    The resume guard (:meth:`JobState._load`) *raises* on a fingerprint
    mismatch — the right behavior for an operator retyping a resume
    path.  A background compaction (:class:`pypardis_tpu.serve.ingest.
    Compactor`) has the opposite contract: its snapshot moves with the
    write stream, so a jobstate file left by a killed cycle over an
    OLDER point set describes an obsolete partial generation — discard
    it and refit fresh, never refuse.  An unreadable file (a torn write
    from a kill that raced the atomic replace's tmp file) is discarded
    the same way."""
    p = _norm_npz(path)
    if not os.path.exists(p):
        return False
    try:
        with np.load(p, allow_pickle=False) as z:
            saved = json.loads(str(z["meta"]))
    except Exception:  # noqa: BLE001 — torn/foreign file: discard
        os.unlink(p)
        return True
    if saved != dict(meta):
        os.unlink(p)
        return True
    return False


class JobState:
    """One resumable fit's snapshot file.

    Route payloads live in memory between flushes; :meth:`due` gates
    both the snapshot fetches at the call sites and the disk rewrites
    here, so checkpointing costs nothing faster than the cadence.
    """

    def __init__(self, path: str, meta: Dict,
                 every_s: Optional[float] = None):
        self.path = _norm_npz(path)
        self.meta = dict(meta)
        if every_s is None:
            try:
                every_s = float(
                    envreg.raw("PYPARDIS_CKPT_EVERY_S", 0.0)
                )
            except (TypeError, ValueError):
                every_s = 0.0
        self.every_s = max(float(every_s), 0.0)
        self._last_write = 0.0
        self.restored_partitions = 0
        self.restored_rounds = 0
        # chained: {p: (glab, core, pstats)}, one budget generation.
        self._ch_budget: Optional[int] = None
        self._chained: Dict[int, Tuple] = {}
        # stepped: (f, batches) under a budget.
        self._st_budget: Optional[int] = None
        self._stepped: Optional[Tuple[np.ndarray, int]] = None
        # gm fixpoint: (lab_map, round) under a budget.
        self._gm_budget: Optional[int] = None
        self._gm: Optional[Tuple[np.ndarray, int]] = None

    # -- lifecycle --------------------------------------------------------

    @classmethod
    def open(cls, path: str, meta: Dict, *, resume: bool = False,
             every_s: Optional[float] = None) -> "JobState":
        """Open a job-state file for writing; with ``resume`` and an
        existing file, load its payloads (fingerprint must match)."""
        js = cls(path, meta, every_s=every_s)
        p = js.path
        if resume and os.path.exists(p):
            js._load(p)
        return js

    def _load(self, p: str) -> None:
        with np.load(p, allow_pickle=False) as z:
            saved_meta = json.loads(str(z["meta"]))
            if saved_meta != self.meta:
                raise ValueError(
                    f"jobstate {p} was written by a different fit "
                    f"(saved {saved_meta}, current {self.meta}); "
                    f"resume only matches identical data and params"
                )
            if "ch_ps" in z.files and len(z["ch_ps"]):
                self._ch_budget = int(z["ch_budget"])
                glab, core, pstats = (
                    z["ch_glab"], z["ch_core"], z["ch_pstats"]
                )
                self._chained = {
                    int(p_): (glab[i], core[i], pstats[i])
                    for i, p_ in enumerate(z["ch_ps"])
                }
            if "st_f" in z.files and z["st_f"].size:
                self._st_budget = int(z["st_budget"])
                self._stepped = (z["st_f"], int(z["st_batches"]))
            if "gm_lab" in z.files and z["gm_lab"].size:
                self._gm_budget = int(z["gm_budget"])
                self._gm = (z["gm_lab"], int(z["gm_round"]))

    def due(self) -> bool:
        """Whether the cadence allows another snapshot now."""
        return time.monotonic() - self._last_write >= self.every_s

    def flush(self, force: bool = False) -> None:
        if not force and not self.due():
            return
        # Multi-process fleets: every worker tracks the same snapshot
        # state (the fixpoint fetches are allgathered), but only the
        # coordinator writes — N workers racing os.replace on one
        # shared-store path would tear it.  Resume reads the shared
        # path on every worker.
        from ..parallel import dist

        if not dist.is_coordinator():
            self._last_write = time.monotonic()
            return
        payload: Dict = {"meta": json.dumps(self.meta)}
        if self._chained:
            ps = sorted(self._chained)
            payload.update(
                ch_budget=np.int64(self._ch_budget or 0),
                ch_ps=np.asarray(ps, np.int64),
                ch_glab=np.stack(
                    [np.asarray(self._chained[p][0], np.int32)
                     for p in ps]
                ),
                ch_core=np.stack(
                    [np.asarray(self._chained[p][1], bool) for p in ps]
                ),
                ch_pstats=np.stack(
                    [np.asarray(self._chained[p][2], np.int64)
                     for p in ps]
                ),
            )
        if self._stepped is not None:
            payload.update(
                st_budget=np.int64(self._st_budget or 0),
                st_f=np.asarray(self._stepped[0], np.int32),
                st_batches=np.int64(self._stepped[1]),
            )
        if self._gm is not None:
            payload.update(
                gm_budget=np.int64(self._gm_budget or 0),
                gm_lab=np.asarray(self._gm[0], np.int32),
                gm_round=np.int64(self._gm[1]),
            )
        tmp = f"{self.path}.tmp-{os.getpid()}"
        with open(tmp, "wb") as f:
            np.savez(f, **payload)
        os.replace(tmp, self.path)
        self._last_write = time.monotonic()

    # -- chained route ----------------------------------------------------

    def chained_restore(self, budget: int) -> Dict[int, Tuple]:
        """{partition -> (glab, core, pstats)} valid under ``budget``
        ({} on a budget mismatch — those tables are never reused)."""
        if self._ch_budget != int(budget) or not self._chained:
            return {}
        self.restored_partitions = len(self._chained)
        return dict(self._chained)

    def chained_note(self, p: int, glab, core, pstats,
                     budget: int) -> None:
        if self._ch_budget != int(budget):
            self._ch_budget = int(budget)
            self._chained = {}
        self._chained[int(p)] = (
            np.asarray(glab, np.int32),
            np.asarray(core, bool),
            np.asarray(pstats, np.int64).reshape(-1),
        )
        self.flush()

    # -- stepped route ----------------------------------------------------

    def stepped_restore(self, budget: int, capk: int
                        ) -> Optional[Tuple[np.ndarray, int]]:
        if (
            self._stepped is None or self._st_budget != int(budget)
            or len(self._stepped[0]) != int(capk)
        ):
            return None
        self.restored_rounds = int(self._stepped[1])
        return self._stepped

    def stepped_note(self, f: np.ndarray, batches: int,
                     budget: int) -> None:
        self._st_budget = int(budget)
        self._stepped = (np.asarray(f, np.int32), int(batches))
        self.flush()

    # -- global-Morton fixpoint -------------------------------------------

    def gm_restore(self, budget: int, n1: int
                   ) -> Optional[Tuple[np.ndarray, int]]:
        if (
            self._gm is None or self._gm_budget != int(budget)
            or len(self._gm[0]) != int(n1)
        ):
            return None
        self.restored_rounds = int(self._gm[1])
        return self._gm

    def gm_note(self, lab_map: np.ndarray, rounds: int,
                budget: int) -> None:
        self._gm_budget = int(budget)
        self._gm = (np.asarray(lab_map, np.int32), int(rounds))
        self.flush()
