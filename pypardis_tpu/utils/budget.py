"""Shared pair-budget / merge-rounds retry driver.

The kernels' live tile-pair extraction runs against a static budget
(``ops.distances.live_tile_pairs``); overflow is reported in-band as
``[total, budget]`` stats and the labels built from a truncated pair
list are INVALID.  Every driver — single-shard (`dbscan._pad_and_run`)
and all three sharded paths (`parallel.sharded.sharded_dbscan`) — must
therefore run the same ladder: consult the hint cache, retry once with
the exact total, raise if overflow persists, and seed the hint only
after an observed overflow.  One implementation here so the paths
cannot silently diverge.
"""

from __future__ import annotations

import numpy as np

from .hints import PAIR_BUDGET_HINTS
from . import envreg
from .shaping import round_up


def _stat_rows(pstats) -> np.ndarray:
    """Normalize pstats to (n_runs, width) rows.

    Rows are ``[live_pairs_total, budget]`` or the full 5-wide
    ``[live_pairs_total, budget, kernel_passes, band_pairs,
    rescored_tiles]`` (``ops.precision.PAIR_STATS_WIDTH``) — the
    ladder only reads the first two columns; the rest ride through for
    the drivers' FLOP model and the mixed-precision band telemetry.
    """
    from ..parallel import dist

    ps = dist.fetch_np(pstats)
    return ps.reshape(-1, ps.shape[-1] if ps.ndim else 1)


def pair_overflow(pstats) -> int:
    """Exact pair budget to retry with, or 0 when nothing overflowed.

    ``pstats``: (n_runs, 2+) per-run ``[live_pairs_total, budget, ...]``.
    Budgets are shared (static), so the max total is the binding
    requirement; the total is exact, so one retry always suffices.
    ``budget == 0`` means no static budget was in play (the XLA path's
    "cannot overflow" report).
    """
    ps = _stat_rows(pstats)
    total, budget = int(ps[:, 0].max()), int(ps[:, 1].max())
    if budget and total > budget:
        from ..obs import event as obs_event
        from .log import get_logger

        obs_event("pair_overflow", total=total, budget=budget)
        get_logger().warning(
            "live tile-pair budget overflow (%d > %d); rerunning with "
            "an exact budget", total, budget,
        )
        return round_up(total, 4096)
    return 0


def seed_hint(key, pstats) -> None:
    """Remember the exact budget that sufficed after an observed
    overflow (seed-on-overflow-only — see utils.hints)."""
    total = int(_stat_rows(pstats)[:, 0].max())
    if total > 0:
        PAIR_BUDGET_HINTS.put(key, round_up(total, 4096))


def unconverged_error(merge_rounds: int) -> RuntimeError:
    return RuntimeError(
        f"cross-partition label merge did not converge within "
        f"{merge_rounds} rounds — the result would be under-merged "
        f"(a cluster chain threading more partitions than the rounds "
        f"covered would come back split); raise merge_rounds"
    )


def run_ladders(run_step, hint_key, pair_budget, merge_rounds):
    """Drive ``run_step`` through the pair-budget and merge-rounds
    retry ladders.

    ``run_step(pair_budget, merge_rounds)`` returns ``(outputs, pstats,
    converged)``.  Handles, in order: hint lookup when ``pair_budget``
    is None, one exact-total pair-overflow retry (a persisting overflow
    raises — never returns labels built from a truncated pair list),
    hint seeding after an observed overflow, and one 4x merge-rounds
    retry on non-convergence (then raises).

    Returns ``(outputs, pstats)`` — the successful attempt's outputs
    plus its pair stats, so drivers can surface live-pair volume and
    kernel passes (the achieved-FLOP/s model) without a second fetch.
    """
    from .log import get_logger

    this_pair = pair_budget
    if this_pair is None:
        # Operator knob: a known-dense deployment can pin the budget
        # process-wide and skip the overflow-rerun (and its recompile)
        # on every cold fit.
        env = envreg.raw("PYPARDIS_PAIR_BUDGET")
        if env:
            this_pair = int(env)
    pair_attempts = 2  # exact-total retry: one is always enough
    this_rounds = merge_rounds
    rounds_attempts = 2
    overflowed = False
    while True:
        use_pair = (
            this_pair if this_pair is not None
            else PAIR_BUDGET_HINTS.get(hint_key)
        )
        outputs, pstats, converged = run_step(use_pair, this_rounds)
        retry_pair = pair_overflow(pstats)
        if retry_pair:
            pair_attempts -= 1
            if pair_attempts <= 0:
                from .retry import note_giveup

                err = RuntimeError(
                    f"live tile-pair budget overflow persisted after an "
                    f"exact-total retry: the kernels need at least "
                    f"{retry_pair} live tile pairs; pass "
                    f"pair_budget={retry_pair} (or set "
                    f"PYPARDIS_PAIR_BUDGET={retry_pair}) — labels from "
                    f"a truncated pair list would be silently wrong, "
                    f"so this never returns"
                )
                note_giveup("pair_budget", err)
                raise err
            from .retry import note_retry

            note_retry(
                "pair_budget", 0.0,
                RuntimeError(f"pair budget overflow, need {retry_pair}"),
            )
            this_pair = retry_pair
            overflowed = True
            continue
        if not bool(np.asarray(converged)):
            rounds_attempts -= 1
            if rounds_attempts <= 0:
                from .retry import note_giveup

                err = unconverged_error(this_rounds)
                note_giveup("merge_rounds", err)
                raise err
            nxt = max(1, 4 * this_rounds)
            from ..obs import event as obs_event
            from .retry import note_retry

            obs_event("merge_unconverged", rounds=this_rounds, next=nxt)
            note_retry(
                "merge_rounds", 0.0,
                RuntimeError(f"unconverged at {this_rounds} rounds"),
            )
            get_logger().warning(
                "label merge unconverged after %d rounds; retrying with "
                "%d", this_rounds, nxt,
            )
            this_rounds = nxt
            continue
        break
    if overflowed:
        seed_hint(hint_key, pstats)
    return outputs, pstats
