"""Deterministic fault injection for fault-tolerance testing.

The north-star run is an hours-long multi-phase job; proving it
survives a mid-flight failure requires *producing* one on demand, at a
named point in the pipeline, on a reproducible occurrence — not waiting
for the tunnel to hiccup.  This module is that switchboard: a
:class:`FaultPlan` (parsed from the ``PYPARDIS_FAULTS`` env var or
installed programmatically) maps **injection sites** — stable names
threaded through the hot paths — to counted-occurrence fault kinds.

Spec grammar (comma-separated entries)::

    site[:occurrence]=kind[(arg)]

    gm.ring_round:2=transfer_error     # 2nd arrival at the site fails
    stepped.batch:5=oom                # 5th round batch raises an OOM
    serve.drain:1=hang(3s)             # 1st drain stalls 3 seconds
    chained.partition:*=hang(0.2)      # EVERY partition stalls 0.2s

Occurrences are 1-based arrival counts per site (``*`` = every
arrival), so a test or probe replays the identical failure every run.

Fault kinds:

* ``transfer_error`` — raises a :class:`FaultInjected` whose message
  carries ``UNAVAILABLE`` (the axon tunnel's transient-fault signature),
  so the unified retry layer (:mod:`pypardis_tpu.utils.retry`)
  classifies and retries it exactly like the real thing;
* ``oom`` — raises with ``RESOURCE_EXHAUSTED ... Out of memory``:
  retryable where a recovery action exists (the staging layer evicts
  its device cache first), degradable otherwise (merge host-spill,
  global-Morton → KD mode fallback);
* ``error`` — a terminal, non-retryable failure (exercises giveup
  paths and the jobstate kill window without a subprocess);
* ``hang(Ns)`` — sleeps N seconds and returns (a stuck ticket /
  watchdog stall; the serving deadline machinery must fail the ticket
  rather than wait forever — and probes use it to widen kill windows
  deterministically).

Injection sites (each a ``maybe_fail`` call placed INSIDE the retry
scope that owns recovery, so an injected transient recovers through the
very machinery a real fault would exercise):

===================== ====================================================
``staging.device_put`` host→device slab transfers (:func:`pypardis_tpu.
                       parallel.staging.transfer`)
``pipeline.cluster``   fused single-shard kernel dispatch
``stepped.batch``      host-stepped propagation round batches
``chained.partition``  1-device chained per-partition dispatches
``sharded.execute``    KD sharded execute step (degradation rung tests)
``gm.exchange``        global-Morton boundary-tile exchange
``gm.ring_round``      each boundary-tile ppermute ring round
``gm.fixpoint_round``  each cross-device pmin fixpoint round
``gm.execute``         global-Morton cluster/execute dispatches
``gm.chained_range``   1-device chained global-Morton per-range
                       dispatches (counts + propagation)
``serve.drain``        :meth:`QueryEngine.drain`
``ingest.batch``       batched writes (``LiveModel.insert_batch`` /
                       ``delete_batch`` — fired BEFORE any state
                       mutates, so a failed batch leaves the model
                       untouched and fails only its queue tickets)
``compact.phase``      each streaming-ingest compaction phase boundary
                       (snapshot / refit / build / swap, occurrences
                       1..4 per cycle — ``serve.ingest.Compactor``)
``gateway.admit``      every gateway admission decision
                       (``serve.gateway.ModelGateway`` — fired before
                       the quota check, so an injected fault is shed
                       upstream and no engine state mutates)
``dist.worker``        each fixpoint round of a MULTI-PROCESS fit, on
                       every worker (fired before the round's
                       collective, so plans scoped to one worker's
                       PYPARDIS_FAULTS kill/stall that worker mid-
                       fixpoint — the pod fault drill: tear down the
                       fleet, relaunch with ``train(resume=)``, labels
                       byte-identical)
===================== ====================================================

Zero-cost when unset: ``maybe_fail`` is one module-global ``is None``
check — no parsing, no counters, nothing observable on a clean run
(``report()["faults"]["injected"] == 0`` is schema-enforced on bench
rows).
"""

from __future__ import annotations

import contextlib
import re
import time
from typing import Dict, List, Optional, Tuple
from . import envreg

_KINDS = ("transfer_error", "oom", "error", "hang")

# The machine-readable site registry (the docstring table above is the
# prose twin).  graftlint's fault-site rule (R6) fails CI on any
# maybe_fail/transfer/plan literal not declared here AND on any entry
# here with no surviving injection site — this tuple can neither rot
# nor drift the way the prose table once silently missed
# ``gm.execute`` / ``gm.chained_range``.
KNOWN_SITES = (
    "staging.device_put",
    "pipeline.cluster",
    "stepped.batch",
    "chained.partition",
    "sharded.execute",
    "gm.exchange",
    "gm.ring_round",
    "gm.fixpoint_round",
    "gm.execute",
    "gm.chained_range",
    "serve.drain",
    "ingest.batch",
    "compact.phase",
    "gateway.admit",
    "dist.worker",
)

_ENTRY_RE = re.compile(
    r"^(?P<site>[a-z0-9_.]+?)(?::(?P<occ>\*|\d+))?="
    r"(?P<kind>[a-z_]+)(?:\((?P<arg>[^)]*)\))?$"
)


class FaultInjected(RuntimeError):
    """An injected fault (never raised on a clean run).

    The message embeds the runtime error-class signature the kind
    imitates, so the production retry/degradation classifiers treat it
    exactly like the real failure.
    """

    def __init__(self, site: str, kind: str, message: str):
        super().__init__(message)
        self.site = site
        self.kind = kind


class FaultPlan:
    """Parsed injection plan with per-site arrival counters."""

    def __init__(self, entries: Dict[str, List[Tuple[object, str, float]]],
                 spec: str):
        # site -> [(occurrence | "*", kind, arg), ...]
        self.entries = entries
        self.spec = spec
        self._arrivals: Dict[str, int] = {}
        self.injected: Dict[str, int] = {}

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        entries: Dict[str, List[Tuple[object, str, float]]] = {}
        for raw in str(spec).split(","):
            raw = raw.strip()
            if not raw:
                continue
            m = _ENTRY_RE.match(raw)
            if not m:
                raise ValueError(
                    f"bad PYPARDIS_FAULTS entry {raw!r}; expected "
                    f"site[:occurrence]=kind[(arg)], e.g. "
                    f"gm.ring_round:2=transfer_error or "
                    f"serve.drain:1=hang(3s)"
                )
            kind = m.group("kind")
            if kind not in _KINDS:
                raise ValueError(
                    f"unknown fault kind {kind!r} in {raw!r}; one of "
                    f"{'|'.join(_KINDS)}"
                )
            occ: object = m.group("occ") or "1"
            if occ != "*":
                occ = int(occ)
                if occ < 1:
                    raise ValueError(
                        f"occurrence must be >= 1 or '*' in {raw!r}"
                    )
            arg = 0.0
            if m.group("arg"):
                arg = float(m.group("arg").rstrip("s"))
            entries.setdefault(m.group("site"), []).append(
                (occ, kind, arg)
            )
        return cls(entries, str(spec))

    def check(self, site: str) -> None:
        rules = self.entries.get(site)
        if rules is None:
            return
        n = self._arrivals.get(site, 0) + 1
        self._arrivals[site] = n
        for occ, kind, arg in rules:
            if occ == "*" or occ == n:
                self._fire(site, kind, arg, n)

    def _fire(self, site: str, kind: str, arg: float, occurrence: int
              ) -> None:
        self.injected[site] = self.injected.get(site, 0) + 1
        # Telemetry before the raise: the fit's recorder counts every
        # injection (report()["faults"]["injected"]); the event names
        # the site so a flight replay shows exactly where it landed.
        try:
            from ..obs import current, event

            current().metrics.inc("faults.injected")
            # NB: the event() helper's positional is named ``kind`` —
            # the injected fault's kind rides as ``fault_kind``.
            event("fault_injected", site=site, fault_kind=kind,
                  occurrence=occurrence)
        except Exception:  # noqa: BLE001 — injection must not need obs
            pass
        from .log import get_logger

        get_logger().warning(
            "fault injection: %s at %s (occurrence %d)",
            kind, site, occurrence,
        )
        if kind == "hang":
            time.sleep(max(arg, 0.0))
            return
        if kind == "transfer_error":
            raise FaultInjected(
                site, kind,
                f"UNAVAILABLE: injected transfer_error at {site} "
                f"(PYPARDIS_FAULTS occurrence {occurrence})",
            )
        if kind == "oom":
            raise FaultInjected(
                site, kind,
                f"RESOURCE_EXHAUSTED: injected oom at {site}: Out of "
                f"memory (PYPARDIS_FAULTS occurrence {occurrence})",
            )
        raise FaultInjected(
            site, kind,
            f"injected terminal error at {site} "
            f"(PYPARDIS_FAULTS occurrence {occurrence})",
        )


# The active plan.  None on clean runs — maybe_fail's entire cost is
# this one check.
_PLAN: Optional[FaultPlan] = None


def _init_from_env() -> None:
    global _PLAN
    spec = envreg.raw("PYPARDIS_FAULTS")
    if spec:
        _PLAN = FaultPlan.parse(spec)


_init_from_env()


def install(spec: Optional[str]) -> Optional[FaultPlan]:
    """Install a plan programmatically (None clears); returns it.
    Arrival counters start fresh — reinstalling the same spec replays
    the same injections."""
    global _PLAN
    _PLAN = FaultPlan.parse(spec) if spec else None
    return _PLAN


def clear() -> None:
    install(None)


def active() -> Optional[FaultPlan]:
    return _PLAN


@contextlib.contextmanager
def plan(spec: str):
    """Scoped plan for tests: installed on entry, previous plan (almost
    always None) restored on exit."""
    global _PLAN
    prev = _PLAN
    _PLAN = FaultPlan.parse(spec)
    try:
        yield _PLAN
    finally:
        _PLAN = prev


def maybe_fail(site: str) -> None:
    """The injection hook: a no-op unless a plan names this site."""
    if _PLAN is None:
        return
    _PLAN.check(site)


def fault_stats() -> Dict[str, int]:
    """{site -> injections fired} for the active plan ({} when none)."""
    return dict(_PLAN.injected) if _PLAN is not None else {}
