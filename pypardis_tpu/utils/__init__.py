"""Shared utilities: shape arithmetic, metrics, checkpointing."""

from .shaping import clamp_block, round_up

__all__ = ["round_up", "clamp_block"]
