"""Shared utilities: shape arithmetic, validation, metrics,
checkpointing."""

from .shaping import clamp_block, round_up
from .validate import check_query_points, validate_params

__all__ = [
    "round_up", "clamp_block", "validate_params", "check_query_points",
]
