"""Shared utilities: shape arithmetic, validation, metrics,
checkpointing."""

from .shaping import clamp_block, round_up
from .validate import validate_params

__all__ = ["round_up", "clamp_block", "validate_params"]
