"""Bounded cache of sufficient live tile-pair budgets.

The pair extraction's static budget (``ops.distances.live_tile_pairs``)
is a compile-time shape: a dataset dense enough to defeat the default
budget pays an extract-overflow-rerun (plus a 30-300s recompile) on the
first fit.  This cache remembers the exact budget that sufficed, keyed
by (shape, block, precision, eps, metric), so later fits of the same
configuration compile the right program the first time.

Seeding policy (round-3 advisor finding): entries are written ONLY when
an overflow was actually observed.  Seeding after every fit made the
hint a *new* static value for configurations whose default budget was
fine, recompiling the whole cluster program on the second fit of
everything — the exact cost the hint exists to avoid.

The cache is LRU-bounded: one long-lived process sweeping eps values or
fitting many shapes must not leak an unbounded dict (each entry is tiny,
but the single-shard staging buffer keeps only the latest shape for the
same reason).
"""

from __future__ import annotations

from typing import Hashable, Optional


class BudgetHintCache:
    """Insertion-ordered dict with LRU eviction past ``maxsize``."""

    def __init__(self, maxsize: int = 64):
        self.maxsize = int(maxsize)
        self._d: dict = {}

    def get(self, key: Hashable) -> Optional[int]:
        val = self._d.pop(key, None)
        if val is not None:
            self._d[key] = val  # refresh recency
        return val

    def put(self, key: Hashable, value: int) -> None:
        self._d.pop(key, None)
        self._d[key] = int(value)
        while len(self._d) > self.maxsize:
            self._d.pop(next(iter(self._d)))

    def clear(self) -> None:
        self._d.clear()

    def __len__(self) -> int:
        return len(self._d)

    def __contains__(self, key: Hashable) -> bool:
        return key in self._d


def dispatch_tag(nt: int | None = None) -> str:
    """Hint-key component naming the kernel dispatch mode.

    The compacted pair-list dispatch and the dense grid size their
    budgets against different effective grids — a budget learned under
    dense dispatch over-reserves the compacted kernels' static budget
    (and a pair-mode budget can undershoot the dense-era pallas-parity
    grid) — so every hint key carries the mode and entries never cross
    it.  ``nt``: the caller's slab tile-count estimate for the
    auto-by-size policy (a pre-segment-break estimate may disagree
    with the kernel's post-break decision in a narrow band around the
    threshold; the only cost is a missed hint, i.e. one extra
    overflow rerun, never a wrong budget).  Lazy import: ops.distances
    owns the env knob.
    """
    from ..ops.distances import pair_dispatch_enabled

    return "pair" if pair_dispatch_enabled(nt) else "dense"


# One shared instance: the single-shard driver (dbscan._pad_and_run) and
# the sharded driver (parallel.sharded.sharded_dbscan) key their entries
# differently, so they coexist without collisions.
PAIR_BUDGET_HINTS = BudgetHintCache()
