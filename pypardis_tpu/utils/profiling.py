"""Per-phase timing + device tracing.

The reference has no instrumentation at all — its only observability was
the Spark web UI and a dead ``LOGGING`` flag (reference dbscan.py:9,
SURVEY §5).  Here the driver phases (partition / shard / cluster / merge)
report wall time through :class:`PhaseTimer`, and :func:`trace` wraps
``jax.profiler`` so a device trace of the whole pipeline is one context
manager away (view in TensorBoard / Perfetto).
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict


class PhaseTimer:
    """Accumulate named phase durations.

    >>> t = PhaseTimer()
    >>> with t.phase("cluster") as p:
    ...     labels = kernel(...)
    ...     p.sync_on(labels)        # time includes device execution
    >>> t.as_dict()  # {"cluster_s": 0.123}

    ``sync_on(arrays)`` blocks on the phase's actual outputs — the
    reliable way to include async-dispatched device work.  ``sync=True``
    instead issues a trivial transfer barrier per device on exit; TPU
    devices execute in order so that bounds prior compute there, but on
    out-of-order backends prefer ``sync_on``.
    """

    def __init__(self, sync: bool = False):
        self.phases: Dict[str, float] = {}
        self._sync = sync
        self._pending = None

    def sync_on(self, arrays) -> None:
        """Register this phase's outputs to block on at phase exit."""
        self._pending = arrays

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield self
        finally:
            import jax

            if self._pending is not None:
                jax.block_until_ready(self._pending)
                self._pending = None
            elif self._sync:
                for dev in jax.devices():
                    jax.device_put(0, dev).block_until_ready()
            self.phases[f"{name}_s"] = self.phases.get(
                f"{name}_s", 0.0
            ) + (time.perf_counter() - t0)

    def as_dict(self) -> Dict[str, float]:
        return dict(self.phases)


@contextlib.contextmanager
def trace(logdir: str):
    """Capture a jax.profiler device trace of the enclosed block."""
    import jax

    with jax.profiler.trace(logdir):
        yield
