"""Per-phase timing + device tracing (now backed by :mod:`..obs`).

The reference has no instrumentation at all — its only observability was
the Spark web UI and a dead ``LOGGING`` flag (reference dbscan.py:9,
SURVEY §5).  :class:`PhaseTimer` keeps its original API (the drivers and
tests use it), but every phase now also lands in the unified telemetry
layer: a span in the current :class:`~pypardis_tpu.obs.RunRecorder`'s
tracer (Chrome-trace exportable) and a ``phase.<name>`` timing in its
metrics registry — and, when the fit has a flight recorder attached
(``DBSCAN(flight=...)`` / ``PYPARDIS_FLIGHT``), both stream to the
crash-safe JSONL file as they happen: the span open lands on disk when
the phase STARTS, so a killed run's post-mortem shows which phase it
died in (:mod:`pypardis_tpu.obs.flight`).  :func:`trace` still wraps ``jax.profiler`` so a
device-level trace of the whole pipeline is one context manager away
(view in TensorBoard / Perfetto) — the obs tracer is the cheap,
always-on driver's-eye complement.
"""

from __future__ import annotations

import contextlib
from typing import Dict

# graftlint: disable=unused-import -- back-compat re-export surface
from ..obs import MetricsRegistry, RunRecorder, Tracer


class PhaseTimer:
    """Accumulate named phase durations.

    >>> t = PhaseTimer()
    >>> with t.phase("cluster") as p:
    ...     labels = kernel(...)
    ...     p.sync_on(labels)        # time includes device execution
    >>> t.as_dict()  # {"cluster_s": 0.123}

    ``sync_on(arrays)`` blocks on the phase's actual outputs — the
    reliable way to include async-dispatched device work.  ``sync=True``
    instead issues a trivial transfer barrier per device on exit; TPU
    devices execute in order so that bounds prior compute there, but on
    out-of-order backends prefer ``sync_on``.
    """

    def __init__(self, sync: bool = False):
        self.phases: Dict[str, float] = {}
        self._sync = sync
        self._pending = None

    def sync_on(self, arrays) -> None:
        """Register this phase's outputs to block on at phase exit."""
        self._pending = arrays

    @contextlib.contextmanager
    def phase(self, name: str):
        from ..obs import current

        rec = current()
        with rec.span(name, sync=self._sync) as sp:
            try:
                yield self
            finally:
                if self._pending is not None:
                    sp.sync_on(self._pending)
                    self._pending = None
        # sp.dur_s is set once the span context closed (after any sync).
        self.phases[f"{name}_s"] = (
            self.phases.get(f"{name}_s", 0.0) + sp.dur_s
        )
        from ..obs.registry import sanitize_segment

        rec.metrics.observe(f"phase.{sanitize_segment(name)}", sp.dur_s)

    def as_dict(self) -> Dict[str, float]:
        return dict(self.phases)


@contextlib.contextmanager
def trace(logdir: str):
    """Capture a jax.profiler device trace of the enclosed block."""
    import jax

    with jax.profiler.trace(logdir):
        yield
