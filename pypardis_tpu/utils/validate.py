"""Hyperparameter validation shared by the drivers and kernel entry
points.

The reference inherits sklearn's input contract (reject bad
hyperparameters loudly); this repro silently accepted ``eps <= 0`` —
the kernels compare SQUARED distances, so ``eps=-0.3`` behaved exactly
like ``eps=0.3`` — and non-finite eps produced all-noise labels.  One
validator, called by ``DBSCAN.train`` with the concrete values and by
``ops.labels.dbscan_fixed_size`` defensively (tracers pass through
unchecked; their driver already validated).
"""

from __future__ import annotations

import numpy as np


def check_query_points(points, k=None) -> np.ndarray:
    """Validate an out-of-sample query array against a fitted tree.

    A wrong-dimensionality array would route through split axes that
    mean something else entirely, and a NaN coordinate fails every
    ``>=`` comparison and silently drifts down the left spine of the
    tree — both came back as garbage labels instead of an error.
    Returns the array as numpy; raises ValueError otherwise.
    """
    points = np.asarray(points)
    if points.ndim != 2:
        raise ValueError(
            f"query points must be a 2-D (N, k) array, got shape "
            f"{points.shape}"
        )
    if k is not None and points.shape[1] != int(k):
        raise ValueError(
            f"query dimensionality {points.shape[1]} does not match the "
            f"fitted tree's k={int(k)}"
        )
    if points.dtype.kind in "fc" and not np.isfinite(points).all():
        raise ValueError("query points contain NaN or infinite coordinates")
    return points


def check_precision(precision) -> str:
    """Validate a kernel precision spec; returns the canonical mode.

    Accepts the mode strings (``default``/``high``/``highest``/
    ``mixed``, any case) and ``jax.lax.Precision`` values, raising the
    shared normalizer's ValueError otherwise — so a typo'd
    ``DBSCAN(precision="hgih")`` fails at construction with the
    allowed list, not deep inside a jit trace at first fit.
    """
    from ..ops.precision import norm_precision_mode

    return norm_precision_mode(precision)


def check_kernel_backend(backend) -> str:
    """Validate a kernel backend spec (``auto``/``xla``/``pallas``)."""
    b = str(backend).lower()
    if b not in ("auto", "xla", "pallas"):
        raise ValueError(
            f"kernel_backend must be one of ('auto', 'xla', 'pallas'), "
            f"got {backend!r}"
        )
    return b


def check_metric(metric, eps=None) -> str:
    """Normalize/validate a DBSCAN metric spec at construction time.

    Accepts the kernel metrics (euclidean/cityblock spellings and
    scipy callables, via the kernels' own normalizer) plus
    ``"cosine"``/``"angular"`` — a DRIVER metric: the fit path
    unit-normalizes rows and remaps eps onto the L2 kernels (on the
    unit sphere ``d^2 = 2 - 2 cos(theta)``), so the kernels never see
    it.  For cosine, ``eps`` thresholds the cosine distance ``1 -
    cos`` and must lie in (0, 2] — a threshold past 2 would accept
    antipodal pairs of every orientation, which is always a spec bug.
    """
    name = metric
    if callable(metric):
        name = getattr(metric, "__name__", str(metric))
    name = str(name).lower()
    if name == "haversine":
        # Driver metric for trajectories: (lat, lon) radians embed
        # onto the 3-D unit sphere and the great-circle eps remaps to
        # the chord ``2 sin(eps / 2)`` for the L2 kernels
        # (geometry.latlon_to_unit_sphere).  eps is the great-circle
        # ANGLE in radians — the sklearn haversine convention (scale
        # by the sphere radius outside); past pi every pair qualifies,
        # which is always a spec bug (degrees passed as radians, most
        # likely).
        if eps is not None and isinstance(
            eps, (int, float, np.floating)
        ) and np.isfinite(eps) and not 0 < eps <= np.pi:
            raise ValueError(
                f"metric='haversine' thresholds the great-circle "
                f"angle in RADIANS, which lies in [0, pi]; eps must "
                f"be in (0, pi], got {eps} (degrees instead of "
                f"radians?)"
            )
        return "haversine"
    if name in ("cosine", "angular"):
        if eps is not None and isinstance(
            eps, (int, float, np.floating)
        ) and np.isfinite(eps) and not 0 < eps <= 2:
            raise ValueError(
                f"metric='cosine' thresholds the cosine distance "
                f"1 - cos(theta), which lies in [0, 2]; eps must be in "
                f"(0, 2], got {eps}"
            )
        return "cosine"
    from ..ops.distances import _norm_metric

    return _norm_metric(metric)


def validate_params(eps, min_samples, allow_none_eps: bool = False) -> None:
    """Raise ValueError on an invalid concrete (eps, min_samples).

    Values that are not plain numbers (jax tracers on the in-jit call
    sites) are skipped — validation happens once, host-side, with the
    concrete hyperparameters.

    ``eps=None`` rule (density hierarchy): ``None`` is legal ONLY where
    ``allow_none_eps=True`` — the ``DBSCAN`` constructor and the
    fit-time hierarchy path, which selects eps by HDBSCAN*'s stability
    rule and exposes it as ``eps_``.  Everywhere downstream of a fit
    (``predict``/serving/``query_engine``) a concrete radius is
    required and comes from that stability-selected ``eps_``; a
    concrete ``eps <= 0`` or non-finite value still fails loudly at
    construction regardless of ``allow_none_eps``.
    """
    if isinstance(min_samples, (int, np.integer)) and min_samples < 1:
        raise ValueError(f"min_samples must be >= 1, got {min_samples}")
    if eps is None:
        if allow_none_eps:
            return
        raise ValueError(
            "eps=None is only legal at construction/fit time (the "
            "density-hierarchy path selects eps by stability); this "
            "call site needs a concrete positive radius"
        )
    if isinstance(eps, (int, float, np.floating)):
        if not np.isfinite(eps) or eps <= 0:
            raise ValueError(f"eps must be positive and finite, got {eps}")
