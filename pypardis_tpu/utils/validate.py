"""Hyperparameter validation shared by the drivers and kernel entry
points.

The reference inherits sklearn's input contract (reject bad
hyperparameters loudly); this repro silently accepted ``eps <= 0`` —
the kernels compare SQUARED distances, so ``eps=-0.3`` behaved exactly
like ``eps=0.3`` — and non-finite eps produced all-noise labels.  One
validator, called by ``DBSCAN.train`` with the concrete values and by
``ops.labels.dbscan_fixed_size`` defensively (tracers pass through
unchecked; their driver already validated).
"""

from __future__ import annotations

import numpy as np


def validate_params(eps, min_samples) -> None:
    """Raise ValueError on an invalid concrete (eps, min_samples).

    Values that are not plain numbers (jax tracers on the in-jit call
    sites) are skipped — validation happens once, host-side, with the
    concrete hyperparameters.
    """
    if isinstance(min_samples, (int, np.integer)) and min_samples < 1:
        raise ValueError(f"min_samples must be >= 1, got {min_samples}")
    if isinstance(eps, (int, float, np.floating)):
        if not np.isfinite(eps) or eps <= 0:
            raise ValueError(f"eps must be positive and finite, got {eps}")
