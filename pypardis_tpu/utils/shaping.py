"""Static-shape arithmetic shared by the host-side drivers.

XLA compiles one program per shape, so every capacity in the framework is
rounded to a tile-block multiple; these helpers are the single home for
that arithmetic.
"""

from __future__ import annotations


def round_up(x: int, multiple: int) -> int:
    """Smallest multiple of ``multiple`` >= x."""
    return -(-x // multiple) * multiple


def clamp_block(block: int, n: int, floor: int = 128) -> int:
    """Shrink a tile block for small problems, keep MXU width for big ones.

    Returns a power-of-two-ish block <= ``block`` that is no wider than
    the problem needs (next power of two above ``n``) and no narrower
    than ``floor`` (a full lane tile).
    """
    return min(block, max(floor, 1 << (max(n, 1) - 1).bit_length()))
