"""The single registry of every ``PYPARDIS_*`` environment variable.

Before this module existed the project had ~40 ``PYPARDIS_*`` knobs
read at 37 sites with no central declaration: a typo'd name silently
fell back to its default (the reader can't tell "unset" from
"misspelled"), new knobs documented themselves only in CHANGES.md
prose, and one knob (``PYPARDIS_GM_BTCAP``) was *named in an error
message as the remedy* while nothing ever read it.  The graftlint R4
rule (``env-registry``) now fails CI on any ``PYPARDIS_*`` literal not
declared here, and the README "Environment variables" table is
generated from this registry (``scripts/graftlint.py --envdocs``) so
the docs cannot drift from the code.

Trace-time semantics (the R3 ``trace-env-read`` contract)
---------------------------------------------------------

:func:`raw` reads the LIVE process environment at call time.  When the
calling function runs inside a ``jax.jit`` / ``shard_map`` / ``pjit``
trace (directly or transitively — e.g. the ``PYPARDIS_DISPATCH`` read
in ``ops.distances.pair_dispatch_enabled``), the value read is **baked
into the compiled program**: flipping the variable afterwards does NOT
change already-compiled programs, only ones traced later (callers must
``jax.clear_caches()`` to re-resolve — the PR 11 dispatch lesson).
Routing every such read through this module is what lets graftlint
R3 distinguish a *documented* trace-time read from an accidental one:
direct ``os.environ`` reads inside jit-reachable functions fail lint.

The registry is parsed STATICALLY by the analysis package
(``pypardis_tpu.analysis.envmodel``) — keep every :class:`EnvVar`
field a literal (no computed names, defaults, or docs).
"""

from __future__ import annotations

import difflib
import os
from dataclasses import dataclass
from typing import Dict, Optional, Tuple


@dataclass(frozen=True)
class EnvVar:
    """One declared knob: its name, value type, default, and one-line doc.

    ``default`` is the *documented* default rendered in the README
    table — a human-readable spelling (``"auto"``, ``"0 (off)"``,
    ``"~/.cache/pypardis_tpu/xla"``), not necessarily the literal the
    reading site passes to :func:`raw` (sites keep their exact
    historical parsing so the migration is value-identical).
    """

    name: str
    type: str  # str | int | float | bool | path | spec
    default: str
    doc: str


# Declaration order is the README table order: grouped by subsystem,
# alphabetical within a group.  Every field must stay a literal — the
# static checker reads this file with ast, it never imports it.
_DECLARATIONS: Tuple[EnvVar, ...] = (
    # -- kernels / dispatch -------------------------------------------
    EnvVar("PYPARDIS_DISPATCH", "str", "auto",
           "Kernel tile-pair dispatch: `auto` compacts past the tile "
           "threshold, `pair` forces the live-pair list, `dense` the "
           "T² scan (read at TRACE time; flip needs "
           "`jax.clear_caches()`)."),
    EnvVar("PYPARDIS_PAIR_DISPATCH_TILES", "int", "2048",
           "Tile count past which `dispatch=auto` compacts to the "
           "live tile-pair list."),
    EnvVar("PYPARDIS_PAIR_BUDGET", "int", "unset (auto ladder)",
           "Process-wide live tile-pair budget pin; skips the "
           "overflow-rerun recompile on known-dense deployments."),
    EnvVar("PYPARDIS_STEP_THRESHOLD", "int", "33554432",
           "Point count past which the fused single-shard route "
           "switches to host-stepped propagation rounds."),
    EnvVar("PYPARDIS_ROUND_BATCH", "int", "8",
           "Propagation rounds per host-stepped dispatch batch."),
    EnvVar("PYPARDIS_STEP_OVERLAP", "bool", "auto (off on TPU)",
           "Speculative next-batch dispatch on the stepped route; "
           "queued re-execution poisons tunneled TPU workers."),
    # -- sketch prefilter ---------------------------------------------
    EnvVar("PYPARDIS_SKETCH", "spec", "auto",
           "Random-projection sketch prefilter for the distance "
           "pass: `auto` picks k from the dimensionality, an integer "
           "pins k, `0`/`off` disables (read at TRACE time; flip "
           "needs `jax.clear_caches()`)."),
    EnvVar("PYPARDIS_SKETCH_DELTA", "float", "0.01",
           "JL failure probability the PREDICTIVE `jl_band` "
           "halfwidth is quoted at (planner/telemetry only; the "
           "kernel gate uses the certified bound)."),
    EnvVar("PYPARDIS_SKETCH_MIN_D", "int", "128",
           "Dimensionality below which `sketch=auto` resolves to "
           "off (low-d tiles prune fine with full-d boxes)."),
    EnvVar("PYPARDIS_SKETCH_SEED", "int", "1299721",
           "Seed of the sparse random-projection matrix; fixed per "
           "(d, k, seed) so sketches are reproducible across hosts."),
    # -- distributed execution ----------------------------------------
    EnvVar("PYPARDIS_DIST_COORD", "str", "unset (single-process)",
           "jax.distributed coordinator address (`host:port`); set on "
           "every worker of a multi-process fleet, unset runs the "
           "classic single-process path."),
    EnvVar("PYPARDIS_DIST_NPROCS", "int", "unset (single-process)",
           "Total controller processes in the fleet "
           "(`jax.distributed.initialize(num_processes=)`)."),
    EnvVar("PYPARDIS_DIST_PROC_ID", "int", "unset (single-process)",
           "This worker's rank in [0, PYPARDIS_DIST_NPROCS); process "
           "0 is the coordinator (writes jobstate snapshots and the "
           "shared spill dir for the whole fleet)."),
    EnvVar("PYPARDIS_CHAINED_OVERLAP", "bool", "1",
           "Double-buffered host build/ship overlap on the 1-device "
           "chained route."),
    EnvVar("PYPARDIS_GM_BTCAP", "int", "unset (auto ladder)",
           "Explicit global-Morton boundary-tile send capacity per "
           "device; unset uses the metadata plan + doubling ladder."),
    EnvVar("PYPARDIS_GM_CHAIN", "int", "0",
           "On a 1-device mesh, chain this many global-Morton ranges "
           "through the single chip."),
    EnvVar("PYPARDIS_GM_OVERLAP", "bool", "1",
           "Hide global-Morton ring rounds behind the owned-prefix "
           "counts pass."),
    EnvVar("PYPARDIS_GM_SEGBREAK", "bool", "1",
           "Segment-break padding of global-Morton shard slabs (off "
           "leaks live pairs vs KD boxes)."),
    # -- out-of-core / streaming builds -------------------------------
    EnvVar("PYPARDIS_SPILL_DIR", "path", "system tempdir",
           "Parent directory for the external sample-sort's "
           "tempdir-scoped spill files."),
    EnvVar("PYPARDIS_STREAM_BUCKET_MB", "float", "32",
           "Target spill-bucket size for the streaming Morton build "
           "(<= 512 buckets)."),
    # -- sweeps -------------------------------------------------------
    EnvVar("PYPARDIS_SWEEP_EDGE_BUDGET", "int", "unset (96/row)",
           "Neighbor-pair graph edge capacity for `DBSCAN.sweep`; "
           "seeds the exact-total retry ladder."),
    EnvVar("PYPARDIS_SWEEP_EMISSION", "str", "auto",
           "Sweep-graph pair-emission route: `host`, `device`, or "
           "`auto` (host on CPU, device elsewhere)."),
    EnvVar("PYPARDIS_SWEEP_MAX_PAIRS", "int", "67108864",
           "Hard cap on the sweep graph slab in edges; past it the "
           "sweep degrades label-safely to per-config refits."),
    # -- density hierarchy (eps=None fits) ----------------------------
    EnvVar("PYPARDIS_HIER_EPS_MAX", "float", "unset (sample-kNN x4)",
           "USER-frame ceiling for the eps=None pair graph; unset "
           "derives it from a strided sample-kNN overestimate."),
    EnvVar("PYPARDIS_HIER_LADDER_K", "int", "8",
           "Rungs `sweep(eps_list=\"auto\")` extracts from the "
           "dendrogram (top-stability cuts, ascending eps)."),
    EnvVar("PYPARDIS_HIER_SAMPLE", "int", "2048",
           "Strided sample rows for the eps=None ceiling heuristic "
           "(deterministic; larger = tighter ceiling, slower probe)."),
    # -- caches -------------------------------------------------------
    EnvVar("PYPARDIS_COMPILE_CACHE", "path", "~/.cache/pypardis_tpu/xla",
           "Persistent XLA compilation cache directory; empty "
           "disables."),
    EnvVar("PYPARDIS_LAYOUT_CACHE", "bool", "1",
           "Single-shard device layout cache (warm refits skip "
           "staging + Morton sort)."),
    EnvVar("PYPARDIS_LAYOUT_CACHE_MAX", "int", "536870912",
           "Per-entry byte ceiling for the layout cache."),
    # -- checkpoint / resume ------------------------------------------
    EnvVar("PYPARDIS_CKPT", "path", "unset",
           "Checkpoint-resume npz path for fits (same as "
           "`train(resume=...)`)."),
    EnvVar("PYPARDIS_CKPT_EVERY_S", "float", "0",
           "Minimum seconds between phase-boundary checkpoint "
           "snapshots (0 = every boundary)."),
    # -- ingest / compaction ------------------------------------------
    EnvVar("PYPARDIS_COMPACT_DELTAS", "int", "512",
           "Compact once this many write deltas landed since the "
           "last index generation swap."),
    EnvVar("PYPARDIS_COMPACT_SLAB_BYTES", "int", "67108864",
           "Compact once the index's appended slabs hold this many "
           "bytes."),
    # -- serving gateway ----------------------------------------------
    EnvVar("PYPARDIS_GATEWAY_BUDGET_BYTES", "int", "0 (unlimited)",
           "Device-slab byte budget across a gateway's resident "
           "model indexes; registering past it evicts LRU models "
           "(save_index spill, byte-identical reload on demand)."),
    EnvVar("PYPARDIS_GATEWAY_EVICTION", "str", "lru",
           "Gateway eviction policy under budget pressure: `lru` "
           "(least recently served first) or `largest` (biggest "
           "resident index first)."),
    EnvVar("PYPARDIS_GATEWAY_SPILL_DIR", "path",
           "~/.cache/pypardis_tpu/gateway",
           "Directory for evicted-model index spills (one npz per "
           "evicted model, reloaded byte-identical on readmission)."),
    EnvVar("PYPARDIS_GATEWAY_TENANT_BURST", "float", "8",
           "Default token-bucket burst capacity (requests) per "
           "tenant — how far a tenant may briefly exceed its QPS "
           "quota."),
    EnvVar("PYPARDIS_GATEWAY_TENANT_QPS", "float", "0 (unlimited)",
           "Default per-tenant admission quota in requests/s "
           "(token bucket); 0 disables quota shedding for tenants "
           "without an explicit quota."),
    # -- fault tolerance ----------------------------------------------
    EnvVar("PYPARDIS_FAULTS", "spec", "unset",
           "Deterministic fault-injection plan: "
           "`site[:occurrence]=kind[(arg)]`, comma-separated."),
    EnvVar("PYPARDIS_RETRY_DEADLINE_S", "float", "unset",
           "Wall-clock deadline across a retry ladder's attempts."),
    # -- observability ------------------------------------------------
    EnvVar("PYPARDIS_FLEET_SKEW_WARN_S", "float", "5",
           "FleetReplay clock-skew warning threshold: member flight "
           "files whose `t_unix` anchors spread wider than this flag "
           "`clock_skew_warning` in the fleet report."),
    EnvVar("PYPARDIS_FLIGHT", "path", "unset",
           "Flight-recorder JSONL file (or directory for one file "
           "per fit); unset disables."),
    EnvVar("PYPARDIS_FLIGHT_FLUSH_S", "float", "0.25",
           "Flight-recorder flush interval (spans/events flush "
           "eagerly regardless)."),
    EnvVar("PYPARDIS_HEARTBEAT", "float", "0 (off)",
           "Minimum gap between heartbeat log lines with ETA; "
           "0/unset logs none (flight records always carry them)."),
    EnvVar("PYPARDIS_HIST_WINDOW_S", "float", "60",
           "Sliding-window width for latency-histogram percentiles "
           "(serving/load/ingest p50/p99 answer over this window)."),
    EnvVar("PYPARDIS_METRICS_PORT", "int", "unset (off)",
           "OpenMetrics scrape endpoint port on 127.0.0.1 "
           "(`/metrics`); `0` binds an ephemeral port."),
    EnvVar("PYPARDIS_METRICS_SNAPSHOT", "path", "unset",
           "Periodic JSONL metrics-snapshot file appended during "
           "fits and load harnesses; unset disables."),
    EnvVar("PYPARDIS_METRICS_SNAPSHOT_S", "float", "0.5",
           "Metrics-snapshot emit interval in seconds."),
    EnvVar("PYPARDIS_PEAK_FLOPS", "float", "per-backend table",
           "Chip peak FLOP/s override for the MFU gauge."),
    EnvVar("PYPARDIS_RESOURCE_INTERVAL_S", "float", "0.2",
           "Resource-watermark sampler period."),
    EnvVar("PYPARDIS_RSS_SOFT_LIMIT", "int", "0 (off)",
           "Host-RSS soft watermark in bytes; crossing it flips "
           "`merge='auto'` to the host-spill rung preemptively."),
    # -- validation ---------------------------------------------------
    EnvVar("PYPARDIS_SKIP_FINITE_CHECK", "bool", "0",
           "Skip the NaN/inf input scan for trusted pipelines."),
    # -- auto-tuning --------------------------------------------------
    EnvVar("PYPARDIS_TUNE_CORPUS", "path",
           "~/.cache/pypardis_tpu/tuning_corpus.jsonl",
           "Local auto-fit telemetry corpus JSONL; `0`/empty "
           "disables the feedback loop."),
    EnvVar("PYPARDIS_TUNE_ROOT", "path", "unset",
           "Extra directory scanned for committed benchmark archives "
           "when harvesting the tuning corpus."),
    EnvVar("PYPARDIS_TUNE_SAMPLE", "int", "unset (adaptive)",
           "Auto-tune probe sample rows; unset picks "
           "min(32768, max(4096, n/16))."),
    # -- data ---------------------------------------------------------
    EnvVar("PYPARDIS_DATA_DIR", "path", "~/.cache/pypardis_tpu/data",
           "Cache directory for checksum-verified real-dataset "
           "downloads."),
    # -- bench / CI harness -------------------------------------------
    EnvVar("PYPARDIS_BENCH_DIFF_THR", "float", "0.05",
           "bench_diff regression threshold on the best-of-N delta "
           "between disjoint sample ranges."),
    EnvVar("PYPARDIS_PROBE_DEVICES", "int", "8",
           "Faked CPU-mesh device count the probe scripts "
           "configure."),
    EnvVar("PYPARDIS_PROBE_PLATFORM", "str", "unset",
           "`native` makes probe scripts leave the ambient JAX "
           "platform alone (hardware runs)."),
    EnvVar("PYPARDIS_TEST_PLATFORM", "str", "unset",
           "`native` makes the test harness leave the ambient JAX "
           "platform alone (`make tpu-smoke`)."),
)

REGISTRY: Dict[str, EnvVar] = {v.name: v for v in _DECLARATIONS}
assert len(REGISTRY) == len(_DECLARATIONS), "duplicate EnvVar declaration"


class UnregisteredEnvVar(KeyError):
    """A ``PYPARDIS_*`` read of a name not declared in the registry."""


def _require(name: str) -> None:
    if name in REGISTRY:
        return
    hint = difflib.get_close_matches(name, REGISTRY, n=1)
    raise UnregisteredEnvVar(
        f"{name} is not declared in pypardis_tpu.utils.envreg"
        + (f" — did you mean {hint[0]}?" if hint else "")
    )


def raw(name: str, default: Optional[str] = None) -> Optional[str]:
    """``os.environ.get(name, default)`` for a REGISTERED knob.

    The one sanctioned read path: byte-identical to the direct read it
    replaces (callers keep their historical parsing of the returned
    string), plus the registration check that makes a typo'd name fail
    loudly instead of silently meaning "unset".  See the module
    docstring for the trace-time contract when called under a jit
    trace.
    """
    _require(name)
    return os.environ.get(name, default)


def declared_names() -> Tuple[str, ...]:
    """Registered names, declaration order."""
    return tuple(v.name for v in _DECLARATIONS)


def render_markdown() -> str:
    """The README "Environment variables" table body.

    ``scripts/graftlint.py --envdocs`` prints this; the R4 lint run
    fails when the committed README section differs, the same way
    ``check_bench_json`` pins the telemetry schema.
    """
    lines = [
        "| Variable | Type | Default | Meaning |",
        "| --- | --- | --- | --- |",
    ]
    for v in _DECLARATIONS:
        doc = " ".join(v.doc.split())
        lines.append(
            f"| `{v.name}` | {v.type} | `{v.default}` | {doc} |"
        )
    return "\n".join(lines) + "\n"
