"""Multi-tenant serving gateway: model registry, budgeted eviction,
per-tenant admission control.

Everything Clipper-shaped below this module serves ONE fitted model
per process (adaptive batching, bounded queues, deadline shedding —
:mod:`.engine`; epoch-swapped generations — :mod:`.ingest`).
Production traffic is many models x many clients; the
:class:`ModelGateway` turns the engine/index/live trio into a fleet by
*composition over model handles*, never special cases:

* **Registry + residency budget** — ``register(model_id, model)``
  builds the model's :class:`~.engine.QueryEngine` under a per-handle
  staging route (the ISSUE 19 refactor: ``handle`` threads through
  ``build_index``/``CorePointIndex``/``LiveModel``, so N resident
  indexes share the device cache without evicting each other).  A
  device-slab byte budget (``PYPARDIS_GATEWAY_BUDGET_BYTES``) is
  enforced across residents: registering or readmitting past it evicts
  models — ``lru`` (least recently served) or ``largest`` policy —
  by **spilling** the index via :func:`pypardis_tpu.checkpoint.
  save_index` and freeing its device slabs.  A request for an evicted
  model **readmits** it through ``load_index`` — slabs reload
  byte-identical, so the readmitted model serves answers bitwise equal
  to its pre-eviction self (asserted by ``make gateway-probe``).

* **Admission control** — every request passes one shared admission
  gate: a per-tenant token bucket (``qps`` quota + ``burst``,
  defaults from ``PYPARDIS_GATEWAY_TENANT_QPS`` / ``_BURST``)
  sheds over-quota tenants with :class:`TenantQuotaExceeded` *before*
  touching any engine, so one hot tenant cannot starve another's p99;
  the ``gateway.admit`` fault site (``PYPARDIS_FAULTS``) fires here,
  upstream of all engine state.  Deadline shedding rides the existing
  machinery: ``timeout_s`` flows to the engine's ticket deadline
  (:class:`~.engine.DeadlineExceeded`), a full queue raises
  :class:`~.engine.QueueFull`.

* **Hot swap** — ``refresh(model_id, model)`` installs a refreshed
  clustering through the :meth:`~.index.CorePointIndex.
  replace_generation` epoch-swap contract: drain in-flight tickets
  against the old generation, swap the fresh slabs in place, zero
  dropped tickets (the same pinned contract the Compactor honors).

* **Fleet telemetry** — :meth:`gateway_report` emits the schema'd
  ``pypardis_tpu/gateway_report@1`` block (per-tenant windowed latency
  :class:`~pypardis_tpu.obs.export.Histogram`\\ s,
  resident/evicted/reload counters, admission shed counts); the same
  numbers publish into the gateway's metrics registry under
  ``gateway.model.<id>.*`` / ``gateway.tenant.<id>.*`` keys, which the
  OpenMetrics exporter renders as ``model=``/``tenant=`` **labels** —
  one scrape shows the whole fleet.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

from ..obs.registry import sanitize_segment
from ..utils import envreg
from .engine import QueryEngine

GATEWAY_REPORT_SCHEMA = "pypardis_tpu/gateway_report@1"

# Documented defaults of the PYPARDIS_GATEWAY_* knobs (utils/envreg.py
# carries the registered declarations; constructor kwargs win).
DEFAULT_SPILL_DIR = "~/.cache/pypardis_tpu/gateway"
DEFAULT_TENANT_BURST = 8.0
EVICTION_POLICIES = ("lru", "largest")


def _env_num(name: str, default, cast):
    try:
        return cast(envreg.raw(name, default))
    except (TypeError, ValueError):
        return cast(default)


class GatewayError(RuntimeError):
    """Base of the gateway's refusal surface (admission, residency,
    staleness) — callers catch this to back off without touching
    engine internals."""


class ModelNotRegistered(GatewayError):
    """A request named a model this gateway has never seen."""


class TenantQuotaExceeded(GatewayError):
    """The shared admission controller refused a request: the tenant's
    token bucket is empty.  Counted per tenant (``admission_sheds``) —
    the isolation signal that keeps one hot tenant from starving
    another's p99."""


class StaleModelHandle(GatewayError):
    """The registered model was refit after registration; the resident
    index serves the PREVIOUS clustering.  The gateway refuses rather
    than silently serving stale answers — ``refresh()`` swaps the new
    generation in."""


class _TokenBucket:
    """Per-tenant admission quota: ``rate`` requests/s with ``burst``
    capacity; rate <= 0 admits everything (quota off)."""

    __slots__ = ("rate", "burst", "tokens", "t_last")

    def __init__(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = max(float(burst), 1.0)
        self.tokens = self.burst
        self.t_last = time.perf_counter()

    def try_take(self) -> bool:
        if self.rate <= 0:
            return True
        now = time.perf_counter()
        self.tokens = min(
            self.burst, self.tokens + (now - self.t_last) * self.rate
        )
        self.t_last = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


class ModelHandle:
    """One registered model's serving state inside the gateway: the
    explicit handle the refactored engine/index/live trio threads, plus
    the residency bookkeeping (spill path, byte size, eviction/reload
    counters) the budget enforcer reads."""

    __slots__ = (
        "model_id", "model", "engine", "index", "live",
        "pinned", "resident", "spill_path", "fit_generation",
        "index_bytes", "engine_kw", "evictions", "reloads", "swaps",
        "queries_done",
    )

    def __init__(self, model_id: str, model):
        self.model_id = str(model_id)
        self.model = model
        self.engine: Optional[QueryEngine] = None
        self.index = None
        self.live = None
        self.pinned = False
        self.resident = False
        self.spill_path: Optional[str] = None
        self.fit_generation = 0
        self.index_bytes = 0
        self.engine_kw: Dict = {}
        self.evictions = 0
        self.reloads = 0
        self.swaps = 0
        self.queries_done = 0


class ModelGateway:
    """Registry of resident fitted models behind one admission gate.

    ``budget_bytes`` caps the summed index slab bytes of resident
    models (0 = unlimited); ``eviction`` picks the victim policy
    (``lru``/``largest``).  ``tenant_qps``/``tenant_burst`` set the
    default per-tenant token bucket (override per tenant with
    :meth:`set_quota`).  ``engine_kw`` are the default
    :class:`~.engine.QueryEngine` build kwargs every ``register``
    inherits (``backend``/``interpret``/``batch_capacity``/...).

    The gateway is a composition over N model handles: every handle's
    engine/index stages under its own route, drains under the shared
    :attr:`lock`, and reports into one registry — there is no "the
    model" anywhere in this plane.
    """

    def __init__(
        self, *,
        budget_bytes: Optional[int] = None,
        eviction: Optional[str] = None,
        spill_dir: Optional[str] = None,
        tenant_qps: Optional[float] = None,
        tenant_burst: Optional[float] = None,
        recorder=None,
        **engine_kw,
    ):
        from ..obs import RunRecorder

        self.budget_bytes = (
            int(budget_bytes) if budget_bytes is not None
            else _env_num("PYPARDIS_GATEWAY_BUDGET_BYTES", 0, int)
        )
        self.eviction = str(
            eviction if eviction is not None
            else envreg.raw("PYPARDIS_GATEWAY_EVICTION", "lru")
        ).lower()
        if self.eviction not in EVICTION_POLICIES:
            raise ValueError(
                f"eviction policy {self.eviction!r} is not one of "
                f"{EVICTION_POLICIES}"
            )
        self.spill_dir = os.path.expanduser(str(
            spill_dir if spill_dir is not None
            else envreg.raw("PYPARDIS_GATEWAY_SPILL_DIR",
                            DEFAULT_SPILL_DIR)
        ))
        self.tenant_qps = (
            float(tenant_qps) if tenant_qps is not None
            else _env_num("PYPARDIS_GATEWAY_TENANT_QPS", 0.0, float)
        )
        self.tenant_burst = (
            float(tenant_burst) if tenant_burst is not None
            else _env_num("PYPARDIS_GATEWAY_TENANT_BURST",
                          DEFAULT_TENANT_BURST, float)
        )
        self.engine_kw = dict(engine_kw)
        self.recorder = recorder if recorder is not None else RunRecorder()
        # One lock serializes registry mutation, admission, every
        # engine's submit/drain, and the swap — the same single-writer
        # discipline the sustained-load harness already imposes on one
        # engine, now shared by the fleet (re-entrant: refresh and
        # readmission nest inside request handling).
        self.lock = threading.RLock()
        # model_id -> handle; dict order IS the LRU order (oldest
        # served first) — move_to_end on every touch.
        self._handles: "OrderedDict[str, ModelHandle]" = OrderedDict()
        self._quotas: Dict[str, _TokenBucket] = {}
        # (ticket, tenant) pairs awaiting resolution — swept into the
        # per-tenant latency histograms at each drain, then dropped
        # (O(in-flight) memory, the harness discipline).
        self._pending: deque = deque()
        self._tenant: Dict[str, Dict] = {}
        self._counters = {
            "evictions": 0, "reloads": 0, "epoch_swaps": 0,
            "admission_sheds": 0, "admitted": 0, "spilled_bytes": 0,
            "reloaded_bytes": 0,
        }
        # Completed eviction/reload and swap windows [(t0, t1)] — the
        # load harness classifies read latencies inside/outside these.
        self.evict_windows: List[Tuple[float, float]] = []
        self.swap_windows: List[Tuple[float, float]] = []

    # -- registry ---------------------------------------------------------

    def register(self, model_id: str, model, *, pin: bool = False,
                 live: bool = False, **engine_kw) -> ModelHandle:
        """Admit a fitted model into the registry and build its serving
        engine under the per-handle staging route.

        ``pin`` exempts the handle from budget eviction.  ``live``
        builds the handle over the model's :class:`~.live.LiveModel`
        (the gateway adopts its engine/index, so tenant writes through
        ``handle.live`` are served immediately) — live handles are
        implicitly pinned: their mutated slabs carry live-update state
        a disk spill does not persist.
        """
        model._require_fitted()
        mid = str(model_id)
        with self.lock:
            if mid in self._handles:
                raise GatewayError(
                    f"model {mid!r} is already registered with this "
                    f"gateway; call refresh() or unregister() first"
                )
            h = ModelHandle(mid, model)
            h.engine_kw = {**self.engine_kw, **engine_kw}
            h.fit_generation = getattr(model, "_fit_generation", 0)
            if live:
                h.live = model.live(handle=mid)
                h.engine = h.live.engine
                h.index = h.live.index
                h.pinned = True
            else:
                h.engine = QueryEngine.from_model(
                    model, handle=mid, **h.engine_kw
                )
                h.index = h.engine.index
                h.pinned = bool(pin)
            h.resident = True
            h.index_bytes = int(h.index.stats.get("index_bytes", 0))
            self._handles[mid] = h
            self._ensure_budget(keep=mid)
            self._publish()
            return h

    def unregister(self, model_id: str) -> None:
        """Drop a model from the registry and free its device slabs
        (the spill file, if any, is removed too)."""
        from ..parallel import staging

        with self.lock:
            h = self._handles.pop(str(model_id), None)
            if h is None:
                raise ModelNotRegistered(
                    f"this gateway has no model {model_id!r}; "
                    f"call register() first"
                )
            if h.index is not None:
                staging.device_evict(h.index.staging_route)
            if h.spill_path and os.path.exists(h.spill_path):
                os.unlink(h.spill_path)
            self._publish()

    def handle(self, model_id: str) -> ModelHandle:
        """The (resident) handle for ``model_id`` — readmits an
        evicted model first, so the returned handle always has a live
        engine/index."""
        with self.lock:
            return self._resolve(str(model_id))

    @property
    def model_ids(self) -> List[str]:
        with self.lock:
            return list(self._handles)

    def resident_bytes(self) -> int:
        """Summed index slab bytes of the resident handles — the
        quantity the budget bounds."""
        with self.lock:
            return sum(
                h.index_bytes for h in self._handles.values()
                if h.resident
            )

    # -- residency / eviction ---------------------------------------------

    def _resolve(self, mid: str) -> ModelHandle:
        h = self._handles.get(mid)
        if h is None:
            raise ModelNotRegistered(
                f"this gateway has no model {mid!r}; "
                f"call register() first"
            )
        if h.model is not None and getattr(
            h.model, "_fit_generation", 0
        ) != h.fit_generation:
            raise StaleModelHandle(
                f"model {mid!r} was refit after it was registered; "
                f"this handle serves the PREVIOUS clustering — call "
                f"refresh({mid!r}) first"
            )
        if not h.resident:
            self._readmit(h)
        self._handles.move_to_end(mid)
        return h

    def _victim(self, keep: str) -> Optional[ModelHandle]:
        cands = [
            h for m, h in self._handles.items()
            if h.resident and not h.pinned and m != keep
        ]
        if not cands:
            return None
        if self.eviction == "largest":
            return max(cands, key=lambda h: h.index_bytes)
        return cands[0]  # lru: dict order is least-recently-served

    def _ensure_budget(self, keep: str) -> None:
        """Evict until the residents fit the budget (``keep`` stays —
        the model a request is being served from is never its own
        victim)."""
        if self.budget_bytes <= 0:
            return
        while self.resident_bytes() > self.budget_bytes:
            victim = self._victim(keep)
            if victim is None:
                return  # everything left is pinned or in use
            self._evict(victim)

    def _evict(self, h: ModelHandle) -> None:
        """Spill ``h`` to disk (``save_index``) and free its device
        slabs; the handle stays registered and readmits on demand."""
        from ..checkpoint import save_index
        from ..parallel import staging

        t0 = time.perf_counter()
        os.makedirs(self.spill_dir, exist_ok=True)
        h.spill_path = os.path.join(
            self.spill_dir, f"{sanitize_segment(h.model_id)}.npz"
        )
        # Resolve straggler tickets against the resident slabs first —
        # eviction must never strand an in-flight read.
        h.engine.drain()
        self._sweep()
        h.queries_done += int(h.engine.queries)
        save_index(h.index, h.spill_path)
        staging.device_evict(h.index.staging_route)
        h.index = None
        h.engine = None
        h.resident = False
        h.evictions += 1
        self._counters["evictions"] += 1
        self._counters["spilled_bytes"] += int(h.index_bytes)
        self.evict_windows.append((t0, time.perf_counter()))
        m = self.recorder.metrics
        m.inc("gateway.evictions")
        m.inc(f"gateway.model.{sanitize_segment(h.model_id)}.evictions")

    def _readmit(self, h: ModelHandle) -> None:
        """Reload an evicted model from its spill — slabs restore
        byte-identical (``load_index``), so the readmitted engine
        serves answers bitwise equal to pre-eviction."""
        from ..checkpoint import load_index

        if not h.spill_path or not os.path.exists(h.spill_path):
            raise GatewayError(
                f"model {h.model_id!r} was evicted but its spill "
                f"{h.spill_path!r} is gone; register() it again first"
            )
        t0 = time.perf_counter()
        self._ensure_budget(keep=h.model_id)
        h.index = load_index(h.spill_path, handle=h.model_id)
        # Build-time kwargs (leaves/block/qblock) shaped the PERSISTED
        # index; only the engine-init kwargs apply to the reload.
        eng_kw = {
            k: v for k, v in h.engine_kw.items()
            if k not in ("leaves", "block", "qblock")
        }
        h.engine = QueryEngine(h.index, model=h.model, **eng_kw)
        # The engine's staleness guard must compare against the
        # generation REGISTERED, not whatever the model drifted to
        # while evicted (a refit during eviction is stale too).
        h.engine._model_generation = h.fit_generation
        h.resident = True
        h.reloads += 1
        self._counters["reloads"] += 1
        self._counters["reloaded_bytes"] += int(h.index_bytes)
        self.evict_windows.append((t0, time.perf_counter()))
        m = self.recorder.metrics
        m.inc("gateway.reloads")
        m.inc(f"gateway.model.{sanitize_segment(h.model_id)}.reloads")
        self._ensure_budget(keep=h.model_id)

    # -- admission --------------------------------------------------------

    def set_quota(self, tenant: str, qps: float,
                  burst: Optional[float] = None) -> None:
        """Install a per-tenant admission quota (replaces the env-var
        default for this tenant; ``qps <= 0`` turns quota off)."""
        with self.lock:
            self._quotas[str(tenant)] = _TokenBucket(
                qps, burst if burst is not None else self.tenant_burst
            )

    def _tenant_state(self, tenant: str) -> Dict:
        st = self._tenant.get(tenant)
        if st is None:
            sid = sanitize_segment(tenant)
            st = self._tenant[tenant] = {
                "sid": sid, "admitted": 0, "shed": 0, "failed": 0,
                "hist": self.recorder.metrics.hist(
                    f"gateway.tenant.{sid}.latency_ms"
                ),
            }
        return st

    def _admit(self, tenant: str) -> None:
        from ..utils import faults

        # Injection site: a gateway.admit fault sheds at the front
        # door — upstream of the quota bucket and every engine, so no
        # serving state mutates on an injected failure.
        faults.maybe_fail("gateway.admit")
        st = self._tenant_state(tenant)
        bucket = self._quotas.get(tenant)
        if bucket is None:
            bucket = self._quotas[tenant] = _TokenBucket(
                self.tenant_qps, self.tenant_burst
            )
        if not bucket.try_take():
            st["shed"] += 1
            self._counters["admission_sheds"] += 1
            m = self.recorder.metrics
            m.inc("gateway.admission_sheds")
            m.inc(f"gateway.tenant.{st['sid']}.shed_total")
            raise TenantQuotaExceeded(
                f"tenant {tenant!r} is not admitted: over its "
                f"{bucket.rate:g} qps quota (burst {bucket.burst:g}); "
                f"back off or set_quota() first"
            )
        st["admitted"] += 1
        self._counters["admitted"] += 1

    # -- request surface --------------------------------------------------

    def submit(self, model_id: str, X, *, tenant: str = "default",
               timeout_s: Optional[float] = None):
        """Admit, route, and enqueue one request; returns the engine's
        :class:`~.engine.QueryTicket` (resolved by the next
        :meth:`drain`).  Sheds with :class:`TenantQuotaExceeded` /
        :class:`~.engine.QueueFull`; ``timeout_s`` arms the existing
        deadline machinery."""
        with self.lock:
            self._admit(tenant)
            h = self._resolve(str(model_id))
            t = h.engine.submit(X, timeout_s=timeout_s)
            self._pending.append((t, tenant))
            return t

    def predict(self, model_id: str, X, *, tenant: str = "default",
                timeout_s: Optional[float] = None,
                return_distance: bool = False):
        """Sync assignment through the gateway (admission + routing +
        drain in one call)."""
        with self.lock:
            self._admit(tenant)
            h = self._resolve(str(model_id))
            t = h.engine.submit(X, timeout_s=timeout_s)
            self._pending.append((t, tenant))
            h.engine.drain()
            self._sweep()
            return t.result(return_distance)

    def drain(self, model_id: Optional[str] = None) -> int:
        """Pump every resident engine's drain (or one model's) and fold
        resolved tickets into the per-tenant histograms; returns the
        query-row count processed."""
        with self.lock:
            n = 0
            if model_id is not None:
                n += self._resolve(str(model_id)).engine.drain()
            else:
                for h in list(self._handles.values()):
                    if h.resident:
                        n += h.engine.drain()
            self._sweep()
            self._publish()
            return n

    def _sweep(self) -> None:
        for _ in range(len(self._pending)):
            t, tenant = self._pending.popleft()
            if not t.done:
                self._pending.append((t, tenant))
                continue
            st = self._tenant_state(tenant)
            if t.failed:
                st["failed"] += 1
            elif t.latency_ms is not None:
                st["hist"].observe(t.latency_ms)

    # -- hot swap ---------------------------------------------------------

    def refresh(self, model_id: str, model=None) -> None:
        """Hot-swap a refreshed clustering into a resident handle with
        zero dropped tickets.

        Builds a fresh index generation from ``model`` (default: the
        registered model, after its refit) in the OLD generation's
        recentring frame, drains in-flight tickets against the old
        slabs, then installs the fresh generation through the
        :meth:`~.index.CorePointIndex.replace_generation` epoch-swap
        contract — every ticket submitted before the swap resolves
        against the old generation, every one after sees the new."""
        from .index import CorePointIndex, _model_core_set

        mid = str(model_id)
        with self.lock:
            h = self._handles.get(mid)
            if h is None:
                raise ModelNotRegistered(
                    f"this gateway has no model {mid!r}; "
                    f"call register() first"
                )
            if h.live is not None:
                raise GatewayError(
                    f"model {mid!r} is a live handle; its Compactor "
                    f"owns generation swaps — refresh() is for "
                    f"read-only residents"
                )
            if model is None:
                model = h.model
            model._require_fitted()
            if not h.resident:
                # Adopt the new generation directly: the evicted spill
                # is the OLD clustering, superseded the moment the
                # refreshed model registers.
                h.model = model
                h.fit_generation = getattr(model, "_fit_generation", 0)
                self._readmit_fresh(h, model)
                self._publish()
                return
            cores, labels = _model_core_set(model)
            eps = float(getattr(model, "kernel_eps", model.eps))
            old = h.index
            t0 = time.perf_counter()
            fresh = CorePointIndex.build(
                cores, labels, eps, block=old.block, qblock=old.qblock,
                stage=False, center=old.center, handle=mid,
            )
            metric_norm = getattr(model, "_metric_norm", None)
            fresh.unit_norm = metric_norm == "cosine"
            fresh.projection = {
                "cosine": "unit", "haversine": "latlon"
            }.get(metric_norm, "none")
            # Zero-drop contract: tickets in flight resolve against
            # the OLD generation before the slabs move.
            h.engine.drain()
            self._sweep()
            old.replace_generation(fresh)
            h.model = model
            h.fit_generation = getattr(model, "_fit_generation", 0)
            h.engine._model_ref = weakref.ref(model)
            h.engine._model_generation = h.fit_generation
            h.index_bytes = int(old.stats.get("index_bytes", 0))
            h.swaps += 1
            self._counters["epoch_swaps"] += 1
            self.swap_windows.append((t0, time.perf_counter()))
            self.recorder.metrics.inc("gateway.epoch_swaps")
            self._ensure_budget(keep=mid)
            self._publish()

    def _readmit_fresh(self, h: ModelHandle, model) -> None:
        """Refresh of an evicted handle: rebuild from the new model
        (counts as a swap — the generation moved while spilled)."""
        t0 = time.perf_counter()
        self._ensure_budget(keep=h.model_id)
        h.engine = QueryEngine.from_model(
            model, handle=h.model_id, **h.engine_kw
        )
        h.index = h.engine.index
        h.resident = True
        h.index_bytes = int(h.index.stats.get("index_bytes", 0))
        h.swaps += 1
        self._counters["epoch_swaps"] += 1
        self.swap_windows.append((t0, time.perf_counter()))
        self.recorder.metrics.inc("gateway.epoch_swaps")
        if h.spill_path and os.path.exists(h.spill_path):
            os.unlink(h.spill_path)  # the spill is the OLD clustering
        h.spill_path = None
        self._ensure_budget(keep=h.model_id)

    # -- telemetry --------------------------------------------------------

    def _publish(self) -> None:
        m = self.recorder.metrics
        n_res = sum(1 for h in self._handles.values() if h.resident)
        m.set("gateway.models_registered", len(self._handles))
        m.set("gateway.resident_models", n_res)
        m.set("gateway.resident_bytes", self.resident_bytes())
        m.set("gateway.budget_bytes", int(self.budget_bytes))
        for h in self._handles.values():
            sid = sanitize_segment(h.model_id)
            m.set(f"gateway.model.{sid}.resident", int(h.resident))
            m.set(f"gateway.model.{sid}.index_bytes",
                  int(h.index_bytes))
            m.set(
                f"gateway.model.{sid}.queries",
                int(h.queries_done)
                + int(h.engine.queries if h.resident else 0),
            )

    def gateway_report(self) -> Dict:
        """The fleet telemetry block (``pypardis_tpu/
        gateway_report@1``): registry/budget state, eviction + reload +
        swap counters, and per-tenant admission + windowed-latency
        stats — what the ``gateway@1`` bench row embeds and
        ``check_bench_json`` gates."""
        with self.lock:
            self._publish()
            models = {}
            for mid, h in self._handles.items():
                models[mid] = {
                    "resident": bool(h.resident),
                    "pinned": bool(h.pinned),
                    "live": h.live is not None,
                    "index_bytes": int(h.index_bytes),
                    "queries": int(h.queries_done) + int(
                        h.engine.queries if h.resident else 0
                    ),
                    "evictions": int(h.evictions),
                    "reloads": int(h.reloads),
                    "epoch_swaps": int(h.swaps),
                    "index_epoch": int(
                        getattr(h.index, "epoch", 0) if h.resident
                        else 0
                    ),
                    "index_generation": int(
                        getattr(h.index, "generation", 0)
                        if h.resident else 0
                    ),
                }
            tenants = {}
            for tenant, st in self._tenant.items():
                hist = st["hist"]
                tenants[tenant] = {
                    "admitted": int(st["admitted"]),
                    "shed": int(st["shed"]),
                    "failed": int(st["failed"]),
                    "p50_ms": hist.percentile(50),
                    "p99_ms": hist.percentile(99),
                    "latency_hist": hist.snapshot(),
                }
            c = self._counters
            return {
                "schema": GATEWAY_REPORT_SCHEMA,
                "models_registered": len(self._handles),
                "resident_models": sum(
                    1 for h in self._handles.values() if h.resident
                ),
                "budget_bytes": int(self.budget_bytes),
                "resident_bytes": int(self.resident_bytes()),
                "eviction_policy": self.eviction,
                "evictions": int(c["evictions"]),
                "reloads": int(c["reloads"]),
                "spilled_bytes": int(c["spilled_bytes"]),
                "reloaded_bytes": int(c["reloaded_bytes"]),
                "epoch_swaps": int(c["epoch_swaps"]),
                "admitted": int(c["admitted"]),
                "admission_sheds": int(c["admission_sheds"]),
                "eviction_windows": len(self.evict_windows),
                "swap_windows": len(self.swap_windows),
                "in_flight": len(self._pending),
                "models": models,
                "tenants": tenants,
            }
