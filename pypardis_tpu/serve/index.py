"""The device-resident core-point index behind the query engine.

Built once from a fitted (or checkpoint-loaded) model:

1. extract the core points and their global labels;
2. build a small KD tree over the (centered, float32) cores and bucket
   them by leaf via the same split-tree replay that routes training
   points (:func:`pypardis_tpu.partition.route_tree` semantics);
3. Morton-sort each bucket (tile-local bounding boxes stay tight, so
   the query kernel's block pruning works) and pad every bucket to one
   common block-multiple capacity ``C`` — pad slots carry far-away
   coordinates and INT32_MAX labels, so no mask enters the kernels;
4. park the ``(d, L*C)`` coordinate slab, label row, and per-block
   bounds on device through the staging economy
   (:mod:`pypardis_tpu.parallel.staging`, route ``serve_index``),
   content-keyed: a second engine build over the same clustering — or
   a refit that reproduces the same core set — reuses the device
   memory and ships nothing (``staged_bytes_reused`` in the stats).

Query routing replays the SAME tree with an eps-widened margin
(:func:`pypardis_tpu.partition.expanded_members` — the box-expansion
logic of the fit path): a query within eps of a leaf boundary lands in
every leaf whose core set could contain its nearest within-eps core, so
the per-leaf kernel results combine into the exact global answer.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

import numpy as np

from ..ops.query import (
    BIG,
    PAD_COORD,
    _INT_INF,
    brute_force_query,
    eps2_f32,
)
from ..utils import clamp_block, round_up
from ..utils.validate import check_query_points, validate_params

# Routing margin slack over eps: the leaf-membership test runs in
# float64 on float32 coordinates, while the within-eps verdict is a
# float32 sum — 0.1% of slack dwarfs any accumulated ulp gap, and extra
# slack only ever ADDS candidate leaves (never changes the answer).
_MARGIN_SLACK = 1.001


def _leaf_partition(cores_c: np.ndarray, leaves: int, seed: int):
    """(tree, {leaf -> core indices}) over the centered float32 cores.

    A fresh deterministic KDPartitioner (not the fit's partition tree):
    the serving tree must balance the CORE set — the fit tree balances
    all points and may be absent entirely (single-shard fits,
    checkpoint-loaded models).  Determinism makes a rebuilt index —
    same cores, any process — byte-identical, which is what lets
    checkpoint-restored models serve identical answers.
    """
    from ..partition import KDPartitioner

    if leaves <= 1 or len(cores_c) < 2:
        return [], {0: np.arange(len(cores_c), dtype=np.int64)}
    part = KDPartitioner(
        cores_c, max_partitions=int(leaves), split_method="min_var",
        seed=seed,
    )
    return part.tree, part.partitions


class CorePointIndex:
    """Core points of a fitted DBSCAN, laid out for batched queries.

    Construct via :meth:`build` (from core points + labels) or
    :func:`pypardis_tpu.checkpoint.load_index`.  All host arrays are
    plain numpy; device residency happens lazily in
    :meth:`device_arrays` through the staging cache.
    """

    def __init__(
        self, *, eps, center, tree, coords, labels, blo, bhi,
        block: int, qblock: int, n_core: int, stats: Optional[Dict] = None,
        leaf_slabs: Optional[Dict] = None, gids=None,
        handle: Optional[str] = None,
    ):
        # Model handle: names which fitted model this index serves.
        # ``None`` keeps the historical single-model staging route
        # (``serve_index``); a named handle gets its OWN route, so N
        # resident indexes coexist in the device cache instead of
        # evicting each other through the one-entry-per-route rule —
        # the seam the multi-tenant gateway composes over.
        self.handle = None if handle is None else str(handle)
        self.staging_route = (
            "serve_index" if self.handle is None
            else f"serve_index.{self.handle}"
        )
        self.eps = float(eps)
        self.eps2 = eps2_f32(eps)
        self.center = np.asarray(center, np.float64)
        self.tree = [
            (int(p), int(a), float(b), int(l), int(r))
            for p, a, b, l, r in tree
        ]
        self.coords = np.asarray(coords, np.float32)  # (d, L*C)
        self.labels = np.asarray(labels, np.int32)  # (L*C,)
        self.blo = np.asarray(blo, np.float32)  # (L*nb, d)
        self.bhi = np.asarray(bhi, np.float32)
        self.block = int(block)
        self.qblock = int(qblock)
        self.n_core = int(n_core)
        self.stats: Dict = dict(stats or {})
        # Driver-metric frame (set by build_index / load_index):
        # ``unit`` (cosine) unit-normalizes queries before centering,
        # ``latlon`` (haversine) embeds (lat, lon)-radian queries onto
        # the 3-D unit sphere — either way the L2 kernels then answer
        # the driver metric's threshold question exactly.  The legacy
        # ``unit_norm`` bool is kept in sync for old checkpoints.
        self.unit_norm = False
        self.projection = "none"
        self._margin = self.eps * _MARGIN_SLACK
        self._dev = None
        # Live-update state (the serve_index_delta path): monotone
        # generation counter (bumped on every in-place mutation — the
        # epoch the engine publishes), tree-leaf -> slab ids (a leaf
        # that overflowed its pad slots owns extra slabs appended past
        # the build layout; routing fans a query out to all of them),
        # and per-slot point gids so deletions can find their columns.
        self.epoch = 0
        self.delta_bytes = 0
        # Streaming-ingest generation state (serve.ingest): how many
        # whole-index generation swaps this object has absorbed, how
        # many write deltas landed since the last one, and the column
        # width the current generation was BUILT with — appended slabs
        # past it are the LSM write debt the compaction trigger policy
        # watermarks (appended_slab_bytes).
        self.generation = 0
        self.deltas_since_compact = 0
        self._base_cols = int(self.coords.shape[1])
        if leaf_slabs is not None:
            self.leaf_slabs = {
                int(l): [int(s) for s in slabs]
                for l, slabs in leaf_slabs.items()
            }
        else:
            n_slabs = (
                0 if self.coords.shape[1] == 0
                else self.coords.shape[1] // max(self.leaf_cap, 1)
            )
            self.leaf_slabs = {s: [s] for s in range(n_slabs)}
            if not self.leaf_slabs:
                self.leaf_slabs = {0: []}
        self.gids = (
            None if gids is None else np.asarray(gids, np.int64)
        )
        self._gid_col: Optional[Dict[int, int]] = None

    # -- construction -----------------------------------------------------

    @classmethod
    def build(
        cls, cores, labels, eps, *, leaves: Optional[int] = None,
        block: int = 256, qblock: int = 128, seed: int = 0,
        stage: bool = True, center=None, handle: Optional[str] = None,
    ):
        """Index ``(n_core, d)`` core points with their cluster labels.

        ``leaves``: KD leaf budget (default scales with the core count);
        ``block``: column tile of the query kernels (clamped to the
        largest bucket); ``qblock``: query rows per tile.  ``stage``
        ships the slabs to device immediately so the build's
        ``staged_bytes_reused``/``staged_bytes`` telemetry is complete.
        ``center`` overrides the recentring frame (default: the core
        mean) — a compaction rebuild passes the PREVIOUS generation's
        center so queries already centered and queued against the old
        generation stay valid across the epoch swap (any center is
        correct; the frame only sets f32 rounding, and kernels + oracle
        share it).
        """
        validate_params(eps, 1)
        cores = np.asarray(cores)
        if cores.ndim != 2:
            raise ValueError(
                f"core points must be (N, k) 2-D, got shape {cores.shape}"
            )
        labels = np.asarray(labels, np.int32)
        if len(labels) != len(cores):
            raise ValueError(
                f"{len(cores)} core points but {len(labels)} labels"
            )
        n, d = cores.shape
        t0 = time.perf_counter()
        if n == 0:
            idx = cls(
                eps=eps,
                center=np.zeros(d) if center is None else center,
                tree=[],
                coords=np.full((d, 0), PAD_COORD, np.float32),
                labels=np.empty(0, np.int32),
                blo=np.empty((0, d), np.float32),
                bhi=np.empty((0, d), np.float32),
                block=int(block), qblock=int(qblock), n_core=0,
                handle=handle,
            )
            idx.stats = {"n_core": 0, "n_leaves": 0, "build_s": 0.0,
                         "index_bytes": 0, "staged_bytes_reused": 0,
                         "staged_bytes": 0}
            idx.src_index = np.empty(0, np.int64)
            return idx
        # Center in float64 (the fit drivers' discipline: the f32 cast
        # after a f64 subtract keeps GPS-scale magnitudes accurate) —
        # the center also recenters every query, so distances are
        # preserved exactly.
        if center is None:
            center = cores.mean(axis=0, dtype=np.float64)
        else:
            center = np.asarray(center, np.float64)
        cores_c = np.ascontiguousarray(
            (cores.astype(np.float64) - center).astype(np.float32)
        )
        from ..partition import spatial_order

        if leaves is None:
            leaves = int(np.clip(n // max(4 * block, 1), 1, 64))
        tree, parts = _leaf_partition(cores_c, int(leaves), seed)
        L = len(parts)
        assert sorted(parts) == list(range(L)), sorted(parts)
        max_leaf = max(len(v) for v in parts.values())
        block = clamp_block(int(block), max_leaf, floor=8)
        C = round_up(max_leaf, block)
        nb = C // block
        coords = np.full((d, L * C), PAD_COORD, np.float32)
        slab_labels = np.full(L * C, _INT_INF, np.int32)
        # slab column -> input core row (-1 pads): the permutation the
        # live path needs to attach stable point ids to slots.
        src_index = np.full(L * C, -1, np.int64)
        for leaf in range(L):
            idx_l = np.asarray(parts[leaf])
            idx_l = idx_l[spatial_order(cores_c[idx_l])]
            s = leaf * C
            coords[:, s:s + len(idx_l)] = cores_c[idx_l].T
            slab_labels[s:s + len(idx_l)] = labels[idx_l]
            src_index[s:s + len(idx_l)] = idx_l
        # Per-column-block core bounds for the XLA kernel's gap pruning
        # (empty blocks invert, so they always prune).
        valid = (slab_labels != _INT_INF).reshape(L * nb, block)
        c3 = coords.reshape(d, L * nb, block)
        blo = np.where(valid[None], c3, BIG).min(axis=2).T
        bhi = np.where(valid[None], c3, -BIG).max(axis=2).T
        idx = cls(
            eps=eps, center=center, tree=tree, coords=coords,
            labels=slab_labels, blo=blo, bhi=bhi, block=block,
            qblock=int(qblock), n_core=n, handle=handle,
        )
        idx.src_index = src_index
        # The constructor's slab map derives from stats["leaf_cap"],
        # which is only assigned below — set the build layout's
        # tree-leaf <-> slab identity explicitly.
        idx.leaf_slabs = {leaf: [leaf] for leaf in range(L)}
        idx.stats = {
            "n_core": n,
            "n_leaves": L,
            "leaf_cap": C,
            "block": block,
            "pad_waste": round(L * C / n - 1.0, 6),
            "index_bytes": int(
                coords.nbytes + slab_labels.nbytes + blo.nbytes + bhi.nbytes
            ),
            "staged_bytes_reused": 0,
            "staged_bytes": 0,
        }
        if stage:
            from ..parallel import staging

            staging.begin_fit()
            idx.device_arrays()
            reused, shipped = staging.fit_stats()
            idx.stats["staged_bytes_reused"] = int(reused)
            idx.stats["staged_bytes"] = int(shipped)
        idx.stats["build_s"] = round(time.perf_counter() - t0, 6)
        return idx

    # -- geometry ---------------------------------------------------------

    @property
    def d(self) -> int:
        return self.coords.shape[0]

    @property
    def n_leaves(self) -> int:
        return 0 if self.coords.shape[1] == 0 else (
            self.coords.shape[1] // self.leaf_cap
        )

    @property
    def leaf_cap(self) -> int:
        cap = int(self.stats.get("leaf_cap", 0) or 0)
        if cap > 0:
            return cap
        if self.coords.shape[1] == 0:
            return self.block
        return int(self.coords.shape[1])

    @property
    def nb(self) -> int:
        return self.leaf_cap // self.block

    @property
    def appended_slab_bytes(self) -> int:
        """Bytes of the slabs appended past this generation's build
        layout — the LSM write debt the compaction trigger policy
        watermarks (``PYPARDIS_COMPACT_SLAB_BYTES``).  Zero right after
        a build or a generation swap."""
        extra = int(self.coords.shape[1]) - self._base_cols
        if extra <= 0:
            return 0
        nb = extra // max(self.block, 1)
        # coords (d x f32) + labels (i32) + gids (i64) per column, plus
        # the per-block bound rows.
        return int(extra * (4 * self.d + 4 + 8) + nb * (8 * self.d))

    # -- device residency -------------------------------------------------

    @property
    def delta_route(self) -> str:
        """Staging route of this index's live-update deltas (per
        handle, like :attr:`staging_route`)."""
        return self.staging_route + "_delta"

    def _content_key(self):
        from ..parallel import staging

        return (
            staging.points_fingerprint(self.coords),
            staging.points_fingerprint(self.labels),
            self.block,
        )

    def device_arrays(self):
        """The staged (coords, labels, blo, bhi) device arrays —
        content-keyed through this handle's staging route
        (:attr:`staging_route`), so a rebuilt index over the same
        clustering reuses device memory, and indexes of DIFFERENT
        handles never evict each other."""
        if self._dev is not None:
            return self._dev
        import jax.numpy as jnp

        from ..parallel import staging

        key = self._content_key()
        cached = staging.device_get(self.staging_route, key)
        if cached is not None:
            arrays, _aux = cached
        else:
            arrays = staging.device_put_cached(
                self.staging_route, key,
                (
                    jnp.asarray(self.coords),
                    jnp.asarray(self.labels),
                    jnp.asarray(self.blo),
                    jnp.asarray(self.bhi),
                ),
            )
        self._dev = arrays
        return arrays

    # -- live updates (the serve_index_delta path) ------------------------

    def attach_gids(self, core_gids) -> None:
        """Attach stable point ids to the slab slots: ``core_gids`` is
        in the order the cores were passed to :meth:`build` (the
        ``src_index`` permutation maps them onto columns).  Required
        before :meth:`remove_gids` / :meth:`set_label_gids`."""
        src = getattr(self, "src_index", None)
        gids = np.full(len(self.labels), -1, np.int64)
        if src is not None and len(src):
            sel = src >= 0
            gids[sel] = np.asarray(core_gids, np.int64)[src[sel]]
        self.gids = gids
        self._gid_col = None

    def _gid_map(self) -> Dict[int, int]:
        if self.gids is None:
            raise RuntimeError(
                "index has no point ids; call attach_gids() first"
            )
        if self._gid_col is None:
            self._gid_col = {
                int(g): int(c)
                for c, g in enumerate(self.gids) if g >= 0
            }
        return self._gid_col

    def begin_update(self) -> None:
        """Open a mutation batch: every insert/remove/relabel until
        :meth:`commit_update` edits the host mirrors only; the commit
        recomputes touched block bounds and ships ONE device delta."""
        if getattr(self, "_pending", None) is not None:
            raise RuntimeError("an index update is already open")
        self._pending = {
            "cols": set(), "old_w": self.coords.shape[1], "lut": None,
        }

    def insert_cores(self, cores, labels, gids) -> None:
        """Add core points (raw-frame coordinates) with their cluster
        labels and stable ids.  Each point routes through the SAME
        split tree queries replay, into its leaf's pad slots; a leaf
        out of pad slots has its slab set rebuilt — members plus
        newcomers re-Morton-sorted across the old slab(s) and one
        appended slab — and only that leaf's columns re-ship."""
        cores = np.asarray(cores)
        labels = np.asarray(labels, np.int32)
        gids = np.asarray(gids, np.int64)
        n = len(cores)
        if n == 0:
            return
        cc = np.ascontiguousarray(
            (cores.astype(np.float64) - self.center).astype(np.float32)
        )
        if self.tree:
            from ..partition import route_tree

            leaves = route_tree(self.tree, cc)
        else:
            leaves = np.zeros(n, np.int32)
        for leaf in np.unique(leaves):
            sel = np.flatnonzero(leaves == leaf)
            self._insert_into_leaf(
                int(leaf), cc[sel], labels[sel], gids[sel]
            )
        self.n_core += n

    def _insert_into_leaf(self, leaf, pts, labels, gids) -> None:
        C = self.leaf_cap
        slabs = self.leaf_slabs.setdefault(leaf, [])
        free: list = []
        for s in slabs:
            free.extend(
                (np.flatnonzero(
                    self.labels[s * C:(s + 1) * C] == _INT_INF
                ) + s * C).tolist()
            )
        if len(free) >= len(pts):
            cols = np.asarray(free[:len(pts)], np.int64)
            self._set_cols(cols, pts, labels, gids)
        else:
            self._rebuild_leaf(leaf, pts, labels, gids)

    def _set_cols(self, cols, pts, labels, gids) -> None:
        self.coords[:, cols] = pts.T
        self.labels[cols] = labels
        if self.gids is not None:
            self.gids[cols] = gids
            self._gid_col = None
        self._pending["cols"].update(int(c) for c in cols)

    def _rebuild_leaf(self, leaf, new_pts, new_labels, new_gids) -> None:
        """Re-lay-out ONE overflowing leaf: old members + newcomers,
        Morton re-sorted, across its slabs plus however many appended
        slabs the overflow needs.  Every other leaf's columns are
        untouched — the commit ships only this leaf's slabs."""
        from ..partition import spatial_order

        C = self.leaf_cap
        slabs = self.leaf_slabs.setdefault(leaf, [])
        old_cols = np.concatenate(
            [np.arange(s * C, (s + 1) * C) for s in slabs]
        ) if slabs else np.empty(0, np.int64)
        live = old_cols[self.labels[old_cols] != _INT_INF] \
            if len(old_cols) else old_cols
        pts = np.concatenate(
            [self.coords[:, live].T, np.asarray(new_pts, np.float32)]
        ) if len(live) else np.asarray(new_pts, np.float32)
        labs = np.concatenate([self.labels[live], new_labels])
        gds = np.concatenate([
            self.gids[live] if self.gids is not None
            else np.full(len(live), -1, np.int64),
            new_gids,
        ])
        m = len(pts)
        while len(slabs) * C < m:
            slabs.append(self._append_slab())
        cols_all = np.concatenate(
            [np.arange(s * C, (s + 1) * C) for s in slabs]
        )
        self.coords[:, cols_all] = PAD_COORD
        self.labels[cols_all] = _INT_INF
        if self.gids is not None:
            self.gids[cols_all] = -1
        order = spatial_order(pts)
        dest = cols_all[:m]
        self.coords[:, dest] = pts[order].T
        self.labels[dest] = labs[order]
        if self.gids is not None:
            self.gids[dest] = gds[order]
            self._gid_col = None
        self.leaf_slabs[leaf] = slabs
        self._pending["cols"].update(int(c) for c in cols_all)

    def _append_slab(self) -> int:
        C = self.leaf_cap
        self.stats.setdefault("leaf_cap", C)
        d = self.coords.shape[0]
        nb = C // self.block
        s = self.coords.shape[1] // C
        self.coords = np.concatenate(
            [self.coords, np.full((d, C), PAD_COORD, np.float32)], axis=1
        )
        self.labels = np.concatenate(
            [self.labels, np.full(C, _INT_INF, np.int32)]
        )
        if self.gids is not None:
            self.gids = np.concatenate(
                [self.gids, np.full(C, -1, np.int64)]
            )
        self.blo = np.concatenate(
            [self.blo, np.full((nb, d), BIG, np.float32)]
        )
        self.bhi = np.concatenate(
            [self.bhi, np.full((nb, d), -BIG, np.float32)]
        )
        return s

    def remove_gids(self, gids) -> None:
        """Turn the given points' slots back into pad slots (far-away
        coordinates, INT32_MAX labels) — deletion never re-lays-out a
        leaf; freed slots are absorbed by later inserts."""
        gmap = self._gid_map()
        cols = np.asarray(
            [gmap[int(g)] for g in np.asarray(gids).reshape(-1)], np.int64
        )
        if len(cols) == 0:
            return
        self.coords[:, cols] = PAD_COORD
        self.labels[cols] = _INT_INF
        self.gids[cols] = -1
        for g in np.asarray(gids).reshape(-1):
            gmap.pop(int(g), None)
        self.n_core -= len(cols)
        self._pending["cols"].update(int(c) for c in cols)

    def set_label_gids(self, gids, labels) -> None:
        """Rewrite the cluster labels of existing slots (the delete
        path's re-clustered fresh ids)."""
        gmap = self._gid_map()
        gids = np.asarray(gids).reshape(-1)
        if len(gids) == 0:
            return
        cols = np.asarray([gmap[int(g)] for g in gids], np.int64)
        self.labels[cols] = np.asarray(labels, np.int32)
        self._pending["cols"].update(int(c) for c in cols)

    def apply_label_map(self, lut) -> None:
        """Apply a union-find relabel LUT (identity outside the merged
        ids — :func:`pypardis_tpu.ops.incremental.label_lut`) to every
        live slot.  Device-side this ships only the LUT and gathers in
        place, so a merge that renames a million-slot cluster costs a
        kilobyte of transfer."""
        lut = np.asarray(lut, np.int32)
        sel = self.labels != _INT_INF
        if sel.any():
            self.labels[sel] = lut[
                np.clip(self.labels[sel], 0, len(lut) - 1)
            ]
        p = self._pending
        p["lut"] = lut if p["lut"] is None else lut[
            np.clip(p["lut"], 0, len(lut) - 1)
        ]

    def _recompute_bounds(self, blocks) -> None:
        b = self.block
        blocks = np.asarray(sorted(blocks), np.int64)
        if len(blocks) == 0:
            return
        idx = (blocks[:, None] * b + np.arange(b)[None, :]).reshape(-1)
        cc = self.coords[:, idx].reshape(self.d, len(blocks), b)
        valid = (self.labels[idx] != _INT_INF).reshape(len(blocks), b)
        self.blo[blocks] = np.where(valid[None], cc, BIG).min(axis=2).T
        self.bhi[blocks] = np.where(valid[None], cc, -BIG).max(axis=2).T

    def commit_update(self) -> int:
        """Close the mutation batch: recompute touched block bounds,
        ship one device delta (scattered columns + appended slabs + the
        relabel LUT — never the whole index), bump the epoch, and
        refresh the staging-cache entry so ``staged_bytes_reused``
        accounting and ``route_nbytes`` stay truthful.  Returns the
        delta bytes shipped."""
        p = getattr(self, "_pending", None)
        if p is None:
            raise RuntimeError("no index update open; call begin_update()")
        self._pending = None
        cols = np.asarray(sorted(p["cols"]), np.int64)
        old_w = int(p["old_w"])
        lut = p["lut"]
        touched_blocks = set((cols // self.block).tolist())
        self._recompute_bounds(touched_blocks)
        delta = 0
        if self._dev is not None:
            import jax.numpy as jnp

            from ..parallel import staging

            coords_d, labels_d, blo_d, bhi_d = self._dev
            new_w = self.coords.shape[1]
            old_rows = old_w // self.block
            if new_w > old_w:
                app_c = self.coords[:, old_w:]
                app_l = self.labels[old_w:]
                app_lo = self.blo[old_rows:]
                app_hi = self.bhi[old_rows:]
                coords_d = jnp.concatenate(
                    [coords_d, jnp.asarray(app_c)], axis=1
                )
                labels_d = jnp.concatenate([labels_d, jnp.asarray(app_l)])
                blo_d = jnp.concatenate([blo_d, jnp.asarray(app_lo)])
                bhi_d = jnp.concatenate([bhi_d, jnp.asarray(app_hi)])
                delta += (
                    app_c.nbytes + app_l.nbytes + app_lo.nbytes
                    + app_hi.nbytes
                )
            scat = cols[cols < old_w]
            if len(scat):
                ji = jnp.asarray(scat)
                coords_d = coords_d.at[:, ji].set(
                    jnp.asarray(self.coords[:, scat])
                )
                labels_d = labels_d.at[ji].set(
                    jnp.asarray(self.labels[scat])
                )
                delta += self.coords[:, scat].nbytes \
                    + self.labels[scat].nbytes
            if lut is not None:
                jl = jnp.asarray(lut)
                labels_d = jnp.where(
                    labels_d == _INT_INF,
                    labels_d,
                    jl[jnp.clip(labels_d, 0, len(lut) - 1)],
                )
                delta += lut.nbytes
            brows = np.asarray(
                sorted(b for b in touched_blocks if b < old_rows), np.int64
            )
            if len(brows):
                jb = jnp.asarray(brows)
                blo_d = blo_d.at[jb].set(jnp.asarray(self.blo[brows]))
                bhi_d = bhi_d.at[jb].set(jnp.asarray(self.bhi[brows]))
                delta += 2 * self.blo[brows].nbytes
            self._dev = (coords_d, labels_d, blo_d, bhi_d)
            staging.device_replace(
                self.staging_route, self._content_key(), self._dev,
                staged_nbytes=delta, delta_route=self.delta_route,
            )
        self.epoch += 1
        self.delta_bytes += int(delta)
        self.deltas_since_compact += 1
        self.stats["n_leaves"] = self.n_leaves
        self.stats["index_bytes"] = int(
            self.coords.nbytes + self.labels.nbytes + self.blo.nbytes
            + self.bhi.nbytes
        )
        return int(delta)

    def replace_generation(self, fresh: "CorePointIndex") -> None:
        """Whole-index generation swap, IN PLACE: adopt a freshly built
        index's slabs/tree/bounds/gids wholesale while keeping this
        object's identity and epoch clock.

        This is the PR 8 epoch mechanism extended from per-leaf deltas
        to whole generations: every engine holding this index object —
        the live engine, a ReplicatedQueryEngine, anything a caller
        built over it — sees the compacted generation at its next
        dispatch, and the epoch bump makes replica caches keyed on it
        re-broadcast.  The fresh build must share this generation's
        recentring frame (``build(center=self.center)``) so queries
        centered before the swap stay valid; the caller (the Compactor)
        drains in-flight tickets against the OLD slabs first, so
        readers submitted before the swap resolve against the old
        generation and readers after see the new one.
        """
        if getattr(self, "_pending", None) is not None:
            raise RuntimeError(
                "cannot swap index generations with a delta update open; "
                "commit_update() first"
            )
        from ..parallel import staging

        for attr in ("center", "tree", "coords", "labels", "blo", "bhi",
                     "block", "qblock", "n_core", "leaf_slabs", "gids",
                     "unit_norm", "projection"):
            setattr(self, attr, getattr(fresh, attr))
        self.src_index = getattr(fresh, "src_index", None)
        self.stats = dict(fresh.stats)
        self._gid_col = None
        # Drop the old generation's device residency: the next
        # device_arrays() stages the compacted slabs under their own
        # content key (a FULL re-ship, the compaction's one bulk
        # transfer — write deltas stay cheap between swaps).
        self._dev = None
        staging.device_evict(self.staging_route)
        self._base_cols = int(self.coords.shape[1])
        self.deltas_since_compact = 0
        self.generation += 1
        self.epoch += 1

    # -- query-side layout ------------------------------------------------

    def prepare_queries(self, X) -> np.ndarray:
        """Validated, centered float32 queries (the serving dtype both
        the kernels and the oracle consume).  A cosine-frame index
        (``unit_norm``/``projection='unit'``) projects queries onto
        the unit sphere first; a haversine-frame index
        (``projection='latlon'``) embeds (lat, lon)-radian queries
        into the same 3-D frame the fit indexed — the projection the
        fit applied to the core set, replayed on every query."""
        if getattr(self, "projection", "none") == "latlon":
            from ..geometry import latlon_to_unit_sphere

            X = latlon_to_unit_sphere(check_query_points(X, 2))
        X = check_query_points(X, self.d)
        X = X.astype(np.float64)
        if self.unit_norm:
            nrm = np.sqrt(np.einsum("ij,ij->i", X, X))
            if not nrm.all():
                raise ValueError(
                    "metric='cosine' is undefined for zero vectors: "
                    "query row(s) with zero norm"
                )
            X = X / nrm[:, None]
        return (X - self.center).astype(np.float32)

    def route(self, qf32: np.ndarray):
        """[(slab, query indices)] in ascending slab order — each query
        appears in EVERY slab of every tree leaf whose eps-expanded
        region contains it (the neighbor-leaf path for
        boundary-straddling queries; a leaf grown past its pad capacity
        by live inserts owns several slabs, and its queries scan each)."""
        n = len(qf32)
        if n == 0:
            return []
        if not self.tree:
            slabs = sorted(self.leaf_slabs.get(0, []))
            idx = np.arange(n, dtype=np.int64)
            return [(s, idx) for s in slabs]
        from ..partition import expanded_members

        members = expanded_members(self.tree, qf32, self._margin)
        out = []
        for leaf in sorted(members):
            arr = members[leaf][0]
            if len(arr):
                for slab in self.leaf_slabs.get(leaf, [leaf]):
                    out.append((slab, arr))
        out.sort(key=lambda t: t[0])
        return out

    def assemble(self, qf32: np.ndarray):
        """Pack routed queries into padded device tiles.

        Returns ``(qbuf, qmask, tile_leaf, rowmap)``: ``qbuf`` is a
        pooled ``(nqt, d, qb)`` float32 host buffer (borrowed from the
        staging host pool — return it via ``staging.give_back`` once
        the batch's results have materialized, the same rotation
        barrier the fit pipelines use), ``rowmap[t]`` the query indices
        tile ``t``'s rows answer for.  The tile count rounds up to a
        power of two so batch programs are shared across sizes.
        """
        from ..parallel import staging

        qb = self.qblock
        tiles = []
        for leaf, arr in self.route(qf32):
            for s in range(0, len(arr), qb):
                tiles.append((leaf, arr[s:s + qb]))
        nqt = 1 << (max(len(tiles), 1) - 1).bit_length()
        qbuf = staging.borrow((nqt, self.d, qb), np.float32)
        qbuf.fill(PAD_COORD)
        qmask = np.zeros((nqt, qb), bool)
        tile_leaf = np.zeros(nqt, np.int32)
        rowmap = []
        for t, (leaf, arr) in enumerate(tiles):
            qbuf[t, :, :len(arr)] = qf32[arr].T
            qmask[t, :len(arr)] = True
            tile_leaf[t] = leaf
            rowmap.append(arr)
        return qbuf, qmask, tile_leaf, rowmap

    def dispatch(self, qbuf, qmask, tile_leaf, backend: str = "auto",
                 interpret: bool = False, precision: str = "high"):
        """Launch the query kernel for one assembled batch (async);
        returns the packed (2, nqt, qb) int32 device result.

        ``precision="mixed"`` turns on the bf16-peak candidate prune in
        both kernels (survivors rescore through the sealed exact path,
        so the bitwise oracle contract is preserved — see
        :func:`pypardis_tpu.ops.query.query_min_core`).
        """
        import jax.numpy as jnp

        from ..ops.query import query_min_core, resolve_query_backend

        coords, labels, blo, bhi = self.device_arrays()
        be = resolve_query_backend(backend, self.qblock, self.block)
        # The anti-FMA seal's zero rides as a runtime ARGUMENT — a
        # literal inside the jit would constant-fold and re-admit the
        # contraction (ops.query.seal_f32).
        if be == "pallas":
            from ..ops.pallas_kernels import query_min_core_pallas

            return query_min_core_pallas(
                jnp.asarray(qbuf), jnp.asarray(tile_leaf), coords, labels,
                jnp.zeros(1, jnp.int32),
                jnp.full(1, self.eps2, jnp.float32),
                block=self.block, nb=self.nb, interpret=interpret,
                precision=precision,
            )
        return query_min_core(
            jnp.asarray(qbuf), jnp.asarray(qmask), jnp.asarray(tile_leaf),
            coords, labels, blo, bhi, jnp.float32(self.eps2),
            jnp.int32(0),
            block=self.block, nb=self.nb, precision=precision,
        )

    # -- oracle -----------------------------------------------------------

    def oracle_predict(self, X):
        """Brute-force numpy reference over the index's own core set:
        ``(labels, d2)`` — the exactness target for ``predict`` (tests
        pin bitwise equality of both)."""
        qf32 = self.prepare_queries(X)
        sel = self.labels != _INT_INF
        return brute_force_query(
            qf32, self.coords[:, sel].T, self.labels[sel], self.eps
        )


def _model_core_set(model):
    """(core coordinates, core labels) of a fitted model — from the live
    training data when present, else from the checkpoint-restored core
    set (``save_model`` persists it precisely so a restarted process
    can build this index without re-clustering)."""
    mask = np.asarray(model.core_sample_mask_, bool)
    labels = np.asarray(model.labels_, np.int32)[mask]
    stored = getattr(model, "_serve_core_points", None)
    if stored is not None:
        cores = np.asarray(stored)
        if len(cores) != len(labels):
            raise ValueError(
                f"checkpoint core set has {len(cores)} points but the "
                f"core mask marks {len(labels)}"
            )
    elif model.data is not None:
        # Device-resident training data fetches ONCE here (cores only
        # ride forward) — serving is the explicit opt-in for that.
        cores = np.asarray(model.data)[mask]
    else:
        raise RuntimeError(
            "serving needs the core-point coordinates: fit()/train() in "
            "this process, or load a checkpoint that carries core points "
            "(save_model now persists them)"
        )
    return cores, labels


def build_index(
    model, *, leaves=None, block: int = 256, qblock: int = 128,
    seed: int = 0, handle=None,
):
    """Serving index of a fitted (or checkpoint-loaded) ``DBSCAN``.

    ``handle`` names the model in a multi-model serving plane: the
    index stages under its own per-handle route so a
    :class:`~pypardis_tpu.serve.gateway.ModelGateway` fleet of N
    resident indexes shares the device cache without collisions.

    A ``metric='cosine'`` model indexes in its unit-sphere kernel
    frame: the core coordinates are already normalized (the model's
    ``data`` frame), the index eps is the remapped L2 threshold
    (``model.kernel_eps``), and ``unit_norm`` makes
    :meth:`CorePointIndex.prepare_queries` project queries onto the
    sphere too — so ``predict`` and the bitwise oracle both answer the
    cosine question exactly through the unchanged L2 kernels.
    """
    model._require_fitted()
    cores, labels = _model_core_set(model)
    eps = float(getattr(model, "kernel_eps", model.eps))
    idx = CorePointIndex.build(
        cores, labels, eps, leaves=leaves, block=block,
        qblock=qblock, seed=seed, handle=handle,
    )
    metric_norm = getattr(model, "_metric_norm", None)
    idx.unit_norm = metric_norm == "cosine"
    idx.projection = {
        "cosine": "unit", "haversine": "latlon"
    }.get(metric_norm, "none")
    return idx
