"""The device-resident core-point index behind the query engine.

Built once from a fitted (or checkpoint-loaded) model:

1. extract the core points and their global labels;
2. build a small KD tree over the (centered, float32) cores and bucket
   them by leaf via the same split-tree replay that routes training
   points (:func:`pypardis_tpu.partition.route_tree` semantics);
3. Morton-sort each bucket (tile-local bounding boxes stay tight, so
   the query kernel's block pruning works) and pad every bucket to one
   common block-multiple capacity ``C`` — pad slots carry far-away
   coordinates and INT32_MAX labels, so no mask enters the kernels;
4. park the ``(d, L*C)`` coordinate slab, label row, and per-block
   bounds on device through the staging economy
   (:mod:`pypardis_tpu.parallel.staging`, route ``serve_index``),
   content-keyed: a second engine build over the same clustering — or
   a refit that reproduces the same core set — reuses the device
   memory and ships nothing (``staged_bytes_reused`` in the stats).

Query routing replays the SAME tree with an eps-widened margin
(:func:`pypardis_tpu.partition.expanded_members` — the box-expansion
logic of the fit path): a query within eps of a leaf boundary lands in
every leaf whose core set could contain its nearest within-eps core, so
the per-leaf kernel results combine into the exact global answer.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

import numpy as np

from ..ops.query import (
    BIG,
    PAD_COORD,
    _INT_INF,
    brute_force_query,
    eps2_f32,
)
from ..utils import clamp_block, round_up
from ..utils.validate import check_query_points, validate_params

# Routing margin slack over eps: the leaf-membership test runs in
# float64 on float32 coordinates, while the within-eps verdict is a
# float32 sum — 0.1% of slack dwarfs any accumulated ulp gap, and extra
# slack only ever ADDS candidate leaves (never changes the answer).
_MARGIN_SLACK = 1.001


def _leaf_partition(cores_c: np.ndarray, leaves: int, seed: int):
    """(tree, {leaf -> core indices}) over the centered float32 cores.

    A fresh deterministic KDPartitioner (not the fit's partition tree):
    the serving tree must balance the CORE set — the fit tree balances
    all points and may be absent entirely (single-shard fits,
    checkpoint-loaded models).  Determinism makes a rebuilt index —
    same cores, any process — byte-identical, which is what lets
    checkpoint-restored models serve identical answers.
    """
    from ..partition import KDPartitioner

    if leaves <= 1 or len(cores_c) < 2:
        return [], {0: np.arange(len(cores_c), dtype=np.int64)}
    part = KDPartitioner(
        cores_c, max_partitions=int(leaves), split_method="min_var",
        seed=seed,
    )
    return part.tree, part.partitions


class CorePointIndex:
    """Core points of a fitted DBSCAN, laid out for batched queries.

    Construct via :meth:`build` (from core points + labels) or
    :func:`pypardis_tpu.checkpoint.load_index`.  All host arrays are
    plain numpy; device residency happens lazily in
    :meth:`device_arrays` through the staging cache.
    """

    def __init__(
        self, *, eps, center, tree, coords, labels, blo, bhi,
        block: int, qblock: int, n_core: int, stats: Optional[Dict] = None,
    ):
        self.eps = float(eps)
        self.eps2 = eps2_f32(eps)
        self.center = np.asarray(center, np.float64)
        self.tree = [
            (int(p), int(a), float(b), int(l), int(r))
            for p, a, b, l, r in tree
        ]
        self.coords = np.asarray(coords, np.float32)  # (d, L*C)
        self.labels = np.asarray(labels, np.int32)  # (L*C,)
        self.blo = np.asarray(blo, np.float32)  # (L*nb, d)
        self.bhi = np.asarray(bhi, np.float32)
        self.block = int(block)
        self.qblock = int(qblock)
        self.n_core = int(n_core)
        self.stats: Dict = dict(stats or {})
        self._margin = self.eps * _MARGIN_SLACK
        self._dev = None

    # -- construction -----------------------------------------------------

    @classmethod
    def build(
        cls, cores, labels, eps, *, leaves: Optional[int] = None,
        block: int = 256, qblock: int = 128, seed: int = 0,
        stage: bool = True,
    ):
        """Index ``(n_core, d)`` core points with their cluster labels.

        ``leaves``: KD leaf budget (default scales with the core count);
        ``block``: column tile of the query kernels (clamped to the
        largest bucket); ``qblock``: query rows per tile.  ``stage``
        ships the slabs to device immediately so the build's
        ``staged_bytes_reused``/``staged_bytes`` telemetry is complete.
        """
        validate_params(eps, 1)
        cores = np.asarray(cores)
        if cores.ndim != 2:
            raise ValueError(
                f"core points must be (N, k) 2-D, got shape {cores.shape}"
            )
        labels = np.asarray(labels, np.int32)
        if len(labels) != len(cores):
            raise ValueError(
                f"{len(cores)} core points but {len(labels)} labels"
            )
        n, d = cores.shape
        t0 = time.perf_counter()
        if n == 0:
            idx = cls(
                eps=eps, center=np.zeros(d), tree=[],
                coords=np.full((d, 0), PAD_COORD, np.float32),
                labels=np.empty(0, np.int32),
                blo=np.empty((0, d), np.float32),
                bhi=np.empty((0, d), np.float32),
                block=int(block), qblock=int(qblock), n_core=0,
            )
            idx.stats = {"n_core": 0, "n_leaves": 0, "build_s": 0.0,
                         "index_bytes": 0, "staged_bytes_reused": 0,
                         "staged_bytes": 0}
            return idx
        # Center in float64 (the fit drivers' discipline: the f32 cast
        # after a f64 subtract keeps GPS-scale magnitudes accurate) —
        # the center also recenters every query, so distances are
        # preserved exactly.
        center = cores.mean(axis=0, dtype=np.float64)
        cores_c = np.ascontiguousarray(
            (cores.astype(np.float64) - center).astype(np.float32)
        )
        from ..partition import spatial_order

        if leaves is None:
            leaves = int(np.clip(n // max(4 * block, 1), 1, 64))
        tree, parts = _leaf_partition(cores_c, int(leaves), seed)
        L = len(parts)
        assert sorted(parts) == list(range(L)), sorted(parts)
        max_leaf = max(len(v) for v in parts.values())
        block = clamp_block(int(block), max_leaf, floor=8)
        C = round_up(max_leaf, block)
        nb = C // block
        coords = np.full((d, L * C), PAD_COORD, np.float32)
        slab_labels = np.full(L * C, _INT_INF, np.int32)
        for leaf in range(L):
            idx_l = np.asarray(parts[leaf])
            idx_l = idx_l[spatial_order(cores_c[idx_l])]
            s = leaf * C
            coords[:, s:s + len(idx_l)] = cores_c[idx_l].T
            slab_labels[s:s + len(idx_l)] = labels[idx_l]
        # Per-column-block core bounds for the XLA kernel's gap pruning
        # (empty blocks invert, so they always prune).
        valid = (slab_labels != _INT_INF).reshape(L * nb, block)
        c3 = coords.reshape(d, L * nb, block)
        blo = np.where(valid[None], c3, BIG).min(axis=2).T
        bhi = np.where(valid[None], c3, -BIG).max(axis=2).T
        idx = cls(
            eps=eps, center=center, tree=tree, coords=coords,
            labels=slab_labels, blo=blo, bhi=bhi, block=block,
            qblock=int(qblock), n_core=n,
        )
        idx.stats = {
            "n_core": n,
            "n_leaves": L,
            "leaf_cap": C,
            "block": block,
            "pad_waste": round(L * C / n - 1.0, 6),
            "index_bytes": int(
                coords.nbytes + slab_labels.nbytes + blo.nbytes + bhi.nbytes
            ),
            "staged_bytes_reused": 0,
            "staged_bytes": 0,
        }
        if stage:
            from ..parallel import staging

            staging.begin_fit()
            idx.device_arrays()
            reused, shipped = staging.fit_stats()
            idx.stats["staged_bytes_reused"] = int(reused)
            idx.stats["staged_bytes"] = int(shipped)
        idx.stats["build_s"] = round(time.perf_counter() - t0, 6)
        return idx

    # -- geometry ---------------------------------------------------------

    @property
    def d(self) -> int:
        return self.coords.shape[0]

    @property
    def n_leaves(self) -> int:
        return 0 if self.coords.shape[1] == 0 else (
            self.coords.shape[1] // self.leaf_cap
        )

    @property
    def leaf_cap(self) -> int:
        if self.n_core == 0:
            return self.block
        return int(self.stats.get("leaf_cap", self.coords.shape[1]))

    @property
    def nb(self) -> int:
        return self.leaf_cap // self.block

    # -- device residency -------------------------------------------------

    def _content_key(self):
        from ..parallel import staging

        return (
            staging.points_fingerprint(self.coords),
            staging.points_fingerprint(self.labels),
            self.block,
        )

    def device_arrays(self):
        """The staged (coords, labels, blo, bhi) device arrays —
        content-keyed through the ``serve_index`` staging route, so a
        rebuilt index over the same clustering reuses device memory."""
        if self._dev is not None:
            return self._dev
        import jax.numpy as jnp

        from ..parallel import staging

        key = self._content_key()
        cached = staging.device_get("serve_index", key)
        if cached is not None:
            arrays, _aux = cached
        else:
            arrays = staging.device_put_cached(
                "serve_index", key,
                (
                    jnp.asarray(self.coords),
                    jnp.asarray(self.labels),
                    jnp.asarray(self.blo),
                    jnp.asarray(self.bhi),
                ),
            )
        self._dev = arrays
        return arrays

    # -- query-side layout ------------------------------------------------

    def prepare_queries(self, X) -> np.ndarray:
        """Validated, centered float32 queries (the serving dtype both
        the kernels and the oracle consume)."""
        X = check_query_points(X, self.d)
        return (X.astype(np.float64) - self.center).astype(np.float32)

    def route(self, qf32: np.ndarray):
        """[(leaf, query indices)] in ascending leaf order — each query
        appears in EVERY leaf whose eps-expanded region contains it
        (the neighbor-leaf path for boundary-straddling queries)."""
        n = len(qf32)
        if not self.tree:
            return [(0, np.arange(n, dtype=np.int64))] if n else []
        from ..partition import expanded_members

        members = expanded_members(self.tree, qf32, self._margin)
        return [
            (leaf, members[leaf][0])
            for leaf in sorted(members)
            if len(members[leaf][0])
        ]

    def assemble(self, qf32: np.ndarray):
        """Pack routed queries into padded device tiles.

        Returns ``(qbuf, qmask, tile_leaf, rowmap)``: ``qbuf`` is a
        pooled ``(nqt, d, qb)`` float32 host buffer (borrowed from the
        staging host pool — return it via ``staging.give_back`` once
        the batch's results have materialized, the same rotation
        barrier the fit pipelines use), ``rowmap[t]`` the query indices
        tile ``t``'s rows answer for.  The tile count rounds up to a
        power of two so batch programs are shared across sizes.
        """
        from ..parallel import staging

        qb = self.qblock
        tiles = []
        for leaf, arr in self.route(qf32):
            for s in range(0, len(arr), qb):
                tiles.append((leaf, arr[s:s + qb]))
        nqt = 1 << (max(len(tiles), 1) - 1).bit_length()
        qbuf = staging.borrow((nqt, self.d, qb), np.float32)
        qbuf.fill(PAD_COORD)
        qmask = np.zeros((nqt, qb), bool)
        tile_leaf = np.zeros(nqt, np.int32)
        rowmap = []
        for t, (leaf, arr) in enumerate(tiles):
            qbuf[t, :, :len(arr)] = qf32[arr].T
            qmask[t, :len(arr)] = True
            tile_leaf[t] = leaf
            rowmap.append(arr)
        return qbuf, qmask, tile_leaf, rowmap

    def dispatch(self, qbuf, qmask, tile_leaf, backend: str = "auto",
                 interpret: bool = False, precision: str = "high"):
        """Launch the query kernel for one assembled batch (async);
        returns the packed (2, nqt, qb) int32 device result.

        ``precision="mixed"`` turns on the bf16-peak candidate prune in
        both kernels (survivors rescore through the sealed exact path,
        so the bitwise oracle contract is preserved — see
        :func:`pypardis_tpu.ops.query.query_min_core`).
        """
        import jax.numpy as jnp

        from ..ops.query import query_min_core, resolve_query_backend

        coords, labels, blo, bhi = self.device_arrays()
        be = resolve_query_backend(backend, self.qblock, self.block)
        # The anti-FMA seal's zero rides as a runtime ARGUMENT — a
        # literal inside the jit would constant-fold and re-admit the
        # contraction (ops.query.seal_f32).
        if be == "pallas":
            from ..ops.pallas_kernels import query_min_core_pallas

            return query_min_core_pallas(
                jnp.asarray(qbuf), jnp.asarray(tile_leaf), coords, labels,
                jnp.zeros(1, jnp.int32),
                jnp.full(1, self.eps2, jnp.float32),
                block=self.block, nb=self.nb, interpret=interpret,
                precision=precision,
            )
        return query_min_core(
            jnp.asarray(qbuf), jnp.asarray(qmask), jnp.asarray(tile_leaf),
            coords, labels, blo, bhi, jnp.float32(self.eps2),
            jnp.int32(0),
            block=self.block, nb=self.nb, precision=precision,
        )

    # -- oracle -----------------------------------------------------------

    def oracle_predict(self, X):
        """Brute-force numpy reference over the index's own core set:
        ``(labels, d2)`` — the exactness target for ``predict`` (tests
        pin bitwise equality of both)."""
        qf32 = self.prepare_queries(X)
        sel = self.labels != _INT_INF
        return brute_force_query(
            qf32, self.coords[:, sel].T, self.labels[sel], self.eps
        )


def _model_core_set(model):
    """(core coordinates, core labels) of a fitted model — from the live
    training data when present, else from the checkpoint-restored core
    set (``save_model`` persists it precisely so a restarted process
    can build this index without re-clustering)."""
    mask = np.asarray(model.core_sample_mask_, bool)
    labels = np.asarray(model.labels_, np.int32)[mask]
    stored = getattr(model, "_serve_core_points", None)
    if stored is not None:
        cores = np.asarray(stored)
        if len(cores) != len(labels):
            raise ValueError(
                f"checkpoint core set has {len(cores)} points but the "
                f"core mask marks {len(labels)}"
            )
    elif model.data is not None:
        # Device-resident training data fetches ONCE here (cores only
        # ride forward) — serving is the explicit opt-in for that.
        cores = np.asarray(model.data)[mask]
    else:
        raise RuntimeError(
            "serving needs the core-point coordinates: fit()/train() in "
            "this process, or load a checkpoint that carries core points "
            "(save_model now persists them)"
        )
    return cores, labels


def build_index(
    model, *, leaves=None, block: int = 256, qblock: int = 128,
    seed: int = 0,
):
    """Serving index of a fitted (or checkpoint-loaded) ``DBSCAN``."""
    model._require_fitted()
    cores, labels = _model_core_set(model)
    return CorePointIndex.build(
        cores, labels, model.eps, leaves=leaves, block=block,
        qblock=qblock, seed=seed,
    )
