"""Live updates: incremental insert/delete on a fitted model.

Incremental DBSCAN (Ester et al., VLDB 1998) observes that an update
only perturbs the clustering inside the eps-neighborhood of the change:
neighbor counts move only within ``eps`` of an inserted/deleted point,
and labels only within ``eps`` of a core-ness flip.  This maps exactly
onto the locality primitives the repo already has — the KD split tree
bounds the blast radius to a few leaves, and the device-resident
:class:`~pypardis_tpu.serve.CorePointIndex` is refreshed *in place*
(pad slots absorb inserts; one overflowing leaf rebuilds alone) through
the ``serve_index_delta`` staging route, never a full rebuild.

The update algebra, per batch:

* **insert** — counts can only rise, so core-ness only flips *on*, and
  clusters only grow or MERGE (never split).  The fast path (no flip,
  no new core) attaches each newcomer to the nearest core within eps —
  or noise — and touches nothing else.  Otherwise the blast radius is
  the set of KD leaves whose eps-expanded box contains a new or
  flipped point; every NEW eps-edge provably has both endpoints inside
  those leaves, so a **local re-cluster** of the extracted slab (the
  existing fused device kernel with ``min_samples=1`` over KNOWN
  cores, :func:`pypardis_tpu.ops.incremental.core_components`) plus a
  union-find stitch of (old label, local component) edges
  (:func:`pypardis_tpu.parallel.merge.resolve_label_edges` — the same
  machinery that merges shards; one insert bridging three clusters is
  exactly the PR 2 multi-edge lesson) reproduces the full refit's
  partition.  A merge renames labels globally, but as a LUT — no
  geometry outside the slab is ever touched.

* **delete** — counts can only fall, so core-ness only flips *off*, and
  clusters only shrink or SPLIT.  A split is not leaf-local (removing
  one bridge can sever a cluster spanning the dataset), but it is
  *cluster*-local: only the clusters owning a deleted point or a
  demoted core can change.  Those clusters' surviving members are
  re-clustered (same two primitives) under fresh labels; everything
  else keeps its label untouched.

Determinism note: count verdicts run in float64 on raw coordinates
(:mod:`pypardis_tpu.ops.incremental`) — one frame for the whole update
sequence, where a maintained f32 verdict would depend on the drifting
dataset mean.  Border points attach to the nearest core within eps
(ties: smallest label) — the serving rule.  A full refit breaks border
ties by Morton-order root instead, so equality with a refit is a
*partition* (ARI == 1.0) guarantee on geometries where no border point
straddles two clusters — ambiguous straddlers are the one documented
divergence, same as any incremental-DBSCAN formulation.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from ..ops.incremental import (
    attach_to_cores,
    core_components,
    count_within_eps,
    label_lut,
)

# Routing slack over eps (the serve index's discipline): leaf-membership
# replays in f64, verdicts in f64 here too — the slack only ever adds
# candidate leaves, never changes an answer.
_SLACK = 1.001


class LiveModel:
    """Incremental insert/delete on a fitted :class:`~pypardis_tpu.
    dbscan.DBSCAN`, with the serving index refreshed in place.

    Points carry stable integer ids (returned by :meth:`insert`,
    consumed by :meth:`delete`); the initial fit's points get ids
    ``0..n-1``.  ``model.labels_`` / ``core_sample_mask_`` / ``data``
    are kept in sync after every update (canonical — not re-densified —
    cluster ids, so ``predict`` labels and training labels agree), and
    ``model.report()["live"]`` carries the update telemetry.
    """

    def __init__(self, model, *, leaves: Optional[int] = None,
                 block: int = 256, qblock: int = 128, warm: bool = True,
                 handle: Optional[str] = None,
                 _resume: Optional[Dict] = None, **engine_kw):
        model._require_fitted()
        self.model = model
        # Model handle: names this model in a multi-model serving
        # plane — threaded into the engine/index build so the mutable
        # index stages under its own per-handle route (the gateway's
        # composition seam); ``None`` keeps the historical
        # one-model-per-process route.
        self.handle = None if handle is None else str(handle)
        self.eps = float(model.eps)
        self.min_samples = int(model.min_samples)
        self._fit_generation = getattr(model, "_fit_generation", 0)
        if _resume is not None:
            pts = np.asarray(_resume["points"], np.float64)
            labels = np.asarray(_resume["labels"], np.int32)
            core = np.asarray(_resume["core"], bool)
            self._next_label = int(_resume["next_label"])
        else:
            if model.data is None:
                raise RuntimeError(
                    "live updates need the training coordinates; "
                    "model.data was cleared (or the model came from a "
                    "checkpoint without live state)"
                )
            pts = np.asarray(model.data, np.float64)
            labels = np.asarray(model.labels_, np.int32)
            core = np.asarray(model.core_sample_mask_, bool)
            self._next_label = (
                int(labels.max()) + 1 if (labels >= 0).any() else 0
            )
        n, k = pts.shape
        self.k = int(k)
        self._data_dtype = (
            np.asarray(model.data).dtype if model.data is not None
            else np.float64
        )
        cap = max(2 * n, n + 64)
        self._coords = np.empty((cap, k), np.float64)
        self._coords[:n] = pts
        self._alive = np.zeros(cap, bool)
        self._alive[:n] = True
        self._labels = np.full(cap, -1, np.int32)
        self._labels[:n] = labels
        self._core = np.zeros(cap, bool)
        self._core[:n] = core
        self._n = n

        # Spatial tree over the initial points: the locality structure
        # every update routes through.  Fresh and deterministic (the
        # fit's partitioner may be absent or describe a mesh layout) —
        # split planes cover all space, so points drifting outside the
        # initial extent still route.
        if leaves is None:
            leaves = int(np.clip(n // 512, 4, 64))
        if _resume is not None:
            self._tree = [
                (int(p), int(a), float(b), int(l), int(r))
                for p, a, b, l, r in _resume["tree"]
            ]
        elif leaves > 1 and n >= 2:
            from ..partition import KDPartitioner

            part = KDPartitioner(
                pts, max_partitions=int(leaves), split_method="min_var",
                seed=0,
            )
            self._tree = part.tree
        else:
            self._tree = []
        from ..partition import route_tree

        self._leaf_of = np.zeros(cap, np.int32)
        self._leaf_of[:n] = (
            route_tree(self._tree, pts) if self._tree
            else np.zeros(n, np.int32)
        )
        self._leaf_members: Dict[int, List[int]] = {}
        for i in range(n):
            self._leaf_members.setdefault(
                int(self._leaf_of[i]), []
            ).append(i)
        self.n_leaves = max(len(self._leaf_members), 1)

        # Serving surface: the model's cached engine over a gid-tagged
        # index (resume restores the mutated slabs byte-identically).
        if _resume is not None:
            from .engine import QueryEngine

            self.index = _resume["index"]
            self.engine = QueryEngine(
                self.index, backend=model.kernel_backend, model=model,
                handle=self.handle, **engine_kw,
            )
            model._serve_engine = self.engine
        else:
            self.engine = model.query_engine(
                block=block, qblock=qblock, handle=self.handle,
                **engine_kw
            )
            self.index = self.engine.index
            self.index.attach_gids(np.flatnonzero(core))

        # Telemetry (the ``live`` block of ``model.report()``): ONE
        # dict object updated in place, so a report taken at any time
        # reads current gauges.
        self._ins_ms: deque = deque(maxlen=4096)
        self._del_ms: deque = deque(maxlen=4096)
        self.stats: Dict = {}
        self._counters = {
            "inserts": 0, "deletes": 0, "updates": 0,
            "recluster_events": 0, "recluster_points": 0,
            "recluster_dispatches": 0,
            "label_remaps": 0,
            "compactions": 0, "epoch_swaps": 0,
        }
        self._last_fraction = 0.0
        # Streaming-ingest state (serve.ingest): sizes of the write
        # batches applied (singles are size-1 batches — the
        # amortization gauge reclusters_per_write reads off them),
        # cumulative background-compaction seconds, whether a Compactor
        # cycle is mid-flight (persisted by save() so a restore knows a
        # partial generation was discarded), and the replay flag that
        # keeps compaction-replay traffic out of the user-facing write
        # counters/latencies while its kernel work stays counted.
        self._batch_sizes: deque = deque(maxlen=64)
        self._compaction_s = 0.0
        self._compact_active = False
        self.compact_pending = False
        self._replay = False
        # Lazy model-surface sync (satellite, CHANGES PR 8 note):
        # updates only mark the model's labels_/core_sample_mask_/data
        # dirty; the O(N) copies happen at most once per READ of those
        # surfaces (DBSCAN's properties call _sync_if_dirty), never per
        # write.  model_syncs/model_sync_bytes in the live stats gauge
        # what the laziness saves.
        self._dirty = False
        self._syncs = 0
        self._sync_bytes = 0
        # Warm-compile the recluster kernel at build time so the FIRST
        # insert's latency excludes the jit trace (~1.6s measured):
        # core_components buckets its slab to power-of-two sizes, and
        # the warmup compiles the buckets an insert will actually hit
        # (the typical 1-2-leaf blast radius and the all-cores worst
        # case) with a 2-point dummy padded up via min_bucket.
        self._warm_ms = 0.0
        if warm:
            self._warm_kernel()
        model._live_stats = self.stats
        model._live_model = self
        self._publish()

    def _warm_kernel(self) -> None:
        import time as _time

        n_core = int(self._core[:self._n][self._alive[:self._n]].sum())
        if n_core < 2:
            return
        from ..ops.incremental import bucket_size

        per_leaf = max(n_core // max(self.n_leaves, 1), 1)
        buckets = {
            bucket_size(min(2 * per_leaf + 8, n_core + 8)),
            bucket_size(n_core + 8),
        }
        dummy = np.zeros((2, self.k), np.float64)
        dummy[1, 0] = max(100.0 * self.eps, 100.0)
        t0 = _time.perf_counter()
        for b in sorted(buckets):
            core_components(
                dummy, self.eps,
                block=min(int(self.model.block), 256),
                precision=self.model.precision,
                backend=self.model.kernel_backend,
                min_bucket=b,
            )
        self._warm_ms = (_time.perf_counter() - t0) * 1e3

    # -- public write surface ---------------------------------------------

    def insert(self, X) -> np.ndarray:
        """Insert points; returns their stable ids.

        DBSCAN-correct label maintenance: a newcomer within eps of a
        core point joins (nearest core's cluster); a newcomer or
        neighbor crossing the core threshold triggers the local
        re-cluster + union-find merge described in the module docs.
        A multi-row ``X`` is ONE batch: one union blast radius, one
        recluster dispatch, one index delta (see :meth:`insert_batch`).
        """
        return self._do_insert(self._check_points(X))

    def insert_batch(self, X) -> np.ndarray:
        """Batched insert — the streaming-ingest write primitive.

        Semantically identical to :meth:`insert` (which already
        amortizes per batch); this is the explicit ingest surface: it
        carries the ``ingest.batch`` fault-injection site (fired BEFORE
        any state mutates, so an injected failure leaves the model
        untouched) and is what :class:`~pypardis_tpu.serve.ingest.
        IngestQueue` coalesces single-point write streams into.
        Inserting B points here costs exactly one recluster kernel
        dispatch and one index delta (``recluster_dispatches`` /
        ``index_epoch`` in the stats pin it; ``make ingest-probe``
        asserts it at B=256).
        """
        from ..utils import faults

        faults.maybe_fail("ingest.batch")
        return self._do_insert(self._check_points(X))

    def delete_batch(self, ids) -> int:
        """Batched delete by stable ids — one union blast radius over
        the affected clusters, one recluster dispatch, one index delta
        (the :meth:`insert_batch` contract, delete-side).  Carries the
        ``ingest.batch`` fault site, fired before any state mutates."""
        from ..utils import faults

        faults.maybe_fail("ingest.batch")
        return self.delete(ids)

    def _do_insert(self, X, ids=None) -> np.ndarray:
        """The insert algebra.  ``ids=None`` appends fresh stable ids;
        a compaction replay passes the ids it is re-applying (rows
        already present in ``_coords``/``_leaf_of``, currently marked
        dead by the generation install)."""
        t0 = time.perf_counter()
        m = len(X)
        if m == 0:
            return np.empty(0, np.int64)
        eps, ms = self.eps, self.min_samples

        cand = self._pool(X)
        cand_pts = self._coords[cand]
        # Existing points whose counts rise, and their new full counts.
        delta = count_within_eps(cand_pts, X, eps)
        changed = cand[delta > 0]
        if len(changed):
            pool2 = self._pool(self._coords[changed])
            new_counts = (
                count_within_eps(
                    self._coords[changed], self._coords[pool2], eps
                )
                + count_within_eps(self._coords[changed], X, eps)
            )
            flips = changed[~self._core[changed] & (new_counts >= ms)]
        else:
            flips = np.empty(0, np.int64)
        # Newcomers' counts: alive candidates + the batch itself (the
        # self-count rides in the new-new term).
        new_counts_p = (
            count_within_eps(X, cand_pts, eps)
            + count_within_eps(X, X, eps)
        )
        new_core = new_counts_p >= ms

        if ids is None:
            ids = self._append(X)
        else:
            # Replay revival: rows/leaf membership already in place.
            ids = np.asarray(ids, np.int64)
            self._alive[ids] = True
            self._labels[ids] = -1
        self._core[ids] = new_core
        self._core[flips] = True

        if len(flips) == 0 and not new_core.any():
            # Fast path: every newcomer is border or noise; no
            # structure moved.  Candidate cores all live in the routed
            # leaves (a core within eps of p puts p in its leaf's
            # eps-expanded box).
            core_cand = cand[self._core[cand]]
            labs, _d2 = attach_to_cores(
                X, self._coords[core_cand], self._labels[core_cand], eps
            )
            self._labels[ids] = labs
            self._last_fraction = 0.0
            self._finish_update("inserts", m, t0, self._ins_ms)
            return ids

        # Local re-cluster of the blast radius.
        changed_pts = np.concatenate([X, self._coords[flips]])
        lut, s_core, s_core_labels = self._recluster_insert(changed_pts)

        # Index refresh, one delta: the merge LUT renames in place; the
        # new cores (inserted + flipped) fill pad slots.
        self.index.begin_update()
        if lut is not None:
            self.index.apply_label_map(lut)
            self._counters["label_remaps"] += 1
        add = np.concatenate([ids[new_core], flips]).astype(np.int64)
        if len(add):
            self.index.insert_cores(
                self._coords[add], self._labels[add], add
            )
        self.index.commit_update()
        self._finish_update("inserts", m, t0, self._ins_ms)
        return ids

    def delete(self, ids) -> int:
        """Delete points by id; returns the number removed.

        Labels of untouched clusters never move; clusters that owned a
        deleted point or a demoted core re-cluster locally (a split's
        true blast radius) under fresh labels.
        """
        t0 = time.perf_counter()
        # Dedupe: a repeated id in one call must count (and free its
        # index slot) exactly once.
        ids = np.unique(np.asarray(ids, np.int64).reshape(-1))
        if len(ids) == 0:
            return 0
        bad = ids[(ids < 0) | (ids >= self._n) | ~self._alive[
            np.clip(ids, 0, max(self._n - 1, 0))
        ]]
        if len(bad):
            raise KeyError(
                f"unknown or already-deleted point id(s): "
                f"{bad[:8].tolist()}"
            )
        eps, ms = self.eps, self.min_samples
        D = self._coords[ids].copy()
        was_core = self._core[ids].copy()
        dead_labels = self._labels[ids].copy()
        self._alive[ids] = False
        self._core[ids] = False

        cand = self._pool(D)
        delta = count_within_eps(self._coords[cand], D, eps)
        changed = cand[delta > 0]
        if len(changed):
            pool2 = self._pool(self._coords[changed])
            new_counts = count_within_eps(
                self._coords[changed], self._coords[pool2], eps
            )
            flips = changed[self._core[changed] & (new_counts < ms)]
        else:
            flips = np.empty(0, np.int64)

        if not was_core.any() and len(flips) == 0:
            # Border/noise deletions detach nothing else.
            self._labels[ids] = -1
            self._last_fraction = 0.0
            self._finish_update("deletes", len(ids), t0, self._del_ms)
            return len(ids)

        flip_labels = self._labels[flips]
        self._core[flips] = False
        self._labels[ids] = -1
        affected = np.unique(np.concatenate([
            dead_labels[dead_labels >= 0],
            flip_labels[flip_labels >= 0],
        ]))
        s_core, s_labels, touched_leaves = self._recluster_delete(
            affected, ids
        )

        self.index.begin_update()
        gone = np.concatenate([ids[was_core], flips]).astype(np.int64)
        if len(gone):
            self.index.remove_gids(gone)
        if len(s_core):
            self.index.set_label_gids(s_core, s_labels)
        self.index.commit_update()
        self._finish_update("deletes", len(ids), t0, self._del_ms)
        return len(ids)

    # -- re-cluster machinery ---------------------------------------------

    def _recluster_insert(self, changed_pts):
        """Re-cluster the leaves reached by new/flipped points; stitch
        the local components back into the global labels through the
        union-find.  Returns ``(lut_or_None, s_core_ids, labels)``."""
        leaves = self._leaves_reaching(changed_pts)
        S = self._members(leaves)
        s_core = S[self._core[S]]
        if len(s_core) >= 2:
            self._counters["recluster_dispatches"] += 1
        comp = core_components(
            self._coords[s_core], self.eps,
            block=min(int(self.model.block), 256),
            precision=self.model.precision,
            backend=self.model.kernel_backend,
        )
        n_comp = int(comp.max()) + 1 if len(comp) else 0
        fresh = self._next_label + comp.astype(np.int64)
        self._next_label += n_comp
        old = self._labels[s_core].astype(np.int64)
        sel = old >= 0
        edges = np.stack([old[sel], fresh[sel]], axis=1)
        from ..parallel.merge import resolve_label_edges

        alive_labels = self._labels[:self._n][self._alive[:self._n]]
        ids_univ = np.unique(np.concatenate([
            alive_labels[alive_labels >= 0].astype(np.int64),
            fresh,
        ])) if len(fresh) else np.unique(
            alive_labels[alive_labels >= 0].astype(np.int64)
        )
        lut = None
        if len(ids_univ):
            mapping = resolve_label_edges(edges, ids_univ)
            lut = label_lut(mapping, int(ids_univ.max()))
            live = self._alive[:self._n] & (self._labels[:self._n] >= 0)
            self._labels[:self._n][live] = lut[
                self._labels[:self._n][live]
            ]
            final = lut[np.clip(fresh, 0, len(lut) - 1)]
        else:
            final = fresh.astype(np.int32)
        self._labels[s_core] = final
        self._attach_noncore(S[~self._core[S]])
        self._note_recluster(leaves, len(S))
        return lut, s_core, self._labels[s_core]

    def _recluster_delete(self, affected, deleted_ids):
        """Re-cluster the surviving members of the affected clusters
        under fresh labels (no stitching: a cross-cluster core edge
        would have merged them before the delete)."""
        alive = self._alive[:self._n]
        in_affected = np.isin(self._labels[:self._n], affected) & alive
        S = np.flatnonzero(in_affected).astype(np.int64)
        s_core = S[self._core[S]]
        if len(s_core) >= 2:
            self._counters["recluster_dispatches"] += 1
        comp = core_components(
            self._coords[s_core], self.eps,
            block=min(int(self.model.block), 256),
            precision=self.model.precision,
            backend=self.model.kernel_backend,
        )
        n_comp = int(comp.max()) + 1 if len(comp) else 0
        fresh = (self._next_label + comp.astype(np.int64)).astype(np.int32)
        self._next_label += n_comp
        self._labels[s_core] = fresh
        self._attach_noncore(S[~self._core[S]])
        leaves = set(
            int(l) for l in np.unique(np.concatenate([
                self._leaf_of[S], self._leaf_of[deleted_ids]
            ]))
        ) if len(S) or len(deleted_ids) else set()
        self._note_recluster(leaves, len(S))
        return s_core, self._labels[s_core], leaves

    def _attach_noncore(self, pts_ids) -> None:
        """Re-attach non-core points: nearest core within eps (ties:
        smallest label), else noise — candidate cores gathered from the
        leaves each point's eps-ball reaches."""
        if len(pts_ids) == 0:
            return
        pool = self._pool(self._coords[pts_ids])
        core_cand = pool[self._core[pool]]
        labs, _d2 = attach_to_cores(
            self._coords[pts_ids], self._coords[core_cand],
            self._labels[core_cand], self.eps,
        )
        self._labels[pts_ids] = labs

    # -- locality helpers -------------------------------------------------

    def _pool(self, pts) -> np.ndarray:
        """Alive ids in every leaf whose eps-expanded box contains one
        of ``pts`` — the candidate set that provably contains all
        eps-neighbors of ``pts``."""
        return self._members(self._leaves_reaching(pts))

    def _leaves_reaching(self, pts):
        if not self._tree:
            return {0}
        from ..partition import expanded_members

        members = expanded_members(
            self._tree, np.asarray(pts, np.float64),
            self.eps * _SLACK,
        )
        return {l for l, (idx, _own) in members.items() if len(idx)}

    def _members(self, leaves) -> np.ndarray:
        out = []
        for leaf in leaves:
            lst = self._leaf_members.get(int(leaf))
            if not lst:
                continue
            arr = np.asarray(lst, np.int64)
            arr = arr[self._alive[arr]]
            if len(arr) * 2 < len(lst):
                self._leaf_members[int(leaf)] = arr.tolist()
            out.append(arr)
        if not out:
            return np.empty(0, np.int64)
        return np.unique(np.concatenate(out))

    def _append(self, X) -> np.ndarray:
        m = len(X)
        need = self._n + m
        if need > len(self._coords):
            cap = max(2 * len(self._coords), need)
            for name in ("_coords", "_alive", "_labels", "_core",
                         "_leaf_of"):
                old = getattr(self, name)
                fresh = np.zeros(
                    (cap,) + old.shape[1:], old.dtype
                ) if old.dtype != np.int32 else np.full(
                    (cap,) + old.shape[1:], -1, np.int32
                )
                fresh[:self._n] = old[:self._n]
                setattr(self, name, fresh)
            self._leaf_of[self._n:] = 0
        ids = np.arange(self._n, need, dtype=np.int64)
        self._coords[ids] = X
        self._alive[ids] = True
        self._labels[ids] = -1
        self._core[ids] = False
        from ..partition import route_tree

        leaf = (
            route_tree(self._tree, X) if self._tree
            else np.zeros(m, np.int32)
        )
        self._leaf_of[ids] = leaf
        for i, l in zip(ids, leaf):
            self._leaf_members.setdefault(int(l), []).append(int(i))
        self._n = need
        return ids

    def _check_points(self, X) -> np.ndarray:
        from ..utils.validate import check_query_points

        if getattr(self.model, "_fit_generation", 0) \
                != self._fit_generation:
            raise RuntimeError(
                "model was refit after this LiveModel was built; "
                "rebuild it (model.live())"
            )
        return np.asarray(
            check_query_points(X, self.k), np.float64
        )

    # -- read surface -----------------------------------------------------

    def ids(self) -> np.ndarray:
        """Stable ids of the alive points, ascending."""
        return np.flatnonzero(self._alive[:self._n]).astype(np.int64)

    def points(self) -> np.ndarray:
        return self._coords[:self._n][self._alive[:self._n]].copy()

    def labels(self) -> np.ndarray:
        """Current cluster labels of the alive points (canonical ids —
        stable across updates, not re-densified)."""
        return self._labels[:self._n][self._alive[:self._n]].copy()

    def core_mask(self) -> np.ndarray:
        return self._core[:self._n][self._alive[:self._n]].copy()

    def predict(self, X, return_distance: bool = False):
        """Out-of-sample assignment against the CURRENT index (bitwise
        oracle-exact — the in-place refresh preserves the seal_f32
        contract)."""
        return self.engine.predict(X, return_distance)

    # -- bookkeeping ------------------------------------------------------

    def _note_recluster(self, leaves, n_points) -> None:
        self._counters["recluster_events"] += 1
        self._counters["recluster_points"] += int(n_points)
        self._last_fraction = round(
            len(set(leaves)) / max(self.n_leaves, 1), 6
        )

    def _finish_update(self, kind, m, t0, lat) -> None:
        # Compaction replay re-applies writes the user already counted;
        # its kernel work stays in the recluster counters, but the
        # user-facing write volumes/latencies/batch sizes don't move.
        if not self._replay:
            lat.append((time.perf_counter() - t0) * 1e3)
            self._counters[kind] += int(m)
            self._counters["updates"] += 1
            self._batch_sizes.append(int(m))
        self._mark_dirty()
        self._publish()

    def _mark_dirty(self) -> None:
        """O(1) per update: invalidate the model's derived surfaces;
        the O(N) array copies are deferred to :meth:`_sync_if_dirty`
        (triggered by the DBSCAN properties on first read)."""
        m = self.model
        self._dirty = True
        m._result_cache = None
        m._serve_core_points = None

    def _sync_if_dirty(self) -> None:
        if not self._dirty:
            return
        # Clear FIRST: the assignments below go through DBSCAN's
        # property setters (no recursion), but a re-entrant read during
        # the sync should see the in-progress state, not loop.
        self._dirty = False
        m = self.model
        alive = self._alive[:self._n]
        m.labels_ = self._labels[:self._n][alive].copy()
        m.core_sample_mask_ = self._core[:self._n][alive].copy()
        m.data = self._coords[:self._n][alive].astype(self._data_dtype)
        m._keys = np.flatnonzero(alive).astype(np.int64)
        self._syncs += 1
        self._sync_bytes += int(
            m._labels_v.nbytes + m._core_mask_v.nbytes
            + m._data_v.nbytes + m._keys.nbytes
        )

    def _sync_model(self) -> None:
        """Force-materialize the model surface (save()/checkpoints)."""
        self._sync_if_dirty()

    def _publish(self) -> None:
        def _pct(d, q):
            return round(float(np.percentile(np.asarray(d), q)), 3) \
                if len(d) else 0.0

        from ..parallel import staging

        c = self._counters
        self.stats.update({
            "model": self.handle or "default",
            "points": int(self._alive[:self._n].sum()),
            "cores": int(self._core[:self._n][
                self._alive[:self._n]].sum()),
            "inserts": c["inserts"],
            "deletes": c["deletes"],
            "updates": c["updates"],
            "recluster_events": c["recluster_events"],
            "recluster_points": c["recluster_points"],
            "recluster_tile_fraction": float(self._last_fraction),
            "label_remaps": c["label_remaps"],
            "n_leaves": int(self.n_leaves),
            "index_epoch": int(self.index.epoch),
            "index_delta_bytes": int(self.index.delta_bytes),
            "index_delta_route_bytes": int(
                staging.route_delta_nbytes(
                    getattr(
                        self.index, "delta_route", "serve_index_delta"
                    )
                )
            ),
            "insert_p50_ms": _pct(self._ins_ms, 50),
            "insert_p99_ms": _pct(self._ins_ms, 99),
            "delete_p50_ms": _pct(self._del_ms, 50),
            "delete_p99_ms": _pct(self._del_ms, 99),
            # Warm-compile + lazy-sync economy: the recluster-kernel
            # jit trace paid at build time (excluded from insert p99),
            # and how many O(N) model-surface copies reads actually
            # forced (vs one per update before).  Batched insert(X)
            # amortizes the per-update delta further: index_delta_bytes
            # and the sync cost are per UPDATE, not per row.
            "warm_compile_ms": round(float(self._warm_ms), 3),
            "model_syncs": int(self._syncs),
            "model_sync_bytes": int(self._sync_bytes),
            # Streaming-ingest block (serve.ingest): write-batch sizes
            # applied (singles are 1-row batches), the amortization
            # gauge (recluster events per written row — 1/B for a
            # B-row batch that reclustered once), and the LSM
            # maintenance economy (compaction cycles, their seconds,
            # whole-index generation swaps, and the appended-slab
            # write debt the trigger policy watermarks).
            "batch_sizes": [int(b) for b in self._batch_sizes],
            "reclusters_per_write": round(
                c["recluster_events"]
                / max(c["inserts"] + c["deletes"], 1), 6
            ),
            "recluster_dispatches": c["recluster_dispatches"],
            "compactions": c["compactions"],
            "compaction_s": round(float(self._compaction_s), 3),
            "epoch_swaps": c["epoch_swaps"],
            "index_generation": int(
                getattr(self.index, "generation", 0)
            ),
            "appended_slab_bytes": int(
                getattr(self.index, "appended_slab_bytes", 0)
            ),
        })

    # -- compaction (serve.ingest.Compactor drives these) -----------------

    def begin_compaction_snapshot(self) -> Dict:
        """Freeze the compaction input under the caller's lock: the
        alive ids and a copy of their coordinates (the full-refit
        input).  Ids are append-only and never reused, so the writes
        that land while the refit runs are recoverable at swap time by
        pure id arithmetic — no write-ahead log needed."""
        ids = np.flatnonzero(self._alive[:self._n]).astype(np.int64)
        self._compact_active = True
        return {
            "n": int(self._n),
            "ids": ids,
            "points": self._coords[ids].copy(),
        }

    def _install_generation(self, snap, labels, core, fresh):
        """Atomic epoch swap of a compacted generation, under the
        caller's lock.  Four steps:

        1. drain the engine — readers submitted BEFORE the swap resolve
           against the old generation (zero dropped tickets);
        2. adopt the refit's clustering for the snapshot set (canonical
           labels re-densify here — the LSM re-organization);
        3. swap the fresh index generation in IN PLACE
           (:meth:`CorePointIndex.replace_generation` — every engine
           holding the index object sees it, epoch-keyed replica
           caches re-broadcast);
        4. replay the writes that landed during the refit through the
           normal incremental algebra against the new generation (the
           memtable replay; excluded from user-facing write counters).

        Returns ``(replayed_insert_rows, replayed_delete_rows)``.
        """
        self.engine.drain()
        ids = snap["ids"]
        later = np.arange(snap["n"], self._n, dtype=np.int64)
        later = later[self._alive[later]]
        deleted = ids[~self._alive[ids]]
        labels = np.asarray(labels, np.int32)
        core = np.asarray(core, bool)
        # Step 2: the compacted clustering of the snapshot set (ids
        # deleted or inserted during the refit go through the replay).
        self._labels[:self._n] = -1
        self._core[:self._n] = False
        self._alive[:self._n] = False
        self._alive[ids] = True
        self._labels[ids] = labels
        self._core[ids] = core
        self._next_label = (
            int(labels.max()) + 1 if (labels >= 0).any() else 0
        )
        # Step 3: whole-index generation swap (epoch clock continues).
        self.index.replace_generation(fresh)
        self._counters["epoch_swaps"] += 1
        # Step 4: memtable replay.
        self._replay = True
        try:
            if len(deleted):
                self.delete(deleted)
            if len(later):
                self._do_insert(self._coords[later].copy(), ids=later)
        finally:
            self._replay = False
        self._mark_dirty()
        self._publish()
        return int(len(later)), int(len(deleted))

    def _note_compaction(self, seconds: float) -> None:
        self._counters["compactions"] += 1
        self._compaction_s += float(seconds)
        self._publish()

    # -- persistence ------------------------------------------------------

    def save(self, path: str) -> None:
        """Checkpoint the LIVE state: current points/labels/cores, the
        routing tree, counters, and the mutated index slabs — a
        restarted server resumes serving the updated model
        byte-identically (:func:`pypardis_tpu.checkpoint.save_model`
        grows the live payload).

        Saving MID-COMPACTION is safe and well-defined: the serving
        state (the old generation, every write delta included) is what
        persists; the in-flight partial generation is NOT half-saved —
        a restore either re-runs the compaction (cheaply, via its
        jobstate snapshot) or keeps serving the old generation.  The
        ``compact_pending`` flag rides the checkpoint so the restored
        model knows a cycle was in flight."""
        from ..checkpoint import save_model

        self._sync_model()
        save_model(
            self.model, path,
            live={
                "points": self.points(),
                "labels": self.labels(),
                "core": self.core_mask(),
                "gids": self.ids(),
                "next_label": int(self._next_label),
                "tree": np.asarray(self._tree, np.float64).reshape(-1, 5),
                "counters": dict(self._counters),
                "compact_pending": bool(self._compact_active),
            },
            index=self.index,
        )

    @classmethod
    def load(cls, path: str, **engine_kw) -> "LiveModel":
        """Restore a live checkpoint; point ids re-densify to
        ``0..n_alive-1`` (in the saved id order).

        A checkpoint written mid-compaction restores the SERVING state
        (the pre-swap generation, byte-exact) — the partial generation
        is cleanly discarded, never half-swapped; ``compact_pending``
        is True on the restored model so a server can re-run the
        compaction (its jobstate snapshot makes the re-run cheap)."""
        from ..checkpoint import load_model

        model = load_model(path)
        ck = getattr(model, "_live_ckpt", None)
        if ck is None:
            raise ValueError(
                f"{path} is a plain model checkpoint without live "
                f"state; build a fresh LiveModel(model) instead"
            )
        compact_pending = bool(ck.pop("compact_pending", False))
        index = ck.pop("index")
        old_gids = np.asarray(ck.pop("gids"), np.int64)
        # Saved gids were sparse (deletions); positions restart dense.
        remap = {int(g): i for i, g in enumerate(old_gids)}
        if index.gids is not None:
            g = index.gids
            index.gids = np.asarray(
                [remap.get(int(x), -1) if x >= 0 else -1 for x in g],
                np.int64,
            )
            index._gid_col = None
        live = cls(model, _resume={**ck, "index": index}, **engine_kw)
        counters = ck.get("counters") or {}
        for k, v in counters.items():
            if k in live._counters:
                live._counters[k] = int(v)
        live.compact_pending = compact_pending
        live._publish()
        return live
