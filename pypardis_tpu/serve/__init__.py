"""Serving subsystem: device-resident core-point index + query engine.

The reference stops at ``assignments()`` — a dump of training-set
labels (its dbscan.py:128-134).  This package answers *out-of-sample*
queries ("which cluster does this new point belong to?") at high QPS:

* :class:`CorePointIndex` (:mod:`.index`) — core points + labels of a
  fitted model, bucketed by KD leaf, Morton-sorted, padded to block
  shape, and parked on device through the staging economy
  (:mod:`pypardis_tpu.parallel.staging`, route ``serve_index``) so
  repeated engine builds and refits over the same clustering re-ship
  nothing;
* the query kernels (:mod:`pypardis_tpu.ops.query` and the Pallas twin
  in :mod:`pypardis_tpu.ops.pallas_kernels`) — tiled min-squared-
  distance-within-eps scans of each query tile against its leaf's core
  slab, exact against the numpy brute-force oracle by construction;
* :class:`QueryEngine` (:mod:`.engine`) — ``predict`` plus a bounded
  submit/drain queue that coalesces small requests into padded device
  batches and double-buffers host routing against device execution,
  reporting QPS / batch-fill / latency percentiles through the obs
  registry into ``report()["serving"]``.

Surface via the model: ``DBSCAN.predict(X)`` / ``DBSCAN.query_engine()``;
persistence via :func:`pypardis_tpu.checkpoint.save_index` /
``load_index`` (and ``save_model`` checkpoints carry the core points, so
a restarted process serves without re-clustering).

The write path mirrors it: :class:`LiveModel` (:mod:`.live`) maintains
the clustering under insert/delete, and the streaming-ingest layer
(:mod:`.ingest`) adds batched writes (:class:`IngestQueue` coalescing,
one recluster dispatch + one index delta per batch) and LSM-style
background compaction (:class:`Compactor`) with an atomic whole-index
epoch swap that never drops in-flight tickets.

Above all of it sits the multi-tenant plane: :class:`ModelGateway`
(:mod:`.gateway`) composes N model handles — each index staged under
its own device route — behind one registry with a device-slab byte
budget (LRU spill via ``save_index``, byte-identical readmission via
``load_index``) and one shared admission controller (per-tenant token
buckets; over-quota requests shed with :class:`TenantQuotaExceeded`
before touching any engine, full queues with :class:`QueueFull`,
blown deadlines with :class:`DeadlineExceeded`).
:func:`gateway_load` drives Zipf-distributed tenant traffic through
it (``make gateway-probe``).
"""

from .engine import DeadlineExceeded, QueryEngine, QueueFull, \
    ReplicatedQueryEngine
from .gateway import (
    GatewayError,
    ModelGateway,
    ModelNotRegistered,
    StaleModelHandle,
    TenantQuotaExceeded,
)
from .index import CorePointIndex, build_index
from .ingest import Compactor, IngestQueue
from .live import LiveModel
from .load import gateway_load, sustained_load

__all__ = [
    "Compactor",
    "CorePointIndex",
    "DeadlineExceeded",
    "GatewayError",
    "IngestQueue",
    "ModelGateway",
    "ModelNotRegistered",
    "QueryEngine",
    "QueueFull",
    "ReplicatedQueryEngine",
    "StaleModelHandle",
    "TenantQuotaExceeded",
    "LiveModel",
    "build_index",
    "gateway_load",
    "sustained_load",
]
