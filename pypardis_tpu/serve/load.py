"""Sustained multi-client load harness for the serving subsystem.

Drives N concurrent client threads against a :class:`~pypardis_tpu.
serve.QueryEngine` under **Poisson arrivals** (exponential inter-arrival
sleeps per client — the standard open-loop traffic model), with an
optional write mix routed through a :class:`~pypardis_tpu.serve.live.
LiveModel`.  A dedicated drainer thread pumps ``drain()`` continuously,
so request latency includes real queue wait and coalescing — the
serving numbers a production deployment would see, not a closed-loop
best case.

The engine's submit/drain surface is single-threaded by design (the
double-buffered drain rotates pooled staging buffers); the harness
serializes access through one lock, which is also the honest model on
the CPU CI host — contention shows up in p99, not in corruption.

Measured per run (the ``live_load`` bench row's payload): sustained
qps over the harness wall, p50/p99 request latency, batch fill,
and — for writes — **update-visible latency**: the wall time from a
write entering :meth:`LiveModel.insert` until a ``predict`` of the
written point returns its post-update label through the refreshed
index.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

import numpy as np


def sustained_load(
    engine,
    *,
    clients: int = 4,
    duration_s: float = 2.0,
    rate_hz: float = 200.0,
    batch_rows: int = 16,
    write_fraction: float = 0.0,
    live=None,
    query_sampler: Optional[Callable] = None,
    seed: int = 0,
    submit_timeout_s: Optional[float] = None,
) -> Dict:
    """Run the harness; returns the schema'd stats dict.

    ``rate_hz`` is the per-client request rate (Poisson); ``clients``
    threads run open-loop for ``duration_s``.  ``write_fraction`` of
    requests become single-point inserts against ``live`` (required
    when > 0); the rest submit ``batch_rows``-row query batches.
    ``query_sampler(rng, n) -> (n, k)`` supplies query coordinates
    (default: uniform over the index's core bounding box ± eps).

    Fault mode: ``submit_timeout_s`` attaches a per-ticket deadline, a
    full queue is counted as a shed (the client backs off — never
    aborts the harness), and deadline-failed tickets are counted
    rather than crashed on — so the harness runs clean under an
    injected ``serve.drain`` hang (``PYPARDIS_FAULTS``) and reports
    how the serving tier degraded (``shed`` / ``deadline_failures``
    in the stats row).
    """
    if write_fraction > 0 and live is None:
        raise ValueError(
            "write_fraction > 0 needs a LiveModel (live=...)"
        )
    from .engine import QueueFull
    index = engine.index
    if query_sampler is None:
        sel = np.asarray(index.labels) != np.iinfo(np.int32).max
        if sel.any():
            lo = index.coords[:, sel].min(axis=1) - index.eps
            hi = index.coords[:, sel].max(axis=1) + index.eps
            center = index.center
        else:
            lo = np.full(index.d, -1.0)
            hi = np.full(index.d, 1.0)
            center = np.zeros(index.d)

        def query_sampler(rng, n):
            # Raw-frame queries (prepare_queries re-centers).
            return rng.uniform(lo, hi, size=(n, index.d)) + center

    lock = threading.Lock()
    tickets: list = []
    visible_ms: list = []
    errors: list = []
    stop = threading.Event()
    t_start = time.perf_counter()
    deadline = t_start + float(duration_s)
    n_writes = [0]
    n_shed = [0]

    def client(cid: int) -> None:
        rng = np.random.default_rng(seed * 1000 + cid)
        while time.perf_counter() < deadline and not stop.is_set():
            # Poisson arrivals: exponential inter-arrival gap.
            time.sleep(float(rng.exponential(1.0 / rate_hz)))
            if time.perf_counter() >= deadline:
                break
            try:
                if live is not None and rng.random() < write_fraction:
                    q = np.asarray(query_sampler(rng, 1))
                    t0 = time.perf_counter()
                    with lock:
                        ids = live.insert(q)
                        labs = engine.predict(q)
                    visible_ms.append(
                        (time.perf_counter() - t0) * 1e3
                    )
                    del ids, labs
                    n_writes[0] += 1
                else:
                    q = np.asarray(query_sampler(rng, batch_rows))
                    with lock:
                        tickets.append(
                            engine.submit(
                                q, timeout_s=submit_timeout_s
                            )
                        )
            except QueueFull:
                # Shed load: the bounded queue refused this request —
                # the open-loop client drops it and keeps its arrival
                # process going (the production behavior the counter
                # measures), never aborts the harness.
                n_shed[0] += 1
            except Exception as e:  # noqa: BLE001 — harness must drain
                errors.append(e)
                stop.set()
                return

    def drainer() -> None:
        while not stop.is_set():
            try:
                with lock:
                    engine.drain()
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                stop.set()
                return
            time.sleep(0.0005)
            if time.perf_counter() >= deadline:
                return  # stragglers resolve in the final drain below

    threads = [
        threading.Thread(target=client, args=(c,), daemon=True)
        for c in range(int(clients))
    ]
    pump = threading.Thread(target=drainer, daemon=True)
    for t in threads:
        t.start()
    pump.start()
    for t in threads:
        t.join()
    stop.set()
    pump.join()
    with lock:
        engine.drain()  # resolve any straggler tickets
    wall = time.perf_counter() - t_start
    if errors:
        raise errors[0]

    lat = np.asarray(
        [t.latency_ms for t in tickets if t.latency_ms is not None],
        np.float64,
    )
    queries = int(sum(t.n for t in tickets if t.done and not t.failed))
    failed = int(sum(1 for t in tickets if t.failed))
    vis = np.asarray(visible_ms, np.float64)

    def _pct(a, q):
        return round(float(np.percentile(a, q)), 3) if len(a) else 0.0

    stats = engine.serving_stats()
    return {
        "arrival": "poisson",
        "clients": int(clients),
        "duration_s": round(wall, 3),
        "rate_hz": float(rate_hz),
        "requests": len(tickets) + int(n_writes[0]),
        "queries": queries,
        "writes": int(n_writes[0]),
        "write_fraction": float(write_fraction),
        "qps": round(queries / wall, 1) if wall > 0 else 0.0,
        "p50_ms": _pct(lat, 50),
        "p99_ms": _pct(lat, 99),
        "batch_fill": stats.get("batch_fill", 0.0),
        "update_visible_p50_ms": _pct(vis, 50),
        "update_visible_p99_ms": _pct(vis, 99),
        "index_epoch": stats.get("index_epoch", 0),
        # Fault-mode telemetry: queue-full refusals seen by the open-
        # loop clients, and tickets that missed their deadline (both 0
        # on a clean run with no timeout).
        "shed": int(n_shed[0]),
        "deadline_failures": failed,
        "submit_timeout_s": (
            float(submit_timeout_s) if submit_timeout_s else 0.0
        ),
    }
