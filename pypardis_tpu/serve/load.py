"""Sustained multi-client load harness for the serving subsystem.

Drives N concurrent client threads against a :class:`~pypardis_tpu.
serve.QueryEngine` under **Poisson arrivals** (exponential inter-arrival
sleeps per client — the standard open-loop traffic model), with an
optional write mix routed through a :class:`~pypardis_tpu.serve.live.
LiveModel`.  A dedicated drainer thread pumps ``drain()`` continuously,
so request latency includes real queue wait and coalescing — the
serving numbers a production deployment would see, not a closed-loop
best case.

The engine's submit/drain surface is single-threaded by design (the
double-buffered drain rotates pooled staging buffers); the harness
serializes access through one lock, which is also the honest model on
the CPU CI host — contention shows up in p99, not in corruption.

Measured per run (the ``live_load`` bench row's payload): sustained
qps over the harness wall, p50/p99 request latency, batch fill,
and — for writes — **update-visible latency**: the wall time from a
write entering :meth:`LiveModel.insert` until a ``predict`` of the
written point returns its post-update label through the refreshed
index.

The streaming-ingest mode (``writers > 0``) adds a dedicated Poisson
**writer population** whose batched writes coalesce through an
:class:`~pypardis_tpu.serve.ingest.IngestQueue`, and an optional
background :class:`~pypardis_tpu.serve.ingest.Compactor` whose epoch
swap happens under the harness lock mid-run — the returned stats then
carry write throughput/coalescing, update-visible latency through the
batched path, the zero-dropped-tickets contract, and read-p99
inside-vs-outside the compaction windows (the ``ingest@1`` row's
payload, ``make ingest-probe``).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, Optional

import numpy as np

from ..obs.export import Histogram, attach_exporters


def sustained_load(engine, **kw) -> Dict:
    """Run the harness; returns the schema'd stats dict (see
    :func:`_sustained_load` for every knob).

    This wrapper owns the live export plane: when
    ``PYPARDIS_METRICS_PORT`` / ``PYPARDIS_METRICS_SNAPSHOT`` are set,
    the engine's registry — the serving latency histogram included —
    is scrapeable/snapshotted for the duration of the run.
    """
    exporters = attach_exporters(getattr(engine, "recorder", None))
    try:
        return _sustained_load(engine, **kw)
    finally:
        if exporters is not None:
            exporters.close()


def _sustained_load(
    engine,
    *,
    clients: int = 4,
    duration_s: float = 2.0,
    rate_hz: float = 200.0,
    batch_rows: int = 16,
    write_fraction: float = 0.0,
    live=None,
    query_sampler: Optional[Callable] = None,
    seed: int = 0,
    submit_timeout_s: Optional[float] = None,
    writers: int = 0,
    write_rate_hz: float = 60.0,
    write_batch_rows: int = 8,
    delete_fraction: float = 0.2,
    write_sampler: Optional[Callable] = None,
    ingest=None,
    compactor=None,
    compact_at_s: Optional[float] = None,
) -> Dict:
    """Run the harness; returns the schema'd stats dict.

    ``rate_hz`` is the per-client request rate (Poisson); ``clients``
    threads run open-loop for ``duration_s``.  ``write_fraction`` of
    requests become single-point inserts against ``live`` (required
    when > 0); the rest submit ``batch_rows``-row query batches.
    ``query_sampler(rng, n) -> (n, k)`` supplies query coordinates
    (default: uniform over the index's core bounding box ± eps).

    **Writer population (the streaming-ingest mixed-traffic mode)**:
    ``writers`` dedicated Poisson write clients run alongside the
    readers, each submitting ``write_batch_rows``-row writes (a
    ``delete_fraction`` share deletes its own previously-acknowledged
    inserts) into an :class:`~pypardis_tpu.serve.ingest.IngestQueue`
    (one is built over ``live`` when not passed) — the pump thread
    flushes it next to every drain, so writes coalesce into batches
    exactly the way reads do.  **Update-visible latency** is measured
    per write ticket: submit → coalesced flush → a ``predict`` of the
    written point answering through the refreshed index.  When a
    ``compactor`` is given, its lock serializes the harness (writers,
    drains, and the epoch swap all agree on one lock); the pump starts
    a background cycle at ``compact_at_s`` seconds (and whenever the
    watermark policy fires), and read latencies are classified against
    the compactor's cycle windows — ``read_p99_during_compaction_ms``
    vs ``read_p99_outside_ms`` is the compaction-overlap degradation
    the ``ingest@1`` row reports.  The zero-dropped-tickets contract is
    explicit: ``dropped_tickets`` counts read tickets left unresolved
    after the final drain (always 0 — the swap drains in-flight
    tickets against the old generation rather than dropping them).

    Fault mode: ``submit_timeout_s`` attaches a per-ticket deadline, a
    full queue is counted as a shed (the client backs off — never
    aborts the harness), and deadline-failed tickets are counted
    rather than crashed on — so the harness runs clean under an
    injected ``serve.drain`` hang (``PYPARDIS_FAULTS``) and reports
    how the serving tier degraded (``shed`` / ``deadline_failures``
    in the stats row).
    """
    if write_fraction > 0 and live is None:
        raise ValueError(
            "write_fraction > 0 needs a LiveModel (live=...)"
        )
    if writers > 0 and live is None and ingest is None:
        raise ValueError(
            "writers > 0 needs a LiveModel (live=...) or an "
            "IngestQueue (ingest=...)"
        )
    if writers > 0 and ingest is None:
        from .ingest import IngestQueue

        ingest = IngestQueue(live)
    from .engine import QueueFull
    index = engine.index
    if query_sampler is None:
        sel = np.asarray(index.labels) != np.iinfo(np.int32).max
        if sel.any():
            lo = index.coords[:, sel].min(axis=1) - index.eps
            hi = index.coords[:, sel].max(axis=1) + index.eps
            center = index.center
        else:
            lo = np.full(index.d, -1.0)
            hi = np.full(index.d, 1.0)
            center = np.zeros(index.d)

        def query_sampler(rng, n):
            # Raw-frame queries (prepare_queries re-centers).
            return rng.uniform(lo, hi, size=(n, index.d)) + center

    # One lock serializes the engine, the ingest queue, AND the epoch
    # swap: when a compactor rides along, its lock IS the harness lock,
    # so the swap's drain-then-replace is atomic against every client.
    lock = compactor.lock if compactor is not None else threading.Lock()
    # Resolved tickets fold into bounded histograms at each pump sweep
    # and are discarded — the harness holds O(in-flight) tickets, never
    # O(requests), and the reported percentiles are windowed.
    pending: deque = deque()
    hist_all = Histogram()
    hist_in = Histogram()   # reads completing inside a compaction cycle
    hist_out = Histogram()
    hist_vis = Histogram()  # update-visible round trips
    n_tickets = [0]
    n_queries = [0]
    n_failed = [0]
    errors: list = []
    stop = threading.Event()
    t_start = time.perf_counter()
    deadline = t_start + float(duration_s)
    n_writes = [0]
    n_shed = [0]
    # Start of the compaction cycle currently in flight (None outside
    # one): completed cycles land in compactor.windows, but a read
    # finishing DURING the cycle must classify as inside before the
    # window closes.
    cycle_t0: list = [None]

    def _inside_compaction(done_at: float) -> bool:
        windows = getattr(compactor, "windows", ()) or ()
        if any(a <= done_at <= b for a, b in windows):
            return True
        t0 = cycle_t0[0]
        return t0 is not None and done_at >= t0

    def _sweep_resolved() -> None:
        """Fold resolved read tickets into the histograms and drop
        them (caller holds the lock)."""
        for _ in range(len(pending)):
            t = pending.popleft()
            if not t.done:
                pending.append(t)
                continue
            if t.failed:
                n_failed[0] += 1
            else:
                n_queries[0] += t.n
            if t.latency_ms is not None:
                hist_all.observe(t.latency_ms)
                done_at = t._t_submit + t.latency_ms / 1e3
                (hist_in if _inside_compaction(done_at)
                 else hist_out).observe(t.latency_ms)

    def client(cid: int) -> None:
        rng = np.random.default_rng(seed * 1000 + cid)
        while time.perf_counter() < deadline and not stop.is_set():
            # Poisson arrivals: exponential inter-arrival gap.
            time.sleep(float(rng.exponential(1.0 / rate_hz)))
            if time.perf_counter() >= deadline:
                break
            try:
                if live is not None and rng.random() < write_fraction:
                    q = np.asarray(query_sampler(rng, 1))
                    t0 = time.perf_counter()
                    with lock:
                        ids = live.insert(q)
                        labs = engine.predict(q)
                    hist_vis.observe(
                        (time.perf_counter() - t0) * 1e3
                    )
                    del ids, labs
                    n_writes[0] += 1
                else:
                    q = np.asarray(query_sampler(rng, batch_rows))
                    with lock:
                        pending.append(
                            engine.submit(
                                q, timeout_s=submit_timeout_s
                            )
                        )
                        n_tickets[0] += 1
            except QueueFull:
                # Shed load: the bounded queue refused this request —
                # the open-loop client drops it and keeps its arrival
                # process going (the production behavior the counter
                # measures), never aborts the harness.
                n_shed[0] += 1
            except Exception as e:  # noqa: BLE001 — harness must drain
                errors.append(e)
                stop.set()
                return

    if write_sampler is None:
        write_sampler = query_sampler

    def writer(wid: int) -> None:
        """A dedicated Poisson write client: batches into the ingest
        queue, deletes a share of its own acknowledged inserts."""
        rng = np.random.default_rng(seed * 1000 + 500 + wid)
        mine: list = []  # resolved insert tickets not yet consumed
        own_ids: list = []
        while time.perf_counter() < deadline and not stop.is_set():
            time.sleep(float(rng.exponential(1.0 / write_rate_hz)))
            if time.perf_counter() >= deadline:
                break
            # Harvest acknowledged ids from earlier tickets.
            still = []
            for t in mine:
                if t.done:
                    if not t.failed and t.ids is not None:
                        own_ids.extend(int(i) for i in t.ids)
                else:
                    still.append(t)
            mine = still
            try:
                if own_ids and rng.random() < delete_fraction:
                    take = min(len(own_ids), int(write_batch_rows))
                    ids = [own_ids.pop() for _ in range(take)]
                    with lock:
                        ingest.submit_delete(ids)
                else:
                    q = np.asarray(
                        write_sampler(rng, int(write_batch_rows))
                    )
                    with lock:
                        t = ingest.submit_insert(q)
                    mine.append(t)
                n_writes[0] += 1
            except QueueFull:
                n_shed[0] += 1
            except Exception as e:  # noqa: BLE001 — harness must drain
                errors.append(e)
                stop.set()
                return

    compact_started = [False]

    def pump_once() -> None:
        """One serialized pump round: drain reads, flush writes,
        measure update visibility, and fire the compactor."""
        with lock:
            engine.drain()
            if ingest is not None:
                resolved = ingest.flush()
                now = time.perf_counter()
                probed = False
                for t in resolved:
                    if t.failed or t.kind != "insert":
                        continue
                    if not probed and t.ids is not None and len(t.ids) \
                            and live is not None:
                        # One predict per flush: the written point
                        # answers through the refreshed index — the
                        # update-visible round trip.
                        engine.predict(
                            live._coords[t.ids[:1]].copy()
                        )
                        probed = True
                    t.visible_ms = (now - t._t_submit) * 1e3
                    hist_vis.observe(t.visible_ms)
            _sweep_resolved()
        if compactor is not None:
            elapsed = time.perf_counter() - t_start
            due = (
                compact_at_s is not None and elapsed >= compact_at_s
                and not compact_started[0]
            )
            if due and not compactor.running:
                compactor.start()
                compact_started[0] = True
            elif compactor.maybe_compact():
                compact_started[0] = True
            if compactor.running:
                if cycle_t0[0] is None:
                    cycle_t0[0] = time.perf_counter()
            else:
                cycle_t0[0] = None

    def drainer() -> None:
        while not stop.is_set():
            try:
                pump_once()
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                stop.set()
                return
            time.sleep(0.0005)
            if time.perf_counter() >= deadline:
                return  # stragglers resolve in the final drain below

    threads = [
        threading.Thread(target=client, args=(c,), daemon=True)
        for c in range(int(clients))
    ] + [
        threading.Thread(target=writer, args=(w,), daemon=True)
        for w in range(int(writers))
    ]
    pump = threading.Thread(target=drainer, daemon=True)
    for t in threads:
        t.start()
    pump.start()
    for t in threads:
        t.join()
    stop.set()
    pump.join()
    if compactor is not None and compactor._thread is not None:
        compactor.join()  # the swap lands; its error (if any) raises
    cycle_t0[0] = None  # completed cycles are in compactor.windows now
    with lock:
        engine.drain()  # resolve any straggler tickets
        if ingest is not None:
            ingest.flush()
        _sweep_resolved()
    wall = time.perf_counter() - t_start
    if errors:
        raise errors[0]

    # Tickets still unresolved after the final drain (the zero-dropped
    # contract) — everything resolved was folded into the histograms
    # and discarded at sweep time.
    dropped = len(pending)
    # Compaction-overlap classification happened at sweep time (a read
    # completing inside a live cycle classifies before the window
    # closes); p99s here are lifetime — each side's window may have
    # expired by end of run.
    p99_in = hist_in.percentile(99, window=False) \
        if hist_in.count else 0.0
    p99_out = hist_out.percentile(99, window=False) \
        if hist_out.count else 0.0

    stats = engine.serving_stats()
    return {
        "arrival": "poisson",
        "clients": int(clients),
        "duration_s": round(wall, 3),
        "rate_hz": float(rate_hz),
        "requests": int(n_tickets[0]) + int(n_writes[0]),
        "queries": int(n_queries[0]),
        "writes": int(n_writes[0]),
        "write_fraction": float(write_fraction),
        "qps": round(n_queries[0] / wall, 1) if wall > 0 else 0.0,
        # Windowed percentiles (PYPARDIS_HIST_WINDOW_S): how serving is
        # doing NOW, not averaged over the whole run.
        "p50_ms": hist_all.percentile(50),
        "p99_ms": hist_all.percentile(99),
        "latency_hist": hist_all.snapshot(),
        "batch_fill": stats.get("batch_fill", 0.0),
        "update_visible_p50_ms": hist_vis.percentile(50),
        "update_visible_p99_ms": hist_vis.percentile(99),
        "visible_hist": hist_vis.snapshot(),
        "index_epoch": stats.get("index_epoch", 0),
        # Fault-mode telemetry: queue-full refusals seen by the open-
        # loop clients, and tickets that missed their deadline (both 0
        # on a clean run with no timeout).
        "shed": int(n_shed[0]),
        "deadline_failures": int(n_failed[0]),
        "submit_timeout_s": (
            float(submit_timeout_s) if submit_timeout_s else 0.0
        ),
        # Streaming-ingest block (writers + background compaction):
        # write volumes/coalescing, zero-dropped-tickets contract, and
        # the compaction-overlap degradation (read p99 with a cycle in
        # flight vs without — 0.0 when no cycle overlapped the run).
        "writers": int(writers),
        "write_rows": int(getattr(ingest, "rows", 0)),
        "write_batches": int(getattr(ingest, "batches", 0)),
        "mean_write_batch": (
            ingest.stats()["mean_batch_rows"] if ingest is not None
            else 0.0
        ),
        "write_qps": (
            round(getattr(ingest, "rows", 0) / wall, 1)
            if wall > 0 else 0.0
        ),
        "write_failures": int(
            getattr(ingest, "failed_batches", 0)
        ),
        "dropped_tickets": dropped,
        "compactions": int(
            getattr(compactor, "stats", {}).get("compactions", 0)
            if compactor is not None else 0
        ),
        "epoch_swaps": int(
            live.stats.get("epoch_swaps", 0) if live is not None else 0
        ),
        "compaction_s": (
            round(float(compactor.stats.get("compaction_s", 0.0)), 3)
            if compactor is not None else 0.0
        ),
        "read_p99_during_compaction_ms": p99_in,
        "read_p99_outside_ms": p99_out,
        "compaction_overlap_degradation": (
            round(p99_in / p99_out, 3)
            if p99_in > 0 and p99_out > 0 else 0.0
        ),
    }


def gateway_load(gateway, model_ids, **kw) -> Dict:
    """Multi-tenant fleet traffic against a :class:`~pypardis_tpu.
    serve.gateway.ModelGateway` (see :func:`_gateway_load` for every
    knob); attaches the live export plane for the run the way
    :func:`sustained_load` does."""
    exporters = attach_exporters(getattr(gateway, "recorder", None))
    try:
        return _gateway_load(gateway, model_ids, **kw)
    finally:
        if exporters is not None:
            exporters.close()


def _gateway_load(
    gateway,
    model_ids,
    *,
    tenants: int = 4,
    clients_per_tenant: int = 1,
    duration_s: float = 2.0,
    rate_hz: float = 120.0,
    batch_rows: int = 8,
    zipf_s: float = 1.2,
    write_fraction: float = 0.0,
    seed: int = 0,
    submit_timeout_s: Optional[float] = None,
    refresh_at_s: Optional[float] = None,
    refresher: Optional[Callable] = None,
    query_sampler: Optional[Callable] = None,
) -> Dict:
    """Drive ``tenants`` x ``clients_per_tenant`` open-loop Poisson
    clients through the gateway's admission gate.

    Each client's per-request model choice is **Zipf-distributed**
    (p(rank) proportional to ``(rank+1)**-zipf_s``) over a per-tenant
    *rotation* of ``model_ids`` — every tenant has a different hot
    model, so under a residency budget the fleet's long tail churns
    through eviction/readmission while each tenant's head stays warm
    (the access pattern LRU is built for).  ``write_fraction`` of a
    tenant's requests become single-point live inserts when the chosen
    model is a live handle (measured as update-visible round trips);
    non-live choices fall back to reads.

    Sheds are first-class: :class:`~pypardis_tpu.serve.gateway.
    TenantQuotaExceeded` (per-tenant quota) and
    :class:`~pypardis_tpu.serve.QueueFull` are counted per tenant, and
    the client backs off — the harness never aborts on admission
    control doing its job.  ``refresher()`` (e.g. a closure around
    ``gateway.refresh``) fires once from the pump thread at
    ``refresh_at_s`` seconds — the hot swap lands mid-traffic, and the
    zero-dropped-tickets contract is checked the same way the ingest
    harness checks the Compactor's.

    Read latencies are classified against the gateway's eviction and
    swap windows (``read_p99_in_window_ms`` vs
    ``read_p99_outside_ms``) — residency churn and epoch swaps are
    synchronous under the gateway lock, so completed windows are
    authoritative by sweep time.
    """
    from .engine import QueueFull
    from .gateway import TenantQuotaExceeded

    model_ids = [str(m) for m in model_ids]
    if not model_ids:
        raise ValueError("gateway_load needs at least one model id")
    tenant_names = [f"t{i:02d}" for i in range(int(tenants))]
    lock = gateway.lock
    # Zipf pmf over model ranks, shared by every client; each tenant
    # rotates the model order so rank 0 (the hot model) differs.
    ranks = np.arange(len(model_ids), dtype=np.float64)
    pmf = (ranks + 1.0) ** -float(zipf_s)
    pmf /= pmf.sum()

    bounds: Dict[str, tuple] = {}

    def _default_sampler(rng, n, mid):
        # Lazily captured per-model sampling box (resolving the handle
        # under the lock readmits an evicted model — the serving path).
        box = bounds.get(mid)
        if box is None:
            with lock:
                idx = gateway.handle(mid).index
                sel = (np.asarray(idx.labels)
                       != np.iinfo(np.int32).max)
                if sel.any():
                    lo = idx.coords[:, sel].min(axis=1) - idx.eps
                    hi = idx.coords[:, sel].max(axis=1) + idx.eps
                    center = idx.center
                else:
                    lo = np.full(idx.d, -1.0)
                    hi = np.full(idx.d, 1.0)
                    center = np.zeros(idx.d)
                box = bounds[mid] = (lo, hi, center, int(idx.d))
        lo, hi, center, d = box
        return rng.uniform(lo, hi, size=(n, d)) + center

    if query_sampler is None:
        query_sampler = _default_sampler

    pending: deque = deque()  # (ticket, t_submit) for window classing
    hist_all = Histogram()
    hist_in = Histogram()   # reads completing inside evict/swap windows
    hist_out = Histogram()
    hist_vis = Histogram()
    n_tickets = [0]
    n_queries = [0]
    n_failed = [0]
    n_writes = [0]
    shed_by_tenant = {t: 0 for t in tenant_names}
    errors: list = []
    stop = threading.Event()
    t_start = time.perf_counter()
    deadline = t_start + float(duration_s)

    def _windows():
        return list(gateway.evict_windows) + list(gateway.swap_windows)

    def _sweep_resolved() -> None:
        windows = _windows()
        for _ in range(len(pending)):
            t, t_sub = pending.popleft()
            if not t.done:
                pending.append((t, t_sub))
                continue
            if t.failed:
                n_failed[0] += 1
            else:
                n_queries[0] += t.n
            if t.latency_ms is not None:
                hist_all.observe(t.latency_ms)
                done_at = t_sub + t.latency_ms / 1e3
                (hist_in if any(a <= done_at <= b
                                for a, b in windows)
                 else hist_out).observe(t.latency_ms)

    def client(tenant: str, tidx: int, cid: int) -> None:
        rng = np.random.default_rng(
            seed * 10000 + tidx * 100 + cid
        )
        order = list(np.roll(model_ids, tidx))
        while time.perf_counter() < deadline and not stop.is_set():
            time.sleep(float(rng.exponential(1.0 / rate_hz)))
            if time.perf_counter() >= deadline:
                break
            mid = order[int(rng.choice(len(order), p=pmf))]
            try:
                q = np.asarray(query_sampler(rng, batch_rows, mid))
                if write_fraction > 0 and rng.random() < write_fraction:
                    with lock:
                        h = gateway.handle(mid)
                        if h.live is not None:
                            t0 = time.perf_counter()
                            h.live.insert(q[:1])
                            gateway.predict(
                                mid, q[:1], tenant=tenant,
                                timeout_s=submit_timeout_s,
                            )
                            hist_vis.observe(
                                (time.perf_counter() - t0) * 1e3
                            )
                            n_writes[0] += 1
                            continue
                with lock:
                    t = gateway.submit(
                        mid, q, tenant=tenant,
                        timeout_s=submit_timeout_s,
                    )
                    pending.append((t, t._t_submit))
                    n_tickets[0] += 1
            except (TenantQuotaExceeded, QueueFull):
                # Admission control working as designed: the open-loop
                # client drops the request and keeps arriving.
                shed_by_tenant[tenant] += 1
            except Exception as e:  # noqa: BLE001 — harness must drain
                errors.append(e)
                stop.set()
                return

    refreshed = [False]

    def pump() -> None:
        while not stop.is_set():
            try:
                with lock:
                    gateway.drain()
                    _sweep_resolved()
                if (
                    refresher is not None and not refreshed[0]
                    and refresh_at_s is not None
                    and time.perf_counter() - t_start >= refresh_at_s
                ):
                    refreshed[0] = True
                    refresher()
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                stop.set()
                return
            time.sleep(0.0005)
            if time.perf_counter() >= deadline:
                return  # stragglers resolve in the final drain below

    threads = [
        threading.Thread(
            target=client, args=(tenant, tidx, cid), daemon=True
        )
        for tidx, tenant in enumerate(tenant_names)
        for cid in range(int(clients_per_tenant))
    ]
    pump_t = threading.Thread(target=pump, daemon=True)
    for t in threads:
        t.start()
    pump_t.start()
    for t in threads:
        t.join()
    stop.set()
    pump_t.join()
    if refresher is not None and not refreshed[0]:
        refreshed[0] = True
        refresher()  # a short run must still exercise the swap
    with lock:
        gateway.drain()
        _sweep_resolved()
    wall = time.perf_counter() - t_start
    if errors:
        raise errors[0]

    dropped = len(pending)
    p99_in = hist_in.percentile(99, window=False) \
        if hist_in.count else 0.0
    p99_out = hist_out.percentile(99, window=False) \
        if hist_out.count else 0.0
    report = gateway.gateway_report()
    return {
        "arrival": "poisson-zipf",
        "zipf_s": float(zipf_s),
        "tenants": int(tenants),
        "clients_per_tenant": int(clients_per_tenant),
        "models": len(model_ids),
        "duration_s": round(wall, 3),
        "rate_hz": float(rate_hz),
        "requests": int(n_tickets[0]) + int(n_writes[0]),
        "queries": int(n_queries[0]),
        "writes": int(n_writes[0]),
        "write_fraction": float(write_fraction),
        "qps": round(n_queries[0] / wall, 1) if wall > 0 else 0.0,
        "p50_ms": hist_all.percentile(50),
        "p99_ms": hist_all.percentile(99),
        "latency_hist": hist_all.snapshot(),
        "update_visible_p50_ms": hist_vis.percentile(50),
        "update_visible_p99_ms": hist_vis.percentile(99),
        "shed": int(sum(shed_by_tenant.values())),
        "shed_by_tenant": {
            t: int(n) for t, n in shed_by_tenant.items()
        },
        "deadline_failures": int(n_failed[0]),
        "submit_timeout_s": (
            float(submit_timeout_s) if submit_timeout_s else 0.0
        ),
        # The zero-dropped-tickets contract across eviction,
        # readmission, AND the mid-run epoch swap.
        "dropped_tickets": dropped,
        # Residency-churn / swap overlap: read p99 completing inside an
        # eviction-or-swap window vs fully outside one.
        "read_p99_in_window_ms": p99_in,
        "read_p99_outside_ms": p99_out,
        "window_degradation": (
            round(p99_in / p99_out, 3)
            if p99_in > 0 and p99_out > 0 else 0.0
        ),
        "gateway": report,
    }
