"""Micro-batching query engine over a :class:`CorePointIndex`.

``predict(X)`` is the sync path; ``submit(X)`` / ``drain()`` is the
serving path: a bounded queue coalesces small requests into padded
device batches, and the drain loop double-buffers — while the device
executes batch *i*, the host routes and assembles batch *i+1* (the same
discipline as the fit pipeline's ``_chained_tables_overlap``).  The
rotation barrier is the result fetch: a batch's pooled host staging
buffer goes back to the pool only after its packed result has
materialized on host, so an in-flight transfer can never alias a reused
buffer.

Telemetry rides the obs registry (gauges ``serving.*``): QPS over
engine-busy wall time, batch-fill ratio (real routed rows / padded
device rows), and p50/p99 request latency — surfaced as the
``serving`` block of ``DBSCAN.report()`` and validated by
``scripts/check_bench_json.py``.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Dict, Optional

import numpy as np

from ..ops.query import _INT_INF, unpack_query_result
from .index import CorePointIndex, build_index


class QueueFull(RuntimeError):
    """``submit`` backpressure: the bounded queue is at ``max_pending``.
    Counted as a shed (``serving.shed_total``) — the Clipper-style
    load-shedding signal a saturated serving tier must surface rather
    than buffer unboundedly."""


class DeadlineExceeded(RuntimeError):
    """A ticket's ``timeout_s`` elapsed before its result was usable.
    The ticket is FAILED — a result delivered after its SLA is a miss,
    and a stuck drain must fail tickets instead of hanging callers."""


class QueryTicket:
    """One submitted request; resolved (or failed) by the next
    ``drain()``."""

    __slots__ = (
        "n", "labels", "d2", "_t_submit", "latency_ms", "_q",
        "deadline", "error",
    )

    def __init__(self, n: int, q: np.ndarray,
                 timeout_s: Optional[float] = None):
        self.n = int(n)
        self.labels: Optional[np.ndarray] = None
        self.d2: Optional[np.ndarray] = None
        self.latency_ms: Optional[float] = None
        self._t_submit = time.perf_counter()
        self._q = q
        self.deadline = (
            self._t_submit + float(timeout_s)
            if timeout_s is not None else None
        )
        self.error: Optional[BaseException] = None

    @property
    def done(self) -> bool:
        return self.labels is not None or self.error is not None

    @property
    def failed(self) -> bool:
        return self.error is not None

    def result(self, return_distance: bool = False):
        if self.error is not None:
            raise self.error
        if self.labels is None:
            raise RuntimeError(
                "ticket not resolved yet; call QueryEngine.drain() first"
            )
        if return_distance:
            return self.labels, np.sqrt(self.d2)
        return self.labels


class _Inflight:
    __slots__ = ("packed", "rowmap", "qbuf", "tickets", "n_rows", "fill")

    def __init__(self, packed, rowmap, qbuf, tickets, n_rows, fill):
        self.packed = packed
        self.rowmap = rowmap
        self.qbuf = qbuf
        self.tickets = tickets
        self.n_rows = n_rows
        self.fill = fill


class QueryEngine:
    """Batched out-of-sample cluster assignment at serving rates.

    ``backend`` dispatches the query kernel (``auto`` picks Pallas on
    TPU when the tiles are Mosaic-legal, XLA everywhere else;
    ``interpret=True`` runs the Pallas kernel through its interpreter —
    the CI path).  ``batch_capacity`` bounds the rows coalesced into
    one device batch; ``max_pending`` bounds the queue (``submit``
    raises when full — backpressure, never silent truncation).
    """

    def __init__(
        self,
        index: CorePointIndex,
        *,
        backend: str = "auto",
        interpret: bool = False,
        batch_capacity: int = 4096,
        max_pending: int = 1 << 16,
        precision: str = "high",
        model=None,
        handle: Optional[str] = None,
    ):
        from ..obs import RunRecorder
        from ..utils.validate import check_precision

        self.index = index
        # Model handle: which model this engine serves.  Defaults to
        # the index's own handle — the explicit thread the gateway
        # pulls on when composing N engines; ``None`` keeps the
        # historical one-model-per-process behavior.
        self.handle = (
            str(handle) if handle is not None
            else getattr(index, "handle", None)
        )
        # Staleness guard: an engine built from a model records the
        # model's fit generation; a caller holding this engine across a
        # REFIT gets a clear error instead of silently serving the
        # previous clustering.  (Live updates mutate the index in place
        # and bump its epoch — same model generation, never stale.)
        import weakref

        self._model_ref = weakref.ref(model) if model is not None else None
        self._model_generation = getattr(model, "_fit_generation", 0)
        self.backend = backend
        self.interpret = bool(interpret)
        # Kernel precision for the query pass: "mixed" prunes candidate
        # blocks at the bf16 peak and rescores survivors through the
        # sealed exact path (results stay bitwise oracle-exact — only
        # the work changes); inherited from the model's precision by
        # from_model when that mode is mixed.
        self.precision = check_precision(precision)
        self.batch_capacity = int(batch_capacity)
        self.max_pending = int(max_pending)
        self.recorder = RunRecorder()
        self._pending: deque = deque()
        self._pending_rows = 0
        # Latency tracking lives on a bounded log-bucket histogram
        # (obs.export.Histogram via the registry) — O(buckets) memory
        # under sustained traffic where the old per-request list grew
        # O(requests), and p50/p99 answer over a sliding window
        # (PYPARDIS_HIST_WINDOW_S) instead of the run lifetime.
        self._lat_hist = self.recorder.metrics.hist("serving.latency_ms")
        self.queries = 0
        self.batches = 0
        self._busy_s = 0.0
        self._fill_num = 0
        self._fill_den = 0
        # Load-shedding / deadline telemetry (the Clipper-style
        # production-serving counters): requests refused at a full
        # queue, and tickets failed for a blown timeout_s.
        self._shed = 0
        self._deadline_failures = 0

    @classmethod
    def from_model(cls, model, *, leaves=None, block: int = 256,
                   qblock: int = 128, backend: Optional[str] = None,
                   handle: Optional[str] = None, **kw) -> "QueryEngine":
        index = build_index(
            model, leaves=leaves, block=block, qblock=qblock,
            handle=handle,
        )
        if backend is None:
            backend = getattr(model, "kernel_backend", "auto")
        # A mixed-precision model serves mixed too (the same fast-bulk
        # + exact-rescore economy); the exact modes keep the exact
        # query pass unchanged.  Explicit precision kwarg wins.
        if "precision" not in kw:
            from ..utils.validate import check_precision

            try:
                mode = check_precision(
                    getattr(model, "precision", "high")
                )
            except ValueError:
                mode = "high"
            if mode == "mixed":
                kw["precision"] = "mixed"
        return cls(index, backend=backend, model=model, **kw)

    # -- request surface --------------------------------------------------

    def _check_stale(self) -> None:
        if self._model_ref is None:
            return
        model = self._model_ref()
        if model is not None and getattr(
            model, "_fit_generation", 0
        ) != self._model_generation:
            raise RuntimeError(
                "model was refit after this engine was built; this "
                "engine indexes the PREVIOUS clustering — call "
                "model.query_engine() to get the rebuilt engine"
            )

    def submit(self, X, timeout_s: Optional[float] = None) -> QueryTicket:
        """Enqueue a request (validated immediately; results after the
        next :meth:`drain`).

        ``timeout_s`` sets the ticket's deadline: if the result is not
        usable within it — queue wait included — the ticket FAILS with
        :class:`DeadlineExceeded` instead of the caller waiting forever
        on a stuck drain.  A full queue raises :class:`QueueFull`
        (counted in ``serving_stats()["shed_total"]``) — backpressure,
        never silent truncation.
        """
        self._check_stale()
        q = self.index.prepare_queries(X)
        if self._pending_rows + len(q) > self.max_pending:
            self._shed += 1
            raise QueueFull(
                f"query queue full ({self._pending_rows} rows pending, "
                f"max_pending={self.max_pending}); drain() first or "
                f"shed load upstream"
            )
        t = QueryTicket(len(q), q, timeout_s=timeout_s)
        self._pending.append(t)
        self._pending_rows += len(q)
        return t

    def predict(self, X, return_distance: bool = False):
        """Sync out-of-sample assignment: (N,) int32 labels (noise =
        -1), plus float32 distances to the assigning core point
        (+inf for noise) when ``return_distance``."""
        t = self.submit(X)
        self.drain()
        return t.result(return_distance)

    def drain(self) -> int:
        """Process every pending request; returns the query count.

        Coalesces tickets into ``batch_capacity``-row batches and
        pipelines them: batch *i+1*'s host routing/assembly overlaps
        batch *i*'s device execution; finalizing *i* (the result fetch)
        is the rotation barrier that frees its pooled staging buffer.
        """
        if not self._pending:
            return 0
        from ..utils import faults

        # Injection site: a serve.drain hang(Ns) fault stalls here —
        # exactly the stuck-ticket scenario the deadline machinery must
        # convert into failed tickets rather than a hung caller.
        faults.maybe_fail("serve.drain")
        t0 = time.perf_counter()
        batches = []
        cur, rows = [], 0
        while self._pending:
            t = self._pending.popleft()
            if t.deadline is not None and time.perf_counter() > t.deadline:
                # Already past its SLA (queue wait, a stalled previous
                # drain): fail now, never dispatch dead work.
                self._fail_deadline(t)
                continue
            if cur and rows + t.n > self.batch_capacity:
                batches.append(cur)
                cur, rows = [], 0
            cur.append(t)
            rows += t.n
        if cur:
            batches.append(cur)
        self._pending_rows = 0
        inflight = None
        n_done = 0
        for group in batches:
            nxt = self._dispatch(group)
            if inflight is not None:
                n_done += self._finalize(inflight)
            inflight = nxt
        if inflight is not None:
            n_done += self._finalize(inflight)
        self._busy_s += time.perf_counter() - t0
        self.queries += n_done
        self.batches += len(batches)
        self._publish()
        return n_done

    # -- internals --------------------------------------------------------

    def _dispatch(self, tickets) -> _Inflight:
        qf32 = (
            tickets[0]._q if len(tickets) == 1
            else np.concatenate([t._q for t in tickets])
        )
        n_rows = len(qf32)
        if self.index.n_core == 0 or n_rows == 0:
            return _Inflight(None, [], None, tickets, n_rows, 1.0)
        qbuf, qmask, tile_leaf, rowmap = self.index.assemble(qf32)
        packed = self.index.dispatch(
            qbuf, qmask, tile_leaf, backend=self.backend,
            interpret=self.interpret, precision=self.precision,
        )
        fill = sum(len(a) for a in rowmap) / max(qbuf.shape[0]
                                                 * qbuf.shape[2], 1)
        return _Inflight(packed, rowmap, qbuf, tickets, n_rows, fill)

    def _finalize(self, fl: _Inflight) -> int:
        best_d2 = np.full(fl.n_rows, np.inf, np.float32)
        best_lab = np.full(fl.n_rows, _INT_INF, np.int32)
        if fl.packed is not None:
            # The host materialization IS the execution sync — after
            # it, the batch's input transfer is provably consumed and
            # the staging buffer may rotate back into the pool.
            labs, d2 = unpack_query_result(fl.packed, self.index.eps2)
            for t, arr in enumerate(fl.rowmap):
                lt, dt = labs[t, :len(arr)], d2[t, :len(arr)]
                cur_d, cur_l = best_d2[arr], best_lab[arr]
                take = (dt < cur_d) | ((dt == cur_d) & (lt < cur_l))
                best_d2[arr] = np.where(take, dt, cur_d)
                best_lab[arr] = np.where(take, lt, cur_l)
            from ..parallel import staging

            staging.give_back([fl.qbuf])
        within = best_d2 <= self.index.eps2
        labels = np.where(within, best_lab, -1).astype(np.int32)
        d2 = np.where(within, best_d2, np.float32(np.inf))
        now = time.perf_counter()
        s = 0
        for t in fl.tickets:
            if t.deadline is not None and now > t.deadline:
                # The result exists but arrived past the ticket's SLA
                # — a deadline miss is a failure, not a slow success.
                self._fail_deadline(t)
                s += t.n
                continue
            t.labels = labels[s:s + t.n]
            t.d2 = d2[s:s + t.n]
            t.latency_ms = (now - t._t_submit) * 1e3
            t._q = None
            self.recorder.metrics.observe_ms(
                "serving.latency_ms", t.latency_ms
            )
            s += t.n
        self._fill_num += int(round(fl.fill * fl.n_rows))
        self._fill_den += fl.n_rows
        return fl.n_rows

    def _fail_deadline(self, t: QueryTicket) -> None:
        waited_ms = (time.perf_counter() - t._t_submit) * 1e3
        t.error = DeadlineExceeded(
            f"query ticket missed its deadline: waited "
            f"{waited_ms:.1f}ms against a "
            f"{(t.deadline - t._t_submit) * 1e3:.1f}ms timeout "
            f"(queue wait + drain stall included); the ticket is "
            f"failed, resubmit if still wanted"
        )
        t._q = None
        self._deadline_failures += 1

    def _publish(self) -> None:
        m = self.recorder.metrics
        for k, v in self.serving_stats().items():
            if isinstance(v, (int, float, bool)):
                m.set(f"serving.{_key(k)}", v)

    # -- telemetry --------------------------------------------------------

    def serving_stats(self) -> Dict:
        """Finite-by-construction serving gauges (the ``serving`` block
        of ``DBSCAN.report()``)."""
        p50 = self._lat_hist.percentile(50)
        p99 = self._lat_hist.percentile(99)
        from ..parallel import staging

        st = self.index.stats
        return {
            "model": self.handle or "default",
            "queries": int(self.queries),
            "batches": int(self.batches),
            "qps": round(self.queries / self._busy_s, 1)
            if self._busy_s > 0 else 0.0,
            "p50_ms": round(p50, 3),
            "p99_ms": round(p99, 3),
            "batch_fill": round(
                self._fill_num / self._fill_den, 4
            ) if self._fill_den else 0.0,
            "n_core": int(self.index.n_core),
            "n_leaves": int(st.get("n_leaves", 0)),
            "index_bytes": int(st.get("index_bytes", 0)),
            "index_device_bytes": int(
                staging.route_nbytes(
                    getattr(self.index, "staging_route", "serve_index")
                )
            ),
            "staged_bytes_reused": int(st.get("staged_bytes_reused", 0)),
            "backend": str(self.backend),
            "precision": str(self.precision),
            # Load-shedding / deadline counters (Clipper NSDI'17: the
            # bounded-queue + SLA surface of a production serving tier).
            "shed_total": int(self._shed),
            "deadline_failures": int(self._deadline_failures),
            # Live-update generation of the underlying index (bumped by
            # every in-place serve_index_delta refresh), and the
            # whole-index generation counter (bumped by each
            # compaction epoch swap — serve.ingest.Compactor replaces
            # the slabs in place, so this engine serves the new
            # generation with no rebuild; in-flight tickets are
            # drained against the old one first).
            "index_epoch": int(getattr(self.index, "epoch", 0)),
            "index_generation": int(
                getattr(self.index, "generation", 0)
            ),
            "index_delta_bytes": int(
                staging.route_delta_nbytes(
                    getattr(
                        self.index, "delta_route", "serve_index_delta"
                    )
                )
            ),
            # Full bounded-histogram snapshot (pypardis_tpu/hist@1):
            # windowed percentiles + lifetime bucket counts, what the
            # scrape endpoint and the monitor render.
            "latency_hist": self._lat_hist.snapshot(),
        }


class ReplicatedQueryEngine(QueryEngine):
    """Replicated-index serving: core-point slabs broadcast to every
    device of the mesh, query tiles dealt round-robin across devices
    and answered in ONE ``shard_map`` dispatch.

    Read throughput scales with device count on a real mesh (each chip
    scans only its deal of the tiles against its local replica); on the
    CPU CI mesh the measured win is dispatch amortization — eight
    devices' worth of tiles ride one program launch instead of eight.
    The slabs are placed once per index epoch (a live in-place refresh
    re-broadcasts), and results fold through the same leaf-replica
    combine as the single-device engine — answers stay bitwise
    oracle-exact.
    """

    def __init__(self, index: CorePointIndex, *, mesh=None, **kw):
        super().__init__(index, **kw)
        from ..parallel.mesh import default_mesh

        self.mesh = mesh if mesh is not None else default_mesh()
        self.n_devices = int(self.mesh.size)
        self._rep_key = None
        self._rep_arrays = None
        self._fns: Dict = {}

    # -- replica management ----------------------------------------------

    def _replicated_arrays(self):
        """The (coords, labels, blo, bhi) slabs, fully replicated over
        the mesh — re-broadcast only when the index epoch moves."""
        idx = self.index
        key = (getattr(idx, "epoch", 0), idx.coords.shape[1])
        if self._rep_key != key:
            import jax
            from jax.sharding import NamedSharding, PartitionSpec

            rep = NamedSharding(self.mesh, PartitionSpec())
            self._rep_arrays = tuple(
                # graftlint: disable=device-put-aliasing -- replicates
                # the index's own host mirrors (caller-owned, never
                # pooled); the epoch in _rep_key invalidates on update
                jax.device_put(np.asarray(a), rep)
                for a in (idx.coords, idx.labels, idx.blo, idx.bhi)
            )
            self._rep_key = key
        return self._rep_arrays

    def _rep_fn(self, block: int, nb: int, precision: str):
        key = (block, nb, precision)
        fn = self._fns.get(key)
        if fn is None:
            import jax
            from jax.sharding import PartitionSpec as P

            from ..ops.query import query_min_core
            from ..parallel.mesh import shard_map

            def per_dev(q, qmask, tl, coords, labels, blo, bhi, eps2,
                        zero):
                return query_min_core(
                    q, qmask, tl, coords, labels, blo, bhi, eps2, zero,
                    block=block, nb=nb, precision=precision,
                )

            fn = jax.jit(shard_map(
                per_dev, mesh=self.mesh,
                in_specs=(
                    P("p"), P("p"), P("p"),
                    P(), P(), P(), P(), P(), P(),
                ),
                out_specs=P(None, "p", None),
            ))
            self._fns[key] = fn
        return fn

    # -- dispatch override -------------------------------------------------

    def _dispatch(self, tickets) -> _Inflight:
        qf32 = (
            tickets[0]._q if len(tickets) == 1
            else np.concatenate([t._q for t in tickets])
        )
        n_rows = len(qf32)
        if self.index.n_core == 0 or n_rows == 0:
            return _Inflight(None, [], None, tickets, n_rows, 1.0)
        qbuf, qmask, tile_leaf, rowmap = self.index.assemble(qf32)
        P_ = self.n_devices
        nqt = qbuf.shape[0]
        pad = (-nqt) % P_
        if pad:
            from ..ops.query import PAD_COORD

            qbuf2 = np.empty((nqt + pad,) + qbuf.shape[1:], np.float32)
            qbuf2.fill(PAD_COORD)
            qbuf2[:nqt] = qbuf
            qmask = np.concatenate(
                [qmask, np.zeros((pad,) + qmask.shape[1:], bool)]
            )
            tile_leaf = np.concatenate(
                [tile_leaf, np.zeros(pad, np.int32)]
            )
            from ..parallel import staging

            staging.give_back([qbuf])
            qbuf = qbuf2
            nqt += pad
        # Round-robin deal: device d answers tiles d, d+P, d+2P, ... —
        # shard_map splits axis 0 contiguously, so reorder tiles so
        # chunk d IS that deal.
        perm = np.concatenate(
            [np.arange(d, nqt, P_) for d in range(P_)]
        )
        rowmap_full = [
            rowmap[i] if i < len(rowmap) else np.empty(0, np.int64)
            for i in perm
        ]
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as PS

        coords, labels, blo, bhi = self._replicated_arrays()
        fn = self._rep_fn(self.index.block, self.index.nb, self.precision)
        # graftlint: disable=device-put-aliasing -- each put ships a
        # fresh np.ascontiguousarray copy made in the call itself
        q_d = jax.device_put(
            np.ascontiguousarray(qbuf[perm]),
            NamedSharding(self.mesh, PS("p", None, None)),
        )
        # graftlint: disable=device-put-aliasing -- same as q_d
        qm_d = jax.device_put(
            np.ascontiguousarray(qmask[perm]),
            NamedSharding(self.mesh, PS("p", None)),
        )
        # graftlint: disable=device-put-aliasing -- same as q_d
        tl_d = jax.device_put(
            np.ascontiguousarray(tile_leaf[perm]),
            NamedSharding(self.mesh, PS("p")),
        )
        packed = fn(
            q_d, qm_d, tl_d, coords, labels, blo, bhi,
            jnp.float32(self.index.eps2), jnp.int32(0),
        )
        fill = sum(len(a) for a in rowmap) / max(
            qbuf.shape[0] * qbuf.shape[2], 1
        )
        return _Inflight(packed, rowmap_full, qbuf, tickets, n_rows, fill)

    def serving_stats(self) -> Dict:
        stats = super().serving_stats()
        per_dev = int(
            self.index.coords.nbytes + self.index.labels.nbytes
            + self.index.blo.nbytes + self.index.bhi.nbytes
        )
        stats.update({
            "replicated": True,
            "replicated_devices": self.n_devices,
            "per_device_index_bytes": per_dev,
        })
        return stats


def _key(k: str) -> str:
    from ..obs.registry import sanitize_segment

    return sanitize_segment(k)
