"""Streaming ingest: batched writes + LSM-style background compaction.

The read path coalesces (``QueryEngine`` batches tickets per Clipper);
this module is its write-side mirror, making heavy write traffic a
first-class workload instead of an interactive convenience:

* :class:`IngestQueue` — a bounded queue that coalesces individual
  writes into batches the way the query engine coalesces reads.
  ``submit_insert``/``submit_delete`` return :class:`WriteTicket`\\ s
  immediately; ``flush()`` groups CONSECUTIVE same-kind writes (order
  is preserved — an insert/delete interleaving is semantically ordered)
  into ``max_batch_rows``-row batches and dispatches each through
  :meth:`LiveModel.insert_batch` / :meth:`LiveModel.delete_batch`,
  so a stream of B single-point writes costs ONE union blast radius,
  ONE recluster kernel dispatch, and ONE index delta instead of B of
  each.

* :class:`Compactor` — the LSM maintenance schedule.  Write deltas
  accumulate in the serving index's appended slabs (the L0 of this
  design); when the deterministic trigger policy fires (appended-slab
  bytes or delta count past the ``PYPARDIS_COMPACT_*`` watermarks), a
  background full refit — checkpoint-resumable through the PR 9
  jobstate machinery, so a killed compaction resumes instead of
  restarting — re-clusters the current point set, re-Mortons and
  re-balances the cores into a fresh index generation built in the
  SAME recentring frame, and atomically **epoch-swaps** it into the
  live index object (:meth:`CorePointIndex.replace_generation`)
  without dropping in-flight tickets: the swap drains the engine
  first, so readers submitted before it resolve against the old
  generation and readers after see the new one, and every engine
  holding the index object (replicated ones included) picks the new
  generation up through the epoch bump.  Writes that land DURING the
  compaction are replayed through the normal incremental algebra
  against the new generation at swap time — the memtable-replay step
  of any LSM store.

The lineage is the LSM-tree (O'Neil, Cheng, Gauthier & O'Neil 1996 —
see PAPERS.md): absorb writes in cheap append-structured deltas, pay
the re-organization in a background merge, serve reads continuously
from the freshest generation.  At-scale deployments should point the
merge at the fastest engine: ``Compactor(fit_kw={"mode":
"global_morton"})`` runs the background refit on the zero-duplication
global-Morton route (the measured 10M+ default; labels byte-identical
to every other mode).

Fault injection sites (``PYPARDIS_FAULTS``): ``ingest.batch`` fires at
the head of every batched write — before any state mutates, so an
injected failure leaves the model untouched and the queue fails only
that batch's tickets; ``compact.phase`` fires at each compaction phase
boundary (snapshot / refit / build / swap — occurrences 1..4), and the
refit inside additionally carries every existing fit-path site.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np
from ..obs.export import Histogram
from ..utils import envreg


def _env_int(name: str, default: int) -> int:
    try:
        return int(envreg.raw(name, default))
    except (TypeError, ValueError):
        return int(default)


# Deterministic compaction watermarks: compact once the appended slabs
# hold this many bytes, or this many write deltas have landed since the
# last generation swap — whichever fires first.  Defaults are sized so
# interactive CI workloads never auto-trigger; production knobs.
DEFAULT_COMPACT_SLAB_BYTES = 64 << 20
DEFAULT_COMPACT_DELTAS = 512


class WriteTicket:
    """One submitted write; resolved (ids assigned / error set) by the
    next :meth:`IngestQueue.flush`."""

    __slots__ = (
        "kind", "rows", "ids", "error", "latency_ms", "visible_ms",
        "_t_submit", "_payload",
    )

    def __init__(self, kind: str, payload):
        self.kind = kind  # "insert" | "delete"
        self._payload = payload
        self.rows = int(len(payload))
        self.ids: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.latency_ms: Optional[float] = None
        # Set by harnesses that measure update-visible latency (the
        # wall from submit until a predict of the written point answers
        # through the refreshed index); None when nobody measured it.
        self.visible_ms: Optional[float] = None
        self._t_submit = time.perf_counter()

    @property
    def done(self) -> bool:
        return self.ids is not None or self.error is not None

    @property
    def failed(self) -> bool:
        return self.error is not None

    def result(self) -> np.ndarray:
        if self.error is not None:
            raise self.error
        if self.ids is None:
            raise RuntimeError(
                "write ticket not resolved yet; call IngestQueue.flush()"
            )
        return self.ids


class IngestQueue:
    """Bounded write coalescer over a :class:`LiveModel`.

    The write-side twin of the query engine's submit/drain queue:
    ``submit_*`` validates and enqueues (``QueueFull`` backpressure at
    ``max_pending_rows`` — never silent truncation), ``flush()`` walks
    the queue in order, groups consecutive same-kind writes into
    ``max_batch_rows``-row batches, and dispatches each as ONE batched
    update.  A batch that fails (an injected ``ingest.batch`` fault, a
    validation error surfacing late) fails ONLY its own tickets — the
    flush continues, and the error rides the tickets the way a blown
    deadline rides query tickets.
    """

    def __init__(self, live, *, max_batch_rows: int = 1024,
                 max_pending_rows: int = 1 << 16):
        self.live = live
        self.max_batch_rows = int(max_batch_rows)
        self.max_pending_rows = int(max_pending_rows)
        self._pending: deque = deque()
        self._pending_rows = 0
        self.batches = 0
        self.rows = 0
        self.shed = 0
        self.failed_batches = 0
        self._batch_rows: deque = deque(maxlen=256)
        # Write-latency distribution (submit -> resolved at flush) on
        # the bounded windowed histogram — same structure the query
        # engine tracks read latency on.
        self.lat_hist = Histogram()

    def _enqueue(self, t: WriteTicket) -> WriteTicket:
        from .engine import QueueFull

        if self._pending_rows + t.rows > self.max_pending_rows:
            self.shed += 1
            raise QueueFull(
                f"ingest queue full ({self._pending_rows} rows pending, "
                f"max_pending_rows={self.max_pending_rows}); flush() "
                f"first or shed load upstream"
            )
        self._pending.append(t)
        self._pending_rows += t.rows
        return t

    def submit_insert(self, X) -> WriteTicket:
        """Enqueue an insert (validated now, applied at the next
        flush); returns the ticket whose ``ids`` the flush fills."""
        X = self.live._check_points(X)
        return self._enqueue(WriteTicket("insert", X))

    def submit_delete(self, ids) -> WriteTicket:
        """Enqueue a delete by stable ids (existence is checked at
        flush time, against the state the preceding writes produce)."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        return self._enqueue(WriteTicket("delete", ids))

    def _groups(self) -> List[Tuple[str, List[WriteTicket]]]:
        groups: List[Tuple[str, List[WriteTicket]]] = []
        cur_kind, cur, cur_rows = None, [], 0
        while self._pending:
            t = self._pending.popleft()
            if (
                t.kind != cur_kind
                or (cur and cur_rows + t.rows > self.max_batch_rows)
            ):
                if cur:
                    groups.append((cur_kind, cur))
                cur_kind, cur, cur_rows = t.kind, [], 0
            cur.append(t)
            cur_rows += t.rows
        if cur:
            groups.append((cur_kind, cur))
        return groups

    def flush(self) -> List[WriteTicket]:
        """Apply every pending write, coalesced; returns the tickets
        resolved by this flush (failed ones included)."""
        if not self._pending:
            return []
        resolved: List[WriteTicket] = []
        for kind, tickets in self._groups():
            now = time.perf_counter
            try:
                if kind == "insert":
                    X = (
                        tickets[0]._payload if len(tickets) == 1
                        else np.concatenate(
                            [t._payload for t in tickets]
                        )
                    )
                    ids = self.live.insert_batch(X)
                    s = 0
                    for t in tickets:
                        t.ids = ids[s:s + t.rows]
                        s += t.rows
                else:
                    ids = (
                        tickets[0]._payload if len(tickets) == 1
                        else np.concatenate(
                            [t._payload for t in tickets]
                        )
                    )
                    self.live.delete_batch(ids)
                    for t in tickets:
                        t.ids = t._payload
                n_rows = sum(t.rows for t in tickets)
                self.batches += 1
                self.rows += n_rows
                self._batch_rows.append(n_rows)
            except Exception as e:  # noqa: BLE001 — per-batch failure
                self.failed_batches += 1
                for t in tickets:
                    t.error = e
            for t in tickets:
                t.latency_ms = (now() - t._t_submit) * 1e3
                self.lat_hist.observe(t.latency_ms)
                t._payload = None
                self._pending_rows -= t.rows
                resolved.append(t)
        return resolved

    def stats(self) -> Dict:
        br = list(self._batch_rows)
        return {
            "batches": int(self.batches),
            "rows": int(self.rows),
            "pending_rows": int(self._pending_rows),
            "shed": int(self.shed),
            "failed_batches": int(self.failed_batches),
            "mean_batch_rows": (
                round(sum(br) / len(br), 2) if br else 0.0
            ),
            "write_p50_ms": self.lat_hist.percentile(50),
            "write_p99_ms": self.lat_hist.percentile(99),
            "latency_hist": self.lat_hist.snapshot(),
        }


class Compactor:
    """Background full-refit compaction with atomic epoch swap.

    One cycle (:meth:`compact`): snapshot the live point set under the
    lock → full refit of the snapshot (a fresh ``DBSCAN`` fit,
    checkpoint-resumable when ``ckpt`` is given — a SIGKILLed
    compaction resumes its fixpoint instead of restarting) → build a
    fresh :class:`CorePointIndex` generation over the refit cores in
    the OLD generation's recentring frame → under the lock, drain the
    engine (in-flight readers resolve against the old generation),
    install the compacted clustering + index generation in place, and
    replay the writes that landed during the refit through the normal
    incremental algebra.  The live index keeps serving throughout; the
    only serialized sections are the snapshot and the swap.

    ``lock`` serializes the snapshot/swap against writers and the
    engine's drain — pass the serving harness's lock (or let the
    harness adopt :attr:`lock`).  ``fit_kw`` overrides the refit's
    DBSCAN construction (``mode``/``merge``/``mesh``/...); by default
    the refit runs the fused single-device engine with the live
    model's eps/min_samples/block/precision — right for CI-scale
    indexes.  **At scale (10M+ points) pass
    ``fit_kw={"mode": "global_morton"}``**: the zero-duplication
    global-Morton engine is the measured at-scale default for full
    refits (streaming build, boundary tiles instead of halo slabs,
    byte-identical labels), so the background compaction re-clusters
    at the same speed a fresh fit would.
    """

    PHASES = ("snapshot", "refit", "build", "swap")

    def __init__(
        self, live, *, ckpt: Optional[str] = None, lock=None,
        slab_bytes: Optional[int] = None,
        max_deltas: Optional[int] = None,
        fit_kw: Optional[Dict] = None,
        phase_hook: Optional[Callable[[str], None]] = None,
    ):
        self.live = live
        self.ckpt = ckpt
        self.lock = lock if lock is not None else threading.Lock()
        self.slab_bytes = (
            int(slab_bytes) if slab_bytes is not None
            else _env_int("PYPARDIS_COMPACT_SLAB_BYTES",
                          DEFAULT_COMPACT_SLAB_BYTES)
        )
        self.max_deltas = (
            int(max_deltas) if max_deltas is not None
            else _env_int("PYPARDIS_COMPACT_DELTAS",
                          DEFAULT_COMPACT_DELTAS)
        )
        self.fit_kw = dict(fit_kw or {})
        # Test/telemetry seam: called at each phase boundary (after the
        # fault site) — deterministic mid-compaction scheduling without
        # threads (the save/load and concurrent-write regression tests).
        self._phase_hook = phase_hook
        self.stats: Dict = {
            "compactions": 0, "compaction_s": 0.0, "resumed_rounds": 0,
            "replayed_inserts": 0, "replayed_deletes": 0,
        }
        # [(perf_counter start, end)] of completed cycles — the mixed
        # load harness classifies read latencies against these windows.
        self.windows: List[Tuple[float, float]] = []
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._active = False

    # -- trigger policy ---------------------------------------------------

    def should_compact(self) -> bool:
        """Deterministic watermark policy: appended-slab bytes or the
        delta count since the last swap crossed its threshold."""
        idx = self.live.index
        return (
            idx.appended_slab_bytes >= self.slab_bytes
            or idx.deltas_since_compact >= self.max_deltas
        )

    def maybe_compact(self) -> bool:
        """Fire a background cycle when the policy says so (no-op while
        one is already running); returns whether one was started."""
        if self.running or not self.should_compact():
            return False
        self.start()
        return True

    # -- the cycle --------------------------------------------------------

    def _phase(self, name: str) -> None:
        from ..utils import faults

        faults.maybe_fail("compact.phase")
        if self._phase_hook is not None:
            self._phase_hook(name)

    def compact(self) -> Dict:
        """Run one synchronous compaction cycle; returns its stats."""
        if self._active:
            raise RuntimeError("a compaction cycle is already running")
        self._active = True
        live = self.live
        t0 = time.perf_counter()
        try:
            self._phase("snapshot")
            with self.lock:
                snap = live.begin_compaction_snapshot()
            try:
                self._phase("refit")
                labels, core, resumed = self._refit(snap)
                self._phase("build")
                fresh = self._build_generation(snap, labels, core)
                self._phase("swap")
                with self.lock:
                    replayed = live._install_generation(
                        snap, labels, core, fresh
                    )
            finally:
                live._compact_active = False
            if self.ckpt:
                # A finished cycle's snapshot must never be resumed by
                # the NEXT one (different point set -> the fingerprint
                # guard would refuse the whole refit).
                from ..utils.jobstate import _norm_npz

                p = _norm_npz(self.ckpt)
                if os.path.exists(p):
                    os.unlink(p)
            dt = time.perf_counter() - t0
            self.windows.append((t0, time.perf_counter()))
            self.stats["compactions"] += 1
            self.stats["compaction_s"] = round(
                self.stats["compaction_s"] + dt, 6
            )
            self.stats["resumed_rounds"] += int(resumed)
            self.stats["replayed_inserts"] += int(replayed[0])
            self.stats["replayed_deletes"] += int(replayed[1])
            live._note_compaction(dt)
            return dict(self.stats)
        finally:
            self._active = False

    def _refit(self, snap):
        """Full refit of the snapshot set — checkpoint-resumable: a
        jobstate file from a KILLED cycle over the SAME snapshot
        resumes; one from a different snapshot is discarded (the
        partial generation it described is obsolete)."""
        from ..dbscan import DBSCAN

        live = self.live
        kw = {
            "eps": live.eps,
            "min_samples": live.min_samples,
            "block": int(live.model.block),
            "precision": live.model.precision,
            "kernel_backend": live.model.kernel_backend,
        }
        kw.update(self.fit_kw)
        if "mesh" not in kw and "mode" not in kw:
            from ..parallel.mesh import default_mesh

            kw["mesh"] = default_mesh(1)
        model = DBSCAN(**kw)
        if self.ckpt:
            from ..utils.jobstate import discard_stale, fit_meta

            discard_stale(self.ckpt, fit_meta(
                snap["points"], eps=model.eps,
                min_samples=model.min_samples,
                metric=model.metric if isinstance(model.metric, str)
                else getattr(model.metric, "__name__", "callable"),
                block=model.block, mode=model.mode,
            ))
        model.train(snap["points"], resume=self.ckpt)
        resumed = 0
        js = getattr(model, "_jobstate", None)
        if js is not None:
            resumed = int(js.restored_rounds) + int(
                js.restored_partitions
            )
        return (
            np.asarray(model.labels_, np.int32),
            np.asarray(model.core_sample_mask_, bool),
            resumed,
        )

    def _build_generation(self, snap, labels, core):
        """The fresh generation: refit cores re-Morton-sorted and
        re-balanced into a build-layout index (no appended slabs), in
        the OLD generation's recentring frame, gid-tagged with the
        snapshot's stable ids."""
        from .index import CorePointIndex

        live = self.live
        idx = live.index
        fresh = CorePointIndex.build(
            snap["points"][core], labels[core], live.eps,
            block=idx.block, qblock=idx.qblock, stage=False,
            center=idx.center,
        )
        fresh.attach_gids(snap["ids"][core])
        return fresh

    # -- background execution ---------------------------------------------

    @property
    def running(self) -> bool:
        return self._active or (
            self._thread is not None and self._thread.is_alive()
        )

    def start(self) -> threading.Thread:
        """Run one cycle on a background thread (the live index keeps
        serving; only snapshot and swap take the lock)."""
        if self.running:
            raise RuntimeError("a compaction cycle is already running")
        self._error = None

        def run():
            try:
                self.compact()
            except BaseException as e:  # noqa: BLE001 — join re-raises
                self._error = e

        self._thread = threading.Thread(
            target=run, name="pypardis-compactor", daemon=True
        )
        self._thread.start()
        return self._thread

    def join(self, timeout: Optional[float] = None) -> None:
        """Wait for the background cycle; re-raises its error."""
        if self._thread is not None:
            self._thread.join(timeout)
        if self._error is not None:
            err, self._error = self._error, None
            raise err
