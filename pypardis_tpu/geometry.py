"""Axis-aligned bounding-box algebra.

TPU-native re-design of the reference geometry layer
(``/root/reference/dbscan/geometry.py:5-100``).  Two deliberate departures
from the reference:

* ``all_space`` / empty boxes use ±inf, fixing the reference's sign bug
  where ``sys.float_info.min`` (smallest *positive* float, geometry.py:25)
  excluded every negative coordinate from "all space".
* In addition to the scalar ``BoundingBox`` object (API parity), a
  vectorized :class:`BoxStack` holds many boxes as ``(P, k)`` arrays so
  containment of N points in P boxes is one broadcasted comparison — the
  shape XLA wants, instead of the reference's per-box Python ``filter``
  closures (dbscan.py:146-147).
"""

from __future__ import annotations

import numpy as np


class BoundingBox:
    """An axis-aligned box in k dimensions.

    Semantics match ``dbscan/geometry.py``: inclusive ``contains``
    (geometry.py:89-96), ``split`` children share the boundary plane
    (geometry.py:56-71), ``expand`` is additive or proportional
    (geometry.py:73-87).
    """

    __slots__ = ("lower", "upper")

    def __init__(self, lower=None, upper=None, k=None, all_space=False):
        if lower is not None:
            self.lower = np.asarray(lower, dtype=np.float64)
            self.upper = (
                np.asarray(upper, dtype=np.float64)
                if upper is not None
                else self.lower.copy()
            )
        elif k is not None:
            if all_space:
                self.lower = np.full(k, -np.inf)
                self.upper = np.full(k, np.inf)
            else:
                # Empty box: union with anything yields the other operand.
                self.lower = np.full(k, np.inf)
                self.upper = np.full(k, -np.inf)
        else:
            self.lower = None
            self.upper = None

    @property
    def k(self) -> int:
        return len(self.lower)

    def intersection(self, other: "BoundingBox") -> "BoundingBox":
        return BoundingBox(
            lower=np.maximum(self.lower, other.lower),
            upper=np.minimum(self.upper, other.upper),
        )

    def union(self, other: "BoundingBox") -> "BoundingBox":
        return BoundingBox(
            lower=np.minimum(self.lower, other.lower),
            upper=np.maximum(self.upper, other.upper),
        )

    def split(self, dim: int, value: float):
        """Split along ``dim`` at ``value`` → (left, right).

        Both children include the split plane (geometry.py:56-71); point
        assignment disambiguates with a strict ``<`` on the left side.
        """
        left = BoundingBox(lower=self.lower.copy(), upper=self.upper.copy())
        left.upper[dim] = value
        right = BoundingBox(lower=self.lower.copy(), upper=self.upper.copy())
        right.lower[dim] = value
        return left, right

    def expand(self, eps=0, how: str = "add") -> "BoundingBox":
        if how == "add":
            return BoundingBox(self.lower - eps, self.upper + eps)
        elif how == "multiply":
            span = self.upper - self.lower
            return BoundingBox(self.lower - eps * span, self.upper + eps * span)
        raise ValueError(f"how must be 'add' or 'multiply', got {how!r}")

    def contains(self, vector) -> bool:
        vector = np.asarray(vector)
        return bool(
            np.all(self.lower <= vector) and np.all(self.upper >= vector)
        )

    def contains_points(self, points: np.ndarray) -> np.ndarray:
        """Vectorized containment: (N, k) points → (N,) bool mask."""
        points = np.asarray(points)
        return np.all(
            (points >= self.lower) & (points <= self.upper), axis=-1
        )

    def volume(self) -> float:
        return float(np.prod(np.maximum(self.upper - self.lower, 0.0)))

    def __eq__(self, other):
        if not isinstance(other, BoundingBox):
            return NotImplemented
        return np.array_equal(self.lower, other.lower) and np.array_equal(
            self.upper, other.upper
        )

    def __repr__(self):
        return f"BoundingBox(lower={self.lower}\n\tupper={self.upper})"


class BoxStack:
    """P bounding boxes stored as two (P, k) arrays.

    The reference materializes each neighborhood with a per-box Python
    closure over the whole dataset (dbscan.py:141-151).  On TPU the same
    query — which of P expanded boxes contain each of N points — is a
    single broadcasted comparison producing an (N, P) membership matrix.
    """

    __slots__ = ("lower", "upper")

    def __init__(self, lower: np.ndarray, upper: np.ndarray):
        self.lower = np.asarray(lower, dtype=np.float64)
        self.upper = np.asarray(upper, dtype=np.float64)
        assert self.lower.shape == self.upper.shape

    @classmethod
    def from_boxes(cls, boxes) -> "BoxStack":
        boxes = list(boxes)
        return cls(
            np.stack([b.lower for b in boxes]),
            np.stack([b.upper for b in boxes]),
        )

    def __len__(self) -> int:
        return self.lower.shape[0]

    @property
    def k(self) -> int:
        return self.lower.shape[1]

    def __getitem__(self, i: int) -> BoundingBox:
        return BoundingBox(lower=self.lower[i], upper=self.upper[i])

    def expand(self, eps=0) -> "BoxStack":
        return BoxStack(self.lower - eps, self.upper + eps)

    def membership(self, points: np.ndarray, chunk: int = 1 << 16) -> np.ndarray:
        """(N, k) points → (N, P) bool: point n inside box p (inclusive).

        Evaluated in chunks of ``chunk`` points so the broadcast temp is
        O(chunk · P · k) regardless of N (the (N, P, k) one-shot
        broadcast was the round-1 memory wall).  For halo routing prefer
        :func:`pypardis_tpu.partition.expanded_members`, which is
        O(N · depth) time as well as memory.
        """
        points = np.asarray(points)
        n = len(points)
        out = np.empty((n, len(self)), bool)
        for s in range(0, max(n, 1), chunk):
            e = min(s + chunk, n)
            c = points[s:e, None, :]
            np.all(
                (c >= self.lower[None, :, :]) & (c <= self.upper[None, :, :]),
                axis=-1,
                out=out[s:e],
            )
        return out


def latlon_to_unit_sphere(points) -> np.ndarray:
    """(N, 2) [lat, lon] RADIANS -> (N, 3) unit-sphere embedding.

    The haversine metric's kernel frame: great-circle distance theta
    between two points equals the angle between their unit vectors, and
    the CHORD length ``2 sin(theta / 2)`` is monotone in theta on
    [0, pi] — so after this embedding the existing L2 kernels answer
    haversine thresholds exactly (``eps_theta -> 2 sin(eps_theta / 2)``,
    the remap :attr:`pypardis_tpu.dbscan.DBSCAN.kernel_eps` applies).
    Trigonometry runs in float64 (the centering-accuracy discipline);
    float32 inputs come back float32.  Inputs follow the sklearn
    haversine convention (radians, [lat, lon] column order); rows are
    validated finite and 2-D — a degrees-by-mistake input is usually
    caught by the eps validator instead (eps must be <= pi).
    """
    pts = np.asarray(points)
    if pts.ndim != 2 or pts.shape[1] != 2:
        raise ValueError(
            f"metric='haversine' needs (N, 2) [lat, lon] input in "
            f"radians, got shape {pts.shape}"
        )
    out_dtype = np.float32 if pts.dtype == np.float32 else np.float64
    out = np.empty((len(pts), 3), out_dtype)
    chunk = 1 << 20
    for s in range(0, len(pts), chunk):
        e = min(s + chunk, len(pts))
        sub = np.asarray(pts[s:e], np.float64)
        if not np.isfinite(sub).all():
            raise ValueError(
                "input contains NaN or infinite coordinates"
            )
        lat, lon = sub[:, 0], sub[:, 1]
        clat = np.cos(lat)
        out[s:e, 0] = (clat * np.cos(lon)).astype(out_dtype)
        out[s:e, 1] = (clat * np.sin(lon)).astype(out_dtype)
        out[s:e, 2] = np.sin(lat).astype(out_dtype)
    return out
