"""Config surface.

The reference's configuration is constructor kwargs only — no files, env
vars, or CLI (reference dbscan.py:74-75, partition.py:111-112; SURVEY
§5).  The dataclass mirrors that surface one-to-one, adds the TPU-native
knobs, and gives the validation/defaulting the reference did inline
(silent ``split_method`` fallback at partition.py:129-130 is reproduced
by ``KDPartitioner`` itself).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional


@dataclasses.dataclass
class DBSCANConfig:
    """Everything ``DBSCAN(...)`` accepts, as serializable data."""

    eps: float = 0.5
    min_samples: int = 5
    metric: Any = "euclidean"
    max_partitions: Optional[int] = None
    split_method: str = "min_var"
    block: int = 1024
    precision: str = "high"
    kernel_backend: str = "auto"
    # Owned-block clustering + edge-table merge on the sharded paths
    # (halo points as adjacency evidence, never re-clustered); False
    # restores the legacy duplicate-and-recluster step.
    owner_computes: bool = True

    def build(self, mesh=None):
        from .dbscan import DBSCAN

        return DBSCAN(mesh=mesh, **dataclasses.asdict(self))

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        if callable(d["metric"]):
            d["metric"] = getattr(d["metric"], "__name__", "euclidean")
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "DBSCANConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})
